#!/bin/bash
# Build the native packer shared library.
set -e
cd "$(dirname "$0")"
# -march=native: the library is always built on the host that runs it
# (build-on-demand via native/__init__.py; the wheel ships sources).
# The .host sidecar records the build host's ISA so the loader rebuilds
# instead of SIGILL-ing when a copied working tree lands on a host with
# a different instruction set (native/__init__.py _host_isa()).
g++ -O3 -march=native -funroll-loops -shared -fPIC -std=c++17 \
    -o libldtpack.so packer.cc epilogue.cc -lpthread
{ uname -m; grep -m1 '^flags' /proc/cpuinfo 2>/dev/null | md5sum; } \
    > libldtpack.so.host 2>/dev/null || true
echo "built $(pwd)/libldtpack.so"
