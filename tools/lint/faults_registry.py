"""Fault-registry analyzer: every fault point declared, hit, and
documented.

The declaration is language_detector_tpu/faults.py's FAULT_POINTS
(name -> where the seam lives); the docs contract is the fault-point
table in docs/ROBUSTNESS.md between the ldt-fault-table markers (first
backticked token of each table row). Usage is extracted from the first
string argument of faults.hit / faults.hit_async / faults.evaluate /
faults.corruption calls — the same first-literal-argument discipline
the metric-registry
analyzer uses, so a seam wired through a variable name is invisible to
the operator docs and the analyzer alike (don't do that).

  fault-undeclared    a seam hits a point missing from FAULT_POINTS
                      (KeyError at the first armed run — catch it here)
  fault-unused        a point is declared but no seam hits it (a chaos
                      profile naming it silently injects nothing)
  fault-undocumented  drift between FAULT_POINTS and the
                      docs/ROBUSTNESS.md table, either direction
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .base import (Violation, apply_suppressions, first_str_arg,
                   iter_package_files, load_source, repo_root)

FAULTS_REL = "language_detector_tpu/faults.py"
DOCS_REL = "docs/ROBUSTNESS.md"

HIT_CALLS = frozenset({"hit", "hit_async", "evaluate", "corruption"})

MARK_BEGIN = "<!-- ldt-fault-table:begin -->"
MARK_END = "<!-- ldt-fault-table:end -->"

# first backticked token of a markdown table row: | `point` | ...
_DOC_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|", re.MULTILINE)


def declared_points(root: Path, faults_rel: str = FAULTS_REL):
    """{name: line} of FAULT_POINTS keys, by AST."""
    sf = load_source(root / faults_rel, root)
    for node in sf.tree.body:
        if isinstance(node, ast.Assign):
            is_points = any(isinstance(t, ast.Name)
                            and t.id == "FAULT_POINTS"
                            for t in node.targets)
        elif isinstance(node, ast.AnnAssign):
            is_points = (isinstance(node.target, ast.Name)
                         and node.target.id == "FAULT_POINTS")
        else:
            continue
        if is_points and isinstance(node.value, ast.Dict):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return {}


def used_points(sources):
    """{name: (rel, line)} of points passed as the literal first
    argument of a faults.hit / hit_async / evaluate call."""
    used: dict = {}
    for sf in sources:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # only attribute calls rooted at a `faults` name count:
            # an unrelated object's .hit() must not register a seam
            if not (isinstance(f, ast.Attribute)
                    and f.attr in HIT_CALLS
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "faults"):
                continue
            name = first_str_arg(node)
            if name:
                used.setdefault(name, (sf.rel, node.lineno))
    return used


def doc_points(root: Path, docs_rel: str = DOCS_REL) -> set:
    """Backticked first-column tokens of the fault table between the
    markers; empty when the docs or markers are missing (reported as
    undocumented-declared drift by check)."""
    path = root / docs_rel
    if not path.exists():
        return set()
    text = path.read_text()
    if MARK_BEGIN not in text or MARK_END not in text:
        return set()
    between = text.split(MARK_BEGIN, 1)[1].split(MARK_END, 1)[0]
    return set(_DOC_ROW_RE.findall(between))


def check(root: Path | None = None, files=None,
          faults_rel: str = FAULTS_REL, docs_rel: str = DOCS_REL):
    """Run the analyzer. Returns (violations, n_suppressed)."""
    root = root or repo_root()
    declared = declared_points(root, faults_rel)
    paths = list(iter_package_files(root)) if files is None else \
        [root / f if not Path(f).is_absolute() else Path(f)
         for f in files]
    # faults.py's own evaluate() calls take a variable, never a
    # literal; skip it so the registry module can't vouch for itself
    paths = [p for p in paths
             if str(p.resolve()) != str((root / faults_rel).resolve())]
    sources = [load_source(p, root) for p in paths]
    used = used_points(sources)
    in_docs = doc_points(root, docs_rel)

    per_file: dict = {sf.rel: [] for sf in sources}
    extra: list = []

    for name, (rel, line) in sorted(used.items()):
        if name not in declared:
            per_file.setdefault(rel, []).append(Violation(
                "fault-undeclared", rel, line,
                f"fault point {name} is hit but not declared in "
                f"faults.FAULT_POINTS (KeyError the first armed run)"))
    for name, line in sorted(declared.items()):
        if name not in used:
            extra.append(Violation(
                "fault-unused", faults_rel, line,
                f"fault point {name} is declared but no seam hits it "
                f"(an LDT_FAULTS rule naming it injects nothing)"))
        if name not in in_docs:
            extra.append(Violation(
                "fault-undocumented", faults_rel, line,
                f"fault point {name} is declared but missing from the "
                f"{docs_rel} fault table"))
    for name in sorted(in_docs):
        if name not in declared:
            extra.append(Violation(
                "fault-undocumented", docs_rel, 1,
                f"{docs_rel} fault table lists {name}, which is not "
                f"declared in faults.FAULT_POINTS (stale docs)"))

    violations: list = []
    n_suppressed = 0
    for sf in sources:
        kept, ns = apply_suppressions(sf, per_file.get(sf.rel, []))
        violations.extend(kept)
        n_suppressed += ns
    violations.extend(extra)
    return violations, n_suppressed
