"""On-demand device profiling (language_detector_tpu/profiling.py):
arm/stop lifecycle, the busy and unavailable refusals, and the window
clamp — all against the real jax.profiler on the CPU backend."""
from __future__ import annotations

import glob
import time

import pytest

from language_detector_tpu import profiling, telemetry


@pytest.fixture(autouse=True)
def _clean_window(monkeypatch):
    monkeypatch.setattr(profiling, "_ACTIVE", None)
    yield
    # never leave a live trace behind for the next test
    if profiling.active() is not None:
        import contextlib

        import jax
        with contextlib.suppress(Exception):
            jax.profiler.stop_trace()
    monkeypatch.setattr(profiling, "_ACTIVE", None)


def test_arm_unavailable_without_dir(monkeypatch):
    monkeypatch.delenv("LDT_PROFILE_DIR", raising=False)
    before = telemetry.REGISTRY.counter_value(
        "ldt_profile_captures_total", result="unavailable")
    status, payload = profiling.arm()
    assert status == 503
    assert "LDT_PROFILE_DIR" in payload["error"]
    assert profiling.active() is None
    assert telemetry.REGISTRY.counter_value(
        "ldt_profile_captures_total",
        result="unavailable") == before + 1


def test_arm_window_and_busy_then_stop(tmp_path, monkeypatch):
    monkeypatch.setenv("LDT_PROFILE_DIR", str(tmp_path))
    status, payload = profiling.arm(window_sec=0.001)  # clamps to 0.05
    assert status == 200
    assert payload["window_sec"] == 0.05
    assert payload["dir"].startswith(str(tmp_path))
    act = profiling.active()
    assert act is not None and act["dir"] == payload["dir"]
    # second arm while a window is live: typed 409, original untouched
    status2, payload2 = profiling.arm()
    assert status2 == 409
    assert payload2["dir"] == payload["dir"]
    # the watchdog stops the window on its own
    deadline = time.time() + 10.0
    while profiling.active() is not None and time.time() < deadline:
        time.sleep(0.02)
    assert profiling.active() is None, "watchdog never stopped it"
    # the capture actually landed on disk
    deadline = time.time() + 10.0
    while not glob.glob(f"{payload['dir']}/**/*.xplane.pb",
                        recursive=True) and time.time() < deadline:
        time.sleep(0.05)
    assert glob.glob(f"{payload['dir']}/**/*.xplane.pb", recursive=True)


def test_install_sigusr2_reports_thread_context():
    # pytest's main thread: installation succeeds and is undoable
    import signal
    old = signal.getsignal(signal.SIGUSR2)
    try:
        assert profiling.install_sigusr2() is True
    finally:
        signal.signal(signal.SIGUSR2, old)
