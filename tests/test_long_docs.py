"""Long documents on the device path: multi-round hitbuffer fills.

Spans with more than 1000 base hits score in rounds (the reference's
hitbuffer refill loop, scoreonescriptspan.cc:1249-1274); the native packer
mirrors it (packer.cc scan_quad_round/scan_cjk_round), so long documents
no longer fall back to the scalar engine. detect_many routes them to a
wide-slot sibling engine automatically.
"""
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
from golden_data import golden_pairs  # noqa: E402

from language_detector_tpu.engine_scalar import detect_scalar  # noqa: E402
from language_detector_tpu.models.ngram import NgramBatchEngine  # noqa: E402

PAIRS = golden_pairs()
pytestmark = pytest.mark.skipif(not PAIRS,
                                reason="reference snapshot unavailable")


def _texts():
    return [raw.decode("utf-8", errors="replace") for _, _, raw in PAIRS]


def _long_docs():
    texts = _texts()
    # distinct-paragraph concatenations (varied text, so the squeeze
    # predictor does not trigger), 5-35KB
    return [" ".join(texts[(k + i * 7) % len(texts)] for i in range(n))
            for k, n in ((3, 12), (17, 25), (41, 40), (89, 60), (11, 100))]


def test_multi_round_spans_stay_on_device():
    eng = NgramBatchEngine(max_slots=16384, max_chunks=256)
    docs = _long_docs()
    rs = eng.detect_batch(docs)
    assert eng.stats["fallback_docs"] == 0, \
        "long documents must score on the device path"
    for d, r in zip(docs, rs):
        s = detect_scalar(d, eng.tables, eng.reg)
        assert (r.summary_lang, r.language3, r.percent3) == \
            (s.summary_lang, s.language3, s.percent3), d[:60]


def test_detect_many_routes_long_docs():
    texts = _texts()
    docs = [texts[i % len(texts)][:200] for i in range(120)]
    for pos, d in zip((7, 40, 77), _long_docs()):
        docs.insert(pos, d)
    eng = NgramBatchEngine()
    rs = eng.detect_many(docs, batch_size=64)
    assert eng.stats["fallback_docs"] == 0
    for d, r in zip(docs, rs):
        s = detect_scalar(d, eng.tables, eng.reg)
        assert (r.summary_lang, r.percent3) == \
            (s.summary_lang, s.percent3), d[:60]


def test_single_script_60kb_on_device():
    """A long single-SCRIPT document (one span chain, hundreds of chunks)
    exceeds the old u8 chunk lane; the u16 lane keeps it on the device."""
    texts = _texts()
    latin = [t for t in texts if max(t.encode("utf-8", "replace")) < 0xD0
             or all(ord(c) < 0x500 for c in t)]
    doc = " ".join((latin or texts) * 3)[:60000]
    eng = NgramBatchEngine(max_slots=32768, max_chunks=2048)
    rb = eng._pack([doc], eng.tables, eng.reg, max_slots=eng.max_slots,
                   max_chunks=eng.max_chunks, flags=eng.flags)
    assert int(rb.n_chunks.max()) > 256, \
        "document must overflow the u8 chunk lane to pin the regression"
    rs = eng.detect_batch([doc])
    assert eng.stats["fallback_docs"] == 0
    s = detect_scalar(doc, eng.tables, eng.reg)
    assert (rs[0].summary_lang, rs[0].language3, rs[0].percent3) == \
        (s.summary_lang, s.language3, s.percent3)
