"""Worker supervisor: restart the serving process on planned recycles,
and (opt-in) on crashes — with backoff and crash-loop detection.

The reference ships its restart story as a container policy
(/root/reference/Dockerfile); this is the same story for bare-metal and
for the repo's own Dockerfile CMD: run the HTTP front as a child and
restart it per policy (docs/ROBUSTNESS.md):

  - exit RECYCLE_EXIT_CODE (planned self-recycle, service/recycle.py):
    restart immediately, always — a recycle is healthy behavior, it
    resets the crash counter;
  - exit 0, or a signal-initiated stop: propagate (done);
  - any other exit ("crash"): propagate by default, so crashes surface
    to the outer restart policy / operator. With LDT_RESTART_ON_CRASH
    set, restart after an exponential backoff with jitter
    (LDT_CRASH_BACKOFF_BASE_SEC doubling per consecutive crash up to
    LDT_CRASH_BACKOFF_MAX_SEC, scaled x0.5-1.5) — unless
    LDT_CRASH_LOOP_MAX crashes landed inside the trailing
    LDT_CRASH_LOOP_WINDOW_SEC, which declares a crash loop: a worker
    that cannot hold a generation up is broken, not unlucky, and
    restarting it forever hides the outage. The loop propagates the
    last exit code.

Each spawned worker gets LDT_WORKER_GENERATION=<n> in its environment
(1, 2, ...), which the fronts export as the ldt_worker_generation
gauge, plus a shared LDT_COMPILE_CACHE_DIR (operator-set or a
per-supervisor tempdir) so generation 2+ warms its bucket ladder from
generation 1's persisted XLA compiles instead of recompiling cold.
Every lifecycle event is one structured JSON log line with a "reason"
field (recycle | crash | crash-loop | clean-exit | signal | swap |
swap-abort).

SIGHUP runs the blue/green swap drill (docs/ROBUSTNESS.md): spawn a
STANDBY generation (LDT_SWAPPED=1, optionally pointed at a new
artifact via the LDT_ARTIFACT_POINTER file), hold it until its
LDT_READY_FILE handshake lands (readiness open: warmup done, bucket
ladder pre-compiled — service/swap.startup_ready_task), then cut over
by SIGTERM-draining the old generation. Zero dropped requests when the
operator sets LDT_REUSEPORT in the supervisor's env (both generations
then share the listening port while the old one drains); any abort —
standby dies, readiness times out, pointer unreadable, injected
``standby_spawn`` fault — leaves the old generation serving untouched.

Run: python -m language_detector_tpu.service.supervisor [module]
     (module defaults to language_detector_tpu.service.aioserver, the
      single-core production front; pass .service.server for the
      threaded one)
"""
from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time

from .. import faults, flightrec, knobs, telemetry
from .recycle import RECYCLE_EXIT_CODE


def _log(msg: str, **fields):
    print(json.dumps({"msg": msg, **fields}), flush=True)


def _harvest_crash(pid: int | None, rc) -> dict | None:
    """Read the crashed worker's flight-recorder ring (it inherited
    the supervisor's LDT_FLIGHTREC_DIR) into a postmortem log line.
    Best-effort: no recorder dir / no ring file is not an error."""
    base = knobs.get_str("LDT_FLIGHTREC_DIR")
    if not base or not pid:
        return None
    path = flightrec.ring_path(base, pid)
    try:
        pm = flightrec.harvest_postmortem(path, reason="crash", rc=rc)
    except (OSError, ValueError) as e:
        telemetry.REGISTRY.counter_inc("ldt_postmortem_total",
                                       result="missing")
        _log("supervisor: postmortem harvest failed — no readable "
             "recorder ring", reason="postmortem", pid=pid,
             error=repr(e))
        return None
    telemetry.REGISTRY.counter_inc("ldt_postmortem_total",
                                   result="harvested")
    flightrec.emit_event("postmortem", pid=pid, rc=rc, reason="crash",
                         events_total=pm.get("events_total"),
                         inflight=len(
                             pm.get("inflight_request_ids") or ()))
    _log("supervisor: postmortem harvested", reason="postmortem",
         pid=pid, rc=rc, events_total=pm.get("events_total"),
         events_held=pm.get("events_held"),
         inflight_request_ids=pm.get("inflight_request_ids"))
    flightrec.discard(path)  # consumed: the respawn starts clean
    return pm


# Worker lifecycle states, declared in tools/lint/fsm_registry.py
# (machine "supervisor-worker"): the `worker` local in main() tracks
# which phase the current generation is in, and the conformance
# analyzer proves every phase change below matches the declared table.
WORKER_IDLE = 0      # no generation spawned yet
WORKER_RUNNING = 1   # child alive, supervisor in the wait loop
WORKER_STOPPED = 2   # signal-initiated stop: propagate rc
WORKER_RECYCLED = 3  # planned self-recycle: respawn immediately
WORKER_EXITED = 4    # clean exit 0: propagate
WORKER_CRASHED = 5   # crash: propagate or backoff-respawn

# Blue/green swap drill phases (machine "supervisor-swap-drill"):
# tracked by the `drill` local in _swap_drill().
DRILL_IDLE = 0      # drill requested, standby not spawned yet
DRILL_SPAWNED = 1   # standby alive, waiting on the ready handshake
DRILL_CUTOVER = 2   # standby ready: draining the old generation
DRILL_PROMOTED = 3  # standby is now the supervised child
DRILL_ABORTED = 4   # any failure: old generation keeps serving


def _forward_stop(child, signaled, signum=None):
    """Forward a stop signal to `child` exactly once across all three
    forwarding sites (signal handler, spawn race, wait loop). Returns
    the new already-signaled child. A repeat SIGTERM can land
    mid-shutdown, after the worker's handler is gone, and turn a clean
    drain into a SIGTERM death — hence the `signaled` latch."""
    if child is not None and child is not signaled \
            and child.poll() is None:
        child.send_signal(signum if signum is not None
                          else signal.SIGTERM)
        return child
    return signaled


def main() -> int:
    module = sys.argv[1] if len(sys.argv) > 1 else \
        "language_detector_tpu.service.aioserver"
    if (knobs.get_int("LDT_FLEET_WORKERS") or 0) >= 1:
        # N-member front tier: same entry point, fleet control plane
        # (health-gated membership, crash circuit, rolling SIGHUP swap,
        # autoscaling) — see service/fleet.py
        from .fleet import fleet_main
        return fleet_main(module)
    flightrec.init_from_env(role="supervisor")
    restart_on_crash = knobs.get_bool("LDT_RESTART_ON_CRASH")
    backoff_base = knobs.get_float("LDT_CRASH_BACKOFF_BASE_SEC") or 0.5
    backoff_max = knobs.get_float("LDT_CRASH_BACKOFF_MAX_SEC") or 30.0
    loop_window = knobs.get_float("LDT_CRASH_LOOP_WINDOW_SEC") or 60.0
    loop_max = knobs.get_int("LDT_CRASH_LOOP_MAX") or 5

    # persistent-XLA-cache continuity across generations: every spawned
    # generation (restart, recycle, blue/green standby) shares one
    # compile-cache dir, so generation 2+ pre-compiles its bucket
    # ladder from generation 1's persisted programs instead of paying
    # the full cold-start compile (the dominant cost of readiness).
    # An operator-set LDT_COMPILE_CACHE_DIR is honored as-is; otherwise
    # a per-supervisor dir under tempdir keeps concurrent supervisors
    # (tests, canaries) from sharing entries
    cache_dir = knobs.get_str("LDT_COMPILE_CACHE_DIR")
    if not cache_dir:
        cache_dir = os.path.join(
            tempfile.gettempdir(), f"ldt-compile-cache-{os.getpid()}")
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        cache_dir = None
    # AOT-bundle continuity (aot.py): same contract one level up the
    # boot-hot ladder — every generation shares one bundle dir, so
    # generation 2+ deserializes generation 1's exported executables
    # (no trace, no lower, no compile) instead of replaying even the
    # cached compiles
    aot_dir = knobs.get_str("LDT_AOT_DIR")
    if not aot_dir:
        aot_dir = os.path.join(
            tempfile.gettempdir(), f"ldt-aot-{os.getpid()}")
    try:
        os.makedirs(aot_dir, exist_ok=True)
    except OSError:
        aot_dir = None

    generation = 0
    consec_crashes = 0
    crash_times: list = []  # wall times of recent crashes (loop window)
    child: subprocess.Popen | None = None
    stopping = False
    swap_requested = False
    signaled: subprocess.Popen | None = None  # child already SIGTERMed
    worker = WORKER_IDLE
    t0 = time.time()

    # PID-1 duty (the Dockerfile CMD): forward SIGTERM/SIGINT to the
    # worker so `docker stop` gives it a graceful shutdown instead of
    # the namespace teardown SIGKILLing it mid-request; then stop
    # restarting and exit with the worker's code.
    def _forward(signum, frame):
        nonlocal stopping, signaled
        stopping = True
        signaled = _forward_stop(child, signaled, signum)

    signal.signal(signal.SIGTERM, _forward)
    signal.signal(signal.SIGINT, _forward)

    # SIGHUP = "roll to a new generation without dropping traffic";
    # the flag is drained by the wait loop below, never the handler
    def _request_swap(signum, frame):
        nonlocal swap_requested
        swap_requested = True

    if hasattr(signal, "SIGHUP"):
        signal.signal(signal.SIGHUP, _request_swap)

    def _swap_drill():
        nonlocal child, generation, t0, signaled
        drill = DRILL_IDLE
        old = child
        gen = generation + 1
        _log("supervisor: swap drill starting", reason="swap",
             generation=generation, standby_generation=gen)
        artifact = None
        pointer = knobs.get_str("LDT_ARTIFACT_POINTER")
        if pointer:
            try:
                with open(pointer) as f:
                    artifact = f.read().strip()
            except OSError as e:
                drill = DRILL_ABORTED
                _log("supervisor: swap aborted — artifact pointer "
                     "unreadable", reason="swap-abort",
                     pointer=pointer, error=repr(e))
                return
        try:
            if faults.ACTIVE is not None:
                faults.hit("standby_spawn")
        except faults.FaultInjected as e:
            drill = DRILL_ABORTED
            _log("supervisor: swap aborted — injected fault",
                 reason="swap-abort", error=repr(e))
            return
        ready_file = os.path.join(
            tempfile.gettempdir(),
            f"ldt-ready-{os.getpid()}-{gen}.json")
        try:
            os.remove(ready_file)
        except OSError:
            pass
        env = dict(os.environ)  # ldt-lint: disable=knob-direct-env -- building the child environment, not reading config
        env["LDT_WORKER_GENERATION"] = str(gen)
        env["LDT_SWAPPED"] = "1"
        env["LDT_READY_FILE"] = ready_file
        if cache_dir:
            env["LDT_COMPILE_CACHE_DIR"] = cache_dir
        if aot_dir:
            env["LDT_AOT_DIR"] = aot_dir
        if artifact:
            env["LDT_ARTIFACT_PATH"] = artifact
        standby = subprocess.Popen([sys.executable, "-m", module],
                                   env=env)
        drill = DRILL_SPAWNED
        st0 = time.time()
        timeout = knobs.get_float("LDT_SWAP_TIMEOUT_SEC") or 30.0
        deadline = st0 + timeout
        ready = False
        while time.time() < deadline:
            if standby.poll() is not None:
                # a standby that dies before ready (corrupt artifact,
                # port clash) aborts the drill; old keeps serving
                drill = DRILL_ABORTED
                _log("supervisor: swap aborted — standby died before "
                     "ready", reason="swap-abort",
                     rc=standby.returncode, standby_generation=gen)
                return
            if os.path.exists(ready_file):
                ready = True
                break
            # the ready check comes FIRST: a SIGTERM racing the
            # handshake must not abort a standby that already landed
            # its ready file — the cutover completes and the main loop
            # forwards the stop to the promoted generation
            if stopping:
                break
            time.sleep(0.05)
        if not ready:
            drill = DRILL_ABORTED
            standby.kill()
            standby.wait()
            _log("supervisor: swap aborted — standby not ready "
                 "in time", reason="swap-abort",
                 standby_generation=gen, timeout_sec=timeout)
            return
        # cutover: standby is warmed and listening (share the port via
        # LDT_REUSEPORT for zero-drop) — drain the old generation
        # gracefully (SIGTERM: stop accepting, flush in-flight, exit 0)
        drill = DRILL_CUTOVER
        _log("supervisor: swap cutover — draining old generation",
             reason="swap", generation=generation,
             standby_generation=gen)
        # the drain shares the exactly-once latch: if a stop already
        # SIGTERMed the old generation mid-drill, a second SIGTERM here
        # could land after its handler is gone and kill the drain
        signaled = _forward_stop(old, signaled)
        try:
            old.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            old.kill()
            old.wait()
        try:
            os.remove(ready_file)
        except OSError:
            pass
        child = standby
        drill = DRILL_PROMOTED
        generation = gen
        t0 = st0
        _log("supervisor: swap complete", reason="swap",
             generation=gen,
             standby_ready_sec=round(time.time() - st0, 3))

    while True:
        generation += 1
        _log(f"supervisor: starting {module} (generation {generation})",
             generation=generation)
        t0 = time.time()
        # the supervisor WRITES the child's env; its own reads above go
        # through the registry
        env = dict(os.environ)  # ldt-lint: disable=knob-direct-env -- building the child environment, not reading config
        env["LDT_WORKER_GENERATION"] = str(generation)
        if cache_dir:
            env["LDT_COMPILE_CACHE_DIR"] = cache_dir
        if aot_dir:
            env["LDT_AOT_DIR"] = aot_dir
        child = subprocess.Popen([sys.executable, "-m", module], env=env)
        worker = WORKER_RUNNING
        if stopping:  # signal raced the spawn: stop the new worker too
            signaled = _forward_stop(child, signaled)
        while True:
            try:
                # short-poll wait so a SIGHUP swap request is noticed
                # while the worker is healthy (the only time a drill
                # makes sense)
                rc = child.wait(timeout=0.2)
                break
            except subprocess.TimeoutExpired:
                if stopping:
                    # a stop that raced a swap drill forwarded the
                    # signal to the OLD child; make sure whichever
                    # generation is current hears it — exactly once
                    # (a repeat can land mid-shutdown, after the
                    # worker's handler is gone, and turn a clean drain
                    # into a SIGTERM death)
                    signaled = _forward_stop(child, signaled)
                elif swap_requested:
                    swap_requested = False
                    _swap_drill()
                continue
            except KeyboardInterrupt:  # Ctrl+C raced the handler
                continue
        uptime = round(time.time() - t0, 3)
        if stopping:
            worker = WORKER_STOPPED
            _log("supervisor: worker stopped by signal — propagating",
                 reason="signal", rc=rc, generation=generation,
                 uptime_sec=uptime)
            return rc
        if rc == RECYCLE_EXIT_CODE:
            # planned recycle: healthy; restart now and forget crashes
            worker = WORKER_RECYCLED
            consec_crashes = 0
            _log("supervisor: worker recycled", reason="recycle",
                 rc=rc, generation=generation, uptime_sec=uptime)
            continue
        if rc == 0:
            worker = WORKER_EXITED
            _log("supervisor: worker exited cleanly — propagating",
                 reason="clean-exit", rc=rc, generation=generation,
                 uptime_sec=uptime)
            return rc
        worker = WORKER_CRASHED
        _harvest_crash(child.pid, rc)
        if not restart_on_crash:
            _log("supervisor: worker crashed — propagating "
                 "(LDT_RESTART_ON_CRASH not set)", reason="crash",
                 rc=rc, generation=generation, uptime_sec=uptime)
            return rc
        now = time.time()
        crash_times = [t for t in crash_times if now - t <= loop_window]
        crash_times.append(now)
        if len(crash_times) >= loop_max:
            _log(f"supervisor: crash-loop — {len(crash_times)} crashes "
                 f"in {loop_window:g}s, propagating",
                 reason="crash-loop", rc=rc, generation=generation,
                 uptime_sec=uptime)
            return rc
        consec_crashes += 1
        backoff = min(backoff_base * (2 ** (consec_crashes - 1)),
                      backoff_max)
        backoff *= 0.5 + random.random()  # jitter: x0.5 - x1.5
        _log("supervisor: worker crashed — restarting after backoff",
             reason="crash", rc=rc, generation=generation,
             uptime_sec=uptime, backoff_sec=round(backoff, 3),
             consecutive_crashes=consec_crashes)
        # interruptible backoff: a SIGTERM during the wait must end the
        # supervisor, not spawn one more doomed generation
        deadline = time.time() + backoff
        while time.time() < deadline:
            if stopping:
                _log("supervisor: stopped during backoff — propagating",
                     reason="signal", rc=rc, generation=generation)
                return rc
            try:
                time.sleep(min(0.1, max(deadline - time.time(), 0)))
            except KeyboardInterrupt:
                continue


if __name__ == "__main__":
    sys.exit(main())
