"""Test configuration.

Single-device tests run on whatever backend jax picked at startup (the one
real TPU chip here; plain CPU elsewhere). Multi-device sharding tests
(test_sharding.py) spawn a subprocess with an 8-device virtual CPU mesh —
env vars cannot retarget this process because the platform plugin imports
jax before pytest starts.
"""
import os
import subprocess
from pathlib import Path

# Effective only where jax is not pre-imported at interpreter startup.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8",
)
# Persist XLA executables across test runs: the scoring programs take
# up to ~1 min to compile on CPU the first time, milliseconds after.
# (Set via jax.config — env vars are too late: jax is pre-imported at
# startup here.)
from language_detector_tpu import enable_jit_cache  # noqa: E402

enable_jit_cache()

import ctypes  # noqa: E402

import pytest  # noqa: E402

REPO = Path(__file__).resolve().parent.parent
ORACLE_SO = REPO / "tools" / "oracle" / "libcld2_oracle.so"


@pytest.fixture(scope="session")
def base_tables():
    """Snapshot-parity table set: quadgram tables explicitly disabled, like
    the compiled oracle (whose quad data files are missing upstream)."""
    from language_detector_tpu.tables import ScoringTables
    return ScoringTables.load(quad_path=False)


@pytest.fixture(scope="session")
def oracle():
    """ctypes handle to the reference parity oracle; builds it on demand.

    Skips dependent tests when the read-only reference snapshot is absent
    (e.g. in a deployment environment)."""
    if not ORACLE_SO.exists():
        build = ORACLE_SO.parent / "build.sh"
        if not Path("/root/reference/cld2").exists():
            pytest.skip("reference snapshot unavailable; oracle tests skipped")
        subprocess.run([str(build)], check=True, capture_output=True)
    lib = ctypes.CDLL(str(ORACLE_SO))
    lib.o_quadhash.restype = ctypes.c_uint32
    lib.o_octahash.restype = ctypes.c_uint64
    lib.o_bihash.restype = ctypes.c_uint32
    lib.o_pairhash.restype = ctypes.c_uint64
    lib.o_pairhash.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    lib.o_detect.restype = ctypes.c_int
    lib.o_lang_code.restype = ctypes.c_char_p
    lib.o_scanner_new.restype = ctypes.c_void_p
    lib.o_scanner_new.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.o_scanner_next.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_int),
                                   ctypes.POINTER(ctypes.c_int)]
    lib.o_scanner_free.argtypes = [ctypes.c_void_p]
    return lib


def oracle_detect(lib, text: bytes, flags: int = 0,
                  is_plain_text: bool = True):
    """Helper: run full oracle detection, return (summary_code, top3, reliable)."""
    l3 = (ctypes.c_int * 3)()
    p3 = (ctypes.c_int * 3)()
    s3 = (ctypes.c_double * 3)()
    tb = ctypes.c_int()
    rel = ctypes.c_int()
    lang = lib.o_detect(text, len(text), 1 if is_plain_text else 0, flags,
                        l3, p3, s3, ctypes.byref(tb), ctypes.byref(rel))
    top3 = [(lib.o_lang_code(l3[i]).decode(), p3[i], s3[i]) for i in range(3)]
    return (lib.o_lang_code(lang).decode(), lang, top3, bool(rel.value),
            tb.value)


def oracle_spans(lib, text: bytes, is_plain_text: bool = True):
    """Helper: iterate the oracle's script-span scanner."""
    h = lib.o_scanner_new(text, len(text), 1 if is_plain_text else 0)
    out = ctypes.create_string_buffer(40960 + 16)
    n = ctypes.c_int()
    sc = ctypes.c_int()
    spans = []
    while lib.o_scanner_next(h, out, ctypes.byref(n), ctypes.byref(sc)):
        spans.append((out.raw[:n.value], sc.value))
    lib.o_scanner_free(h)
    return spans
