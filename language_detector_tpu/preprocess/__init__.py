from .segment import ScriptSpan, segment_text  # noqa: F401
from .hashing import (  # noqa: F401
    quad_hash_v2,
    octa_hash40,
    bi_hash_v2,
    pair_hash,
)
