#!/usr/bin/env python3
"""Expand the golden suite into a large labeled TSV corpus.

The reference's published evaluations run ~765K labeled documents
(cld2/docs/evaluate_cld2_large_20140122.txt); the snapshot carries no
such corpus, so this derives one from the 402 golden documents: per
document, deterministic contiguous word windows (30-60 words) — window
sampling preserves the document's language while varying the n-gram
mix, so the large-scale eval exercises real per-document variance
instead of 250 identical copies.

Usage: python3 tools/make_eval_corpus.py OUT.tsv [n_docs]
"""
from __future__ import annotations

import random
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))


def main(out: str, n_docs: int = 100_000):
    from golden_data import golden_pairs
    pairs = [(lang, raw.decode("utf-8", errors="replace"))
             for _, lang, raw in golden_pairs()]
    if not pairs:
        sys.exit("golden suite unavailable")
    rng = random.Random(20260730)
    with open(out, "w", encoding="utf-8") as f:
        for i in range(n_docs):
            lang, text = pairs[i % len(pairs)]
            words = text.split()
            take = rng.randint(30, 60)
            if len(words) > take:
                start = rng.randint(0, len(words) - take)
                words = words[start:start + take]
            doc = " ".join(words).replace("\t", " ").replace("\n", " ")
            f.write(f"{lang}\t{doc}\n")
    print(f"wrote {n_docs} docs to {out}")


if __name__ == "__main__":
    main(sys.argv[1], *(int(a) for a in sys.argv[2:]))
