#!/usr/bin/env python3
"""HTTP-path throughput benchmark: docs/sec through POST / end-to-end.

Starts the real service in-process (device engine + batcher + the
reference's JSON contract, service/server.py), drives it with concurrent
keep-alive HTTP clients, and reports end-to-end docs/sec — the number the
reference actually shipped (its Go layer logged throughput per 1000
objects, main.go:209-218, but never published one). Results feed
docs/PERF.md.

Usage: bench_service.py [total_docs] [clients] [docs_per_request]
       bench_service.py --aio [total_docs] [clients] [docs_per_request]
       bench_service.py --aio-cold [total_docs] [clients] [docs_per_request]
Prints one JSON line. --aio benches the asyncio server (the single-core
production front) with a same-loop asyncio load generator, plus a
unix-socket pass and wire-stage stats; the default benches the threaded
server with threaded clients. --aio-cold runs exactly ONE pass and
reports it as the value: run it in a FRESH process with
LDT_COMPILE_CACHE_DIR pointing at an empty directory for an honest
cold number (bench.py does this).
"""
from __future__ import annotations

import http.client
import json
import os
import struct
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from language_detector_tpu import enable_jit_cache, knobs  # noqa: E402

# honor LDT_COMPILE_CACHE_DIR when set (the cold bench points it at a
# fresh temp dir so the pass genuinely compiles); default persistent dir
# otherwise
enable_jit_cache(knobs.get_str("LDT_COMPILE_CACHE_DIR"))


def run(total_docs: int = 98304, clients: int = 8,
        docs_per_request: int = 512) -> dict:
    from bench import make_corpus
    from language_detector_tpu.service.server import (DetectorService,
                                                      make_server)

    svc = DetectorService(use_device=True, max_delay_ms=4.0)
    httpd, metricsd, svc = make_server(0, 0, service=svc)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    port = httpd.server_address[1]

    docs = make_corpus(total_docs)
    n_requests = total_docs // docs_per_request
    payloads = []
    for r in range(n_requests):
        chunk = docs[r * docs_per_request:(r + 1) * docs_per_request]
        payloads.append(json.dumps(
            {"request": [{"text": d} for d in chunk]}).encode())

    # warm-up: compile the device programs on a small request
    warm = json.dumps({"request": [{"text": d}
                                   for d in docs[:256]]}).encode()
    conn = http.client.HTTPConnection("127.0.0.1", port)
    conn.request("POST", "/", warm,
                 {"Content-Type": "application/json"})
    conn.getresponse().read()
    conn.close()

    results = {"docs": 0, "errors": 0}
    lock = threading.Lock()
    work = list(enumerate(payloads))
    widx = [0]

    def client():
        conn = http.client.HTTPConnection("127.0.0.1", port)
        got, errs = 0, 0
        while True:
            with lock:
                if widx[0] >= len(work):
                    break
                _, payload = work[widx[0]]
                widx[0] += 1
            conn.request("POST", "/", payload,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            if resp.status in (200, 203):
                # byte count instead of a JSON parse: the client runs on
                # the same single core as the server, so client-side
                # parsing steals serve-side throughput
                got += body.count(b'"iso6391code"')
            else:
                errs += 1
        conn.close()
        with lock:
            results["docs"] += got
            results["errors"] += errs

    threads = [threading.Thread(target=client) for _ in range(clients)]
    t0 = time.time()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    took = time.time() - t0

    httpd.shutdown()
    svc.batcher.close()
    docs_sec = results["docs"] / took
    return dict(
        metric="service_http_throughput",
        value=round(docs_sec, 1),
        unit="docs/sec",
        detail=dict(total_docs=results["docs"], errors=results["errors"],
                    clients=clients, docs_per_request=docs_per_request,
                    took_sec=round(took, 2)),
    )


def _wire_stats() -> dict:
    """Parse/serialize stage stats + fast-path hit rate, read straight
    off the in-process telemetry registry (the server shares it)."""
    from language_detector_tpu import telemetry
    reg = telemetry.REGISTRY
    out: dict = {}
    for field, name in (("parse_ms", "ldt_http_parse_ms"),
                        ("serialize_ms", "ldt_http_serialize_ms")):
        h = reg.histogram_peek(name)
        if h is not None:
            _, hsum, hcount, _ = h.snapshot()
            if hcount:
                out[field + "_mean"] = round(hsum / hcount, 4)
                out[field + "_p95"] = round(h.percentile(95), 4)
    hit = reg.counter_value("ldt_http_parse_fast_total", result="hit")
    miss = reg.counter_value("ldt_http_parse_fast_total", result="miss")
    if hit + miss:
        out["parse_fast_hit_rate"] = round(hit / (hit + miss), 4)
    return out


def run_aio(total_docs: int = 98304, clients: int = 32,
            docs_per_request: int = 512,
            cold_only: bool = False) -> dict:
    """Bench the asyncio server: server + clients share one event loop
    (and the one CPU core), no thread thrash. The full bench runs a
    cold pass, a warm timed pass, and a unix-socket pass; cold_only
    stops after the first pass (see module docstring)."""
    import asyncio

    from bench import make_corpus
    from language_detector_tpu.service.aioserver import serve
    from language_detector_tpu.service.server import DetectorService

    docs = make_corpus(total_docs)
    n_requests = total_docs // docs_per_request
    bodies = []
    payloads = []
    for r in range(n_requests):
        chunk = docs[r * docs_per_request:(r + 1) * docs_per_request]
        body = json.dumps(
            {"request": [{"text": d} for d in chunk]}).encode()
        bodies.append(body)
        payloads.append(
            b"POST / HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: %d\r\n\r\n" % len(body) + body)

    uds_path = os.path.join(tempfile.mkdtemp(prefix="ldt-bench-"),
                            "ldt.sock")
    os.environ["LDT_UNIX_SOCKET"] = uds_path

    async def client(port, work, results):
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", port, limit=1 << 22)
        sock = writer.get_extra_info("socket")
        import socket as _s
        sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
        while work:
            payload = work.pop()
            writer.write(payload)
            await writer.drain()
            head = await reader.readuntil(b"\r\n\r\n")
            length = int(head.lower().split(b"content-length:")[1]
                         .split(b"\r\n")[0])
            body = await reader.readexactly(length)
            status = int(head.split(b" ")[1])
            if status in (200, 203):
                results["docs"] += body.count(b'"iso6391code"')
            else:
                results["errors"] += 1
        writer.close()

    async def uds_client(work, results):
        reader, writer = await asyncio.open_unix_connection(
            uds_path, limit=1 << 22)
        while work:
            body = work.pop()
            writer.write(struct.pack("!I", len(body)))
            writer.write(body)
            await writer.drain()
            hdr = await reader.readexactly(6)
            length, status = struct.unpack("!IH", hdr)
            payload = await reader.readexactly(length)
            if status in (200, 203):
                results["docs"] += payload.count(b'"iso6391code"')
            else:
                results["errors"] += 1
        writer.close()

    async def main():
        svc = DetectorService(use_device=True, max_delay_ms=4.0,
                              start_batcher=False)
        ready = asyncio.get_running_loop().create_future()
        server_task = asyncio.create_task(
            serve(0, 0, svc=svc, ready=ready))
        port, _ = await ready

        async def one_pass(fn, work_items):
            results = {"docs": 0, "errors": 0}
            work = list(work_items)
            t0 = time.time()
            await asyncio.gather(*[fn(work, results)
                                   for _ in range(clients)])
            return results, time.time() - t0

        def tcp(work, results):
            return client(port, work, results)

        # Cold pass first (compiles + first-flush shapes land inside it;
        # reported as cold_docs_sec), then the warm timed pass. Sequential
        # small warm-ups are NOT enough: the full-size flush shapes only
        # appear under concurrent load, so a cold "warmed" window used to
        # pay them and read ~40% low. NOTE the in-process "cold" still
        # sees whatever persistent compile cache this process started
        # with — bench.py's --aio-cold subprocess is the honest number.
        cold_results, cold_took = await one_pass(tcp, payloads)
        if cold_only:
            server_task.cancel()
            return cold_results, cold_took, None, None, None, None
        results, took = await one_pass(tcp, payloads)
        uds_results, uds_took = await one_pass(uds_client, bodies)
        server_task.cancel()
        return (cold_results, cold_took, results, took,
                uds_results, uds_took)

    (cold_results, cold_took, results, took,
     uds_results, uds_took) = asyncio.run(main())
    if cold_only:
        docs_sec = cold_results["docs"] / cold_took
        return dict(
            metric="service_http_throughput_aio_cold",
            value=round(docs_sec, 1),
            unit="docs/sec",
            detail=dict(total_docs=cold_results["docs"],
                        errors=cold_results["errors"],
                        clients=clients,
                        docs_per_request=docs_per_request,
                        took_sec=round(cold_took, 2),
                        compile_cache_dir=knobs.get_str(
                            "LDT_COMPILE_CACHE_DIR"),
                        **_wire_stats()),
        )
    docs_sec = results["docs"] / took
    return dict(
        metric="service_http_throughput_aio",
        value=round(docs_sec, 1),
        unit="docs/sec",
        detail=dict(total_docs=results["docs"], errors=results["errors"],
                    clients=clients, docs_per_request=docs_per_request,
                    took_sec=round(took, 2),
                    cold_docs_sec=round(
                        cold_results["docs"] / cold_took, 1),
                    cold_errors=cold_results["errors"],
                    uds_docs_sec=round(
                        uds_results["docs"] / uds_took, 1),
                    uds_errors=uds_results["errors"],
                    **_wire_stats()),
    )


if __name__ == "__main__":
    argv = sys.argv[1:]
    if argv and argv[0] == "--aio":
        print(json.dumps(run_aio(*[int(a) for a in argv[1:]])))
    elif argv and argv[0] == "--aio-cold":
        print(json.dumps(run_aio(*[int(a) for a in argv[1:]],
                                 cold_only=True)))
    else:
        print(json.dumps(run(*[int(a) for a in argv])))
