#!/usr/bin/env python3
"""Hyperparameter sweep for the trained quadgram tables.

Collects the training corpus once, then trains + evaluates each
configuration against the golden suite (tests/golden_data.py) in parallel
worker processes (corpus shared copy-on-write via fork). Reports accuracy
per config; use the winner for tools/train_quad_tables.py defaults.
"""
from __future__ import annotations

import dataclasses
import itertools
import multiprocessing as mp
import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))
sys.path.insert(0, str(REPO / "tests"))

from language_detector_tpu.registry import registry  # noqa: E402
from language_detector_tpu.tables import (NgramTable,  # noqa: E402
                                          load_tables)

_corpus = None
_pairs = None


def _init():
    global _corpus, _pairs
    from golden_data import golden_pairs
    from train_quad_tables import collect_corpus
    tables = load_tables()
    _corpus = collect_corpus(tables, registry)
    _pairs = golden_pairs()


def evaluate(cfg: dict) -> tuple:
    from train_quad_tables import train
    from language_detector_tpu.detector import LanguageDetector
    tables = load_tables()
    out = train(tables, registry, _corpus, verbose=False, **cfg)
    quad = NgramTable.from_npz(out, "quadgram")
    prod = dataclasses.replace(
        tables, quadgram=quad,
        avg_delta_octa_score=out["expected_score_override"])
    det = LanguageDetector(tables=prod)
    hits = 0
    for name, lang, raw in _pairs:
        # UTF-8 validity gate, like the reference harness (CheckUTF8)
        got = det.detect_bytes(raw).language
        if got == lang or (got, lang) == ("hmn", "blu"):
            hits += 1
    return cfg, hits, len(_pairs)


def main():
    import json
    grid = []
    for shrink, slope, base in itertools.product(
            [0.1, 0.5, 2.0], [1.5, 2.5, 3.5], [5]):
        grid.append(dict(shrink=shrink, slope=slope, base=base))
    if len(sys.argv) > 1:  # explicit configs as JSON dicts
        grid = [json.loads(a) for a in sys.argv[1:]]
    _init()
    print(f"corpus items: {len(_corpus)}, goldens: {len(_pairs)}, "
          f"configs: {len(grid)}", flush=True)
    n_proc = max(1, min(len(grid), mp.cpu_count() - 2))
    if n_proc == 1:
        for cfg in grid:
            cfg, hits, total = evaluate(cfg)
            print(f"{hits:4d}/{total} = {hits/total*100:5.1f}%  {cfg}",
                  flush=True)
    else:
        with mp.Pool(n_proc) as pool:
            for cfg, hits, total in pool.imap_unordered(evaluate, grid):
                print(f"{hits:4d}/{total} = {hits/total*100:5.1f}%  {cfg}",
                      flush=True)


if __name__ == "__main__":
    main()
