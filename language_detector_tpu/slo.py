"""Per-tenant SLO engine: rolling SLI windows and error-budget burn
rates over the traffic both fronts already measure.

The histograms in telemetry.py say what latency the process HAS served
since boot; they cannot say whether the fleet is MEETING a target right
now, per tenant, or how fast a declared error budget is burning. This
module turns declared targets (the LDT_SLO spec string, e.g.
``p99_ms=50,err_pct=0.5,window_sec=300``) into:

  - rolling SLI windows — per tenant and fleet-wide — computing the
    windowed latency percentile and error ratio. Each window is a
    time-bucketed ring (`_WindowRing`): a fixed number of coarse time
    buckets, each holding log-scaled latency bucket counts, so one
    request costs one bisect plus a handful of integer adds (O(1),
    no per-request allocation) and expiry is bucket reuse, never a
    scan over stored events;
  - multi-window error-budget burn rates: the spec's window is the
    FAST window, the slow window is 12x it — the default 300 s gives
    the canonical fast-5m/slow-1h pair. burn = (bad fraction in
    window) / (err_pct/100); burn 1.0 means the budget burns exactly
    as fast as it accrues;
  - a breach/recover alert state machine: the alert fires when BOTH
    windows burn at >= 1.0 (a blip cannot page, and a long-stale slow
    window alone cannot either) with at least LDT_SLO_MIN_EVENTS fast-
    window events, and clears when the fast burn drops below 1.0.
    Transitions emit the `slo_breach`/`slo_recovered` flight-recorder
    events and count ldt_slo_breaches_total.

A request is "bad" (burns budget) when it answered 5xx or exceeded the
latency target; sheds (429/503 from admission) are tracked as their own
SLI but deliberately do not burn the budget — overload protection
working as designed is not an SLO violation of the service.

Wired through telemetry.finish_request — the single authoritative
completion path — so the SLO engine, the capture plane, and the
request histogram can never disagree on a request's outcome. Exposed
as the /sloz JSON endpoint on both fronts' metrics ports, merged onto
the fleet's /fleetz, and rendered as ldt_slo_* gauges on /metrics.

Enabled by LDT_SLO (unset = every observe is one attribute check, the
faults.ACTIVE cost contract). The clock is injectable so the alert
state machine is testable against a fake clock.
"""
from __future__ import annotations

import logging
import re
import time
from bisect import bisect_left
from dataclasses import dataclass

from . import knobs
from .locks import make_lock
from .telemetry import BUCKET_EDGES_MS

_log = logging.getLogger(__name__)

# slow window = SLOW_FACTOR x the spec window: window_sec=300 gives the
# canonical fast-5m / slow-1h burn-rate pair
SLOW_FACTOR = 12
# time buckets per window ring: expiry granularity is window/20
RING_BUCKETS = 20
# burn rate at which the alert engages/clears (budget burning exactly
# as fast as it accrues)
BREACH_BURN = 1.0
# per-tenant window cap: past it new tenants aggregate into "~other"
# so a tenant-id flood cannot grow memory unboundedly
MAX_TENANTS = 64
OVERFLOW_TENANT = "~other"

_SPEC_KEY = re.compile(r"^p(\d{1,2}(?:\.\d+)?)_ms$")


@dataclass(frozen=True)
class SloSpec:
    """Parsed LDT_SLO declaration."""

    percentile: float = 99.0     # which latency percentile is targeted
    target_ms: float | None = None   # latency target (None: error-only)
    err_pct: float = 1.0         # error budget as percent of requests
    window_sec: float = 300.0    # FAST window span (slow = 12x)

    def as_dict(self) -> dict:
        return {"percentile": self.percentile,
                "target_ms": self.target_ms,
                "err_pct": self.err_pct,
                "window_sec": self.window_sec,
                "slow_window_sec": self.window_sec * SLOW_FACTOR}


def parse_spec(text: str | None) -> SloSpec | None:
    """Parse an LDT_SLO spec string (``p99_ms=50,err_pct=0.5,
    window_sec=300``) into an SloSpec. None/blank disables the engine;
    a malformed entry logs a loud warning and is skipped (same
    semantics as a mistyped knob); a spec with no valid entry at all
    disables the engine rather than silently enforcing defaults the
    operator never declared."""
    if not text or not text.strip():
        return None
    percentile = 99.0
    target_ms: float | None = None
    err_pct: float | None = None
    window_sec = 300.0
    valid = 0
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, val = part.partition("=")
        if not sep:
            _log.warning("LDT_SLO entry %r is not key=value — skipped",
                         part)
            continue
        key = key.strip()
        try:
            num = float(val)
        except ValueError:
            _log.warning("LDT_SLO %s=%r is not a number — skipped",
                         key, val)
            continue
        m = _SPEC_KEY.match(key)
        if m:
            percentile = float(m.group(1))
            target_ms = num
            valid += 1
        elif key == "err_pct":
            err_pct = num
            valid += 1
        elif key == "window_sec":
            if num <= 0:
                _log.warning("LDT_SLO window_sec=%r must be positive "
                             "— keeping %gs", val, window_sec)
            else:
                window_sec = num
                valid += 1
        else:
            _log.warning("LDT_SLO key %r is not pNN_ms/err_pct/"
                         "window_sec — skipped", key)
    if not valid:
        _log.warning("LDT_SLO=%r declared no valid target — SLO "
                     "engine stays off", text)
        return None
    return SloSpec(percentile=percentile, target_ms=target_ms,
                   err_pct=err_pct if err_pct is not None else 1.0,
                   window_sec=window_sec)


class _WindowRing:
    """One rolling SLI window: RING_BUCKETS coarse time buckets, each
    holding log-scaled latency bucket counts plus total/bad/shed
    tallies. observe() is O(1): locate the time bucket by epoch
    (reusing it wholesale when its epoch is stale — that IS the
    expiry), bisect the latency into BUCKET_EDGES_MS, bump integers.
    Mutation happens under the owning engine's lock (single writer
    discipline, like Trace spans under the GIL)."""

    __slots__ = ("span", "bucket_sec", "epochs", "lat", "total",
                 "bad", "shed", "sums")

    def __init__(self, window_sec: float):
        self.span = float(window_sec)
        self.bucket_sec = self.span / RING_BUCKETS
        self.epochs = [-1] * RING_BUCKETS
        self.lat = [[0] * (len(BUCKET_EDGES_MS) + 1)
                    for _ in range(RING_BUCKETS)]
        self.total = [0] * RING_BUCKETS
        self.bad = [0] * RING_BUCKETS
        self.shed = [0] * RING_BUCKETS
        self.sums = [0.0] * RING_BUCKETS

    def _slot(self, now: float) -> int:
        ep = int(now / self.bucket_sec)
        i = ep % RING_BUCKETS
        if self.epochs[i] != ep:
            self.epochs[i] = ep
            self.lat[i] = [0] * (len(BUCKET_EDGES_MS) + 1)
            self.total[i] = 0
            self.bad[i] = 0
            self.shed[i] = 0
            self.sums[i] = 0.0
        return i

    def observe(self, now: float, latency_ms: float, bad: bool,
                shed: bool) -> None:
        i = self._slot(now)
        self.total[i] += 1
        self.sums[i] += latency_ms
        self.lat[i][bisect_left(BUCKET_EDGES_MS, latency_ms)] += 1
        if bad:
            self.bad[i] += 1
        if shed:
            self.shed[i] += 1

    def _live(self, now: float) -> list:
        floor = int(now / self.bucket_sec) - RING_BUCKETS + 1
        return [i for i in range(RING_BUCKETS)
                if self.epochs[i] >= floor]

    def counts(self, now: float) -> tuple:
        """(total, bad, shed) over the in-window buckets — the cheap
        scan the per-request alert evaluation runs (2xRING_BUCKETS
        integer reads, no latency-bucket merge)."""
        live = self._live(now)
        return (sum(self.total[i] for i in live),
                sum(self.bad[i] for i in live),
                sum(self.shed[i] for i in live))

    def snapshot(self, now: float) -> dict:
        """Full windowed SLIs: count/bad/shed/err_ratio, mean, and the
        p50 + declared-percentile latency estimates (merged latency
        buckets, interpolated like telemetry.Histogram)."""
        live = self._live(now)
        merged = [0] * (len(BUCKET_EDGES_MS) + 1)
        for i in live:
            row = self.lat[i]
            for j, c in enumerate(row):
                if c:
                    merged[j] += c
        total = sum(self.total[i] for i in live)
        bad = sum(self.bad[i] for i in live)
        shed = sum(self.shed[i] for i in live)
        lat_sum = sum(self.sums[i] for i in live)
        return {"count": total, "bad": bad, "shed": shed,
                "err_ratio": round(bad / total, 6) if total else 0.0,
                "mean_ms": round(lat_sum / total, 3) if total else 0.0,
                "_merged": merged}


def _bucket_percentile(merged: list, q: float) -> float | None:
    """q-th percentile from merged latency bucket counts by linear
    interpolation inside the holding bucket (the +Inf bucket answers
    its lower edge: the window keeps no max)."""
    total = sum(merged)
    if total == 0:
        return None
    target = total * q / 100.0
    cum = 0
    lo = 0.0
    for i, c in enumerate(merged):
        cum += c
        if cum >= target and c > 0:
            if i >= len(BUCKET_EDGES_MS):
                return BUCKET_EDGES_MS[-1]
            hi = BUCKET_EDGES_MS[i]
            frac = (target - (cum - c)) / c
            return lo + (hi - lo) * frac
        if i < len(BUCKET_EDGES_MS):
            lo = BUCKET_EDGES_MS[i]
    return lo


class SloEngine:
    """Declared targets + per-tenant and fleet-wide window pairs + the
    burn-rate alert state machine. `clock` is injectable (monotonic
    seconds) so window expiry and alert transitions run against a fake
    clock in tests."""

    def __init__(self, spec: SloSpec, clock=time.monotonic,
                 min_events: int | None = None):
        self.spec = spec
        self.clock = clock
        if min_events is None:
            min_events = knobs.get_int("LDT_SLO_MIN_EVENTS") or 4
        self.min_events = max(int(min_events), 1)
        self._lock = make_lock("slo.engine")
        self._fleet = (_WindowRing(spec.window_sec),
                       _WindowRing(spec.window_sec * SLOW_FACTOR))
        self._tenants: dict = {}   # tenant -> (fast, slow) window pair
        self._alert = False
        self._alert_since: float | None = None
        self._breaches = 0
        self._observed = 0

    # -- per-request hot path -----------------------------------------------

    def observe(self, tenant: str | None, status, latency_ms: float,
                shed: bool = False) -> None:
        now = self.clock()
        spec = self.spec
        bad = (isinstance(status, int) and status >= 500) or (
            not shed and spec.target_ms is not None
            and latency_ms > spec.target_ms)
        tenant = str(tenant) if tenant else "default"
        with self._lock:
            self._observed += 1
            fast, slow = self._fleet
            fast.observe(now, latency_ms, bad, shed)
            slow.observe(now, latency_ms, bad, shed)
            pair = self._tenants.get(tenant)
            if pair is None:
                if len(self._tenants) >= MAX_TENANTS:
                    tenant = OVERFLOW_TENANT
                    pair = self._tenants.get(tenant)
                if pair is None:
                    pair = (_WindowRing(spec.window_sec),
                            _WindowRing(spec.window_sec * SLOW_FACTOR))
                    self._tenants[tenant] = pair
            pair[0].observe(now, latency_ms, bad, shed)
            pair[1].observe(now, latency_ms, bad, shed)
            transition = self._evaluate_locked(now)
        # registry counters and flight-recorder events are emitted
        # OUTSIDE the engine lock (their own locks; keep the order
        # graph edge-free, flightrec.emit discipline)
        from . import telemetry
        telemetry.REGISTRY.counter_inc(
            "ldt_slo_events_total",
            result="shed" if shed else ("bad" if bad else "good"))
        if transition is not None:
            self._announce(transition)

    # -- burn rates & alert state machine -----------------------------------

    def _burns_locked(self, now: float) -> tuple:
        budget = max(self.spec.err_pct, 1e-9) / 100.0
        fast, slow = self._fleet
        ft, fb, _ = fast.counts(now)
        st, sb, _ = slow.counts(now)
        burn_fast = (fb / ft) / budget if ft else 0.0
        burn_slow = (sb / st) / budget if st else 0.0
        return burn_fast, burn_slow, ft

    def _evaluate_locked(self, now: float) -> dict | None:
        """Run the alert state machine; returns the transition record
        to announce (outside the lock), or None."""
        burn_fast, burn_slow, fast_total = self._burns_locked(now)
        if not self._alert:
            if (fast_total >= self.min_events
                    and burn_fast >= BREACH_BURN
                    and burn_slow >= BREACH_BURN):
                self._alert = True
                self._alert_since = now
                self._breaches += 1
                return {"event": "slo_breach",
                        "burn_fast": round(burn_fast, 3),
                        "burn_slow": round(burn_slow, 3)}
        elif burn_fast < BREACH_BURN:
            since = self._alert_since
            self._alert = False
            self._alert_since = None
            return {"event": "slo_recovered",
                    "burn_fast": round(burn_fast, 3),
                    "breach_sec": round(now - since, 3)
                    if since is not None else None}
        return None

    def _announce(self, transition: dict) -> None:
        from . import flightrec, telemetry
        ev = transition.pop("event")
        if ev == "slo_breach":
            telemetry.REGISTRY.counter_inc("ldt_slo_breaches_total")
            flightrec.emit_event("slo_breach", **transition)
        else:
            flightrec.emit_event("slo_recovered", **transition)

    # -- views --------------------------------------------------------------

    def stats(self) -> dict:
        """Small numeric view for /metrics gauges and /debug/vars; runs
        the state machine too so recovery is visible without traffic."""
        now = self.clock()
        with self._lock:
            transition = self._evaluate_locked(now)
            burn_fast, burn_slow, _ = self._burns_locked(now)
            st, sb, _ = self._fleet[1].counts(now)
            alert = self._alert
            breaches = self._breaches
            observed = self._observed
            tenants = len(self._tenants)
        if transition is not None:
            self._announce(transition)
        budget = max(self.spec.err_pct, 1e-9) / 100.0
        remaining = 1.0 - ((sb / st) / budget if st else 0.0)
        return {"alert": 1 if alert else 0,
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "budget_remaining": round(min(max(remaining, 0.0),
                                              1.0), 4),
                "breaches_total": breaches,
                "observed": observed,
                "tenants": tenants}

    def _window_view(self, pair: tuple, now: float) -> dict:
        budget = max(self.spec.err_pct, 1e-9) / 100.0
        out = {}
        for label, ring in (("fast", pair[0]), ("slow", pair[1])):
            snap = ring.snapshot(now)
            merged = snap.pop("_merged")
            p50 = _bucket_percentile(merged, 50.0)
            pq = _bucket_percentile(merged, self.spec.percentile)
            snap["p50_ms"] = round(p50, 3) if p50 is not None else None
            snap[f"p{self.spec.percentile:g}_ms"] = \
                round(pq, 3) if pq is not None else None
            snap["burn_rate"] = round(
                (snap["bad"] / snap["count"]) / budget, 4) \
                if snap["count"] else 0.0
            snap["window_sec"] = ring.span
            out[label] = snap
        return out

    def snapshot(self) -> dict:
        """The /sloz document: spec, fleet-wide + per-tenant windowed
        SLIs, and the alert state."""
        now = self.clock()
        with self._lock:
            transition = self._evaluate_locked(now)
            fleet = self._window_view(self._fleet, now)
            tenants = {t: self._window_view(pair, now)
                       for t, pair in sorted(self._tenants.items())}
            alert = {"state": "breach" if self._alert else "ok",
                     "since_sec": round(now - self._alert_since, 3)
                     if self._alert_since is not None else None,
                     "breaches_total": self._breaches,
                     "min_events": self.min_events}
            observed = self._observed
        if transition is not None:
            self._announce(transition)
        return {"enabled": True, "spec": self.spec.as_dict(),
                "observed": observed, "fleet": fleet,
                "tenants": tenants, "alert": alert}


# Module-level engine: None = disabled (the fast-path check). Armed by
# init_from_env() at front startup; rebound atomically.
ENGINE: SloEngine | None = None


def init_from_env() -> SloEngine | None:
    """Arm the process SLO engine from LDT_SLO (unset/invalid = stay
    disabled). Called by both fronts' startup; idempotent."""
    global ENGINE
    if ENGINE is not None:
        return ENGINE
    spec = parse_spec(knobs.get_str("LDT_SLO"))
    if spec is None:
        return None
    ENGINE = SloEngine(spec)
    return ENGINE


def observe(trace, meta: dict | None, total_ms: float) -> None:
    """finish_request's SLO hook: one observation per completed
    request. No-op (one attribute check) when the engine is off."""
    eng = ENGINE
    if eng is None:
        return
    meta = meta or {}
    eng.observe(tenant=getattr(trace, "tenant", None),
                status=meta.get("status"), latency_ms=total_ms,
                shed=bool(meta.get("shed")))


def stats() -> dict | None:
    """Gauge source for /metrics and /debug/vars; None when off."""
    eng = ENGINE
    return eng.stats() if eng is not None else None


def sloz() -> dict:
    """The /sloz endpoint body (both fronts' metrics ports)."""
    eng = ENGINE
    if eng is None:
        return {"enabled": False,
                "hint": "set LDT_SLO=p99_ms=...,err_pct=...,"
                        "window_sec=... to declare targets"}
    return eng.snapshot()


def reset_for_tests() -> None:
    """Disarm the module engine (tests re-init with their own spec)."""
    global ENGINE
    ENGINE = None
