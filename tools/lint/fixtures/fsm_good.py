"""Conforming twin of fsm_bad.py: same declared machine, every write
narrowed to a declared transition, every declared transition exercised
(tests/test_lint.py drives this through a fixture Machine)."""

IDLE, RUN, DONE, HALT = 0, 1, 2, 3


class Widget:
    def __init__(self):
        self.count = 0
        self._state = IDLE

    def start(self):
        if self._state == IDLE:
            self._state = RUN

    def finish(self):
        if self._state == RUN:
            self._state = DONE

    def park(self):
        if self._state == RUN:
            self._state = IDLE
        elif self._state == DONE:
            self._state = HALT
