"""Device-resident scoring tables: the model weights in TPU HBM.

Uploaded once, replicated across the mesh (they are small: ~2MB total).
All seven n-gram tables are concatenated into ONE bucket array and ONE
indirect array so the device probes any mix of candidate kinds with two
gathers total (per-kind base offsets and geometry ride in small [8]
constant vectors indexed by the slot's kind) — see ops/score.py.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import Registry
from ..tables import NgramTable, ScoringTables

# Kind ids (keep in sync with preprocess/pack.py)
PAD, SEED, QUAD, UNI, DELTA_OCTA, DISTINCT_OCTA, BI_DELTA, BI_DISTINCT = \
    range(8)

# kind -> probed table (None = no hash probe; UNI resolves its direct
# payload through cjkcompat's indirect array)
_KIND_TABLE = {QUAD: "quadgram", DELTA_OCTA: "deltaocta",
               DISTINCT_OCTA: "distinctocta", BI_DELTA: "cjkdeltabi",
               BI_DISTINCT: "distinctbi", UNI: "cjkcompat"}


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KindTables:
    """Per-kind table geometry, indexed by the slot kind id ([8]-vectors)."""
    bucket_off: jnp.ndarray   # [8] i32 table's first row in cat_buckets
    size: jnp.ndarray         # [8] u32 bucket count (power of two)
    keymask: jnp.ndarray      # [8] u32
    ind_off: jnp.ndarray      # [8] i32 table's first entry in cat_ind
    size_one: jnp.ndarray     # [8] i32 single/double indirect boundary
    probes: jnp.ndarray       # [8] bool kind performs a hash probe


@dataclasses.dataclass(frozen=True)
class Quad2Static:
    """Dual quadgram table geometry (static: branch pruned when absent)."""
    bucket_off: int
    size: int
    keymask: int
    ind_off: int
    size_one: int


@dataclasses.dataclass
class HostTables:
    """Host-side (numpy) view of the concatenated tables + geometry, shared
    by DeviceTables.from_host and the native resolver (packer.cc
    ldt_init_tables). cat_ind2 = cat_ind ++ per-script seed langprobs, so a
    u16 wire index addresses every possible tote add, seeds included."""
    cat_buckets: np.ndarray        # [rows, 4] u32
    cat_ind: np.ndarray            # [n] u32
    cat_ind2: np.ndarray           # [n + num_scripts] u32
    bucket_off: np.ndarray         # [8] i64 per-kind first bucket row
    size: np.ndarray               # [8] u32
    keymask: np.ndarray            # [8] u32
    ind_off: np.ndarray            # [8] i32
    size_one: np.ndarray           # [8] i32
    probes: np.ndarray             # [8] u8
    q2: "Quad2Static" = None
    q2_enabled: bool = False
    seed_ind_base: int = 0


_host_tables_cache: list = []  # [(tables, reg, HostTables)] single slot


def _pad128(n: int) -> int:
    """Round a table row count up to the SIMD width (128 lanes)."""
    return -(-n // 128) * 128


def host_tables(t: ScoringTables, reg: Registry) -> HostTables:
    if _host_tables_cache and _host_tables_cache[0][0] is t \
            and _host_tables_cache[0][1] is reg:
        return _host_tables_cache[0][2]
    tables = [t.quadgram, t.quadgram2, t.deltaocta, t.distinctocta,
              t.cjkdeltabi, t.distinctbi, t.cjkcompat]
    names = ["quadgram", "quadgram2", "deltaocta", "distinctocta",
             "cjkdeltabi", "distinctbi", "cjkcompat"]
    bucket_off, ind_off = {}, {}
    b_parts, i_parts = [], []
    row, ent = 0, 0
    for name, tbl in zip(names, tables):
        bucket_off[name] = row
        ind_off[name] = ent
        b_parts.append(tbl.buckets.reshape(-1, 4))
        i_parts.append(tbl.ind)
        row += tbl.buckets.reshape(-1, 4).shape[0]
        ent += len(tbl.ind)
    cat_buckets = np.ascontiguousarray(
        np.concatenate(b_parts, axis=0).astype(np.uint32))
    cat_ind = np.ascontiguousarray(np.concatenate(i_parts).astype(np.uint32))

    # seed block: the per-script default-language langprob the packer's
    # SEED records used to carry inline (LinearizeAll's weight-1 seed,
    # scoreonescriptspan.cc:936-964)
    from ..registry import ULSCRIPT_LATIN
    seeds = np.zeros(reg.num_scripts, np.uint32)
    for s in range(reg.num_scripts):
        seeds[s] = np.uint32(
            reg.per_script_number(ULSCRIPT_LATIN, reg.default_language(s))
            << 8)
    cat_ind2 = np.ascontiguousarray(np.concatenate([cat_ind, seeds]))
    if len(cat_ind2) > 0xFFFF:
        raise ValueError(
            f"concatenated indirect arrays ({len(cat_ind2)} entries) "
            "exceed the u16 resolved-wire index; shrink the tables or "
            "widen the wire lane")

    ko = np.zeros(8, np.int64)
    ks = np.ones(8, np.uint32)
    km = np.full(8, 0xFFFFFFFF, np.uint32)
    ki = np.zeros(8, np.int32)
    k1 = np.zeros(8, np.int32)
    kp = np.zeros(8, np.uint8)
    for kind, name in _KIND_TABLE.items():
        tbl = dict(zip(names, tables))[name]
        ko[kind] = bucket_off[name]
        ks[kind] = tbl.size
        km[kind] = tbl.keymask
        ki[kind] = ind_off[name]
        k1[kind] = tbl.size_one
        kp[kind] = kind != UNI
    q2 = t.quadgram2
    ht = HostTables(
        cat_buckets=cat_buckets, cat_ind=cat_ind, cat_ind2=cat_ind2,
        bucket_off=ko, size=ks, keymask=km, ind_off=ki, size_one=k1,
        probes=kp,
        q2=Quad2Static(bucket_off=bucket_off["quadgram2"],
                       size=int(q2.size), keymask=int(q2.keymask),
                       ind_off=ind_off["quadgram2"],
                       size_one=int(q2.size_one)),
        q2_enabled=not q2.empty and q2.size != 0,
        seed_ind_base=len(cat_ind),
    )
    _host_tables_cache.clear()
    _host_tables_cache.append((t, reg, ht))
    return ht


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DeviceTables:
    cat_buckets: jnp.ndarray       # [sum sizes, 4] u32 all bucket arrays
    cat_ind: jnp.ndarray           # [sum inds] u32 all indirect arrays
    cat_ind2: jnp.ndarray          # cat_ind ++ per-script seed langprobs
    kind_tbl: KindTables
    lg_prob3: jnp.ndarray          # [240, 3] uint8: 3-entry qprob decode
    expected_score: jnp.ndarray    # [614, 4] int32
    # quantized/padded companions for the fused kernels (ops/kernels.py):
    # SIMD-width (128-lane) padded so gathers vectorize without clips.
    # lg_prob3_pad rows >= 240 REPLICATE the last real row — XLA clamps
    # out-of-range gather indices, so padding with the clamp row keeps
    # the padded decode bit-identical to the clipped one. close_set_pad
    # / expected_score_pad pad with zeros: language ids come from
    # plang_to_lang and are in-range by construction, the pad rows only
    # square up the tile.
    lg_prob3_pad: jnp.ndarray      # [256, 3] uint8
    expected_score_pad: jnp.ndarray  # [640, 4] int32
    close_set_pad: jnp.ndarray     # [640] int32
    plang_to_lang: jnp.ndarray     # [2, 256] int32 (latn, othr)
    lang_rtype_default: jnp.ndarray  # [102, 2] int32 (rtype, default lang)
    close_set: jnp.ndarray         # [614] int32 close-set id
    closest_alt: jnp.ndarray       # [614] int32 closest alternate (or 26)
    is_figs: jnp.ndarray           # [614] bool
    kind_tbl2: Quad2Static = dataclasses.field(metadata=dict(static=True))
    quad2_enabled: bool = dataclasses.field(metadata=dict(static=True))

    @classmethod
    def from_host(cls, t: ScoringTables, reg: Registry) -> "DeviceTables":
        ht = host_tables(t, reg)
        cat_buckets, cat_ind = ht.cat_buckets, ht.cat_ind

        _validate_qprobs(t, cat_ind)

        kind_tbl = KindTables(
            bucket_off=jnp.asarray(ht.bucket_off.astype(np.int32)),
            size=jnp.asarray(ht.size),
            keymask=jnp.asarray(ht.keymask),
            ind_off=jnp.asarray(ht.ind_off),
            size_one=jnp.asarray(ht.size_one),
            probes=jnp.asarray(ht.probes.astype(bool)))
        kind_tbl2 = ht.q2

        close = np.zeros(reg.num_languages, np.int32)
        for lang in range(reg.num_languages):
            close[lang] = reg.close_set(lang)
        alt = np.full(reg.num_languages, 26, np.int32)  # 26 = UNKNOWN
        alt[:len(reg.closest_alt_lang)] = reg.closest_alt_lang
        figs = np.zeros(reg.num_languages, bool)
        for code in ("fr", "it", "de", "es"):
            figs[reg.code_to_lang[code]] = True
        rd = np.stack([reg.ulscript_rtype.astype(np.int32),
                       reg.ulscript_default_lang.astype(np.int32)], axis=1)

        lg3 = np.asarray(t.lg_prob[:, 5:8], dtype=np.uint8)
        lg3_pad = np.empty((256, 3), np.uint8)
        lg3_pad[:len(lg3)] = lg3
        lg3_pad[len(lg3):] = lg3[-1]               # the clamp row
        exp = t.avg_delta_octa_score.astype(np.int32)
        exp_pad = np.zeros((_pad128(exp.shape[0]), 4), np.int32)
        exp_pad[:exp.shape[0]] = exp
        close_pad = np.zeros(exp_pad.shape[0], np.int32)
        close_pad[:len(close)] = close
        return cls(
            cat_buckets=jnp.asarray(cat_buckets),
            cat_ind=jnp.asarray(cat_ind),
            cat_ind2=jnp.asarray(ht.cat_ind2),
            kind_tbl=kind_tbl,
            lg_prob3=jnp.asarray(t.lg_prob[:, 5:8]),
            expected_score=jnp.asarray(
                t.avg_delta_octa_score.astype(np.int32)),
            lg_prob3_pad=jnp.asarray(lg3_pad),
            expected_score_pad=jnp.asarray(exp_pad),
            close_set_pad=jnp.asarray(close_pad),
            plang_to_lang=jnp.asarray(np.stack([
                reg.plang_to_lang_latn.astype(np.int32),
                reg.plang_to_lang_othr.astype(np.int32)])),
            lang_rtype_default=jnp.asarray(rd),
            close_set=jnp.asarray(close),
            closest_alt=jnp.asarray(alt),
            is_figs=jnp.asarray(figs),
            kind_tbl2=kind_tbl2,
            quad2_enabled=ht.q2_enabled,
        )


def digest_arrays(dt: "DeviceTables") -> list:
    """The dt planes the integrity scrub digests, in a deterministic
    order: the pytree leaves (registered-dataclass field order, nested
    KindTables included; static geometry fields are treedef, not
    leaves). Host fingerprint() and the jitted device fold in
    ops/kernels.py both iterate THIS list, so index i always names the
    same plane on both sides."""
    return jax.tree_util.tree_leaves(dt)


def fold_host(a) -> int:
    """Host (numpy) twin of the device digest fold in ops/kernels.py:
    normalize the plane to u32 words, weight each word by its position
    (mod-65521 stride so a swap of equal words still changes the sum),
    and wrap-sum mod 2^32. Must stay bit-identical to kernels._fold —
    the scrub compares the two."""
    v = np.asarray(a)
    if v.dtype == bool:
        v = v.astype(np.uint8)
    if v.dtype.itemsize == 1:
        w = v.astype(np.uint32)
    elif v.dtype.itemsize == 2:
        w = v.view(np.uint16).astype(np.uint32)
    else:
        w = v.view(np.uint32)
    w = w.ravel()
    weights = (np.arange(w.size, dtype=np.uint32) % 65521) + 1
    return int((w * weights).sum(dtype=np.uint32))


def fingerprint(dt: "DeviceTables") -> tuple:
    """Per-plane digest tuple of an uploaded table set — the lane's
    expected fingerprint, recorded at upload time (np.asarray reads
    back the actual device bytes, so the reference covers the upload
    itself, not just the host source)."""
    return tuple(fold_host(a) for a in digest_arrays(dt))


def _validate_qprobs(t: ScoringTables, cat_ind: np.ndarray) -> None:
    """Assert the group-in-use invariant the device scorer relies on:
    every packed langprob with a nonzero pslang decodes to qprob >= 1, so
    'Tote group in use' == 'some language in the group scored > 0'
    (ops/score.py stage 8). Holds for the reference tables and by
    construction for trained ones; a table violating it would silently
    change top-2 tie-breaking, so fail loudly at load.

    Also enforces the fused kernels' int16 accumulator bound
    (ops/kernels.py): a chunk tote for one language is at most
    K(256) slots x 3 planes x qprob_max, which must stay below 2^15
    for the quantized i16 accumulation to be lossless. Reference
    tables sit at qprob_max = 12 (tote <= 9216); anything up to 42 is
    safe, beyond that the quantized paths would silently wrap."""
    lg3 = np.asarray(t.lg_prob[:, 5:8])
    qmax = int(lg3.max()) if lg3.size else 0
    if 256 * 3 * qmax > 0x7FFF:
        raise ValueError(
            f"table qprob_max={qmax} breaks the fused kernels' int16 "
            f"tote bound (256 slots x 3 planes x qprob must stay "
            f"< 32768); retrain or rescale lg_prob")
    lps = np.unique(cat_ind)
    rows = lps & 0xFF
    ok_rows = rows < len(lg3)
    q = lg3[np.minimum(rows, len(lg3) - 1)]       # [n, 3]
    for j, shift in enumerate((8, 16, 24)):
        ps = (lps >> shift) & 0xFF
        bad = ok_rows & (ps > 0) & (q[:, j] == 0)
        if bad.any():
            raise ValueError(
                f"table payload violates qprob>=1 invariant: "
                f"langprob {hex(int(lps[np.argmax(bad)]))}")
