"""Self-tests for the static-analysis suite (tools/lint).

Each rule family is exercised against seeded-violation fixtures in
tools/lint/fixtures/ (bad fixtures must trip, good fixtures must pass,
suppressions must be honored and counted), and a meta-check asserts the
live tree itself is clean — the same invariant ci.sh enforces by
running `python -m tools.lint` before the test suite.
"""
from __future__ import annotations

import subprocess
import sys
from collections import Counter
from pathlib import Path

from tools.lint import (faults_registry, fsm_registry,
                        future_resolution, jit_contract,
                        knob_registry, lock_discipline,
                        metric_registry, trace_safety)
from tools.lint.__main__ import run
from tools.lint.ownership import _cl

REPO = Path(__file__).resolve().parent.parent
FIX = "tools/lint/fixtures"


def _rules(violations):
    return Counter(v.rule for v in violations)


# -- trace safety ------------------------------------------------------------


def test_trace_bad_fixture_trips_every_rule():
    v, _ = trace_safety.check(root=REPO, files=[f"{FIX}/trace_bad.py"])
    rules = _rules(v)
    assert rules["trace-host-sync"] == 3        # float(), .item(), np.asarray
    assert rules["trace-python-branch"] == 1    # if n:
    assert rules["jit-shape-source"] == 1       # score(dt, wire)
    assert sum(rules.values()) == 5


def test_trace_good_fixture_is_clean():
    # the trace-time-static idioms the live scorer relies on: shape
    # reads, range loops, literal-bool config flags, identity tests,
    # packer-sourced wires
    v, ns = trace_safety.check(root=REPO, files=[f"{FIX}/trace_good.py"])
    assert v == []
    assert ns == 0


def test_trace_suppression_honored_and_reasonless_inert():
    v, ns = trace_safety.check(root=REPO,
                               files=[f"{FIX}/trace_suppressed.py"])
    rules = _rules(v)
    assert ns == 1                                   # reasoned comment
    assert rules["trace-host-sync"] == 1             # reasonless: kept
    assert rules["lint-suppression-missing-reason"] == 1


# -- lock discipline ---------------------------------------------------------

_LOCK_BAD_OWNERSHIP = {
    f"{FIX}/lock_bad.py": {
        "Counter": _cl(lock="_lock", attrs=("n",),
                       aliases={"ladder": "Ladder"}),
        "Ladder": _cl(lock="_lock", attrs=("level",)),
    },
}

_LOCK_GOOD_OWNERSHIP = {
    f"{FIX}/lock_good.py": {
        "Gauge": _cl(lock="_lock", attrs=("v", "hint"),
                     held=("_apply",),
                     lockfree={"hint": "fixture: monotonic hint"}),
    },
}


def test_lock_bad_fixture_trips():
    v, _ = lock_discipline.check(root=REPO,
                                 ownership=_LOCK_BAD_OWNERSHIP)
    assert len(v) == 2
    assert all(x.rule == "lock-discipline" for x in v)
    texts = "\n".join(x.message for x in v)
    assert "Counter.n" in texts          # owned attr outside the lock
    assert "self.ladder.level" in texts  # torn read through the alias


def test_lock_good_fixture_is_clean():
    v, _ = lock_discipline.check(root=REPO,
                                 ownership=_LOCK_GOOD_OWNERSHIP)
    assert v == []


def test_lock_stale_map_entry_fails():
    stale = {
        f"{FIX}/lock_good.py": {
            "Gauge": _cl(lock="_lock", attrs=("v", "renamed_attr")),
        },
    }
    v, _ = lock_discipline.check(root=REPO, ownership=stale)
    assert any("stale map entry" in x.message for x in v)


# -- knob registry -----------------------------------------------------------


def test_knob_bad_fixture_trips():
    v, _ = knob_registry.check(root=REPO, files=[f"{FIX}/knob_bad.py"])
    rules = _rules(v)
    assert rules["knob-direct-env"] == 3   # from-import, environ, getenv
    assert rules["knob-undeclared"] == 1   # LDT_NOT_DECLARED
    # module-level _CACHED_INFLIGHT + def-time default in g()
    assert rules["knob-mutable-cached"] == 2
    assert sum(rules.values()) == 6


def test_knob_good_fixture_clean_with_suppression():
    v, ns = knob_registry.check(root=REPO,
                                files=[f"{FIX}/knob_good.py"])
    assert v == []
    assert ns == 1                         # env passthrough, reasoned


def test_knob_table_generated_from_registry():
    table = knob_registry.generated_table(REPO)
    for name in ("LDT_LOCK_DEBUG", "LDT_MAX_QUEUE_DOCS",
                 "LDT_SLOW_TRACE_MS"):
        assert f"`{name}`" in table
    # the docs carry exactly this table between the markers (drift in
    # either direction is a knob-docs-drift violation on the live tree)
    text = (REPO / knob_registry.DOCS_REL).read_text()
    between = text.split(knob_registry.MARK_BEGIN, 1)[1] \
        .split(knob_registry.MARK_END, 1)[0].strip()
    assert between == table.strip()


# -- metric registry ---------------------------------------------------------


def test_metric_fixture_drift_both_directions():
    v, _ = metric_registry.check(
        root=REPO,
        files=[f"{FIX}/metrics_use.py"],
        telemetry_rel=f"{FIX}/metrics_mod.py",
        docs_rel=f"{FIX}/metrics_docs.md")
    rules = _rules(v)
    assert rules["metric-undeclared"] == 1      # ldt_fix_rogue_total
    assert rules["metric-unused"] == 1          # ldt_fix_unused_total
    # declared-but-undocumented (unused_total, undoc_total) plus the
    # stale doc token (ldt_fix_stale_total); the _count exposition
    # suffix on the documented series does NOT count as drift
    assert rules["metric-undocumented"] == 3
    names = "\n".join(x.message for x in v)
    assert "ldt_fix_stale_total" in names
    assert "ldt_fix_used_total" not in names


# -- event registry ----------------------------------------------------------


def test_event_fixture_drift_both_directions():
    from tools.lint import event_registry
    v, _ = event_registry.check(
        root=REPO,
        files=[f"{FIX}/events_use.py"],
        flightrec_rel=f"{FIX}/events_mod.py",
        docs_rel=f"{FIX}/events_docs.md")
    rules = _rules(v)
    assert rules["event-undeclared"] == 1       # fix_rogue
    assert rules["event-unused"] == 1           # fix_unused
    # declared-but-undocumented (fix_unused, fix_undoc) plus the stale
    # doc row (fix_stale); the prose mention of fix_unused OUTSIDE the
    # table markers must NOT count as documentation
    assert rules["event-undocumented"] == 3
    names = "\n".join(x.message for x in v)
    assert "fix_stale" in names
    assert "fix_used" not in names.replace("fix_unused", "")


# -- fault registry ----------------------------------------------------------


def test_fault_fixture_drift_both_directions():
    v, _ = faults_registry.check(
        root=REPO,
        files=[f"{FIX}/faults_use.py"],
        faults_rel=f"{FIX}/faults_mod.py",
        docs_rel=f"{FIX}/faults_docs.md")
    rules = _rules(v)
    assert rules["fault-undeclared"] == 1       # fix_rogue
    assert rules["fault-unused"] == 1           # fix_unused
    # declared-but-undocumented (fix_unused, fix_undoc) plus the stale
    # docs row (fix_stale); the token outside the markers doesn't count
    assert rules["fault-undocumented"] == 3
    names = "\n".join(x.message for x in v)
    assert "fix_stale" in names
    assert "fix_not_a_seam" not in names        # not rooted at `faults`
    assert "fix_used" not in names


def test_fault_live_points_all_hit():
    # the shipped seams cover every declared point, no rogue hits
    v, _ = faults_registry.check(root=REPO)
    assert [x for x in v if x.rule != "fault-undocumented"] == []


# -- whole-suite meta-checks -------------------------------------------------


def test_live_tree_is_clean():
    # the shipped package, docs, and ownership map pass their own lint
    assert run(root=REPO) == 0


def test_rule_filter_unknown_rule_exits_2():
    assert run(rules="not-a-rule", root=REPO) == 2


def test_rule_filter_single_family():
    assert run(rules="knob-registry", root=REPO) == 0
    assert run(rules="lock-discipline", root=REPO) == 0


def test_cli_entrypoint_clean():
    r = subprocess.run([sys.executable, "-m", "tools.lint"],
                       cwd=REPO, capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


# -- fsm conformance ---------------------------------------------------------


def _widget_machine(path, transitions=None):
    return fsm_registry.Machine(
        name="fixture-widget", file=path, scope=("class", "Widget"),
        kind="attr", var="_state",
        states={"IDLE": 0, "RUN": 1, "DONE": 2, "HALT": 3},
        initial="IDLE",
        transitions=frozenset(transitions or {
            ("IDLE", "RUN"), ("RUN", "DONE"),
            ("RUN", "IDLE"), ("DONE", "HALT")}))


def test_fsm_bad_fixture_trips_both_directions():
    v, _ = fsm_registry.check(
        root=REPO, machines=(_widget_machine(f"{FIX}/fsm_bad.py"),))
    rules = _rules(v)
    # wrong initial, undeclared guarded write, non-constant assignment
    assert rules["fsm-undeclared-transition"] == 3
    # RUN->DONE, RUN->IDLE, DONE->HALT declared but never written
    assert rules["fsm-dead-transition"] == 3
    assert sum(rules.values()) == 6


def test_fsm_good_fixture_is_clean():
    v, ns = fsm_registry.check(
        root=REPO, machines=(_widget_machine(f"{FIX}/fsm_good.py"),))
    assert v == []
    assert ns == 0


def test_fsm_stale_scope_entry_fails():
    stale = fsm_registry.Machine(
        name="gone", file=f"{FIX}/fsm_good.py",
        scope=("class", "Renamed"), kind="attr", var="_state",
        states={"IDLE": 0}, initial="IDLE", transitions=frozenset())
    v, _ = fsm_registry.check(root=REPO, machines=(stale,))
    assert len(v) == 1
    assert "Renamed" in v[0].message


def test_fsm_undeclared_state_constant_rejected_at_declaration():
    import pytest
    with pytest.raises(ValueError, match="not declared"):
        _widget_machine(f"{FIX}/fsm_good.py",
                        transitions={("IDLE", "SPRINT")})


def test_fsm_live_registry_is_clean():
    v, _ = fsm_registry.check(root=REPO)
    assert v == []


# -- future resolution -------------------------------------------------------


def test_future_bad_fixture_trips():
    v, _ = future_resolution.check(
        root=REPO, files=[f"{FIX}/future_bad.py"],
        consumers=((f"{FIX}/future_bad.py", "Consumer", "_drain"),))
    rules = _rules(v)
    assert rules["future-unresolved"] == 2       # branch leak, 0-iter loop
    assert rules["future-consumer-guard"] == 1   # swallowing handler
    assert sum(rules.values()) == 3


def test_future_good_fixture_is_clean():
    # resolve-on-both-branches, queue escape, pre-escape raise,
    # resolver closure, and a _fail-guarded consumer
    v, ns = future_resolution.check(
        root=REPO, files=[f"{FIX}/future_good.py"],
        consumers=((f"{FIX}/future_good.py", "Consumer", "_drain"),))
    assert v == []
    assert ns == 0


def test_future_stale_consumer_entry_fails():
    v, _ = future_resolution.check(
        root=REPO, files=[f"{FIX}/future_good.py"],
        consumers=((f"{FIX}/future_good.py", "Consumer", "_gone"),))
    assert any("no longer exists" in x.message for x in v)


def test_future_live_tree_suppression_counted():
    # pool._fetch's relaunch handler carries the one reasoned
    # suppression in the live tree (it re-raises via PoolExhausted)
    v, ns = future_resolution.check(root=REPO)
    assert v == []
    assert ns == 1


# -- jit contract ------------------------------------------------------------


def test_jit_bad_fixture_trips():
    v, _ = jit_contract.check(root=REPO, files=[f"{FIX}/jit_bad.py"])
    rules = _rules(v)
    assert rules["jit-donated-read"] == 1        # read after donate
    assert rules["jit-recompile-capture"] == 1   # loop-varying capture
    assert sum(rules.values()) == 2


def test_jit_good_fixture_is_clean():
    # rebind-after-donate and single-assignment factory captures are
    # the legal idioms
    v, ns = jit_contract.check(root=REPO, files=[f"{FIX}/jit_good.py"])
    assert v == []
    assert ns == 0


def test_jit_ring_bad_fixture_trips():
    # staging-ring donation pattern: a wire read between the donating
    # launch and its result future's resolution, and a wire whose
    # future was rebound before ever resolving
    v, _ = jit_contract.check(root=REPO,
                              files=[f"{FIX}/jit_ring_bad.py"])
    rules = _rules(v)
    assert rules["jit-donated-read"] == 2
    assert sum(rules.values()) == 2


def test_jit_ring_good_fixture_is_clean():
    # ring-slot reuse AFTER np.asarray(fut) / fut.block_until_ready()
    # is the legal staging pattern, including the engine's
    # resolve-and-read-in-one-statement fetch shape
    v, ns = jit_contract.check(root=REPO,
                               files=[f"{FIX}/jit_ring_good.py"])
    assert v == []
    assert ns == 0


def test_jit_live_device_path_is_clean():
    v, _ = jit_contract.check(root=REPO)
    assert v == []


# -- pallas entry points -----------------------------------------------------


def test_pallas_bad_fixture_trips():
    # the kernel body passed to pl.pallas_call traces under the same
    # rules as a jit entry, and input_output_aliases keys are donated
    # positions
    v, _ = trace_safety.check(root=REPO,
                              files=[f"{FIX}/pallas_bad.py"])
    rules = _rules(v)
    assert rules["trace-host-sync"] == 2        # float(), np.asarray
    assert rules["trace-python-branch"] == 1    # if v.sum() > 0:
    assert sum(rules.values()) == 3
    v, _ = jit_contract.check(root=REPO, files=[f"{FIX}/pallas_bad.py"])
    rules = _rules(v)
    assert rules["jit-donated-read"] == 1       # wire after aliased call
    assert sum(rules.values()) == 1


def test_pallas_good_fixture_is_clean():
    # shape reads, static range loops, jnp.where in the kernel body;
    # ring-slot reuse only after the aliased call's future resolves
    v, ns = trace_safety.check(root=REPO,
                               files=[f"{FIX}/pallas_good.py"])
    assert v == []
    assert ns == 0
    v, ns = jit_contract.check(root=REPO,
                               files=[f"{FIX}/pallas_good.py"])
    assert v == []
    assert ns == 0


# -- incremental (--changed) mode --------------------------------------------


def test_changed_scoping_runs_only_touched_scopes():
    # a device-path file: scoped analyzers cover it, drift analyzers
    # run whole-tree (they are only sound that way) — still clean
    assert run(root=REPO,
               changed={"language_detector_tpu/ops/score.py"}) == 0
    # a protocol file: layout/publish-order/torn-write scope to it
    # (one torn product runs, not all four) — still clean
    assert run(root=REPO,
               changed={"language_detector_tpu/capture.py"}) == 0
    # docs-only change: nothing to analyze, vacuously clean
    assert run(root=REPO, changed={"README.md"}) == 0
    assert run(root=REPO, changed=set()) == 0


def test_changed_cli_falls_back_to_full_on_lint_changes(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--changed"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    # clean either way; with tools/lint itself modified in the work
    # tree the CLI must announce the full-run fallback
    if "registry/analyzer files changed" in r.stderr:
        assert "clean" in r.stdout


# -- layout registry ---------------------------------------------------------

from tools.lint import layout_registry, publish_order  # noqa: E402

_LG = f"{FIX}/layout_good.py"
_LB = f"{FIX}/layout_bad.py"


def _layout(name, file, var, fmt, size, **kw):
    return layout_registry.Layout(
        name, file, var, fmt, size, ("a", "b", "c"), "fixture", **kw)


_LAYOUT_GOOD = (
    _layout("fix-rec", _LG, "REC", "<IHH", 8,
            writers=(f"{_LG}::write_rec",),
            readers=(f"{_LG}::read_rec",)),
)

_LAYOUT_BAD = (
    _layout("fix-rec", _LB, "REC", "<IHH", 8,
            writers=(f"{_LB}::write_rec",),
            readers=(f"{_LB}::read_rec",)),
    _layout("fix-gone", _LB, "GONE", "<I", 4),
    _layout("fix-word", _LB, "WORD", "<I", 4),
)


def test_layout_bad_fixture_trips_every_rule():
    v, _ = layout_registry.check(root=REPO, files=[_LB],
                                 layouts=_LAYOUT_BAD)
    rules = _rules(v)
    # REC format drift + REC missing width assert + GONE missing from
    # the module + WORD assert pinning the wrong width
    assert rules["layout-drift"] == 4
    # EXTRA module Struct, the inline "<ff" pack, the ad-hoc Struct
    assert rules["layout-undeclared"] == 3
    # declared writer/reader gone both ways + the undeclared stray
    assert rules["layout-reader-writer-mismatch"] == 3
    assert sum(rules.values()) == 10
    texts = "\n".join(x.message for x in v)
    assert "write_rec no longer packs" in texts
    assert "read_rec no longer unpacks" in texts
    assert "stray_writer packs layout 'fix-word'" in texts


def test_layout_good_fixture_clean_with_suppression():
    v, ns = layout_registry.check(root=REPO, files=[_LG],
                                  layouts=_LAYOUT_GOOD)
    assert v == []
    assert ns == 1                   # the reasoned SCRATCH suppression


def test_layout_live_docs_table_current():
    # the generated table matches docs/OBSERVABILITY.md verbatim (drift
    # either direction is a layout-drift violation on the live tree)
    table = layout_registry.generated_table()
    text = (REPO / layout_registry.DOCS_REL).read_text()
    between = text.split(layout_registry.MARK_BEGIN, 1)[1] \
        .split(layout_registry.MARK_END, 1)[0].strip()
    assert between == table.strip()


def test_layout_live_tree_is_clean():
    v, _ = layout_registry.check(root=REPO)
    assert v == []


# -- publish order -----------------------------------------------------------

_PG = f"{FIX}/publish_good.py"
_PB = f"{FIX}/publish_bad.py"


def _pub_layouts(rel, writers, readers, seq_writer=None):
    out = [_layout("fix-slot", rel, "HDR", "<IId", 16,
                   commit="seq", commit_slice=True,
                   pub_writers=writers, guard_readers=readers)]
    if seq_writer:
        out.append(_layout(
            "fix-seqslot", rel, "HDR", "<IId", 16, commit="seq",
            seqlock=True, commit_struct="SEQ",
            pub_writers=(seq_writer,),
            guard_readers=(f"{rel}::SeqSlot.get",) if "good" in rel
            else (), read_helpers=("_seq",)))
    return tuple(out)


def test_publish_bad_fixture_trips_every_failure_mode():
    layouts = _pub_layouts(
        _PB,
        writers=(f"{_PB}::bad_write_after_commit",
                 f"{_PB}::bad_commit_first",
                 f"{_PB}::bad_never_commit"),
        readers=(f"{_PB}::bad_reader_no_commit",
                 f"{_PB}::bad_reader_unguarded"),
        seq_writer=f"{_PB}::SeqBad.put")
    v, _ = publish_order.check(root=REPO, files=[_PB], layouts=layouts)
    assert all(x.rule == "publish-order" for x in v)
    assert len(v) == 6
    texts = "\n".join(x.message for x in v)
    assert "write-after-commit" in texts
    assert "commit-before-payload" in texts
    assert "never stores the commit word" in texts
    assert "breaks the seqlock sequence" in texts
    assert "never reads the commit word" in texts
    assert "does not re-validate" in texts


def test_publish_good_fixture_is_clean():
    layouts = _pub_layouts(
        _PG,
        writers=(f"{_PG}::write_rec",),
        readers=(f"{_PG}::read_rec",),
        seq_writer=f"{_PG}::SeqSlot.put")
    v, ns = publish_order.check(root=REPO, files=[_PG],
                                layouts=layouts)
    assert v == []
    assert ns == 0


def test_publish_stale_registry_entry_fails():
    layouts = _pub_layouts(
        _PG, writers=(f"{_PG}::renamed_away",), readers=())
    v, _ = publish_order.check(root=REPO, files=[_PG],
                               layouts=layouts)
    assert len(v) == 1
    assert "does not exist" in v[0].message


def test_publish_live_tree_is_clean():
    v, _ = publish_order.check(root=REPO)
    assert v == []
