// Batched document epilogue: DocTote replay + close pairs + unreliable
// removal + summary language, per document over the device scorer's
// [B, C, 5] chunk summaries.
//
// C++ twin of the oracle-validated Python epilogue in models/ngram.py
// _doc_epilogue + engine_scalar.py (refine_close_pairs :469,
// remove_unreliable :495, extract_lang_etc :543, calc_summary_lang :594),
// which in turn mirrors the reference document pipeline
// (compact_lang_det_impl.cc:1956-2106; DocTote tote.cc:127-252).
// tests/test_native_epilogue.py asserts array equality against the Python
// path on the golden suite and on randomized chunk summaries.
//
// O(1) per document, no allocation; the per-doc loop is trivially
// parallel but single-threaded here (it runs at ~1us/doc).

#include <cstdint>
#include <cstring>

#include "ldt_internal.h"

namespace {

constexpr int kMax = 24;
constexpr int kUnused = 0xFFFF;
constexpr int kUnknown = 26;       // UNKNOWN_LANGUAGE
constexpr int kTgUnknown = 25;     // TG_UNKNOWN_LANGUAGE
constexpr int kEnglish = 0;

constexpr int kMinReliableKeepPercent = 41;
constexpr int kNonEnBoilerplateMinPercent = 17;
constexpr int kNonFigsBoilerplateMinPercent = 20;
constexpr int kGoodFirstMinPercent = 26;
constexpr int kGoodFirstReliableMinPercent = 51;
constexpr int kIgnoreMaxPercent = 20;
constexpr int kKeepMinPercent = 2;
constexpr int kGoodSecondT1T2MinBytes = 15;
constexpr int kGoodLang1Percent = 70;
constexpr int kGoodLang1and2Percent = 93;
constexpr int kShortTextThresh = 256;

constexpr int kFlagFinish = 1;
constexpr int kFlagBestEffort = 0x4000;

struct Reg {
  const int32_t* close_set;    // [n_lang]
  const int32_t* closest_alt;  // [n_lang] (kUnknown when none)
  const uint8_t* is_figs;      // [n_lang]
  int n_lang;

  int close(int lang) const {
    return (lang >= 0 && lang < n_lang) ? close_set[lang] : 0;
  }
  int alt(int lang) const {
    return (lang >= 0 && lang < n_lang) ? closest_alt[lang] : kUnknown;
  }
  bool figs(int lang) const {
    return lang >= 0 && lang < n_lang && is_figs[lang];
  }
  bool efigs(int lang) const { return lang == kEnglish || figs(lang); }
};

struct DocTote {
  int64_t key[kMax];
  int64_t value[kMax];
  int64_t score[kMax];
  int64_t rel[kMax];

  void init() {
    for (int i = 0; i < kMax; i++) {
      key[i] = kUnused;
      value[i] = score[i] = rel[i] = 0;
    }
  }

  // tote.cc:127-177 3-way set-associative insert with smallest-victim
  // eviction (engine_scalar.py DocTote.add)
  void add(int lang, int64_t nbytes, int64_t s, int64_t reliability) {
    int subs[3] = {lang & 15, (lang & 15) ^ 8, (lang & 7) + 16};
    for (int s3 : subs) {
      if (key[s3] == lang) {
        value[s3] += nbytes;
        score[s3] += s;
        rel[s3] += reliability * nbytes;
        return;
      }
    }
    int alloc = -1;
    for (int s3 : subs) {
      if (key[s3] == kUnused) { alloc = s3; break; }
    }
    if (alloc < 0) {
      alloc = subs[0];
      for (int s3 : subs) {
        if (value[s3] < value[alloc]) alloc = s3;
      }
    }
    key[alloc] = lang;
    value[alloc] = nbytes;
    score[alloc] = s;
    rel[alloc] = reliability * nbytes;
  }

  int find(int lang) const {
    for (int i = 0; i < kMax; i++) {
      if (key[i] == lang) return i;
    }
    return -1;
  }

  // stable sort by decreasing byte count, UNUSED last (tote.cc:221-250)
  void sort() {
    for (int i = 0; i < kMax; i++) {
      if (key[i] == kUnused) value[i] = -1;
    }
    // insertion sort, stable, 24 elements
    for (int i = 1; i < kMax; i++) {
      int64_t k = key[i], v = value[i], s = score[i], r = rel[i];
      int j = i - 1;
      while (j >= 0 && value[j] < v) {
        key[j + 1] = key[j];
        value[j + 1] = value[j];
        score[j + 1] = score[j];
        rel[j + 1] = rel[j];
        j--;
      }
      key[j + 1] = k;
      value[j + 1] = v;
      score[j + 1] = s;
      rel[j + 1] = r;
    }
  }
};

// RefineScoredClosePairs (impl.cc:1154-1203)
void refine_close_pairs(const Reg& reg, DocTote* t) {
  for (int sub = 0; sub < kMax; sub++) {
    int lang = (int)t->key[sub];
    if (lang == kUnused) continue;
    int cs = reg.close(lang);
    if (cs == 0) continue;
    for (int sub2 = sub + 1; sub2 < kMax; sub2++) {
      int lang2 = (int)t->key[sub2];
      if (lang2 == kUnused || reg.close(lang2) != cs) continue;
      int frm = sub, to = sub2;
      if (t->value[sub] >= t->value[sub2]) { frm = sub2; to = sub; }
      t->value[to] += t->value[frm];
      t->score[to] += t->score[frm];
      t->rel[to] += t->rel[frm];
      t->key[frm] = kUnused;
      t->value[frm] = t->score[frm] = t->rel[frm] = 0;
      break;
    }
  }
}

// RemoveUnreliableLanguages (impl.cc:997-1101)
void remove_unreliable(const Reg& reg, DocTote* t) {
  for (int sub = 0; sub < kMax; sub++) {
    int lang = (int)t->key[sub];
    if (lang == kUnused) continue;
    int64_t nbytes = t->value[sub];
    if (nbytes == 0) continue;
    int64_t pct = t->rel[sub] / nbytes;
    if (pct >= kMinReliableKeepPercent) continue;
    int alt = reg.alt(lang);
    if (alt == kUnknown) continue;
    int altsub = t->find(alt);
    if (altsub < 0) continue;
    int64_t bytes2 = t->value[altsub];
    if (bytes2 == 0) continue;
    int64_t pct2 = t->rel[altsub] / bytes2;
    int tosub = altsub, fromsub = sub;
    if (pct2 < pct || (pct2 == pct && lang < alt)) {
      tosub = sub;
      fromsub = altsub;
    }
    int64_t newpct = pct > pct2 ? pct : pct2;
    if (newpct < kMinReliableKeepPercent) newpct = kMinReliableKeepPercent;
    int64_t newbytes = nbytes + bytes2;
    t->key[fromsub] = kUnused;
    t->score[fromsub] = 0;
    t->rel[fromsub] = 0;
    t->score[tosub] = newbytes;  // reference stores bytes via SetScore
    t->rel[tosub] = newpct * newbytes;
  }
  for (int sub = 0; sub < kMax; sub++) {
    if (t->key[sub] == kUnused) continue;
    int64_t nbytes = t->value[sub];
    if (nbytes == 0) continue;
    if (t->rel[sub] / nbytes < kMinReliableKeepPercent) {
      t->key[sub] = kUnused;
      t->score[sub] = 0;
      t->rel[sub] = 0;
    }
  }
}

struct Extract {
  int lang3[3];
  int percent3[3];
  int rel3[3];
  int64_t ns3[3];   // integer-valued normalized score (score<<10)/bytes
  int64_t total;
  bool is_reliable;
};

// ExtractLangEtc (impl.cc:1276-1384)
void extract_lang_etc(const DocTote& t, int64_t total_text_bytes,
                      Extract* e) {
  int64_t bc[3] = {0, 0, 0};
  for (int i = 0; i < 3; i++) {
    e->lang3[i] = kUnknown;
    e->percent3[i] = 0;
    e->rel3[i] = 0;
    e->ns3[i] = 0;
    int lang = (int)t.key[i];
    if (lang != kUnused && lang != kUnknown) {
      e->lang3[i] = lang;
      bc[i] = t.value[i];
      int64_t d = bc[i] > 0 ? bc[i] : 1;
      e->rel3[i] = (int)(t.rel[i] / d);
      e->ns3[i] = bc[i] ? ((t.score[i] << 10) / bc[i]) : 0;
    }
  }
  int64_t total12 = bc[0] + bc[1];
  int64_t total123 = total12 + bc[2];
  int64_t total = total_text_bytes > total123 ? total_text_bytes : total123;
  int64_t div = total > 1 ? total : 1;
  e->percent3[0] = (int)(bc[0] * 100 / div);
  e->percent3[1] = (int)(total12 * 100 / div);
  e->percent3[2] = (int)(total123 * 100 / div);
  e->percent3[2] -= e->percent3[1];
  e->percent3[1] -= e->percent3[0];
  if (e->percent3[1] < e->percent3[2]) {
    e->percent3[1]++;
    e->percent3[2]--;
  }
  if (e->percent3[0] < e->percent3[1]) {
    e->percent3[0]++;
    e->percent3[1]--;
  }
  e->total = total;
  e->is_reliable = false;
  if (e->lang3[0] != kUnknown) {
    e->is_reliable = e->rel3[0] >= kMinReliableKeepPercent;
  }
  int ignore = 100 - (e->percent3[0] + e->percent3[1] + e->percent3[2]);
  if (ignore > kIgnoreMaxPercent) e->is_reliable = false;
}

// CalcSummaryLang (impl.cc:1414-1522)
void calc_summary_lang(const Reg& reg, const Extract& e,
                       int64_t total_text_bytes, int flags, int* summary_out,
                       bool* reliable_out) {
  const int* lang3 = e.lang3;
  const int* percent3 = e.percent3;
  int slot[3] = {0, 1, 2};
  int slot_count = 3;
  int ignore_percent = 0;
  int return_percent = percent3[0];
  int summary = lang3[0];
  bool reliable = true;
  if (percent3[0] < kKeepMinPercent) reliable = false;

  for (int i = 0; i < 3; i++) {
    if (lang3[i] == kTgUnknown) {
      ignore_percent += percent3[i];
      for (int j = i + 1; j < 3; j++) slot[j - 1] = slot[j];
      slot_count--;
      return_percent = (percent3[0] * 100) / (101 - ignore_percent);
      summary = lang3[slot[0]];
      if (percent3[slot[0]] < kKeepMinPercent) reliable = false;
    }
  }

  int64_t second_bytes = total_text_bytes * percent3[slot[1]] / 100;
  if (lang3[slot[0]] == kEnglish && lang3[slot[1]] != kEnglish &&
      lang3[slot[1]] != kUnknown &&
      percent3[slot[1]] >= kNonEnBoilerplateMinPercent &&
      second_bytes >= kGoodSecondT1T2MinBytes) {
    ignore_percent += percent3[slot[0]];
    return_percent = (percent3[slot[1]] * 100) / (101 - ignore_percent);
    summary = lang3[slot[1]];
    if (percent3[slot[1]] < kKeepMinPercent) reliable = false;
  } else if (reg.figs(lang3[slot[0]]) && !reg.efigs(lang3[slot[1]]) &&
             lang3[slot[1]] != kUnknown &&
             percent3[slot[1]] >= kNonFigsBoilerplateMinPercent &&
             second_bytes >= kGoodSecondT1T2MinBytes) {
    ignore_percent += percent3[slot[0]];
    return_percent = (percent3[slot[1]] * 100) / (101 - ignore_percent);
    summary = lang3[slot[1]];
    if (percent3[slot[1]] < kKeepMinPercent) reliable = false;
  } else if (lang3[slot[1]] == kEnglish && lang3[slot[0]] != kEnglish) {
    ignore_percent += percent3[slot[1]];
    return_percent = (percent3[slot[0]] * 100) / (101 - ignore_percent);
  } else if (reg.figs(lang3[slot[1]]) && !reg.efigs(lang3[slot[0]])) {
    ignore_percent += percent3[slot[1]];
    return_percent = (percent3[slot[0]] * 100) / (101 - ignore_percent);
  }

  if (return_percent < kGoodFirstMinPercent && !(flags & kFlagBestEffort)) {
    summary = kUnknown;
    reliable = false;
  }
  if (return_percent < kGoodFirstReliableMinPercent) reliable = false;
  ignore_percent = 100 - (percent3[0] + percent3[1] + percent3[2]);
  if (ignore_percent > kIgnoreMaxPercent) reliable = false;
  if (slot_count == 0) {
    summary = kUnknown;
    reliable = false;
  }
  *summary_out = summary;
  *reliable_out = reliable;
}

}  // namespace

extern "C" {

// Batched document epilogue. Output layout per doc (int64, 14 lanes):
//   0 summary | 1-3 lang3 | 4-6 percent3 | 7-9 ns3 | 10 text_bytes
//   11 is_reliable | 12 need_scalar (good-answer gate failed ->
//   caller runs the batched recursion) | 13 unused
// Chunk summaries arrive as one
// flat [G, 5] array (all docs' chunks concatenated, the device layout of
// the flat wire) and each doc owns rows [doc_chunk_start[b],
// doc_chunk_start[b] + n_chunks[b]). direct_adds chunk ids stay
// doc-local. Same output contract.
void ldt_epilogue_flat(
    const int32_t* rows,             // [G, 5] lang1, bytes, score1, rel, real
    const int64_t* doc_chunk_start,  // [B] doc's first chunk row
    const int32_t* n_chunks,         // [B]
    const int32_t* direct,           // [B, D, 3] chunk_id, lang, bytes
    const int32_t* text_bytes,       // [B]
    const uint8_t* skip,             // [B] nonzero = packer fallback
    int32_t B, int32_t D, int32_t flags,
    const int32_t* close_set, const int32_t* closest_alt,
    const uint8_t* is_figs, int32_t n_lang,
    int64_t* out) {                  // [B, 14]
  Reg reg{close_set, closest_alt, is_figs, n_lang};
  for (int b = 0; b < B; b++) {
    int64_t* o = out + (int64_t)b * 14;
    std::memset(o, 0, 14 * sizeof(int64_t));
    if (skip && skip[b]) {
      o[12] = 1;  // scalar path decides everything
      continue;
    }
    DocTote t;
    t.init();
    const int32_t* dd = direct + (int64_t)b * D * 3;
    const int32_t* rr = rows + doc_chunk_start[b] * 5;
    int nd = 0;
    while (nd < D && dd[nd * 3] >= 0) nd++;
    for (int c = 0; c < n_chunks[b]; c++) {
      bool is_direct = false;
      for (int d = 0; d < nd; d++) {
        if (dd[d * 3] == c) {
          t.add(dd[d * 3 + 1], dd[d * 3 + 2], dd[d * 3 + 2], 100);
          is_direct = true;
          break;
        }
      }
      if (!is_direct && rr[c * 5 + 4]) {
        t.add(rr[c * 5], rr[c * 5 + 1], rr[c * 5 + 2], rr[c * 5 + 3]);
      }
    }

    refine_close_pairs(reg, &t);
    t.sort();
    Extract e;
    extract_lang_etc(t, text_bytes[b], &e);

    bool good = (flags & kFlagFinish) || e.total <= kShortTextThresh ||
                (e.is_reliable && e.percent3[0] >= kGoodLang1Percent) ||
                (e.is_reliable &&
                 e.percent3[0] + e.percent3[1] >= kGoodLang1and2Percent);
    if (!good) {
      o[12] = 1;
      continue;
    }

    if (!(flags & kFlagBestEffort)) remove_unreliable(reg, &t);
    t.sort();
    extract_lang_etc(t, text_bytes[b], &e);
    int summary;
    bool reliable;
    calc_summary_lang(reg, e, e.total, flags, &summary, &reliable);

    o[0] = summary;
    for (int i = 0; i < 3; i++) {
      o[1 + i] = e.lang3[i];
      o[4 + i] = e.percent3[i];
      o[7 + i] = e.ns3[i];
    }
    o[10] = e.total;
    o[11] = (e.is_reliable && reliable) ? 1 : 0;
  }
}

}  // extern "C"
