"""Scriptable stand-in worker for the supervisor tests
(tests/test_supervisor.py). Behavior is driven by env vars so the
supervisor can run it with its normal `python -m <module>` spawn:

  FAKE_WORKER_EXIT       exit immediately with this code
  FAKE_WORKER_RECYCLE    path to a marker file: first run (no marker)
                         creates it and exits with RECYCLE_EXIT_CODE;
                         the restarted run sees the marker and exits 0
  FAKE_WORKER_CRASH_UNTIL  "path:N" — a run counter lives at path; each
                         run increments it and crashes (exit 9) until N
                         runs have crashed, then exits 0. Exercises the
                         supervisor's restart-on-crash backoff path.
  FAKE_WORKER_SIGFILE    install a SIGTERM/SIGINT handler that writes
                         the signal number to this path and exits 0;
                         the worker then waits (bounded) to be signaled
  FAKE_WORKER_SERVE      path to a directory: drop gen-<N>.up there at
                         start, honor the LDT_READY_FILE handshake the
                         supervisor's swap drill polls for (write the
                         ready JSON once "serving"), then wait for
                         SIGTERM/SIGINT and exit 0 — a scriptable
                         generation for the blue/green drill tests
  FAKE_WORKER_STANDBY_CRASH  with FAKE_WORKER_SERVE: if this run is the
                         standby (LDT_SWAPPED set), exit 9 before the
                         ready file — exercises the drill's abort path
  FAKE_WORKER_CRASH_FILE  with FAKE_WORKER_SERVE: poll this path while
                         serving; when it appears, CONSUME it (unlink)
                         and exit with the int it contains (default 9).
                         Lets fleet tests kill one specific member —
                         and exactly once, so the respawn serves.
  FAKE_WORKER_READY_DELAY  with FAKE_WORKER_SERVE: sleep this many
                         seconds between the .up marker and the ready
                         file — holds a fleet roll/spawn in its
                         not-yet-ready window so tests can race it.

Every path-valued variable substitutes %SLOT% with LDT_FLEET_SLOT (or
"0"), so one template addresses per-member files in a fleet. Every run
prints one JSON line with the LDT_WORKER_GENERATION and LDT_FLEET_SLOT
it was handed, so tests can assert the supervisor numbers its children.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import time

from language_detector_tpu.service.recycle import RECYCLE_EXIT_CODE


def _path(name: str) -> str | None:
    """Env lookup with %SLOT% substitution for path-valued modes."""
    val = os.environ.get(name)
    if val is None:
        return None
    return val.replace("%SLOT%", os.environ.get("LDT_FLEET_SLOT", "0"))


def main() -> int:
    print(json.dumps({
        "fake_worker_generation":
            os.environ.get("LDT_WORKER_GENERATION", "unset"),
        "fake_worker_slot":
            os.environ.get("LDT_FLEET_SLOT", "unset"),
        "fake_worker_cache_dir":
            os.environ.get("LDT_COMPILE_CACHE_DIR", "unset"),
    }), flush=True)

    exit_code = os.environ.get("FAKE_WORKER_EXIT")
    if exit_code is not None:
        return int(exit_code)

    crash_until = _path("FAKE_WORKER_CRASH_UNTIL")
    if crash_until is not None:
        path, _, n = crash_until.rpartition(":")
        runs = 0
        if os.path.exists(path):
            with open(path) as f:
                runs = int(f.read() or "0")
        runs += 1
        with open(path, "w") as f:
            f.write(str(runs))
        return 9 if runs <= int(n) else 0

    marker = _path("FAKE_WORKER_RECYCLE")
    if marker is not None:
        if os.path.exists(marker):
            return 0  # second generation: a clean exit ends the loop
        with open(marker, "w") as f:
            f.write("recycled")
        return RECYCLE_EXIT_CODE

    serve_dir = _path("FAKE_WORKER_SERVE")
    if serve_dir is not None:
        gen = os.environ.get("LDT_WORKER_GENERATION", "0")
        crash_file = _path("FAKE_WORKER_CRASH_FILE")
        ready_delay = float(
            os.environ.get("FAKE_WORKER_READY_DELAY") or 0)
        stop = []

        def on_stop(signum, frame):
            stop.append(signum)

        # handlers BEFORE the .up marker: tests treat the marker as
        # "safe to signal", so the install must already be done
        signal.signal(signal.SIGTERM, on_stop)
        signal.signal(signal.SIGINT, on_stop)
        with open(os.path.join(serve_dir, f"gen-{gen}.up"), "w") as f:
            f.write(str(os.getpid()))
        ready_file = os.environ.get("LDT_READY_FILE")
        if ready_file:
            if os.environ.get("FAKE_WORKER_STANDBY_CRASH") and \
                    os.environ.get("LDT_SWAPPED"):
                return 9  # standby dies before ready: drill must abort
            if ready_delay:
                time.sleep(ready_delay)
            with open(ready_file, "w") as f:
                json.dump({"generation": int(gen), "pid": os.getpid(),
                           "port": 0, "metrics_port": 0,
                           "warmup_ms": 0.0}, f)
        deadline = time.time() + 60
        while time.time() < deadline and not stop:
            if crash_file and os.path.exists(crash_file):
                with open(crash_file) as f:
                    code = f.read().strip()
                os.remove(crash_file)  # consume: the respawn serves
                return int(code or "9")
            time.sleep(0.05)
        return 0 if stop else 3

    sigfile = _path("FAKE_WORKER_SIGFILE")
    if sigfile is not None:
        def on_signal(signum, frame):
            with open(sigfile, "w") as f:
                f.write(str(signum))
            sys.exit(0)

        signal.signal(signal.SIGTERM, on_signal)
        signal.signal(signal.SIGINT, on_signal)
        # announce readiness so the test doesn't signal a worker that
        # has not installed its handler yet
        ready = sigfile + ".ready"
        with open(ready, "w") as f:
            f.write(str(os.getpid()))
        deadline = time.time() + 30
        while time.time() < deadline:
            time.sleep(0.05)
        return 3  # never signaled: fail loudly
    return 0


if __name__ == "__main__":
    sys.exit(main())
