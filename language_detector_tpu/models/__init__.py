from .ngram import NgramBatchEngine

__all__ = ["NgramBatchEngine"]
