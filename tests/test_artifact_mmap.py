"""Single-file mmap artifact (artifact.py + tables.load_mmap).

The serving-format twin of the reference's dynamic-data file
(cld2_dynamic_data.h:23-110): one aligned little-endian blob, loaded as
zero-copy views over a single mapping, bit-identical to the npz pair.
"""
from __future__ import annotations

from pathlib import Path

import numpy as np
import pytest

from language_detector_tpu.artifact import load_artifact, write_artifact
from language_detector_tpu.tables import ScoringTables

DATA = Path(__file__).resolve().parent.parent / \
    "language_detector_tpu" / "data"


def test_round_trip(tmp_path):
    rng = np.random.default_rng(3)
    arrays = {
        "a/ints": rng.integers(0, 1 << 31, (7, 3)).astype(np.int64),
        "b/bytes": rng.integers(0, 255, 1000).astype(np.uint8),
        "c/f32": rng.random((2, 2, 2)).astype(np.float32),
        "d/strs": np.array(["alpha", "βήτα", ""]),
        "e/empty": np.zeros(0, np.uint32),
        "f/zerodim": np.array("one long scalar string"),
    }
    p = tmp_path / "t.ldta"
    write_artifact(arrays, p)
    back = load_artifact(p)
    assert set(back) == set(arrays)
    for k, a in arrays.items():
        assert np.array_equal(np.asarray(back[k]), a), k
        assert back[k].dtype == a.dtype, k
        assert back[k].shape == a.shape, k


def test_zero_copy_views(tmp_path):
    p = tmp_path / "t.ldta"
    write_artifact({"x": np.arange(1024, dtype=np.uint32)}, p)
    back = load_artifact(p)
    # views, not copies: numpy must not own the data (it references the
    # shared mmap buffer)
    assert not back["x"].flags["OWNDATA"]


def test_truncation_detected(tmp_path):
    p = tmp_path / "t.ldta"
    write_artifact({"x": np.arange(4096, dtype=np.uint32)}, p)
    data = p.read_bytes()
    p.write_bytes(data[:-64])
    with pytest.raises(ValueError, match="truncated|size"):
        load_artifact(p)


def test_packed_artifact_matches_npz():
    """data/model.ldta (committed, built by artifact_tool --pack) loads
    into a ScoringTables bit-identical to the npz pair."""
    ldta = DATA / "model.ldta"
    if not ldta.exists():
        pytest.skip("model.ldta not packed")
    t_npz = ScoringTables.load()
    t_map = ScoringTables.load_mmap(ldta)
    for field in ("cjk_uni_prop", "avg_delta_octa_score", "lg_prob",
                  "script_of_cp", "lower_pairs", "interchange_ok",
                  "entity_values", "tld_hint_prior1"):
        assert np.array_equal(getattr(t_map, field),
                              getattr(t_npz, field)), field
    for tbl in ("quadgram", "quadgram2", "deltaocta", "distinctocta",
                "cjkdeltabi", "distinctbi", "cjkcompat"):
        a, b = getattr(t_map, tbl), getattr(t_npz, tbl)
        assert np.array_equal(a.buckets, b.buckets), tbl
        assert np.array_equal(a.ind, b.ind), tbl
        assert (a.size_one, a.size, a.keymask) == \
            (b.size_one, b.size, b.keymask), tbl


def test_detection_over_mmap_tables():
    """End-to-end: detection over mmap-loaded tables equals detection
    over npz-loaded tables (the scalar engine exercises every table)."""
    ldta = DATA / "model.ldta"
    if not ldta.exists():
        pytest.skip("model.ldta not packed")
    from language_detector_tpu.engine_scalar import detect_scalar
    from language_detector_tpu.registry import registry
    t_npz = ScoringTables.load()
    t_map = ScoringTables.load_mmap(ldta)
    for text in ("Le gouvernement a annoncé de nouvelles mesures",
                 "こんにちは世界。今日はとても良い天気ですね。",
                 "ภาษาไทยเป็นภาษาที่สวยงาม",
                 "Der Hund läuft schnell durch den großen Wald heute"):
        a = detect_scalar(text, t_map, registry)
        b = detect_scalar(text, t_npz, registry)
        assert (a.summary_lang, a.language3, a.percent3) == \
            (b.summary_lang, b.language3, b.percent3), text


def test_empty_artifact_is_typed(tmp_path):
    """An empty file (open() succeeded, nothing written yet) is a typed
    ArtifactError, not mmap's raw 'cannot mmap an empty file'."""
    from language_detector_tpu.artifact import ArtifactError
    p = tmp_path / "empty.ldta"
    p.write_bytes(b"")
    with pytest.raises(ArtifactError, match="shorter than"):
        load_artifact(p)


def test_missing_artifact_is_typed(tmp_path):
    from language_detector_tpu.artifact import ArtifactError
    with pytest.raises(ArtifactError, match="cannot open"):
        load_artifact(tmp_path / "never-written.ldta")


def test_half_written_artifact_aborts_swap_cleanly(tmp_path):
    """A half-written pack (ENOSPC / packer died mid-write) fails
    size-vs-header validation BEFORE the mmap exists, with an
    actionable typed error — and ScoringTables.load_mmap surfaces the
    same ArtifactError, so a swap drill aborts on the old tables
    instead of dying on a raw OSError."""
    from language_detector_tpu.artifact import ArtifactError
    p = tmp_path / "half.ldta"
    write_artifact({"x": np.arange(8192, dtype=np.uint32)}, p)
    data = p.read_bytes()
    p.write_bytes(data[:len(data) // 2])
    with pytest.raises(ArtifactError, match="half-written|truncated"):
        load_artifact(p)
    with pytest.raises(ArtifactError):
        ScoringTables.load_mmap(p)


def test_short_garbage_header_is_typed(tmp_path):
    """A few stray bytes (shorter than the header struct) are refused
    before fstat-vs-header comparison can even run."""
    from language_detector_tpu.artifact import ArtifactError
    p = tmp_path / "stub.ldta"
    p.write_bytes(b"LD")
    with pytest.raises(ArtifactError, match="shorter than"):
        load_artifact(p)
