"""HTTP service integration tests against a live local server.

Mirrors the reference's Go suite (main_test.go:24-345): usage, 404, bad
JSON, wrong content type, missing text keys with per-item errors, valid
detection with exact response shapes, mention/link stripping, and the
metrics endpoint. The server runs in-process on ephemeral ports with the
scalar engine (use_device=False keeps the suite off the accelerator and
deterministic).
"""
from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from language_detector_tpu.service.server import (DetectorService,
                                                  make_server, strip_extras)


@pytest.fixture(scope="module")
def server():
    svc = DetectorService(use_device=False, max_delay_ms=1.0)
    httpd, metricsd, svc = make_server(0, 0, service=svc)
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in (httpd, metricsd)]
    for t in threads:
        t.start()
    yield {"url": f"http://127.0.0.1:{httpd.server_address[1]}",
           "metrics_url": f"http://127.0.0.1:{metricsd.server_address[1]}",
           "svc": svc}
    httpd.shutdown()
    metricsd.shutdown()
    svc.batcher.close()


def _post(url, payload, content_type="application/json", raw=None):
    data = raw if raw is not None else json.dumps(payload).encode()
    req = urllib.request.Request(url, data=data, method="POST",
                                 headers={"Content-Type": content_type})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, json.loads(body) if body else None


def _get(url):
    try:
        with urllib.request.urlopen(url) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_usage(server):
    status, body = _get(server["url"] + "/")
    assert status == 200
    doc = json.loads(body)
    assert doc["result"]["id"] == "language-detector"
    assert doc["result"]["out"]["iso6391code"] == {"type": "string"}


def test_not_found(server):
    status, body = _get(server["url"] + "/nope")
    assert status == 404
    assert json.loads(body) == {"error": "Not found"}


def test_wrong_content_type(server):
    status, body = _post(server["url"], {"request": []},
                         content_type="text/plain")
    assert status == 400
    assert body == {"error": "Content-Type must be set to application/json"}


def test_bad_json(server):
    status, body = _post(server["url"], None, raw=b"{nope")
    assert status == 400
    assert body == {"error":
                    "Unable to parse request - invalid JSON detected"}


def test_missing_request_key(server):
    status, body = _post(server["url"], {"nope": []})
    assert status == 400
    assert body == {"error":
                    "Unable to parse request - invalid JSON detected"}


def test_missing_text_key_keeps_batch_going(server):
    status, body = _post(server["url"], {"request": [
        {"text": "Le gouvernement a annoncé de nouvelles mesures pour "
                 "aider les familles concernées"},
        {"wrong": "key"},
        {"text": "こんにちは世界、今日はとても良い天気ですね"},
    ]})
    assert status == 400
    assert body["response"][0] == {"iso6391code": "fr", "name": "French"}
    assert body["response"][1] == {"error": "Missing text key"}
    assert body["response"][2] == {"iso6391code": "ja", "name": "Japanese"}


def test_valid_detection_exact_body(server):
    status, body = _post(server["url"], {"request": [
        {"text": "this is a simple english sentence with common words "
                 "that should be detected without any trouble at all"},
    ]})
    assert status == 200
    assert body == {"response": [{"iso6391code": "en", "name": "English"}]}


def test_mention_and_link_stripping(server):
    assert strip_extras("hello @user world") == "hello world "
    assert strip_extras("see https://x.example and http://y.example now"
                        ) == "see and now "
    status, body = _post(server["url"], {"request": [
        {"text": "@someone https://t.co/xyz Le gouvernement a annoncé de "
                 "nouvelles mesures pour aider les familles"},
    ]})
    assert status == 200
    assert body["response"][0]["iso6391code"] == "fr"


def test_unknown_language_203(server):
    status, body = _post(server["url"], {"request": [{"text": "?!"}]})
    assert status == 203
    assert body["response"][0] == {"iso6391code": "un", "name": "Unknown"}


def test_empty_request_list(server):
    status, body = _post(server["url"], {"request": []})
    assert status == 200
    assert body == {"response": []}


def test_oversized_body_rejected(server):
    # >1MB body -> 413 without reading the payload, and the connection
    # is closed (the unread body would otherwise poison keep-alive)
    big = b'{"request": [{"text": "' + b"a" * 1_100_000 + b'"}]}'
    status, body = _post(server["url"], None, raw=big)
    assert status == 413
    assert body == {"error": "Request body exceeds 1MB limit"}
    # regression: the server must still answer fresh requests after
    # rejecting the oversized one
    status, body = _post(server["url"], {"request": [
        {"text": "this is a simple english sentence with common words "
                 "that should be detected without any trouble at all"}]})
    assert status == 200
    assert body["response"][0]["iso6391code"] == "en"


def test_metrics_endpoint(server):
    status, body = _get(server["metrics_url"] + "/metrics")
    assert status == 200
    text = body.decode()
    assert "augmentation_requests_total" in text
    assert 'augmentation_objects_processed_total{status="successful"}' \
        in text
    assert 'augmentation_detected_language{language="French"}' in text


def test_codes_match_reference_data():
    """Generated code->name map agrees with the reference's
    data/cld_codes.json on every shared code (gen_service_codes.py)."""
    from pathlib import Path
    ref_path = Path("/root/reference/data/cld_codes.json")
    if not ref_path.exists():
        pytest.skip("reference snapshot unavailable")
    mine = json.loads((Path(__file__).resolve().parent.parent /
                       "language_detector_tpu/service/cld_codes.json")
                      .read_text())
    ref = json.loads(ref_path.read_text())
    diffs = {k: (mine[k], ref[k]) for k in ref
             if k in mine and mine[k] != ref[k]}
    assert not diffs
    # every service-relevant reference code except legacy renames exists
    missing = set(ref) - set(mine) - {"mo", "sit", "sr-me", "zhT"}
    assert not missing


def test_mixed_traffic_batch(server):
    """Spam, long, degenerate, and normal docs in one request: every item
    gets a well-formed response in order (per-item resilience,
    handlers.go:133-160 contract)."""
    texts = [
        "le monde est grand et la vie est belle pour tous les hommes",
        "buy cheap now " * 300,                       # squeeze spam
        " ".join("Le gouvernement a annoncé de nouvelles mesures."
                 for _ in range(120)),                # long doc
        "",                                           # empty
        "国民の大多数が内閣を支持し、集団的自衛権の行使を認める判断を",
    ]
    status, body = _post(server["url"] + "/",
                         {"request": [{"text": t} for t in texts]})
    assert status in (200, 203)
    resp = body["response"]
    assert len(resp) == len(texts)
    for item in resp:
        assert set(item) == {"iso6391code", "name"}
    assert resp[0]["iso6391code"] == "fr"
    assert resp[4]["iso6391code"] == "ja"


def test_device_engine_service_path():
    """The service's production configuration (use_device=True): requests
    flow through the batcher into the batched device engine and back.
    The scalar-path suite above covers HTTP semantics; this covers the
    service -> NgramBatchEngine seam."""
    svc = DetectorService(use_device=True, max_delay_ms=1.0)
    httpd, metricsd, svc = make_server(0, 0, service=svc)
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in (httpd, metricsd)]
    for t in threads:
        t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        status, body = _post(url + "/", {"request": [
            {"text": "le monde est grand et la vie est belle pour tous"},
            {"text": "国民の大多数が内閣を支持し、集団的自衛権の行使を"},
            {"text": "buy cheap now " * 300},
        ]})
        assert status in (200, 203)
        codes = [r["iso6391code"] for r in body["response"]]
        assert codes[0] == "fr" and codes[1] == "ja"
        assert len(codes) == 3
    finally:
        httpd.shutdown()
        metricsd.shutdown()
        svc.batcher.close()


def test_aio_server_contract():
    """The asyncio front end (service/aioserver.py) speaks the same
    contract as the threaded server: usage, detection, per-item errors,
    wrong content type, 404, metrics — served from one event loop."""
    import asyncio
    import queue as _q

    from language_detector_tpu.service.aioserver import serve

    ports_q: _q.Queue = _q.Queue()
    loop_holder = {}

    def run_loop():
        async def main():
            loop_holder["loop"] = asyncio.get_running_loop()
            ready = asyncio.get_running_loop().create_future()
            svc = DetectorService(use_device=False, max_delay_ms=1.0,
                                  start_batcher=False)
            task = asyncio.get_running_loop().create_task(
                serve(0, 0, svc=svc, ready=ready))
            ports_q.put(await ready)
            try:
                await task
            except asyncio.CancelledError:
                pass
        try:
            asyncio.run(main())
        except RuntimeError:
            pass  # loop.stop() teardown ends the run mid-await

    t = threading.Thread(target=run_loop, daemon=True)
    t.start()
    port, mport = ports_q.get(timeout=30)
    url = f"http://127.0.0.1:{port}"
    try:
        status, body = _get(url + "/")
        assert status == 200 and body and json.loads(body)["result"]

        status, body = _post(url + "/", {"request": [
            {"text": "ภาษาไทยเป็นภาษาที่สวยงามมาก"},
            {"nokey": 1},
        ]})
        assert status == 400  # per-item error forces 400 overall
        assert body["response"][0]["iso6391code"] == "th"
        assert body["response"][1] == {"error": "Missing text key"}

        status, body = _post(url + "/", {"x": 1}, raw=b"not json{{")
        assert status == 400

        status, body = _post(url + "/", {"request": []},
                             content_type="text/plain")
        assert status == 400
        assert "Content-Type" in body["error"]

        status, body = _get(url + "/bogus")
        assert status == 404

        status, body = _get(f"http://127.0.0.1:{mport}/metrics")
        assert status == 200
        assert b"augmentation_requests_total" in body
    finally:
        loop = loop_holder.get("loop")
        if loop is not None:
            loop.call_soon_threadsafe(loop.stop)


def test_strip_extras_fast_slow_path_agreement():
    """strip_extras' fast path returns the ORIGINAL text while the slow
    path collapses whitespace and leaves a trailing space — different
    byte streams. The invariant the fast path relies on: segmentation
    maps every non-letter run to a single space, so detection output is
    identical either way. Pinned here over whitespace-heavy inputs so a
    future byte-sensitive consumer can't silently break it."""
    from language_detector_tpu.engine_scalar import detect_scalar
    from language_detector_tpu.registry import registry
    from language_detector_tpu.service.server import strip_extras
    from language_detector_tpu.tables import load_tables
    tables = load_tables()
    texts = [
        "Le  gouvernement\t\ta annoncé\n\nde   nouvelles mesures",
        "  leading and   trailing   whitespace   ",
        "日本語の　テキスト　です。",  # ideographic spaces
        "word\r\nword\r\nword des mots encore des mots",
        "tabs\tbetween\tevery\tsingle\tword ici aussi",
    ]
    for t in texts:
        fast = strip_extras(t)
        assert fast == t  # no @/http: scan-only fast path
        slow = "".join(w + " " for w in t.split())
        rf = detect_scalar(fast, tables, registry, 0)
        rs = detect_scalar(slow, tables, registry, 0)
        assert registry.code(rf.summary_lang) == \
            registry.code(rs.summary_lang), t
