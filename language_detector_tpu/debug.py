"""Score-trace debugging: the TPU framework's equivalent of the
reference's HTML debug dumps (debug.cc CLD2_Debug chunk rendering +
DumpHitBuffer/DumpSummaryBuffer, scoreonescriptspan.cc:561-661, flag-gated
by kCLDFlagHtml/kCLDFlagVerbose, compact_lang_det.h:343-348).

`trace_detect` runs full scalar detection while recording every scoring
decision — spans, per-chunk summaries, the document tote before and after
close-pair refinement and unreliable-language removal, recursion events,
and the final summary-language calculation — and `format_trace` renders it
as readable text. Unlike the reference's stderr HTML (not thread safe,
compact_lang_det_impl.cc:478-485), the trace is a plain data object.
"""
from __future__ import annotations

import dataclasses

from .engine_scalar import (FLAG_BEST_EFFORT, FLAG_FINISH, FLAG_REPEATS,
                            FLAG_SQUEEZE, ScalarResult, detect_scalar)
from .registry import Registry, registry as default_registry
from .tables import ScoringTables, load_tables


@dataclasses.dataclass
class DetectionTrace:
    """Ordered trace events: (kind, payload) tuples.

    Kinds: "pass" (flags for a detection pass; recursion produces
    several), "span" (script/bytes), "chunk" (per-chunk summary),
    "doc_tote" (stage name + [(lang, bytes, score, reliability)]),
    "summary" (final decision)."""
    events: list = dataclasses.field(default_factory=list)
    result: ScalarResult | None = None

    def add(self, kind: str, **payload):
        self.events.append((kind, payload))

    def add_tote(self, stage: str, doc_tote, reg):
        """Record a doc-tote snapshot (called by the engine so it does not
        depend on this module's helpers)."""
        self.add("doc_tote", stage=stage, rows=_tote_rows(doc_tote, reg))


def _tote_rows(doc_tote, reg):
    rows = []
    for i in range(doc_tote.MAX):
        if doc_tote.key[i] != doc_tote.UNUSED and doc_tote.value[i] > 0:
            rows.append((reg.code(int(doc_tote.key[i])),
                         int(doc_tote.value[i]), int(doc_tote.score[i]),
                         int(doc_tote.rel[i]) //
                         max(int(doc_tote.value[i]), 1)))
    return sorted(rows, key=lambda r: -r[2])


def trace_detect(text: str, tables: ScoringTables | None = None,
                 reg: Registry | None = None, flags: int = 0,
                 is_plain_text: bool = True, hints=None,
                 want_chunks: bool = False) -> DetectionTrace:
    """Full-document detection with a recorded score trace.

    want_chunks traces the result-VECTOR path instead (offset-preserving
    squeeze rewrites + boundary sharpening) — exactly like the reference,
    that path can produce different byte totals and therefore different
    percentages on squeeze/repeat-triggering documents, so it is off by
    default: a plain trace matches a plain detect_scalar call."""
    tables = tables or load_tables()
    reg = reg or default_registry
    trace = DetectionTrace()
    trace.result = detect_scalar(text, tables, reg, flags,
                                 is_plain_text=is_plain_text, hints=hints,
                                 want_chunks=want_chunks, _trace=trace)
    return trace


def format_trace(trace: DetectionTrace, reg: Registry | None = None,
                 html: bool = False) -> str:
    """Render a DetectionTrace as indented text, or — with html=True —
    as a self-contained HTML page with every chunk decision as a colored
    cell (the eyeballable per-chunk dump the reference renders to stderr
    under kCLDFlagHtml, debug.cc CLD2_Debug)."""
    if html:
        return _format_trace_html(trace, reg or default_registry)
    reg = reg or default_registry
    out = []
    for kind, p in trace.events:
        if kind == "pass":
            fl = []
            if p["flags"] & FLAG_FINISH:
                fl.append("FINISH")
            if p["flags"] & FLAG_SQUEEZE:
                fl.append("SQUEEZE")
            if p["flags"] & FLAG_REPEATS:
                fl.append("REPEATS")
            if p["flags"] & FLAG_BEST_EFFORT:
                fl.append("BEST_EFFORT")
            out.append(f"pass flags={p['flags']:#x} "
                       f"[{' '.join(fl) or 'default'}]")
        elif kind == "span":
            out.append(f"  span script={p['script']} "
                       f"({reg.ulscript_code[p['script']]}) "
                       f"bytes={p['bytes']} rtype={p['rtype']}")
        elif kind == "chunk":
            out.append(
                f"    chunk @{p['offset']}+{p['bytes']}B "
                f"{reg.code(p['lang1'])}.{p['score1']} "
                f"{reg.code(p['lang2'])}.{p['score2']} "
                f"grams={p['grams']} relD={p['rel_delta']} "
                f"relS={p['rel_score']}")
        elif kind == "doc_tote":
            rows = " ".join(f"{c}:{b}B/{s}/{r}%" for c, b, s, r in p["rows"])
            out.append(f"  doc_tote[{p['stage']}] {rows or '(empty)'}")
        elif kind == "summary":
            out.append(
                f"summary {reg.code(p['lang'])} reliable={p['reliable']} "
                f"top3={[(reg.code(l), pc) for l, pc in p['top3']]} "
                f"bytes={p['text_bytes']}")
    return "\n".join(out)


def _lang_color(code: str) -> str:
    """Stable pastel per language code (debug.cc keys its colors off the
    language too; exact palette is presentation, not contract)."""
    h = 0
    for ch in code:
        h = (h * 131 + ord(ch)) % 360
    return f"hsl({h},70%,85%)"


def _format_trace_html(trace: DetectionTrace, reg: Registry) -> str:
    from html import escape

    rows: list = []
    cur_pass = 0
    for kind, p in trace.events:
        if kind == "pass":
            cur_pass += 1
            rows.append(f"<h3>pass {cur_pass} "
                        f"(flags={p['flags']:#x})</h3>")
        elif kind == "span":
            rows.append(
                f"<div class=span>span "
                f"{escape(str(reg.ulscript_code[p['script']]))} "
                f"{p['bytes']}B rtype={p['rtype']}</div>")
        elif kind == "chunk":
            c1 = reg.code(p["lang1"])
            c2 = reg.code(p["lang2"])
            rows.append(
                f"<span class=chunk style=\"background:"
                f"{_lang_color(c1)}\" title=\""
                f"offset={p['offset']} bytes={p['bytes']} "
                f"grams={p['grams']} relD={p['rel_delta']} "
                f"relS={p['rel_score']}\">"
                f"{escape(c1)}.{p['score1']}&nbsp;/"
                f"&nbsp;{escape(c2)}.{p['score2']}"
                f"<small>&nbsp;{p['bytes']}B</small></span>")
        elif kind == "doc_tote":
            body = "".join(
                f"<tr><td style=\"background:{_lang_color(c)}\">"
                f"{escape(c)}</td><td>{b}</td><td>{s}</td>"
                f"<td>{r}%</td></tr>"
                for c, b, s, r in p["rows"])
            rows.append(
                f"<details><summary>doc_tote "
                f"[{escape(p['stage'])}]</summary><table>"
                f"<tr><th>lang</th><th>bytes</th><th>score</th>"
                f"<th>rel</th></tr>{body}</table></details>")
        elif kind == "summary":
            top3 = " ".join(f"{escape(reg.code(l))}:{pc}%"
                            for l, pc in p["top3"])
            rows.append(
                f"<div class=summary style=\"background:"
                f"{_lang_color(reg.code(p['lang']))}\">summary "
                f"<b>{escape(reg.code(p['lang']))}</b> "
                f"reliable={p['reliable']} {top3} "
                f"bytes={p['text_bytes']}</div>")
    style = ("<style>body{font:13px monospace;margin:1em}"
             ".chunk{padding:2px 6px;margin:1px;display:inline-block;"
             "border:1px solid #bbb;border-radius:3px}"
             ".span{color:#666;margin-top:4px}"
             ".summary{padding:6px;margin-top:8px;border:1px solid #888}"
             "table{border-collapse:collapse;margin:4px 0}"
             "td,th{border:1px solid #ccc;padding:1px 6px}</style>")
    return ("<!doctype html><html><head><meta charset=\"utf-8\">"
            f"<title>score trace</title>{style}</head><body>"
            + "\n".join(rows) + "</body></html>")


def format_engine_stats(stats: dict) -> str:
    """Human-readable render of the batched engine's scheduler counters
    (models/ngram.py NgramBatchEngine.stats / detector.engine_stats):
    dispatch lanes per shape tier, retry-lane overlap, dedup savings,
    fallback/recursion volume. The service /metrics endpoint exports the
    same counters as Prometheus series; this is their terminal twin for
    bench output and the CLI."""
    order = ["batches", "device_dispatches", "c_path_docs",
             "tier_short_dispatches", "tier_mid_dispatches",
             "tier_long_dispatches", "tier_mixed_dispatches",
             "retry_lane_dispatches", "dedup_docs",
             "retry_skipped_docs",
             "fallback_docs", "scalar_recursion_docs"]
    keys = ([k for k in order if k in stats] +
            sorted(k for k in stats if k not in order))
    if not keys:
        return "(no engine stats)"
    w = max(len(k) for k in keys)
    return "\n".join(f"{k:<{w}}  {stats[k]}" for k in keys)


def format_slow_traces(doc: dict) -> str:
    """Pretty-print a /debug/slow JSON document (telemetry.SlowTraceRing
    snapshot): one block per sampled request, spans indented by depth
    with start offset and duration — the span-tree twin of
    format_trace's indented scalar dump."""
    lines = [f"slow traces: {len(doc.get('traces', []))} held / "
             f"{doc.get('recorded', 0)} recorded "
             f"(threshold {doc.get('threshold_ms', 0)}ms, "
             f"ring {doc.get('capacity', 0)})"]
    import datetime
    for i, tr in enumerate(doc.get("traces", [])):
        when = datetime.datetime.fromtimestamp(tr.get("ts", 0)) \
            .strftime("%H:%M:%S.%f")[:-3]
        meta = " ".join(f"{k}={v}" for k, v in
                        sorted(tr.get("meta", {}).items()))
        lines.append(f"\n#{i} {when} total={tr.get('total_ms', 0)}ms"
                     + (f" [{meta}]" if meta else ""))
        for sp in tr.get("spans", []):
            pad = "  " * (sp.get("depth", 0) + 1)
            lines.append(f"{pad}{sp.get('name', '?'):<12} "
                         f"@{sp.get('start_ms', 0):>9.3f}ms "
                         f"+{sp.get('dur_ms', 0):.3f}ms")
    return "\n".join(lines)


def format_fleet_traces(doc: dict) -> str:
    """Pretty-print a fleet /tracez merge (service/fleet._fleet_traces):
    one block per request id, listing the processes it touched, every
    slow-trace capture (with owning member slot), and the recorder
    events carrying that id in time order — the cross-process twin of
    format_slow_traces."""
    reqs = doc.get("requests", [])
    lines = [f"fleet traces: {doc.get('count', len(reqs))} request "
             f"id(s) merged"]
    for e in reqs:
        procs = ", ".join(str(p) for p in e.get("processes", []))
        lines.append(f"\nrequest {e.get('request_id', '?')} "
                     f"[{procs}]")
        for tr in e.get("traces", []):
            meta = " ".join(f"{k}={v}" for k, v in
                            sorted(tr.get("meta", {}).items()))
            lines.append(f"  slot {tr.get('slot', '?')} trace "
                         f"total={tr.get('total_ms', 0)}ms"
                         + (f" [{meta}]" if meta else ""))
            for sp in tr.get("spans", []):
                pad = "  " * (sp.get("depth", 0) + 2)
                lines.append(f"{pad}{sp.get('name', '?'):<12} "
                             f"@{sp.get('start_ms', 0):>9.3f}ms "
                             f"+{sp.get('dur_ms', 0):.3f}ms")
        for ev in sorted(e.get("events", []),
                         key=lambda x: x.get("ts", 0)):
            fields = " ".join(
                f"{k}={v}" for k, v in sorted(ev.items())
                if k not in ("ev", "ts", "pid", "request_id"))
            lines.append(f"  pid {ev.get('pid', '?')} "
                         f"{ev.get('ev', '?'):<14}"
                         + (f" {fields}" if fields else ""))
    return "\n".join(lines)


def format_admission(doc: dict) -> str:
    """Human-readable render of the admission controller's state as
    published under /debug/vars "admission" (service/admission.py
    AdmissionController.stats): live queue occupancy against configured
    bounds, brownout ladder position, breaker state, and shed counts by
    reason — the operator's first stop when clients start seeing 429s."""
    adm = doc.get("admission", doc)
    if not adm:
        return "(admission control idle: no stats published)"
    limits = adm.get("limits", {})

    def bound(v):
        return "unbounded" if v is None else str(v)

    lines = [
        f"queue_docs   {adm.get('queue_docs', 0)} / "
        f"{bound(limits.get('max_queue_docs'))}",
        f"queue_bytes  {adm.get('queue_bytes', 0)} / "
        f"{bound(limits.get('max_queue_bytes'))}",
        f"inflight     {adm.get('inflight', 0)} / "
        f"{bound(limits.get('max_inflight'))}",
        f"brownout     level={adm.get('brownout_level', 0)} "
        f"ema={adm.get('brownout_ema', 0.0):.3f}",
    ]
    br = adm.get("breaker", {})
    lines.append(f"breaker      {br.get('state_name', 'closed')} "
                 f"consec={br.get('consecutive_failures', 0)} "
                 f"trips={br.get('trips', 0)} "
                 f"probes={br.get('probes', 0)}")
    shed = adm.get("shed", {})
    total = sum(shed.values()) if shed else 0
    lines.append(f"shed         total={total} " +
                 " ".join(f"{k}={v}" for k, v in sorted(shed.items())))
    lines.append(f"deadline_expired  {adm.get('deadline_expired', 0)}")
    return "\n".join(lines)


def format_slo(doc: dict) -> str:
    """Human-readable render of a /sloz document (slo.sloz, or the
    fleet merge's "slo" block): declared targets, fleet-wide and
    per-tenant windowed SLIs, budget burn rates, and the alert state —
    the operator's answer to "are we meeting the SLO, and for whom
    not"."""
    if not doc.get("enabled"):
        return ("(SLO engine off: " +
                doc.get("hint", "set LDT_SLO on the fronts") + ")")
    # the fleet merge has a different shape (aggregated tenants, no
    # window pairs) — render it with the member count it carries
    if "members" in doc and "fleet" not in doc:
        lines = [f"fleet SLO: alert={doc.get('alert', 'ok')} "
                 f"members={len(doc.get('members', []))}"]
        spec = doc.get("spec") or {}
        if spec:
            lines.append(
                f"targets      p{spec.get('percentile', 99):g}"
                f"<={spec.get('target_ms')}ms "
                f"err<={spec.get('err_pct')}% "
                f"window={spec.get('window_sec')}s")
        for t, agg in sorted((doc.get("tenants") or {}).items()):
            lines.append(
                f"  {t:<20} count={agg.get('count', 0)} "
                f"bad={agg.get('bad', 0)} shed={agg.get('shed', 0)} "
                f"worst_burn={agg.get('burn_rate_max', 0.0)}")
        return "\n".join(lines)
    spec = doc.get("spec", {})
    alert = doc.get("alert", {})
    lines = [
        f"targets      p{spec.get('percentile', 99):g}"
        f"<={spec.get('target_ms')}ms err<={spec.get('err_pct')}% "
        f"windows={spec.get('window_sec')}s/"
        f"{spec.get('slow_window_sec')}s",
        f"alert        {alert.get('state', 'ok')}"
        + (f" since={alert.get('since_sec')}s"
           if alert.get("since_sec") is not None else "")
        + f" breaches_total={alert.get('breaches_total', 0)}",
        f"observed     {doc.get('observed', 0)} requests",
    ]

    def _scope(name: str, view: dict) -> None:
        for label in ("fast", "slow"):
            w = view.get(label) or {}
            pq = next((v for k, v in w.items()
                       if k.startswith("p") and k.endswith("_ms")
                       and k != "p50_ms"), None)
            lines.append(
                f"  {name:<18} {label:<4} count={w.get('count', 0)} "
                f"err={w.get('err_ratio', 0.0)} "
                f"shed={w.get('shed', 0)} p50={w.get('p50_ms')}ms "
                f"pX={pq}ms burn={w.get('burn_rate', 0.0)}")

    lines.append("fleet-wide")
    _scope("(all tenants)", doc.get("fleet") or {})
    tenants = doc.get("tenants") or {}
    if tenants:
        lines.append("per-tenant")
        for t, view in sorted(tenants.items()):
            _scope(t, view)
    return "\n".join(lines)


def format_capture_summary(doc: dict) -> str:
    """Human-readable render of capture.summarize(dir): segment/record
    volumes, the capture's time span, and the tenant/lane/status mix —
    the sanity check before pointing bench.py --replay at it."""
    lines = [
        f"capture {doc.get('dir', '?')}",
        f"records      {doc.get('records', 0)} across "
        f"{doc.get('segments', 0)} sealed segment(s) + "
        f"{doc.get('rings', 0)} live/abandoned ring(s)",
        f"span         {doc.get('span_sec', 0.0)}s",
        f"tenants      {doc.get('tenants', 0)} distinct "
        f"(sheds={doc.get('sheds', 0)})",
    ]
    for row in doc.get("top_tenants", []):
        lines.append(f"  {row.get('tenant', '?'):<20} "
                     f"{row.get('records', 0)} record(s)")
    lanes = doc.get("lanes") or {}
    if lanes:
        lines.append("lanes        " + " ".join(
            f"{k}={v}" for k, v in sorted(lanes.items())))
    statuses = doc.get("statuses") or {}
    if statuses:
        lines.append("statuses     " + " ".join(
            f"{k}={v}" for k, v in sorted(statuses.items())))
    return "\n".join(lines)


def _read_slow_source(src: str) -> dict:
    """--slow-traces input: an http(s) URL (a running front's
    GET /debug/slow), a JSON file path, or '-' for stdin."""
    import json
    import sys
    if src == "-":
        return json.loads(sys.stdin.read())
    if src.startswith(("http://", "https://")):
        from urllib.request import urlopen
        with urlopen(src, timeout=10) as r:
            return json.loads(r.read())
    from pathlib import Path
    return json.loads(Path(src).read_text())


def format_scorecard(card: dict) -> str:
    """Render an ACC_r*.json accuracy scorecard (bench.py --eval /
    evalsuite.run_eval) for postmortem reading: agreement vs the scalar
    oracle, label accuracy, the top per-script confusions, reliability
    calibration, and the documented hint-flip demo."""
    out = [f"accuracy scorecard — round {card.get('round', '?')}"
           f" ({card['corpus_docs']} docs, {card['languages']} languages"
           f"{', quick' if card.get('quick') else ''},"
           f" engine={card.get('engine', '?')})"]
    ag = card["agreement"]
    out.append(f"  device-vs-oracle agreement: "
               f"top-1 {ag['top1']:.4f}  top-3 {ag['top3']:.4f}  "
               f"(floor {ag['floor']})")
    la = card["label_accuracy"]
    out.append(f"  label accuracy: top-1 {la['top1']:.4f}  "
               f"top-3 {la['top3']:.4f}")
    scripts = card.get("per_script") or {}
    if scripts:
        out.append("  per-script label accuracy (confusions "
                   "label->got xN):")
        for name in sorted(scripts):
            row = scripts[name]
            conf = "  ".join(f"{l}->{g} x{n}"
                             for l, g, n in row.get("confusions", []))
            out.append(f"    {name:4s} docs={row['docs']:<4d} "
                       f"top-1 {row['label_top1']:.3f}"
                       + (f"  ({conf})" if conf else ""))
    cal = card.get("calibration") or []
    if cal:
        out.append("  calibration (reported pct -> observed accuracy):")
        for b in cal:
            rng = f"{b['pct_lo']}-{b['pct_hi']}"
            out.append(f"    {rng:>7s}  n={b['docs']:<4d} "
                       f"acc={b['label_top1']:.3f} "
                       f"reliable={b['reliable_frac']:.3f}")
    hf = card.get("hint_flip")
    if hf:
        out.append(f"  hint-flip demo: {hf['before']} -> {hf['after']} "
                   f"({hf['hint']}; flipped={hf['flipped']})")
    return "\n".join(out)


def _latest_scorecard(src: str | None):
    """--eval source: an explicit ACC_r*.json path, or the
    highest-numbered round in the repo root when given 'latest'."""
    import json
    from pathlib import Path
    if src and src != "latest":
        return json.loads(Path(src).read_text())
    root = Path(__file__).resolve().parent.parent
    cards = sorted(root.glob("ACC_r*.json"))
    if not cards:
        raise SystemExit("no ACC_r*.json found — run bench.py --eval")
    return json.loads(cards[-1].read_text())


def format_spans(text: str, spans: list, reg) -> str:
    """Pretty-print per-span verdicts: one line per span with its byte
    range, code, confidence, and the (escaped, truncated) text slice."""
    out = []
    data = text.encode("utf-8")
    for off, ln, code, pct, rel in spans:
        piece = data[off:off + ln].decode("utf-8", errors="replace")
        piece = piece.replace("\n", " ")
        if len(piece) > 48:
            piece = piece[:45] + "..."
        mark = " " if rel else "?"
        out.append(f"  [{off:6d}..{off + ln:6d}) {code:4s} "
                   f"{pct:3d}%{mark} {piece!r}")
    return "\n".join(out)


def _main(argv=None):
    """CLI harness (the reference's compact_lang_det_test.cc interactive
    tool): text from args/stdin -> summary + optional score trace and
    per-range vector.

      python -m language_detector_tpu.debug [--vector] [--plain|--html]
                                            [text ...]   (stdin if none)
    """
    import argparse
    import sys

    ap = argparse.ArgumentParser(prog="language_detector_tpu.debug")
    ap.add_argument("text", nargs="*", help="text (stdin when omitted)")
    ap.add_argument("--html", action="store_true",
                    help="treat input as HTML (strip tags, expand "
                         "entities, scan lang= attributes)")
    ap.add_argument("--vector", action="store_true",
                    help="also print per-range results")
    ap.add_argument("--quiet", action="store_true",
                    help="summary line only, no trace")
    ap.add_argument("--render-html", metavar="FILE",
                    help="write the colored per-chunk HTML dump to FILE "
                         "(the kCLDFlagHtml debug render)")
    ap.add_argument("--engine-stats", action="store_true",
                    help="run the input through the batched engine "
                         "(each arg / stdin line = one document) and "
                         "print the scheduler's dispatch/tier/dedup "
                         "counters instead of a scalar trace")
    ap.add_argument("--slow-traces", metavar="SRC",
                    help="pretty-print sampled slow-request span trees: "
                         "SRC is a metrics-port URL (the front's "
                         "GET /debug/slow), a JSON file, or '-' for "
                         "stdin (requires LDT_SLOW_TRACE_MS set on the "
                         "server)")
    ap.add_argument("--fleet-traces", metavar="SRC",
                    help="pretty-print the fleet-wide request-id merge: "
                         "SRC is the fleet status port's GET /tracez "
                         "URL, a JSON file, or '-' for stdin (requires "
                         "LDT_FLEET_STATUS_PORT on the fleet)")
    ap.add_argument("--slo", metavar="SRC",
                    help="pretty-print SLO targets, windowed SLIs, "
                         "budget burn rates, and alert state: SRC is a "
                         "metrics-port /sloz URL (front or fleet "
                         "status port), a JSON file, or '-' for stdin "
                         "(requires LDT_SLO set on the server)")
    ap.add_argument("--capture-summary", metavar="DIR",
                    help="summarize a traffic-capture directory tree "
                         "(LDT_CAPTURE_DIR): segment/record counts, "
                         "time span, tenant/lane/status mix")
    ap.add_argument("--eval", metavar="SRC", nargs="?", const="latest",
                    dest="eval_src",
                    help="render an accuracy scorecard: SRC is an "
                         "ACC_r*.json path, or omitted for the latest "
                         "round in the repo root (bench.py --eval)")
    ap.add_argument("--spans", action="store_true",
                    help="pretty-print per-span language verdicts for "
                         "the input text (the LDT_SPANS surface; "
                         "scalar oracle, no accelerator needed)")
    ap.add_argument("--admission", metavar="SRC",
                    help="pretty-print admission-control state "
                         "(queue occupancy, brownout level, breaker, "
                         "shed counts): SRC is a metrics-port URL (the "
                         "front's GET /debug/vars), a JSON file, or "
                         "'-' for stdin")
    args = ap.parse_args(argv)
    if args.slow_traces:
        print(format_slow_traces(_read_slow_source(args.slow_traces)))
        return 0
    if args.fleet_traces:
        print(format_fleet_traces(
            _read_slow_source(args.fleet_traces)))
        return 0
    if args.slo:
        print(format_slo(_read_slow_source(args.slo)))
        return 0
    if args.capture_summary:
        from . import capture
        print(format_capture_summary(
            capture.summarize(args.capture_summary)))
        return 0
    if args.admission:
        print(format_admission(_read_slow_source(args.admission)))
        return 0
    if args.eval_src:
        print(format_scorecard(_latest_scorecard(args.eval_src)))
        return 0
    if args.spans:
        from .engine_scalar import detect_scalar_spans
        from .tables import load_tables
        text = " ".join(args.text) if args.text else sys.stdin.read()
        tables = load_tables()
        r = detect_scalar_spans(text, tables, default_registry)
        code = default_registry.code(r.summary_lang)
        print(f"=> {code} reliable={r.is_reliable} "
              f"spans={len(r.spans or [])}")
        print(format_spans(text, r.spans or [], default_registry))
        return 0
    if args.engine_stats:
        docs = list(args.text) if args.text \
            else [ln for ln in sys.stdin.read().splitlines() if ln]
        from .models.ngram import NgramBatchEngine
        eng = NgramBatchEngine()
        for d, r in zip(docs, eng.detect_many(docs)):
            code = default_registry.code(r.summary_lang)
            print(f"{code:4s} {d[:60]!r}")
        print(format_engine_stats(eng.stats))
        return 0
    text = " ".join(args.text) if args.text else sys.stdin.read()

    tr = trace_detect(text, is_plain_text=not args.html,
                      want_chunks=args.vector)
    if args.render_html:
        from pathlib import Path
        Path(args.render_html).write_text(format_trace(tr, html=True))
        print(f"wrote {args.render_html}")
    if not args.quiet:
        print(format_trace(tr))
    r = tr.result
    reg = default_registry
    print(f"=> {reg.code(r.summary_lang)} "
          f"reliable={r.is_reliable} "
          f"top3={[(reg.code(l), p) for l, p in zip(r.language3, r.percent3)]}")
    if args.vector and r.chunks:
        for c in r.chunks:
            print(f"   [{c.offset:6d}..{c.offset + c.bytes:6d}) "
                  f"{reg.code(c.lang1)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
