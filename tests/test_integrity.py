"""Data-plane integrity contract (integrity.py + the seams it guards):
artifact digest footers, the host/device digest-fold parity the scrub
depends on, the IntegrityMonitor quarantine/heal state machine against
the REAL DevicePool, the `corrupt` fault-rule grammar, wire/shm frame
CRC guards, and the epoch-namespaced ResultCache.

The exhaustive interleaving proof ("scrub-heal") runs with the other
model-check products in tests/test_model_check.py; ci.sh drives the
live detect -> quarantine -> re-upload -> re-admit cycle as a chaos
smoke on a real 2-lane engine.
"""
from __future__ import annotations

import json
import os
import socket
import struct
import tempfile
import zlib

import numpy as np
import pytest

from language_detector_tpu import artifact, faults, integrity, telemetry
from language_detector_tpu.service import shmring, wire
from language_detector_tpu.service.batcher import _MISS, ResultCache

# -- artifact digest footer --------------------------------------------------


def _small_arrays():
    return {
        "a/ints": np.arange(50, dtype=np.int32),
        "b/floats": np.linspace(0.0, 1.0, 33, dtype=np.float32),
        "c/bytes": np.frombuffer(b"hello artifact", dtype=np.uint8),
    }


def test_footer_roundtrip_and_digest(tmp_path):
    path = str(tmp_path / "m.ldta")
    arrays = _small_arrays()
    artifact.write_artifact(arrays, path)
    loaded = artifact.load_artifact(path)
    for k, v in arrays.items():
        np.testing.assert_array_equal(np.asarray(loaded[k]), v)
    dig = artifact.artifact_digest(path)
    assert dig is not None and len(dig) == 8
    int(dig, 16)  # stable hex token
    assert artifact.verify_artifact(path) == dig
    # identity: same content -> same digest, different -> different
    artifact.write_artifact(arrays, str(tmp_path / "m2.ldta"))
    assert artifact.artifact_digest(str(tmp_path / "m2.ldta")) == dig
    arrays["a/ints"] = arrays["a/ints"] + 1
    artifact.write_artifact(arrays, str(tmp_path / "m3.ldta"))
    assert artifact.artifact_digest(str(tmp_path / "m3.ldta")) != dig


def _first_blob_offset(raw: bytes) -> int:
    """Data offset of the first array blob (descriptor field 8)."""
    fields = artifact._DESC.unpack_from(raw, artifact._HDR.size)
    return fields[7]


def test_payload_bitflip_raises_integrity_error(tmp_path):
    path = str(tmp_path / "m.ldta")
    artifact.write_artifact(_small_arrays(), path)
    raw = bytearray(open(path, "rb").read())
    raw[_first_blob_offset(raw)] ^= 0x01
    open(path, "wb").write(bytes(raw))
    with pytest.raises(artifact.ArtifactIntegrityError):
        artifact.load_artifact(path)
    with pytest.raises(artifact.ArtifactIntegrityError):
        artifact.verify_artifact(path)
    # the typed subclass keeps every existing ArtifactError handler
    assert issubclass(artifact.ArtifactIntegrityError,
                      artifact.ArtifactError)


def test_descriptor_corruption_still_typed(tmp_path):
    """A flip in the descriptor table (not digest-covered) must still
    fail LOUD with the base typed error, never load garbage."""
    path = str(tmp_path / "m.ldta")
    artifact.write_artifact(_small_arrays(), path)
    raw = bytearray(open(path, "rb").read())
    raw[artifact._HDR.size + 56] = 0xFF  # descriptor 0's ndim word
    open(path, "wb").write(bytes(raw))
    with pytest.raises(artifact.ArtifactError):
        artifact.load_artifact(path)


def test_legacy_footerless_artifact_loads(tmp_path):
    """A pre-footer artifact (flags=0, no digest table) loads
    unchanged; digest helpers answer None instead of raising."""
    path = str(tmp_path / "legacy.ldta")
    arrays = _small_arrays()
    artifact.write_artifact(arrays, path)
    raw = bytearray(open(path, "rb").read())
    magic, ver, n, flags, hb, total = artifact._HDR.unpack_from(raw, 0)
    assert flags & artifact.FLAG_DIGESTS
    foot = artifact._FOOT.size + 4 * n
    artifact._HDR.pack_into(raw, 0, magic, ver, n, 0, hb, total - foot)
    open(path, "wb").write(bytes(raw[:total - foot]))
    loaded = artifact.load_artifact(path)
    for k, v in arrays.items():
        np.testing.assert_array_equal(np.asarray(loaded[k]), v)
    assert artifact.artifact_digest(path) is None
    assert artifact.verify_artifact(path) is None


# -- host/device digest-fold parity ------------------------------------------


@pytest.mark.parametrize("arr", [
    np.zeros(0, dtype=np.uint8),
    np.arange(257, dtype=np.uint8),
    np.array([True, False, True, True]),
    (np.arange(1000) % 7 == 0),
    np.arange(-300, 300, dtype=np.int16),
    (np.arange(70000, dtype=np.uint64) * 2654435761
     % (2 ** 32)).astype(np.uint32),
    np.linspace(-1.0, 1.0, 513, dtype=np.float32),
    np.arange(24, dtype=np.int32).reshape(2, 3, 4),
], ids=["empty", "u8", "bool", "bool-long", "i16", "u32", "f32", "3d"])
def test_fold_parity_host_vs_device(arr):
    """The scrub's whole detection premise: the numpy fold and the
    jitted device fold agree bit-for-bit on every plane dtype."""
    import jax.numpy as jnp

    from language_detector_tpu.ops import kernels
    from language_detector_tpu.ops.device_tables import fold_host

    host = fold_host(arr)
    dev = int(np.asarray(kernels._fold(jnp.asarray(arr))))
    assert host == dev
    assert 0 <= host < 2 ** 32


def test_fold_is_position_sensitive():
    from language_detector_tpu.ops.device_tables import fold_host
    a = np.array([1, 2, 3, 4], dtype=np.uint32)
    b = np.array([2, 1, 3, 4], dtype=np.uint32)
    assert fold_host(a) != fold_host(b)  # equal-sum swap still detected


# -- IntegrityMonitor against the real pool ----------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


_GOOD = ("good",)
_BAD = ("bad",)


def _monitor(n_lanes=2, canary_fn=None, interval=0.0):
    """IntegrityMonitor over REAL pool lanes, digests faked through
    the same closure seam build_from_env uses (the model-check
    harness pattern)."""
    from language_detector_tpu.parallel.pool import DevicePool, Lane

    clock = _Clock()
    lanes = [Lane(i, None) for i in range(n_lanes)]
    pool = DevicePool(lanes, hedge_factor=0.0, hedge_min_ms=0.0,
                      evict_failures=1, probe_cooldown_sec=100.0,
                      max_redispatch=1, clock=clock)
    st = {"corrupt": [False] * n_lanes, "host_bad": False,
          "raw": np.zeros(1, dtype=np.int32)}

    def digest_fn(lane):
        return _BAD if st["corrupt"][lane.idx] else _GOOD

    def reupload_fn(lane):
        if not st["host_bad"]:
            st["corrupt"][lane.idx] = False
        return _GOOD

    mon = integrity.IntegrityMonitor(
        lanes, {i: _GOOD for i in range(n_lanes)}, digest_fn,
        reupload_fn, canary_fn=canary_fn, interval_sec=interval,
        clock=clock)
    return clock, pool, mon, st


def _serve(pool, st, n=1):
    for _ in range(n):
        pf = pool.launch(lambda lane: st["raw"])
        pool._fetch_on(pf.lane, pf.raw)
        yield pf.lane


def test_detect_quarantines_and_excludes_capacity():
    from language_detector_tpu.parallel.pool import LANE_CORRUPT
    clock, pool, mon, st = _monitor()
    assert pool.capacity() == (2, 2)
    st["corrupt"][0] = True
    d0 = mon.stats["detected"]
    assert mon.detect(pool.lanes[0], "scrub") is True
    assert pool.lanes[0].state() == LANE_CORRUPT
    assert pool.capacity() == (1, 2)
    assert mon.stats["detected"] == d0 + 1
    # idempotent: a second detection of the same lane never double-counts
    assert mon.detect(pool.lanes[0], "canary") is False
    assert mon.stats["detected"] == d0 + 1
    # the quarantined lane is never drafted
    assert all(ln.idx == 1 for ln in _serve(pool, st, n=8))


def test_all_corrupt_raises_instead_of_serving():
    from language_detector_tpu.parallel.pool import PoolExhausted
    clock, pool, mon, st = _monitor()
    for ln in pool.lanes:
        st["corrupt"][ln.idx] = True
        mon.detect(ln, "scrub")
    with pytest.raises(PoolExhausted):
        list(_serve(pool, st, n=1))


def test_scrub_detects_heals_and_readmits():
    from language_detector_tpu.parallel.pool import (LANE_ACTIVE,
                                                     LANE_EVICTED)
    clock, pool, mon, st = _monitor()
    h0 = telemetry.REGISTRY.counter_value("ldt_integrity_healed_total",
                                          lane=pool.lanes[0].name)
    st["corrupt"][0] = True
    assert mon.scrub_lane(pool.lanes[0]) == "mismatch"
    # healed: fresh tables verified, probe immediately due — but the
    # lane still owes one healthy served batch before it is ACTIVE
    assert pool.lanes[0].state() == LANE_EVICTED
    assert telemetry.REGISTRY.counter_value(
        "ldt_integrity_healed_total",
        lane=pool.lanes[0].name) == h0 + 1
    served = set()
    for _ in range(4):
        served.update(ln.idx for ln in _serve(pool, st, n=1))
        if all(ln.state() == LANE_ACTIVE for ln in pool.lanes):
            break
    assert all(ln.state() == LANE_ACTIVE for ln in pool.lanes)
    assert 0 in served  # re-admission went THROUGH a served probe
    assert pool.capacity() == (2, 2)
    assert mon.scrub_lane(pool.lanes[0]) == "ok"


def test_bad_heal_source_keeps_quarantine_and_retries():
    from language_detector_tpu.parallel.pool import (LANE_CORRUPT,
                                                     LANE_EVICTED)
    clock, pool, mon, st = _monitor()
    st["corrupt"][0] = True
    st["host_bad"] = True
    assert mon.scrub_lane(pool.lanes[0]) == "mismatch"
    assert pool.lanes[0].state() == LANE_CORRUPT  # heal failed: stays out
    assert mon.stats["healed"] == 0
    # next scrub retries the heal even though detect() is a no-op now
    st["host_bad"] = False
    assert mon.scrub_lane(pool.lanes[0]) == "mismatch"
    assert pool.lanes[0].state() == LANE_EVICTED
    assert mon.stats["healed"] == 1


def test_canary_mismatch_detects():
    from language_detector_tpu.parallel.pool import LANE_EVICTED
    verdict = {"ok": True}
    clock, pool, mon, st = _monitor(canary_fn=lambda lane:
                                    verdict["ok"])
    assert mon.scrub_lane(pool.lanes[0]) == "ok"
    verdict["ok"] = False
    d0 = mon.stats["detected"]
    assert mon.scrub_lane(pool.lanes[0]) == "mismatch"
    assert mon.stats["detected"] == d0 + 1
    # table digests were clean, so the re-upload "heals" immediately
    assert pool.lanes[0].state() == LANE_EVICTED


def test_scrub_pass_contains_lane_errors():
    clock, pool, mon, st = _monitor()
    boom = {0: True}

    def digest_fn(lane):
        if boom.get(lane.idx):
            raise RuntimeError("digest launch died")
        return _GOOD

    mon.digest_fn = digest_fn
    e0 = telemetry.REGISTRY.counter_value(
        "ldt_integrity_scrub_total", lane=pool.lanes[0].name,
        result="error")
    mon.scrub_pass()  # must not raise
    assert mon.stats["scrubs"] == 1
    assert telemetry.REGISTRY.counter_value(
        "ldt_integrity_scrub_total", lane=pool.lanes[0].name,
        result="error") == e0 + 1


def test_maybe_scrub_cadence():
    clock, pool, mon, st = _monitor(interval=10.0)
    assert mon.maybe_scrub() is False      # not due yet
    clock.t = 11.0
    assert mon.maybe_scrub() is True
    assert mon.stats["scrubs"] == 1
    assert mon.maybe_scrub() is False      # gated until the next window
    clock.t = 22.0
    assert mon.maybe_scrub() is True
    mon.interval_sec = 0.0
    clock.t = 1e9
    assert mon.maybe_scrub() is False      # interval 0 = scrubbing off


# -- the `corrupt` fault action ----------------------------------------------


def test_corrupt_rule_schedule_and_isolation():
    faults.configure("table_upload:corrupt:seed=5")
    try:
        # evaluate() (error/delay seams) must not consume the schedule
        assert faults.evaluate("table_upload") == (0.0, False)
        assert faults.corruption("table_upload") == 5
        assert faults.corruption("table_upload") == 6  # arrival-indexed
        assert faults.corruption("frame_payload") is None
    finally:
        faults.configure(None)
    assert faults.corruption("table_upload") is None  # disarmed


def test_corrupt_rule_once_fires_once():
    faults.configure("table_upload:corrupt:seed=9:once")
    try:
        assert faults.corruption("table_upload") == 9
        assert faults.corruption("table_upload") is None
    finally:
        faults.configure(None)


def test_corrupt_buffer_is_deterministic_single_bit():
    a = np.arange(64, dtype=np.uint8)
    b1 = faults.corrupt_buffer(a, 7)
    b2 = faults.corrupt_buffer(a, 7)
    np.testing.assert_array_equal(b1, b2)
    diff = np.bitwise_xor(a, b1)
    assert np.count_nonzero(diff) == 1
    assert bin(int(diff[diff != 0][0])).count("1") == 1
    assert not np.array_equal(faults.corrupt_buffer(a, 8), b1)
    np.testing.assert_array_equal(a, np.arange(64, dtype=np.uint8))


def test_corrupt_tables_flips_one_plane():
    import jax.numpy as jnp

    # a plain tuple is a pytree, so it stands in for DeviceTables here
    dt = (jnp.arange(16, dtype=jnp.uint32), jnp.ones(8, jnp.uint8))
    bad = integrity.corrupt_tables(dt, seed=3)
    changed = [not np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(dt, bad)]
    assert sum(changed) == 1


# -- wire-frame CRC guard ----------------------------------------------------


def test_pack_frame_crc_layout_and_default_off(monkeypatch):
    monkeypatch.delenv("LDT_WIRE_CRC", raising=False)
    body = b'{"request": [{"text": "hi"}]}'
    # knob unset: v1 frames stay byte-identical (zero adoption risk)
    assert wire.pack_frame(body) == struct.pack("!I", len(body)) + body
    f = wire.pack_frame(body, crc=True)
    (lw,) = struct.unpack_from("!I", f)
    assert lw & wire.FRAME_V2_FLAG and (lw ^ wire.FRAME_V2_FLAG) == \
        len(body)
    assert f[4] & wire.FRAME_CRC  # ext header leads with the flag byte
    (crc,) = wire.FRAME_CRC_WORD.unpack_from(f, len(f) - len(body) - 4)
    assert crc == zlib.crc32(body)
    # knob on: pack_frame defaults to guarded frames
    monkeypatch.setenv("LDT_WIRE_CRC", "1")
    assert wire.pack_frame(body) == f


def _read_frame(sock):
    hdr = b""
    while len(hdr) < 6:
        chunk = sock.recv(6 - len(hdr))
        assert chunk, "connection closed mid-header"
        hdr += chunk
    length, status = struct.unpack("!IH", hdr)
    payload = b""
    while len(payload) < length:
        payload += sock.recv(length - len(payload))
    return status, payload


@pytest.fixture(scope="module")
def scalar_svc():
    from language_detector_tpu.service.server import DetectorService
    svc = DetectorService(use_device=False, max_delay_ms=1.0)
    yield svc
    svc.batcher.close()


def test_uds_crc_mismatch_answers_400_and_conn_survives(scalar_svc):
    path = os.path.join(tempfile.mkdtemp(prefix="ldt-crc-"), "c.sock")
    uds = wire.UnixFrameServer(scalar_svc, path)
    uds.start()
    body = b'{"request": [{"text": "a plain english sentence"}]}'
    ok0 = telemetry.REGISTRY.counter_value(
        "ldt_integrity_crc_total", lane="uds", result="ok")
    bad0 = telemetry.REGISTRY.counter_value(
        "ldt_integrity_crc_total", lane="uds", result="mismatch")
    det0 = telemetry.REGISTRY.counter_value(
        "ldt_integrity_detected_total", kind="frame_crc", lane="uds")
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.sendall(wire.pack_frame(body, crc=True))
        status, payload = _read_frame(s)
        assert status == 200 and b"iso6391code" in payload
        # tamper: flip one body byte AFTER the crc was computed
        frame = bytearray(wire.pack_frame(body, crc=True))
        frame[-1] ^= 0x01
        s.sendall(bytes(frame))
        status, payload = _read_frame(s)
        assert status == 400
        assert payload == wire.CRC_ERROR_BODY
        assert "CRC32" in json.loads(payload)["error"]
        # the stream stayed framed: the SAME connection keeps serving
        s.sendall(wire.pack_frame(body, crc=True))
        status, payload = _read_frame(s)
        assert status == 200 and b"iso6391code" in payload
        s.close()
    finally:
        uds.close()
    assert telemetry.REGISTRY.counter_value(
        "ldt_integrity_crc_total", lane="uds", result="ok") == ok0 + 2
    assert telemetry.REGISTRY.counter_value(
        "ldt_integrity_crc_total", lane="uds",
        result="mismatch") == bad0 + 1
    assert telemetry.REGISTRY.counter_value(
        "ldt_integrity_detected_total", kind="frame_crc",
        lane="uds") == det0 + 1


def test_uds_frame_payload_fault_drives_crc_refusal(scalar_svc):
    """The frame_payload chaos seam: an armed corrupt rule bit-flips
    the received body and the CRC guard must catch it."""
    path = os.path.join(tempfile.mkdtemp(prefix="ldt-crc-"), "f.sock")
    uds = wire.UnixFrameServer(scalar_svc, path)
    uds.start()
    body = b'{"request": [{"text": "a plain english sentence"}]}'
    faults.configure("frame_payload:corrupt:seed=11:once")
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.sendall(wire.pack_frame(body, crc=True))
        status, payload = _read_frame(s)
        assert status == 400 and payload == wire.CRC_ERROR_BODY
        # rule was :once — the next frame parses clean
        s.sendall(wire.pack_frame(body, crc=True))
        status, payload = _read_frame(s)
        assert status == 200 and b"iso6391code" in payload
        s.close()
    finally:
        faults.configure(None)
        uds.close()


# -- shm slot CRC word -------------------------------------------------------


def test_ring_crc_word_roundtrip(tmp_path):
    rf = shmring.RingFile(str(tmp_path / "r.ring"), create=True,
                          slots=4)
    try:
        payload = b"x" * 100
        rf.write_payload(1, (payload,))
        rf.write_slot(1, shmring.SLOT_READY, 0, os.getpid(), 1.0,
                      len(payload), 0, reqid=0xAB12)
        rf.write_crc(1, zlib.crc32(payload))
        assert rf.read_crc(1) == zlib.crc32(payload)
        assert rf.read_crc(0) == 0  # per-slot: neighbours untouched
        # the crc word lives OUTSIDE the packed slot header: stamping it
        # never perturbs the published state/length/reqid
        st, gen, pid, ts, ln, status = rf.read_slot(1)
        assert (st, ln) == (shmring.SLOT_READY, len(payload))
        assert rf.slot_request_id(1) == 0xAB12
        assert zlib.crc32(rf.read_payload(1, ln)) == rf.read_crc(1)
    finally:
        rf.close()


# -- epoch-namespaced ResultCache --------------------------------------------


def test_result_cache_epoch_flush_and_namespace():
    c = ResultCache(1 << 20)
    key = (None, "hello world")
    c.put(key, {"lang": "en"}, "hello world")
    assert c.get(key) == {"lang": "en"}
    c.set_epoch("digest-A")
    # the swap regression this PR fixes: a hit can never be a stale
    # answer produced by the pre-swap tables
    assert c.get(key) is _MISS
    assert c.bytes == 0
    c.put(key, {"lang": "fr"}, "hello world")
    assert c.get(key) == {"lang": "fr"}
    c.set_epoch("digest-A")  # idempotent: same epoch keeps entries
    assert c.get(key) == {"lang": "fr"}
    c.set_epoch("digest-B")
    assert c.get(key) is _MISS
