"""language_detector_tpu — a TPU-native language-identification framework.

A ground-up JAX/XLA re-design of the capabilities of the reference
GolosChain/language-detector service (Go HTTP shell over the CLD2 C++
n-gram engine; see /root/reference). The host side segments UTF-8 text
into per-script spans and computes n-gram fingerprints; the device side
scores batches of documents with vectorized hash-table gathers and
segmented reductions over a `jax.sharding.Mesh`.

Public API:
    detect(text)                 -> DetectionResult (top-3 + reliability)
    detect_batch(texts)          -> list[DetectionResult]
    LanguageDetector             -> configurable detector object
    load_tables() / ScoringTables
"""

from .registry import (  # noqa: F401
    Registry,
    registry,
    UNKNOWN_LANGUAGE,
    TG_UNKNOWN_LANGUAGE,
    ENGLISH,
)
from .tables import ScoringTables, load_tables  # noqa: F401
from .detector import (LanguageDetector, DetectionResult, detect,  # noqa: F401
                       detect_batch, detect_language_version)
from .hints import CLDHints  # noqa: F401

__version__ = "0.4.0"


def enable_jit_cache(cache_dir=None, min_compile_secs: float = 0.3):
    """Persist compiled XLA programs across processes (tools, tests, and
    benches share one setting; a fresh process otherwise pays 20-40s of
    jit compilation for the engine's block shapes). Safe no-op without
    jax. Call before the first jit dispatch."""
    try:
        import jax
        from pathlib import Path
        d = cache_dir or Path(__file__).resolve().parent.parent / \
            ".jax_cache"
        jax.config.update("jax_compilation_cache_dir", str(d))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
    except Exception:  # noqa: BLE001 - cacheless operation is fine
        pass
