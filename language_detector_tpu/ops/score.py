"""Batched device scoring: resolved hits -> per-chunk summaries.

The numeric core of detection (ScoreOneChunk totes + top-2 + reliability,
scoreonescriptspan.cc:208-302, cldutil.cc:553-605) as one jitted program
of fixed-shape tensor ops over the chunk-major flat wire the native
packer builds (packer.cc ldt_pack_flat_begin/finish): langprob decode,
chunk totes over 256 per-script languages as a fused one-hot reduction,
masked double-argmax top-2, and the reliability formulas.

Design rules for this device (TPU behind a high-latency tunnel): NO
scatter, NO sort, NO scan — the chunk tote is a masked one-hot reduce
over each chunk row's slot axis, top-k(2) is two masked argmaxes, and
everything sequential (probes, repeat cache, chunk assignment, boost
rotation) lives in the C++ packer where the few-MB tables are
cache-resident. History: ops/score.py@01ee7ba^ held an all-on-device
program (probes + lax.scan) — the wire transfer and the fixed ~95ms
dispatch latency dominated, so the split moved host-ward; the doc-major
successor (dense [B, L] slots + [B, C, L] one-hot chunk matmul,
@01ef460) coupled program shape to the longest document and collapsed on
mixed traffic, so the doc axis was dropped entirely.

The per-document epilogue (DocTote replay, close pairs, unreliable-language
removal, summary language — all O(1) per doc) runs on the host in
native/epilogue.cc, reusing the oracle-validated scalar semantics, so the
batched path agrees with the scalar engine exactly
(tests/test_batch_agreement.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .device_tables import DeviceTables

# keep in sync with packer.cc kHintBase / native HINT_BASE: wire idx
# values at or above this address the per-batch hint_lp window
HINT_BASE = 40960


def _decode3(lp):
    """langprob -> pslangs [.., 3] and group row index for qprob decode."""
    lp = lp.astype(jnp.uint32)
    ps = jnp.stack([(lp >> 8) & 0xFF, (lp >> 16) & 0xFF, (lp >> 24) & 0xFF],
                   axis=-1).astype(jnp.int32)
    return ps, (lp & 0xFF).astype(jnp.int32)


def _reliability_delta(s1, s2, grams):
    """cldutil.cc:553-570, integer math."""
    maxp = jnp.where(grams < 8, 12 * grams, 100)
    thresh = jnp.clip((grams * 5) >> 3, 3, 16)
    delta = s1 - s2
    pct = jnp.where(delta >= thresh, maxp,
                    jnp.where(delta <= 0, 0,
                              jnp.minimum(maxp, (100 * delta) // thresh)))
    return pct


def _reliability_expected(actual, expected):
    """cldutil.cc:587-605. f32 ratio math mirroring the scalar engine."""
    hi = jnp.maximum(actual, expected).astype(jnp.float32)
    lo = jnp.minimum(actual, expected).astype(jnp.float32)
    ratio = hi / jnp.maximum(lo, 1.0)
    pct = (100.0 * (4.0 - ratio) / 2.5).astype(jnp.int32)
    pct = jnp.where(ratio <= 1.5, 100, jnp.where(ratio > 4.0, 0, pct))
    pct = jnp.where(expected == 0, 100, pct)
    return jnp.where(actual == 0, jnp.where(expected == 0, 100, 0), pct)


def _lscript4(script):
    return jnp.where(script == 1, 0,
                     jnp.where(script == 3, 1, jnp.where(script == 6, 2, 3)))


# cmeta bit layout (keep in sync with packer.cc pack_resolve_one_doc):
#   cbytes(16) | grams(12) << 16 | side << 28 | real << 29
CM2_GRAMS_SHIFT = 16
CM2_SIDE_SHIFT = 28
CM2_REAL_SHIFT = 29
# output word: lang1(10) | s1(14) << 10 | rel(7) << 24 | real << 31
OUTW_S1_SHIFT = 10
OUTW_REL_SHIFT = 24
OUTW_REAL_SHIFT = 31
# second output word (result-vector batches only):
#   lang2(10) | rd(7) << 10 | rs(7) << 17
OUTW2_RD_SHIFT = 10
OUTW2_RS_SHIFT = 17


def _chunk_out_word(dt, scores, cbytes, grams, side, real, script,
                    group_scores=None, full_out=False, prior=None):
    """[..., 256] chunk totes + chunk meta -> packed u32 chunk summary:
    group-in-use top-2 (tote.cc:30-100), reliability (cldutil.cc:553-605),
    output word OUTW_* layout. Leading dims are free.

    group_scores: pre-whack scores for the group-in-use mask — the
    scalar tote marks groups in use at ADD time, and a hint whack zeroes
    the score without retiring the group (ZeroPSLang), so a fully
    whacked chunk still reports its zeroed top language.

    prior: optional [..., 256] per-chunk hint-prior vector (LDT_HINTS=1,
    hints.prior_vector) added to languages the chunk already scored,
    post-whack and pre-top-2. Only observed languages move: a prior
    never conjures a language with zero chunk evidence, and the
    group-in-use mask stays on pre-whack/pre-prior scores, so a
    prior-free document's word is bit-identical with hints on or off."""
    iota256 = jnp.arange(256, dtype=jnp.int32)
    lead = scores.shape[:-1]
    if group_scores is None:
        group_scores = scores
    if prior is not None:
        scores = jnp.where(scores > 0, scores + prior, scores)
    # group-in-use top-2 (qprob >= 1 invariant validated at
    # DeviceTables.from_host)
    groups = jnp.any((group_scores > 0).reshape(lead + (64, 4)), axis=-1)
    slot_in_use = jnp.repeat(groups, 4, axis=-1)
    sortkey = jnp.where(slot_in_use, scores * 256 + (255 - iota256), -1)
    k1 = jnp.argmax(sortkey, axis=-1)
    top1 = jnp.take_along_axis(sortkey, k1[..., None], axis=-1)[..., 0]
    sortkey2 = jnp.where(iota256 == k1[..., None], -1, sortkey)
    k2 = jnp.argmax(sortkey2, axis=-1)
    top2 = jnp.take_along_axis(sortkey2, k2[..., None], axis=-1)[..., 0]
    s1 = jnp.where(top1 >= 0, top1 >> 8, 0)
    s2 = jnp.where(top2 >= 0, top2 >> 8, 0)
    k1 = jnp.where(top1 >= 0, k1, 0)
    k2 = jnp.where(top2 >= 0, k2, 0)

    # per-script language mapping (rtype<=1 spans never reach the device:
    # the packer routes them through direct_adds)
    lang1 = dt.plang_to_lang[side, k1]
    lang2 = dt.plang_to_lang[side, k2]

    actual_kb = jnp.where(cbytes > 0, (s1 << 10) // jnp.maximum(cbytes, 1),
                          0)
    expected_kb = dt.expected_score[lang1, _lscript4(script)]
    rd = _reliability_delta(s1, s2, grams)
    same_set = (dt.close_set[lang1] != 0) & \
        (dt.close_set[lang1] == dt.close_set[lang2])
    rd = jnp.where(same_set, 100, rd)
    rs = _reliability_expected(actual_kb, expected_kb)
    crel = jnp.minimum(rd, rs)

    # single packed word per chunk: 4 bytes device->host readback.
    # s1 clips at 16383 — chunk totes are bounded far below (<= ~110
    # entries x qprob 12 + 4x12 boosts); the batch-agreement suite pins
    # exactness against the scalar engine.
    word1 = (lang1.astype(jnp.uint32) |
             (jnp.clip(s1, 0, 0x3FFF).astype(jnp.uint32)
              << OUTW_S1_SHIFT) |
             (jnp.clip(crel, 0, 127).astype(jnp.uint32)
              << OUTW_REL_SHIFT) |
             (real.astype(jnp.uint32) << OUTW_REAL_SHIFT))
    if not full_out:
        return word1
    # result-vector batches read lang2 / rd / rs separately: the chunk
    # relabeling pass (SummaryBufferToVector, scoreonescriptspan.cc:
    # 462-505) consults each, not just min(rd, rs)
    word2 = (lang2.astype(jnp.uint32) |
             (jnp.clip(rd, 0, 127).astype(jnp.uint32)
              << OUTW2_RD_SHIFT) |
             (jnp.clip(rs, 0, 127).astype(jnp.uint32)
              << OUTW2_RS_SHIFT))
    return jnp.stack([word1, word2], axis=-1)


# ---------------------------------------------------------------------------
# Chunk-major scorer: the flat wire (native.pack_chunks_native).
#
# The doc axis is gone — chunks from every document form one [G, K] grid
# (G = chunk rows per shard, K = fattest chunk's slot count, <= 256), so
# device cost is linear in total text and a 100KB document just
# contributes more rows to the same dispatch as the tweets around it.
# The doc-major wire's [B, C, L] one-hot chunk matmul (quadratic in doc
# length, the round-3 mixed-traffic cliff) has no equivalent here: the
# chunk reduction IS the K-axis sum.
# ---------------------------------------------------------------------------


def score_chunks_impl(dt: DeviceTables, p: dict, full_out: bool = False):
    """Score a chunk-major flat wire into packed chunk outputs [G] u32.

    p (built by native.pack_chunks_native):
      idx       [N]        u16  cat_ind2 index per resolved slot (flat);
                                values >= HINT_BASE address hint_lp
      cnsl      [G]        u8   chunk's slot count (chunk starts derive
                                here as a per-shard exclusive cumsum —
                                slots concatenate in chunk order)
      cmeta     [G]        u32  chunk meta (CM2_* layout)
      cscript   [G]        u8   chunk ULScript
      cwhack    [G]        u16  whack-table row (0 = no whacks), or a
                                1-wide dummy when no doc carries whacks
                                (the gather drops out at trace time)
      hint_lp   [H]        u32  hint-prior langprob window (per batch)
      whack_tbl [W,2,256]  u8   close-set whack masks per side
      k_iota    [K]        u8   dense chunk-row length carrier
      cprior    [G]        u16  OPTIONAL (LDT_HINTS=1): prior_tbl row
                                per chunk (0 = no prior)
      prior_tbl [P,2,256]  u8   OPTIONAL: per-doc hint-prior vectors
                                per side (row 0 all-zero)

    Reductions are chunk-local: safe under jit and shard_map over the
    chunk axis with zero collectives (the cnsl cumsum is per shard
    row, i.e. over the trailing axis of the shard's own block)."""
    idxf = p["idx"].reshape(-1)
    N = idxf.shape[0]
    cnsl2 = p["cnsl"].astype(jnp.int32)            # [D, Gs]
    cstart = (jnp.cumsum(cnsl2, axis=-1) - cnsl2).reshape(-1)
    cnsl = cnsl2.reshape(-1)
    cmeta = p["cmeta"].reshape(-1).astype(jnp.uint32)
    G = cstart.shape[0]
    K = p["k_iota"].shape[0]

    # dense [G, K] chunk rows (one gather pair); hint-prior slots read
    # the per-batch window (hints.py apply_hints boosts — extra tote
    # adds per chunk, scoreonescriptspan.cc:125-142)
    ki = jnp.arange(K, dtype=jnp.int32)
    valid = ki[None, :] < cnsl[:, None]
    gidx = jnp.clip(cstart[:, None] + ki[None, :], 0, N - 1)
    raw = idxf[gidx].astype(jnp.int32)
    hint_lp = p["hint_lp"]
    H = hint_lp.shape[0]
    lp_tbl = dt.cat_ind2[jnp.clip(raw, 0, dt.cat_ind2.shape[0] - 1)]
    lp_hint = hint_lp[jnp.clip(raw - HINT_BASE, 0, H - 1)]
    lp = jnp.where(valid,
                   jnp.where(raw >= HINT_BASE, lp_hint, lp_tbl), 0)

    # decode + chunk totes: the K-axis sum is the whole chunk reduction
    # (XLA fuses the one-hot compare into the reduce; nothing [G, K, 256]
    # materializes)
    ps, row = _decode3(lp)                                     # [G, K, 3]
    q = dt.lg_prob3[row].astype(jnp.int32)
    iota256 = jnp.arange(256, dtype=jnp.int32)
    # single vectorized reduction: the 3 pslang planes fold into one
    # [G, 3K] plane so XLA emits one fused compare+select+reduce pass
    # instead of three (integer adds commute, so this is bit-identical
    # to the per-plane loop it replaced; ops/kernels.py quantizes the
    # same shape further)
    contrib = jnp.where(valid[..., None] & (ps > 0), q, 0)
    psf = ps.reshape(G, -1)
    contribf = contrib.reshape(G, -1)
    scores = jnp.sum(
        jnp.where(psf[..., None] == iota256, contribf[..., None], 0),
        axis=1)

    cbytes = (cmeta & jnp.uint32(0xFFFF)).astype(jnp.int32)
    grams = ((cmeta >> CM2_GRAMS_SHIFT) & jnp.uint32(0xFFF)) \
        .astype(jnp.int32)
    side = ((cmeta >> CM2_SIDE_SHIFT) & jnp.uint32(1)).astype(jnp.int32)
    real = ((cmeta >> CM2_REAL_SHIFT) & jnp.uint32(1)).astype(jnp.int32)
    script = p["cscript"].reshape(-1).astype(jnp.int32)

    # close-set whacks (ZeroPSLang, scoreonescriptspan.cc:144-151):
    # zero hinted-out rival languages AFTER all tote adds, per chunk;
    # the group-in-use mask keeps the pre-whack adds (tote semantics).
    # Hint-free batches ship a 1-wide dummy whack lane — the gather
    # (and 64KB/batch of wire) drops out of the traced program.
    if p["cwhack"].shape[-1] == 1:
        whacked = scores
    else:
        cwhack = p["cwhack"].reshape(-1).astype(jnp.int32)
        wmask = p["whack_tbl"][jnp.clip(cwhack, 0,
                                        p["whack_tbl"].shape[0] - 1),
                               side]
        whacked = jnp.where(wmask > 0, 0, scores)
    # hint priors (LDT_HINTS=1): per-doc [2, 256] planes, deduped into
    # prior_tbl with each chunk carrying its doc's row. Keys exist only
    # when some doc in the batch has priors — prior-free batches trace
    # the identical program as before the feature existed.
    if "cprior" in p:  # ldt-lint: disable=trace-python-branch -- dict-key membership on the wire dict is a trace-time structural test (like the cwhack shape test above), not a traced value
        cprior = p["cprior"].reshape(-1).astype(jnp.int32)
        prior = p["prior_tbl"][
            jnp.clip(cprior, 0, p["prior_tbl"].shape[0] - 1),
            side].astype(jnp.int32)
    else:
        prior = None
    return _chunk_out_word(dt, whacked, cbytes, grams, side, real,
                           script, group_scores=scores,
                           full_out=full_out, prior=prior)


score_chunks = jax.jit(score_chunks_impl)
# result-vector variant: [G, 2] u32 (word1 as above + lang2/rd/rs word)
score_chunks_full = jax.jit(
    lambda dt, p: score_chunks_impl(dt, p, full_out=True))
# pipelined variant: the wire dict (arg 1) is donated, so the device
# reuses the transferred dispatch buffers in place instead of holding
# them alive alongside fresh output allocations. Host numpy inputs are
# copied to the device synchronously during the call, so the staging
# arrays behind the wire are reusable as soon as the launch returns —
# the contract the pack staging ring (native/__init__.py) relies on.
# On CPU backends jax warns that donation is unimplemented and falls
# back to copying; harmless, so the engine filters that warning at the
# launch site.
score_chunks_donated = jax.jit(score_chunks_impl, donate_argnums=(1,))


def unpack_chunks_out2(out2: np.ndarray) -> np.ndarray:
    """Second output word [G] u32 -> [G, 3] int32 (lang2, rd, rs)."""
    out2 = np.asarray(out2).reshape(-1)
    lang2 = (out2 & 0x3FF).astype(np.int32)
    rd = ((out2 >> OUTW2_RD_SHIFT) & 0x7F).astype(np.int32)
    rs = ((out2 >> OUTW2_RS_SHIFT) & 0x7F).astype(np.int32)
    return np.stack([lang2, rd, rs], axis=-1)


def unpack_chunks_out(out: np.ndarray, cmeta: np.ndarray) -> np.ndarray:
    """Device output [G] u32 (or sharded [D, Gs]) + host chunk meta ->
    the flat [G, 5] int32 chunk-summary layout the flat epilogue
    consumes."""
    out = np.asarray(out).reshape(-1)
    cmeta = cmeta.reshape(-1)
    lang1 = (out & 0x3FF).astype(np.int32)
    s1 = ((out >> OUTW_S1_SHIFT) & 0x3FFF).astype(np.int32)
    rel = ((out >> OUTW_REL_SHIFT) & 0x7F).astype(np.int32)
    real = ((out >> OUTW_REAL_SHIFT) & 1).astype(np.int32)
    cbytes = (cmeta & 0xFFFF).astype(np.int32)
    return np.stack([lang1, cbytes, s1, rel, real], axis=-1)


