#!/usr/bin/env python3
"""Per-language precision/recall/F evaluation harness.

The TPU rebuild of the reference's offline evaluator (scoreutf8text.cc:547,
whose published outputs are cld2/docs/evaluate_cld2_large_20140122.txt
etc.): detect every labeled document, tally per-language
correct/wrong-got/wrong-missed counts, and print per-language
precision/recall/F plus the _Totals_Known aggregate row and the top
confusions per language.

Input: a TSV of "code<TAB>text" lines (--corpus), or the reference golden
suite by default (tests/golden_data.py). Detection runs on the batched
engine when an accelerator is available, else the scalar engine.

Usage:
  python3 tools/eval_corpus.py [--corpus file.tsv] [--out docs/eval.txt]
"""
from __future__ import annotations

import argparse
import collections
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

from language_detector_tpu.registry import registry  # noqa: E402
from language_detector_tpu.tables import ScoringTables  # noqa: E402

# label aliases: the golden labels use a few codes our newer registry
# renames (tests/test_golden_parity.py applies the same equivalence)
ALIASES = {("hmn", "blu"): True}


def load_pairs(path: str | None):
    if path:
        pairs = []
        for line in Path(path).read_text().splitlines():
            if "\t" in line:
                code, text = line.split("\t", 1)
                pairs.append((code.strip(), text))
        return pairs
    from golden_data import golden_pairs
    return [(lang, raw.decode("utf-8", errors="replace"))
            for _, lang, raw in golden_pairs()]


def detect_all(texts, tables):
    try:
        from language_detector_tpu.models.ngram import NgramBatchEngine
        eng = NgramBatchEngine(tables, registry)
        return [registry.code(r.summary_lang)
                for r in eng.detect_many(texts, batch_size=4096)]
    except (ImportError, RuntimeError):
        from language_detector_tpu.engine_scalar import detect_scalar
        return [registry.code(detect_scalar(t, tables, registry)
                              .summary_lang) for t in texts]


def evaluate(pairs, tables) -> str:
    texts = [t for _, t in pairs]
    t0 = time.time()
    got = detect_all(texts, tables)
    took = time.time() - t0

    per_lang = collections.defaultdict(lambda: dict(correct=0, got=0,
                                                    actual=0))
    confusion = collections.defaultdict(collections.Counter)
    for (want, _), g in zip(pairs, got):
        hit = g == want or (g, want) in ALIASES
        per_lang[want]["actual"] += 1
        per_lang[g]["got"] += 1
        if hit:
            per_lang[want]["correct"] += 1
        else:
            confusion[want][g] += 1

    lines = []
    lines.append(f"Evaluation over {len(pairs)} labeled documents "
                 f"({len(per_lang)} languages), "
                 f"{len(pairs)/max(took,1e-9):.0f} docs/sec")
    lines.append("")
    lines.append(f"{'Language':12s} {'Precision':>9s} {'Recall':>8s} "
                 f"{'F':>7s} {'N':>6s}  Top confusions")
    tot_c = tot_g = tot_a = 0
    for code in sorted(per_lang):
        d = per_lang[code]
        if d["actual"] == 0:
            continue  # only appears as a wrong guess
        prec = d["correct"] / d["got"] if d["got"] else 0.0
        rec = d["correct"] / d["actual"]
        f = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        conf = " ".join(f"{g}={n}" for g, n in
                        confusion[code].most_common(5))
        lines.append(f"{code:12s} {prec*100:8.2f}% {rec*100:7.2f}% "
                     f"{f:7.4f} {d['actual']:6d}  {conf}")
        tot_c += d["correct"]
        tot_g += d["got"]
        tot_a += d["actual"]
    prec = tot_c / tot_g if tot_g else 0.0
    rec = tot_c / tot_a if tot_a else 0.0
    f = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
    lines.append("")
    lines.append(f"{'_Totals_Known':12s} {prec*100:8.2f}% {rec*100:7.2f}% "
                 f"{f:7.4f} {tot_a:6d}")
    return "\n".join(lines) + "\n"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--corpus", default=None,
                    help="TSV code<TAB>text (default: golden suite)")
    ap.add_argument("--quad-tables", default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    tables = ScoringTables.load(quad_path=args.quad_tables)
    pairs = load_pairs(args.corpus)
    report = evaluate(pairs, tables)
    print(report)
    if args.out:
        Path(args.out).write_text(report)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
