"""Host-side batch packer: texts -> fixed-shape candidate tensors.

The TPU engine's front half. For each document the host performs the
inherently sequential byte work (segmentation, gram positions, fingerprints,
the hash-only word repeat filter, squeeze triggers) and emits a *linear
candidate list* in the exact merge order the scalar engine scores hits
(delta <= distinct <= base at equal offsets, seed first). The device then
probes tables, applies the hit-dependent quad repeat filter, assigns chunks,
and reduces — all in fixed [B, L] shapes.

Documents that exceed the slot budget or need multiple hitbuffer rounds per
span are flagged for the scalar fallback path (the long tail; service
traffic is short).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ..registry import (RTYPE_CJK, RTYPE_MANY, RTYPE_NONE, RTYPE_ONE,
                        ULSCRIPT_LATIN, Registry)
from ..tables import ScoringTables
from .grams import MAX_SCORING_HITS, quad_positions, word_positions
from .hashing import bi_hash_v2, octa_hash40, pair_hash, quad_hash_v2
from .segment import ScriptSpan, segment_text, utf8_len_of_cps
from .squeeze import TEST_THRESH, cheap_squeeze_trigger_test

# Candidate kinds (device dispatch)
PAD, SEED, QUAD, UNI, DELTA_OCTA, DISTINCT_OCTA, BI_DELTA, BI_DISTINCT = \
    range(8)

# -- shape-tier ladder (bucketed batch scheduler) ---------------------------
#
# The scheduler in models/ngram.py partitions large streams by estimated
# per-doc slot demand into a small fixed ladder of dispatch lanes, so the
# ~160-byte median service doc shares padded shapes with its peers instead
# of with the 100KB tail. Budgets are SLOT counts (the wire's padded unit);
# the estimate is deliberately cheap — one len() per doc — because it runs
# on the packing hot path. ~1 candidate slot per 4 text chars holds across
# the corpus mix (Latin quads + word grams dominate), plus a fixed floor
# for the per-span seed/dummy slots of short docs.
#
# Two budgets -> three tiers:
#   short  <= 128 slots  (~0.5KB of text: tweets, chat, the service median)
#   mid    <= 1024 slots (~4KB: articles, product pages)
#   long   everything else (the heavy tail gets its own lane)
SLOT_TIER_BUDGETS = (128, 1024)
TIER_NAMES = ("short", "mid", "long")
N_TIERS = len(SLOT_TIER_BUDGETS) + 1
_TIER_BASE_SLOTS = 8


def est_slot_demand(text: str) -> int:
    """Cheap per-doc slot-demand estimate for tier routing: a fixed
    per-span floor plus ~1 slot per 4 chars. Routing only — the packer
    still computes exact n_slots; a misrouted doc just pads a little
    more, it can never change results."""
    return _TIER_BASE_SLOTS + (len(text) >> 2)


def tier_of_text(text: str) -> int:
    """Tier index (0..N_TIERS-1) for a document."""
    est = est_slot_demand(text)
    for k, budget in enumerate(SLOT_TIER_BUDGETS):
        if est <= budget:
            return k
    return N_TIERS - 1


def tier_max_chars(k: int) -> int:
    """Largest text length (in chars) routed to tier k — the exact
    bucket boundary, for boundary-parity tests and the soak."""
    return (SLOT_TIER_BUDGETS[k] - _TIER_BASE_SLOTS) * 4 + 3

# Kinds that count as base hits (chunk quota; UNIHIT/QUADHIT analogue)
BASE_KINDS = (SEED, QUAD, UNI)


# -- long-document span splitting (the longdoc lane) ------------------------
#
# Documents whose slot demand exceeds the top shape bucket serialize the
# lane they ride (one 100KB doc costs as much wire as 600 tweets). The
# engine splits them into sub-documents at SCRIPT-SPAN boundaries so each
# sub-pack is ordinary bucket-ladder work, then merges the per-chunk score
# rows back into one document summary (result_vector.merge_longdoc_chunks).
# Span boundaries are the only exact split points: chunk assignment, the
# octa repeat cache, and word-pair hashes all reset at a span edge, so a
# sub-document packs chunk-for-chunk identically to its slice of the
# unsplit document. Splitting inside a span would drop cross-word pair
# candidates and shift chunk boundaries — never exact — so single-span
# documents (monolingual text under the scanner's ~40KB span cap) ride
# their tier unsplit.

def _maybe_multi_span(text: str, tables) -> bool:
    """Cheap vectorized pre-filter: can this text segment into more than
    one script span? One span is certain when the letters are a single
    script and the text sits under the scanner's span cap — the common
    monolingual long doc, which must not pay the Python span scan."""
    from .segment import MAX_SPAN_PUT_BYTES, _decode_utf32
    cps = _decode_utf32(text)
    if len(cps) == 0:
        return False
    scripts = tables.script_of_cp[np.minimum(cps, 0x10FFFF)]
    letters = scripts[(scripts != 0) & (scripts != 40)]  # 40 = Inherited
    if len(letters) == 0:
        return False
    if letters.min() != letters.max():
        return True  # two scripts -> at least two spans possible
    # single script: multiple spans only if the scanner's cap can split
    return len(text.encode("utf-8", "surrogatepass")) >= MAX_SPAN_PUT_BYTES


def split_longdoc(text: str, tables: ScoringTables,
                  max_slots: int,
                  want_bounds: bool = False) -> list[str] | None:
    """Split one oversized document into span-aligned sub-documents of
    about `max_slots` estimated slots each. Returns the sub-texts (>= 2,
    source-order slices of `text`), or None when the document cannot be
    split exactly (single span, or re-segmentation of a slice would not
    reproduce the document's own spans). want_bounds=True returns
    (subs, bounds) instead, bounds[i] = (a, b) char extent of subs[i]
    in `text` (subs[i] == text[a:b]) — the LDT_SPANS surface derives
    span byte offsets from these; None still means "cannot split".

    Exactness contract: each returned slice re-segments into exactly the
    spans the full document produced for that range, so packing the
    sub-documents yields the same per-span candidates and chunk layout
    as the unsplit pack. Slices under the scanner's span cap are exact
    by construction (no soft-limit or truncation rule can fire inside
    them); larger slices are verified by re-segmentation and the whole
    split is abandoned on any mismatch."""
    from .segment import SOFT_SPAN_PUT_BYTES, segment_text
    if max_slots <= 0 or est_slot_demand(text) <= max_slots:
        return None
    if not _maybe_multi_span(text, tables):
        return None
    spans = segment_text(text, tables)
    if len(spans) < 2:
        return None

    # source-char extent of each span (src_idx maps span-buffer bytes to
    # source char indices; the final entry names the first char past the
    # last letter run, so end is exclusive after +1 at end-of-input)
    extents = []
    for sp in spans:
        if sp.src_idx is None or len(sp.src_idx) < 2:
            return None
        extents.append((int(sp.src_idx[1]), int(sp.src_idx[-1]) + 1))

    # greedy grouping toward the per-sub-doc char budget; a span bigger
    # than the budget (e.g. a scanner-capped 40KB run) is its own group
    budget_chars = max(1, (max_slots - _TIER_BASE_SLOTS) * 4)
    groups: list[list[int]] = [[]]
    cur_chars = 0
    for i, (s0, s1) in enumerate(extents):
        span_chars = s1 - s0
        if groups[-1] and cur_chars + span_chars > budget_chars:
            groups.append([])
            cur_chars = 0
        groups[-1].append(i)
        cur_chars += span_chars
    if len(groups) < 2:
        return None

    subs = []
    bounds = []
    for g in groups:
        a = extents[g[0]][0]
        b = extents[g[-1]][1]
        sub = text[a:b]
        # consecutive same-script spans exist only where a scanner size
        # rule fired; re-segmenting the slice without that rule would
        # merge them, so they always need the verify pass
        same_script_pair = any(
            spans[i].ulscript == spans[j].ulscript
            for i, j in zip(g, g[1:]))
        if same_script_pair or \
                len(sub.encode("utf-8", "surrogatepass")) >= \
                SOFT_SPAN_PUT_BYTES:
            # the scanner's soft-limit / even-split rules could fire
            # inside a slice this big: verify the slice reproduces the
            # document's own spans, else refuse to split
            re_spans = segment_text(sub, tables)
            if len(re_spans) != len(g):
                return None
            for rs, i in zip(re_spans, g):
                os_ = spans[i]
                if rs.text_bytes != os_.text_bytes or \
                        rs.ulscript != os_.ulscript or \
                        not np.array_equal(rs.buf[:rs.text_bytes],
                                           os_.buf[:os_.text_bytes]):
                    return None
        subs.append(sub)
        bounds.append((a, b))
    return (subs, bounds) if want_bounds else subs


@dataclasses.dataclass
class PackedBatch:
    """Fixed-shape candidate tensors for one batch of documents."""

    # Per-slot arrays [B, L]
    kind: np.ndarray          # int8 candidate kind
    offset: np.ndarray        # int32 span-buffer offset
    fp: np.ndarray            # uint32 fingerprint low 32 bits / direct
                              # payload (seed langprob, uni compat class)
    fp_hi: np.ndarray         # uint8 bits 32-39 of the 40-bit octa hash
    chunk_base: np.ndarray    # int32 first chunk id of the slot's span
    span_start: np.ndarray    # int32 first slot index of the slot's span
    span_end_off: np.ndarray  # int32 span end offset (dummy entry offset)
    side: np.ndarray          # int8 0=latn 1=othr boost stream
    cjk: np.ndarray           # int8 1 if CJK-scored span
    script: np.ndarray        # int16 span ULScript
    # Per-chunk arrays [B, C]
    chunk_script: np.ndarray  # int16 ULScript of the chunk's span
    chunk_cjk: np.ndarray     # int8
    chunk_side: np.ndarray    # int8
    chunk_span_end: np.ndarray  # int32 span end offset of the chunk's span
    # Direct doc-tote adds for RTypeNone/One spans [B, 4, 3]
    # (chunk_id, lang, bytes): each add owns a chunk id so the host epilogue
    # can replay all doc-tote adds in original span order.
    direct_adds: np.ndarray
    # Per-doc [B]
    text_bytes: np.ndarray    # int32 total scored text bytes
    fallback: np.ndarray      # bool: needs scalar path
    n_slots: np.ndarray       # int32 slots used (for wire-shape bucketing)
    n_chunks: np.ndarray      # int32 chunk ids allocated
    n_docs: int


def _seed_langprob(reg: Registry, ulscript: int) -> int:
    lang = reg.default_language(ulscript)
    pslang = reg.per_script_number(ULSCRIPT_LATIN, lang)
    return (pslang << 8) | 0  # qprob 1 -> backmap[1] = 0


def _pack_quad_span(span: ScriptSpan, tables: ScoringTables):
    """Quad + word candidates of one RTypeMany span, in linear merge order.

    Returns (records, overflow): records are dicts with kind/offset/... The
    quad repeat filter is left to the device (it depends on hit results);
    the word repeat filter and pair construction are hash-only and happen
    here, exactly as the scalar engine does."""
    limit = span.text_bytes
    qpos, qlens, _ = quad_positions(span.buf, 1, limit)
    if len(qpos) > MAX_SCORING_HITS:
        return None  # multi-round span -> scalar fallback
    qfps = quad_hash_v2(span.buf, qpos, qlens) if len(qpos) else \
        np.zeros(0, np.uint32)

    wstarts, wlens, wpriors = word_positions(span.buf, 1, limit)
    wfps = octa_hash40(span.buf, wstarts, wlens) if len(wstarts) else \
        np.zeros(0, np.uint64)

    # Hash-only octa repeat filter + pair hashes (cldutil.cc:459-502).
    # Records carry the 40-bit fingerprint (low 32 + high 8); the device
    # derives each table's bucket subscript and key (ops/score.py).
    recs = []
    cache = [np.uint64(0), np.uint64(0)]
    nxt = 0
    n_delta = n_distinct = 0
    for i in range(len(wfps)):
        fpw = wfps[i]
        if fpw == cache[0] or fpw == cache[1]:
            continue
        cache[nxt] = fpw
        nxt = 1 - nxt
        prior = cache[nxt]
        if prior != 0 and prior != fpw:
            pfp = int(pair_hash(prior, fpw))
            recs.append(dict(kind=DISTINCT_OCTA, offset=int(wpriors[i]),
                             fp=pfp & 0xFFFFFFFF, fp_hi=(pfp >> 32) & 0xFF))
            n_distinct += 1
        w = int(fpw)
        recs.append(dict(kind=DISTINCT_OCTA, offset=int(wstarts[i]),
                         fp=w & 0xFFFFFFFF, fp_hi=(w >> 32) & 0xFF))
        recs.append(dict(kind=DELTA_OCTA, offset=int(wstarts[i]),
                         fp=w & 0xFFFFFFFF, fp_hi=(w >> 32) & 0xFF))
        n_delta += 1
        n_distinct += 1
        if n_delta >= MAX_SCORING_HITS or n_distinct >= MAX_SCORING_HITS - 1:
            break

    for i in range(len(qpos)):
        recs.append(dict(kind=QUAD, offset=int(qpos[i]), fp=int(qfps[i])))
    return recs


def _pack_cjk_span(span: ScriptSpan, tables: ScoringTables):
    """Unigram + bigram candidates of one RTypeCJK span."""
    lens = utf8_len_of_cps(span.cps)
    ends = np.cumsum(lens)
    starts = ends - lens
    prop = tables.cjk_uni_prop[np.minimum(span.cps, 0x10FFFF)]
    sel = (prop > 0) & (starts >= 1) & (starts < span.text_bytes)
    if int(sel.sum()) > MAX_SCORING_HITS:
        return None  # multi-round span -> scalar fallback
    recs = []
    for e, p in zip(ends[sel].tolist(), prop[sel].tolist()):
        recs.append(dict(kind=UNI, offset=int(e), direct=int(p)))

    len2 = lens[:-1] + lens[1:]
    ok = (len2 >= 6) & (starts[:-1] >= 1) & (starts[:-1] < span.text_bytes)
    idx = np.flatnonzero(ok)
    if len(idx):
        fps = bi_hash_v2(span.buf, starts[idx], len2[idx])
        xt = tables.distinctbi
        # Bigram records carry the raw 32-bit fingerprint; per-table
        # sub/key derive on device (ops/score.py _quad_sub_key).
        for j, i in enumerate(idx.tolist()):
            recs.append(dict(kind=BI_DELTA, offset=int(starts[i]),
                             fp=int(fps[j])))
            if not xt.empty:
                recs.append(dict(kind=BI_DISTINCT, offset=int(starts[i]),
                                 fp=int(fps[j])))
    return recs


# Linear merge priority at equal offsets (LinearizeAll order: delta,
# distinct, base; seed always first)
_PRIORITY = {SEED: -1, DELTA_OCTA: 0, BI_DELTA: 0, DISTINCT_OCTA: 1,
             BI_DISTINCT: 1, QUAD: 2, UNI: 2}


def pack_batch(texts: list[str], tables: ScoringTables, reg: Registry,
               max_slots: int = 2048, max_chunks: int = 64,
               max_direct: int = 4, flags: int = 0) -> PackedBatch:
    """Pack a batch for device scoring.

    `flags` are the engine's scoring flags: FLAG_FINISH (bit 0,
    compact_lang_det_impl.h:31) disables the squeeze-trigger fallback test,
    matching the scalar engine's recursion guard."""
    from ..engine_scalar import FLAG_FINISH
    B = len(texts)
    L, C = max_slots, max_chunks
    out = PackedBatch(
        kind=np.zeros((B, L), np.int8),
        offset=np.zeros((B, L), np.int32),
        fp=np.zeros((B, L), np.uint32),
        fp_hi=np.zeros((B, L), np.uint8),
        chunk_base=np.zeros((B, L), np.int32),
        span_start=np.zeros((B, L), np.int32),
        span_end_off=np.zeros((B, L), np.int32),
        side=np.zeros((B, L), np.int8),
        cjk=np.zeros((B, L), np.int8),
        script=np.zeros((B, L), np.int16),
        chunk_script=np.zeros((B, C), np.int16),
        chunk_cjk=np.zeros((B, C), np.int8),
        chunk_side=np.zeros((B, C), np.int8),
        chunk_span_end=np.zeros((B, C), np.int32),
        direct_adds=np.full((B, max_direct, 3), -1, np.int32),
        text_bytes=np.zeros(B, np.int32),
        fallback=np.zeros(B, bool),
        n_slots=np.zeros(B, np.int32),
        n_chunks=np.zeros(B, np.int32),
        n_docs=B,
    )

    for b, text in enumerate(texts):
        spans = segment_text(text, tables)
        slot = 0
        chunk_base = 0
        n_direct = 0
        total = 0
        ok = True
        for span in spans:
            total += span.text_bytes
            rtype = reg.rtype(span.ulscript)
            # Squeeze-trigger documents take the scalar path (rare/spam);
            # the scalar engine tests every span (impl.cc:1866-1901).
            if not (flags & FLAG_FINISH) and \
                    (TEST_THRESH >> 1) < span.text_bytes and \
                    cheap_squeeze_trigger_test(span.buf.tobytes(),
                                               span.text_bytes):
                ok = False
                break
            if rtype in (RTYPE_NONE, RTYPE_ONE):
                if n_direct >= max_direct or chunk_base >= C:
                    ok = False
                    break
                out.direct_adds[b, n_direct] = (
                    chunk_base, reg.default_language(span.ulscript),
                    span.text_bytes)
                n_direct += 1
                chunk_base += 1
                continue
            if span.text_bytes <= 1:
                continue
            cjk = rtype == RTYPE_CJK
            recs = _pack_cjk_span(span, tables) if cjk \
                else _pack_quad_span(span, tables)
            if recs is None:
                ok = False
                break
            recs.append(dict(kind=SEED, offset=1,
                             direct=_seed_langprob(reg, span.ulscript)))
            recs.sort(key=lambda r: (r["offset"], _PRIORITY[r["kind"]]))
            # Worst-case chunk count if every base candidate hits
            n_base_max = sum(1 for r in recs if r["kind"] in BASE_KINDS)
            span_chunks = max(1, -(-n_base_max //
                                   (50 if cjk else 20)) + 1)
            if slot + len(recs) > L or chunk_base + span_chunks > C:
                ok = False
                break
            side = 0 if span.ulscript == ULSCRIPT_LATIN else 1
            for r in recs:
                out.kind[b, slot] = r["kind"]
                out.offset[b, slot] = r["offset"]
                out.fp[b, slot] = r.get("fp", r.get("direct", 0))
                out.fp_hi[b, slot] = r.get("fp_hi", 0)
                out.chunk_base[b, slot] = chunk_base
                out.span_end_off[b, slot] = span.text_bytes
                out.side[b, slot] = side
                out.cjk[b, slot] = cjk
                out.script[b, slot] = span.ulscript
                slot += 1
            start = slot - len(recs)
            out.span_start[b, start:slot] = start
            sl = slice(chunk_base, chunk_base + span_chunks)
            out.chunk_script[b, sl] = span.ulscript
            out.chunk_cjk[b, sl] = cjk
            out.chunk_side[b, sl] = side
            out.chunk_span_end[b, sl] = span.text_bytes
            chunk_base += span_chunks
        out.text_bytes[b] = total
        out.fallback[b] = not ok
        out.n_slots[b] = slot
        out.n_chunks[b] = chunk_base
    return out
