"""On-demand device profiling: a bounded jax.profiler window you can
arm against live traffic.

POST /profilez on either front's metrics port (or SIGUSR2 to the
worker) starts a jax.profiler trace into LDT_PROFILE_DIR; a watchdog
thread stops it LDT_PROFILE_WINDOW_SEC later, so an operator can never
leave a profiler running against production. Exactly one window can be
armed at a time (a second request answers 409 busy), and everything is
defensive: no LDT_PROFILE_DIR or no importable jax.profiler answers a
typed 503, never a crash — the serving path must not depend on
profiler availability. Outcomes land in
ldt_profile_captures_total{result=} and the profile_capture
flight-recorder event.
"""
from __future__ import annotations

import json
import os
import threading
import time

from . import flightrec, knobs, telemetry
from .locks import make_lock

_LOCK = make_lock("profiling.window")
_ACTIVE: dict | None = None     # {"dir", "t0", "window_sec"} while armed


def _stop_after(window_sec: float, out_dir: str) -> None:
    time.sleep(window_sec)
    global _ACTIVE
    with _LOCK:
        if _ACTIVE is None or _ACTIVE["dir"] != out_dir:
            return
        _ACTIVE = None
    try:
        import jax
        jax.profiler.stop_trace()
        result = "ok"
    except Exception as e:  # noqa: BLE001 - report, never crash serving
        print(json.dumps({"msg": "profiler stop failed",
                          "error": repr(e)}), flush=True)
        result = "error"
    telemetry.REGISTRY.counter_inc("ldt_profile_captures_total",
                                   result=result)
    flightrec.emit_event("profile_capture", phase="stop", result=result,
                         dir=out_dir)


def arm(window_sec: float | None = None) -> tuple:
    """Arm one bounded profiler window -> (status, payload dict).
    503 = disabled/unavailable, 409 = a window is already armed,
    200 = capture started (payload says where and for how long)."""
    global _ACTIVE
    base = knobs.get_str("LDT_PROFILE_DIR")
    if not base:
        telemetry.REGISTRY.counter_inc("ldt_profile_captures_total",
                                       result="unavailable")
        return 503, {"error": "profiling disabled: LDT_PROFILE_DIR "
                              "is not set"}
    if window_sec is None:
        window_sec = knobs.get_float("LDT_PROFILE_WINDOW_SEC") or 5.0
    window_sec = max(0.05, min(float(window_sec), 600.0))
    out_dir = os.path.join(base, f"profile-{os.getpid()}-{int(time.time())}")
    with _LOCK:
        if _ACTIVE is not None:
            telemetry.REGISTRY.counter_inc("ldt_profile_captures_total",
                                           result="busy")
            return 409, {"error": "a profiler window is already armed",
                         "dir": _ACTIVE["dir"]}
        _ACTIVE = {"dir": out_dir, "t0": time.time(),
                   "window_sec": window_sec}
    try:
        os.makedirs(out_dir, exist_ok=True)
        import jax
        jax.profiler.start_trace(out_dir)
    except Exception as e:  # noqa: BLE001 - typed refusal, never a crash
        with _LOCK:
            _ACTIVE = None
        telemetry.REGISTRY.counter_inc("ldt_profile_captures_total",
                                       result="error")
        flightrec.emit_event("profile_capture", phase="start",
                             result="error")
        return 503, {"error": f"profiler unavailable: {e!r}"}
    threading.Thread(target=_stop_after, args=(window_sec, out_dir),
                     daemon=True, name="ldt-profile-stop").start()
    flightrec.emit_event("profile_capture", phase="start", result="ok",
                         dir=out_dir, window_sec=window_sec)
    return 200, {"profiling": "started", "dir": out_dir,
                 "window_sec": window_sec}


def active() -> dict | None:
    with _LOCK:
        return dict(_ACTIVE) if _ACTIVE is not None else None


def install_sigusr2() -> bool:
    """SIGUSR2 -> arm(): the no-HTTP path for profiling a wedged or
    fleet-fronted worker. Main-thread only (signal module contract);
    False when that's not the case (tests, embedded use)."""
    import signal
    try:
        signal.signal(signal.SIGUSR2, lambda *_: arm())
        return True
    except ValueError:
        return False
