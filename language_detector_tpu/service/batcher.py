"""Request batcher: many concurrent HTTP requests -> few large device
batches.

The reference calls the detector once per item inside the handler loop
(handlers.go:133-186, one cgo call each); the TPU redesign accumulates
items from all in-flight requests and dispatches them as one batch
(SURVEY.md §3.1), trading a small queueing delay for device efficiency.
A single worker thread drains the queue, flushing when `max_batch` items
are pending or `max_delay_ms` has passed since the oldest undispatched
item arrived.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import Future


class Batcher:
    """Deadline/size-batched dispatcher over a detection engine."""

    def __init__(self, detect_fn, max_batch: int = 4096,
                 max_delay_ms: float = 5.0):
        self._detect = detect_fn          # list[str] -> list[results]
        self.max_batch = max_batch
        self.max_delay = max_delay_ms / 1e3
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ldt-batcher")
        self._thread.start()

    def submit(self, texts: list) -> Future:
        """Queue one request's texts; resolves to their results (in
        order) once a batch containing them completes."""
        fut: Future = Future()
        self._q.put((texts, fut))
        return fut

    def close(self):
        self._stop.set()
        self._q.put(None)  # wake the worker
        self._thread.join(timeout=5)

    # -- worker --------------------------------------------------------------

    def _run(self):
        while not self._stop.is_set():
            item = self._q.get()
            if item is None:
                continue
            pending = [item]
            n = len(item[0])
            # accumulate until deadline or size cap
            import time
            deadline = time.monotonic() + self.max_delay
            while n < self.max_batch:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=timeout)
                except queue.Empty:
                    break
                if nxt is None:
                    break
                pending.append(nxt)
                n += len(nxt[0])
            texts = [t for ts, _ in pending for t in ts]
            try:
                results = self._detect(texts)
            except Exception as e:  # noqa: BLE001 - fail every waiter
                for _, fut in pending:
                    if not fut.cancelled():
                        fut.set_exception(e)
                continue
            i = 0
            for ts, fut in pending:
                if not fut.cancelled():
                    fut.set_result(results[i:i + len(ts)])
                i += len(ts)
