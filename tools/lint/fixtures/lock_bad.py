"""Fixture: lock-discipline violations — an owned attribute touched
outside its lock, and a cross-object read through an alias."""
import threading


class Ladder:
    def __init__(self):
        self._lock = threading.Lock()
        self.level = 0

    def set(self, v):
        with self._lock:
            self.level = v


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
        self.ladder = Ladder()

    def bump(self):
        self.n += 1                 # owned attr outside the lock

    def read(self):
        with self._lock:
            return self.n           # fine

    def peek_level(self):
        return self.ladder.level    # torn read through the alias
