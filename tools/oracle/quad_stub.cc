// Stub quadgram tables for the parity oracle.
//
// The reference snapshot is missing its two quadgram data files
// (cld2_generated_quad0122.cc / cld2_generated_quadchrome_2.cc — see
// compile_libs.sh:31-53), so the oracle is built with empty 1-bucket tables:
// every quadgram lookup misses and scoring falls back to octagram/CJK/script
// signals. The TPU framework under test runs with the same table set, so
// agreement tests remain apples-to-apples.

#include "integral_types.h"
#include "cld2tablesummary.h"

namespace CLD2 {

static const IndirectProbBucket4 kQuadStubBuckets[1] = {
  {{0, 0, 0, 0}},
};
static const uint32 kQuadStubInd[2] = {0, 0};

extern const CLD2TableSummary kQuad_obj = {
  kQuadStubBuckets,
  kQuadStubInd,
  1,            // kCLDTableSizeOne
  1,            // kCLDTableSize (bucket count; power of two)
  0xFFFFF000,   // kCLDTableKeyMask
  20130527,     // build date
  "",           // recognized lang-scripts
};

// Size 0 disables the dual-table second probe (cldutil.cc:357).
extern const CLD2TableSummary kQuad_obj2 = {
  kQuadStubBuckets,
  kQuadStubInd,
  1,
  0,
  0xFFFFF000,
  20130527,
  "",
};

}  // namespace CLD2
