"""Batched device scoring: packed candidates -> per-chunk summaries.

The hot path of detection (compact_lang_det_impl.cc:1707-2106 ->
cldutil.cc:315-533) runs here as one jitted program of fixed-shape tensor
ops over a [B, L] candidate batch:

  1. 4-way-associative table probes               (vectorized gathers)
  2. quad repeat filter                            (lax.scan, tiny state)
  3. langprob resolution incl. double entries      (gathers)
  4. chunk assignment                              (closed-form ranks)
  5. chunk totes over 256 per-script languages     (segment sums)
  6. top-2 + reliability per chunk                 (top_k + elementwise)
  7. chunk summaries [B, C]                        (lang1/bytes/score/rel)

The per-document epilogue (DocTote replay, close pairs, unreliable-language
removal, summary language — all O(1) per doc) runs on the host in
models/ngram.py, reusing the oracle-validated scalar code, so the batched
path agrees with the scalar engine exactly (tests/test_batch_agreement.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .device_tables import DeviceTables

# Kind ids (keep in sync with preprocess/pack.py)
PAD, SEED, QUAD, UNI, DELTA_OCTA, DISTINCT_OCTA, BI_DELTA, BI_DISTINCT = \
    range(8)

CHUNK_QUADS = 20
CHUNK_UNIS = 50
MAX_BOOST_RANKS = 256


def _probe(table, sub, key):
    """4-way bucket probe: matching keyvalue or 0 (cldutil_shared.h:403)."""
    rows = table.buckets[jnp.clip(sub, 0, table.size - 1)]      # [B, L, 4]
    km = jnp.uint32(table.keymask)
    match = ((rows ^ key[..., None]) & km) == 0
    hit = match.any(-1)
    slot = jnp.argmax(match, axis=-1)
    kv = jnp.take_along_axis(rows, slot[..., None], axis=-1)[..., 0]
    return jnp.where(hit, kv, jnp.uint32(0))


def _resolve_base(table, idx):
    """Base-table indirect -> (lp_a, lp_b) with the double-entry convention
    (LinearizeAll, scoreonescriptspan.cc:936-964)."""
    idx = idx.astype(jnp.int32)
    single = idx < table.size_one
    i2 = idx + (idx - table.size_one)
    n = len(table.ind)
    lp_a = jnp.where(single,
                     table.ind[jnp.clip(idx, 0, n - 1)],
                     table.ind[jnp.clip(i2, 0, n - 1)])
    lp_b = jnp.where(single, jnp.uint32(0),
                     table.ind[jnp.clip(i2 + 1, 0, n - 1)])
    return lp_a, lp_b


def _quad_filter_scan(fp, is_quad_hit, span_begin):
    """Exact 2-entry repeat cache over hit quads, reset at span starts
    (cldutil.cc:334-367). State is [B]-vectors; scan runs over L."""
    B = fp.shape[0]
    init = (jnp.zeros(B, jnp.uint32), jnp.zeros(B, jnp.uint32),
            jnp.zeros(B, jnp.int32))

    def step(state, x):
        c0, c1, nxt = state
        f, active, begin = x
        c0 = jnp.where(begin, jnp.uint32(0), c0)
        c1 = jnp.where(begin, jnp.uint32(0), c1)
        nxt = jnp.where(begin, 0, nxt)
        repeat = (f == c0) | (f == c1)
        keep = active & ~repeat
        c0 = jnp.where(keep & (nxt == 0), f, c0)
        c1 = jnp.where(keep & (nxt == 1), f, c1)
        nxt = jnp.where(keep, 1 - nxt, nxt)
        return (c0, c1, nxt), keep

    xs = (jnp.swapaxes(fp, 0, 1), jnp.swapaxes(is_quad_hit, 0, 1),
          jnp.swapaxes(span_begin, 0, 1))
    _, keep = jax.lax.scan(step, init, xs)
    return jnp.swapaxes(keep, 0, 1)


def _chunk_of_rank(r, n_quota, chunksize):
    """Closed-form ChunkAll boundary rule (scoreonescriptspan.cc:994-1003):
    chunks of `chunksize` until <2 chunks remain, then runt merging."""
    c = chunksize
    n = n_quota
    k_full = jnp.where(n < 2 * c, 0, (n - 2 * c) // c + 1)
    tail = n - k_full * c
    in_full = r < k_full * c
    tr = r - k_full * c
    tail_single = tail < c + (c >> 1)
    half = (tail + 1) >> 1
    tail_chunk = jnp.where(tail_single, 0, (tr >= half).astype(jnp.int32))
    return jnp.where(in_full, r // c, k_full + tail_chunk)


def _decode3(lp):
    """langprob -> pslangs [.., 3] and group row index for qprob decode."""
    lp = lp.astype(jnp.uint32)
    ps = jnp.stack([(lp >> 8) & 0xFF, (lp >> 16) & 0xFF, (lp >> 24) & 0xFF],
                   axis=-1).astype(jnp.int32)
    return ps, (lp & 0xFF).astype(jnp.int32)


def _reliability_delta(s1, s2, grams):
    """cldutil.cc:553-570, integer math."""
    maxp = jnp.where(grams < 8, 12 * grams, 100)
    thresh = jnp.clip((grams * 5) >> 3, 3, 16)
    delta = s1 - s2
    pct = jnp.where(delta >= thresh, maxp,
                    jnp.where(delta <= 0, 0,
                              jnp.minimum(maxp, (100 * delta) // thresh)))
    return pct


def _reliability_expected(actual, expected):
    """cldutil.cc:587-605. f32 ratio math mirroring the scalar engine."""
    hi = jnp.maximum(actual, expected).astype(jnp.float32)
    lo = jnp.minimum(actual, expected).astype(jnp.float32)
    ratio = hi / jnp.maximum(lo, 1.0)
    pct = (100.0 * (4.0 - ratio) / 2.5).astype(jnp.int32)
    pct = jnp.where(ratio <= 1.5, 100, jnp.where(ratio > 4.0, 0, pct))
    pct = jnp.where(expected == 0, 100, pct)
    return jnp.where(actual == 0, jnp.where(expected == 0, 100, 0), pct)


def _lscript4(script):
    return jnp.where(script == 1, 0,
                     jnp.where(script == 3, 1, jnp.where(script == 6, 2, 3)))


def _quad_sub_key(table, fp):
    """Derive bucket subscript + probe key from a 32-bit fingerprint
    (cldutil_shared.h:380-386); geometry is static per table."""
    sub = ((fp + (fp >> jnp.uint32(12))) &
           jnp.uint32(table.size - 1)).astype(jnp.int32)
    return sub, fp & jnp.uint32(table.keymask)


def _octa_sub_key(table, lo, hi):
    """Derive bucket subscript + probe key from a 40-bit fingerprint
    carried as (low 32, bits 32-39), exactly matching
    hashing.octa_subscript_key (cldutil_shared.h:389-397) in pure uint32
    arithmetic: only fingerprint bits 0..35 reach the subscript/key for
    any table geometry <= 2^28 buckets."""
    sum_lo = lo + ((lo >> jnp.uint32(12)) | (hi << jnp.uint32(20)))
    sub = (sum_lo & jnp.uint32(table.size - 1)).astype(jnp.int32)
    key = ((lo >> jnp.uint32(4)) | (hi << jnp.uint32(28))) & \
        jnp.uint32(table.keymask)
    return sub, key


def score_batch_impl(dt: DeviceTables, p: dict):
    """Score one packed batch into stacked chunk summaries.

    p is the wire format built by models/ngram.py (9 bytes/slot over the
    host->device link):
      slots_u8  [B, L, 3] kind, chunk_base, fp_hi (octa hash bits 32-39)
      slots_u16 [B, L]    span-buffer offset
      slots_u32 [B, L]    fingerprint low 32 bits (quad/bi/octa) or direct
                          payload (seed langprob, uni compat class)
      chunk_u8  [B, C, 3] script, cjk, side
      chunk_u16 [B, C]    span end offset

    Every per-table bucket subscript and probe key derives on device; the
    per-slot side/cjk/span-start metadata derives from chunk_base + chunk
    metadata. Pure fixed-shape function: safe under jit and shard_map over
    the leading document axis (documents are independent; every reduction
    is doc-local)."""
    kind = p["slots_u8"][..., 0].astype(jnp.int32)            # [B, L]
    chunk_base = p["slots_u8"][..., 1].astype(jnp.int32)
    fp_hi = p["slots_u8"][..., 2].astype(jnp.uint32)
    B, L = kind.shape
    C = p["chunk_u8"].shape[1]
    offset = p["slots_u16"].astype(jnp.int32)
    w0 = p["slots_u32"].astype(jnp.uint32)
    chunk_script = p["chunk_u8"][..., 0].astype(jnp.int32)
    chunk_cjk = p["chunk_u8"][..., 1].astype(jnp.int32)
    chunk_side = p["chunk_u8"][..., 2].astype(jnp.int32)
    direct = w0
    fp = w0

    # Per-slot metadata from chunk metadata: chunk_base is constant within
    # a span and strictly increases across spans, so span starts are the
    # slots where it changes; side/cjk gather from the span's first chunk.
    pad = kind == PAD
    cb_prev = jnp.concatenate(
        [jnp.full((B, 1), -1, jnp.int32), chunk_base[:, :-1]], axis=1)
    span_begin = (chunk_base != cb_prev) & ~pad
    span_start = jax.lax.cummax(
        jnp.where(span_begin, jnp.arange(L)[None, :], 0), axis=1)
    side = jnp.take_along_axis(chunk_side, chunk_base, axis=1)
    cjk = jnp.take_along_axis(chunk_cjk, chunk_base, axis=1)
    span_end_off = jnp.take_along_axis(
        p["chunk_u16"].astype(jnp.int32), chunk_base, axis=1)

    # ---- 1. table probes -------------------------------------------------
    sub_q1, key_q1 = _quad_sub_key(dt.quadgram, fp)
    kv_quad = _probe(dt.quadgram, sub_q1, key_q1)
    if dt.quad2_enabled:
        sub_q2, key_q2 = _quad_sub_key(dt.quadgram2, fp)
        kv_quad2 = _probe(dt.quadgram2, sub_q2, key_q2)
    else:
        kv_quad2 = jnp.zeros_like(kv_quad)
    sub_o, key_o = _octa_sub_key(dt.deltaocta, w0, fp_hi)
    kv_delta = _probe(dt.deltaocta, sub_o, key_o)
    sub_x, key_x = _octa_sub_key(dt.distinctocta, w0, fp_hi)
    kv_dist = _probe(dt.distinctocta, sub_x, key_x)
    sub_bd, key_bd = _quad_sub_key(dt.cjkdeltabi, fp)
    sub_bx, key_bx = _quad_sub_key(dt.distinctbi, fp)
    kv_bid = _probe(dt.cjkdeltabi, sub_bd, key_bd)
    kv_bix = _probe(dt.distinctbi, sub_bx, key_bx)

    nk = lambda t: jnp.uint32(~np.uint32(t.keymask))  # noqa: E731

    # ---- 2. quad repeat filter (needs hit knowledge) ---------------------
    quad_hit = (kind == QUAD) & ((kv_quad != 0) | (kv_quad2 != 0))
    keep_quad = _quad_filter_scan(fp, quad_hit, span_begin)

    # ---- 3. langprob resolution ------------------------------------------
    use2 = kv_quad == 0
    qa1, qb1 = _resolve_base(dt.quadgram, kv_quad & nk(dt.quadgram))
    qa2, qb2 = _resolve_base(dt.quadgram2, kv_quad2 & nk(dt.quadgram2))
    quad_lp_a = jnp.where(use2, qa2, qa1)
    quad_lp_b = jnp.where(use2, qb2, qb1)
    uni_lp_a, uni_lp_b = _resolve_base(dt.cjkcompat,
                                       direct)
    n_do = len(dt.deltaocta.ind)
    n_xo = len(dt.distinctocta.ind)
    n_bd = len(dt.cjkdeltabi.ind)
    n_bx = len(dt.distinctbi.ind)
    lp_delta = dt.deltaocta.ind[
        jnp.clip((kv_delta & nk(dt.deltaocta)).astype(jnp.int32), 0, n_do - 1)]
    lp_dist = dt.distinctocta.ind[
        jnp.clip((kv_dist & nk(dt.distinctocta)).astype(jnp.int32), 0,
                 n_xo - 1)]
    lp_bid = dt.cjkdeltabi.ind[
        jnp.clip((kv_bid & nk(dt.cjkdeltabi)).astype(jnp.int32), 0, n_bd - 1)]
    lp_bix = dt.distinctbi.ind[
        jnp.clip((kv_bix & nk(dt.distinctbi)).astype(jnp.int32), 0, n_bx - 1)]

    lp_a = jnp.select(
        [kind == SEED, kind == QUAD, kind == UNI, kind == DELTA_OCTA,
         kind == DISTINCT_OCTA, kind == BI_DELTA, kind == BI_DISTINCT],
        [direct, quad_lp_a, uni_lp_a,
         jnp.where(kv_delta != 0, lp_delta, 0),
         jnp.where(kv_dist != 0, lp_dist, 0),
         jnp.where(kv_bid != 0, lp_bid, 0),
         jnp.where(kv_bix != 0, lp_bix, 0)],
        jnp.uint32(0))
    lp_b = jnp.select([kind == QUAD, kind == UNI],
                      [quad_lp_b, uni_lp_b], jnp.uint32(0))
    # Quad slots removed by the repeat filter contribute nothing
    quad_mask = (kind != QUAD) | keep_quad
    lp_a = jnp.where(quad_mask, lp_a, 0)
    lp_b = jnp.where(quad_mask, lp_b, 0)
    valid_a = lp_a != 0
    valid_b = lp_b != 0

    is_base_kind = (kind == SEED) | (kind == QUAD) | (kind == UNI)
    # linear-entry contribution toward chunk quotas and gram counts
    entry_contrib = jnp.where(is_base_kind,
                              valid_a.astype(jnp.int32) +
                              valid_b.astype(jnp.int32), 0)
    # base hit RECORDS (chunk quota input; seed is not a record)
    base_record = ((kind == QUAD) & keep_quad) | \
        ((kind == UNI) & valid_a)

    # ---- 4. chunk assignment ---------------------------------------------
    span_key = (jnp.arange(B)[:, None] * L +
                span_start)  # [B, L]
    flat_span = span_key.reshape(-1)
    n_records = jax.ops.segment_sum(
        base_record.reshape(-1).astype(jnp.int32), flat_span,
        num_segments=B * L).reshape(B, L)
    n_span_records = n_records[
        jnp.arange(B)[:, None], span_start]

    cum_entries = jnp.cumsum(entry_contrib, axis=1)
    start_idx = span_start
    cum_at_start = jnp.take_along_axis(cum_entries, start_idx, axis=1)
    contrib_at_start = jnp.take_along_axis(entry_contrib, start_idx, axis=1)
    cb_incl = cum_entries - cum_at_start + contrib_at_start
    cb_excl = cb_incl - entry_contrib  # consumed strictly before this slot

    chunksize = jnp.where(cjk > 0, CHUNK_UNIS, CHUNK_QUADS)
    quota = jnp.maximum(n_span_records, 0)
    # clip rank so overflow lands in the final chunk (forced end boundary)
    r = jnp.clip(cb_excl, 0, jnp.maximum(quota - 1, 0))
    local_chunk = jnp.where(quota == 0, 0,
                            _chunk_of_rank(r, quota, chunksize))
    chunk_id = chunk_base + local_chunk
    chunk_id = jnp.clip(chunk_id, 0, C - 1)

    slot_valid = valid_a & (kind != PAD)
    flat_chunk = jnp.where(slot_valid,
                           jnp.arange(B)[:, None] * C + chunk_id, B * C)
    flat_chunk_f = flat_chunk.reshape(-1)

    # ---- 5. chunk totes ---------------------------------------------------
    ps_a, row_a = _decode3(lp_a)
    ps_b, row_b = _decode3(lp_b)
    q_a = dt.lg_prob3[row_a].astype(jnp.int32)     # [B, L, 3]
    q_b = dt.lg_prob3[row_b].astype(jnp.int32)

    def tote_scatter(ps, q, ok):
        seg = (flat_chunk[..., None] * 256 + ps).reshape(-1)
        val = jnp.where(ok[..., None] & (ps > 0), q, 0).reshape(-1)
        seg = jnp.where(val > 0, seg, (B * C + 1) * 256 - 1)
        return jax.ops.segment_sum(val, seg,
                                   num_segments=(B * C + 1) * 256)

    scores = tote_scatter(ps_a, q_a, valid_a) + \
        tote_scatter(ps_b, q_b, valid_b)

    # Distinct-word rotating boosts: per doc per side, ranks of distinct hits
    is_distinct = ((kind == DISTINCT_OCTA) | (kind == BI_DISTINCT)) & valid_a
    d_latn = is_distinct & (side == 0)
    d_othr = is_distinct & (side == 1)
    cum_latn = jnp.cumsum(d_latn.astype(jnp.int32), axis=1)
    cum_othr = jnp.cumsum(d_othr.astype(jnp.int32), axis=1)
    R = MAX_BOOST_RANKS

    def rank_lps(d_mask, cum):
        rank = jnp.where(d_mask, cum - 1, R)        # 0-based rank
        rank = jnp.clip(rank, 0, R)
        flat = (jnp.arange(B)[:, None] * (R + 1) + rank).reshape(-1)
        return jax.ops.segment_max(
            jnp.where(d_mask, lp_a, 0).astype(jnp.uint32).reshape(-1), flat,
            num_segments=B * (R + 1)).reshape(B, R + 1)

    lps_latn = rank_lps(d_latn, cum_latn)
    lps_othr = rank_lps(d_othr, cum_othr)

    # cumulative distinct count at each chunk's last slot
    def chunk_cum(cum):
        return jax.ops.segment_max(
            jnp.where(slot_valid, cum, 0).reshape(-1), flat_chunk_f,
            num_segments=B * C + 1)[:B * C].reshape(B, C)

    dk_latn = chunk_cum(cum_latn)
    dk_othr = chunk_cum(cum_othr)
    # chunk_side: [B, C]
    dk = jnp.where(chunk_side == 0, dk_latn, dk_othr)
    src = jnp.where(chunk_side[..., None] == 0, lps_latn[:, None, :],
                    lps_othr[:, None, :])                # [B, C, R+1]
    boost_ranks = dk[..., None] - 1 - jnp.arange(4)      # [B, C, 4]
    boost_ok = boost_ranks >= 0
    boost_lps = jnp.take_along_axis(
        src, jnp.clip(boost_ranks, 0, R), axis=2)
    boost_lps = jnp.where(boost_ok, boost_lps, 0)
    bps, brow = _decode3(boost_lps)                      # [B, C, 4, 3]
    bq = dt.lg_prob3[brow].astype(jnp.int32)
    bval = jnp.where((boost_lps[..., None] != 0) & (bps > 0), bq, 0)
    scores = scores.reshape(B * C + 1, 256)[:B * C].reshape(B, C, 256)
    bseg_scores = jnp.zeros_like(scores)
    bseg_scores = bseg_scores.at[
        jnp.arange(B)[:, None, None, None],
        jnp.arange(C)[None, :, None, None],
        bps].add(bval)
    scores = scores + bseg_scores

    # group-in-use mask: any add (hits or boosts) touches pslang's group;
    # scatter group marks via segment_max on 4-slot groups
    def mark(ps, ok):
        seg = (flat_chunk[..., None] * 64 + (ps >> 2)).reshape(-1)
        val = (ok[..., None] & (ps > 0)).astype(jnp.int32).reshape(-1)
        seg = jnp.where(val > 0, seg, (B * C + 1) * 64 - 1)
        return jax.ops.segment_max(val, seg,
                                   num_segments=(B * C + 1) * 64)

    groups = mark(ps_a, valid_a) | mark(ps_b, valid_b)
    groups = groups[:B * C * 64].reshape(B, C, 64)
    bgroups = jnp.zeros((B, C, 64), jnp.int32)
    bgroups = bgroups.at[
        jnp.arange(B)[:, None, None, None],
        jnp.arange(C)[None, :, None, None],
        bps >> 2].max(jnp.where((boost_lps[..., None] != 0) & (bps > 0),
                                1, 0))
    groups = groups | bgroups
    slot_in_use = jnp.repeat(groups.astype(bool), 4, axis=2)  # [B, C, 256]

    # ---- 6. chunk summaries ----------------------------------------------
    grams = jax.ops.segment_sum(
        jnp.where(kind <= UNI, entry_contrib, 0).reshape(-1), flat_chunk_f,
        num_segments=B * C + 1)[:B * C].reshape(B, C)
    lo_off = jax.ops.segment_min(
        jnp.where(slot_valid, offset, 1 << 30).reshape(-1), flat_chunk_f,
        num_segments=B * C + 1)[:B * C].reshape(B, C)
    chunk_count = jax.ops.segment_sum(
        slot_valid.astype(jnp.int32).reshape(-1), flat_chunk_f,
        num_segments=B * C + 1)[:B * C].reshape(B, C)
    span_end = jax.ops.segment_max(
        jnp.where(slot_valid, span_end_off, 0)
        .reshape(-1), flat_chunk_f,
        num_segments=B * C + 1)[:B * C].reshape(B, C)
    span_of_chunk = jax.ops.segment_max(
        jnp.where(slot_valid, span_key, -1).reshape(-1), flat_chunk_f,
        num_segments=B * C + 1)[:B * C].reshape(B, C)
    real = chunk_count > 0
    next_lo = jnp.concatenate([lo_off[:, 1:], jnp.full((B, 1), 1 << 30)],
                              axis=1)
    next_span = jnp.concatenate([span_of_chunk[:, 1:],
                                 jnp.full((B, 1), -2)], axis=1)
    next_real = jnp.concatenate([real[:, 1:], jnp.zeros((B, 1), bool)],
                                axis=1)
    hi_off = jnp.where(next_real & (next_span == span_of_chunk), next_lo,
                       span_end)
    cbytes = jnp.maximum(hi_off - lo_off, 0)

    sortkey = jnp.where(slot_in_use,
                        scores * 256 + (255 - jnp.arange(256)), -1)
    top2, topi = jax.lax.top_k(sortkey, 2)
    k1 = 255 - (top2[..., 0] & 255)
    k2 = 255 - (top2[..., 1] & 255)
    s1 = jnp.where(top2[..., 0] >= 0, top2[..., 0] >> 8, 0)
    s2 = jnp.where(top2[..., 1] >= 0, top2[..., 1] >> 8, 0)
    k1 = jnp.where(top2[..., 0] >= 0, k1, 0)
    k2 = jnp.where(top2[..., 1] >= 0, k2, 0)

    script = chunk_script
    rtype = dt.lang_rtype_default[script, 0]
    deflang = dt.lang_rtype_default[script, 1]
    side_idx = jnp.where(script == 1, 0, 1)

    def to_lang(ps):
        mapped = dt.plang_to_lang[side_idx, ps]
        return jnp.where(rtype <= 1, deflang, mapped)

    lang1 = to_lang(k1)
    lang2 = to_lang(k2)

    actual_kb = jnp.where(cbytes > 0, (s1 << 10) // jnp.maximum(cbytes, 1), 0)
    expected_kb = dt.expected_score[lang1, _lscript4(script)]
    rd = _reliability_delta(s1, s2, grams)
    same_set = (dt.close_set[lang1] != 0) & \
        (dt.close_set[lang1] == dt.close_set[lang2])
    rd = jnp.where(same_set, 100, rd)
    rs = _reliability_expected(actual_kb, expected_kb)
    crel = jnp.minimum(rd, rs)

    # ---- 7. chunk summary outputs ----------------------------------------
    # One stacked [B, C, 5] array (a single device->host transfer). The
    # document epilogue (DocTote replay, close pairs, unreliable-language
    # removal, summary language) runs on the host over it, reusing the
    # oracle-validated scalar code (models/ngram.py). Chunk ids are
    # allocated in span order by the packer, so replaying chunks by id
    # reproduces the scalar engine's DocTote insertion order exactly.
    return jnp.stack(
        [lang1, cbytes, s1, crel, real.astype(jnp.int32)], axis=-1)


# Lane order of the stacked score_batch output
OUT_LANG1, OUT_BYTES, OUT_SCORE1, OUT_REL, OUT_REAL = range(5)


score_batch = jax.jit(score_batch_impl)
