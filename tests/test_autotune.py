"""Tests for the SLO autotuner (autotune.py) on synthetic response
surfaces — no servers, no replay: `evaluate` is injected.
"""
from __future__ import annotations

import pytest

from language_detector_tpu import autotune, slo, telemetry

SPEC = slo.parse_spec("p99_ms=100,err_pct=2,window_sec=8")


def test_knob_space_covers_declared_mutables():
    space = autotune.knob_space()
    names = [s[0] for s in space]
    assert "LDT_MAX_INFLIGHT" in names
    assert "LDT_BROWNOUT_ALPHA" in names
    for name, lo, hi, _b in space:
        assert lo < hi, name


def test_knob_space_restricts_to_names():
    space = autotune.knob_space(names={"LDT_MAX_INFLIGHT"})
    assert [s[0] for s in space] == ["LDT_MAX_INFLIGHT"]


def test_candidates_for_unset_knob_ladder_in_range():
    cands = autotune.candidates("LDT_MAX_QUEUE_DOCS", None,
                                1.0, 1_000_000.0, True)
    assert cands, "unset knob produced no seed candidates"
    assert all(1 <= c <= 1_000_000 for c in cands)
    assert sorted(cands) == cands  # geometric ladder ascends


def test_candidates_for_live_value_multiplier_moves():
    cands = autotune.candidates("LDT_MAX_INFLIGHT", 64,
                                1.0, 65536.0, True)
    assert 16 in cands and 32 in cands
    assert 128 in cands and 256 in cands
    assert None in cands  # bound knob: "off" is a move


def test_candidates_clamp_to_mrange():
    cands = autotune.candidates("LDT_BROWNOUT_ALPHA", 0.5,
                                0.01, 1.0, False)
    assert all(c <= 1.0 for c in cands)
    assert None not in cands  # not a bound knob


def test_score_feasibility_dominates_throughput():
    good = {"p99_ms": 50, "err_pct": 0.5, "ok_docs_per_sec": 100}
    fast_but_breaching = {"p99_ms": 500, "err_pct": 0.5,
                          "ok_docs_per_sec": 10_000}
    assert autotune.score(good, SPEC) \
        > autotune.score(fast_but_breaching, SPEC)


def test_score_without_spec_is_throughput():
    m = {"p99_ms": 9999, "err_pct": 50, "ok_docs_per_sec": 123.0}
    assert autotune.score(m, None) == 123.0


def test_autotune_finds_feasible_optimum():
    """Synthetic surface: p99 explodes unless LDT_MAX_INFLIGHT is
    bounded near 64; throughput grows with the bound. The search must
    land inside the feasible region, beating the (unbounded, breaching)
    baseline on the declared SLO metric."""

    def evaluate(ov):
        inflight = ov.get("LDT_MAX_INFLIGHT")
        if inflight is None:  # unbounded: queue bloat, terrible p99
            return {"p99_ms": 2000.0, "err_pct": 0.0,
                    "ok_docs_per_sec": 500.0}
        p99 = 20.0 + inflight * 1.0        # grows with concurrency
        thpt = 100.0 * min(inflight, 128) ** 0.5
        return {"p99_ms": p99, "err_pct": 0.0,
                "ok_docs_per_sec": thpt}

    res = autotune.autotune(evaluate,
                            names={"LDT_MAX_INFLIGHT"}, spec=SPEC)
    best = res["best"]
    assert "LDT_MAX_INFLIGHT" in best
    assert best["LDT_MAX_INFLIGHT"] <= 80  # feasible: p99 <= 100
    assert res["best_metrics"]["p99_ms"] <= 100.0
    assert res["baseline_metrics"]["p99_ms"] > 100.0
    assert res["best_score"] > res["baseline_score"]


def test_autotune_counts_evals_and_caches():
    calls = []

    def evaluate(ov):
        calls.append(dict(ov))
        return {"p99_ms": 10.0, "err_pct": 0.0,
                "ok_docs_per_sec": 100.0}

    before = telemetry.REGISTRY.counter_value(
        "ldt_autotune_evals_total")
    res = autotune.autotune(evaluate, names={"LDT_MAX_INFLIGHT"},
                            spec=SPEC, rounds=3)
    after = telemetry.REGISTRY.counter_value(
        "ldt_autotune_evals_total")
    # flat surface: no move improves, so the search stops after one
    # round and every distinct point was evaluated exactly once
    assert res["evals"] == len(calls)
    assert after - before == len(calls)
    assert len({tuple(sorted(c.items())) for c in calls}) == len(calls)


def test_autotune_respects_live_overrides_as_start():
    """A knob already holding a runtime override starts the search
    there, not at the env default."""
    from language_detector_tpu import knobs

    knobs.apply_overrides({"LDT_MAX_INFLIGHT": "64"})
    try:
        seen = []

        def evaluate(ov):
            seen.append(ov.get("LDT_MAX_INFLIGHT"))
            return {"p99_ms": 10.0, "err_pct": 0.0,
                    "ok_docs_per_sec": 1.0}

        autotune.autotune(evaluate, names={"LDT_MAX_INFLIGHT"},
                          spec=SPEC, rounds=1)
        # multiplier moves around 64, not the unset seed ladder
        assert 128 in seen or 32 in seen
    finally:
        knobs.clear_overrides()


def test_autotune_result_shape_for_bench_round():
    def evaluate(ov):
        return {"p99_ms": 10.0, "err_pct": 0.0,
                "ok_docs_per_sec": 100.0}

    res = autotune.autotune(evaluate, names={"LDT_MAX_INFLIGHT"},
                            spec=SPEC)
    for key in ("best", "best_score", "best_metrics",
                "baseline_metrics", "baseline_score", "evals", "spec"):
        assert key in res, key
    assert res["spec"]["target_ms"] == 100.0


def test_autotune_with_pytest_approx_noise_free_determinism():
    """Same evaluate surface twice -> identical result (the search has
    no randomness of its own)."""

    def make_eval():
        def evaluate(ov):
            q = ov.get("LDT_MAX_QUEUE_DOCS") or 0
            return {"p99_ms": 10.0 + (q % 97), "err_pct": 0.0,
                    "ok_docs_per_sec": float(q or 1)}
        return evaluate

    a = autotune.autotune(make_eval(),
                          names={"LDT_MAX_QUEUE_DOCS"}, spec=SPEC)
    b = autotune.autotune(make_eval(),
                          names={"LDT_MAX_QUEUE_DOCS"}, spec=SPEC)
    assert a == b


def test_autotune_uses_declared_slo_from_env(monkeypatch):
    monkeypatch.setenv("LDT_SLO", "p99_ms=55,err_pct=3,window_sec=8")

    def evaluate(ov):
        return {"p99_ms": 10.0, "err_pct": 0.0,
                "ok_docs_per_sec": 1.0}

    res = autotune.autotune(evaluate, names={"LDT_MAX_INFLIGHT"})
    assert res["spec"]["target_ms"] == 55.0
    assert pytest.approx(res["spec"]["err_pct"]) == 3.0
