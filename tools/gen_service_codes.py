#!/usr/bin/env python3
"""Generate the service's ISO-639 code -> English name map.

Mirrors the reference's data/gen_codes.py pipeline (which capitalized the
uppercase CLD2 language-name table into data/cld_codes.json, 164 entries):
walk the registry's (code, name) pairs, keep codes the service should
answer with, capitalize names, and fail on conflicting names per code.
tests/test_service.py diffs the output against the reference's JSON when
the snapshot is present.
"""
from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from language_detector_tpu.registry import registry  # noqa: E402

OUT = REPO / "language_detector_tpu/service/cld_codes.json"


def main():
    langs: dict = {}
    for lang in range(registry.num_languages):
        code = registry.code(lang)
        name = registry.name(lang)
        if not code or code in ("un", "xxx", "none"):
            continue
        # the reference maps both Chinese variants to "Chinese"
        # (data/cld_codes.json zh / zh-Hant rows), and used the older
        # table's names for two codes our newer registry renames
        if code == "zh-Hant":
            name = "Chinese"
        elif code == "ny":
            name = "Chichewa"
        elif code == "tlh":
            name = "Klingon"
        pretty = name.capitalize()
        if code in langs and langs[code] != pretty:
            raise SystemExit(f"conflicting names for {code}: "
                             f"{langs[code]} vs {pretty}")
        langs[code] = pretty
    OUT.parent.mkdir(parents=True, exist_ok=True)
    OUT.write_text(json.dumps(langs, indent=4, separators=(",", ": "),
                              sort_keys=True) + "\n")
    print(f"wrote {OUT} ({len(langs)} codes)")


if __name__ == "__main__":
    main()
