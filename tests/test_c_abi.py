"""C ABI seam: detect_language() / ldt_detect_batch_codes().

The reference's cgo boundary is one C function (wrapper.h:8,
wrapper.cc:7-16): `const char* detect_language(const char*)` returning a
static ISO-code string. A Go host links the library and calls it with no
Python in the loop. These tests call the exported symbols through a raw
ctypes handle — exactly the cgo calling convention — and assert the
C-side pipeline (pack -> C chunk scorer -> epilogue -> recursion) agrees
with the engine's device path on every document.
"""
from __future__ import annotations

import ctypes
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent))
from golden_data import golden_pairs  # noqa: E402

from language_detector_tpu import native  # noqa: E402
from language_detector_tpu.registry import registry  # noqa: E402
from language_detector_tpu.tables import load_tables  # noqa: E402

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


@pytest.fixture(scope="module")
def clib():
    """Raw CDLL handle, as a cgo host would hold it (tables initialized
    through the public init seam first)."""
    tables = load_tables()
    native.ensure_init(tables, registry)
    lib = ctypes.CDLL(str(Path(native.__file__).parent / "libldtpack.so"))
    lib.detect_language.restype = ctypes.c_char_p
    lib.detect_language.argtypes = [ctypes.c_char_p]
    return lib


def test_detect_language_known_scripts(clib):
    cases = [
        ("Le gouvernement a annoncé de nouvelles mesures pour aider "
         "les familles", b"fr"),
        ("こんにちは世界。今日はとても良い天気ですね。散歩に行きましょう。",
         b"ja"),
        ("ภาษาไทยเป็นภาษาที่สวยงามและมีประวัติศาสตร์", b"th"),
        ("Η γρήγορη καφέ αλεπού πηδά πάνω από το τεμπέλικο σκυλί σήμερα "
         "το πρωί στον κήπο", b"el"),
        ("", b"un"),
    ]
    for text, want in cases:
        assert clib.detect_language(text.encode()) == want, text[:40]


def test_detect_language_matches_engine(clib):
    """C-side detection == the engine's device path on the golden suite
    plus squeeze/retry/edge constructions (the pipelines share the
    packer and epilogue; this pins the C chunk scorer against the device
    scorer)."""
    from language_detector_tpu.models.ngram import NgramBatchEngine
    pairs = golden_pairs()
    if not pairs:
        pytest.skip("reference snapshot unavailable")
    texts = [raw.decode("utf-8", errors="replace")
             for _, _, raw in pairs][::4]
    texts += [
        "buy cheap now " * 400,                  # squeeze pass
        "word " * 600,                           # squeeze + repeats
        texts[0][:150] + " " + texts[-1][:150],  # gate-failure retry
        "", "a", "123 !!!", "🎉🎊",
    ]
    eng = NgramBatchEngine()
    want = eng.detect_codes(texts)

    # single-doc entry (NUL-terminated: embedded NULs truncate, so only
    # compare docs without them)
    for t, w in zip(texts, want):
        if "\x00" in t:
            continue
        got = clib.detect_language(t.encode("utf-8", "surrogatepass"))
        assert got.decode() == w, t[:50]

    # batched entry
    enc = [t.encode("utf-8", "surrogatepass") for t in texts]
    bounds = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(e) for e in enc], out=bounds[1:])
    blob = np.frombuffer(b"".join(enc), np.uint8) if bounds[-1] \
        else np.zeros(1, np.uint8)
    blob = np.ascontiguousarray(blob)
    out = np.zeros(len(enc), np.int32)
    clib.ldt_detect_batch_codes(
        blob.ctypes.data_as(ctypes.c_void_p),
        bounds.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int32(len(enc)), ctypes.c_int32(4),
        out.ctypes.data_as(ctypes.c_void_p))
    got_codes = [registry.code(int(i)) for i in out]
    assert got_codes == want
