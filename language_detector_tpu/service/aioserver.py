"""Event-driven HTTP front end: the single-core production server.

Same JSON contract as service/server.py (the reference's handlers.go
semantics — shared via pre_detect/post_detect), but served from one
asyncio event loop instead of a thread per connection. On this host's
single CPU core the threaded stack loses most of its cycles to GIL
convoying and context switches once a few dozen sockets are active; the
event loop serves hundreds of connections from one thread, and only the
engine flushes leave it (a small executor, mirroring the sync Batcher's
worker pool).

Run: python -m language_detector_tpu.service.aioserver
(LISTEN_PORT / PROMETHEUS_PORT env vars, like the sync server).
"""
from __future__ import annotations

import asyncio
import contextlib
import json
import os
from concurrent.futures import ThreadPoolExecutor

from .. import capture, faults, flightrec, knobs, slo, telemetry
from . import wire
from .admission import (DeadlineExceeded, FairScheduler,
                        degraded_detect)
from .batcher import (_MISS, Batcher, ResultCache, _accepts_trace,
                      flush_workers)
from .server import (BODY_LIMIT_BYTES, USAGE, DetectorService,
                     health_response)

_MAX_HEADER_BYTES = 16384


def _drain_sec() -> float:
    """Bounded window for in-flight handlers to finish their response
    before their sockets are aborted (recycle and SIGTERM drains). Read
    at drain time, not import time, so a supervisor/test that sets
    LDT_RECYCLE_DRAIN_SEC after this module imports is still honored."""
    return knobs.get_float("LDT_RECYCLE_DRAIN_SEC") or 5.0


class AioBatcher:
    """Asyncio-native twin of batcher.Batcher: accumulate submissions
    from the event loop, flush to the engine on a small executor, and
    resolve asyncio futures back on the loop."""

    def __init__(self, detect_fn, max_batch: int = 16384,
                 max_delay_ms: float = 5.0, cache_bytes: int = 0):
        self._detect = detect_fn
        # engine-backed detect fns take trace= (see batcher.Batcher)
        self._detect_takes_trace = _accepts_trace(detect_fn)
        self.max_batch = max_batch
        self.max_delay = max_delay_ms / 1e3
        self._q: asyncio.Queue = asyncio.Queue()
        # deficit-weighted fair queueing at dequeue (LDT_TENANT_WEIGHTS;
        # None = strict FIFO). Owned by the collector task alone.
        self._sched = FairScheduler.from_env()
        # widened with the device pool's lane count (batcher.py)
        self._n_flush = flush_workers()
        self._pool = ThreadPoolExecutor(self._n_flush,
                                        thread_name_prefix="ldt-aioflush")
        self._task: asyncio.Task | None = None
        # same LRU result cache as the sync Batcher (this front has no
        # per-request hints, so the key is just the exact text)
        self._cache = ResultCache(cache_bytes) if cache_bytes > 0 \
            else None

    def cache_stats(self) -> dict | None:
        return self._cache.stats() if self._cache is not None else None

    def start(self):
        self._task = asyncio.get_running_loop().create_task(
            self._collector())

    async def submit(self, texts: list, trace=None) -> list:
        """trace: optional telemetry.Trace — the flush serving this
        request grafts its engine stage spans into it (same contract as
        batcher.Batcher.submit)."""
        fut = asyncio.get_running_loop().create_future()
        if faults.ACTIVE is not None:
            # enqueue fault: raises before the future enters the queue,
            # so the handler answers it and nothing is left half-armed
            await faults.hit_async("queue_put")
        await self._q.put((texts, trace, fut))
        # same bound the sync path enforces via fut.result(...): a
        # wedged flush must fail the request, not pin the connection
        return await asyncio.wait_for(
            fut, timeout=knobs.get_float("LDT_FLUSH_TIMEOUT_SEC") or 60.0)

    async def close(self):
        if self._task is not None:
            self._task.cancel()
            try:
                # wait for the collector's CancelledError handler to
                # fail whatever batch it was accumulating — shutting
                # down the executor under a live flush would orphan it
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        # enqueued-but-never-collected submissions: with the collector
        # gone, nothing else will ever pop these off the queue
        while True:
            try:
                item = self._q.get_nowait()
            except asyncio.QueueEmpty:
                break
            fut = item[-1]
            if not fut.done():
                fut.set_exception(RuntimeError("batcher closed"))
        if self._sched is not None:
            # the stash is collector-owned; with the collector
            # cancelled nothing else will ever resolve these futures
            for *_, fut in self._sched.drain_all():
                if not fut.done():
                    fut.set_exception(RuntimeError("batcher closed"))
        self._pool.shutdown(wait=False)

    async def _collector(self):
        loop = asyncio.get_running_loop()
        # bound in-flight flushes (executor queue would otherwise grow
        # unboundedly when the device falls behind)
        slots = asyncio.Semaphore(self._n_flush + 1)
        pending: list = []
        try:
            while True:
                sched = self._sched
                if sched is not None and sched.backlog:
                    # stashed backlog exists: don't block on an empty
                    # queue, just sweep in whatever already arrived
                    try:
                        first = await asyncio.wait_for(self._q.get(),
                                                       self.max_delay)
                    except asyncio.TimeoutError:
                        first = None
                else:
                    first = await self._q.get()
                pending = [first] if first is not None else []
                n = len(first[0]) if first is not None else 0
                deadline = loop.time() + self.max_delay
                while n < self.max_batch and first is not None:
                    timeout = deadline - loop.time()
                    if timeout <= 0:
                        break
                    try:
                        nxt = await asyncio.wait_for(self._q.get(), timeout)
                    except asyncio.TimeoutError:
                        break
                    pending.append(nxt)
                    n += len(nxt[0])
                if sched is not None:
                    # fair queueing at dequeue: stash the sweep, pop the
                    # next batch in deficit-round-robin order; whatever a
                    # saturating tenant over-queued waits in its lane
                    for it in pending:
                        sched.push(it)
                    pending = sched.pop_batch(self.max_batch)
                    if not pending:
                        continue
                if faults.ACTIVE is not None:
                    # dequeue fault: fail THIS batch's waiters with the
                    # typed error and keep collecting — the collector task
                    # must survive any chaos profile (a wait_for-cancelled
                    # future is done(); skip it)
                    try:
                        await faults.hit_async("queue_get")
                    except faults.FaultInjected as e:
                        for *_, fut in pending:
                            if not fut.done():
                                fut.set_exception(e)
                        continue
                # dequeue-time deadline check (shared with the sync
                # Batcher: (texts, trace, fut) has the same tail) — expired
                # requests fail with DeadlineExceeded before this flush
                # takes a slot
                pending = Batcher._drop_expired(pending)
                if not pending:
                    continue
                await slots.acquire()
                texts = [t for ts, _, _ in pending for t in ts]
                # one flush-scoped trace shared by every traced request in
                # the batch (same grafting contract as batcher.Batcher)
                ftrace = telemetry.Trace() \
                    if any(tr is not None for _, tr, _ in pending) else None
                if ftrace is not None:
                    ftrace.adopt_constraints(tr for _, tr, _ in pending)

                def _resolve(results, pending=pending, ftrace=ftrace):
                    i = 0
                    for ts, tr, fut in pending:
                        if not fut.done():
                            if tr is not None and ftrace is not None:
                                tr.graft(ftrace, depth=1)
                            fut.set_result(results[i:i + len(ts)])
                        i += len(ts)

                if self._cache is not None:
                    vals = [self._cache.get((None, t)) for t in texts]
                    miss = [i for i, v in enumerate(vals) if v is _MISS]
                    if not miss:
                        slots.release()
                        _resolve(vals)
                        continue
                else:
                    vals, miss = None, None
                miss_texts = texts if miss is None \
                    else [texts[i] for i in miss]
                if self._detect_takes_trace:
                    task = loop.run_in_executor(
                        self._pool,
                        lambda mt=miss_texts, ft=ftrace:
                            self._detect(mt, trace=ft))
                else:
                    task = loop.run_in_executor(self._pool, self._detect,
                                                miss_texts)

                def _done(ftr, pending=pending, vals=vals, miss=miss,
                          texts=texts, miss_texts=miss_texts,
                          _resolve=_resolve):
                    slots.release()
                    err = ftr.exception()
                    if err is not None:
                        for _, _, fut in pending:
                            if not fut.done():
                                fut.set_exception(err)
                        return
                    results = ftr.result()
                    if miss is None:
                        _resolve(results)
                        return
                    for i, v in zip(miss, results):
                        vals[i] = v
                        self._cache.put((None, texts[i]), v, texts[i])
                    _resolve(vals)
                task.add_done_callback(_done)
                # ownership transferred: _done (which runs even if the
                # loop dies) now answers these futures, so a subsequent
                # cancellation must not double-claim them
                pending = []
        except asyncio.CancelledError:
            # close() cancelled us mid-accumulation: answer the
            # batch we were holding before the task dies, else its
            # submitters hang until their wait_for timeouts (the
            # futures of an already-dispatched flush are owned by
            # _done and stay out of `pending`)
            for *_, fut in pending:
                if not fut.done():
                    fut.set_exception(RuntimeError("batcher closed"))
            raise


def _http_head(status: int, length: int,
               content_type: bytes = b"application/json; "
                                     b"charset=utf-8",
               extra_headers: tuple = ()) -> bytes:
    reason = {200: b"OK", 203: b"Non-Authoritative Information",
              400: b"Bad Request", 404: b"Not Found",
              413: b"Payload Too Large",
              429: b"Too Many Requests",
              431: b"Request Header Fields Too Large",
              500: b"Internal Server Error",
              503: b"Service Unavailable",
              504: b"Gateway Timeout"}.get(status, b"OK")
    head = (b"HTTP/1.1 %d %s\r\nContent-Type: %s\r\n"
            b"Content-Length: %d\r\n"
            % (status, reason, content_type, length))
    for k, v in extra_headers:
        head += k + b": " + v + b"\r\n"
    return head + b"\r\n"


def _http_response(status: int, body: bytes,
                   content_type: bytes = b"application/json; "
                                         b"charset=utf-8",
                   extra_headers: tuple = ()) -> bytes:
    return _http_head(status, len(body), content_type,
                      extra_headers) + body


def _http_response_buffers(status: int, buffers: list,
                           extra_headers: tuple = ()) -> list:
    """writev-style response: the head plus the batch-envelope buffer
    list, handed to writer.writelines without concatenation."""
    length = 0
    for b in buffers:
        length += len(b)
    return [_http_head(status, length,
                       extra_headers=extra_headers), *buffers]


class AioService:
    """Connection handling + routing over a shared DetectorService."""

    def __init__(self, svc: DetectorService | None = None,
                 max_batch: int = 16384, max_delay_ms: float = 5.0):
        # reuse DetectorService for metrics/codes/engine, but route
        # detection through the asyncio batcher — one batching layer
        # only. Callers should construct their service with
        # start_batcher=False; a service arriving with a live sync
        # Batcher gets it closed (and svc.detect_codes disabled), since
        # sharing one service between both fronts double-batches.
        self.svc = svc or DetectorService(max_batch=max_batch,
                                          max_delay_ms=max_delay_ms,
                                          start_batcher=False)
        if self.svc.batcher is not None:
            self.svc.batcher.close()
            self.svc.batcher = None
        self.batcher = AioBatcher(self.svc._detect, max_batch,
                                  max_delay_ms,
                                  cache_bytes=self.svc.cache_bytes)
        if self.batcher._cache is not None:
            # the sync Batcher (just closed, if any) registered its own
            # unused cache; the gauges must read the live one
            self.svc.metrics.cache_stats = self.batcher.cache_stats
            # register with the service so swap_artifact can flush this
            # front-level cache on an artifact rebind (staleness guard)
            self.svc._result_caches = list(
                getattr(self.svc, "_result_caches", ())) \
                + [self.batcher._cache]
            # same boot-epoch contract as the sync Batcher's cache: the
            # shared tier namespaces by artifact content digest from
            # the first request, so a rolling fleet can never cross
            # artifacts (server.py has the full rationale)
            cache = self.batcher._cache
            if self.svc._artifact_path:
                from .. import artifact as artifact_mod
                boot_epoch = artifact_mod.artifact_digest(
                    self.svc._artifact_path)
                if boot_epoch:
                    cache.set_epoch(boot_epoch)
            if cache._shared is not None:
                self.svc.metrics.shared_cache_stats = \
                    cache._shared.stats
        self._usage = json.dumps(USAGE).encode()
        self.recycling = False  # set by _recycle_watch; read by serve()
        self.draining = False   # set by the SIGTERM handler (swap
        # cutover / docker stop): same teardown, exit code 0
        # open client connections: the recycle path must force-close
        # idle keep-alive connections (a Prometheus scraper's persistent
        # socket would otherwise pin Server.wait_closed() forever on
        # Python 3.12.1+, which waits for every accepted connection)
        self._writers: set = set()
        # connections currently INSIDE a request (body read -> response
        # drained): the recycle watcher aborts idle sockets immediately
        # but gives these a bounded window to finish their response
        self._busy: set = set()

    async def handle(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter):
        if faults.ACTIVE is not None:
            # accept fault seam: drop the connection before any byte is
            # read (the client sees a reset, never a torn response)
            try:
                await faults.hit_async("accept")
            except faults.FaultInjected:
                with contextlib.suppress(Exception):
                    writer.close()
                return
        self._writers.add(writer)
        try:
            sock = writer.get_extra_info("socket")
            if sock is not None:
                import socket as _s
                sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
            while True:
                try:
                    head = await reader.readuntil(b"\r\n\r\n")
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                except asyncio.LimitOverrunError:
                    writer.write(_http_response(
                        431, b'{"error":"headers too large"}'))
                    break
                if len(head) > _MAX_HEADER_BYTES:
                    writer.write(_http_response(
                        431, b'{"error":"headers too large"}'))
                    break
                line, _, rest = head.partition(b"\r\n")
                parts = line.split()
                if len(parts) < 2:
                    break
                method, path = parts[0], parts[1].decode("latin-1")
                headers = {}
                for h in rest.split(b"\r\n"):
                    k, _, v = h.partition(b":")
                    if _:
                        headers[k.strip().lower()] = v.strip()
                try:
                    length = int(headers.get(b"content-length", 0) or 0)
                except ValueError:
                    length = 0
                if length > BODY_LIMIT_BYTES:
                    # oversize body: reject + close (the old
                    # truncate-and-parse answered a misleading 400).
                    # Discard the body up to a bounded cap first so a
                    # client mid-upload gets the 413 instead of EPIPE;
                    # past the cap we just close.
                    self.svc.metrics.inc(
                        "augmentation_invalid_requests_total")
                    self.svc.metrics.inc(
                        "augmentation_errors_logged_total")
                    self.svc.metrics.inc_object("unsuccessful")
                    self.svc.metrics.inc("augmentation_requests_total")
                    with contextlib.suppress(Exception):
                        remaining = min(length, 8 * BODY_LIMIT_BYTES)
                        while remaining > 0:
                            chunk = await reader.read(
                                min(remaining, 65536))
                            if not chunk:
                                break
                            remaining -= len(chunk)
                    eh: tuple = ((b"Connection", b"close"),)
                    rid413 = wire.clean_request_id(
                        headers.get(b"x-ldt-request-id"))
                    if rid413:  # the id echoes even on a rejection
                        eh += ((b"X-LDT-Request-Id",
                                rid413.encode("ascii")),)
                    writer.write(_http_response(
                        413, b'{"error":"Request body exceeds 1MB '
                             b'limit"}',
                        extra_headers=eh))
                    with contextlib.suppress(Exception):
                        await writer.drain()
                    break
                body = b""
                self._busy.add(writer)
                try:
                    if length > 0:
                        body = await reader.readexactly(length)
                    try:
                        resp = await self._route(method, path, headers,
                                                 body)
                    except (asyncio.IncompleteReadError, ConnectionError,
                            TimeoutError):
                        raise
                    except Exception:  # noqa: BLE001 - keep-alive: any
                        # engine/handler error answers a 500 instead of
                        # dropping the connection mid-stream
                        import logging
                        logging.getLogger(__name__).exception(
                            "request handler error (answering 500)")
                        self.svc.metrics.inc(
                            "augmentation_errors_logged_total")
                        resp = _http_response(
                            500, b'{"error":"internal error"}')
                    if isinstance(resp, list):
                        writer.writelines(resp)
                    else:
                        writer.write(resp)
                    await writer.drain()
                except (asyncio.IncompleteReadError, ConnectionError,
                        TimeoutError):
                    # abrupt disconnect mid-body or mid-response: drop
                    # the connection quietly (health probes and impatient
                    # clients would otherwise spam task tracebacks)
                    break
                finally:
                    self._busy.discard(writer)
        finally:
            self._busy.discard(writer)
            self._writers.discard(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - already torn down
                pass

    async def _route(self, method: bytes, path: str, headers: dict,
                     body: bytes) -> "bytes | list":
        svc = self.svc
        m = svc.metrics
        import time
        t0 = time.time()
        trace = None
        meta: dict = {"front": "aio"}
        try:
            if method == b"GET":
                if path in ("/", ""):
                    return _http_response(200, self._usage)
                if path in ("/healthz", "/readyz"):
                    hstatus, hbody = health_response(svc, path)
                    return _http_response(hstatus, hbody)
                m.inc("augmentation_invalid_requests_total")
                return _http_response(404, b'{"error":"Not found"}')
            if method != b"POST" or path not in ("/", ""):
                m.inc("augmentation_invalid_requests_total")
                return _http_response(404, b'{"error":"Not found"}')
            telemetry.REGISTRY.counter_inc("ldt_http_requests_total",
                                           lane="tcp")
            trace = telemetry.Trace()
            rid = wire.clean_request_id(
                headers.get(b"x-ldt-request-id")) \
                or wire.gen_request_id()
            trace.request_id = rid
            # request shape for the capture plane (size bucket +
            # priority flag ride the completion meta)
            meta["bytes"] = len(body)
            meta["priority"] = headers.get(b"x-ldt-priority") is not None
            eh = ((b"X-LDT-Request-Id", rid.encode("ascii")),)
            flightrec.emit_event("request_start", request_id=rid,
                                 lane="tcp")
            t = trace.t0
            ct = headers.get(b"content-type")
            pre, err = wire.parse_request(
                svc, ct.decode("latin-1") if ct is not None else None,
                body)
            if err is not None:
                meta["status"] = err[0]
                return _http_response(*err, extra_headers=eh)
            t = telemetry.observe_stage("parse", t, trace=trace)
            texts, slots, responses, status = pre
            meta["docs"] = len(texts)
            adm = svc.admission
            admit = None
            if texts:
                tenant_h = headers.get(b"x-ldt-tenant")
                admit = adm.try_admit(
                    texts,
                    priority=headers.get(b"x-ldt-priority") is not None,
                    tenant=tenant_h.decode("latin-1")
                    if tenant_h else None)
                # tenant lands on the trace before the shed branch: a
                # throttled tenant's sheds must show under ITS SLO/
                # capture identity, not "default"
                trace.tenant = admit.tenant
                if admit.shed:
                    m.inc("augmentation_errors_logged_total")
                    meta["status"] = admit.status
                    meta["shed"] = admit.reason
                    return _http_response(
                        admit.status,
                        json.dumps({"error": admit.message}).encode(),
                        extra_headers=eh + (
                            (b"Retry-After",
                             str(admit.retry_after).encode()),))
                trace.deadline = adm.deadline_from_header(
                    headers.get(b"x-ldt-deadline-ms"))
                if admit.level >= 1 and not admit.probe:
                    # pool probe vehicles keep retry rights: a lost
                    # probe batch must fail over, not 500
                    trace.no_retry = True
            try:
                if admit is not None and admit.degrade:
                    # brownout level 2: result cache + scalar engine on
                    # the flush pool (the scalar loop would otherwise
                    # block the event loop)
                    loop = asyncio.get_running_loop()
                    cache = self.batcher._cache
                    codes = await loop.run_in_executor(
                        self.batcher._pool,
                        lambda: degraded_detect(texts, svc.scalar_codes,
                                                cache=cache,
                                                trace=trace))
                else:
                    codes = await self.batcher.submit(
                        texts, trace=trace) if texts else []
            except DeadlineExceeded:
                m.inc("augmentation_errors_logged_total")
                meta["status"] = 504
                return _http_response(
                    504,
                    b'{"error":"deadline expired before dispatch"}',
                    extra_headers=eh)
            except (asyncio.TimeoutError, TimeoutError):
                # wedged flush (LDT_FLUSH_TIMEOUT_SEC): fail THIS
                # request with a 504 — the backend stalled, the request
                # was fine (the disconnect handler upstream must not eat
                # it; on 3.12 asyncio.TimeoutError IS TimeoutError)
                m.inc("augmentation_errors_logged_total")
                meta["status"] = 504
                meta["timeout"] = "flush"
                return _http_response(
                    504, b'{"error":"detection timed out"}',
                    extra_headers=eh)
            finally:
                if admit is not None:
                    adm.release(admit)
            t = telemetry.observe_stage("detect", t, trace=trace)
            status, buffers = wire.post_detect(svc, codes, slots,
                                               responses, status)
            telemetry.observe_stage("encode", t, trace=trace)
            meta["status"] = status
            return _http_response_buffers(status, buffers,
                                          extra_headers=eh)
        finally:
            m.inc("augmentation_requests_total")
            if trace is not None:
                # detect path: histogram + slow-ring via the trace
                telemetry.finish_request(trace, meta=meta)
            else:
                m.observe_request_ms((time.time() - t0) * 1e3)

    async def handle_uds(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter):
        """Unix-socket ingest lane (wire.py frame contract): length-
        prefixed JSON bodies, no HTTP parsing. An oversize frame
        answers a 413 error frame and closes — a length-prefixed
        stream cannot resync past a rejected body. Connections join
        the same _writers/_busy sets as TCP, so recycle and SIGTERM
        drains cover both lanes."""
        self._writers.add(writer)
        svc = self.svc

        async def _send_408():
            # best-effort explicit refusal before closing: a stalled
            # writer gets told why instead of a silent reset
            with contextlib.suppress(Exception):
                writer.write(wire.FRAME_RESP_HEADER.pack(
                    len(wire.TIMEOUT_BODY), 408))
                writer.write(wire.TIMEOUT_BODY)
                await writer.drain()

        try:
            while True:
                # the FIRST byte of a frame may wait forever (idle
                # keep-alive is legal); the rest of the frame must land
                # within the slow-loris budget or the connection is
                # answered with a 408 frame and closed
                try:
                    first = await reader.readexactly(1)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                tmo = knobs.get_float("LDT_FRAME_READ_TIMEOUT_SEC")

                def _tread(n):
                    if tmo:
                        return asyncio.wait_for(
                            reader.readexactly(n), tmo)
                    return reader.readexactly(n)

                try:
                    hdr = first + await _tread(
                        wire.FRAME_HEADER.size - 1)
                except asyncio.TimeoutError:
                    await _send_408()
                    break
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                (length,) = wire.FRAME_HEADER.unpack(hdr)
                tenant = None
                deadline_ms = None
                priority = False
                request_id = None
                if length & wire.FRAME_V2_FLAG:
                    length &= ~wire.FRAME_V2_FLAG
                    try:
                        ext = await _tread(wire.FRAME_EXT_HEADER.size)
                    except asyncio.TimeoutError:
                        await _send_408()
                        break
                    except (asyncio.IncompleteReadError,
                            ConnectionError):
                        break
                    flags, tlen, dl = \
                        wire.FRAME_EXT_HEADER.unpack(ext)
                    priority = bool(flags & wire.FRAME_PRIORITY)
                    if dl:
                        deadline_ms = dl
                    if tlen:
                        try:
                            tenant = (await _tread(tlen)) \
                                .decode("latin-1")
                        except asyncio.TimeoutError:
                            await _send_408()
                            break
                        except (asyncio.IncompleteReadError,
                                ConnectionError):
                            break
                    if flags & wire.FRAME_REQID:
                        try:
                            (rlen,) = await _tread(1)
                            request_id = wire.clean_request_id(
                                await _tread(rlen) if rlen else b"")
                        except asyncio.TimeoutError:
                            await _send_408()
                            break
                        except (asyncio.IncompleteReadError,
                                ConnectionError):
                            break

                def _resp_head(blen, status, rid=None):
                    # echo the client-supplied id (v1 responses stay
                    # byte-identical; see wire.send_frame)
                    if rid is None:
                        return wire.FRAME_RESP_HEADER.pack(blen, status)
                    rb = rid.encode("ascii")
                    return wire.FRAME_RESP_HEADER.pack(
                        wire.FRAME_V2_FLAG | blen, status) \
                        + bytes([len(rb)]) + rb

                if length > BODY_LIMIT_BYTES:
                    m = svc.metrics
                    m.inc("augmentation_requests_total")
                    m.inc("augmentation_invalid_requests_total")
                    m.inc_object("unsuccessful")
                    telemetry.REGISTRY.counter_inc(
                        "ldt_http_requests_total", lane="uds")
                    writer.write(_resp_head(len(wire.OVERSIZE_BODY),
                                            413, request_id))
                    writer.write(wire.OVERSIZE_BODY)
                    with contextlib.suppress(Exception):
                        await writer.drain()
                    break
                self._busy.add(writer)
                try:
                    try:
                        body = await _tread(length) if length else b""
                    except asyncio.TimeoutError:
                        await _send_408()
                        break
                    try:
                        status, buffers = await self._frame(
                            body, tenant=tenant,
                            deadline_ms=deadline_ms,
                            priority=priority, request_id=request_id)
                    except (asyncio.IncompleteReadError,
                            ConnectionError, TimeoutError):
                        raise
                    except Exception:  # noqa: BLE001 - typed 500,
                        # never a torn frame (chaos invariant)
                        import logging
                        logging.getLogger(__name__).exception(
                            "uds frame handler error (answering 500)")
                        svc.metrics.inc(
                            "augmentation_errors_logged_total")
                        status = 500
                        buffers = [b'{"error":"internal error"}']
                    blen = sum(len(b) for b in buffers)
                    writer.write(_resp_head(blen, status, request_id))
                    writer.writelines(buffers)
                    await writer.drain()
                except (asyncio.IncompleteReadError, ConnectionError,
                        TimeoutError):
                    break
                finally:
                    self._busy.discard(writer)
        finally:
            self._busy.discard(writer)
            self._writers.discard(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - already torn down
                pass

    async def _frame(self, body: bytes, tenant=None, deadline_ms=None,
                     priority=False, request_id=None) -> tuple:
        """One UDS frame body through the shared wire path ->
        (status, buffer list); the async twin of wire.handle_frame
        over the aio batcher. tenant/deadline_ms/priority come from a
        v2 frame's ext header and drive the same admission decisions
        as the HTTP headers. The concatenated buffers are identical
        to the TCP front's payload for the same batch."""
        svc = self.svc
        m = svc.metrics
        m.inc("augmentation_requests_total")
        telemetry.REGISTRY.counter_inc("ldt_http_requests_total",
                                       lane="uds")
        trace = telemetry.Trace()
        # correlate even id-less callers: the recorder/trace id is
        # server-generated then, just never echoed on the wire
        trace.request_id = request_id or wire.gen_request_id()
        flightrec.emit_event("request_start",
                             request_id=trace.request_id, lane="uds")
        t = trace.t0
        meta: dict = {"front": "uds", "bytes": len(body),
                      "priority": bool(priority)}
        try:
            pre, err = wire.parse_request(svc, "application/json",
                                          body)
            if err is not None:
                meta["status"] = err[0]
                return err[0], [err[1]]
            t = telemetry.observe_stage("parse", t, trace=trace)
            texts, slots, responses, status = pre
            meta["docs"] = len(texts)
            adm = svc.admission
            admit = None
            if texts:
                admit = adm.try_admit(texts, priority=priority,
                                      tenant=tenant)
                # tenant before the shed branch: sheds must carry the
                # throttled tenant's identity into SLO/capture
                trace.tenant = admit.tenant
                if admit.shed:
                    m.inc("augmentation_errors_logged_total")
                    meta["status"] = admit.status
                    meta["shed"] = admit.reason
                    return admit.status, [json.dumps(
                        {"error": admit.message}).encode()]
                trace.deadline = adm.deadline_from_header(deadline_ms)
                if admit.level >= 1 and not admit.probe:
                    trace.no_retry = True
            try:
                if admit is not None and admit.degrade:
                    loop = asyncio.get_running_loop()
                    cache = self.batcher._cache
                    codes = await loop.run_in_executor(
                        self.batcher._pool,
                        lambda: degraded_detect(texts,
                                                svc.scalar_codes,
                                                cache=cache,
                                                trace=trace))
                else:
                    codes = await self.batcher.submit(
                        texts, trace=trace) if texts else []
            except DeadlineExceeded:
                m.inc("augmentation_errors_logged_total")
                meta["status"] = 504
                return 504, [b'{"error":"deadline expired before '
                             b'dispatch"}']
            except (asyncio.TimeoutError, TimeoutError):
                m.inc("augmentation_errors_logged_total")
                meta["status"] = 504
                meta["timeout"] = "flush"
                return 504, [b'{"error":"detection timed out"}']
            finally:
                if admit is not None:
                    adm.release(admit)
            t = telemetry.observe_stage("detect", t, trace=trace)
            status, buffers = wire.post_detect(svc, codes, slots,
                                               responses, status)
            telemetry.observe_stage("encode", t, trace=trace)
            meta["status"] = status
            return status, buffers
        finally:
            telemetry.finish_request(trace, meta=meta)

    async def handle_metrics(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter):
        self._writers.add(writer)
        try:
            while True:
                try:
                    head = await reader.readuntil(b"\r\n\r\n")
                except (asyncio.IncompleteReadError, ConnectionError,
                        asyncio.LimitOverrunError):
                    break
                line, _, rest = head.partition(b"\r\n")
                parts = line.split()
                method = parts[0] if parts else b"GET"
                path = parts[1].decode("latin-1").split("?", 1)[0] \
                    if len(parts) >= 2 else "/metrics"
                mheaders = {}
                for h in rest.split(b"\r\n"):
                    k, sep, v = h.partition(b":")
                    if sep:
                        mheaders[k.strip().lower()] = v.strip()
                try:
                    length = int(mheaders.get(b"content-length", 0)
                                 or 0)
                except ValueError:
                    length = 0
                if length > _MAX_HEADER_BYTES:
                    writer.write(_http_response(
                        413, b'{"error":"body too large"}',
                        extra_headers=((b"Connection", b"close"),)))
                    with contextlib.suppress(Exception):
                        await writer.drain()
                    break
                try:
                    body = await reader.readexactly(length) if length \
                        else b""
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                self._busy.add(writer)
                try:
                    if method == b"POST" and path == "/swap":
                        status, sbody = await self._swap(body)
                        writer.write(_http_response(status, sbody))
                    elif method == b"POST" and path == "/profilez":
                        from .. import profiling
                        pstatus, payload = profiling.arm()
                        writer.write(_http_response(
                            pstatus, json.dumps(payload).encode()))
                    elif method == b"POST" and path == "/configz":
                        from .. import configplane
                        cstatus, payload = configplane.handle_post(
                            body)
                        writer.write(_http_response(
                            cstatus, json.dumps(payload).encode()))
                    elif path == "/configz":
                        from .. import configplane
                        body = json.dumps(configplane.handle_get(),
                                          indent=2).encode()
                        writer.write(_http_response(200, body))
                    elif path in ("/healthz", "/readyz"):
                        hstatus, hbody = health_response(self.svc, path)
                        writer.write(_http_response(hstatus, hbody))
                    elif path == "/debug/vars":
                        body = json.dumps(telemetry.debug_vars(
                            self.svc.metrics), indent=2).encode()
                        writer.write(_http_response(200, body))
                    elif path == "/sloz":
                        body = json.dumps(slo.sloz(),
                                          indent=2).encode()
                        writer.write(_http_response(200, body))
                    elif path == "/debug/slow":
                        ring = telemetry.REGISTRY.slow
                        body = json.dumps(
                            {"threshold_ms": ring.threshold_ms,
                             "capacity": ring.capacity,
                             "recorded": ring.recorded,
                             "traces": ring.snapshot()},
                            indent=2).encode()
                        writer.write(_http_response(200, body))
                    else:
                        body = self.svc.metrics.render().encode()
                        writer.write(_http_response(
                            200, body, b"text/plain; version=0.0.4"))
                    await writer.drain()
                finally:
                    self._busy.discard(writer)
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _swap(self, body: bytes) -> tuple:
        """POST /swap on the metrics port: in-process artifact hot swap
        (service/swap.py). Body {"path": ...}, falling back to
        LDT_ARTIFACT_PATH. The mmap + device-table build run on the
        default executor so the event loop keeps serving."""
        from . import swap as swap_mod
        try:
            doc = json.loads(body.decode("utf-8") or "{}")
        except (ValueError, UnicodeDecodeError):
            return 400, b'{"error":"invalid JSON body"}'
        path = (doc.get("path") if isinstance(doc, dict) else None) \
            or knobs.get_str("LDT_ARTIFACT_PATH")
        if not path:
            return 400, (b'{"error":"no artifact path: POST '
                         b'{\\"path\\":...} or set LDT_ARTIFACT_PATH"}')
        loop = asyncio.get_running_loop()
        try:
            info = await loop.run_in_executor(
                None, swap_mod.swap_artifact, self.svc, path)
        except swap_mod.SwapError as e:
            return 409, json.dumps({"error": str(e)}).encode()
        return 200, json.dumps(info).encode()


async def _recycle_watch(aio: "AioService", server, mserver,
                         userver=None):
    """Planned self-recycle for the plugin's per-dispatch host RSS leak
    (docs/PERF.md; tunneled backend only): past LDT_MAX_DISPATCHES /
    LDT_MAX_RSS_MB, stop accepting, give in-flight handlers a moment,
    and exit with RECYCLE_EXIT_CODE for the supervisor / container
    restart policy (service/recycle.py). No-op when neither env bound
    is set."""
    from .recycle import (check_interval_sec, limits_from_env,
                          should_recycle)
    max_d, max_r = limits_from_env()
    if max_d is None and max_r is None:
        return
    while True:
        await asyncio.sleep(check_interval_sec())
        stats = aio.svc.metrics.engine_stats()
        # the leak tracks DEVICE dispatches; all-C tiny flushes never
        # touch the plugin and must not burn recycle budget
        n = stats.get("device_dispatches", stats.get("batches", 0))
        reason = should_recycle(n, max_d, max_r)
        if reason:
            print(json.dumps({"msg": f"recycling worker: {reason}"}),
                  flush=True)
            # flag + close; serve() swallows the resulting cancellation
            # and returns the recycle indicator so main() exits with the
            # code (exiting from THIS task would race the loop teardown
            # cancelling it first). The connection teardown happens
            # HERE: serve()'s `async with` exit awaits wait_closed()
            # DURING exception propagation — before any except clause —
            # and on 3.12.1+ that waits for every accepted connection,
            # so an idle keep-alive socket would pin the recycle forever
            # unless aborted by the drain's final sweep. Idle sockets
            # are spared through the settle window (a connection
            # accepted just before the listener closed may not have
            # surfaced in the busy set yet — aborting it would reset a
            # request already on the wire), in-flight requests get a
            # bounded window to finish writing their response, then any
            # stragglers abort.
            aio.recycling = True
            await _teardown(aio, server, mserver, spare_idle=True,
                            userver=userver)
            return


def _abort(w):
    try:
        w.transport.abort()
    except Exception:  # noqa: BLE001 - already gone
        pass


async def _teardown(aio: "AioService", server, mserver,
                    spare_idle: bool = False, userver=None):
    """Shared drain for recycle and SIGTERM (swap cutover): stop
    accepting, give in-flight requests a bounded window, then abort
    whatever is left so wait_closed() cannot hang on a survivor.
    spare_idle: leave idle keep-alive sockets alone until the busy set
    settles — a connection accepted just before the listener closed may
    still be delivering its request (not yet in the busy set), and
    neither a cutover nor a recycle handoff may reset it. The final
    sweep still aborts true idlers, so wait_closed() never hangs."""
    server.close()
    mserver.close()
    if userver is not None:
        userver.close()
    if not spare_idle:
        for w in list(aio._writers):
            if w not in aio._busy:
                _abort(w)
    loop = asyncio.get_running_loop()
    deadline = loop.time() + _drain_sec()
    while aio._busy and loop.time() < deadline:
        await asyncio.sleep(0.05)
    if spare_idle:
        # settle window: requests racing the listener close surface in
        # _busy a beat after the accept; drain those too
        settle = loop.time() + 0.25
        while loop.time() < min(settle, deadline):
            await asyncio.sleep(0.05)
            while aio._busy and loop.time() < deadline:
                await asyncio.sleep(0.05)
    # stragglers past the bound + connections that went idle
    # (and may have picked up a new request) since the sweep
    for w in list(aio._writers):
        _abort(w)


async def serve(port: int = 3000, metrics_port: int = 30000,
                svc: DetectorService | None = None,
                ready: "asyncio.Future | None" = None):
    flightrec.init_from_env(role="aio-front")
    capture.init_from_env()
    slo.init_from_env()
    from .. import profiling
    profiling.install_sigusr2()
    aio = AioService(svc)
    aio.batcher.start()
    # the stream limit must exceed the body contract limit: readexactly
    # waits for the full body in the buffer, and the transport pauses at
    # 2x limit — a smaller limit would deadlock large (legal) bodies.
    # Bind IPv4 explicitly: host "" dual-stack-binds v4 AND v6, and with
    # port=0 each family gets a DIFFERENT ephemeral port (sockets[0]'s
    # family is unordered — callers would connect to the wrong one).
    # SO_REUSEPORT (LDT_REUSEPORT): an old and a standby generation
    # overlap on the same port during a blue/green swap drill
    kw = {"reuse_port": True} if knobs.get_bool("LDT_REUSEPORT") else {}
    server = await asyncio.start_server(aio.handle, "0.0.0.0", port,
                                        limit=BODY_LIMIT_BYTES + 65536,
                                        **kw)
    mserver = await asyncio.start_server(aio.handle_metrics, "0.0.0.0",
                                         metrics_port, **kw)
    # co-located callers can skip HTTP entirely: length-prefixed frames
    # over a unix socket, same batch contract, byte-identical responses
    userver = None
    uds_path = knobs.get_str("LDT_UNIX_SOCKET")
    if uds_path:
        with contextlib.suppress(OSError):
            os.unlink(uds_path)
        userver = await asyncio.start_unix_server(
            aio.handle_uds, path=uds_path,
            limit=BODY_LIMIT_BYTES + 65536)
        print(json.dumps({"msg": f"unix-socket lane on {uds_path}"}),
              flush=True)
    ports = (server.sockets[0].getsockname()[1],
             mserver.sockets[0].getsockname()[1])
    print(json.dumps({"msg": f"language-detector (asyncio) listening on "
                             f":{ports[0]}, metrics on :{ports[1]}"}),
          flush=True)
    if ready is not None and not ready.done():
        ready.set_result(ports)
    loop = asyncio.get_running_loop()
    # shared-memory ring lane (service/shmring.py): the scan thread is
    # synchronous, so its detect bridges onto this loop's batcher
    shm = None
    shm_dir = knobs.get_str("LDT_SHM_DIR")
    if shm_dir:
        from . import shmring

        def _shm_detect(texts, trace=None):
            fut = asyncio.run_coroutine_threadsafe(
                aio.batcher.submit(texts, trace=trace), loop)
            return fut.result(
                (knobs.get_float("LDT_FLUSH_TIMEOUT_SEC") or 60.0) + 5.0)

        shm = shmring.ShmRingServer(aio.svc, shm_dir,
                                    detect=_shm_detect)
        shm.start()
        print(json.dumps({"msg": f"shm ring lane on {shm_dir}"}),
              flush=True)
    # warmup (LDT_WARMUP) + readiness handshake (LDT_READY_FILE /
    # LDT_SWAPPED) off the loop: the standby contract with the
    # supervisor's swap drill
    from .swap import startup_ready_task
    loop.run_in_executor(None, startup_ready_task, aio.svc, ports)

    def _on_term():
        # graceful drain (the supervisor's swap cutover, docker stop):
        # stop accepting, flush in-flight, then exit 0
        if aio.recycling or aio.draining:
            return
        aio.draining = True
        print(json.dumps({"msg": "draining worker: SIGTERM"}),
              flush=True)
        loop.create_task(_teardown(aio, server, mserver,
                                   spare_idle=True, userver=userver))

    try:
        import signal as _signal
        loop.add_signal_handler(_signal.SIGTERM, _on_term)
    except (ValueError, RuntimeError, NotImplementedError):
        pass  # embedded in a non-main thread (tests) or no signals
    watch = loop.create_task(_recycle_watch(aio, server, mserver,
                                            userver=userver))
    try:
        async with server, mserver:
            aws = [server.serve_forever(), mserver.serve_forever()]
            if userver is not None:
                aws.append(userver.serve_forever())
            await asyncio.gather(*aws)
    except asyncio.CancelledError:
        if not (aio.recycling or aio.draining):
            raise  # external cancellation (tests, embedding callers)
    finally:
        flightrec.emit_event("proc_exit", role="aio-front")
        watch.cancel()
        if shm is not None:
            # stop the scan thread before the loop dies: a leased frame
            # mid-bridge would otherwise wait on a dead loop
            await asyncio.to_thread(shm.close, 1.0)
        if userver is not None:
            userver.close()
            if uds_path:
                with contextlib.suppress(OSError):
                    os.unlink(uds_path)
        with contextlib.suppress(ValueError, RuntimeError,
                                 NotImplementedError):
            import signal as _signal
            loop.remove_signal_handler(_signal.SIGTERM)
    if aio.recycling:
        return "recycle"
    return "drain" if aio.draining else None


def main():
    import sys

    from .recycle import RECYCLE_EXIT_CODE
    port = knobs.get_int("LISTEN_PORT") or 0
    metrics_port = knobs.get_int("PROMETHEUS_PORT") or 0
    try:
        result = asyncio.run(serve(port, metrics_port))
    except KeyboardInterrupt:
        return
    if result == "recycle":
        sys.exit(RECYCLE_EXIT_CODE)


if __name__ == "__main__":
    main()
