"""jit-contract analyzer: donation and recompilation contracts on the
device path, extending trace_safety's entry discovery.

Two rules over the same device-path files trace_safety scans:

  jit-donated-read       a buffer passed at a ``donate_argnums``
                         position of a jitted callable is DEAD after
                         the call — XLA may have aliased its memory
                         into the outputs — so any later read of that
                         name in the same function is a
                         use-after-donate. One legal exception, the
                         engine's staging-ring pattern
                         (models/ngram.py): when the donating call's
                         result future is bound to a name, resolving
                         that future (``np.asarray(fut)`` or
                         ``fut.block_until_ready()``) settles the
                         dispatch — every host byte was copied to the
                         device during the call — so reads AFTER the
                         resolution are ring-slot reuse and clean;
                         reads between launch and resolution still
                         flag.
  jit-recompile-capture  a jitted entry that reads a per-call-varying
                         Python value from an enclosing scope bakes it
                         in as a trace-time constant: every new value
                         is a silent retrace + recompile (the XLA
                         cache-churn class). Flagged captures are
                         enclosing-function locals that are reassigned
                         or loop-assigned (single-assignment factory
                         state is a legitimate per-instance constant),
                         and module globals mutated via ``global``.

Module constants, imports, parameters, and the entry's own locals are
never flagged — exactly the names ops/score.py's entries rely on.
"""
from __future__ import annotations

import ast
import builtins
from pathlib import Path

from .base import Violation, apply_suppressions, load_source, repo_root
from .trace_safety import SCAN_FILES, _collect_entries_and_jitted

_BUILTINS = frozenset(dir(builtins))


# -- jit-donated-read --------------------------------------------------------


def _donated_positions(call: ast.Call):
    """Donated input positions of a jit(...) call (donate_argnums) or
    a pl.pallas_call(...) (input_output_aliases keys — an aliased
    input's buffer becomes an output and is equally dead at the call
    site), or None."""
    fname = call.func.attr if isinstance(call.func, ast.Attribute) \
        else getattr(call.func, "id", None)
    if fname == "pallas_call":
        for kw in call.keywords:
            if kw.arg != "input_output_aliases":
                continue
            v = kw.value
            if isinstance(v, ast.Dict):
                out = set()
                for k in v.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, int):
                        out.add(k.value)
                return out
        return None
    if fname not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)):
            out = set()
            for e in v.elts:
                if isinstance(e, ast.Constant) \
                        and isinstance(e.value, int):
                    out.add(e.value)
            return out
    return None


def _donating_bindings(sources) -> dict:
    """name -> donated positional indices, for every
    `X = jax.jit(f, donate_argnums=...)` binding in the scan set."""
    donating: dict = {}
    for sf in sources:
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            pos = _donated_positions(node.value)
            if not pos:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    donating[tgt.id] = pos
    return donating


def _resolved_future(node) -> str | None:
    """The future Name this expression resolves, or None: matches
    ``np.asarray(fut)`` / ``asarray(fut)`` / ``fut.block_until_ready()``
    — the fetch forms every engine dispatch site uses."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "block_until_ready" and isinstance(f.value,
                                                        ast.Name):
            return f.value.id
        if f.attr == "asarray" and node.args \
                and isinstance(node.args[0], ast.Name):
            return node.args[0].id
    elif isinstance(f, ast.Name) and f.id == "asarray" and node.args \
            and isinstance(node.args[0], ast.Name):
        return node.args[0].id
    return None


def _check_donated_reads(sf, donating: dict, out: list):
    """Within each function: once a Name is passed at a donated
    position of a donating callable, any later Load of it is flagged.
    A Store rebinds the name to a live value and clears it. When the
    donating call's result is bound (`fut = score(dt, wire)`),
    resolving that future (`np.asarray(fut)`,
    `fut.block_until_ready()`) clears the call's donated names — the
    staging-ring reuse pattern — while reads before the resolution
    still flag."""

    def scan_stmt(stmt, donated, futures):
        """One simple statement, in evaluation order: resolutions of a
        bound result future settle their donated names first (so
        `rows = unpack(np.asarray(fut), wire)` is the legal
        fetch-then-read shape); reads of a still-donated name then
        flag; the statement's own donating calls register (binding
        their result future when assigned); its stores then rebind (so
        `acc = step(acc, xs)` donates the old `acc` AND leaves the
        name alive on the result)."""
        just_bound: set = set()
        for node in ast.walk(stmt):
            fname = _resolved_future(node)
            if fname is not None and fname in futures:
                for n in futures.pop(fname):
                    donated.pop(n, None)
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in donated:
                out.append(Violation(
                    "jit-donated-read", sf.rel, node.lineno,
                    f"`{node.id}` was donated to a jitted call on "
                    f"line {donated[node.id]} "
                    f"(donate_argnums); its buffer may be aliased "
                    f"into the outputs — rebind before reuse, or "
                    f"resolve the call's result future first "
                    f"(staging-ring reuse)"))
                donated.pop(node.id)
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in donating:
                names = [a.id for i, a in enumerate(node.args)
                         if i in donating[node.func.id]
                         and isinstance(a, ast.Name)]
                for n in names:
                    donated[n] = node.lineno
                if names and isinstance(stmt, ast.Assign) \
                        and stmt.value is node:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            futures[tgt.id] = set(names)
                            just_bound.add(tgt.id)
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Store):
                donated.pop(node.id, None)
                # rebinding a future name abandons the old future: its
                # donated names can never resolve and stay flagged
                if node.id not in just_bound:
                    futures.pop(node.id, None)

    def scan_scope(body):
        donated: dict = {}  # name -> line it was donated on
        futures: dict = {}  # future name -> names donated by its call

        def walk(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue  # separate scope
                subs = [getattr(stmt, "body", None),
                        getattr(stmt, "orelse", None),
                        getattr(stmt, "finalbody", None)] + \
                    [h.body for h in getattr(stmt, "handlers", ())]
                subs = [s for s in subs if s]
                if subs:
                    # compound: headers (test/iter/items) read first,
                    # then the branch bodies share one
                    # flow-insensitive donation map
                    for hdr in ("test", "iter"):
                        h = getattr(stmt, hdr, None)
                        if h is not None:
                            scan_stmt(h, donated, futures)
                    for item in getattr(stmt, "items", ()):
                        scan_stmt(item.context_expr, donated, futures)
                    tgt = getattr(stmt, "target", None)
                    if tgt is not None:
                        scan_stmt(tgt, donated, futures)
                    for sub in subs:
                        walk(sub)
                else:
                    scan_stmt(stmt, donated, futures)

        walk(body)

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_scope(node.body)
    scan_scope([s for s in sf.tree.body
                if not isinstance(s, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef))])


# -- jit-recompile-capture ---------------------------------------------------


def _assigned_names(fn) -> set:
    """Parameters plus every Name the function stores (its locals)."""
    args = fn.args
    names = {a.arg for a in args.posonlyargs + args.args
             + args.kwonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                     ast.Store):
            names.add(node.id)
    return names


def _varying_locals(fn) -> set:
    """Enclosing-scope names whose value plausibly changes between
    calls of a nested jitted entry: reassigned more than once, or
    assigned under a loop (single-assignment factory state is a
    per-instance constant and fine to capture)."""
    counts: dict = {}
    in_loop: set = set()

    def visit(node, loop_depth):
        if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                     ast.Store):
            counts[node.id] = counts.get(node.id, 0) + 1
            if loop_depth:
                in_loop.add(node.id)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested scopes own their stores
            d = loop_depth + (1 if isinstance(
                node, (ast.While, ast.For, ast.AsyncFor)) else 0)
            visit(child, d)

    visit(fn, 0)
    # loop targets themselves vary by construction
    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    in_loop.add(t.id)
    return {n for n, c in counts.items() if c > 1} | in_loop


def _module_facts(sf):
    """(module-scope names, names mutated via `global` anywhere)."""
    mod_names: set = set()
    for node in sf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            mod_names.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                mod_names.add((a.asname or a.name).split(".")[0])
        else:
            for n in ast.walk(node):
                if isinstance(n, ast.Name) and isinstance(n.ctx,
                                                          ast.Store):
                    mod_names.add(n.id)
    mutated: set = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Global):
            mutated.update(node.names)
    return mod_names, mutated


def _entry_defs_with_enclosers(sf, entries: set):
    """Yield (entry def node, [enclosing function defs, outer-first])."""

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                if child.name in entries:
                    yield child, list(stack)
                yield from walk(child, stack + [child])
            else:
                yield from walk(child, stack)

    yield from walk(sf.tree, [])


def _check_recompile_capture(sf, entries: set, out: list):
    mod_names, mutated_globals = _module_facts(sf)
    for fn, enclosers in _entry_defs_with_enclosers(sf, entries):
        own = _assigned_names(fn)
        enclosing_local: dict = {}  # name -> defining fn (innermost)
        varying: set = set()
        for enc in enclosers:
            v = _varying_locals(enc)
            for n in _assigned_names(enc):
                enclosing_local[n] = enc
                if n in v:
                    varying.add(n)
                else:
                    varying.discard(n)
        nonlocal_names: set = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Nonlocal):
                nonlocal_names.update(n.names)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)):
                continue
            name = node.id
            if name in own or name in _BUILTINS:
                continue
            if name in enclosing_local:
                if name in varying or name in nonlocal_names:
                    out.append(Violation(
                        "jit-recompile-capture", sf.rel, node.lineno,
                        f"jitted entry `{fn.name}` closes over "
                        f"`{name}`, a per-call-varying value of "
                        f"enclosing `{enclosing_local[name].name}`: "
                        f"each new value is a silent retrace; pass it "
                        f"as an argument instead"))
            elif name in mutated_globals and name in mod_names:
                out.append(Violation(
                    "jit-recompile-capture", sf.rel, node.lineno,
                    f"jitted entry `{fn.name}` reads module global "
                    f"`{name}` which is mutated via `global`: the "
                    f"trace bakes in one value; pass it as an "
                    f"argument instead"))


def check(root: Path | None = None, files=None):
    """Run the analyzer. Returns (violations, n_suppressed)."""
    root = root or repo_root()
    rels = SCAN_FILES if files is None else files
    sources = [load_source(root / rel, root) for rel in rels
               if (root / rel).exists()]
    entries, _ = _collect_entries_and_jitted(sources)
    donating = _donating_bindings(sources)

    violations: list = []
    n_suppressed = 0
    for sf in sources:
        raw: list = []
        _check_donated_reads(sf, donating, raw)
        _check_recompile_capture(sf, entries, raw)
        kept, ns = apply_suppressions(sf, raw)
        violations.extend(kept)
        n_suppressed += ns
    return violations, n_suppressed
