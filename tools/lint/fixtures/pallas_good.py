"""Clean twin of pallas_bad.py: the idioms a Pallas kernel body and
its launch site are allowed — shape reads, static range loops,
jnp.where for data-dependent selection, and ring-slot reuse only
AFTER the aliased call's result future resolves."""
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _score_kernel(wire_ref, lg3_ref, out_ref):
    v = wire_ref[...]
    rows = v.astype(jnp.int32)
    for plane in range(3):      # static iteration count: legal
        rows = rows + lg3_ref[...][:, plane]
    if v.shape[0] > 1:          # shape read: trace-static, legal
        rows = rows * 2
    out_ref[...] = jnp.where(v > 0, rows, 0)


score_fused = pl.pallas_call(_score_kernel, out_shape=None,
                             input_output_aliases={0: 0})


def fetch_then_reuse(wire, ring):
    fut = score_fused(wire)
    rows = np.asarray(fut)      # resolution settles the dispatch
    meta = wire.sum()           # legal: ring-slot reuse after resolve
    ring.release(wire)
    return rows, meta
