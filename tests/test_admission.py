"""Admission control & graceful degradation (service/admission.py).

Unit coverage for the four pieces — bounded queues with cost
accounting, deadline propagation, the brownout ladder's hysteresis,
and the circuit breaker state machine — plus HTTP-level proof on BOTH
fronts (sync threaded server and the asyncio server) that shed
responses carry 429/503 + Retry-After, expired deadlines answer 504,
priority traffic survives shed-all, and the new Prometheus series
scrape. The controllers under test are injected with tiny bounds;
the default (no LDT_* overrides) configuration is asserted to change
nothing.
"""
from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from language_detector_tpu import telemetry
from language_detector_tpu.service.admission import (
    DEFAULT_TENANT, AdmissionConfig, AdmissionController, BrownoutLadder,
    CircuitBreaker, Deadline, DeadlineExceeded, FairScheduler,
    parse_tenant_weights, request_cost, retry_after_sec)
from language_detector_tpu.service.batcher import Batcher
from language_detector_tpu.service.server import (DetectorService,
                                                  make_server)

EN = ("this is a simple english sentence with common words that "
      "should be detected without any trouble at all")
FR = ("Le gouvernement a annoncé de nouvelles mesures pour aider "
      "les familles concernées")


# -- cost accounting ---------------------------------------------------------


def test_request_cost_monotone_in_bytes():
    small = request_cost(["ab"])
    big = request_cost(["ab" * 500])
    assert 0 < small < big
    # additive across documents
    assert request_cost(["ab", "cd"]) == \
        request_cost(["ab"]) + request_cost(["cd"])


def test_retry_after_bounds():
    assert 1 <= retry_after_sec(0) <= 30
    assert retry_after_sec(10_000_000) == 30  # clamped at the cap


# -- bounded queues ----------------------------------------------------------


def test_queue_docs_bound_sheds_and_release_recovers():
    ctrl = AdmissionController(AdmissionConfig(max_queue_docs=2))
    a = ctrl.try_admit([EN, FR])
    assert not a.shed and ctrl.queue_docs == 2 and ctrl.inflight == 1
    b = ctrl.try_admit([EN])
    assert b.shed and b.status == 429 and b.reason == "queue_docs"
    assert 1 <= b.retry_after <= 30
    ctrl.release(a)
    assert ctrl.queue_docs == 0 and ctrl.inflight == 0
    c = ctrl.try_admit([EN])
    assert not c.shed
    ctrl.release(c)


def test_queue_bytes_and_inflight_bounds():
    # bound just under one request's cost: occupancy stays ~1.0 so the
    # brownout ladder (which sheds first) can't race ahead of the bound
    ctrl = AdmissionController(
        AdmissionConfig(max_queue_bytes=request_cost([EN]) - 1))
    a = ctrl.try_admit([EN])
    assert a.shed and a.status == 429 and a.reason == "queue_bytes"
    ctrl = AdmissionController(AdmissionConfig(max_inflight=1))
    a = ctrl.try_admit([EN])
    b = ctrl.try_admit([FR])
    assert not a.shed and b.shed and b.reason == "inflight"
    ctrl.release(a)
    assert not ctrl.try_admit([FR]).shed


def test_shed_counters_exported():
    ctrl = AdmissionController(AdmissionConfig(max_queue_docs=1))
    ctrl.try_admit([EN, FR])  # 2 docs > 1: shed
    s = ctrl.stats()
    assert s["shed"]["queue_docs"] >= 1
    assert s["limits"]["max_queue_docs"] == 1


def test_default_config_admits_everything():
    """No LDT_* overrides: every bound off, ladder stays healthy, no
    degradation — the subsystem must be a no-op by default."""
    ctrl = AdmissionController(AdmissionConfig())
    a = ctrl.try_admit([EN] * 10_000)
    assert not a.shed and a.level == 0 and not a.degrade
    ctrl.release(a)
    assert ctrl.deadline_from_header(None) is None


# -- per-tenant isolation ----------------------------------------------------


def test_tenant_quota_docs_sheds_only_that_tenant():
    ctrl = AdmissionController(AdmissionConfig(tenant_quota_docs=2))
    a = ctrl.try_admit([EN, FR], tenant="hot")
    assert not a.shed and a.tenant == "hot"
    b = ctrl.try_admit([EN], tenant="hot")
    assert b.shed and b.status == 429 and b.reason == "tenant_docs"
    assert 1 <= b.retry_after <= 30
    # a different tenant (and the default one) is untouched
    c = ctrl.try_admit([EN], tenant="cold")
    d = ctrl.try_admit([EN])
    assert not c.shed and not d.shed
    assert d.tenant == DEFAULT_TENANT
    # release frees the hot tenant's quota and drops its entry
    ctrl.release(a)
    assert "hot" not in ctrl.tenants
    assert not ctrl.try_admit([EN], tenant="hot").shed
    ctrl.release(c)
    ctrl.release(d)


def test_tenant_quota_bytes_sheds():
    ctrl = AdmissionController(
        AdmissionConfig(tenant_quota_bytes=request_cost([EN]) - 1))
    a = ctrl.try_admit([EN], tenant="hot")
    assert a.shed and a.reason == "tenant_bytes" and a.status == 429
    assert not ctrl.try_admit(["hi"], tenant="hot").shed


def test_tenant_stats_and_shed_counter():
    ctrl = AdmissionController(AdmissionConfig(tenant_quota_docs=1))
    a = ctrl.try_admit([EN], tenant="t1")
    ctrl.try_admit([EN], tenant="t1")  # shed
    s = ctrl.stats()
    assert s["shed"]["tenant_docs"] >= 1
    assert s["tenants"]["t1"]["queue_docs"] == 1
    assert s["limits"]["tenant_quota_docs"] == 1
    assert telemetry.REGISTRY.counter_value(
        "ldt_tenant_shed_total", tenant="t1", reason="tenant_docs") >= 1
    ctrl.release(a)
    assert ctrl.stats()["tenants"] == {}


def test_parse_tenant_weights():
    assert parse_tenant_weights("a=4, b=1.5,c") == \
        {"a": 4.0, "b": 1.5, "c": 1.0}
    # malformed / non-positive entries drop; blank spec means disabled
    assert parse_tenant_weights("a=-1,=3,b=x") == {}
    assert parse_tenant_weights(None) == {}
    assert parse_tenant_weights("") == {}


def _wfq_item(tenant, nbytes=40):
    class _T:
        pass
    t = _T()
    t.tenant = tenant
    from concurrent.futures import Future
    return (["x" * nbytes], None, t, Future())


def test_fair_scheduler_weighted_interleave():
    sched = FairScheduler({"a": 4, "b": 1}, quantum=64)
    for _ in range(10):
        sched.push(_wfq_item("a"))
        sched.push(_wfq_item("b"))
    assert sched.backlog == 20
    drained = []
    while sched.backlog:
        drained.append([FairScheduler._tenant(i)
                        for i in sched.pop_batch(4)])
    flat = [t for row in drained for t in row]
    assert sorted(flat) == ["a"] * 10 + ["b"] * 10  # nothing lost
    # the weighted tenant drains ~4x faster up front
    head = [t for row in drained[:3] for t in row]
    assert head.count("a") > head.count("b")


def test_fair_scheduler_always_makes_progress():
    # one item costing far more than a quantum must still pop (the
    # ring visit re-credits until the head fits; out-empty pops force
    # progress) — a fat document cannot wedge the collector
    sched = FairScheduler({}, quantum=8)
    sched.push(_wfq_item("big", nbytes=10_000))
    batch = sched.pop_batch(4)
    assert len(batch) == 1 and sched.backlog == 0


def test_fair_scheduler_drain_all():
    sched = FairScheduler({}, quantum=64)
    for t in ("a", "b", "a"):
        sched.push(_wfq_item(t))
    items = sched.drain_all()
    assert len(items) == 3 and sched.backlog == 0
    assert sched.pop_batch(4) == []


# -- deadlines ---------------------------------------------------------------


def test_deadline_parse_and_expiry():
    ctrl = AdmissionController(AdmissionConfig())
    assert ctrl.deadline_from_header(None) is None
    dl = ctrl.deadline_from_header("5000")
    assert dl is not None and not dl.expired() \
        and 0 < dl.remaining_ms() <= 5000
    assert ctrl.deadline_from_header(b"5000") is not None  # aio bytes
    assert ctrl.deadline_from_header("garbage") is None
    ctrl = AdmissionController(
        AdmissionConfig(default_deadline_ms=1000.0))
    assert ctrl.deadline_from_header(None) is not None      # default
    assert ctrl.deadline_from_header("garbage") is not None  # fallback
    assert Deadline(0).expired()
    assert Deadline(-5).expired()


def test_batcher_drops_expired_at_dequeue():
    """An expired request fails with DeadlineExceeded at flush time
    without burning detect work; a live neighbor in the same batch is
    still served."""
    seen = []

    def detect(texts):
        seen.extend(texts)
        return ["en"] * len(texts)

    before = telemetry.REGISTRY.counter_value(
        "ldt_deadline_expired_total")
    b = Batcher(detect, max_delay_ms=30.0)
    try:
        tr_dead = telemetry.Trace()
        tr_dead.deadline = Deadline(0)  # already expired
        f_dead = b.submit(["expired doc"], trace=tr_dead)
        f_live = b.submit(["live doc"])
        assert f_live.result(timeout=10) == ["en"]
        with pytest.raises(DeadlineExceeded):
            f_dead.result(timeout=10)
        assert "expired doc" not in seen and "live doc" in seen
        assert telemetry.REGISTRY.counter_value(
            "ldt_deadline_expired_total") >= before + 1
    finally:
        b.close()


def test_batcher_close_fails_queued_and_new_submits():
    started = threading.Event()
    release = threading.Event()

    def slow_detect(texts):
        started.set()
        release.wait(timeout=10)
        return ["en"] * len(texts)

    b = Batcher(slow_detect, max_delay_ms=1.0)
    f1 = b.submit([EN])
    started.wait(timeout=10)
    release.set()
    b.close()
    assert f1.result(timeout=10) == ["en"]
    # post-close submits fail fast instead of hanging to a timeout
    f2 = b.submit([FR])
    with pytest.raises(RuntimeError, match="batcher closed"):
        f2.result(timeout=10)


def test_engine_near_deadline_sets_no_retry():
    """A trace whose remaining budget is under ~2 expected flushes makes
    the engine scheduler skip the pipelined retry lane (trace.no_retry),
    resolving any gate retries through the scalar oracle instead —
    results stay exact either way."""
    from language_detector_tpu import native
    if not native.available():
        pytest.skip("native packer unavailable")
    from language_detector_tpu.models.ngram import NgramBatchEngine
    eng = NgramBatchEngine()
    # >TINY_BATCH_C_PATH docs: the all-C shortcut (which has no retry
    # lane to skip) must not swallow the batch
    texts = [EN, FR,
             "こんにちは世界、今日はとても良い天気ですね"] * 24
    want = ["en", "fr", "ja"] * 24

    tr = telemetry.Trace()
    tr.deadline = Deadline(30_000)  # generous: retry lane stays on
    assert eng.detect_codes(texts, trace=tr) == want
    assert tr.no_retry is False

    tr = telemetry.Trace()
    tr.deadline = Deadline(1)  # ~expired: way under 2 expected flushes
    assert eng.detect_codes(texts, trace=tr) == want
    assert tr.no_retry is True
    assert "retry_skipped_docs" in eng.stats


# -- brownout ladder ---------------------------------------------------------


def test_brownout_ladder_hysteresis():
    lad = BrownoutLadder(enter=(0.5, 0.7, 0.9), exit=(0.3, 0.5, 0.7),
                         alpha=1.0)  # alpha 1: ema == last sample
    assert lad.observe(0.4) == 0
    assert lad.observe(0.55) == 1      # crossed enter[0]
    assert lad.observe(0.45) == 1      # between exit[0] and enter[1]: hold
    assert lad.observe(0.95) == 3      # multi-step ascend
    assert lad.observe(0.75) == 3      # above exit[2]: hold shed-all
    assert lad.observe(0.65) == 2      # below exit[2]: one step down
    assert lad.observe(0.2) == 0       # full recovery


def test_brownout_ladder_ema_smoothing():
    lad = BrownoutLadder(enter=(0.5, 0.7, 0.9), exit=(0.3, 0.5, 0.7),
                         alpha=0.3)
    assert lad.observe(1.0) == 0       # single spike: ema only 0.3
    assert lad.observe(1.0) == 1       # persistent load climbs
    for _ in range(20):
        lad.observe(1.0)
    assert lad.level == 3


def test_brownout_ladder_validates_thresholds():
    with pytest.raises(ValueError):
        BrownoutLadder(enter=(0.5, 0.7, 0.9), exit=(0.5, 0.5, 0.7))
    with pytest.raises(ValueError):
        BrownoutLadder(enter=(0.5, 0.7), exit=(0.3, 0.5))


# -- circuit breaker ---------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_breaker_trip_halfopen_recover():
    clk = FakeClock()
    br = CircuitBreaker(failures=2, cooldown_sec=10.0, clock=clk)
    assert br.allow_device()
    br.record_failure()
    assert br.state == 0 and br.allow_device()  # below threshold
    br.record_failure()
    assert br.state == 2 and not br.allow_device()  # tripped open
    clk.t += 5.0
    assert not br.allow_device()                # cooldown not elapsed
    clk.t += 6.0
    assert br.allow_device()                    # half-open probe admitted
    assert br.state == 1
    assert not br.allow_device()                # only ONE probe at a time
    br.record_success(elapsed_ms=50.0)
    assert br.state == 0 and br.allow_device()  # recovered
    assert br.stats()["trips"] == 1 and br.stats()["probes"] == 1


def test_breaker_halfopen_failure_reopens():
    clk = FakeClock()
    br = CircuitBreaker(failures=1, cooldown_sec=10.0, clock=clk)
    br.record_failure()
    assert br.state == 2
    clk.t += 11.0
    assert br.allow_device()          # probe
    br.record_failure()               # probe failed
    assert br.state == 2 and not br.allow_device()
    assert br.stats()["trips"] == 2


def test_breaker_stalled_success_counts_as_failure():
    clk = FakeClock()
    br = CircuitBreaker(failures=1, cooldown_sec=10.0,
                        stall_min_ms=2000.0, clock=clk)
    br.record_success(elapsed_ms=br.stall_ms() + 1.0)
    assert br.state == 2              # a 30x-slow "success" is an outage
    assert br.stats()["stalls_total"] == 1


def test_breaker_routes_detect_to_scalar():
    """The server seam, against an injected failing detect_fn: trips
    open after N failures, serves scalar meanwhile, recovers through a
    half-open probe once the device heals."""
    clk = FakeClock()
    br = CircuitBreaker(failures=2, cooldown_sec=10.0, clock=clk)
    device_ok = {"v": False}
    calls = {"device": 0, "scalar": 0}

    def device_fn(texts):
        calls["device"] += 1
        if not device_ok["v"]:
            raise RuntimeError("device wedged")
        return ["dev"] * len(texts)

    def scalar_fn(texts):
        calls["scalar"] += 1
        return ["sca"] * len(texts)

    def detect(texts):  # mirrors DetectorService._make_detect wiring
        if not br.allow_device():
            return scalar_fn(texts)
        try:
            out = device_fn(texts)
        except Exception:
            br.record_failure()
            return scalar_fn(texts)
        br.record_success(1.0)
        return out

    assert detect(["x"]) == ["sca"]   # failure 1, answered via scalar
    assert detect(["x"]) == ["sca"]   # failure 2: trips open
    assert br.state == 2
    assert detect(["x"]) == ["sca"]   # open: no device call at all
    assert calls["device"] == 2
    device_ok["v"] = True
    clk.t += 11.0
    assert detect(["x"]) == ["dev"]   # half-open probe succeeds
    assert br.state == 0
    assert detect(["x"]) == ["dev"]   # closed again


# -- HTTP fronts -------------------------------------------------------------


@pytest.fixture(scope="module")
def front():
    """Sync threaded server with an injected all-off controller whose
    knobs the tests below flip per scenario (and restore)."""
    ctrl = AdmissionController(AdmissionConfig())
    svc = DetectorService(use_device=False, max_delay_ms=1.0,
                          admission=ctrl)
    httpd, metricsd, svc = make_server(0, 0, service=svc)
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in (httpd, metricsd)]
    for t in threads:
        t.start()
    yield {"url": f"http://127.0.0.1:{httpd.server_address[1]}",
           "metrics_url":
               f"http://127.0.0.1:{metricsd.server_address[1]}",
           "svc": svc, "ctrl": ctrl}
    httpd.shutdown()
    metricsd.shutdown()
    svc.batcher.close()


@pytest.fixture()
def adm(front):
    """Yields the live controller; restores bounds/ladder after each
    test so scenarios stay independent."""
    ctrl = front["ctrl"]
    yield ctrl
    c = ctrl.config
    c.max_queue_docs = c.max_queue_bytes = c.max_inflight = None
    c.tenant_quota_docs = c.tenant_quota_bytes = None
    c.default_deadline_ms = None
    ctrl.ladder.alpha = c.brownout_alpha
    ctrl.ladder.ema = 0.0
    ctrl.ladder.level = 0


def _post(url, payload, headers=None, raw=None):
    data = raw if raw is not None else json.dumps(payload).encode()
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    req = urllib.request.Request(url, data=data, method="POST",
                                 headers=h)
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, dict(e.headers), \
            json.loads(body) if body else None


def _pin_ladder(ctrl, level, ema):
    ctrl.ladder.alpha = 0.0  # observe() keeps ema (and thus level) put
    ctrl.ladder.ema = ema
    ctrl.ladder.level = level


def test_sync_queue_bound_429_with_retry_after(front, adm):
    adm.config.max_queue_docs = 1
    status, headers, body = _post(front["url"],
                                  {"request": [{"text": EN},
                                               {"text": FR}]})
    assert status == 429
    assert body == {"error": "server overloaded: document queue full"}
    assert 1 <= int(headers["Retry-After"]) <= 30
    adm.config.max_queue_docs = None
    status, _, body = _post(front["url"], {"request": [{"text": EN}]})
    assert status == 200  # recovery once the bound lifts


def test_sync_brownout_shed_all_and_priority_survives(front, adm):
    _pin_ladder(adm, level=3, ema=1.0)
    status, headers, body = _post(front["url"],
                                  {"request": [{"text": EN}]})
    assert status == 503
    assert "Retry-After" in headers
    assert body == {"error":
                    "server overloaded, shedding non-priority traffic"}
    status, _, body = _post(front["url"], {"request": [{"text": EN}]},
                            headers={"X-LDT-Priority": "1"})
    assert status == 200
    assert body["response"][0]["iso6391code"] == "en"


def test_sync_brownout_degraded_bypasses_batcher(front, adm):
    """Level 2: answers come from the cache+scalar path — the batcher
    (and device) must not be touched."""
    _pin_ladder(adm, level=2, ema=0.75)
    svc = front["svc"]
    real_submit = svc.batcher.submit

    def boom(*a, **k):
        raise AssertionError("degraded request reached the batcher")

    svc.batcher.submit = boom
    try:
        status, _, body = _post(front["url"],
                                {"request": [{"text": EN}]})
    finally:
        svc.batcher.submit = real_submit
    assert status == 200
    assert body["response"][0]["iso6391code"] == "en"


def test_sync_expired_deadline_504(front, adm):
    status, _, body = _post(front["url"], {"request": [{"text": EN}]},
                            headers={"X-LDT-Deadline-Ms": "0"})
    assert status == 504
    assert body == {"error": "deadline expired before dispatch"}
    # generous deadline: served normally
    status, _, body = _post(front["url"], {"request": [{"text": EN}]},
                            headers={"X-LDT-Deadline-Ms": "30000"})
    assert status == 200


def test_metrics_scrape_has_admission_series(front):
    with urllib.request.urlopen(front["metrics_url"] +
                                "/metrics") as resp:
        text = resp.read().decode()
    for series in ("ldt_admission_queue_docs",
                   "ldt_admission_queue_bytes",
                   "ldt_admission_inflight",
                   "ldt_brownout_level", "ldt_breaker_state",
                   'ldt_shed_total{reason="queue_docs"}',
                   "ldt_deadline_expired_total"):
        assert series in text, series


def test_debug_vars_surfaces_admission(front):
    with urllib.request.urlopen(front["metrics_url"] +
                                "/debug/vars") as resp:
        doc = json.loads(resp.read())
    adm = doc["admission"]
    assert adm["brownout_level"] == 0
    assert adm["breaker"]["state_name"] == "closed"
    assert set(adm["shed"]) == {"brownout", "tenant_docs",
                                "tenant_bytes", "queue_docs",
                                "queue_bytes", "inflight"}
    from language_detector_tpu.debug import format_admission
    out = format_admission(doc)
    assert "brownout" in out and "breaker" in out


def test_sync_default_config_behavior_unchanged(front, adm):
    """With every knob off the contract answers are identical to the
    pre-admission service: plain 200/203s, no shed, no deadline."""
    status, headers, body = _post(front["url"],
                                  {"request": [{"text": EN},
                                               {"text": FR}]})
    assert status == 200
    assert [r["iso6391code"] for r in body["response"]] == ["en", "fr"]
    assert "Retry-After" not in headers
    assert adm.stats()["queue_docs"] == 0  # fully released


def test_sync_two_tenant_saturation(front, adm):
    """A tenant saturating its quota 429s with a tenant_* reason while
    another tenant (and headerless default traffic) keeps being
    served at 200 — the isolation contract through the sync front."""
    adm.config.tenant_quota_docs = 1

    # a 2-doc request exceeds the 1-doc tenant quota outright
    status, headers, body = _post(
        front["url"], {"request": [{"text": EN}, {"text": FR}]},
        headers={"X-LDT-Tenant": "hot"})
    assert status == 429
    assert "quota" in body["error"]
    assert 1 <= int(headers["Retry-After"]) <= 30
    # cold tenant and headerless traffic: unaffected
    for extra in ({"X-LDT-Tenant": "cold"}, {}):
        status, _, body = _post(front["url"],
                                {"request": [{"text": EN}]},
                                headers=extra)
        assert status == 200
        assert body["response"][0]["iso6391code"] == "en"
    # single-doc request fits the quota once nothing is queued
    status, _, _ = _post(front["url"], {"request": [{"text": EN}]},
                         headers={"X-LDT-Tenant": "hot"})
    assert status == 200
    # shed counter carries the tenant label through the scrape
    with urllib.request.urlopen(front["metrics_url"] +
                                "/metrics") as resp:
        text = resp.read().decode()
    assert 'ldt_tenant_shed_total{' in text
    assert 'tenant="hot"' in text


def test_aio_front_admission_contract():
    """The asyncio front speaks the same shed/deadline/priority
    contract: 429 + Retry-After past a bound, 503 at shed-all with
    priority surviving, 504 on an expired deadline, series in
    /metrics."""
    import asyncio
    import queue as _q

    from language_detector_tpu.service.aioserver import serve

    ctrl = AdmissionController(AdmissionConfig())
    ports_q: _q.Queue = _q.Queue()
    loop_holder = {}

    def run_loop():
        async def main():
            loop_holder["loop"] = asyncio.get_running_loop()
            ready = asyncio.get_running_loop().create_future()
            svc = DetectorService(use_device=False, max_delay_ms=1.0,
                                  start_batcher=False, admission=ctrl)
            task = asyncio.get_running_loop().create_task(
                serve(0, 0, svc=svc, ready=ready))
            ports_q.put(await ready)
            try:
                await task
            except asyncio.CancelledError:
                pass
        try:
            asyncio.run(main())
        except RuntimeError:
            pass  # loop.stop() teardown ends the run mid-await

    t = threading.Thread(target=run_loop, daemon=True)
    t.start()
    port, mport = ports_q.get(timeout=30)
    url = f"http://127.0.0.1:{port}"
    try:
        # queue bound: 429 + Retry-After, recovery after lifting it
        ctrl.config.max_queue_docs = 1
        status, headers, body = _post(url, {"request": [{"text": EN},
                                                        {"text": FR}]})
        assert status == 429
        assert body == {"error":
                        "server overloaded: document queue full"}
        assert 1 <= int(headers["Retry-After"]) <= 30
        ctrl.config.max_queue_docs = None
        status, _, _ = _post(url, {"request": [{"text": EN}]})
        assert status == 200

        # shed-all: non-priority 503s, priority is served
        _pin_ladder(ctrl, level=3, ema=1.0)
        status, headers, body = _post(url, {"request": [{"text": EN}]})
        assert status == 503 and "Retry-After" in headers
        status, _, body = _post(url, {"request": [{"text": EN}]},
                                headers={"X-LDT-Priority": "1"})
        assert status == 200
        assert body["response"][0]["iso6391code"] == "en"

        # level 2: degraded path still answers correctly
        _pin_ladder(ctrl, level=2, ema=0.75)
        status, _, body = _post(url, {"request": [{"text": FR}]})
        assert status == 200
        assert body["response"][0]["iso6391code"] == "fr"
        _pin_ladder(ctrl, level=0, ema=0.0)
        ctrl.ladder.alpha = ctrl.config.brownout_alpha

        # expired deadline: dropped at dequeue, 504
        status, _, body = _post(url, {"request": [{"text": EN}]},
                                headers={"X-LDT-Deadline-Ms": "0"})
        assert status == 504
        assert body == {"error": "deadline expired before dispatch"}

        # two-tenant saturation: hot tenant over quota 429s, the cold
        # tenant and headerless default traffic stay at 200
        ctrl.config.tenant_quota_docs = 1
        status, headers, body = _post(
            url, {"request": [{"text": EN}, {"text": FR}]},
            headers={"X-LDT-Tenant": "hot-aio"})
        assert status == 429 and "quota" in body["error"]
        assert 1 <= int(headers["Retry-After"]) <= 30
        for extra in ({"X-LDT-Tenant": "cold-aio"}, {}):
            status, _, body = _post(url, {"request": [{"text": EN}]},
                                    headers=extra)
            assert status == 200
            assert body["response"][0]["iso6391code"] == "en"
        ctrl.config.tenant_quota_docs = None

        # new series scrape through the aio metrics port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics") as resp:
            text = resp.read().decode()
        for series in ("ldt_admission_queue_docs", "ldt_brownout_level",
                       "ldt_breaker_state", "ldt_shed_total{reason=",
                       "ldt_deadline_expired_total",
                       'ldt_tenant_shed_total{'):
            assert series in text, series
        assert 'tenant="hot-aio"' in text
    finally:
        loop = loop_holder.get("loop")
        if loop is not None:
            loop.call_soon_threadsafe(loop.stop)
