"""Single-file mmap model artifact.

The TPU rebuild of the reference's dynamic-data format
(cld2_dynamic_data.h:23-110, loader cld2_dynamic_data_loader.cc:164):
one little-endian file = fixed header + per-array descriptors + 64-byte
aligned data blobs, reconstructed at load time as zero-copy views over a
single mmap — no parsing, no decompression, no per-array allocation.
The npz artifacts remain the interchange format (tools/artifact_tool.py
converts with --pack); this is the serving format.

Layout (all little-endian):
  0   u32  magic "LDTA" (0x4154444C)
  4   u32  format version
  8   u32  n_arrays
  12  u32  flags (bit 0: digest footer present; was reserved=0, so
             pre-footer artifacts read as flags=0 and still load)
  16  u64  header_bytes (end of the descriptor table)
  24  u64  total_bytes  (file size; load-time truncation check)
  32  n_arrays x 108-byte packed descriptors:
      48s  name (NUL-padded UTF-8)
      8s   numpy dtype string, e.g. "<u4" (NUL-padded)
      u32  ndim (<= 4)
      4xu64 shape (unused dims 0)
      u64  offset (file-relative), u64 nbytes
  blobs: each 64-byte aligned.
  footer (when flags bit 0 is set, included in total_bytes):
      u32  footer magic "LDTD" (0x4454444C)
      u32  n_arrays (must match the header)
      n_arrays x u32  zlib.crc32 of each blob, descriptor order

The fixed flat layout is deliberately C-parsable so a native host can
mmap the same file (the cgo seam's table story).
"""
from __future__ import annotations

import mmap
import os
import struct
import zlib
from pathlib import Path

import numpy as np

from . import faults

MAGIC = 0x4154444C  # "LDTA"
FOOT_MAGIC = 0x4454444C  # "LDTD"
VERSION = 1
ALIGN = 64
FLAG_DIGESTS = 0x1
_HDR = struct.Struct("<IIII QQ")
_DESC = struct.Struct("<48s8sI 4Q QQ")
_FOOT = struct.Struct("<II")

# pinned artifact geometry: a drive-by field edit must fail at import,
# not invalidate every packed model.ldta in the field
# (tools/lint/layout_registry.py declares the same widths)
assert _HDR.size == 32
assert _DESC.size == 108
assert _FOOT.size == 8


class ArtifactError(ValueError):
    """A corrupt, truncated, or wrong-version artifact file. Subclasses
    ValueError so every pre-existing `except ValueError` load guard
    still catches it; new code should catch ArtifactError and let the
    message (which names the file, the failure, and the fix) reach the
    operator — startup fails loud and /readyz stays false."""


class ArtifactIntegrityError(ArtifactError):
    """A structurally valid artifact whose blob bytes do not match the
    digest footer: bit rot, a torn copy, or deliberate tampering. Kept
    distinct from ArtifactError so the swap path can refuse a corrupt
    standby (ldt_swap_total{result="integrity_refused"}) while still
    treating structural damage as a plain abort."""


def write_artifact(arrays: dict, path: str | Path) -> None:
    """Write name->ndarray as one aligned little-endian artifact file."""
    items = []
    for name, a in arrays.items():
        a = np.asarray(a)
        # note: ascontiguousarray would promote 0-d arrays to 1-d, so
        # shape/ndim come from the original and only the BYTES go
        # through a contiguous copy
        buf = a if a.flags.c_contiguous else np.ascontiguousarray(a)
        if len(name.encode()) > 47:
            raise ValueError(f"array name too long: {name!r}")
        if a.ndim > 4:
            raise ValueError(f"{name}: ndim {a.ndim} > 4")
        if a.dtype.hasobject:
            raise ValueError(f"{name}: object arrays not supported")
        items.append((name, a, buf))

    header_bytes = _HDR.size + len(items) * _DESC.size
    pos = -(-header_bytes // ALIGN) * ALIGN
    descs = []
    for name, a, _ in items:
        shape = list(a.shape) + [0] * (4 - a.ndim)
        descs.append((name.encode(), a.dtype.str.encode(), a.ndim,
                      shape, pos, a.nbytes))
        pos += -(-max(a.nbytes, 1) // ALIGN) * ALIGN
    foot_off = pos
    total = pos + _FOOT.size + 4 * len(items)

    with open(path, "wb") as f:
        f.write(_HDR.pack(MAGIC, VERSION, len(items), FLAG_DIGESTS,
                          header_bytes, total))
        for (name, dt, ndim, shape, off, nb) in descs:
            f.write(_DESC.pack(name, dt, ndim, *shape, off, nb))
        crcs = []
        for (name, a, buf), (_, _, _, _, off, nb) in zip(items, descs):
            f.seek(off)
            # buf is C-contiguous: its buffer writes zero-copy
            f.write(buf.data if buf.size else b"")
            crcs.append(zlib.crc32(buf.data) if buf.size else 0)
        f.seek(foot_off)
        f.write(_FOOT.pack(FOOT_MAGIC, len(items)))
        f.write(struct.pack(f"<{len(crcs)}I", *crcs) if crcs else b"")
        f.truncate(total)


def load_artifact(path: str | Path) -> dict:
    """mmap the artifact and return name -> zero-copy ndarray views.
    The mapping stays alive as long as any view does (numpy holds the
    buffer reference).

    Every load failure — including open/mmap OS errors and a
    half-written file (ENOSPC mid-pack, a swap drill racing the
    packer) — surfaces as a typed ArtifactError with an actionable
    message, so ScoringTables.load_mmap callers (startup, hot swap)
    abort cleanly on the old tables instead of dying on a raw OSError."""
    if faults.ACTIVE is not None:
        faults.hit("artifact_load")
    try:
        f = open(path, "rb")
    except OSError as e:
        raise ArtifactError(
            f"{path}: cannot open artifact ({e.strerror or e}) — check "
            "the path/permissions or re-pack with "
            "tools/artifact_tool.py --pack") from e
    with f:
        # size-vs-header validation BEFORE the mapping exists: a
        # truncated or still-being-written file must produce a typed,
        # actionable error here, not a raw mmap ValueError/OSError or
        # a SIGBUS past the end of a short mapping later
        size = os.fstat(f.fileno()).st_size
        if size < _HDR.size:
            raise ArtifactError(
                f"{path}: {size}-byte file is shorter than the header "
                f"({_HDR.size} bytes; empty or half-written artifact) "
                "— re-pack with tools/artifact_tool.py --pack")
        pre_magic, _pv, _pn, _pr, _phb, pre_total = \
            _HDR.unpack(f.read(_HDR.size))
        if pre_magic == MAGIC and pre_total != size:
            raise ArtifactError(
                f"{path}: file is {size} bytes but the header records "
                f"{pre_total} (truncated or corrupt — half-written "
                "pack: packer died or disk filled mid-write) — restore "
                "it from source or re-pack with tools/artifact_tool.py "
                "--pack")
        try:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (OSError, ValueError) as e:
            raise ArtifactError(
                f"{path}: cannot mmap artifact ({e}) — the file must "
                "be a regular, readable, non-empty LDTA pack; re-pack "
                "with tools/artifact_tool.py --pack") from e
    try:
        if len(mm) < _HDR.size:
            raise ArtifactError(
                f"{path}: not an LDTA artifact (file shorter than the "
                "header) — re-pack it with tools/artifact_tool.py --pack")
        magic, version, n, flags, header_bytes, total = \
            _HDR.unpack_from(mm, 0)
        if magic != MAGIC:
            raise ArtifactError(
                f"{path}: bad magic {magic:#x} (want {MAGIC:#x} 'LDTA') "
                "— this is not a packed artifact; re-pack the npz with "
                "tools/artifact_tool.py --pack")
        if version != VERSION:
            raise ArtifactError(
                f"{path}: format version {version}, this build reads "
                f"version {VERSION} — re-pack with a matching "
                "tools/artifact_tool.py --pack")
        if total != len(mm):
            raise ArtifactError(
                f"{path}: file is {len(mm)} bytes but the header "
                f"records {total} (truncated or corrupt) — restore it "
                "from source or re-pack with tools/artifact_tool.py "
                "--pack")
        # a corrupted n_arrays/header_bytes must fail the ArtifactError
        # contract, not crash struct.unpack past the mapping
        if header_bytes != _HDR.size + n * _DESC.size or \
                header_bytes > total:
            raise ArtifactError(
                f"{path}: header_bytes {header_bytes} inconsistent "
                f"with {n} descriptors (corrupt header) — re-pack with "
                "tools/artifact_tool.py --pack")
        data_start = -(-header_bytes // ALIGN) * ALIGN
        crcs = None
        if flags & FLAG_DIGESTS:
            foot_off = total - (_FOOT.size + 4 * n)
            if foot_off < data_start:
                raise ArtifactError(
                    f"{path}: digest footer overlaps the data region "
                    "(corrupt header) — re-pack with "
                    "tools/artifact_tool.py --pack")
            fmagic, fn = _FOOT.unpack_from(mm, foot_off)
            if fmagic != FOOT_MAGIC or fn != n:
                raise ArtifactIntegrityError(
                    f"{path}: digest footer corrupt (magic {fmagic:#x}"
                    f", {fn} entries for {n} arrays) — restore the "
                    "file from source or re-pack with "
                    "tools/artifact_tool.py --pack")
            crcs = struct.unpack_from(f"<{n}I", mm,
                                      foot_off + _FOOT.size)
        out: dict = {}
        buf = memoryview(mm)
        for i in range(n):
            name_b, dt_b, ndim, s0, s1, s2, s3, off, nb = \
                _DESC.unpack_from(mm, _HDR.size + i * _DESC.size)
            name = name_b.rstrip(b"\0").decode()
            try:
                dtype = np.dtype(dt_b.rstrip(b"\0").decode())
            except TypeError as e:
                raise ArtifactError(
                    f"{path}: array {name!r} has an unreadable dtype "
                    f"({e}) — corrupt descriptor; re-pack with "
                    "tools/artifact_tool.py --pack") from None
            shape = (s0, s1, s2, s3)[:ndim]
            # offsets must land in the data region: a corrupt descriptor
            # must not alias array views over the header/descriptor table
            if ndim > 4 or off < data_start or off + nb > total:
                raise ArtifactError(
                    f"{path}: array {name!r} descriptor out of bounds "
                    "(corrupt) — re-pack with tools/artifact_tool.py "
                    "--pack")
            count = 1
            for s in shape:
                count *= s
            if nb != count * dtype.itemsize:
                raise ArtifactError(
                    f"{path}: array {name!r} records {nb} bytes but "
                    f"shape {shape} x itemsize {dtype.itemsize} "
                    "disagrees (corrupt descriptor) — re-pack with "
                    "tools/artifact_tool.py --pack")
            if crcs is not None and \
                    zlib.crc32(buf[off:off + nb]) != crcs[i]:
                raise ArtifactIntegrityError(
                    f"{path}: array {name!r} fails its digest "
                    "(bit rot, a torn copy, or tampering) — restore "
                    "the file from source or re-pack with "
                    "tools/artifact_tool.py --pack")
            a = np.frombuffer(buf[off:off + nb], dtype=dtype)
            out[name] = a.reshape(shape)
    except BaseException:
        # no view escaped: close the mapping instead of leaking it (a
        # successful return keeps mm alive via the views' buffer refs).
        # Partially-built views and the memoryview must drop first or
        # their live buffer exports would block the close.
        try:  # a: loop-local view of the last successfully parsed
            # array before the corrupt descriptor
            del a
        except NameError:
            pass
        try:
            del out, buf
        except NameError:
            pass
        try:
            mm.close()
        except BufferError:  # an export still alive: GC reclaims later
            pass
        raise
    if faults.ACTIVE is not None and out:
        # chaos seam: a seeded bit-flip in one loaded array models
        # memory corruption AFTER the digest check passed (the scrub
        # and canary layers are what must catch it downstream)
        seed = faults.corruption("artifact_load")
        if seed is not None:
            name = sorted(out)[seed % len(out)]
            out[name] = faults.corrupt_buffer(out[name], seed)
    return out


def artifact_digest(path: str | Path) -> str | None:
    """Cheap whole-artifact identity: the hex crc32 of the digest
    footer bytes (header-only reads — no blob I/O). None for a
    pre-footer artifact. The result-cache epoch and swap telemetry use
    this as the artifact generation key."""
    try:
        with open(path, "rb") as f:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                return None
            magic, _ver, n, flags, _hb, total = _HDR.unpack(hdr)
            if magic != MAGIC or not flags & FLAG_DIGESTS:
                return None
            foot_size = _FOOT.size + 4 * n
            if total < foot_size:
                return None
            f.seek(total - foot_size)
            foot = f.read(foot_size)
            if len(foot) < foot_size:
                return None
            return "%08x" % zlib.crc32(foot)
    except OSError:
        return None


def verify_artifact(path: str | Path) -> str | None:
    """Full read-only verification: structural checks plus every blob
    digest (load_artifact does both). Returns the artifact digest (or
    None for a pre-footer file); raises ArtifactIntegrityError on a
    digest mismatch, ArtifactError on structural damage. The swap path
    runs this against a standby artifact BEFORE cutover."""
    arrays = load_artifact(path)
    del arrays  # views drop -> the mapping closes with them
    return artifact_digest(path)
