"""Public detection API.

`LanguageDetector` wraps the engines: the scalar host engine (reference
semantics, used for validation and as fallback for rare recursion paths) and
the batched TPU engine (models/ngram.py) for throughput. Mirrors the service
surface of the reference wrapper (wrapper.cc:7-16 detect_language) and the
richer ExtDetectLanguageSummary (compact_lang_det.h:168-426).
"""
from __future__ import annotations

import dataclasses

from .engine_scalar import ScalarResult, detect_scalar
from .registry import Registry, UNKNOWN_LANGUAGE, registry as default_registry
from .tables import ScoringTables, load_tables


@dataclasses.dataclass
class DetectionResult:
    """Top-3 detection result (compact_lang_det.h:147-165 contract)."""

    language: str             # ISO code of summary language ("un" if unknown)
    language_id: int
    is_reliable: bool
    top3: list                # [(code, percent, normalized_score)] * 3
    text_bytes: int

    @classmethod
    def from_scalar(cls, r: ScalarResult, reg: Registry) -> "DetectionResult":
        return cls(
            language=reg.code(r.summary_lang),
            language_id=r.summary_lang,
            is_reliable=r.is_reliable,
            top3=[(reg.code(l), p, s) for l, p, s in
                  zip(r.language3, r.percent3, r.normalized_score3)],
            text_bytes=r.text_bytes,
        )


class LanguageDetector:
    """Configurable detector over a table artifact."""

    def __init__(self, tables: ScoringTables | None = None,
                 reg: Registry | None = None, flags: int = 0):
        self.tables = tables or load_tables()
        self.registry = reg or default_registry
        self.flags = flags
        self._batch_engine = None  # lazily built batched JAX engine

    def detect(self, text: str) -> DetectionResult:
        r = detect_scalar(text, self.tables, self.registry, self.flags)
        return DetectionResult.from_scalar(r, self.registry)

    def detect_batch(self, texts: list[str]) -> list[DetectionResult]:
        eng = self._get_batch_engine()
        if eng is None:  # no usable accelerator backend: scalar per doc
            return [self.detect(t) for t in texts]
        rs = eng.detect_batch(texts)
        return [DetectionResult.from_scalar(r, self.registry) for r in rs]

    def _get_batch_engine(self):
        if self._batch_engine is None:
            try:
                from .models.ngram import NgramBatchEngine
                self._batch_engine = NgramBatchEngine(
                    self.tables, self.registry, self.flags)
            except (ImportError, RuntimeError) as e:
                # jax missing or accelerator backend failed to initialize;
                # anything else (bad tables, shape bugs) propagates loudly
                import warnings
                warnings.warn(f"batched engine unavailable ({e!r}); "
                              "falling back to scalar detection")
                self._batch_engine = False
        return self._batch_engine or None


_default_detector: LanguageDetector | None = None


def _get_default() -> LanguageDetector:
    global _default_detector
    if _default_detector is None:
        _default_detector = LanguageDetector()
    return _default_detector


def detect(text: str) -> DetectionResult:
    return _get_default().detect(text)


def detect_batch(texts: list[str]) -> list[DetectionResult]:
    return _get_default().detect_batch(texts)
