"""Fixture: fault seam call sites for the fault-registry analyzer."""


def seams(faults, other):
    if faults.ACTIVE is not None:
        faults.hit("fix_used")
        faults.hit("fix_rogue")  # never declared
    faults.evaluate("fix_undoc")
    other.hit("fix_not_a_seam")  # not rooted at `faults`: ignored
