"""Data-parallel mesh sharding: correctness over multiple devices.

The 8-device run uses a virtual CPU mesh in a subprocess (the current
process's backend is pinned to the single real chip by the platform plugin,
so --xla_force_host_platform_device_count must be set before jax imports).
This is the same mechanism the driver's dryrun_multichip check uses.
"""
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _cpu_mesh_env(n: int) -> dict:
    env = os.environ.copy()
    env["PALLAS_AXON_POOL_IPS"] = ""  # keep the TPU platform plugin out
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        f" --xla_force_host_platform_device_count={n}")
    env["JAX_COMPILATION_CACHE_DIR"] = str(REPO / ".jax_cache")
    return env


def test_dryrun_multichip_8dev():
    """__graft_entry__.dryrun_multichip(8): one sharded step over an
    8-device mesh, scalar-exact results."""
    r = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"],
        cwd=REPO, env=_cpu_mesh_env(8), capture_output=True, text=True,
        timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "dryrun_multichip ok" in r.stdout


def test_sharded_equals_unsharded():
    """shard_map over the batch axis returns the same chunk summaries as the
    single-device program (4-device virtual CPU mesh)."""
    code = """
import numpy as np
import __graft_entry__ as g
from language_detector_tpu import native
from language_detector_tpu.models.ngram import NgramBatchEngine
from language_detector_tpu.parallel.mesh import batch_mesh

texts = g._TINY_TEXTS
single = NgramBatchEngine()
cb1 = native.pack_chunks_native(texts, single.tables, single.reg)
a = single.score_chunk_batch(cb1)
sharded = NgramBatchEngine(mesh=batch_mesh(4))
cb4 = native.pack_chunks_native(texts, sharded.tables, sharded.reg,
                                n_shards=4)
b = sharded.score_chunk_batch(cb4)
# shard-major layouts differ; compare per-document chunk sequences
for i in range(len(texts)):
    sa = int(cb1.doc_chunk_start[i]); na = int(cb1.n_chunks[i])
    sb = int(cb4.doc_chunk_start[i]); nb = int(cb4.n_chunks[i])
    assert na == nb, (i, na, nb)
    assert np.array_equal(a[sa:sa + na], b[sb:sb + nb]), i
print("sharded==unsharded ok")
"""
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       env=_cpu_mesh_env(4), capture_output=True, text=True,
                       timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "sharded==unsharded ok" in r.stdout


def test_distributed_initialize_single_process():
    """initialize() is a safe no-op in single-process mode and the local
    slice helper covers the whole batch."""
    code = """
from language_detector_tpu.parallel import distributed
assert distributed.initialize() is False   # nothing to set up
start, size = distributed.local_batch_slice(64)
assert (start, size) == (0, 64)
print("distributed single-process ok")
"""
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       env=_cpu_mesh_env(1), capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "distributed single-process ok" in r.stdout
