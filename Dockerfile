# language_detector_tpu serving container — the reference's deployment
# surface (/root/reference/Dockerfile: build in-image, expose 3000 +
# 30000, run the service) rebuilt for this framework.
#
# The CMD runs the worker under the in-repo supervisor, which restarts
# it on planned self-recycles (LDT_MAX_DISPATCHES / LDT_MAX_RSS_MB —
# the tunneled TPU backend's plugin leaks host RSS per dispatch,
# docs/PERF.md; real TPU hosts can leave the bounds unset). Pair with
# `--restart on-failure` so crashes restart too, like the reference.
#
# Build:  docker build -t language-detector-tpu .
# Run:    docker run -p 3000:3000 -p 30000:30000 \
#             -e LDT_MAX_DISPATCHES=20000 --restart on-failure \
#             language-detector-tpu
#
# Base: a jax-capable python image. On TPU VMs use a base with the TPU
# jaxlib preinstalled (e.g. the Cloud TPU pytorch/jax images) — the
# requirements below install CPU jax as the fallback compute path.
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY language_detector_tpu ./language_detector_tpu
COPY bench.py ./

# jax pinned loosely: the engine needs any recent CPU jax; TPU images
# bring their own. The native packer builds on first import (build.sh,
# -march=native on the RUNTIME host's ISA), so no compile step here
# beyond having g++ available.
RUN pip install --no-cache-dir "jax>=0.4" numpy && \
    pip install --no-cache-dir --no-deps .

EXPOSE 3000
EXPOSE 30000

ENV LISTEN_PORT=3000 PROMETHEUS_PORT=30000

CMD ["python", "-m", "language_detector_tpu.service.supervisor"]
