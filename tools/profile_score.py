#!/usr/bin/env python3
"""Device timing for the chunk-major scorer.

Times the production program (ops/score.py score_chunks) over the bench
corpus three ways — device-resident inputs (compute + readback), full
round trip (transfer + compute + readback), and a trivial jit call (the
backend's fixed dispatch latency) — so wire-size and compute changes can
be attributed. Results feed docs/PERF.md.

NOTE (axon backend): block_until_ready returns at dispatch, not at
completion — only a host fetch (np.asarray) forces execution, so all
timings go through a fetch.
"""
from __future__ import annotations

import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main(batch_size: int = 8192, iters: int = 5):
    import jax
    import jax.numpy as jnp
    from bench import make_corpus
    from language_detector_tpu import native
    from language_detector_tpu.models.ngram import NgramBatchEngine
    from language_detector_tpu.ops.score import score_chunks

    eng = NgramBatchEngine()
    docs = make_corpus(batch_size)
    t0 = time.time()
    cb = native.pack_chunks_native(docs, eng.tables, eng.reg,
                                   flags=eng.flags)
    t_pack = time.time() - t0
    p = cb.wire
    print(f"wire: B={batch_size} N={p['idx'].shape[1]} "
          f"G={p['cmeta'].shape[1]} K={p['k_iota'].shape[0]} "
          f"avg_slots={cb.n_slots.mean():.1f} "
          f"({sum(a.nbytes for a in p.values()) / 1e6:.2f} MB); "
          f"pack {t_pack * 1e3:.1f} ms", flush=True)

    @jax.jit
    def tiny(x):
        return jnp.sum(x)

    x = jax.device_put(np.arange(1024, dtype=np.int32))
    np.asarray(tiny(x))
    t0 = time.time()
    for _ in range(iters):
        np.asarray(tiny(x))
    print(f"fixed dispatch latency:      {(time.time()-t0)/iters*1e3:8.1f} "
          "ms", flush=True)

    pd = {k: jax.device_put(v) for k, v in p.items()}
    np.asarray(score_chunks(eng.dt, pd))  # compile
    t0 = time.time()
    for _ in range(iters):
        np.asarray(score_chunks(eng.dt, pd))
    print(f"compute + readback:          {(time.time()-t0)/iters*1e3:8.1f} "
          "ms", flush=True)

    t0 = time.time()
    for _ in range(iters):
        np.asarray(score_chunks(eng.dt, p))
    print(f"transfer+compute+readback:   {(time.time()-t0)/iters*1e3:8.1f} "
          "ms", flush=True)


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:]))
