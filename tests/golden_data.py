"""Parse the reference's golden {language, paragraph} suite as test fixtures.

Reads unittest_data.h from the read-only reference snapshot at test time
(kept out of the repo); tests depending on it skip when the snapshot is
absent. Handles C string concatenation, hex/octal escapes, and commented-out
entries. Expected labels come from the authoritative kTestPair tables in
cld2_unittest.cc / cld2_unittest_full.cc ({LANG_ENUM, kTeststr_*} rows,
cld2_unittest_full.cc:48-270), resolved through the registry's C enum names —
not from the kTeststr_* variable names, whose prefixes are lossy
(kTeststr_zh_Hant pairs with CHINESE_T, kTeststr_xx_Bugi with X_Buginese).
"""
import re
from functools import lru_cache
from pathlib import Path

DATA_H = Path("/root/reference/cld2/internal/unittest_data.h")
UNITTESTS = [Path("/root/reference/cld2/internal/cld2_unittest_full.cc"),
             Path("/root/reference/cld2/internal/cld2_unittest.cc")]

_ESC = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\", "'": "'"}


def _unescape(lit: str) -> bytes:
    out = bytearray()
    i = 0
    raw = lit.encode("utf-8")
    while i < len(raw):
        c = raw[i]
        if c != 0x5C:  # backslash
            out.append(c)
            i += 1
            continue
        nxt = chr(raw[i + 1])
        if nxt == "x":
            j = i + 2
            h = ""
            while j < len(raw) and chr(raw[j]) in "0123456789abcdefABCDEF":
                h += chr(raw[j])
                j += 1
            out.append(int(h, 16) & 0xFF)
            i = j
        elif nxt in "01234567":
            j = i + 1
            o = ""
            while j < len(raw) and chr(raw[j]) in "01234567" and len(o) < 3:
                o += chr(raw[j])
                j += 1
            out.append(int(o, 8) & 0xFF)
            i = j
        else:
            out.extend(_ESC.get(nxt, nxt).encode())
            i += 2
    return bytes(out)


@lru_cache(maxsize=1)
def expected_labels() -> dict:
    """kTeststr name -> expected ISO code, from the kTestPair tables."""
    from language_detector_tpu.registry import registry

    cname_to_code = {str(c): str(registry.lang_code[i])
                     for i, c in enumerate(registry.lang_cname)}
    out = {}
    for path in UNITTESTS:
        if not path.exists():
            continue
        for line in path.read_text(encoding="utf-8").splitlines():
            line = line.strip()
            if line.startswith("//"):
                continue
            m = re.match(r"\{(\w+),\s*kTeststr_(\w+)\}", line)
            if m and m.group(1) in cname_to_code:
                out.setdefault(m.group(2), cname_to_code[m.group(1)])
    return out


@lru_cache(maxsize=1)
def golden_pairs() -> list:
    """[(name, expected_lang_code, text_bytes)] from unittest_data.h."""
    if not DATA_H.exists():
        return []
    src = DATA_H.read_text(encoding="utf-8")
    # Strip line comments so commented-out variants are ignored
    src = "\n".join(l for l in src.splitlines()
                    if not l.lstrip().startswith("//"))
    labels = expected_labels()
    out = []
    for m in re.finditer(
            r'const char\*\s+kTeststr_(\w+)\s*=\s*((?:"(?:[^"\\]|\\.)*"\s*)+);',
            src, re.S):
        name = m.group(1)
        lits = re.findall(r'"((?:[^"\\]|\\.)*)"', m.group(2))
        text = b"".join(_unescape(l) for l in lits)
        if name == "version":
            continue
        # kTestPair labels are per base name; the numbered variants
        # (kTeststr_ar2 etc.) share the base entry's language.
        base = name.rstrip("0123456789")
        lang = labels.get(name) or labels.get(base)
        if lang is None:
            # Not in any kTestPair table: fall back to the name prefix
            lang = name.split("_")[0]
        out.append((name, lang, text))
    return out
