"""Event-registry analyzer: every flight-recorder event declared,
documented, and emitted.

The declaration is flightrec.EVENTS (name -> (category, doc)); the
docs contract is the event table between the
`<!-- ldt-event-table:begin/end -->` markers in docs/OBSERVABILITY.md.
Usage is extracted from the first string argument of emit_event()
calls — the module-level entry every emit site goes through (the
FlightRecorder.emit method only ever receives the already-validated
name variable, never a literal).

  event-undeclared    emitted in code but missing from EVENTS (the
                      runtime raises KeyError at the call site; lint
                      catches it before the first crash does)
  event-unused        declared in EVENTS but never emitted (a
                      postmortem reader greps for events that can
                      never appear)
  event-undocumented  drift between EVENTS and the docs event table,
                      either direction
"""
from __future__ import annotations

import ast
import re
from pathlib import Path

from .base import (Violation, apply_suppressions, first_str_arg,
                   iter_package_files, load_source, repo_root)

FLIGHTREC_REL = "language_detector_tpu/flightrec.py"
DOCS_REL = "docs/OBSERVABILITY.md"

EMIT_CALLS = frozenset({"emit_event"})

MARK_BEGIN = "<!-- ldt-event-table:begin -->"
MARK_END = "<!-- ldt-event-table:end -->"

# first backticked cell of a table row: | `event_name` | ...
_DOC_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|", re.MULTILINE)


def declared_events(root: Path, flightrec_rel: str = FLIGHTREC_REL):
    """{name: line} of EVENTS keys, by AST."""
    sf = load_source(root / flightrec_rel, root)
    for node in sf.tree.body:
        if isinstance(node, ast.Assign):
            is_events = any(isinstance(t, ast.Name)
                            and t.id == "EVENTS"
                            for t in node.targets)
        elif isinstance(node, ast.AnnAssign):
            is_events = (isinstance(node.target, ast.Name)
                         and node.target.id == "EVENTS")
        else:
            continue
        if is_events and isinstance(node.value, ast.Dict):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return {}


def used_events(sources):
    """{name: (rel, line)} of event names passed as the first argument
    of an emit_event() call."""
    used: dict = {}
    for sf in sources:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = node.func.attr \
                if isinstance(node.func, ast.Attribute) \
                else getattr(node.func, "id", None)
            if fname not in EMIT_CALLS:
                continue
            name = first_str_arg(node)
            if name:
                used.setdefault(name, (sf.rel, node.lineno))
    return used


def doc_events(root: Path, docs_rel: str = DOCS_REL) -> set:
    """Event names documented in the marked table. Outside the markers
    nothing counts: prose may mention an event name without being the
    contract."""
    text = (root / docs_rel).read_text()
    if MARK_BEGIN in text and MARK_END in text:
        text = text.split(MARK_BEGIN, 1)[1].split(MARK_END, 1)[0]
    return set(_DOC_ROW_RE.findall(text))


def check(root: Path | None = None, files=None,
          flightrec_rel: str = FLIGHTREC_REL,
          docs_rel: str = DOCS_REL):
    """Run the analyzer. Returns (violations, n_suppressed)."""
    root = root or repo_root()
    declared = declared_events(root, flightrec_rel)
    paths = list(iter_package_files(root)) if files is None else \
        [root / f if not Path(f).is_absolute() else Path(f)
         for f in files]
    sources = [load_source(p, root) for p in paths]
    used = used_events(sources)
    in_docs = doc_events(root, docs_rel) \
        if (root / docs_rel).exists() else set()

    per_file: dict = {sf.rel: [] for sf in sources}
    extra: list = []

    for name, (rel, line) in sorted(used.items()):
        if name not in declared:
            per_file.setdefault(rel, []).append(Violation(
                "event-undeclared", rel, line,
                f"event {name} is emitted but not declared in "
                f"flightrec.EVENTS (KeyError at the call site)"))
    for name, line in sorted(declared.items()):
        if name not in used:
            extra.append(Violation(
                "event-unused", flightrec_rel, line,
                f"event {name} is declared in flightrec.EVENTS but "
                f"never emitted"))
        if name not in in_docs:
            extra.append(Violation(
                "event-undocumented", flightrec_rel, line,
                f"event {name} is declared but missing from the event "
                f"table in {docs_rel}"))
    for name in sorted(in_docs):
        if name not in declared:
            extra.append(Violation(
                "event-undocumented", docs_rel, 1,
                f"{docs_rel} event table lists {name}, which is not "
                f"declared in flightrec.EVENTS (stale docs)"))

    violations: list = []
    n_suppressed = 0
    for sf in sources:
        kept, ns = apply_suppressions(sf, per_file.get(sf.rel, []))
        violations.extend(kept)
        n_suppressed += ns
    violations.extend(extra)
    return violations, n_suppressed
