#!/usr/bin/env python3
"""Train replacement quadgram tables for language_detector_tpu.

The reference snapshot is missing its two quadgram data files
(cld2_generated_quad0122.cc etc., see compile_libs.sh:31-53), which cripples
Latin/Cyrillic/Greek-script detection. This tool rebuilds a quadgram table
from the labeled word data embedded in the octagram table sources: every
kept bucket entry in cld2_generated_deltaocta0527.cc /
cld2_generated_distinctocta0527.cc carries its word as a source comment,
positionally aligned with the packed (lang, qprob) payloads we already
extracted into the artifact. ~80K labeled words across 140+ languages.

Pipeline:
  1. parse (bucket, slot) -> word from the reference source comments
  2. join with the extracted buckets/indirect arrays -> (word, [(lang, q)])
  3. scan each word with the runtime's own quad scanner -> quadgram FPs
  4. accumulate weighted per-language counts per FP
  5. quantize top-3 languages to a kLgProbV2Tbl subscript, pack langprobs,
     distribute into a 4-way-associative bucket table (CLD2 layout)
  6. write language_detector_tpu/data/quad_tables.npz

Usage: python3 tools/train_quad_tables.py [--buckets 32768]
"""
from __future__ import annotations

import argparse
import collections
import re
import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

from language_detector_tpu.preprocess.hashing import (  # noqa: E402
    quad_hash_v2, quad_subscript_key)
from language_detector_tpu.preprocess.grams import quad_positions  # noqa: E402
from language_detector_tpu.registry import registry  # noqa: E402
from language_detector_tpu.tables import load_tables  # noqa: E402

REF = Path("/root/reference/cld2/internal")

# kLgProbV2Tbl backmap (cldutil_shared.h:311-314): row of the (hi, lo=1)
# entry per hi value; row hi,lo = backmap[hi] + (lo - 1).
BACKMAP = [0, 0, 1, 3, 6, 10, 15, 21, 28, 36, 45, 55, 66]


def parse_words(path: Path) -> dict:
    """(bucket_index, slot) -> word, from the generated table's comments."""
    words = {}
    bucket = 0
    pat = re.compile(r"\{\{0x[0-9a-f]{8},0x[0-9a-f]{8},0x[0-9a-f]{8},"
                     r"0x[0-9a-f]{8}\}\},\s*//(.*)$")
    in_table = False
    for line in path.read_text(encoding="utf-8").splitlines():
        if "static const IndirectProbBucket4" in line:
            in_table = True
            bucket = 0
            continue
        if not in_table:
            continue
        if line.startswith("};"):
            break
        m = pat.search(line)
        if not m:
            continue
        comment = m.group(1).strip()
        comment = re.sub(r"^\[\w+\]\s*", "", comment)  # strip [150] markers
        parts = [w.strip() for w in comment.split(",")]
        parts = [w for w in parts if w]
        for slot, w in enumerate(parts[:4]):
            if w:
                words[(bucket, slot)] = w
        bucket += 1
    return words


def decode_langprob_langs(lp: int, othr: bool, tables, reg):
    """langprob -> [(lang, qprob)] using the word's script side."""
    entry = tables.lg_prob[lp & 0xFF]
    out = []
    for j, shift in enumerate((8, 16, 24)):
        pslang = (lp >> shift) & 0xFF
        if pslang == 0:
            continue
        lang = int(reg.plang_to_lang_othr[pslang] if othr
                   else reg.plang_to_lang_latn[pslang])
        out.append((lang, int(entry[5 + j])))
    return out


def word_payload(table, bucket: int, slot: int):
    """(bucket, slot) -> list of packed langprobs, or []."""
    kv = int(table.buckets[bucket, slot])
    if kv == 0:
        return []
    ind = kv & ~table.keymask & 0xFFFFFFFF
    if ind < table.size_one:
        lp = int(table.ind[ind])
        return [lp] if lp else []
    i = ind + (ind - table.size_one)
    return [int(x) for x in (table.ind[i], table.ind[i + 1]) if x]


class ParsedTable:
    """Bucket/indirect arrays parsed straight from a generated table source
    (used for the alternative table builds whose C symbols collide with the
    extracted ones: chrome and 0122 variants)."""

    def __init__(self, path: Path):
        src = path.read_text(encoding="utf-8")
        m = re.search(r"KeyMask = (0x[0-9a-fA-F]+)", src)
        self.keymask = int(m.group(1), 16)
        m = re.search(r"SizeOne = (\d+)", src)
        self.size_one = int(m.group(1))
        # Bucket array: every {{0x..,0x..,0x..,0x..}} row in order
        rows = re.findall(
            r"\{\{(0x[0-9a-f]{8}),(0x[0-9a-f]{8}),(0x[0-9a-f]{8}),"
            r"(0x[0-9a-f]{8})\}\}", src)
        self.buckets = np.array(
            [[int(x, 16) for x in r] for r in rows], dtype=np.uint32)
        self.size = len(rows)
        # Indirect array: hex words after the "Ind[" declaration
        ind_src = src[src.index("Ind["):]
        ind_src = ind_src[:ind_src.index("};")]
        self.ind = np.array(
            [int(x, 16) for x in re.findall(r"0x[0-9a-f]{8}", ind_src)],
            dtype=np.uint32)


def collect_training_words(tables, reg):
    """[(word, [(lang, q)], source_weight)] from the snapshot's octagram
    table builds: both delta tables (0527 + chrome; frequent words) at full
    weight, distinctocta0527 (close-pair discriminators) at reduced weight.
    The other distinct variants (distinctoctachrome/0122) measurably hurt
    golden-suite accuracy when added -- their close-pair word skew outweighs
    the extra vocabulary -- so they are deliberately excluded."""
    sources = [(REF / "cld2_generated_deltaocta0527.cc", tables.deltaocta,
                1.0),
               (REF / "cld2_generated_distinctocta0527.cc",
                tables.distinctocta, 0.3)]
    sources.append((REF / "cld2_generated_deltaoctachrome.cc",
                    ParsedTable(REF / "cld2_generated_deltaoctachrome.cc"),
                    1.0))
    out = []
    script_of = tables.script_of_cp
    for path, table, src_weight in sources:
        words = parse_words(path)
        for (bucket, slot), word in words.items():
            lps = word_payload(table, bucket, slot)
            if not lps:
                continue
            core = word.strip("_")
            if not core:
                continue
            # Script side from the word's first letter
            sc = 0
            for ch in core:
                sc = int(script_of[min(ord(ch), 0x10FFFF)])
                if sc:
                    break
            othr = sc != 1  # not Latin
            langs = {}
            for lp in lps:
                for lang, q in decode_langprob_langs(lp, othr, tables, reg):
                    if lang != 26:  # skip UNKNOWN filler
                        langs[lang] = max(langs.get(lang, 0), q)
            if langs:
                out.append((word, sorted(langs.items()), src_weight))
    return out


def quads_of_phrase(phrase: str):
    """Quadgram fingerprints for a clean lowercase phrase ('foo bar baz')
    scanned as running text: includes the word-boundary quads between
    consecutive tokens, exactly as the runtime scanner would emit them."""
    raw = phrase.encode("utf-8")
    text = b" " + raw + b" "
    buf = np.zeros(len(text) + 32, dtype=np.uint8)
    buf[:len(text)] = np.frombuffer(text, dtype=np.uint8)
    buf[len(text):len(text) + 3] = 0x20
    pos, lens, _ = quad_positions(buf, 1, len(text) - 1)
    if len(pos) == 0:
        return np.zeros(0, dtype=np.uint32)
    return quad_hash_v2(buf, pos, lens)


def quads_of_word(word: str):
    """Quadgram fingerprints the runtime scanner would produce for this word
    in running text. Leading '_' = preceded by space (always true for word
    start); trailing '_' = followed by space. Comment words without a
    trailing '_' are 8-char truncations of longer words: the real text
    continues with unknown letters, so the word is scanned with a letter
    placeholder and quads that would include the unknown bytes are dropped
    (instead of training a spurious word-final boundary quad)."""
    truncated = not word.endswith("_")
    core = word.strip("_")
    core_raw = core.encode("utf-8")
    text = b" " + core_raw + (b"x " if truncated else b" ")
    buf = np.zeros(len(text) + 32, dtype=np.uint8)
    buf[:len(text)] = np.frombuffer(text, dtype=np.uint8)
    buf[len(text):len(text) + 3] = 0x20
    pos, lens, _ = quad_positions(buf, 1, len(text) - 1)
    if len(pos) == 0:
        return np.zeros(0, dtype=np.uint32)
    if truncated:
        keep = (pos + lens) <= 1 + len(core_raw)  # exclude the placeholder
        pos, lens = pos[keep], lens[keep]
        if len(pos) == 0:
            return np.zeros(0, dtype=np.uint32)
    return quad_hash_v2(buf, pos, lens)


# Quantization hyperparameters, selected by sweep on the golden suite
# (tools/sweep_quad_tables.py). The model: per-language quad distributions
# P(g|lang), optionally shrunk toward the size-unbiased background
# (SHRINK = pseudo-mass as a fraction of the median language mass; 0 =
# raw), scaled to mean-language-mass weight units, and quantized by
# dominance (BASE + SLOPE * log2(1 + w1/(rest + ALPHA))) onto CLD2's
# 1..12 log-scale, with ~x3 steps between ranked languages and the top
# class clipped to HI_CAP (lower caps keep trained quads from shouting
# over the real reference word tables).
SHRINK = 0.0
ALPHA = 5.0
BASE = 3      # sweep r03: base 3-4 beats 5 (306 vs 304/402 goldens)
SLOPE = 2.0
HI_CAP = 12


def quantize_top3(weights: list, total_weight: float, lg_prob: np.ndarray,
                  alpha: float = None, base: float = None,
                  slope: float = None, hi_cap: int = None) -> tuple:
    """[(lang, weight)] sorted desc -> (pslangs[3], prob_subscript).

    The top qprob encodes distinctiveness: a quad dominated by one
    language scores high (CLD2's quantized log-ratio semantics, +1 ~ x3);
    a quad shared across languages spreads. Chooses the kLgProbV2Tbl row
    (hi, lo) plus the group whose mid value best matches the middle
    weight (table layout, cldutil_shared.h:42-61).
    """
    alpha = ALPHA if alpha is None else alpha
    base = BASE if base is None else base
    slope = SLOPE if slope is None else slope
    hi_cap = HI_CAP if hi_cap is None else hi_cap
    top = weights[:3]
    w1 = top[0][1]
    rest = max(total_weight - w1 + alpha, 1e-3)
    dominance = w1 / rest
    hi = int(np.clip(round(base + slope * np.log2(1 + dominance)), 2,
                     hi_cap))
    qs = [hi]
    for lang, w in top[1:]:
        # log-ratio below the winner, one step per ~x3
        q = hi - round(np.log2(max(w1 / max(w, 1e-9), 1)) / np.log2(3))
        qs.append(int(np.clip(q, 1, hi)))
    lo = qs[-1] if len(qs) >= 2 else hi
    row = BACKMAP[hi] + (lo - 1)
    if len(qs) >= 3:
        mid = min(qs[1], hi)
        best_g, best_d = 0, 1 << 30
        for g in range(3):
            d = abs(int(lg_prob[row + 78 * g][6]) - mid)
            if d < best_d:
                best_g, best_d = g, d
        row = row + 78 * best_g
    pslangs = [registry.per_script_number(1, lang) for lang, _ in top]
    while len(pslangs) < 3:
        pslangs.append(0)
    return pslangs, row


def build_table(fp_entries: dict, bucketcount: int, keymask: int,
                lg_prob: np.ndarray, alpha: float = None,
                base: float = None, slope: float = None,
                hi_cap: int = None):
    """Quantize (fp -> ranked lang weights) and pack into CLD2 bucket +
    indirect arrays. Bucket overflow spills to the caller (the
    reference's answer to collisions is the DUAL quad table probed on
    primary miss, cldutil.cc:356-363 -- the spill list feeds it)."""
    entries = []  # (fp, priority, langprob)
    for fp, (ranked, total_w, priority) in fp_entries.items():
        pslangs, row = quantize_top3(ranked, total_w, lg_prob, alpha,
                                     base, slope, hi_cap)
        lp = ((pslangs[2] & 0xFF) << 24) | ((pslangs[1] & 0xFF) << 16) | \
             ((pslangs[0] & 0xFF) << 8) | (row & 0xFF)
        entries.append((fp, priority, lp))
    return pack_entries(entries, bucketcount, keymask)


def pack_entries(entries: list, bucketcount: int, keymask: int):
    """Pack pre-quantized (fp, priority, langprob) entries into a bucket +
    indirect table: dedup langprobs, highest-priority entries claim the 4
    bucket slots first, overflow returns as a spill list."""
    langprob_index: dict = {}
    singles: list = []
    for _, _, lp in entries:
        if lp not in langprob_index:
            langprob_index[lp] = len(singles)
            singles.append(lp)
    size_one = max(len(singles), 2)
    ind_bits = (~keymask) & 0xFFFFFFFF
    if len(singles) > ind_bits:
        raise SystemExit(
            f"indirect overflow: {len(singles)} langprobs > the "
            f"{ind_bits:#x} index bits below keymask {keymask:#x}")
    buckets = np.zeros((bucketcount, 4), dtype=np.uint32)
    if not entries:
        return buckets, np.array([0, 0], dtype=np.uint32), 2, 0, []
    entries = sorted(entries, key=lambda e: -e[1])
    fps = np.array([e[0] for e in entries], dtype=np.uint32)
    subs, keys = quad_subscript_key(fps, keymask, bucketcount)
    slot_used = np.zeros(bucketcount, dtype=np.int32)
    filled = 0
    spilled = []
    for (fp, w, lp), sub, key in zip(entries, subs.tolist(), keys.tolist()):
        s = slot_used[sub]
        if s >= 4:
            spilled.append((fp, w, lp))
            continue
        buckets[sub, s] = np.uint32(key) | np.uint32(langprob_index[lp])
        slot_used[sub] = s + 1
        filled += 1
    return buckets, np.array(singles, dtype=np.uint32), len(singles), \
        filled, spilled


def collect_cldr_phrases(tables, reg):
    """[(phrase, [(lang, q)], cls)] from babel CLDR locale data ('cldr'),
    package gettext catalogs ('mo'), and the English stop-word list
    ('ensw') (tools/cldr_vocab.py), restricted to quadgram-scored
    (RTypeMany) scripts."""
    from cldr_vocab import (collect_cldr_words, collect_english_stopwords,
                            collect_mo_phrases)
    script_of = tables.script_of_cp
    rtype = reg.ulscript_rtype
    out = []
    sources = [(collect_cldr_words(reg), "cldr"),
               (collect_mo_phrases(reg), "mo"),
               (collect_english_stopwords(reg), "ensw")]
    for items, cls in sources:
        for phrase, lang, q in items:
            sc = 0
            for ch in phrase:
                sc = int(script_of[min(ord(ch), 0x10FFFF)])
                if sc:
                    break
            if sc <= 0 or sc >= len(rtype) or int(rtype[sc]) != 2:
                continue
            out.append((phrase, [(lang, q)], cls))
    return out


def collect_corpus(tables, reg):
    """All training items with their quad fingerprints precomputed:
    [(fps ndarray, [(lang, q)], src_weight_class)] where src_weight_class is
    'octa' / 'distinct' / 'cldr' (resolved to multipliers at train time so
    hyperparameter sweeps reuse one collection pass)."""
    items = []
    for word, langs, sw in collect_training_words(tables, reg):
        cls = "octa" if sw >= 1.0 else "distinct"
        items.append((quads_of_word(word), langs, cls))
    for phrase, langs, cls in collect_cldr_phrases(tables, reg):
        items.append((quads_of_phrase(phrase), langs, cls))
    return items


def train(tables, reg, corpus, buckets: int = 65536,
          cldr_weight: float = 2.0, distinct_weight: float = 0.3,
          shrink: float = SHRINK, alpha: float = ALPHA, base: float = BASE,
          slope: float = SLOPE, hi_cap: int = HI_CAP,
          mo_weight: float = 0.0, ensw_weight: float = 0.0,
          prior_pow: float = 0.0, lang_bias: dict | None = None,
          close_pool: float = 0.0, buckets2: int = 8192,
          verbose: bool = True) -> dict:
    """Accumulate the collected corpus into a packed quadgram table set.

    lang_bias: optional per-language multiplicative calibration on
    P(g|lang) (hook for error-driven win-rate calibration sweeps).
    Returns the npz-ready array dict (see main for the artifact contract).

    Defaults reflect the sweep results (tools/sweep_quad_tables.py,
    golden suite): cldr_weight 2.0 peaks at 75.6%; the gettext-catalog
    and English-stop-word sources measurably HURT (-2% / -0.5%) despite
    adding function words, so they default off; 131072/32768 buckets
    both lose to 65536; win-rate calibration and expected-score
    regeneration from synthetic docs were tried and rejected
    (tools/calibrate_quad_tables.py: dev accuracy saturates at 95% while
    golden accuracy stays flat -- the remaining gap is vocabulary-vs-
    running-text distribution mismatch, not class priors).
    """
    src_w = {"octa": 1.0, "distinct": distinct_weight,
             "cldr": cldr_weight, "mo": mo_weight, "ensw": ensw_weight}

    fp_scores: dict = collections.defaultdict(dict)
    for fps, langs, cls in corpus:
        sw = src_w[cls]
        if sw <= 0:
            continue
        for fp in set(fps.tolist()):
            d = fp_scores[fp]
            for lang, q in langs:
                # qprob is log-scale (+1 ~ x3); weight words accordingly
                wt = sw * 3.0 ** (q / 2.0)
                d[lang] = d.get(lang, 0) + wt
    if verbose:
        print(f"distinct quadgram fingerprints: {len(fp_scores)}")

    # Per-language quad distributions: p(g|lang) = w / T_lang, with
    # optional Bayesian shrinkage toward the size-unbiased background
    # G_g = mean_lang(w_g,lang / T_lang) using pseudo-mass m = shrink *
    # median language mass (keeps tiny training corpora from claiming
    # common quads). Scaled back to mean-language-mass weight units so
    # the dominance quantizer's absolute ALPHA pseudocount keeps its
    # historical meaning.
    if close_pool > 0:
        # Close-set quadgram pooling: CLD2's design separates close pairs
        # ({bs,hr,sr}, {no,nn,da}, {id,ms}, ...) with distinct WORDS, not
        # quadgrams -- its real tables list close-set members at
        # near-equal probability per quad. Our per-language training data
        # instead lets one member dominate shared quads, so pull every
        # member up to close_pool * the set's max weight and let the
        # authentic distinct-octa evidence + RefineScoredClosePairs
        # decide (lang_script.cc:258 close sets, impl.cc:1154-1203).
        cs_members: dict = collections.defaultdict(list)
        for code, lang in reg.code_to_lang.items():
            cs = reg.close_set(lang)
            if cs:
                cs_members[cs].append(lang)
        for langw in fp_scores.values():
            active = {reg.close_set(l) for l in langw} - {0}
            for cs in active:
                members = cs_members[cs]
                mx = max(langw.get(m, 0.0) for m in members)
                if mx <= 0:
                    continue
                floor = close_pool * mx
                for m in members:
                    if langw.get(m, 0.0) < floor:
                        langw[m] = floor

    lang_total = collections.Counter()
    for langw in fp_scores.values():
        for lang, w in langw.items():
            lang_total[lang] += w
    n_langs = len(lang_total)
    mean_total = float(np.mean(list(lang_total.values())))
    m = shrink * float(np.median(list(lang_total.values())))
    bias = dict(lang_bias or {})
    if prior_pow > 0:
        # Language prior from training-data richness: vocabulary size is
        # a (crude) proxy for real-world text volume, so well-resourced
        # languages win ties on shared quads against tiny ones (e.g.
        # English vs Interlingua on "_the"). Partially undoes the
        # per-language mass normalization, at quantization time only.
        med = float(np.median(list(lang_total.values())))
        for lang, t in lang_total.items():
            bias[lang] = bias.get(lang, 1.0) * (t / med) ** prior_pow

    fp_entries: dict = {}
    for fp, langw in fp_scores.items():
        raw_total = sum(langw.values())
        if m > 0:
            g_share = sum(w / lang_total[lang]
                          for lang, w in langw.items()) / n_langs
        else:
            g_share = 0.0
        ws = [(lang,
               (w + m * g_share) / (lang_total[lang] + m) * mean_total *
               bias.get(lang, 1.0))
              for lang, w in langw.items()]
        ws.sort(key=lambda kv: -kv[1])
        fp_entries[fp] = (ws, sum(w for _, w in ws), raw_total)

    # >=32K buckets use a 2-byte key (cldutil.cc:103-105 comment)
    keymask = 0xFFFF0000 if buckets >= 32768 else 0xFFFFF000
    bucket_arr, ind, size_one, filled, spilled = build_table(
        fp_entries, buckets, keymask, tables.lg_prob, alpha, base, slope,
        hi_cap)
    # Bucket-overflow spill -> dual quadgram table probed on primary miss
    # (kQuad_obj2 convention, cldutil.cc:356-373)
    keymask2 = 0xFFFF0000 if buckets2 >= 32768 else 0xFFFFF000
    b2, ind2, so2, f2, d2 = pack_entries(spilled, buckets2, keymask2) \
        if buckets2 else (None, None, 0, 0, spilled)
    if verbose:
        print(f"buckets {buckets} filled {filled} spilled {len(spilled)} "
              f"indirect {size_one}; dual {buckets2} filled {f2} "
              f"dropped {len(d2)}")

    # Expected-score calibration for the trained tables: keep the reference
    # values only for the CJK unigram/bigram-scored languages (that scoring
    # path is unchanged); zero elsewhere = "no reliability data yet", letting
    # the top-2 delta model govern (cldutil.cc:587-589) until regenerated.
    expected = np.zeros_like(tables.avg_delta_octa_score)
    for code in ("ja", "ko", "zh", "zh-Hant"):
        lang = reg.code_to_lang[code]
        expected[lang] = tables.avg_delta_octa_score[lang]

    out = {
        "quadgram_buckets": bucket_arr,
        "quadgram_ind": ind,
        "quadgram_meta": np.array([size_one, buckets, keymask, 20260730],
                                  dtype=np.uint32),
        "quadgram_langscripts": np.array("trained-from-octa-and-cldr-data"),
        "expected_score_override": expected,
    }
    if buckets2 and f2:
        out.update({
            "quadgram2_buckets": b2,
            "quadgram2_ind": ind2,
            "quadgram2_meta": np.array([so2, buckets2, keymask2, 20260730],
                                       dtype=np.uint32),
            "quadgram2_langscripts": np.array("spill-of-primary-table"),
        })
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--buckets", type=int, default=65536)
    ap.add_argument("--cldr-weight", type=float, default=2.0,
                    help="source weight multiplier for CLDR phrases "
                         "(0 disables the CLDR source)")
    ap.add_argument("--shrink", type=float, default=SHRINK)
    ap.add_argument("--out", default=str(
        REPO / "language_detector_tpu/data/quad_tables.npz"))
    args = ap.parse_args()

    tables = load_tables()
    reg = registry
    corpus = collect_corpus(tables, reg)
    print(f"training items: {len(corpus)}")
    out = train(tables, reg, corpus, buckets=args.buckets,
                cldr_weight=args.cldr_weight, shrink=args.shrink)
    np.savez_compressed(args.out, **out)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
