"""Fixture: a stand-in flightrec module for the event-registry
analyzer (passed via flightrec_rel)."""

EVENTS: dict = {
    "fix_used": ("lifecycle", "emitted and documented"),
    "fix_unused": ("lifecycle", "declared, never emitted"),
    "fix_undoc": ("request", "emitted, absent from docs"),
}
