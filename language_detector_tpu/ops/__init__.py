from .device_tables import DeviceTables  # noqa: F401
from .score import score_chunks  # noqa: F401
