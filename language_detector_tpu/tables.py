"""Scoring-table artifact: the model weights of the n-gram detector.

Holds the 4-way-associative hash tables (buckets + indirect langprob arrays)
and auxiliary decode tables, loaded from the compressed npz artifact built by
tools/extract_tables. Mirrors the reference's ScoringTables bundle
(scoreonescriptspan.h:100-114) and CLD2TableSummary (cld2tablesummary.h:37-49),
re-laid-out as flat numpy arrays so they can be uploaded once to TPU HBM and
probed with vectorized gathers.
"""
from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

_DATA = Path(__file__).parent / "data" / "cld2_tables.npz"


@dataclasses.dataclass
class NgramTable:
    """One 4-way-associative <gram-fingerprint, langprobs> hash table."""

    buckets: np.ndarray    # [size, 4] uint32: key | indirect-subscript
    ind: np.ndarray        # [n] uint32 packed langprobs
    size_one: int          # indirect subscripts >= this decode to 2 entries
    size: int              # bucket count (power of two)
    keymask: int           # upper-bit mask selecting the stored key
    build_date: int
    langscripts: str       # recognized "en-Latn az-Arab ..." list

    @classmethod
    def from_npz(cls, z, prefix: str) -> "NgramTable":
        meta = z[f"{prefix}_meta"]
        return cls(
            buckets=z[f"{prefix}_buckets"],
            ind=z[f"{prefix}_ind"],
            size_one=int(meta[0]),
            size=int(meta[1]),
            keymask=int(meta[2]),
            build_date=int(meta[3]),
            langscripts=str(z[f"{prefix}_langscripts"]),
        )

    @property
    def empty(self) -> bool:
        return self.size <= 1


def _empty_table() -> NgramTable:
    return NgramTable(
        buckets=np.zeros((1, 4), dtype=np.uint32),
        ind=np.zeros(2, dtype=np.uint32),
        size_one=1, size=1, keymask=0xFFFFF000, build_date=0, langscripts="")


@dataclasses.dataclass
class ScoringTables:
    """Full weight bundle for the n-gram scorer."""

    quadgram: NgramTable          # primary quadgram table (RTypeMany base)
    quadgram2: NgramTable         # dual quadgram table (may be empty)
    deltaocta: NgramTable         # word (octagram) delta scores
    distinctocta: NgramTable      # distinctive words + word pairs
    cjkdeltabi: NgramTable        # CJK bigram delta scores
    distinctbi: NgramTable        # CJK distinct bigrams (empty in snapshot)
    cjkcompat: NgramTable         # CJK compat classes -> langprobs
    cjk_uni_prop: np.ndarray      # [0x110000] uint8 codepoint -> compat class
    avg_delta_octa_score: np.ndarray  # [614, 4] int16 expected score/KB
    lg_prob: np.ndarray           # [240, 8] uint8 quantized log-prob decode
    script_of_cp: np.ndarray      # [0x110000] uint8 letter -> ULScript (0=not)
    lower_pairs: np.ndarray       # [n, 2] uint32 (cp, lowercase cp)
    interchange_ok: np.ndarray    # [0x110000] uint8 interchange-valid flag
    entity_names: np.ndarray      # [265] str HTML entity names (sorted)
    entity_values: np.ndarray     # [265] int32 entity codepoints
    # Hint lookup tables (compact_lang_det_hint_code.cc:102-940 data)
    langtag1_keys: np.ndarray     # [213] str long lang= tags
    langtag1_prior1: np.ndarray   # [213] int32 packed OneCLDLangPrior
    langtag1_prior2: np.ndarray
    langtag2_keys: np.ndarray     # [257] str short lang codes
    langtag2_prior1: np.ndarray
    langtag2_prior2: np.ndarray
    tld_hint_keys: np.ndarray     # [181] str TLDs
    tld_hint_prior1: np.ndarray
    tld_hint_prior2: np.ndarray
    encoding_names: np.ndarray    # [76] str Encoding enum names, in order

    @classmethod
    def load(cls, path: Path = _DATA,
             quad_path: Path | None | bool = None) -> "ScoringTables":
        """Load the table bundle.

        quad_path: None = auto-discover data/quad_tables.npz;
        False = explicitly disable quadgram tables (reference-snapshot
        parity mode); a Path = load that file."""
        if quad_path is True:
            raise ValueError("quad_path must be a Path, None (auto-discover) "
                             "or False (disable)")
        z = np.load(path, allow_pickle=False)
        discovery_miss = False
        if quad_path is None:
            qp = Path(__file__).parent / "data" / "quad_tables.npz"
            quad_path = qp if qp.exists() else False
            discovery_miss = quad_path is False
        if quad_path is not False:
            qz = np.load(quad_path, allow_pickle=False)
        else:
            qz = None
        return cls._build(z, qz, quad_warning=(
            "quad_tables.npz not found: quadgram scoring disabled, "
            "so most Latin/Cyrillic/Greek-script languages will "
            "detect as unknown. Build it with "
            "tools/train_quad_tables.py.") if discovery_miss else None)

    @classmethod
    def load_mmap(cls, path: Path) -> "ScoringTables":
        """Load from the single-file mmap artifact (artifact.py): every
        array is a zero-copy view over one shared mapping — the serving
        load path (the npz pair remains the interchange format). Arrays
        are namespaced "c/<name>" (cld2 tables) and "q/<name>" (quad
        tables; absent when the artifact was packed without them)."""
        from .artifact import load_artifact
        arrays = load_artifact(path)
        z = {k[2:]: v for k, v in arrays.items() if k.startswith("c/")}
        qz = {k[2:]: v for k, v in arrays.items() if k.startswith("q/")}
        st = cls._build(z, qz or None, quad_warning=None if qz else (
            f"{path} was packed without quad tables: quadgram scoring "
            "disabled, so most Latin/Cyrillic/Greek-script languages "
            "will detect as unknown. Re-pack with tools/artifact_tool.py "
            "--pack after training quad_tables.npz."))
        # integrity identity: the artifact's digest-footer fingerprint
        # (None for a legacy footerless pack) names the serving
        # generation — result-cache epochs and /debug/vars use it
        from .artifact import artifact_digest
        st.artifact_digest = artifact_digest(path)
        # golden-canary pack baked at artifact build time (the g/
        # arrays, tools/artifact_tool.py --pack): pinned docs and their
        # expected codes for integrity.py's per-lane canary check
        gd, go = arrays.get("g/docs_u8"), arrays.get("g/docs_off")
        cd, co = arrays.get("g/codes_u8"), arrays.get("g/codes_off")
        if gd is not None and go is not None and cd is not None \
                and co is not None:
            st.canary_docs = tuple(
                bytes(gd[go[i]:go[i + 1]]).decode("utf-8")
                for i in range(len(go) - 1))
            st.canary_codes = tuple(
                bytes(cd[co[i]:co[i + 1]]).decode("ascii")
                for i in range(len(co) - 1))
        return st

    @classmethod
    def _build(cls, z, qz, quad_warning: str | None = None
               ) -> "ScoringTables":
        """Shared constructor over mapping-like table sources (npz files
        or mmap-artifact views). quad_warning is emitted when qz is None
        (source-specific remediation advice)."""
        expected_override = None
        if qz is not None:
            quad = NgramTable.from_npz(qz, "quadgram")
            qz_files = getattr(qz, "files", qz)
            quad2 = (NgramTable.from_npz(qz, "quadgram2")
                     if "quadgram2_meta" in qz_files else _empty_table())
            if "expected_score_override" in qz_files:
                # Trained tables carry their own expected-score calibration
                # (the reference regenerates kAvgDeltaOctaScore per table
                # build via cld2_do_score.cc; zero = "no data yet" => the
                # delta reliability model governs, cldutil.cc:588).
                expected_override = qz["expected_score_override"]
        else:
            if quad_warning:
                import warnings
                warnings.warn(quad_warning, stacklevel=2)
            quad, quad2 = _empty_table(), _empty_table()
        expected = z["avg_delta_octa_score"] if expected_override is None \
            else expected_override
        return cls(
            quadgram=quad,
            quadgram2=quad2,
            deltaocta=NgramTable.from_npz(z, "deltaocta"),
            distinctocta=NgramTable.from_npz(z, "distinctocta"),
            cjkdeltabi=NgramTable.from_npz(z, "cjkdeltabi"),
            distinctbi=NgramTable.from_npz(z, "distinctbi"),
            cjkcompat=NgramTable.from_npz(z, "cjkcompat"),
            cjk_uni_prop=z["cjk_uni_prop"],
            avg_delta_octa_score=expected,
            lg_prob=z["lg_prob_v2"],
            script_of_cp=z["script_of_cp"],
            lower_pairs=z["lower_pairs"],
            interchange_ok=z["interchange_ok"],
            entity_names=z["entity_names"],
            entity_values=z["entity_values"],
            **{k: z[k] for k in (
                "langtag1_keys", "langtag1_prior1", "langtag1_prior2",
                "langtag2_keys", "langtag2_prior1", "langtag2_prior2",
                "tld_hint_keys", "tld_hint_prior1", "tld_hint_prior2",
                "encoding_names")},
        )


_tables_cache: dict = {}


def load_tables(path: Path = _DATA) -> ScoringTables:
    """Default table loading: the single-file mmap artifact
    (data/model.ldta, zero-copy) when present next to the npz bundle,
    else the npz pair. tools/artifact_tool.py --pack builds the
    artifact; both sources are bit-identical (test_artifact_mmap).

    The chosen source is logged once, and a stale artifact (npz bundle
    newer than the packed file — retrained tables without re-running
    artifact_tool --pack) logs a warning at load time rather than
    waiting for ci.sh --verify to notice the drift."""
    import logging
    key = str(path)
    if key not in _tables_cache:
        log = logging.getLogger(__name__)
        ldta = Path(path).parent / "model.ldta"
        if str(path) == str(_DATA) and ldta.exists():
            npz_mtime = 0.0
            for src in (Path(path),
                        Path(path).parent / "quad_tables.npz"):
                try:
                    npz_mtime = max(npz_mtime, src.stat().st_mtime)
                except OSError:
                    pass  # optional bundle absent (quadgram disabled)
            try:
                ldta_mtime = ldta.stat().st_mtime
            except OSError:
                # concurrent delete/replace between exists() and stat():
                # the staleness warning is informational only and must
                # never fail table loading (load_mmap below re-raises if
                # the file is truly gone)
                ldta_mtime = None
            if ldta_mtime is not None and npz_mtime > ldta_mtime:
                log.warning(
                    "serving tables from %s but the npz bundle is newer "
                    "— retrained tables without artifact_tool --pack? "
                    "(run tools/artifact_tool.py --pack, or ci.sh "
                    "--verify to check content drift)", ldta)
            else:
                log.info("loading tables from %s (mmap artifact)", ldta)
            _tables_cache[key] = ScoringTables.load_mmap(ldta)
        else:
            log.info("loading tables from %s (npz bundle)", path)
            _tables_cache[key] = ScoringTables.load(path)
    return _tables_cache[key]
