"""Declared fault-injection points for chaos testing.

Same registry discipline as knobs.py / telemetry.METRICS: every seam
the serving stack can fail at is declared ONCE in FAULT_POINTS below,
the `tools/lint` fault-registry analyzer keeps declarations, seam call
sites, and the docs table in docs/ROBUSTNESS.md from drifting, and
hitting an undeclared point raises KeyError at the first call instead
of silently injecting nothing.

Injection is driven by the LDT_FAULTS env knob — a comma-separated
rule list parsed at import (and re-parseable via configure(), which is
what tests use):

    LDT_FAULTS="device_flush:error:p=0.2:seed=7,compile:delay_ms=500:once"

Rule grammar:  point:action[:p=F][:seed=N][:once][:after=N]

    action     `error` (raise FaultInjected at the seam),
               `delay_ms=<float>` (sleep that long at the seam), or
               `corrupt` (deterministic seeded bit-flip on the seam's
               named buffer — only the corruption seams honor it, via
               corruption()/corrupt_buffer(); error/delay seams skip
               corrupt rules without consuming their schedule)
    p=F        fire with probability F per arrival (default 1.0),
               drawn from a per-rule random.Random(seed) — the schedule
               is a pure function of (seed, arrival index), so chaos
               runs are reproducible
    seed=N     the schedule seed (default 0)
    once       fire at most once, then disarm the rule
    after=N    skip the first N arrivals (fire from arrival N+1 on)

Multiple rules may target one point; delays accumulate and any error
rule that fires raises. A bad spec or an unknown point fails LOUD
(ValueError at configure/import) — a typo'd chaos profile must not run
as a silently-healthy soak.

Cost contract: with LDT_FAULTS unset, ACTIVE is None and every seam
guards with `if faults.ACTIVE is not None:` — one module-attribute
load and an identity test, nothing else (verified against bench
throughput; see docs/ROBUSTNESS.md). Every fault that actually fires
counts into ldt_fault_injected_total{point=}.
"""
from __future__ import annotations

import random
import time

from . import knobs, telemetry
from .locks import make_lock

# point -> where the seam lives (the docs table in docs/ROBUSTNESS.md
# carries the operator-facing description; lint checks both directions)
FAULT_POINTS: dict = {
    "artifact_load": "artifact.load_artifact: error/delay fire before "
                     "the mmap/verify; a corrupt rule bit-flips one "
                     "loaded array AFTER the digest check (models "
                     "host-memory rot the scrub/canary layers catch)",
    "table_upload": "integrity scrub pass, per scanned pool lane (a "
                    "corrupt rule bit-flips one plane of that lane's "
                    "device tables — models HBM corruption; the scrub "
                    "digest or canary detects it and the lane heals)",
    "frame_payload": "wire/shm ingest, per received frame body before "
                     "the CRC check (a corrupt rule bit-flips one "
                     "payload byte; with LDT_WIRE_CRC the frame is "
                     "refused instead of parsed)",
    "device_flush": "models/ngram._epilogue, the device result fetch",
    "scorer_launch": "models/ngram._launch, every jitted-scorer launch",
    "compile": "models/ngram._launch, first-execution (compiling) "
               "launches only",
    "queue_put": "both batchers' submit(), before the enqueue",
    "queue_get": "both batchers' collector, after dequeuing a batch "
                 "(an error fails that batch's futures, never the "
                 "collector)",
    "accept": "both HTTP fronts, per accepted connection (an error "
              "drops the connection before any read)",
    "swap_cutover": "service/swap.swap_artifact, after the fresh mmap "
                    "loads but before the engine reference rebinds (an "
                    "error aborts the swap; the old tables keep "
                    "serving)",
    "standby_spawn": "service/supervisor swap drill, before the "
                     "standby generation is spawned (an error aborts "
                     "the drill; the old generation keeps serving)",
    "lane_dispatch": "parallel/pool DevicePool.launch, before a batch "
                     "dispatches on its chosen lane (an error fails "
                     "over to the next lane in rotation)",
    "lane_lost": "parallel/pool fetch path, the in-flight result fetch "
                 "(an error loses the batch on that lane; the pool "
                 "re-dispatches it on a surviving lane)",
    "lane_stall": "parallel/pool fetch path, before the fetch (a delay "
                  "models a straggler lane and triggers hedging)",
    "worker_spawn": "service/fleet member spawn, before the Popen (an "
                    "error fails that spawn; the member retries after "
                    "backoff)",
    "worker_lost": "service/fleet reap pass, per live member poll (an "
                   "error SIGKILLs the member — simulated silent loss; "
                   "the fleet treats it as a crash and fails over)",
    "fleet_route": "service/fleet health plane, before each member's "
                   "/debug/vars scrape (an error counts a failed "
                   "sample toward DEGRADED)",
    "shm_attach": "service/shmring worker attach, before a discovered "
                  "ring file is mapped and its generation bumped (an "
                  "error skips the ring; the scan retries it)",
    "shm_lease": "service/shmring frame lease, before a READY frame "
                 "moves to LEASED (an error leaves the frame READY "
                 "for the next sweep)",
    "shm_reclaim": "service/shmring reclaim sweep, before a stale "
                   "WRITING/DONE slot is forced back to FREE (an "
                   "error defers that reclaim one sweep)",
    "poison_doc": "service/shmring scorer feed, per batch containing "
                  "the poison marker (an error models a doc that "
                  "deterministically kills its scorer batch and "
                  "exercises bisection + quarantine)",
    "aot_load": "aot.AotStore._load, before a bundle entry is read (a "
                "corrupt rule bit-flips one entry byte — the CRC must "
                "refuse the entry, never deserialize it; error/delay "
                "model a slow or failing bundle volume)",
    "aot_export": "aot.AotStore.offer, before the compiled scorer is "
                  "serialized (an error fails the write-back; the "
                  "dispatch that triggered it is unaffected)",
}


class FaultInjected(RuntimeError):
    """Raised at a seam by a point:error rule. A RuntimeError (not a
    service-specific type) on purpose: the recovery machinery under
    test must handle it through its generic failure paths, exactly
    like a real device/queue error."""


class _Rule:
    """One parsed LDT_FAULTS rule; mutable schedule state (calls,
    done, rng) is owned by the module _lock."""

    __slots__ = ("action", "delay_ms", "p", "rng", "seed", "once",
                 "after", "calls", "done")

    def __init__(self, action: str, delay_ms: float, p: float,
                 seed: int, once: bool, after: int):
        self.action = action        # "error" | "delay" | "corrupt"
        self.delay_ms = delay_ms
        self.p = p
        self.rng = random.Random(seed)
        self.seed = seed            # corrupt rules derive flip seeds
        self.once = once
        self.after = after
        self.calls = 0
        self.done = False


# None = injection disabled (the common case, and the whole fast-path
# check); {point: [_Rule, ...]} when armed. Rebound atomically by
# configure(), never mutated in place.
ACTIVE: dict | None = None

# serializes schedule state (call counters, rng draws, once latches)
# across flush workers / handler threads hitting seams concurrently
_lock = make_lock("faults.schedule")


def _parse(spec: str) -> dict:
    rules: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(
                f"LDT_FAULTS rule {part!r}: want "
                f"point:action[:p=][:seed=][:once][:after=]")
        point = fields[0].strip()
        if point not in FAULT_POINTS:
            raise ValueError(
                f"LDT_FAULTS: unknown fault point {point!r}; declared "
                f"points: {', '.join(sorted(FAULT_POINTS))}")
        action = fields[1].strip()
        delay_ms = 0.0
        if action in ("error", "corrupt"):
            kind = action
        elif action.startswith("delay_ms="):
            kind = "delay"
            delay_ms = float(action[len("delay_ms="):])
        else:
            raise ValueError(
                f"LDT_FAULTS rule {part!r}: action must be 'error', "
                f"'corrupt' or 'delay_ms=<float>', got {action!r}")
        p, seed, once, after = 1.0, 0, False, 0
        for opt in fields[2:]:
            opt = opt.strip()
            if opt == "once":
                once = True
            elif opt.startswith("p="):
                p = float(opt[2:])
            elif opt.startswith("seed="):
                seed = int(opt[5:])
            elif opt.startswith("after="):
                after = int(opt[6:])
            else:
                raise ValueError(
                    f"LDT_FAULTS rule {part!r}: unknown option {opt!r}")
        rules.setdefault(point, []).append(
            _Rule(kind, delay_ms, p, seed, once, after))
    return rules


def configure(spec: str | None) -> None:
    """Arm injection from a spec string, or disarm with None/blank.
    Tests drive this directly; the import-time call below arms from
    the LDT_FAULTS env knob so a supervised worker picks its chaos
    profile up at spawn."""
    global ACTIVE
    ACTIVE = _parse(spec) if spec else None


def evaluate(point: str) -> tuple:
    """Advance every rule targeting `point` by one arrival and return
    (delay_sec, inject_error). Callers on an event loop use this
    directly (await the sleep themselves); sync seams use hit().
    An undeclared point is a programming error: KeyError, exactly like
    an undeclared knob."""
    if point not in FAULT_POINTS:
        raise KeyError(f"undeclared fault point {point!r}; declare it "
                       "in language_detector_tpu/faults.py")
    active = ACTIVE
    if active is None:
        return 0.0, False
    rules = active.get(point)
    if not rules:
        return 0.0, False
    delay = 0.0
    err = False
    fired = 0
    with _lock:
        for r in rules:
            if r.action == "corrupt":
                # corruption() owns these schedules: an error/delay
                # seam must not consume a corrupt rule's arrivals
                continue
            r.calls += 1
            if r.done or r.calls <= r.after:
                continue
            if r.p < 1.0 and r.rng.random() >= r.p:
                continue
            if r.once:
                r.done = True
            if r.action == "error":
                err = True
            else:
                delay += r.delay_ms / 1e3
            fired += 1
    if fired:
        telemetry.REGISTRY.counter_inc("ldt_fault_injected_total",
                                       fired, point=point)
        from . import flightrec
        flightrec.emit_event("fault_fired", point=point, fired=fired,
                             action="error" if err else "delay")
    return delay, err


def corruption(point: str) -> int | None:
    """Advance every `corrupt` rule targeting `point` by one arrival.
    Returns a deterministic flip seed when one fires (derived from the
    rule's seed and arrival index, so a chaos run corrupts the same
    bit every time), or None. Non-corrupt rules at the same point are
    untouched — evaluate() owns their schedules. The caller passes the
    seed to corrupt_buffer() against its named buffer."""
    if point not in FAULT_POINTS:
        raise KeyError(f"undeclared fault point {point!r}; declare it "
                       "in language_detector_tpu/faults.py")
    active = ACTIVE
    if active is None:
        return None
    rules = active.get(point)
    if not rules:
        return None
    flip_seed = None
    with _lock:
        for r in rules:
            if r.action != "corrupt":
                continue
            r.calls += 1
            if r.done or r.calls <= r.after:
                continue
            if r.p < 1.0 and r.rng.random() >= r.p:
                continue
            if r.once:
                r.done = True
            flip_seed = r.seed + r.calls - 1
            break
    if flip_seed is not None:
        telemetry.REGISTRY.counter_inc("ldt_fault_injected_total",
                                       point=point)
        from . import flightrec
        flightrec.emit_event("fault_fired", point=point, fired=1,
                             action="corrupt")
    return flip_seed


def corrupt_buffer(arr, seed: int):
    """Deterministic single-bit flip: copy `arr`, flip one bit chosen
    by random.Random(seed) over the flat byte view, return the copy
    (same dtype/shape). The input is never mutated — artifact views
    are read-only mmaps and device tables re-upload from it."""
    import numpy as np
    a = np.asarray(arr)
    raw = bytearray(a.tobytes())
    if raw:
        rng = random.Random(seed)
        byte = rng.randrange(len(raw))
        raw[byte] ^= 1 << rng.randrange(8)
    return np.frombuffer(bytes(raw), dtype=a.dtype).reshape(a.shape)


def hit(point: str) -> None:
    """Synchronous seam entry: sleep any injected delay, raise
    FaultInjected if an error rule fired. Seams guard the call with
    `if faults.ACTIVE is not None:` so the disabled path is a single
    attribute check."""
    delay, err = evaluate(point)
    if delay > 0:
        time.sleep(delay)
    if err:
        raise FaultInjected(f"injected fault at {point!r} (LDT_FAULTS)")


async def hit_async(point: str) -> None:
    """Event-loop seam entry: same contract as hit(), but the delay is
    an asyncio sleep so an injected slowdown never blocks the loop."""
    delay, err = evaluate(point)
    if delay > 0:
        import asyncio
        await asyncio.sleep(delay)
    if err:
        raise FaultInjected(f"injected fault at {point!r} (LDT_FAULTS)")


# arm from the environment at import: a worker spawned with LDT_FAULTS
# set (the CI chaos smoke, an operator's game day) needs no extra
# wiring, and a bad spec fails startup loudly
configure(knobs.get_str("LDT_FAULTS"))
