"""Regression tests for the unlocked shared-state races the static
pass surfaced (and the lock-discipline analyzer now guards):

  - DetectorService.log_processed: handler threads raced the
    read-modify-write on the throughput-window counters (lost updates,
    double-printed windows);
  - NgramBatchEngine.stats_snapshot: /metrics renderers iterated the
    live stats dict while flush workers mutated it;
  - BrownoutLadder: stats reporters read level/ema as two unlocked
    loads (torn read across a step) — snapshot() reads both under the
    ladder's lock;
  - NgramBatchEngine._epilogue: stats counters and trace spans recorded
    BEFORE the fallible fetch/epilogue steps double-counted when the
    pool's lost-batch failover (or the batcher's failure path) retried
    the dispatch — everything now records after the last fallible step,
    exactly once per successful epilogue.
"""
from __future__ import annotations

import json
import threading

import pytest

from language_detector_tpu import native, telemetry
from language_detector_tpu.locks import make_lock
from language_detector_tpu.service import server as server_mod
from language_detector_tpu.service.admission import BrownoutLadder
from language_detector_tpu.service.batcher import Batcher

THREADS = 8
PER_THREAD = 250


def _hammer(fn):
    errors: list = []

    def body():
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - surfaced via assert
            errors.append(e)

    ts = [threading.Thread(target=body) for _ in range(THREADS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors


def test_log_processed_no_lost_updates(monkeypatch, capsys):
    monkeypatch.setattr(server_mod, "OBJECTS_PER_LOG", 100)
    svc = object.__new__(server_mod.DetectorService)
    svc._log_lock = make_lock("server.processed")
    svc._num_processed = 0
    svc._window_start = 0.0

    _hammer(lambda: [svc.log_processed(1) for _ in range(PER_THREAD)])

    printed = sum(
        int(json.loads(line)["msg"].split()[1])
        for line in capsys.readouterr().out.splitlines() if line)
    # every increment lands in exactly one window: the sum of logged
    # window counts plus the residual equals the true total
    assert printed + svc._num_processed == THREADS * PER_THREAD
    assert svc._num_processed < 100


def test_stats_snapshot_survives_concurrent_mutation():
    from language_detector_tpu.models.ngram import NgramBatchEngine

    eng = object.__new__(NgramBatchEngine)
    eng.stats = {f"k{i}": 0 for i in range(64)}
    eng._stats_lock = make_lock("engine.stats")
    stop = threading.Event()

    def mutate():
        i = 0
        while not stop.is_set():
            with eng._stats_lock:
                # value churn plus key churn: the unlocked iteration
                # this guards against died with "dictionary changed
                # size during iteration"
                eng.stats[f"k{i % 64}"] += 1
                eng.stats[f"extra{i % 7}"] = i
                eng.stats.pop(f"extra{(i + 3) % 7}", None)
            i += 1

    w = threading.Thread(target=mutate)
    w.start()
    try:
        def read():
            for _ in range(PER_THREAD):
                snap = eng.stats_snapshot()
                assert len(snap) >= 64
                list(snap.items())  # safe: a private copy

        _hammer(read)
    finally:
        stop.set()
        w.join()


@pytest.mark.skipif(not native.available(),
                    reason="native packer unavailable")
def test_epilogue_stats_and_spans_exactly_once(monkeypatch):
    """A failed epilogue (device fetch error, native epilogue error —
    exactly what a pool failover retries) must record NO stats and NO
    trace spans; the successful retry of the same dispatch records each
    exactly once."""
    from language_detector_tpu.models.ngram import NgramBatchEngine

    eng = NgramBatchEngine()
    texts = [f"plain english words for the exactly once epilogue "
             f"regression number {i}" for i in range(8)]
    cb, fut = eng._dispatch(texts)

    real = native.epilogue_flat_native
    state = {"fail": True}

    def flaky(*a, **kw):
        if state["fail"]:
            state["fail"] = False
            raise RuntimeError("injected epilogue failure")
        return real(*a, **kw)

    monkeypatch.setattr(native, "epilogue_flat_native", flaky)
    tr = telemetry.Trace()
    before = eng.stats_snapshot()
    with pytest.raises(RuntimeError, match="injected epilogue"):
        eng._epilogue(texts, cb, fut, trace=tr)
    mid = eng.stats_snapshot()
    assert mid["batches"] == before["batches"]
    assert mid["device_dispatches"] == before["device_dispatches"]
    names = [s[0] for s in tr.spans]
    assert "dispatch" not in names and "epilogue" not in names

    # the retry of the SAME (cb, fut): counted exactly once
    ep, _patches = eng._epilogue(texts, cb, fut, trace=tr)
    assert ep.shape[0] >= len(texts)
    after = eng.stats_snapshot()
    assert after["batches"] == before["batches"] + 1
    assert after["device_dispatches"] == \
        before["device_dispatches"] + 1
    names = [s[0] for s in tr.spans]
    assert names.count("dispatch") == 1
    assert names.count("epilogue") == 1


def test_ladder_snapshot_is_atomic():
    ladder = BrownoutLadder(alpha=1.0)  # no smoothing: level tracks
    # the last sample exactly, so a torn read is detectable
    stop = threading.Event()

    def drive():
        while not stop.is_set():
            ladder.observe(1.0)   # -> level 3
            ladder.observe(0.0)   # -> level 0

    w = threading.Thread(target=drive)
    w.start()
    try:
        def read():
            for _ in range(PER_THREAD):
                level, ema = ladder.snapshot()
                # with alpha=1 the pair is fully determined by the last
                # sample; a torn read would pair level 3 with ema 0.0
                # (or 0 with 1.0)
                assert (level, ema) in ((3, 1.0), (0, 0.0))

        _hammer(read)
    finally:
        stop.set()
        w.join()


# -- orphaned futures & stop-signal delivery (PR 8 fixes) --------------------
# surfaced by the future-resolution analyzer and the bounded model
# checker (tools/lint/future_resolution.py, tools/lint/model_check.py)


def test_breaker_straggler_success_keeps_open():
    """A success from a flush dispatched BEFORE the breaker tripped
    must not close it: OPEN only recovers through the cooldown ->
    half-open probe path (the FSM table declares no OPEN->CLOSED)."""
    from language_detector_tpu.service.admission import (
        BREAKER_HALF_OPEN, BREAKER_OPEN, CircuitBreaker)

    t = {"now": 1000.0}
    b = CircuitBreaker(failures=2, cooldown_sec=10.0,
                       clock=lambda: t["now"])
    b.record_failure()
    b.record_failure()
    assert b.stats()["state"] == BREAKER_OPEN
    b.record_success(5.0)  # straggler from the pre-trip flush
    assert b.stats()["state"] == BREAKER_OPEN
    assert not b.allow_device()
    t["now"] += 10.1  # cooldown elapsed: the probe path still works
    assert b.allow_device()
    assert b.stats()["state"] == BREAKER_HALF_OPEN


def test_batcher_fail_skips_resolved_futures():
    """_fail guards on done(), not just cancelled(): sweeping a batch
    whose futures already resolved must neither raise nor clobber."""
    from concurrent.futures import Future

    f1, f2 = Future(), Future()
    f1.set_result(["kept"])
    Batcher._fail([(["a"], None, None, f1), (["b"], None, None, f2)],
                  RuntimeError("swept"))
    assert f1.result(timeout=1) == ["kept"]
    with pytest.raises(RuntimeError, match="swept"):
        f2.result(timeout=1)


def test_flush_resolution_error_fails_futures(monkeypatch):
    """An exception INSIDE result resolution (graft, cache fill) must
    fail the batch's futures instead of orphaning them until their
    submit timeouts."""
    b = Batcher(lambda texts: [{"ok": t} for t in texts],
                max_delay_ms=1.0)
    try:
        monkeypatch.setattr(
            b, "_graft",
            lambda tr, ftrace: (_ for _ in ()).throw(
                RuntimeError("resolution exploded")))
        fut = b.submit(["hello"], trace=telemetry.Trace())
        with pytest.raises(RuntimeError, match="resolution exploded"):
            fut.result(timeout=10)
    finally:
        b.close()


def test_aio_close_drains_enqueued_futures():
    """Submissions sitting in the queue when the collector dies must
    be failed by close(), not left to their wait_for timeouts."""
    import asyncio

    from language_detector_tpu.service.aioserver import AioBatcher

    async def main():
        b = AioBatcher(lambda ts: [None] * len(ts))
        fut = asyncio.get_running_loop().create_future()
        await b._q.put((["x"], None, fut))
        await b.close()
        assert isinstance(fut.exception(), RuntimeError)

    asyncio.run(main())


def test_aio_close_fails_accumulating_batch():
    """Cancelling the collector mid-accumulation must answer the batch
    it was holding (the CancelledError handler), not strand it."""
    import asyncio

    from language_detector_tpu.service.aioserver import AioBatcher

    async def main():
        # a 60s accumulation window guarantees the request is parked
        # in the collector's pending list when close() lands
        b = AioBatcher(lambda ts: [None] * len(ts), max_batch=64,
                       max_delay_ms=60_000.0)
        b.start()
        task = asyncio.ensure_future(b.submit(["hello"]))
        await asyncio.sleep(0.05)
        await b.close()
        with pytest.raises(RuntimeError, match="batcher closed"):
            await task

    asyncio.run(main())


def test_forward_stop_exactly_once():
    """The shared latch delivers SIGTERM exactly once per child across
    all forwarding sites (handler re-entry, spawn race, wait loop) —
    invariant (c) of tools/lint/model_check.py, unit-scale."""
    import signal as signal_mod

    from language_detector_tpu.service.supervisor import _forward_stop

    class Child:
        def __init__(self, alive=True):
            self.alive = alive
            self.signals = []

        def poll(self):
            return None if self.alive else 0

        def send_signal(self, sig):
            self.signals.append(sig)

    c = Child()
    signaled = _forward_stop(c, None)
    assert c.signals == [signal_mod.SIGTERM] and signaled is c
    # repeat signal re-enters the handler: latched, no second delivery
    signaled = _forward_stop(c, signaled)
    assert c.signals == [signal_mod.SIGTERM]
    # a NEW generation (spawn race, drill cutover) gets its own one
    c2 = Child()
    signaled = _forward_stop(c2, signaled)
    assert c2.signals == [signal_mod.SIGTERM] and signaled is c2
    # an already-exited child is never signaled
    c3 = Child(alive=False)
    assert _forward_stop(c3, None) is None and c3.signals == []
