"""Batched device scoring: packed candidates -> per-chunk summaries.

The hot path of detection (compact_lang_det_impl.cc:1707-2106 ->
cldutil.cc:315-533) runs here as one jitted program of fixed-shape tensor
ops over a flat candidate wire:

  1. dense [B, L] reconstruction from the ragged wire   (gathers)
  2. 4-way-associative probes of one concatenated table (2 gathers)
  3. langprob resolution incl. double entries           (2 gathers)
  4. quad repeat filter + distinct-boost rotation       (one lax.scan)
  5. chunk assignment                                   (cumsums, closed form)
  6. chunk totes over 256 per-script languages          (one-hot matmul, MXU)
  7. top-2 + reliability per chunk                      (double argmax)

Design rule for this device (TPU behind a high-latency tunnel): NO scatter,
NO sort anywhere — scatters cost ~25ms each and sorts ~28ms at [4096, 256]
shapes while gathers are ~1-6ms and one-hot matmuls ride the MXU (~7ms).
Segment reductions are expressed as one-hot matmuls / masked broadcast
reductions over the small chunk axis; top-k(2) as two masked argmaxes; the
only sequential op is a single lax.scan carrying the 2-entry quad repeat
cache (cldutil.cc:334-367) and the rotating 4-slot distinct-boost lists
(scoreonescriptspan.cc:112-121).

The per-document epilogue (DocTote replay, close pairs, unreliable-language
removal, summary language — all O(1) per doc) runs on the host in
models/ngram.py, reusing the oracle-validated scalar code, so the batched
path agrees with the scalar engine exactly (tests/test_batch_agreement.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .device_tables import DeviceTables

# Kind ids (keep in sync with preprocess/pack.py)
PAD, SEED, QUAD, UNI, DELTA_OCTA, DISTINCT_OCTA, BI_DELTA, BI_DISTINCT = \
    range(8)

CHUNK_QUADS = 20
CHUNK_UNIS = 50

# Wire word layouts (keep in sync with models/ngram.py to_wire):
#   w1 slot meta:  offset(16) | fp_hi(8) | kind(3) | span_begin(1)
#   chunk meta:    span_end(16) | script(7) | cjk(1) | side(1)
W1_OFFSET_BITS = 16
W1_FPHI_SHIFT = 16
W1_KIND_SHIFT = 24
W1_SPANBEGIN_SHIFT = 27
CM_SPANEND_BITS = 16
CM_SCRIPT_SHIFT = 16
CM_CJK_SHIFT = 23
CM_SIDE_SHIFT = 24


def _chunk_of_rank(r, n_quota, chunksize):
    """Closed-form ChunkAll boundary rule (scoreonescriptspan.cc:994-1003):
    chunks of `chunksize` until <2 chunks remain, then runt merging."""
    c = chunksize
    n = n_quota
    k_full = jnp.where(n < 2 * c, 0, (n - 2 * c) // c + 1)
    tail = n - k_full * c
    in_full = r < k_full * c
    tr = r - k_full * c
    tail_single = tail < c + (c >> 1)
    half = (tail + 1) >> 1
    tail_chunk = jnp.where(tail_single, 0, (tr >= half).astype(jnp.int32))
    return jnp.where(in_full, r // c, k_full + tail_chunk)


def _decode3(lp):
    """langprob -> pslangs [.., 3] and group row index for qprob decode."""
    lp = lp.astype(jnp.uint32)
    ps = jnp.stack([(lp >> 8) & 0xFF, (lp >> 16) & 0xFF, (lp >> 24) & 0xFF],
                   axis=-1).astype(jnp.int32)
    return ps, (lp & 0xFF).astype(jnp.int32)


def _reliability_delta(s1, s2, grams):
    """cldutil.cc:553-570, integer math."""
    maxp = jnp.where(grams < 8, 12 * grams, 100)
    thresh = jnp.clip((grams * 5) >> 3, 3, 16)
    delta = s1 - s2
    pct = jnp.where(delta >= thresh, maxp,
                    jnp.where(delta <= 0, 0,
                              jnp.minimum(maxp, (100 * delta) // thresh)))
    return pct


def _reliability_expected(actual, expected):
    """cldutil.cc:587-605. f32 ratio math mirroring the scalar engine."""
    hi = jnp.maximum(actual, expected).astype(jnp.float32)
    lo = jnp.minimum(actual, expected).astype(jnp.float32)
    ratio = hi / jnp.maximum(lo, 1.0)
    pct = (100.0 * (4.0 - ratio) / 2.5).astype(jnp.int32)
    pct = jnp.where(ratio <= 1.5, 100, jnp.where(ratio > 4.0, 0, pct))
    pct = jnp.where(expected == 0, 100, pct)
    return jnp.where(actual == 0, jnp.where(expected == 0, 100, 0), pct)


def _lscript4(script):
    return jnp.where(script == 1, 0,
                     jnp.where(script == 3, 1, jnp.where(script == 6, 2, 3)))


def _filter_boost_scan(fp, quad_active, span_begin, distinct, side, lp_a):
    """One pass over the slot axis carrying the two sequential pieces of
    per-span scoring state:

    - the exact 2-entry quad repeat cache, reset at span starts
      (cldutil.cc:334-367); emits keep[B, L]
    - the rotating 4-slot distinct-word boost list per (doc, side)
      (AddDistinctBoost2, scoreonescriptspan.cc:112-121; persists across
      spans like ScoringContext does); emits the post-slot state
      [B, L, 2, 4] so chunk scoring can read the list as of its last slot.
    """
    B, L = fp.shape
    init = (jnp.zeros(B, jnp.uint32), jnp.zeros(B, jnp.uint32),
            jnp.zeros(B, jnp.int32),
            jnp.zeros((B, 2, 4), jnp.uint32), jnp.zeros((B, 2), jnp.int32))

    iota4 = jnp.arange(4)

    def step(state, x):
        c0, c1, nxt, bufs, ptrs = state
        f, active, begin, dist, sd, lp = x
        c0 = jnp.where(begin, jnp.uint32(0), c0)
        c1 = jnp.where(begin, jnp.uint32(0), c1)
        nxt = jnp.where(begin, 0, nxt)
        repeat = (f == c0) | (f == c1)
        keep = active & ~repeat
        c0 = jnp.where(keep & (nxt == 0), f, c0)
        c1 = jnp.where(keep & (nxt == 1), f, c1)
        nxt = jnp.where(keep, 1 - nxt, nxt)
        # rotating distinct boost list on the slot's script side
        side_oh = jnp.arange(2)[None, :] == sd[:, None]        # [B, 2]
        upd = (dist[:, None] & side_oh)[:, :, None] & \
            (ptrs[:, :, None] == iota4[None, None, :])         # [B, 2, 4]
        bufs = jnp.where(upd, lp[:, None, None], bufs)
        ptrs = jnp.where(dist[:, None] & side_oh, (ptrs + 1) & 3, ptrs)
        return (c0, c1, nxt, bufs, ptrs), (keep, bufs)

    xs = tuple(jnp.swapaxes(a, 0, 1) for a in
               (fp, quad_active, span_begin, distinct, side, lp_a))
    _, (keep, bstate) = jax.lax.scan(step, init, xs)
    return jnp.swapaxes(keep, 0, 1), jnp.moveaxis(bstate, 0, 1)


def _chk(*xs):
    """Tiny checksum that keeps a stage's outputs live under jit (the
    staged profiling hook returns this so XLA dead-code-eliminates
    everything after the stage being measured)."""
    return sum(jnp.sum(x.astype(jnp.int32)) for x in xs)


def score_batch_impl(dt: DeviceTables, p: dict, stage: int = 0):
    """Score one packed batch into stacked chunk summaries [B, C, 5].

    p is the flat wire format built by models/ngram.py to_wire (8 bytes per
    used slot over the host->device link):
      w0        [S, N]  u32  fingerprint low 32 (quad/bi/octa) or direct
                             payload (seed langprob, uni compat class)
      w1        [S, N]  u32  offset | fp_hi | kind | span_begin (see header)
      chunks    [B, C]  u32  span_end | script | cjk | side
      span_cb   [B, C]  u8   chunk_base of span s (span -> first chunk id)
      doc_start [B]     i32  doc's first slot in the flat wire (shard-local)
      n_slots   [B]     i32  slots used by the doc
      l_iota    [L]     u8   dummy: carries the dense slot-axis length

    S is the leading shard axis (1 per device; present so every leaf of the
    wire shards on axis 0 under shard_map). Documents are independent and
    every reduction is doc-local, so the program is safe under jit and
    shard_map over the doc axis with zero collectives."""
    w0f = p["w0"].reshape(-1)
    w1f = p["w1"].reshape(-1)
    N = w0f.shape[0]
    doc_start = p["doc_start"].astype(jnp.int32)
    n_slots = p["n_slots"].astype(jnp.int32)
    B = doc_start.shape[0]
    L = p["l_iota"].shape[0]
    C = p["chunks"].shape[1]
    chunk_meta = p["chunks"].astype(jnp.uint32)
    span_cb = p["span_cb"].astype(jnp.int32)

    # ---- 1. dense [B, L] reconstruction ----------------------------------
    li = jnp.arange(L, dtype=jnp.int32)
    valid_slot = li[None, :] < n_slots[:, None]
    gidx = jnp.clip(doc_start[:, None] + li[None, :], 0, N - 1)
    w0 = jnp.where(valid_slot, w0f[gidx], 0)
    w1 = jnp.where(valid_slot, w1f[gidx], 0)

    offset = (w1 & jnp.uint32(0xFFFF)).astype(jnp.int32)
    fp_hi = (w1 >> W1_FPHI_SHIFT) & jnp.uint32(0xFF)
    kind = ((w1 >> W1_KIND_SHIFT) & jnp.uint32(7)).astype(jnp.int32)
    span_begin = ((w1 >> W1_SPANBEGIN_SHIFT) & jnp.uint32(1)).astype(bool)
    fp = w0
    pad = kind == PAD

    # chunk metadata decode
    chunk_span_end = (chunk_meta & jnp.uint32(0xFFFF)).astype(jnp.int32)
    chunk_script = ((chunk_meta >> CM_SCRIPT_SHIFT) &
                    jnp.uint32(0x7F)).astype(jnp.int32)
    chunk_cjk = ((chunk_meta >> CM_CJK_SHIFT) & jnp.uint32(1)) \
        .astype(jnp.int32)
    chunk_side = ((chunk_meta >> CM_SIDE_SHIFT) & jnp.uint32(1)) \
        .astype(jnp.int32)

    # span structure: span index from begin marks; chunk_base per slot
    span_idx = jnp.clip(jnp.cumsum(span_begin.astype(jnp.int32), axis=1) - 1,
                        0, C - 1)
    chunk_base = jnp.take_along_axis(span_cb, span_idx, axis=1)
    span_start = jax.lax.cummax(
        jnp.where(span_begin, li[None, :], 0), axis=1)
    side = jnp.take_along_axis(chunk_side, chunk_base, axis=1)
    cjk = jnp.take_along_axis(chunk_cjk, chunk_base, axis=1)
    span_end_off = jnp.take_along_axis(chunk_span_end, chunk_base, axis=1)

    # ---- 2. table probes (concatenated tables, 2 gathers) ----------------
    kt = dt.kind_tbl  # per-kind geometry constants, [8]-vectors
    size_k = kt.size[kind]
    keymask_k = kt.keymask[kind]
    probe_k = kt.probes[kind]

    # quad-style sub/key (cldutil_shared.h:380-386)
    sub_q = ((fp + (fp >> jnp.uint32(12))) &
             (size_k - 1).astype(jnp.uint32)).astype(jnp.int32)
    key_q = fp & keymask_k
    # octa-style sub/key from the 40-bit fingerprint carried as (low 32,
    # bits 32-39), exactly matching hashing.octa_subscript_key
    # (cldutil_shared.h:389-397) in pure uint32 arithmetic
    sum_lo = fp + ((fp >> jnp.uint32(12)) | (fp_hi << jnp.uint32(20)))
    sub_o = (sum_lo & (size_k - 1).astype(jnp.uint32)).astype(jnp.int32)
    key_o = ((fp >> jnp.uint32(4)) | (fp_hi << jnp.uint32(28))) & keymask_k

    is_octa = (kind == DELTA_OCTA) | (kind == DISTINCT_OCTA)
    sub = jnp.where(is_octa, sub_o, sub_q)
    key = jnp.where(is_octa, key_o, key_q)
    sub = jnp.where(probe_k, sub, 0)

    def probe(rows, key, keymask):
        match = ((rows ^ key[..., None]) & keymask[..., None]) == 0
        hit = match.any(-1)
        slot = jnp.argmax(match, axis=-1)
        kv = jnp.take_along_axis(rows, slot[..., None], axis=-1)[..., 0]
        return jnp.where(hit, kv, jnp.uint32(0))

    rows1 = dt.cat_buckets[kt.bucket_off[kind] + sub]        # [B, L, 4]
    kv = jnp.where(probe_k, probe(rows1, key, keymask_k), 0)

    # dual quadgram table (second probe only meaningful for QUAD slots)
    q2 = dt.kind_tbl2
    if dt.quad2_enabled:
        sub2 = ((fp + (fp >> jnp.uint32(12))) &
                jnp.uint32(q2.size - 1)).astype(jnp.int32)
        sub2 = jnp.where(kind == QUAD, sub2, 0)
        rows2 = dt.cat_buckets[q2.bucket_off + sub2]
        kv2 = jnp.where(kind == QUAD,
                        probe(rows2, fp & jnp.uint32(q2.keymask),
                              jnp.full_like(fp, q2.keymask)), 0)
    else:
        kv2 = jnp.zeros_like(kv)
    if stage == 1:  # probes only
        return _chk(kv, kv2)

    # ---- 3. langprob resolution (2 gathers + double-entry logic) ---------
    # All tables share the indirect convention (LinearizeAll,
    # scoreonescriptspan.cc:936-964): subscript < size_one -> one langprob
    # at ind[s]; else two at ind[2s - size_one]. The snapshot's octa/bi
    # tables are all-single (size_one == len(ind)) and cjkcompat is
    # all-double (size_one == 0), so one code path covers every kind.
    ind_raw = jnp.where(kind == UNI, w0, kv & ~keymask_k) \
        .astype(jnp.int32)
    so_k = kt.size_one[kind]
    io_k = kt.ind_off[kind]
    single1 = ind_raw < so_k
    ia1 = io_k + jnp.where(single1, ind_raw, 2 * ind_raw - so_k)
    # QUAD slots falling back to the dual table
    use2 = (kind == QUAD) & (kv == 0)
    ind2 = (kv2 & jnp.uint32(~np.uint32(q2.keymask))).astype(jnp.int32)
    single2 = ind2 < q2.size_one
    ia2 = q2.ind_off + jnp.where(single2, ind2, 2 * ind2 - q2.size_one)
    ia = jnp.where(use2, ia2, ia1)
    single = jnp.where(use2, single2, single1)
    hit = jnp.where(use2, kv2 != 0, (kv != 0) | (kind == UNI))

    n_ind = len(dt.cat_ind)
    lp_gather_a = dt.cat_ind[jnp.clip(ia, 0, n_ind - 1)]
    lp_gather_b = dt.cat_ind[jnp.clip(ia + 1, 0, n_ind - 1)]

    lp_a = jnp.where(kind == SEED, w0,
                     jnp.where(hit & (kind > SEED), lp_gather_a, 0))
    lp_b = jnp.where(hit & ((kind == QUAD) | (kind == UNI)) & ~single,
                     lp_gather_b, 0)
    if stage == 2:
        return _chk(lp_a, lp_b)

    # ---- 4. quad repeat filter + distinct boost rotation (one scan) ------
    quad_active = (kind == QUAD) & (lp_a != 0)
    is_distinct = ((kind == DISTINCT_OCTA) | (kind == BI_DISTINCT)) & \
        (lp_a != 0)
    keep_quad, bstate = _filter_boost_scan(
        fp, quad_active, span_begin, is_distinct, side, lp_a)
    quad_mask = (kind != QUAD) | keep_quad
    lp_a = jnp.where(quad_mask, lp_a, 0)
    lp_b = jnp.where(quad_mask, lp_b, 0)
    valid_a = lp_a != 0
    valid_b = lp_b != 0
    if stage == 3:
        return _chk(keep_quad, bstate, lp_a)

    is_base_kind = (kind == SEED) | (kind == QUAD) | (kind == UNI)
    # linear-entry contribution toward chunk quotas and gram counts
    entry_contrib = jnp.where(is_base_kind,
                              valid_a.astype(jnp.int32) +
                              valid_b.astype(jnp.int32), 0)
    # base hit RECORDS (chunk quota input; seed is not a record)
    base_record = (((kind == QUAD) & keep_quad) |
                   ((kind == UNI) & valid_a)).astype(jnp.int32)

    # ---- 5. chunk assignment (cumsums + closed-form boundaries) ----------
    # records per span: masked reduce over the small span axis (<= C spans)
    span_oh = (span_idx[:, None, :] == jnp.arange(C)[None, :, None]) & \
        ~pad[:, None, :]                                      # [B, C, L]
    recs_per_span = jnp.sum(jnp.where(span_oh, base_record[:, None, :], 0),
                            axis=2)                           # [B, C]
    n_span_records = jnp.take_along_axis(recs_per_span, span_idx, axis=1)

    cum_entries = jnp.cumsum(entry_contrib, axis=1)
    cum_at_start = jnp.take_along_axis(cum_entries, span_start, axis=1)
    contrib_at_start = jnp.take_along_axis(entry_contrib, span_start, axis=1)
    cb_incl = cum_entries - cum_at_start + contrib_at_start
    cb_excl = cb_incl - entry_contrib  # consumed strictly before this slot

    chunksize = jnp.where(cjk > 0, CHUNK_UNIS, CHUNK_QUADS)
    quota = jnp.maximum(n_span_records, 0)
    # clip rank so overflow lands in the final chunk (forced end boundary)
    r = jnp.clip(cb_excl, 0, jnp.maximum(quota - 1, 0))
    local_chunk = jnp.where(quota == 0, 0,
                            _chunk_of_rank(r, quota, chunksize))
    chunk_id = jnp.clip(chunk_base + local_chunk, 0, C - 1)
    slot_valid = valid_a & ~pad
    if stage == 4:
        return _chk(chunk_id, slot_valid)

    # ---- 6. chunk totes: one-hot matmul on the MXU -----------------------
    ps_a, row_a = _decode3(lp_a)
    ps_b, row_b = _decode3(lp_b)
    q_a = dt.lg_prob3[row_a].astype(jnp.int32)     # [B, L, 3]
    q_b = dt.lg_prob3[row_b].astype(jnp.int32)

    iota256 = jnp.arange(256, dtype=jnp.int32)
    # per-slot language contribution vector [B, L, 256] (XLA fuses the six
    # iota-compare adds into the einsum operand)
    lang_val = jnp.zeros((B, L, 256), jnp.bfloat16)
    for ps3, q3, ok in ((ps_a, q_a, valid_a), (ps_b, q_b, valid_b)):
        for j in range(3):
            contrib = jnp.where(ok & (ps3[..., j] > 0), q3[..., j], 0)
            lang_val = lang_val + jnp.where(
                ps3[..., j:j + 1] == iota256, contrib[..., None], 0
            ).astype(jnp.bfloat16)

    chunk_oh = ((chunk_id[:, None, :] == jnp.arange(C)[None, :, None]) &
                slot_valid[:, None, :])                       # [B, C, L]
    scores = jnp.einsum("bcl,blk->bck", chunk_oh.astype(jnp.bfloat16),
                        lang_val,
                        preferred_element_type=jnp.float32).astype(jnp.int32)
    if stage == 5:
        return _chk(scores)

    # ---- 7. distinct-word boosts from the scan state ---------------------
    # boost list as of the chunk's last valid slot, on the chunk's side
    last_slot = jnp.max(jnp.where(chunk_oh, li[None, None, :], 0), axis=2)
    chunk_has = jnp.any(chunk_oh, axis=2)                     # [B, C]
    bstate_c = jnp.take_along_axis(
        bstate.reshape(B, L, 8),
        last_slot[..., None], axis=1).reshape(B, C, 2, 4)
    boost_lps = jnp.take_along_axis(
        bstate_c, chunk_side[..., None, None], axis=2)[:, :, 0, :]
    boost_lps = jnp.where(chunk_has[..., None], boost_lps, 0)  # [B, C, 4]
    bps, brow = _decode3(boost_lps)                            # [B, C, 4, 3]
    bq = dt.lg_prob3[brow].astype(jnp.int32)
    bval = jnp.where((boost_lps[..., None] != 0) & (bps > 0), bq, 0)
    boost_scores = jnp.sum(
        jnp.where(bps[..., None] == iota256, bval[..., None], 0),
        axis=(2, 3))                                           # [B, C, 256]
    scores = scores + boost_scores
    if stage == 6:
        return _chk(scores)

    # ---- 8. chunk summaries (no sort, no scatter) ------------------------
    # group-in-use semantics: every langprob add carries qprob >= 1
    # (validated at DeviceTables.from_host), so a Tote group is in use iff
    # any of its 4 language slots scored > 0
    groups = jnp.any((scores > 0).reshape(B, C, 64, 4), axis=3)
    slot_in_use = jnp.repeat(groups, 4, axis=2)                # [B, C, 256]

    grams = jnp.sum(jnp.where(
        chunk_oh, jnp.where(kind <= UNI, entry_contrib, 0)[:, None, :], 0),
        axis=2)
    lo_off = jnp.min(jnp.where(chunk_oh, offset[:, None, :], 1 << 30),
                     axis=2)
    real = chunk_has

    # span of each chunk from the span->chunk_base map: chunk c belongs to
    # span s iff span_cb[s] <= c < span_cb[s+1] (within allocated spans)
    n_spans = jnp.max(jnp.where(span_begin, span_idx + 1, 0), axis=1)
    ci = jnp.arange(C, dtype=jnp.int32)
    span_alloc = jnp.arange(C)[None, :] < n_spans[:, None]     # [B, S]
    span_of_chunk = jnp.sum(
        ((ci[None, :, None] >= span_cb[:, None, :]) & span_alloc[:, None, :])
        .astype(jnp.int32), axis=2) - 1                        # [B, C]

    next_lo = jnp.concatenate([lo_off[:, 1:], jnp.full((B, 1), 1 << 30)],
                              axis=1)
    next_span = jnp.concatenate([span_of_chunk[:, 1:],
                                 jnp.full((B, 1), -2)], axis=1)
    next_real = jnp.concatenate([real[:, 1:], jnp.zeros((B, 1), bool)],
                                axis=1)
    hi_off = jnp.where(next_real & (next_span == span_of_chunk), next_lo,
                       chunk_span_end)
    cbytes = jnp.maximum(hi_off - lo_off, 0)

    # top-2 by (score, lowest key wins ties): two masked argmaxes
    sortkey = jnp.where(slot_in_use,
                        scores * 256 + (255 - iota256), -1)
    k1 = jnp.argmax(sortkey, axis=-1)
    top1 = jnp.take_along_axis(sortkey, k1[..., None], axis=-1)[..., 0]
    sortkey2 = jnp.where(iota256 == k1[..., None], -1, sortkey)
    k2 = jnp.argmax(sortkey2, axis=-1)
    top2 = jnp.take_along_axis(sortkey2, k2[..., None], axis=-1)[..., 0]
    s1 = jnp.where(top1 >= 0, top1 >> 8, 0)
    s2 = jnp.where(top2 >= 0, top2 >> 8, 0)
    k1 = jnp.where(top1 >= 0, k1, 0)
    k2 = jnp.where(top2 >= 0, k2, 0)

    script = chunk_script
    rtype = dt.lang_rtype_default[script, 0]
    deflang = dt.lang_rtype_default[script, 1]
    side_idx = jnp.where(script == 1, 0, 1)

    def to_lang(ps):
        mapped = dt.plang_to_lang[side_idx, ps]
        return jnp.where(rtype <= 1, deflang, mapped)

    lang1 = to_lang(k1)
    lang2 = to_lang(k2)

    actual_kb = jnp.where(cbytes > 0, (s1 << 10) // jnp.maximum(cbytes, 1), 0)
    expected_kb = dt.expected_score[lang1, _lscript4(script)]
    rd = _reliability_delta(s1, s2, grams)
    same_set = (dt.close_set[lang1] != 0) & \
        (dt.close_set[lang1] == dt.close_set[lang2])
    rd = jnp.where(same_set, 100, rd)
    rs = _reliability_expected(actual_kb, expected_kb)
    crel = jnp.minimum(rd, rs)

    # ---- 9. chunk summary outputs ----------------------------------------
    # One stacked [B, C, 5] array (a single device->host transfer). The
    # document epilogue (DocTote replay, close pairs, unreliable-language
    # removal, summary language) runs on the host over it, reusing the
    # oracle-validated scalar code (models/ngram.py). Chunk ids are
    # allocated in span order by the packer, so replaying chunks by id
    # reproduces the scalar engine's DocTote insertion order exactly.
    return jnp.stack(
        [lang1, cbytes, s1, crel, real.astype(jnp.int32)], axis=-1)


# Lane order of the stacked score_batch output
OUT_LANG1, OUT_BYTES, OUT_SCORE1, OUT_REL, OUT_REAL = range(5)


score_batch = jax.jit(score_batch_impl)

# Profiling variant: `stage` is static, so each stage compiles a pruned
# program (everything after the requested stage is dead-code-eliminated) —
# tools/profile_score.py times these to attribute device cost per stage.
score_batch_staged = jax.jit(score_batch_impl, static_argnames=("stage",))


# ---------------------------------------------------------------------------
# Resolved-wire scorer: the production path.
#
# The native packer (packer.cc ldt_pack_resolve) performs the table probes,
# quad repeat cache, chunk assignment, and distinct-boost rotation on the
# HOST (the tables are a few MB and cache-resident there), so the wire
# carries only resolved hits — 3 bytes per slot (u16 index into the
# concatenated indirect array + u8 doc-local chunk id) instead of 8, and
# misses never cross the host->device link. The device keeps the dense
# numeric core that actually benefits from the MXU: langprob decode,
# per-chunk totes as one-hot matmuls, masked top-2, and the reliability
# formulas (cldutil.cc:553-605).
# ---------------------------------------------------------------------------

# cmeta bit layout (keep in sync with packer.cc pack_resolve_one_doc):
#   cbytes(16) | grams(12) << 16 | side << 28 | real << 29
CM2_GRAMS_SHIFT = 16
CM2_SIDE_SHIFT = 28
CM2_REAL_SHIFT = 29
# output word: lang1(10) | s1(14) << 10 | rel(7) << 24 | real << 31
OUTW_S1_SHIFT = 10
OUTW_REL_SHIFT = 24
OUTW_REAL_SHIFT = 31


def score_resolved_impl(dt: DeviceTables, p: dict):
    """Score one resolved wire into packed chunk outputs [B, C, 2] u32.

    p (built by models/ngram.py from ldt_pack_resolve):
      idx       [S, N]  u16  cat_ind2 index per resolved hit
      chk       [S, N]  u8   doc-local chunk id
      doc_start [B]     i32  doc's first slot (shard-local)
      n_slots   [B]     i32
      cmeta     [B, C]  u32  chunk meta (see CM2_* layout)
      cscript   [B, C]  u8   chunk ULScript
      l_iota    [L]     u8   dense slot-axis length carrier

    Every reduction is doc-local: safe under jit and shard_map over the
    doc axis with zero collectives."""
    idxf = p["idx"].reshape(-1)
    chkf = p["chk"].reshape(-1)
    N = idxf.shape[0]
    doc_start = p["doc_start"].astype(jnp.int32)
    n_slots = p["n_slots"].astype(jnp.int32)
    B = doc_start.shape[0]
    L = p["l_iota"].shape[0]
    cmeta = p["cmeta"].astype(jnp.uint32)
    C = cmeta.shape[1]

    # dense [B, L] reconstruction (one gather pair)
    li = jnp.arange(L, dtype=jnp.int32)
    valid = li[None, :] < n_slots[:, None]
    gidx = jnp.clip(doc_start[:, None] + li[None, :], 0, N - 1)
    lp = jnp.where(valid, dt.cat_ind2[idxf[gidx].astype(jnp.int32)], 0)
    chunk_id = jnp.where(valid, chkf[gidx].astype(jnp.int32), 0)

    # decode + per-slot language contribution [B, L, 256]
    ps, row = _decode3(lp)
    q = dt.lg_prob3[row].astype(jnp.int32)                     # [B, L, 3]
    iota256 = jnp.arange(256, dtype=jnp.int32)
    lang_val = jnp.zeros((B, L, 256), jnp.bfloat16)
    for j in range(3):
        contrib = jnp.where(valid & (ps[..., j] > 0), q[..., j], 0)
        lang_val = lang_val + jnp.where(
            ps[..., j:j + 1] == iota256, contrib[..., None], 0
        ).astype(jnp.bfloat16)

    # chunk totes on the MXU
    chunk_oh = ((chunk_id[:, None, :] == jnp.arange(C)[None, :, None]) &
                valid[:, None, :])                             # [B, C, L]
    scores = jnp.einsum("bcl,blk->bck", chunk_oh.astype(jnp.bfloat16),
                        lang_val,
                        preferred_element_type=jnp.float32).astype(jnp.int32)

    # chunk meta decode
    cbytes = (cmeta & jnp.uint32(0xFFFF)).astype(jnp.int32)
    grams = ((cmeta >> CM2_GRAMS_SHIFT) & jnp.uint32(0xFFF)) \
        .astype(jnp.int32)
    side = ((cmeta >> CM2_SIDE_SHIFT) & jnp.uint32(1)).astype(jnp.int32)
    real = ((cmeta >> CM2_REAL_SHIFT) & jnp.uint32(1)).astype(jnp.int32)
    script = p["cscript"].astype(jnp.int32)

    # group-in-use top-2 (tote.cc:30-100 semantics; qprob >= 1 invariant
    # validated at DeviceTables.from_host)
    groups = jnp.any((scores > 0).reshape(B, C, 64, 4), axis=3)
    slot_in_use = jnp.repeat(groups, 4, axis=2)
    sortkey = jnp.where(slot_in_use, scores * 256 + (255 - iota256), -1)
    k1 = jnp.argmax(sortkey, axis=-1)
    top1 = jnp.take_along_axis(sortkey, k1[..., None], axis=-1)[..., 0]
    sortkey2 = jnp.where(iota256 == k1[..., None], -1, sortkey)
    k2 = jnp.argmax(sortkey2, axis=-1)
    top2 = jnp.take_along_axis(sortkey2, k2[..., None], axis=-1)[..., 0]
    s1 = jnp.where(top1 >= 0, top1 >> 8, 0)
    s2 = jnp.where(top2 >= 0, top2 >> 8, 0)
    k1 = jnp.where(top1 >= 0, k1, 0)
    k2 = jnp.where(top2 >= 0, k2, 0)

    # per-script language mapping (rtype<=1 spans never reach the device:
    # the packer routes them through direct_adds)
    lang1 = dt.plang_to_lang[side, k1]
    lang2 = dt.plang_to_lang[side, k2]

    actual_kb = jnp.where(cbytes > 0, (s1 << 10) // jnp.maximum(cbytes, 1),
                          0)
    expected_kb = dt.expected_score[lang1, _lscript4(script)]
    rd = _reliability_delta(s1, s2, grams)
    same_set = (dt.close_set[lang1] != 0) & \
        (dt.close_set[lang1] == dt.close_set[lang2])
    rd = jnp.where(same_set, 100, rd)
    rs = _reliability_expected(actual_kb, expected_kb)
    crel = jnp.minimum(rd, rs)

    # single packed word per chunk: 32 bytes/doc device->host readback.
    # s1 clips at 16383 — chunk totes are bounded far below (<= ~110
    # entries x qprob 12 + 4x12 boosts); the batch-agreement suite pins
    # exactness against the scalar engine.
    return (lang1.astype(jnp.uint32) |
            (jnp.clip(s1, 0, 0x3FFF).astype(jnp.uint32) << OUTW_S1_SHIFT) |
            (jnp.clip(crel, 0, 127).astype(jnp.uint32) << OUTW_REL_SHIFT) |
            (real.astype(jnp.uint32) << OUTW_REAL_SHIFT))


score_resolved = jax.jit(score_resolved_impl)


def unpack_resolved_out(out: np.ndarray, cmeta: np.ndarray) -> np.ndarray:
    """Device output [B, C] u32 + host chunk meta -> the [B, C, 5] int32
    chunk-summary layout the document epilogue consumes (OUT_* lanes)."""
    lang1 = (out & 0x3FF).astype(np.int32)
    s1 = ((out >> OUTW_S1_SHIFT) & 0x3FFF).astype(np.int32)
    rel = ((out >> OUTW_REL_SHIFT) & 0x7F).astype(np.int32)
    real = ((out >> OUTW_REAL_SHIFT) & 1).astype(np.int32)
    cbytes = (cmeta & 0xFFFF).astype(np.int32)
    return np.stack([lang1, cbytes, s1, rel, real], axis=-1)
