"""Torn-write model checking: crash the REAL writers after every store.

The publish-order analyzer (tools/lint/publish_order.py) proves the
store ORDER syntactically; this module proves the store order is
SUFFICIENT. Each product drives the real writer class (flightrec ring,
capture ring, sharedcache seqlock slot, shmring slot) against a
journaling buffer that records every individual store into the mmap
region, then replays byte-prefix crash schedules: the writer is
"SIGKILLed" after every completed store and, for every multi-byte
store, after every byte of a partially applied store (only an aligned
4-byte store — the commit word — is atomic). Each crash state is
handed to the REAL reader, and the invariant is exhaustively checked:

  old-value-or-refusal   a reader of a crashed writer's buffer returns
                         previously committed records (or a miss) —
                         never a torn/mixed record
  commit-liveness        with every store applied, the reader returns
                         the NEW record (the protocol publishes, it
                         does not just refuse forever)

Failures carry the minimal store-schedule trace that produced the bad
state. ``run_product(name, writer=...)`` accepts a replacement writer
so tests and ci.sh can prove the harness detects broken protocols:
``doctored_flightrec_commit_first`` / ``doctored_capture_commit_first``
reintroduce the classic single-forward-memcpy bug (commit word first)
and MUST produce a counterexample.

Deliberately a separate module from model_check.py: that file is
pinned clock-free/random-free by tests, while these products patch the
subject modules' ``time`` binding with a fake so journals are
byte-deterministic.
"""
from __future__ import annotations

import contextlib
import json
import os
import struct as _struct
import sys
import tempfile
from pathlib import Path

from .base import Violation, repo_root

_REPO = repo_root()
if str(_REPO) not in sys.path:  # `python -m tools.lint` has it; direct
    sys.path.insert(0, str(_REPO))  # imports of this module may not

MAX_SCHEDULES = 20000


class TornBuffer:
    """mmap stand-in that journals every store (offset, bytes)."""

    def __init__(self, initial: bytes):
        self.data = bytearray(initial)
        self.journal: list = []   # [(offset, bytes), ...]

    def _store(self, off: int, data: bytes) -> None:
        self.data[off:off + len(data)] = data
        self.journal.append((off, bytes(data)))

    def __setitem__(self, idx, value) -> None:
        if isinstance(idx, slice):
            start = idx.start or 0
            self._store(start, bytes(value))
        else:
            self._store(idx, bytes([value]))

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return bytes(self.data[idx])
        return self.data[idx]

    def __len__(self) -> int:
        return len(self.data)


class _StructProxy:
    """Struct wrapper that routes pack_into/unpack_from through a
    TornBuffer (journaling stores) and passes real buffers through."""

    def __init__(self, st):
        self._st = st
        self.size = st.size
        self.format = st.format

    def pack(self, *a):
        return self._st.pack(*a)

    def unpack(self, buf):
        return self._st.unpack(buf)

    def pack_into(self, buf, off, *vals):
        if isinstance(buf, TornBuffer):
            buf._store(off, self._st.pack(*vals))
        else:
            self._st.pack_into(buf, off, *vals)

    def unpack_from(self, buf, off=0):
        if isinstance(buf, TornBuffer):
            return self._st.unpack_from(buf.data, off)
        return self._st.unpack_from(buf, off)


class _ModStructProxy:
    """``struct`` module stand-in for bare struct.pack_into /
    struct.unpack_from call sites (shmring's crc word)."""

    Struct = _struct.Struct
    pack = staticmethod(_struct.pack)
    unpack = staticmethod(_struct.unpack)
    calcsize = staticmethod(_struct.calcsize)

    @staticmethod
    def pack_into(fmt, buf, off, *vals):
        if isinstance(buf, TornBuffer):
            buf._store(off, _struct.pack(fmt, *vals))
        else:
            _struct.pack_into(fmt, buf, off, *vals)

    @staticmethod
    def unpack_from(fmt, buf, off=0):
        if isinstance(buf, TornBuffer):
            return _struct.unpack_from(fmt, buf.data, off)
        return _struct.unpack_from(fmt, buf, off)


class _FakeTime:
    """Deterministic ``time`` module stand-in for the subject module:
    journals must be byte-identical across runs."""

    def __init__(self, t: float = 1_000_000.0):
        self.t = t

    def time(self) -> float:
        self.t += 0.001
        return self.t

    def monotonic(self) -> float:
        self.t += 0.001
        return self.t

    def monotonic_ns(self) -> int:
        self.t += 0.001
        return int(self.t * 1e9)

    def sleep(self, _dt) -> None:
        pass


@contextlib.contextmanager
def _patched(mod, **attrs):
    prev = {k: getattr(mod, k) for k in attrs}
    for k, v in attrs.items():
        setattr(mod, k, v)
    try:
        yield
    finally:
        for k, v in prev.items():
            setattr(mod, k, v)


def _proxied_structs(mod, names):
    return _patched(mod, **{n: _StructProxy(getattr(mod, n))
                            for n in names})


def crash_states(initial: bytes, journal):
    """Yield (trace, state_bytes, complete) for every crash point:
    after each completed store and after every byte prefix of each
    multi-byte store. An aligned 4-byte store (the commit word) is
    atomic — all-or-nothing; everything longer tears bytewise. The
    final yielded state has every store applied (complete=True)."""
    buf = bytearray(initial)
    applied: list = []
    yield "(no stores applied)", bytes(buf), False
    for k, (off, data) in enumerate(journal):
        label = f"store#{k}@+{off}x{len(data)}"
        atomic = len(data) == 4 and off % 4 == 0
        if not atomic:
            for j in range(1, len(data)):
                torn = bytearray(buf)
                torn[off:off + j] = data[:j]
                yield (" -> ".join(
                    applied + [f"{label} torn at {j}/{len(data)}B"]),
                    bytes(torn), False)
        buf[off:off + len(data)] = data
        applied.append(label)
        yield (" -> ".join(applied), bytes(buf),
               k == len(journal) - 1)


def _verify_states(base, journal, verify, max_schedules):
    """Run verify(state, complete) -> detail|None over every crash
    state. Returns (failures, n_schedules, exhausted)."""
    failures: list = []
    n = 0
    exhausted = True
    for trace, state, complete in crash_states(base, journal):
        if n >= max_schedules:
            exhausted = False
            break
        n += 1
        detail = verify(state, complete)
        if detail is not None:
            failures.append((
                "commit-liveness" if complete
                else "old-value-or-refusal", trace, detail))
    if not journal:
        failures.append((
            "commit-liveness", "(no stores applied)",
            "the writer stored nothing — nothing was published"))
    return failures, n, exhausted


# -- doctored writers (broken-protocol detection hooks) ---------------


def doctored_flightrec_commit_first(rec) -> None:
    """The classic bug: one forward memcpy, commit/seq word FIRST.
    run_product('torn-flightrec', writer=...) with this writer must
    produce a counterexample — tests/ci pin that the harness detects
    broken protocols, not just blesses working ones."""
    from language_detector_tpu import flightrec as fr
    payload = json.dumps({"ev": "ev", "k": 9},
                         separators=(",", ":")).encode()
    rec._seq += 1
    seq = rec._seq
    off = fr.FILE_HDR.size + ((seq - 1) % rec.slots) * rec.slot_bytes
    rec.mm[off:off + fr.SLOT_HDR.size] = fr.SLOT_HDR.pack(
        seq & 0xFFFFFFFF, len(payload), 0.0)
    rec.mm[off + fr.SLOT_HDR.size:
           off + fr.SLOT_HDR.size + len(payload)] = payload


def doctored_capture_commit_first(writer, rec) -> None:
    """Capture-ring variant of the same bug: commit word before the
    record body."""
    from language_detector_tpu import capture as cap
    payload = cap.RECORD.pack(*rec)
    i = writer._seq
    off = cap.FILE_HDR.size + i * cap.SLOT_BYTES
    writer.mm[off:off + cap.COMMIT.size] = cap.COMMIT.pack(i + 1)
    writer.mm[off + cap.COMMIT.size:off + cap.SLOT_BYTES] = payload
    writer._seq = i + 1


# -- products ---------------------------------------------------------


def _run_flightrec(writer=None, max_schedules=MAX_SCHEDULES):
    """Wrap the 8-slot ring, then crash-journal the 9th emit — the
    wrap is the hard case: the overwritten slot already holds a
    COMMITTED record from the previous lap."""
    from language_detector_tpu import flightrec as fr
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "fr.ring")
        with _patched(fr, time=_FakeTime()):
            rec = fr.FlightRecorder(path, slots=8, slot_bytes=96)
            real_mm = rec.mm
            try:
                for i in range(1, 9):
                    rec.emit("ev", {"k": i})
                base = bytes(real_mm[:])
                buf = TornBuffer(base)
                rec.mm = buf
                if writer is None:
                    rec.emit("ev", {"k": 9})
                else:
                    writer(rec)
            finally:
                rec.mm = real_mm
                real_mm.close()
        allowed = {(i, i) for i in range(1, 10)}
        probe = os.path.join(td, "probe.ring")

        def verify(state, complete):
            Path(probe).write_bytes(state)
            events = fr.read_ring(probe)["events"]
            seen = {(e["seq"], e.get("k")) for e in events}
            torn = sorted(seen - allowed)
            if torn:
                return (f"reader accepted torn record(s) "
                        f"{torn} (seq/payload mixed across laps)")
            if complete and (9, 9) not in seen:
                return "fully applied write never became readable"
            return None

        return _verify_states(base, buf.journal, verify, max_schedules)


def _mk_capture_record(i: int) -> tuple:
    """A RECORD tuple whose docs field identifies the record."""
    return (i, i, 0, i, 0.0, 1.0, 0.1, 0.2, 0.3, 200, 1, 0, 0, 0)


def _run_capture(writer=None, max_schedules=MAX_SCHEDULES):
    """Commit one record, then crash-journal the append of a second."""
    from language_detector_tpu import capture as cap
    with tempfile.TemporaryDirectory() as td:
        w = cap.CaptureWriter(td, ring_records=16, sample=1.0,
                              max_segments=2, seed=0)
        real_mm = w.mm
        try:
            w.append(_mk_capture_record(1))
            base = bytes(real_mm[:])
            buf = TornBuffer(base)
            w.mm = buf
            if writer is None:
                w.append(_mk_capture_record(2))
            else:
                writer(w, _mk_capture_record(2))
        finally:
            w.mm = real_mm
            real_mm.close()
        probe = os.path.join(td, "probe.ring")

        def verify(state, complete):
            Path(probe).write_bytes(state)
            docs = [r["docs"] for r in cap._read_file(probe)]
            if complete:
                if docs != [1, 2]:
                    return (f"fully applied append reads back as "
                            f"{docs}, want [1, 2]")
                return None
            if any(d not in (1, 2) for d in docs) or docs[:1] != [1]:
                return (f"reader accepted a torn record: docs={docs} "
                        f"(committed prefix is [1])")
            return None

        return _verify_states(base, buf.journal, verify, max_schedules)


def _run_sharedcache(writer=None, max_schedules=MAX_SCHEDULES):
    """Crash-journal a seqlock put into a cache that already holds an
    unrelated committed key; the reader must keep returning the old
    key's value and never a torn view of the new one."""
    from language_detector_tpu.service import sharedcache as sc
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "cache.shm")
        cache = sc.SharedResultCache(
            path, sc.HEADER_BYTES + 8 * sc.SLOT_BYTES)
        real_mm = cache._mm
        try:
            cache.put("k0", "old")
            base = bytes(real_mm[:])
            with _proxied_structs(sc, ("_U32", "_SLOT_HDR")):
                buf = TornBuffer(base)
                cache._mm = buf
                if writer is None:
                    cache.put("k1", "v1")
                else:
                    writer(cache)

                def verify(state, complete):
                    cache._mm = TornBuffer(state)
                    v0 = cache.get("k0")
                    v1 = cache.get("k1")
                    if v0 != "old":
                        return (f"committed neighbour key was "
                                f"disturbed: get('k0') -> {v0!r}")
                    if complete:
                        if v1 != "v1":
                            return (f"fully applied put never became "
                                    f"readable: get('k1') -> {v1!r}")
                    elif v1 not in (None, "v1"):
                        return (f"reader accepted a torn value: "
                                f"get('k1') -> {v1!r}")
                    return None

                out = _verify_states(base, buf.journal, verify,
                                     max_schedules)
        finally:
            cache._mm = real_mm
            cache.close()
        return out


def _run_shmring(writer=None, max_schedules=MAX_SCHEDULES):
    """Crash-journal a client submit; a sweep-side reader must never
    observe READY with an unsettled header or payload."""
    from language_detector_tpu.service import shmring as sm
    body = b'{"texts":["torn-write probe"]}'
    with tempfile.TemporaryDirectory() as td:
        with _patched(sm, time=_FakeTime(), struct=_ModStructProxy()), \
                _proxied_structs(sm, ("RING_HDR", "SLOT_HDR")):
            client = sm.RingClient(td, slots=2, slot_bytes=4096)
            rf = client.rf
            real_mm = rf.mm
            try:
                rf.set_generation(1, os.getpid())
                base = bytes(real_mm[:])
                buf = TornBuffer(base)
                rf.mm = buf
                if writer is None:
                    assert client.submit(body) == 0
                else:
                    writer(client, body)
            finally:
                rf.mm = real_mm
                rf.close()
        probe = os.path.join(td, "probe.ring")
        final = None

        def read_state(state):
            Path(probe).write_bytes(state)
            prf = sm.RingFile(probe)
            try:
                hdr = prf.read_slot(0)
                payload = bytes(prf.mm[prf.payload_off(0):
                                       prf.payload_off(0) + len(body)])
            finally:
                prf.close()
            return hdr, payload

        # the fully-applied state defines the one legal READY header
        full = bytearray(base)
        for off, data in buf.journal:
            full[off:off + len(data)] = data
        final = read_state(bytes(full))

        def verify(state, complete):
            hdr, payload = read_state(state)
            st = hdr[0]
            if complete:
                if st != sm.SLOT_READY or payload != body:
                    return (f"fully applied submit not readable: "
                            f"state={st} payload={payload!r}")
                return None
            if st == sm.SLOT_READY and (hdr, payload) != final:
                return (f"reader observed READY over an unsettled "
                        f"slot: header={hdr} payload={payload!r}")
            return None

        return _verify_states(base, buf.journal, verify, max_schedules)


# (name, subject file, runner) — mirrors model_check.PRODUCTS shape
TORN_PRODUCTS = (
    ("torn-flightrec", "language_detector_tpu/flightrec.py",
     _run_flightrec),
    ("torn-capture", "language_detector_tpu/capture.py",
     _run_capture),
    ("torn-sharedcache", "language_detector_tpu/service/sharedcache.py",
     _run_sharedcache),
    ("torn-shmring", "language_detector_tpu/service/shmring.py",
     _run_shmring),
)


def run_product(name, writer=None, max_schedules=MAX_SCHEDULES):
    """Explore one named product; returns (failures, n_schedules,
    exhausted). `writer` replaces the real writer — the broken-protocol
    detection hook for tests and the ci torn-write smoke."""
    for pname, _path, runner in TORN_PRODUCTS:
        if pname == name:
            return runner(writer=writer, max_schedules=max_schedules)
    raise KeyError(name)


def check(root=None, files=None, products=TORN_PRODUCTS):
    """Run every torn-write product. `files` (repo-relative paths)
    restricts to products whose subject module is listed. Violations
    carry the minimal store-schedule trace of the failing crash
    state."""
    from language_detector_tpu import faults
    _ = Path(root) if root else _REPO
    if files is not None:
        keep = {str(f) for f in files}
        products = [p for p in products if p[1] in keep]
    violations: list = []
    prev = faults.ACTIVE
    try:
        faults.configure(None)
        for name, path, runner in products:
            failures, n, exhausted = runner()
            if not exhausted:
                violations.append(Violation(
                    "torn-write-invariant", path, 1,
                    f"[{name}] crash-schedule exploration hit the "
                    f"safety cap after {n} schedules without closing"))
            for inv, trace, detail in failures:
                violations.append(Violation(
                    "torn-write-invariant", path, 1,
                    f"[{name}] invariant {inv} violated at crash "
                    f"point {trace}: {detail}"))
    finally:
        faults.ACTIVE = prev
    return violations, 0
