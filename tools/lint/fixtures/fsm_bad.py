"""Seeded fsm-conformance violations (tests/test_lint.py).

The fixture machine (declared in the test, not fsm_registry.MACHINES):

    states  IDLE=0, RUN=1, DONE=2, HALT=3
    initial IDLE
    table   IDLE->RUN, RUN->DONE, RUN->IDLE, DONE->HALT

Expected: 3 fsm-undeclared-transition (wrong initial, undeclared
guarded write, non-constant assignment) and 3 fsm-dead-transition
(RUN->DONE, RUN->IDLE, DONE->HALT are declared but never written).
"""

IDLE, RUN, DONE, HALT = 0, 1, 2, 3


class Widget:
    def __init__(self):
        self.count = 0
        # wrong initial state: the table declares IDLE
        self._state = RUN  # fsm-undeclared-transition

    def start(self):
        if self._state == IDLE:
            self._state = RUN  # legal: IDLE->RUN declared

    def finish(self):
        if self._state == DONE:
            # DONE -> RUN is not in the table
            self._state = RUN  # fsm-undeclared-transition

    def assign_dynamic(self, nxt):
        if self._state == RUN:
            self._state = nxt  # fsm-undeclared-transition (non-const)
