"""Fixture: a stand-in faults module for the fault-registry analyzer
(passed via faults_rel)."""

FAULT_POINTS: dict = {
    "fix_used": "hit and documented",
    "fix_unused": "declared, never hit",
    "fix_undoc": "declared, hit, absent from the docs table",
}
