"""Lock construction with an optional debug-mode order watchdog.

Every lock in the serving stack is built through make_lock(name). With
LDT_LOCK_DEBUG unset (production) that is a plain threading.Lock — zero
overhead, nothing recorded. With LDT_LOCK_DEBUG=1 (CI runs the whole
test suite this way) each lock is wrapped so the process-wide watchdog
records the acquisition-order graph BY LOCK NAME and raises
LockOrderInversion the moment any thread:

  - acquires lock B while holding lock A after some thread previously
    established the opposite A-after-B order (a cycle in the order
    graph = a latent deadlock, even if this run never interleaved into
    one); or
  - re-acquires the exact non-reentrant lock instance it already holds
    (guaranteed self-deadlock).

Names are stable per lock ROLE (e.g. "admission.controller",
"telemetry.histogram"), not per instance: two histograms are many
instances of one role, and an inversion between roles is the bug the
watchdog exists to catch. Nested acquisition of two same-name instances
is deliberately not ordered (the stack has no such pattern; adding one
requires a new role name so the graph sees it).

The declared lock-ownership map lives in tools/lint/ownership.py and is
checked statically by `python -m tools.lint` (rule lock-discipline);
this module is the runtime half of that contract.
"""
from __future__ import annotations

import threading

from . import knobs


class LockOrderInversion(RuntimeError):
    """Two locks were acquired in opposite orders (latent deadlock), or
    a thread re-acquired a non-reentrant lock it already holds."""


class _Watchdog:
    """Process-wide acquisition-order graph + per-thread held stack."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._edges: dict[str, set[str]] = {}  # name -> names acquired
        # while it was held (the recorded legal order)
        self._tls = threading.local()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def _reaches(self, src: str, dst: str) -> bool:
        seen: set[str] = set()
        frontier = [src]
        while frontier:
            n = frontier.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            frontier.extend(self._edges.get(n, ()))
        return False

    def before_acquire(self, lock: "DebugLock") -> None:
        st = self._stack()
        for held in st:
            if held is lock:
                raise LockOrderInversion(
                    f"re-acquiring non-reentrant lock {lock.name!r} "
                    "already held by this thread (self-deadlock)")
        if not st:
            return
        a, b = st[-1].name, lock.name
        if a == b:
            return
        with self._mu:
            fwd = self._edges.setdefault(a, set())
            if b in fwd:
                return
            if self._reaches(b, a):
                raise LockOrderInversion(
                    f"lock-order inversion: acquiring {b!r} while "
                    f"holding {a!r}, but the opposite order "
                    f"({b!r} -> ... -> {a!r}) was previously recorded")
            fwd.add(b)

    def after_acquire(self, lock: "DebugLock") -> None:
        self._stack().append(lock)

    def after_release(self, lock: "DebugLock") -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lock:
                del st[i]
                return

    def edges(self) -> dict[str, set[str]]:
        """Copy of the recorded order graph (tests/debugging)."""
        with self._mu:
            return {k: set(v) for k, v in self._edges.items()}

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()


class DebugLock:
    """Order-checked wrapper over threading.Lock, same interface for
    the `with`-statement call sites the stack uses."""

    def __init__(self, name: str, watchdog: _Watchdog) -> None:
        self.name = name
        self._inner = threading.Lock()
        self._dog = watchdog

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        self._dog.before_acquire(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._dog.after_acquire(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._dog.after_release(self)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<DebugLock {self.name!r} {self._inner!r}>"


WATCHDOG = _Watchdog()


def make_lock(name: str) -> "threading.Lock | DebugLock":
    """The stack's lock constructor. `name` identifies the lock's ROLE
    for the debug order graph; with LDT_LOCK_DEBUG off it is ignored
    and a plain threading.Lock returns."""
    if knobs.get_bool("LDT_LOCK_DEBUG"):
        return DebugLock(name, WATCHDOG)
    return threading.Lock()
