"""AOT bundle tests (round 16, language_detector_tpu/aot.py).

Covers the boot-hot contract end to end: a compiling process exports
every dispatched tier into the bundle, a FRESH process loads the
executables (no compile) and answers bit-identically; every identity
field (table digest, jax version, backend, kernel mode, tier shape)
refuses loudly on mismatch; a corrupt bundle entry is refused by the
CRC (driven through the `aot_load` fault seam's `corrupt` rule); and
LDT_AOT_REQUIRE escalates refusal to the typed AotError. Also pins the
satellite: LDT_COMPILE_CACHE_DIR and LDT_AOT_DIR are *created* (with a
structured log), never silently disabled, when they don't exist yet.
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from language_detector_tpu import aot, faults, native

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native packer unavailable")

# TINY_BATCH_C_PATH (=64) sends small flag-less batches down the all-C
# pipeline without ever dispatching — AOT needs real device dispatches,
# so the corpus is >64 docs and non-ASCII (the C scalar path would
# otherwise still answer everything before a wire is packed).
_SAMPLES = [
    "Привет, как дела? Это тестовый документ на русском языке "
    "про погоду в Москве и планы на выходные дни.",
    "Καλημέρα σας, αυτό είναι ένα δοκιμαστικό έγγραφο στα ελληνικά "
    "για τον καιρό και τις διακοπές του καλοκαιριού.",
    "こんにちは、これは日本語のテスト文書です。今日の天気と週末の"
    "予定について話しましょう。",
    "Bonjour, ceci est un document de test en français à propos de "
    "la météo et des vacances d'été à la montagne.",
    "Hallo, dies ist ein deutsches Testdokument über das Wetter und "
    "den bevorstehenden Urlaub an der Ostsee.",
]


def _docs(n=200):
    return [_SAMPLES[i % len(_SAMPLES)] for i in range(n)]


def _engine(env: dict):
    """Engine constructed under `env` (knobs read the environment at
    construction, so env must bracket the constructor)."""
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        from language_detector_tpu.models.ngram import NgramBatchEngine
        return NgramBatchEngine()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# child for the fresh-process tests: detect the corpus, dump codes and
# the AOT store's counters. The persistent jit cache keeps the compile
# leg of the comparison fast; the AOT leg must not compile at all.
_CHILD = """
import json, sys
from language_detector_tpu import enable_jit_cache
enable_jit_cache()
from language_detector_tpu.models.ngram import NgramBatchEngine
docs = json.load(open(sys.argv[1]))
eng = NgramBatchEngine()
codes = eng.detect_codes(docs, batch_size=4096)
out = {"codes": codes,
       "dispatches": eng.stats["device_dispatches"],
       "aot": eng._aot.stats() if eng._aot is not None else None}
json.dump(out, open(sys.argv[2], "w"))
"""


def _run_child(docs, bundle_dir, tmp_path, tag):
    docs_file = tmp_path / f"docs-{tag}.json"
    out_file = tmp_path / f"out-{tag}.json"
    docs_file.write_text(json.dumps(docs))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               LDT_AOT_DIR=str(bundle_dir))
    r = subprocess.run(
        [sys.executable, "-c", _CHILD, str(docs_file), str(out_file)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-4000:]
    return json.loads(out_file.read_text())


@pytest.fixture(scope="module")
def bundle(tmp_path_factory):
    """A populated bundle: an in-process engine compiles + exports the
    corpus's tier shapes. Returns (dir, engine) for store-level tests."""
    d = tmp_path_factory.mktemp("aot-bundle")
    eng = _engine({"LDT_AOT_DIR": str(d)})
    assert eng._aot is not None
    eng.detect_codes(_docs(), batch_size=4096)
    assert eng.stats["device_dispatches"] > 0, \
        "corpus never dispatched — AOT has nothing to export"
    assert eng._aot.stats()["exports"] > 0
    assert list(Path(d).glob("*.ldtx"))
    return d, eng


def _fresh_store(bundle, **overrides):
    d, eng = bundle
    st = eng._aot
    kw = {"directory": str(d), "digest": st.digest,
          "backend": st.backend, "kernel_mode": st.kernel_mode,
          "require": False}
    kw.update(overrides)
    return aot.AotStore(**kw)


# -- happy path --------------------------------------------------------------


def test_fresh_process_export_then_load_bit_identity(tmp_path):
    docs = _docs()
    bundle_dir = tmp_path / "bundle"
    first = _run_child(docs, bundle_dir, tmp_path, "export")
    assert first["dispatches"] > 0
    assert first["aot"]["exports"] > 0, first["aot"]
    second = _run_child(docs, bundle_dir, tmp_path, "load")
    assert second["aot"]["loads"] > 0, second["aot"]
    assert second["aot"]["refusals"] == 0, second["aot"]
    # the AOT-loaded executables answer bit-identically to the
    # compile-path process that wrote them
    assert second["codes"] == first["codes"]


def test_same_process_second_engine_loads(bundle):
    d, eng = bundle
    eng2 = _engine({"LDT_AOT_DIR": str(d)})
    codes = eng2.detect_codes(_docs(), batch_size=4096)
    st = eng2._aot.stats()
    assert st["loads"] > 0 and st["refusals"] == 0, st
    assert codes == eng.detect_codes(_docs(), batch_size=4096)


def test_preload_deserializes_every_entry(bundle):
    store = _fresh_store(bundle)
    live = store.preload()
    assert live == len(list(Path(bundle[0]).glob("*.ldtx")))
    assert store.stats()["loads"] == live
    # a second preload is a no-op: everything is already memoized
    assert store.preload() == 0


# -- refusal matrix ----------------------------------------------------------


def test_digest_mismatch_refused(bundle):
    store = _fresh_store(bundle, digest="0" * 16)
    assert store.preload() == 0
    assert store.stats()["refusals"] > 0


def test_backend_mismatch_refused(bundle):
    store = _fresh_store(bundle, backend="tpu-v9")
    assert store.preload() == 0
    assert store.stats()["refusals"] > 0


def test_jax_version_mismatch_refused(bundle, monkeypatch):
    monkeypatch.setattr(aot, "_jax_version", lambda: "0.0.0-test")
    store = _fresh_store(bundle)
    assert store.preload() == 0
    assert store.stats()["refusals"] > 0


def test_kernel_mismatch_refused(bundle, tmp_path):
    # repack one entry with a lying kernel field (valid CRC, so only
    # the meta cross-check can catch it) under the original filename
    d, eng = bundle
    src = sorted(Path(d).glob("*.ldtx"))[0]
    meta, hlo, xc = aot._unpack_entry(src.read_bytes())
    meta["kernel"] = "definitely-not-" + meta["kernel"]
    clone = tmp_path / src.name
    clone.write_bytes(aot._pack_entry(meta, hlo, xc))
    store = _fresh_store(bundle, directory=str(tmp_path))
    assert store.preload() == 0
    assert store.stats()["refusals"] > 0


def test_corrupt_entry_refused_via_fault_seam(bundle):
    faults.configure("aot_load:corrupt:seed=5")
    try:
        store = _fresh_store(bundle)
        assert store.preload() == 0
        assert store.stats()["refusals"] > 0
    finally:
        faults.configure(None)


def test_require_escalates_to_typed_error(bundle):
    store = _fresh_store(bundle, digest="f" * 16, require=True)
    with pytest.raises(aot.AotError):
        store.preload()


def test_refused_entry_falls_back_and_self_heals(bundle, tmp_path):
    # an engine pointed at a stale bundle (wrong digest in every entry)
    # must refuse, compile, and overwrite the entries with good ones
    d, _ = bundle
    stale = tmp_path / "stale"
    stale.mkdir()
    for src in Path(d).glob("*.ldtx"):
        meta, hlo, xc = aot._unpack_entry(src.read_bytes())
        meta["digest"] = "0" * 16
        (stale / src.name).write_bytes(aot._pack_entry(meta, hlo, xc))
    eng = _engine({"LDT_AOT_DIR": str(stale)})
    codes = eng.detect_codes(_docs(), batch_size=4096)
    assert codes == bundle[1].detect_codes(_docs(), batch_size=4096)
    st = eng._aot.stats()
    assert st["refusals"] > 0 and st["exports"] > 0, st
    # the overwritten entries now carry the live digest
    meta, _, _ = aot._unpack_entry(
        sorted(stale.glob("*.ldtx"))[0].read_bytes())
    assert meta["digest"] == eng._aot.digest


# -- satellite: cache/bundle dirs are created, never silently dropped --------


def test_compile_cache_dir_created_when_missing(monkeypatch, tmp_path):
    import jax
    target = tmp_path / "nested" / "compile-cache"
    assert not target.exists()
    old = jax.config.jax_compilation_cache_dir
    monkeypatch.setenv("LDT_COMPILE_CACHE_DIR", str(target))
    try:
        _engine({})
        assert target.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(target)
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def test_aot_dir_created_when_missing(bundle, tmp_path):
    target = tmp_path / "nested" / "aot-bundle"
    assert not target.exists()
    eng = _engine({"LDT_AOT_DIR": str(target)})
    assert eng._aot is not None
    assert target.is_dir()


def test_aot_off_without_knob():
    eng = _engine({})
    assert getattr(eng, "_aot", None) is None
