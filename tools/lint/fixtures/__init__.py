"""Seeded-violation fixtures for tests/test_lint.py.

Each file here is analyzed by passing it explicitly to an analyzer's
check(files=...) / check(ownership=...) — nothing in this directory is
ever scanned as part of the live tree (the analyzers scope to
language_detector_tpu/ and the declared ownership map). The files only
need to parse; they are never imported or executed.
"""
