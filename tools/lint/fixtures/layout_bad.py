"""Bad fixture: every layout-registry failure mode, seeded.

The test registry declares REC as "<IHH"/8 with writer write_rec and
reader read_rec, GONE as a registered Struct this module should carry,
and WORD as "<I"/4.
"""
import struct

REC = struct.Struct("<IHB")       # drift: registry pins "<IHH" (and
                                  # the import-time assert is missing)
WORD = struct.Struct("<I")
EXTRA = struct.Struct("<QQ")      # undeclared module-level Struct
assert WORD.size == 8             # drift: pins the wrong width


def write_rec(buf):
    # mismatch: the declared writer no longer packs REC — and the
    # inline format it packs instead is not a declared layout
    struct.pack_into("<ff", buf, 0, 1.0, 2.0)


def stray_writer(buf):
    WORD.pack_into(buf, 0, 1)     # mismatch: not a declared writer


def ad_hoc(n):
    return struct.Struct("<B")    # undeclared ad-hoc format
