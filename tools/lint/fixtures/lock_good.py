"""Fixture: the legal lock-discipline shapes — locked access, a
declared held method, and a documented lock-free attribute."""
import threading


class Gauge:
    def __init__(self):
        self._lock = threading.Lock()
        self.v = 0
        self.hint = 0

    def set(self, x):
        with self._lock:
            self._apply(x)

    def _apply(self, x):
        self.v = x          # held method: caller holds the lock

    def peek_hint(self):
        return self.hint    # declared lock-free
