"""Scriptable stand-in worker for the supervisor tests
(tests/test_supervisor.py). Behavior is driven by env vars so the
supervisor can run it with its normal `python -m <module>` spawn:

  FAKE_WORKER_EXIT       exit immediately with this code
  FAKE_WORKER_RECYCLE    path to a marker file: first run (no marker)
                         creates it and exits with RECYCLE_EXIT_CODE;
                         the restarted run sees the marker and exits 0
  FAKE_WORKER_CRASH_UNTIL  "path:N" — a run counter lives at path; each
                         run increments it and crashes (exit 9) until N
                         runs have crashed, then exits 0. Exercises the
                         supervisor's restart-on-crash backoff path.
  FAKE_WORKER_SIGFILE    install a SIGTERM/SIGINT handler that writes
                         the signal number to this path and exits 0;
                         the worker then waits (bounded) to be signaled

Every run prints one JSON line with the LDT_WORKER_GENERATION it was
handed, so tests can assert the supervisor numbers its children.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import time

from language_detector_tpu.service.recycle import RECYCLE_EXIT_CODE


def main() -> int:
    print(json.dumps({
        "fake_worker_generation":
            os.environ.get("LDT_WORKER_GENERATION", "unset"),
    }), flush=True)

    exit_code = os.environ.get("FAKE_WORKER_EXIT")
    if exit_code is not None:
        return int(exit_code)

    crash_until = os.environ.get("FAKE_WORKER_CRASH_UNTIL")
    if crash_until is not None:
        path, _, n = crash_until.rpartition(":")
        runs = 0
        if os.path.exists(path):
            with open(path) as f:
                runs = int(f.read() or "0")
        runs += 1
        with open(path, "w") as f:
            f.write(str(runs))
        return 9 if runs <= int(n) else 0

    marker = os.environ.get("FAKE_WORKER_RECYCLE")
    if marker is not None:
        if os.path.exists(marker):
            return 0  # second generation: a clean exit ends the loop
        with open(marker, "w") as f:
            f.write("recycled")
        return RECYCLE_EXIT_CODE

    sigfile = os.environ.get("FAKE_WORKER_SIGFILE")
    if sigfile is not None:
        def on_signal(signum, frame):
            with open(sigfile, "w") as f:
                f.write(str(signum))
            sys.exit(0)

        signal.signal(signal.SIGTERM, on_signal)
        signal.signal(signal.SIGINT, on_signal)
        # announce readiness so the test doesn't signal a worker that
        # has not installed its handler yet
        ready = sigfile + ".ready"
        with open(ready, "w") as f:
            f.write(str(os.getpid()))
        deadline = time.time() + 30
        while time.time() < deadline:
            time.sleep(0.05)
        return 3  # never signaled: fail loudly
    return 0


if __name__ == "__main__":
    sys.exit(main())
