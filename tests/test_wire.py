"""Wire fast-path conformance + unix-socket lane e2e.

The zero-copy scanner (wire.fast_parse_texts) must be indistinguishable
from the json.loads path: same parse results, same 400s, same response
BYTES, same metric increments — on both fronts. The adversarial corpus
below covers escapes, surrogate pairs, duplicate keys, nested/huge
bodies, raw control bytes, truncation at every interesting position and
trailing garbage; each case runs with LDT_WIRE_FASTPATH on and off and
the outcomes are compared, so a scanner bug shows up as a diff against
the stdlib, not against a hand-written expectation.

The UDS tests pin the frame contract: byte-identity with the TCP
payload, oversize -> 413 error frame + close, keep-alive buffer reuse
across growing frames, and drain-on-close finishing in-flight frames.
"""
from __future__ import annotations

import http.client
import json
import os
import socket
import struct
import tempfile
import threading
import time

import pytest

from language_detector_tpu.service import wire
from language_detector_tpu.service.server import (DetectorService,
                                                  make_server)

BIG_BODY = json.dumps(
    {"request": [{"text": f"document number {i} with some text"}
                 for i in range(1500)]}).encode()
LONG_DOC = json.dumps({"request": [{"text": "word " * 10000}]}).encode()

CASES = [
    b'{"request": [{"text": "hello world"}]}',
    b'{"request":[{"text":"compact"}]}',
    b'{ "request" : [ { "text" : "spaced" } ] }',
    b'{\n\t"request": [\r\n{"text": "ws"}\n]\n}',
    b'{"request": []}',
    b'{"request": [{"text": ""}]}',
    b'{"request": [{"text": "a"}, {"text": "b"}, {"text": "c"}]}',
    b'{"request": [{"text": "a"} , {"text": "b"}]}',
    # escapes / unicode: ensure_ascii bodies (every non-ASCII char
    # \uXXXX-escaped, the json.dumps default) and raw-UTF-8 bodies
    json.dumps({"request": [{"text": "café 中文"}]}
               ).encode(),
    json.dumps({"request": [{"text": "café 中文"}]},
               ensure_ascii=False).encode(),
    json.dumps({"request": [{"text": "emoji \U0001f600 end"}]}
               ).encode(),                       # surrogate-pair escape
    json.dumps({"request": [{"text": "emoji \U0001f600 end"}]},
               ensure_ascii=False).encode(),
    b'{"request": [{"text": "esc \\" q \\\\ b \\n nl \\u0041"}]}',
    b'{"request": [{"text": "ends in backslash \\\\"}]}',
    b'{"request": [{"text": "\\ud83d\\ude00"}]}',  # paired surrogates
    # shape deviations (fast path must bail; behavior via json.loads)
    b'{"request": [{"text": "dup", "text": "dup2"}]}',
    b'{"request": [{"text": "x", "extra": 1}]}',
    b'{"request": [{"other": "x"}]}',
    b'{"request": [{"text": 5}]}',
    b'{"request": [{"text": null}]}',
    b'{"request": [{"text": ["a"]}]}',
    b'{"request": [{"text": {"deep": {"er": 1}}}]}',
    b'{"request": ["nope"]}',
    b'{"request": [17]}',
    b'{"request": "nope"}',
    b'{"request": 5}',
    b'{"request": [{"text": NaN}]}',   # stdlib json accepts NaN
    b'{"other": []}',
    b'{}',
    b'[]',
    b'5',
    b'',
    # truncation at every interesting position + trailing garbage
    b'{"request": [{"text": "a"}]} trailing',
    b'{"request": [{"text": "a"}]',
    b'{"request": [{"text": "a"',
    b'{"request": [{"text": "unterminated',
    b'{"request": [{"text"',
    b'{"request": [{',
    b'{"request',
    b'{',
    b'not json{{',
    # raw control bytes inside a string literal are invalid JSON
    b'{"request": [{"text": "ctrl \x01 char"}]}',
    b'{"request": [{"text": "tab\ttab"}]}',
    json.dumps({"request": [{"text": "line sep"}]},
               ensure_ascii=False).encode(),     # legal raw U+2028
    # strip_extras interaction (mentions/links)
    b'{"request": [{"text": "hi @user see http://x.com now"}]}',
    BIG_BODY,
    LONG_DOC,
]


@pytest.fixture(scope="module")
def sync_server():
    svc = DetectorService(use_device=False, max_delay_ms=1.0)
    httpd, metricsd, svc = make_server(0, 0, service=svc)
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in (httpd, metricsd)]
    for t in threads:
        t.start()
    yield {"port": httpd.server_address[1], "svc": svc}
    httpd.shutdown()
    metricsd.shutdown()
    svc.batcher.close()


@pytest.fixture(scope="module")
def aio_server():
    """Asyncio front with the UDS lane enabled, mirroring the
    test_service aio pattern."""
    import asyncio
    import queue as _q

    from language_detector_tpu.service.aioserver import serve

    uds_path = os.path.join(tempfile.mkdtemp(prefix="ldt-wire-"),
                            "aio.sock")
    old = os.environ.get("LDT_UNIX_SOCKET")
    os.environ["LDT_UNIX_SOCKET"] = uds_path
    ports_q: _q.Queue = _q.Queue()
    loop_holder = {}

    def run_loop():
        async def main():
            loop_holder["loop"] = asyncio.get_running_loop()
            ready = asyncio.get_running_loop().create_future()
            svc = DetectorService(use_device=False, max_delay_ms=1.0,
                                  start_batcher=False)
            loop_holder["svc"] = svc
            task = asyncio.get_running_loop().create_task(
                serve(0, 0, svc=svc, ready=ready))
            ports_q.put(await ready)
            try:
                await task
            except asyncio.CancelledError:
                pass
        try:
            asyncio.run(main())
        except RuntimeError:
            pass  # loop.stop() teardown ends the run mid-await

    t = threading.Thread(target=run_loop, daemon=True)
    t.start()
    port, _ = ports_q.get(timeout=30)
    yield {"port": port, "uds_path": uds_path,
           "svc": loop_holder["svc"]}
    loop = loop_holder.get("loop")
    if loop is not None:
        loop.call_soon_threadsafe(loop.stop)
    if old is None:
        os.environ.pop("LDT_UNIX_SOCKET", None)
    else:
        os.environ["LDT_UNIX_SOCKET"] = old


def _post_raw(port: int, body: bytes, headers: dict | None = None):
    """(status, payload bytes) for POST / — raw bytes, no JSON parse."""
    conn = http.client.HTTPConnection("127.0.0.1", port)
    try:
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        conn.request("POST", "/", body, hdrs)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _normalize(pre, err):
    if err is not None:
        return ("err", err[0], err[1])
    texts, slots, responses, status = pre
    return ("ok", list(texts), list(slots), list(responses), status)


def test_function_level_parity(monkeypatch):
    """parse_request with the scanner on vs off: identical results AND
    identical metric increments for every adversarial body."""
    svc = DetectorService(use_device=False, start_batcher=False)
    for body in CASES:
        outcomes = []
        for flag in ("1", "0"):
            monkeypatch.setenv("LDT_WIRE_FASTPATH", flag)
            before = (dict(svc.metrics.counters),
                      dict(svc.metrics.objects))
            try:
                pre, err = wire.parse_request(svc, "application/json",
                                              body)
                result = _normalize(pre, err)
            except Exception as e:  # noqa: BLE001 - e.g. bad UTF-8
                result = ("raise", type(e).__name__)
            after = (dict(svc.metrics.counters), dict(svc.metrics.objects))
            deltas = tuple(
                tuple(sorted((k, a[k] - b.get(k, 0))
                             for k in a)) for a, b in zip(after, before))
            outcomes.append((result, deltas))
        assert outcomes[0] == outcomes[1], body[:120]


def test_fast_parse_hits_common_shapes():
    """The shapes real clients send (incl. ensure_ascii escapes) must
    take the scanner, not the fallback — the >0.9 hit-rate floor in ci
    depends on it."""
    hits = [
        json.dumps({"request": [{"text": t}]}).encode()
        for t in ("plain ascii", "café 中文",
                  "emoji \U0001f600", 'quote " inside', "line\nbreak")
    ] + [BIG_BODY, LONG_DOC, b'{"request": []}']
    for body in hits:
        texts = wire.fast_parse_texts(body)
        assert texts is not None, body[:80]
        assert texts == [d["text"] for d in json.loads(body)["request"]]


def test_fast_parse_rejects_deviations():
    for body in (b'{"request": [{"text": "x", "e": 1}]}',
                 b'{"request": [{"text": 5}]}',
                 b'{"request": [{"text": "a"}]} junk',
                 b'{"request": [{"text": "a"}]',
                 b'{"request": [{"text": "ctrl\x01"}]}',
                 b'\xff\xfe broken utf8'):
        assert wire.fast_parse_texts(body) is None, body[:80]


def test_e2e_byte_identity_both_fronts(sync_server, aio_server,
                                       monkeypatch):
    """For every adversarial body: sync-fast, sync-slow, aio-fast and
    aio-slow answer the same (status, payload BYTES)."""
    for body in CASES:
        seen = []
        for flag in ("1", "0"):
            monkeypatch.setenv("LDT_WIRE_FASTPATH", flag)
            seen.append(_post_raw(sync_server["port"], body))
            seen.append(_post_raw(aio_server["port"], body))
        assert len(set(seen)) == 1, (body[:120], [s[0] for s in seen])


def _uds_request(sock, body: bytes, **frame_kw):
    """Send one frame (v1, or v2 when tenant/deadline_ms/priority are
    passed through to wire.pack_frame) and read the response."""
    sock.sendall(wire.pack_frame(bytes(body), **frame_kw))
    hdr = b""
    while len(hdr) < 6:
        chunk = sock.recv(6 - len(hdr))
        if not chunk:
            return None, None
        hdr += chunk
    length, status = struct.unpack("!IH", hdr)
    payload = b""
    while len(payload) < length:
        chunk = sock.recv(length - len(payload))
        if not chunk:
            return status, payload
        payload += chunk
    return status, payload


def test_uds_sync_identity_and_keepalive(sync_server):
    """Sync-front UDS lane: responses byte-identical to TCP for the
    same bodies, over ONE keep-alive connection with growing then
    shrinking frames (exercises the reused grow-only buffer)."""
    svc = sync_server["svc"]
    path = os.path.join(tempfile.mkdtemp(prefix="ldt-wire-"), "s.sock")
    uds = wire.UnixFrameServer(svc, path)
    uds.start()
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        bodies = [
            b'{"request": [{"text": "uds hello"}]}',
            BIG_BODY,                        # grows the buffer
            b'{"request": [{"text": "small again"}]}',
            b'not json',                     # error frame, conn stays up
            b'{"request": [{"other": 1}]}',  # per-item error, 400
        ]
        for body in bodies:
            ustatus, upayload = _uds_request(s, body)
            tstatus, tpayload = _post_raw(sync_server["port"], body)
            assert (ustatus, upayload) == (tstatus, tpayload), body[:80]
        s.close()
    finally:
        uds.close()
    assert not os.path.exists(path)


def test_uds_oversize_answers_413_and_closes(sync_server):
    path = os.path.join(tempfile.mkdtemp(prefix="ldt-wire-"), "o.sock")
    uds = wire.UnixFrameServer(sync_server["svc"], path)
    uds.start()
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.sendall(struct.pack("!I", wire.BODY_LIMIT_BYTES + 1))
        hdr = s.recv(6)
        length, status = struct.unpack("!IH", hdr)
        assert status == 413
        body = s.recv(length)
        assert body == wire.OVERSIZE_BODY
        assert json.loads(body)["error"].startswith("Request body")
        # server closed its side: next read is EOF
        assert s.recv(1) == b""
        s.close()
    finally:
        uds.close()


def test_uds_drain_finishes_inflight(sync_server):
    """close(drain_sec) must let an in-flight frame answer before the
    connection is torn down — the SIGTERM drain contract."""
    svc = sync_server["svc"]
    release = threading.Event()

    def slow_detect(texts, trace=None):
        release.wait(5.0)
        return ["en"] * len(texts)

    path = os.path.join(tempfile.mkdtemp(prefix="ldt-wire-"), "d.sock")
    uds = wire.UnixFrameServer(svc, path, detect=slow_detect)
    uds.start()
    got = {}

    def client():
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        got["resp"] = _uds_request(s, b'{"request": [{"text": "x"}]}')
        s.close()

    t = threading.Thread(target=client, daemon=True)
    t.start()
    deadline = time.monotonic() + 5.0
    while uds.inflight() == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert uds.inflight() == 1

    def closer():
        time.sleep(0.1)
        release.set()

    threading.Thread(target=closer, daemon=True).start()
    uds.close(drain_sec=5.0)     # blocks until the frame resolves
    t.join(timeout=5.0)
    status, payload = got["resp"]
    assert status == 200
    assert json.loads(payload)["response"][0]["iso6391code"] == "en"


def test_uds_aio_identity_and_oversize(aio_server):
    """Asyncio front's UDS lane: byte-identity with its TCP responses
    and the oversize error frame."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(aio_server["uds_path"])
    for body in (b'{"request": [{"text": "aio uds"}]}',
                 b'{"request": [{"text": "\\u4e2d\\u6587"}]}',
                 b'broken'):
        ustatus, upayload = _uds_request(s, body)
        tstatus, tpayload = _post_raw(aio_server["port"], body)
        assert (ustatus, upayload) == (tstatus, tpayload), body[:80]
    s.close()
    # oversize: 413 frame then close
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(aio_server["uds_path"])
    s.sendall(struct.pack("!I", wire.BODY_LIMIT_BYTES + 1))
    hdr = s.recv(6)
    length, status = struct.unpack("!IH", hdr)
    assert status == 413 and s.recv(length) == wire.OVERSIZE_BODY
    assert s.recv(1) == b""
    s.close()


# -- v2 frames: tenant / deadline / priority parity -------------------------


def test_pack_frame_v1_byte_compat_and_v2_roundtrip():
    body = b'{"request": []}'
    # no admission fields -> exactly the legacy v1 bytes
    assert wire.pack_frame(body) == struct.pack("!I", len(body)) + body
    # any field -> v2: MSB flag, ext header, tenant bytes, body
    f = wire.pack_frame(body, tenant="acme", deadline_ms=1500,
                        priority=True)
    (word,) = wire.FRAME_HEADER.unpack(f[:4])
    assert word & wire.FRAME_V2_FLAG
    assert word & ~wire.FRAME_V2_FLAG == len(body)
    flags, tlen, dl = wire.FRAME_EXT_HEADER.unpack(
        f[4:4 + wire.FRAME_EXT_HEADER.size])
    assert flags & wire.FRAME_PRIORITY and dl == 1500
    off = 4 + wire.FRAME_EXT_HEADER.size
    assert f[off:off + tlen] == b"acme"
    assert f[off + tlen:] == body
    # the 1 MB body cap keeps the flag bit unreachable for v1 clients
    assert wire.BODY_LIMIT_BYTES < wire.FRAME_V2_FLAG


def test_uds_v2_fields_reach_admission_sync(sync_server):
    """A v2 frame's ext fields drive the same admission inputs as the
    HTTP headers (priority flag, tenant id, deadline on the trace); a
    v1 frame on the SAME keep-alive connection keeps the legacy
    default-tenant behavior."""
    svc = sync_server["svc"]
    adm = svc.admission
    seen = []
    orig = adm.try_admit

    def spy(texts, priority=False, tenant=None):
        seen.append((priority, tenant))
        return orig(texts, priority=priority, tenant=tenant)

    traces = []

    def rec(texts, trace=None):
        traces.append((trace.tenant, trace.deadline))
        return ["en"] * len(texts)

    path = os.path.join(tempfile.mkdtemp(prefix="ldt-wire-"), "v2.sock")
    uds = wire.UnixFrameServer(svc, path, detect=rec)
    uds.start()
    adm.try_admit = spy
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        body = b'{"request": [{"text": "hello v2"}]}'
        status, _ = _uds_request(s, body, tenant="acme",
                                 deadline_ms=30000, priority=True)
        assert status == 200
        status2, _ = _uds_request(s, body)      # v1 on the same conn
        assert status2 == 200
        s.close()
    finally:
        adm.try_admit = orig
        uds.close()
    assert seen == [(True, "acme"), (False, None)]
    tenant, deadline = traces[0]
    assert tenant == "acme"
    assert deadline is not None
    assert 0 < deadline.remaining_ms() <= 30000
    assert traces[1] == ("default", None)


def test_uds_v2_tenant_quota_parity_sync(sync_server):
    """The satellite gap this closes: the UDS lane used to bypass
    per-tenant quotas. A v2 frame over quota now sheds with the SAME
    status and payload bytes as the HTTP front; a small v1 frame still
    serves."""
    svc = sync_server["svc"]
    c = svc.admission.config
    old = c.tenant_quota_docs
    c.tenant_quota_docs = 1
    path = os.path.join(tempfile.mkdtemp(prefix="ldt-wire-"), "q.sock")
    uds = wire.UnixFrameServer(svc, path)
    uds.start()
    try:
        over = json.dumps(
            {"request": [{"text": "a"}, {"text": "b"}]}).encode()
        tstatus, tpayload = _post_raw(sync_server["port"], over,
                                      headers={"X-LDT-Tenant": "hot"})
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        ustatus, upayload = _uds_request(s, over, tenant="hot")
        assert tstatus == ustatus == 429
        assert upayload == tpayload
        status, payload = _uds_request(
            s, b'{"request": [{"text": "one doc fits"}]}')
        assert status < 400          # served (maybe 203 unknown-lang)
        assert json.loads(payload)["response"][0]["iso6391code"]
        s.close()
    finally:
        c.tenant_quota_docs = old
        uds.close()


def test_uds_v2_tenant_quota_parity_aio(aio_server):
    """Same quota parity on the asyncio front's UDS lane: v2 429
    byte-identical to its TCP 429, v1 unaffected below quota."""
    c = aio_server["svc"].admission.config
    old = c.tenant_quota_docs
    c.tenant_quota_docs = 1
    try:
        over = json.dumps(
            {"request": [{"text": "a"}, {"text": "b"}]}).encode()
        tstatus, tpayload = _post_raw(aio_server["port"], over,
                                      headers={"X-LDT-Tenant": "hot"})
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(aio_server["uds_path"])
        ustatus, upayload = _uds_request(s, over, tenant="hot")
        assert tstatus == ustatus == 429
        assert upayload == tpayload
        status, payload = _uds_request(
            s, b'{"request": [{"text": "one doc fits"}]}')
        assert status < 400          # served (maybe 203 unknown-lang)
        assert json.loads(payload)["response"][0]["iso6391code"]
        s.close()
    finally:
        c.tenant_quota_docs = old


def test_fragment_cache_shared_shape(sync_server):
    """Both fronts share wire.FragmentCache; entries are the exact
    json.dumps bytes of the per-item object."""
    svc = sync_server["svc"]
    frag, name, unknown = svc._frag_cache.entry("en")
    assert frag == json.dumps(
        {"iso6391code": "en", "name": "English"}).encode()
    assert name == "English" and unknown is False
    frag, name, unknown = svc._frag_cache.entry("zz-bogus")
    assert name == "Unknown" and unknown is True
    assert b'"name": "Unknown"' in frag


def test_assemble_response_matches_join():
    frags = [b'{"a": 1}', b'{"b": 2}', b'{"c": 3}']
    assert b"".join(wire.assemble_response(frags)) == \
        b'{"response": [' + b", ".join(frags) + b']}'
    assert b"".join(wire.assemble_response([])) == b'{"response": []}'


def _recv_resp(s):
    hdr = b""
    while len(hdr) < 6:
        chunk = s.recv(6 - len(hdr))
        if not chunk:
            return None, None
        hdr += chunk
    length, status = struct.unpack("!IH", hdr)
    payload = b""
    while len(payload) < length:
        payload += s.recv(length - len(payload))
    return status, payload


def test_uds_slow_loris_sync_408(sync_server, monkeypatch):
    """Slow-loris guard on the threaded front's UDS lane: a stalled
    partial frame answers a 408 error frame and closes, while idle
    keep-alive BETWEEN frames stays unbounded and a prompt frame on
    the same settings still serves."""
    monkeypatch.setenv("LDT_FRAME_READ_TIMEOUT_SEC", "0.2")
    path = os.path.join(tempfile.mkdtemp(prefix="ldt-wire-"), "sl.sock")
    uds = wire.UnixFrameServer(sync_server["svc"], path)
    uds.start()
    try:
        # idle keep-alive longer than the budget: NOT a timeout (the
        # clock only arms once a frame's first byte arrives)
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.settimeout(10.0)
        time.sleep(0.4)
        status, payload = _uds_request(
            s, b'{"request": [{"text": "after idle"}]}')
        assert status < 400
        # now stall mid-header: 2 of 4 length bytes, then nothing
        s.sendall(b"\x00\x00")
        t0 = time.monotonic()
        status, payload = _recv_resp(s)
        assert status == 408
        assert payload == wire.TIMEOUT_BODY
        assert "timed out" in json.loads(payload)["error"]
        assert time.monotonic() - t0 < 5.0   # the 0.2s budget, not 10s
        assert s.recv(1) == b""              # server closed its side
        s.close()
        # stall mid-BODY on a fresh connection
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.settimeout(10.0)
        s.sendall(struct.pack("!I", 100) + b'{"request"')
        status, payload = _recv_resp(s)
        assert status == 408 and payload == wire.TIMEOUT_BODY
        s.close()
    finally:
        uds.close()


# -- request-id correlation: echo, error-path parity, /tracez merge ---------


def _post_with_id(port: int, body: bytes, rid: str | None = None):
    """(status, echoed X-LDT-Request-Id, payload) for POST /."""
    conn = http.client.HTTPConnection("127.0.0.1", port)
    try:
        hdrs = {"Content-Type": "application/json"}
        if rid is not None:
            hdrs[wire.REQUEST_ID_HEADER] = rid
        conn.request("POST", "/", body, hdrs)
        resp = conn.getresponse()
        return (resp.status, resp.getheader(wire.REQUEST_ID_HEADER),
                resp.read())
    finally:
        conn.close()


def test_request_id_validation_and_generation():
    assert wire.clean_request_id("abc-123._X") == "abc-123._X"
    assert wire.clean_request_id(b"deadbeef") == "deadbeef"
    assert wire.clean_request_id("") is None
    assert wire.clean_request_id(None) is None
    assert wire.clean_request_id("bad id") is None
    assert wire.clean_request_id("x" * 65) is None
    assert wire.clean_request_id(b"\xff\xfe") is None
    rid = wire.gen_request_id()
    assert len(rid) == 8
    int(rid, 16)                 # 8 hex chars, the shm-carrier shape
    assert wire.clean_request_id(rid) == rid


def test_pack_frame_request_id_layout():
    body = b'{"request": []}'
    f = wire.pack_frame(body, request_id="r-1")
    (word,) = wire.FRAME_HEADER.unpack(f[:4])
    assert word & wire.FRAME_V2_FLAG
    flags, tlen, dl = wire.FRAME_EXT_HEADER.unpack(
        f[4:4 + wire.FRAME_EXT_HEADER.size])
    assert flags & wire.FRAME_REQID and tlen == 0 and dl == 0
    off = 4 + wire.FRAME_EXT_HEADER.size
    assert f[off] == 3 and f[off + 1:off + 4] == b"r-1"
    assert f[off + 4:] == body
    with pytest.raises(ValueError):
        wire.pack_frame(body, request_id="x" * 256)


def test_http_request_id_echo_both_fronts(sync_server, aio_server):
    """Success AND error responses carry the caller's id back; an
    absent or hostile id is replaced by a server-generated 8-hex one,
    never reflected."""
    ok = b'{"request": [{"text": "hello correlation"}]}'
    for port in (sync_server["port"], aio_server["port"]):
        status, rid, _ = _post_with_id(port, ok, rid="client.id-1")
        assert status < 400 and rid == "client.id-1"
        status, rid, _ = _post_with_id(port, b"not json",
                                       rid="err.id-2")
        assert status == 400 and rid == "err.id-2"
        status, rid, _ = _post_with_id(port, ok)
        assert status < 400 and len(rid) == 8
        int(rid, 16)
        status, rid, _ = _post_with_id(port, ok, rid="bad id!")
        assert status < 400 and rid != "bad id!" and len(rid) == 8


def test_http_413_echoes_request_id(sync_server, aio_server):
    big = b"x" * (wire.BODY_LIMIT_BYTES + 1)
    for port in (sync_server["port"], aio_server["port"]):
        status, rid, _ = _post_with_id(port, big, rid="too-big-1")
        assert status == 413 and rid == "too-big-1"


def _uds_oversize_reqid_frame() -> bytes:
    """A v2 frame declaring an over-limit body, carrying an id and no
    payload — exercises the 413-before-read echo."""
    return wire.FRAME_HEADER.pack(
        wire.FRAME_V2_FLAG | (wire.BODY_LIMIT_BYTES + 1)) \
        + wire.FRAME_EXT_HEADER.pack(wire.FRAME_REQID, 0, 0) \
        + bytes([4]) + b"big4"


def _uds_echo_checks(connect):
    body = b'{"request": [{"text": "uds correlation"}]}'
    s = connect()
    try:
        # v2 with an id: response uses the echo form
        s.sendall(wire.pack_frame(body, request_id="uds-id-7"))
        status, rid, payload = wire.recv_response_frame(s)
        assert status < 400 and rid == "uds-id-7"
        # v1 on the SAME conn: plain header, same payload bytes
        s.sendall(wire.pack_frame(body))
        status1, rid1, payload1 = wire.recv_response_frame(s)
        assert (status1, rid1) == (status, None)
        assert payload1 == payload
        # hostile frame id is cleaned away -> plain v1 response
        s.sendall(wire.pack_frame(body, request_id="bad id"))
        _, rid2, _ = wire.recv_response_frame(s)
        assert rid2 is None
        # error frames echo too
        s.sendall(wire.pack_frame(b"not json", request_id="er-1"))
        status, rid, _ = wire.recv_response_frame(s)
        assert status == 400 and rid == "er-1"
    finally:
        s.close()
    # oversize: 413 echo frame, then close
    s = connect()
    try:
        s.sendall(_uds_oversize_reqid_frame())
        status, rid, payload = wire.recv_response_frame(s)
        assert (status, rid) == (413, "big4")
        assert payload == wire.OVERSIZE_BODY
        assert s.recv(1) == b""
    finally:
        s.close()


def test_uds_request_id_echo_sync(sync_server):
    path = os.path.join(tempfile.mkdtemp(prefix="ldt-wire-"), "r.sock")
    uds = wire.UnixFrameServer(sync_server["svc"], path)
    uds.start()
    try:
        def connect():
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.connect(path)
            return s
        _uds_echo_checks(connect)
    finally:
        uds.close()


def test_uds_request_id_echo_aio(aio_server):
    def connect():
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(aio_server["uds_path"])
        return s
    _uds_echo_checks(connect)


def test_shm_slot_header_reqid_roundtrip(tmp_path):
    """The shm lane's id carrier is the slot header's u32: stamped on
    submit, echoed on DONE, and invalid ids are refused up front."""
    from language_detector_tpu.service import shmring
    rf = shmring.RingFile(str(tmp_path / "ring"), create=True,
                          slots=4, slot_bytes=4096)
    try:
        rf.write_slot(0, shmring.SLOT_READY, 1, os.getpid(), 1.0,
                      10, 0, reqid=0xCAFEF00D)
        assert rf.slot_request_id(0) == 0xCAFEF00D
        assert "%08x" % rf.slot_request_id(0) == "cafef00d"
        rf.write_slot(1, shmring.SLOT_READY, 1, os.getpid(), 1.0,
                      10, 0)
        assert rf.slot_request_id(1) == 0
    finally:
        rf.close()
    ring = shmring.RingClient(str(tmp_path / "c"), slots=4,
                              slot_bytes=4096)
    try:
        for bad in ("zz", "0", "123456789"):   # non-hex, zero, >u32
            with pytest.raises(ValueError):
                ring.submit(b"{}", request_id=bad)
    finally:
        ring.close()


def test_tracez_merges_one_id_across_processes(tmp_path):
    """The fleet /tracez merge: one request id written by two recorder
    files (two pids, three lanes) renders as ONE entry whose processes
    list spans both writers."""
    from language_detector_tpu import flightrec
    from language_detector_tpu.service import fleet
    rid = "cafef00d"
    lanes = {11111: ["tcp"], 22222: ["uds", "shm"]}
    for fake_pid, sub in ((11111, "m0"), (22222, "m1")):
        d = tmp_path / sub
        d.mkdir()
        p = d / f"flightrec-{fake_pid}.ring"
        rec = flightrec.FlightRecorder(str(p), slots=8, slot_bytes=256)
        for lane in lanes[fake_pid]:
            rec.emit("request_start", {"request_id": rid, "lane": lane})
        rec.emit("request_end", {"request_id": rid, "status": 200})
        rec.emit("request_start", {"request_id": f"other-{fake_pid}"})
        rec.close()
        # both rings were written by THIS process: forge the header
        # pid so the merge sees two distinct writers
        data = bytearray(p.read_bytes())
        struct.pack_into("<I", data, 16, fake_pid)
        p.write_bytes(bytes(data))
    doc = fleet._fleet_traces({"members": []}, str(tmp_path))
    assert doc["count"] == 3             # cafef00d + the two others
    top = doc["requests"][0]             # richest entry sorts first
    assert top["request_id"] == rid
    assert sorted(top["processes"]) == ["pid:11111", "pid:22222"]
    assert {e["lane"] for e in top["events"] if "lane" in e} \
        == {"tcp", "uds", "shm"}
    assert len(top["events"]) == 5       # 3 starts + 2 ends


def test_tracez_correlates_live_fronts(sync_server, aio_server,
                                       tmp_path, monkeypatch):
    """End-to-end correlation through real server code: the same id
    sent over HTTP (sync front) and the UDS lane (aio front) lands in
    the recorder and merges into one /tracez entry with both lanes."""
    from language_detector_tpu import flightrec
    from language_detector_tpu.service import fleet
    rec = flightrec.FlightRecorder(
        str(tmp_path / f"flightrec-{os.getpid()}.ring"))
    monkeypatch.setattr(flightrec, "RECORDER", rec)
    rid = "feedc0de"
    body = b'{"request": [{"text": "cross lane"}]}'
    try:
        status, echoed, _ = _post_with_id(sync_server["port"], body,
                                          rid=rid)
        assert status < 400 and echoed == rid
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(aio_server["uds_path"])
        s.sendall(wire.pack_frame(body, request_id=rid))
        status, echoed, _ = wire.recv_response_frame(s)
        assert status < 400 and echoed == rid
        s.close()
        doc = fleet._fleet_traces({"members": []}, str(tmp_path))
        entry = next(e for e in doc["requests"]
                     if e["request_id"] == rid)
        assert len(entry["processes"]) == 1      # same test process
        assert {e["lane"] for e in entry["events"]
                if e["ev"] == "request_start"} == {"tcp", "uds"}
    finally:
        monkeypatch.setattr(flightrec, "RECORDER", None)
        rec.close()


def test_uds_slow_loris_aio_408(aio_server, monkeypatch):
    """Same stalled-client regression against the asyncio front."""
    monkeypatch.setenv("LDT_FRAME_READ_TIMEOUT_SEC", "0.2")
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(aio_server["uds_path"])
    s.settimeout(10.0)
    try:
        # a healthy frame first (keep-alive), then a stalled body
        status, payload = _uds_request(
            s, b'{"request": [{"text": "warm"}]}')
        assert status < 400
        s.sendall(struct.pack("!I", 64) + b'{"partial')
        status, payload = _recv_resp(s)
        assert status == 408
        assert payload == wire.TIMEOUT_BODY
        assert s.recv(1) == b""
    finally:
        s.close()
