"""AOT-exported bucket-ladder executables (LDT_AOT_DIR).

Round 16 (ROADMAP item 2a). Every fresh worker generation — a restart,
a blue/green standby, an autoscaled fleet member — used to pay the full
per-process compile ladder before /readyz opened; the persistent XLA
compile cache (LDT_COMPILE_CACHE_DIR) removes the XLA backend compile
but still re-traces and re-lowers every tier shape through jax. This
module ships the *finished* executables alongside the model artifact
instead, the compile-once-serve-many discipline of the pjit/TPUv4
serving stack and the portable-compiled-artifact framing in PAPERS.md:

  - After the engine compiles a ladder tier (a new padded wire shape on
    the serving scorer), the compiled program is serialized into a
    sidecar bundle entry under LDT_AOT_DIR (write-back, one file per
    tier shape).
  - A later process (generation N+1, a standby, a new fleet member)
    finds the entry at dispatch time and deserializes the executable in
    milliseconds — no trace, no lower, no XLA compile.

Each entry carries TWO payloads:

  - the ``jax.export`` serialized module (portable StableHLO + calling
    convention, the versioned interchange format); loading it costs one
    XLA compile but no Python trace, and it survives jaxlib updates
    that keep the export calling convention;
  - the loaded-executable payload (``jax.experimental
    .serialize_executable``): the exact compiled program, pinned to
    (jax version, backend) — the boot-hot fast path, preferred at load.

Refusal is loud, never silent: every entry is keyed and cross-checked
against (table digest, jax version, backend, kernel mode, tier shape)
plus a whole-entry CRC, and a mismatched or corrupt bundle counts
``ldt_aot_refused_total{reason=}``, logs a structured line, and falls
back to a fresh compile (or raises the typed ``AotError`` under
LDT_AOT_REQUIRE=1 — the deploy guard for fleets that must boot hot).
A refused entry is overwritten by the compile path's write-back, so a
stale bundle self-heals on the first generation that serves through it.

The bundle directory is created if missing (with a structured log —
a nonexistent dir must enable the feature, not silently disable it),
and entries are written atomically (tmp + rename) so a crashed writer
can only ever leave a torn tmp file, which readers never open.
"""
from __future__ import annotations

import dataclasses
import errno
import hashlib
import json
import os
import pickle
import struct
import time
import zlib

from . import faults, knobs, telemetry
from .locks import make_lock

MAGIC = b"LDTAOT1\n"
_LEN = struct.Struct("<Q")
_CRC = struct.Struct("<I")

# pinned bundle geometry: a drive-by field edit must fail at import,
# not strand every deployed AOT sidecar bundle
# (tools/lint/layout_registry.py declares the same widths)
assert _LEN.size == 8
assert _CRC.size == 4

# memo sentinel: the bundle has no (usable) entry for this shape — the
# compile path owns it now and will write one back
_ABSENT = object()


class AotError(RuntimeError):
    """A refused AOT bundle entry: stale key (digest / jax version /
    backend / kernel mode), corrupt bytes, or an undeserializable
    payload. Raised out of dispatch only under LDT_AOT_REQUIRE=1;
    otherwise the engine logs, counts the refusal, and compiles."""


# -- jax.export pytree serialization registration ----------------------
# Exported.serialize() refuses pytrees with unregistered custom nodes;
# the scorer signature is (DeviceTables, wire dict), so the dataclass
# nodes register once per process. Auxdata is the registered
# dataclass's static-field tuple (Quad2Static geometry + the quad2
# flag for DeviceTables, empty for KindTables) — JSON round-trips it.

_export_registered = False


def _ensure_export_registered() -> None:
    global _export_registered
    if _export_registered:
        return
    from jax import export as jexport

    from .ops.device_tables import DeviceTables, KindTables, Quad2Static

    def _ser_dt_aux(aux) -> bytes:
        q2, enabled = aux
        return json.dumps([dataclasses.asdict(q2), enabled]).encode()

    def _des_dt_aux(data: bytes):
        q2, enabled = json.loads(bytes(data).decode())
        return (Quad2Static(**q2), enabled)

    def _ser_empty(aux) -> bytes:
        return b"[]"

    def _des_empty(data: bytes):
        return ()

    try:
        jexport.register_pytree_node_serialization(
            DeviceTables, serialized_name="ldt.DeviceTables",
            serialize_auxdata=_ser_dt_aux,
            deserialize_auxdata=_des_dt_aux)
        jexport.register_pytree_node_serialization(
            KindTables, serialized_name="ldt.KindTables",
            serialize_auxdata=_ser_empty,
            deserialize_auxdata=_des_empty)
    except ValueError:
        pass  # already registered (another engine in this process)
    _export_registered = True


# -- keys --------------------------------------------------------------


def shape_signature(wire: dict) -> tuple:
    """Canonical tier-shape signature of a packed wire: sorted
    (name, shape, dtype) triples. This is the same shape identity the
    compile meter keys on — one bundle entry per bucket-ladder tier."""
    import numpy as np
    return tuple(sorted((k, tuple(int(s) for s in np.shape(v)),
                         str(np.asarray(v).dtype))
                        for k, v in wire.items()))


def table_digest_hex(dt) -> str:
    """Content digest of the serving tables: the per-plane host
    fingerprint (ops/device_tables.py) folded to hex. Artifact-derived
    by construction — two artifacts with identical table bytes share
    executables, any retrain changes the key."""
    from .ops.device_tables import fingerprint
    return hashlib.sha256(
        repr(fingerprint(dt)).encode()).hexdigest()[:16]


def entry_name(kernel_mode: str, sig: tuple) -> str:
    h = hashlib.sha256(json.dumps(sig).encode()).hexdigest()[:20]
    return f"{kernel_mode}-{h}.ldtx"


def _log(msg: str, **fields) -> None:
    print(json.dumps({"msg": msg, **fields}), flush=True)


def _refuse(reason: str, path: str, detail: str, require: bool):
    telemetry.REGISTRY.counter_inc("ldt_aot_refused_total",
                                   reason=reason)
    _log("aot bundle entry refused", reason=reason, path=path,
         detail=detail, require=require)
    if require:
        raise AotError(f"AOT entry refused ({reason}): {path}: "
                       f"{detail} — unset LDT_AOT_REQUIRE to fall "
                       "back to a fresh compile")
    return None


# -- entry file format -------------------------------------------------


def _pack_entry(meta: dict, hlo: bytes, xc: bytes) -> bytes:
    mb = json.dumps(meta, sort_keys=True).encode()
    body = _LEN.pack(len(mb)) + mb + _LEN.pack(len(hlo)) + hlo \
        + _LEN.pack(len(xc)) + xc
    return MAGIC + body + _CRC.pack(zlib.crc32(body))


def _unpack_entry(raw: bytes):
    """(meta, hlo, xc) or raises AotError naming what is wrong."""
    if len(raw) < len(MAGIC) + _LEN.size + _CRC.size or \
            raw[:len(MAGIC)] != MAGIC:
        raise AotError("not an LDTX AOT entry (bad magic or truncated)")
    body, crc = raw[len(MAGIC):-_CRC.size], raw[-_CRC.size:]
    if zlib.crc32(body) != _CRC.unpack(crc)[0]:
        raise AotError("entry CRC mismatch (torn or corrupt bytes)")
    off = 0

    def take(n):
        nonlocal off
        if off + n > len(body):
            raise AotError("entry truncated inside a section")
        out = body[off:off + n]
        off += n
        return out

    mlen = _LEN.unpack(take(_LEN.size))[0]
    meta = json.loads(take(mlen).decode())
    hlen = _LEN.unpack(take(_LEN.size))[0]
    hlo = take(hlen)
    xlen = _LEN.unpack(take(_LEN.size))[0]
    xc = take(xlen)
    return meta, bytes(hlo), bytes(xc)


# -- the store ---------------------------------------------------------


class AotStore:
    """Per-engine view of one AOT bundle directory: lookup-first
    dispatch support plus compile write-back. Thread-safe (flush
    workers race on first-shape dispatches)."""

    def __init__(self, directory: str, digest: str, backend: str,
                 kernel_mode: str, require: bool):
        self.dir = directory
        self.digest = digest
        self.backend = backend
        self.kernel_mode = kernel_mode
        self.require = require
        self._lock = make_lock("engine.aot")
        self._entries: dict = {}  # sig -> callable | _ABSENT
        self._exported: set = set()  # sigs this store already wrote
        self.loads = 0
        self.exports = 0
        self.refusals = 0
        # flipped (sticky) by offer() on a disk-full export: loads
        # keep working, export compiles stop
        self.export_disabled = False

    # -- load path ----------------------------------------------------

    def lookup(self, wire: dict):
        """The deserialized executable for this wire's tier shape, or
        None (absent/refused — compile, then offer()). Never raises
        unless LDT_AOT_REQUIRE is set."""
        sig = shape_signature(wire)
        with self._lock:
            hit = self._entries.get(sig)
        if hit is not None:
            return None if hit is _ABSENT else hit
        fn = self._load(sig)
        with self._lock:
            # first loader wins; a racing loader's identical fn is fine
            cur = self._entries.setdefault(
                sig, fn if fn is not None else _ABSENT)
        return None if cur is _ABSENT else cur

    def _load(self, sig: tuple):
        path = os.path.join(self.dir, entry_name(self.kernel_mode, sig))
        if faults.ACTIVE is not None:
            faults.hit("aot_load")
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            if self.require:
                with self._lock:
                    self.refusals += 1
                return _refuse("missing", path,
                               "no bundle entry for this tier shape",
                               True)
            return None
        except OSError as e:
            with self._lock:
                self.refusals += 1
            return _refuse("io_error", path, repr(e), self.require)
        if faults.ACTIVE is not None:
            seed = faults.corruption("aot_load")
            if seed is not None:
                # chaos seam: a seeded bit-flip models bit rot in the
                # bundle — the CRC must refuse it, never deserialize
                b = bytearray(raw)
                b[seed % len(b)] ^= 1 << (seed % 8)
                raw = bytes(b)
        t0 = time.monotonic()
        try:
            meta, hlo, xc = _unpack_entry(raw)
        except AotError as e:
            with self._lock:
                self.refusals += 1
            return _refuse("corrupt", path, str(e), self.require)
        want = {"digest": self.digest, "jax": _jax_version(),
                "backend": self.backend, "kernel": self.kernel_mode}
        for field, expect in want.items():
            got = meta.get(field)
            if got != expect:
                with self._lock:
                    self.refusals += 1
                return _refuse(
                    f"{field}_mismatch", path,
                    f"entry {field}={got!r}, this process wants "
                    f"{expect!r}", self.require)
        try:
            meta_sig = tuple((k, tuple(s), d)
                             for k, s, d in meta.get("shapes", ()))
        except (TypeError, ValueError):
            meta_sig = ()
        if meta_sig != tuple((k, tuple(s), d) for k, s, d in sig):
            with self._lock:
                self.refusals += 1
            return _refuse("shape_mismatch", path,
                           "entry shapes disagree with the wire",
                           self.require)
        fn = self._deserialize(path, hlo, xc)
        if fn is not None:
            with self._lock:
                self.loads += 1
            telemetry.REGISTRY.counter_inc("ldt_aot_loads_total")
            _log("aot executable loaded", path=path,
                 kernel=self.kernel_mode,
                 ms=round((time.monotonic() - t0) * 1e3, 1))
        return fn

    def _deserialize(self, path: str, hlo: bytes, xc: bytes):
        """Native executable first (zero-compile), exported-module
        fallback (one XLA compile, no trace). Both are the compiled
        path bit-for-bit — tests/test_aot.py pins it."""
        if xc:
            try:
                from jax.experimental import serialize_executable as se
                payload, in_tree, out_tree = pickle.loads(xc)
                return se.deserialize_and_load(payload, in_tree,
                                               out_tree)
            except Exception as e:  # noqa: BLE001 - fall to the hlo payload
                _log("aot native payload unusable — trying the "
                     "exported module", path=path, error=repr(e))
        if hlo:
            try:
                import jax
                from jax import export as jexport
                _ensure_export_registered()
                exported = jexport.deserialize(hlo)
                return jax.jit(exported.call)
            except Exception as e:  # noqa: BLE001 - typed refusal below
                with self._lock:
                    self.refusals += 1
                return _refuse("undeserializable", path, repr(e),
                               self.require)
        with self._lock:
            self.refusals += 1
        return _refuse("empty", path, "entry carries no payload",
                       self.require)

    # -- write-back path ----------------------------------------------

    def offer(self, wire: dict, jit_fn, dt) -> bool:
        """Export the compiled scorer for this wire's tier shape into
        the bundle (write-back after a compiling launch). Best-effort:
        a failed export logs and counts, it never fails the dispatch
        that triggered it."""
        sig = shape_signature(wire)
        with self._lock:
            if self.export_disabled:
                return False  # disk full: stop paying export compiles
            known = self._entries.get(sig)
            if sig in self._exported:
                return False  # this store already wrote the entry
        if known is not None and known is not _ABSENT:
            return False  # loaded from the bundle: nothing to write
        path = os.path.join(self.dir, entry_name(self.kernel_mode, sig))
        try:
            if faults.ACTIVE is not None:
                faults.hit("aot_export")
            import jax
            specs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                     for k, v in wire.items()}
            t0 = time.monotonic()
            # The export compile must BYPASS the persistent compile
            # cache (LDT_COMPILE_CACHE_DIR): an executable XLA
            # deserialized from that cache serializes without its
            # jit-compiled symbol definitions, and the bundle entry
            # then refuses with "Symbols not found" in every fresh
            # process. One genuine compile per shape per exporting
            # generation is the price of a loadable bundle; later
            # generations load it and never compile at all. (A
            # concurrent compile on another flush worker misses the
            # persistent cache during this window — slower once,
            # never wrong.)
            cache_dir = getattr(jax.config, "jax_compilation_cache_dir",
                                None)
            if cache_dir:
                jax.config.update("jax_compilation_cache_dir", None)
            try:
                lowered = jit_fn.lower(dt, specs)
                compiled = lowered.compile()
            finally:
                if cache_dir:
                    jax.config.update("jax_compilation_cache_dir",
                                      cache_dir)
            try:
                from jax.experimental import serialize_executable as se
                xc = pickle.dumps(se.serialize(compiled))
            except Exception as e:  # noqa: BLE001 - hlo payload still ships
                _log("aot native serialization unavailable",
                     path=path, error=repr(e))
                xc = b""
            try:
                from jax import export as jexport
                _ensure_export_registered()
                hlo = jexport.export(jit_fn)(dt, specs).serialize()
            except Exception as e:  # noqa: BLE001 - native payload still ships
                _log("aot export serialization unavailable",
                     path=path, error=repr(e))
                hlo = b""
            if not hlo and not xc:
                raise AotError("neither payload serialized")
            meta = {"digest": self.digest, "jax": _jax_version(),
                    "backend": self.backend,
                    "kernel": self.kernel_mode,
                    "shapes": [list(s) for s in sig]}
            blob = _pack_entry(meta, hlo, xc)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001 - write-back is best-effort
            if isinstance(e, OSError) and e.errno == errno.ENOSPC:
                # sticky: every later offer would recompile just to
                # fail the same write — loads still work, the service
                # keeps serving, the disable is counted and logged
                with self._lock:
                    self.export_disabled = True
                telemetry.REGISTRY.counter_inc(
                    "ldt_aot_disabled_total", reason="enospc")
                _log("aot exports disabled", reason="enospc",
                     path=path, error=repr(e))
            else:
                _log("aot export failed", path=path, error=repr(e))
            return False
        with self._lock:
            self.exports += 1
            self._exported.add(sig)
        telemetry.REGISTRY.counter_inc("ldt_aot_exports_total")
        _log("aot executable exported", path=path,
             kernel=self.kernel_mode, bytes=len(blob),
             ms=round((time.monotonic() - t0) * 1e3, 1))
        return True

    # -- eager preload ------------------------------------------------

    def preload(self) -> int:
        """Deserialize every matching bundle entry up front (the
        startup_ready_task hook): warmup then dispatches straight into
        loaded executables instead of paying per-shape lazy loads
        between batches. Returns the number of entries now live."""
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return 0
        live = 0
        prefix = f"{self.kernel_mode}-"
        for name in names:
            if not name.startswith(prefix) or \
                    not name.endswith(".ldtx"):
                continue
            path = os.path.join(self.dir, name)
            try:
                with open(path, "rb") as f:
                    meta, hlo, xc = _unpack_entry(f.read())
            except (OSError, AotError):
                continue  # lookup() refuses it loudly if dispatched
            try:
                sig = tuple((k, tuple(s), d)
                            for k, s, d in meta.get("shapes", ()))
            except (TypeError, ValueError):
                continue
            with self._lock:
                if self._entries.get(sig) is not None:
                    continue
            fake_wire = {k: _SpecView(s, d) for k, s, d in sig}
            if self.lookup(fake_wire) is not None:
                live += 1
        return live

    def stats(self) -> dict:
        with self._lock:
            return {"dir": self.dir, "kernel": self.kernel_mode,
                    "digest": self.digest, "loads": self.loads,
                    "exports": self.exports,
                    "refusals": self.refusals,
                    "export_disabled": self.export_disabled,
                    "entries": sum(1 for v in self._entries.values()
                                   if v is not _ABSENT)}


class _SpecView:
    """Shape/dtype-only stand-in so preload can drive lookup() through
    shape_signature without materializing arrays."""

    def __init__(self, shape, dtype):
        self.shape = tuple(shape)
        self.dtype = dtype

    def __array__(self):  # np.asarray(...).dtype in shape_signature
        import numpy as np
        return np.empty(self.shape, dtype=self.dtype)


def _jax_version() -> str:
    import jax
    return jax.__version__


def build_from_env(kernel_mode: str, dt) -> AotStore | None:
    """The engine's AOT store per LDT_AOT_DIR, or None when the knob is
    unset. Creates the bundle dir if missing — loudly: a deploy that
    points at a not-yet-existing dir gets an armed (empty) bundle and a
    structured log, never a silently disabled feature."""
    directory = knobs.get_str("LDT_AOT_DIR")
    if not directory:
        return None
    if not os.path.isdir(directory):
        try:
            os.makedirs(directory, exist_ok=True)
            _log("aot bundle dir created", dir=directory)
        except OSError as e:
            telemetry.REGISTRY.counter_inc(
                "ldt_aot_disabled_total",
                reason="enospc" if e.errno == errno.ENOSPC
                else "oserror")
            _log("aot bundle dir unusable — AOT disabled",
                 dir=directory, error=repr(e))
            return None
    import jax
    # pre-touch so a scrape shows the series at 0 before any dispatch
    telemetry.REGISTRY.counter_inc("ldt_aot_loads_total", 0)
    telemetry.REGISTRY.counter_inc("ldt_aot_exports_total", 0)
    return AotStore(directory, table_digest_hex(dt),
                    jax.default_backend(), kernel_mode,
                    knobs.get_bool("LDT_AOT_REQUIRE"))
