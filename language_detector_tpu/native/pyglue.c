/* GIL-held CPython glue: list[str] -> UTF-8 blob + bounds in one pass.
 *
 * The Python-side marshalling for a 16K-doc batch (per-doc .encode()
 * producing 16K transient bytes objects, then b"".join copying them
 * again, then a cumsum over a length list) costs ~6ms of the
 * single-core host per batch. This fills the caller's blob and bounds
 * directly from each str's cached UTF-8 representation: one encode,
 * one copy, zero transient objects.
 *
 * Built as a SEPARATE shared object (build.sh -> libldtglue.so) so
 * libldtpack.so stays free of any libpython dependency — the C-ABI
 * detection seam must remain linkable from a cgo host with no Python
 * in the process. Loaded with ctypes.PyDLL (GIL held across the call:
 * every function here touches CPython API).
 *
 * Returns total bytes; -1 when the caller's blob is too small; -2 when
 * any element is not a str or is not encodable as strict UTF-8 (lone
 * surrogates — the Python caller falls back to its surrogatepass
 * path).
 */
#include <Python.h>

#include <stdint.h>
#include <string.h>

/* Contract version for the loader's staleness check (bump on any
 * signature/semantic change). */
int64_t ldt_glue_version(void) { return 1; }

int64_t ldt_blob_from_list(PyObject* list, int64_t n_expected,
                           uint8_t* blob, int64_t blob_cap,
                           int64_t* bounds) {
  if (!PyList_Check(list)) return -2;
  Py_ssize_t n = PyList_GET_SIZE(list);
  /* bounds was sized from an earlier len(texts); if another thread
   * mutated the list between the Python-side sizing and this call,
   * writing bounds[i+1] for a LONGER list would corrupt the heap. */
  if ((int64_t)n != n_expected) return -2;
  int64_t total = 0;
  bounds[0] = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* s = PyList_GET_ITEM(list, i);
    if (!PyUnicode_Check(s)) return -2;
    Py_ssize_t sz;
    const char* p = PyUnicode_AsUTF8AndSize(s, &sz);
    if (p == NULL) {
      PyErr_Clear(); /* lone surrogate etc.: caller falls back */
      return -2;
    }
    if (blob != NULL) {
      if (total + (int64_t)sz > blob_cap) return -1;
      memcpy(blob + total, p, (size_t)sz);
    }
    total += (int64_t)sz;
    bounds[i + 1] = total;
  }
  return total;
}

/* Total UTF-8 bytes only (sizing pass; also warms each str's cached
 * utf8 so the fill pass is pure memcpy). Same error returns. */
int64_t ldt_blob_size(PyObject* list) {
  if (!PyList_Check(list)) return -2;
  Py_ssize_t n = PyList_GET_SIZE(list);
  int64_t total = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* s = PyList_GET_ITEM(list, i);
    if (!PyUnicode_Check(s)) return -2;
    Py_ssize_t sz;
    if (PyUnicode_AsUTF8AndSize(s, &sz) == NULL) {
      PyErr_Clear();
      return -2;
    }
    total += (int64_t)sz;
  }
  return total;
}
