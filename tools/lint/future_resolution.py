"""Future-resolution analyzer: every future this repo creates must be
provably answered.

The serving path hands waiters three kinds of futures — the sync
Batcher's ``concurrent.futures.Future``, the aio front's
``loop.create_future()``, and the device pool's ``_PoolFuture`` — and a
future that is created but never resolved is the worst failure mode the
stack has: the client connection pins until its flush timeout with no
error, no metric, and no log line. Two rules over the batching files:

  future-unresolved      a function creates a future (``Future()``,
                         ``create_future()``, ``_PoolFuture(...)``)
                         and some path reaches a NORMAL exit with the
                         future neither resolved (set_result /
                         set_exception / cancel) nor escaped to a
                         declared handoff (returned to the caller, or
                         enqueued via ``put``/``put_nowait``). A
                         ``raise`` before the future ever escaped is
                         fine — nothing holds a reference, so nothing
                         waits on it.
  future-consumer-guard  the declared consumer functions (the loops
                         that pop futures off queues and own resolving
                         them) must, in every broad exception handler
                         (bare / Exception / BaseException /
                         CancelledError / FaultInjected), either
                         re-raise, call a bulk-resolver (``_fail``),
                         or resolve futures inline — a swallowed
                         exception in a consumer orphans the whole
                         batch. A declared consumer that no longer
                         exists is itself a violation (stale registry).

The escape model is deliberately a whitelist: a future passed to an
undeclared callee is NOT credited as handed off, so responsibility
stays with the creator and the normal-exit check still fires.
"""
from __future__ import annotations

import ast
from pathlib import Path

from .base import Violation, apply_suppressions, load_source, repo_root

SCAN_FILES = (
    "language_detector_tpu/service/batcher.py",
    "language_detector_tpu/service/aioserver.py",
    "language_detector_tpu/parallel/pool.py",
)

# constructors whose result is a future the creator must account for
CREATOR_CALLS = frozenset({"Future", "create_future", "_PoolFuture"})
# methods on the future that settle it
RESOLVER_ATTRS = frozenset({"set_result", "set_exception", "cancel"})
# declared handoffs: enqueue into a consumer-owned queue
SINK_CALLS = frozenset({"put", "put_nowait"})

# the functions that pop futures from queues/stashes and own resolving
# them: (file rel, class name or None, function name). Every broad
# except handler inside must raise, bulk-fail, or resolve inline.
CONSUMERS = (
    ("language_detector_tpu/service/batcher.py", "Batcher", "_run"),
    ("language_detector_tpu/service/batcher.py", "Batcher", "_flush"),
    ("language_detector_tpu/service/aioserver.py", "AioBatcher",
     "_collector"),
    ("language_detector_tpu/parallel/pool.py", "DevicePool", "_fetch"),
)

# handler types that catch "anything" on a consumer path and therefore
# must prove they answer the batch. Narrow operational types
# (TimeoutError, QueueEmpty, a typed RuntimeError probe) stay exempt.
BROAD_HANDLER_TYPES = frozenset({
    "Exception", "BaseException", "CancelledError", "FaultInjected"})

# possible per-path statuses of one created future
_PENDING = "pending"
_DONE = "done"  # resolved or escaped to a declared owner


def _handler_names(h: ast.ExceptHandler):
    """Trailing identifiers of the caught types (bare -> [None])."""
    t = h.type
    if t is None:
        yield None
        return
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in types:
        if isinstance(e, ast.Attribute):
            yield e.attr
        elif isinstance(e, ast.Name):
            yield e.id


class _FutureScan:
    """Track one created future through the rest of its function.

    Statuses are possible-sets over {pending, done}; a statement list
    returns the fall-through set, or None when every path out of it
    raised/returned. Normal exits (Return, falling off the end) with
    `pending` possible are the violation; exceptional exits never are
    (pre-escape: nothing waits; post-escape: the consumer owns it).
    """

    def __init__(self, name: str, created: ast.stmt, rel: str,
                 out: list):
        self.name = name
        self.created = created
        self.rel = rel
        self.out = out
        self.flagged = False

    def _flag(self, node):
        if self.flagged:
            return  # one report per creation is enough
        self.flagged = True
        self.out.append(Violation(
            "future-unresolved", self.rel, node.lineno,
            f"future `{self.name}` (created line "
            f"{self.created.lineno}) can reach this exit neither "
            f"resolved (set_result/set_exception/cancel) nor handed "
            f"off (returned / put on a consumer queue)"))

    # -- per-statement effects ----------------------------------------------

    def _mentions(self, node) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id == self.name:
                return True
        return False

    def _settles(self, stmt) -> bool:
        """Does this statement resolve or hand off the future?"""
        for n in ast.walk(stmt):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if isinstance(f, ast.Attribute) \
                    and f.attr in RESOLVER_ATTRS \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == self.name:
                return True
            if isinstance(f, ast.Attribute) and f.attr in SINK_CALLS:
                if any(self._mentions(a) for a in n.args):
                    return True
        return False

    def _apply(self, stmt, status: frozenset) -> frozenset:
        if self._settles(stmt):
            return frozenset({_DONE})
        # rebinding the name to a fresh value ends this future's story
        # on that path (the old object is garbage; a new creation gets
        # its own scan)
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == self.name:
                    return frozenset({_DONE})
        return status

    # -- control flow --------------------------------------------------------

    def block(self, stmts, status):
        for s in stmts:
            if status is None:
                return None
            status = self.stmt(s, status)
        return status

    def stmt(self, s, status):
        if isinstance(s, ast.Return):
            if s.value is not None and self._mentions(s.value):
                return None  # escaped to the caller
            if _PENDING in status:
                self._flag(s)
            return None
        if isinstance(s, ast.Raise):
            return None  # exceptional exit: never a violation (above)
        if isinstance(s, ast.If):
            t = self.block(s.body, status)
            f = self.block(s.orelse, status)
            if t is None:
                return f
            if f is None:
                return t
            return t | f
        if isinstance(s, (ast.While, ast.For, ast.AsyncFor)):
            # one settling statement anywhere in the body settles the
            # loop's fall-through only if the body always runs; a
            # zero-iteration loop keeps the entry status. Approximate:
            # fall-through = entry ∪ one-pass body result.
            body = self.block(s.body, status)
            after = status if body is None else status | body
            o = self.block(s.orelse, after)
            return o if o is not None else after
        if isinstance(s, (ast.With, ast.AsyncWith)):
            return self.block(s.body, status)
        if isinstance(s, ast.Try):
            body = self.block(s.body, status)
            # a handler can be entered from any point in the body; if
            # the body could settle, the handler may still see pending
            h_entry = status if not self._body_settles(s.body) \
                else status | frozenset({_DONE})
            outs = []
            for h in s.handlers:
                ho = self.block(h.body, h_entry)
                if ho is not None:
                    outs.append(ho)
            if body is not None:
                o = self.block(s.orelse, body)
                if o is not None:
                    outs.append(o)
            fall = frozenset().union(*outs) if outs else None
            if s.finalbody:
                fin_entry = fall if fall is not None else h_entry
                fin = self.block(s.finalbody, fin_entry)
                if fin is None:
                    return None
                if fall is not None:
                    # the finally body's settles apply to every path
                    fall = fin
            return fall
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            # a nested def CAPTURING the future defers resolution to
            # call time — credit it as a declared resolver closure
            if any(isinstance(n, ast.Name) and n.id == self.name
                   for n in ast.walk(s)):
                return frozenset({_DONE})
            return status
        # simple statement: settles/rebinds apply directly (compound
        # statements above are handled structurally — a settle in one
        # branch must not credit the other)
        return self._apply(s, status)

    def _body_settles(self, stmts) -> bool:
        # _settles walks each statement, nested compounds included
        return any(self._settles(st) for st in stmts)


def _scan_function(fn, rel: str, out: list):
    """Find creations in `fn` and run one _FutureScan per creation over
    the statements that follow it (same block) plus enclosing blocks'
    tails are out of scope — creations in this repo are function-top."""
    def walk_block(stmts):
        for i, s in enumerate(stmts):
            if isinstance(s, (ast.Assign, ast.AnnAssign)):
                targets = s.targets if isinstance(s, ast.Assign) \
                    else [s.target]
                val = s.value
                if isinstance(val, ast.Call) and isinstance(
                        val.func, (ast.Name, ast.Attribute)):
                    cname = val.func.id if isinstance(
                        val.func, ast.Name) else val.func.attr
                    if cname in CREATOR_CALLS:
                        for t in targets:
                            if isinstance(t, ast.Name):
                                scan = _FutureScan(t.id, s, rel, out)
                                st = scan.block(
                                    stmts[i + 1:],
                                    frozenset({_PENDING}))
                                if st is not None and _PENDING in st:
                                    scan._flag(stmts[-1])
            # recurse into nested compound statements so creations
            # inside loops/ifs are scanned against their own block —
            # but not into nested defs, which the module walk visits
            # as functions of their own
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for sub in (getattr(s, "body", None),
                        getattr(s, "orelse", None),
                        getattr(s, "finalbody", None)):
                if sub:
                    walk_block(sub)
            for h in getattr(s, "handlers", ()):
                walk_block(h.body)

    walk_block(fn.body)


def _check_consumers(sources_by_rel: dict, root: Path, out_by_rel):
    for rel, cls, fname in CONSUMERS:
        sf = sources_by_rel.get(rel)
        if sf is None:
            continue  # file filtered out of this run
        fn = None
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name == cls:
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) \
                            and sub.name == fname:
                        fn = sub
        if fn is None:
            out_by_rel[rel].append(Violation(
                "future-consumer-guard", rel, 1,
                f"declared consumer {cls}.{fname} no longer exists; "
                f"update CONSUMERS in tools/lint/future_resolution.py"))
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not any(n is None or n in BROAD_HANDLER_TYPES
                       for n in _handler_names(node)):
                continue
            ok = False
            for n in ast.walk(node):
                if isinstance(n, ast.Raise):
                    ok = True
                elif isinstance(n, ast.Call):
                    f = n.func
                    nm = f.attr if isinstance(f, ast.Attribute) \
                        else getattr(f, "id", None)
                    if nm == "_fail" or nm in RESOLVER_ATTRS:
                        ok = True
            if not ok:
                out_by_rel[rel].append(Violation(
                    "future-consumer-guard", rel, node.lineno,
                    f"broad except in consumer {cls}.{fname} neither "
                    f"re-raises nor resolves the pending futures "
                    f"(_fail / set_exception): a swallowed error here "
                    f"orphans the batch"))


def check(root: Path | None = None, files=None, consumers=None):
    """Run the analyzer. Returns (violations, n_suppressed)."""
    global CONSUMERS
    root = root or repo_root()
    rels = SCAN_FILES if files is None else files
    sources = [load_source(root / rel, root) for rel in rels
               if (root / rel).exists()]
    by_rel = {sf.rel: sf for sf in sources}
    out_by_rel: dict = {sf.rel: [] for sf in sources}

    for sf in sources:
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_function(node, sf.rel, out_by_rel[sf.rel])

    saved = CONSUMERS
    if consumers is not None:
        CONSUMERS = consumers
    try:
        _check_consumers(by_rel, root, out_by_rel)
    finally:
        CONSUMERS = saved

    violations: list = []
    n_suppressed = 0
    for sf in sources:
        kept, ns = apply_suppressions(sf, out_by_rel[sf.rel])
        violations.extend(kept)
        n_suppressed += ns
    return violations, n_suppressed
