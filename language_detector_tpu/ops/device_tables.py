"""Device-resident scoring tables: the model weights in TPU HBM.

Uploaded once, replicated across the mesh (they are small: ~2MB total).
Bucket arrays stay in their packed uint32 form and are probed with
vectorized gathers; auxiliary decode tables are flat arrays.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import Registry
from ..tables import NgramTable, ScoringTables


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DeviceNgramTable:
    buckets: jnp.ndarray   # [size, 4] uint32
    ind: jnp.ndarray       # [n] uint32
    size_one: int = dataclasses.field(metadata=dict(static=True))
    size: int = dataclasses.field(metadata=dict(static=True))
    keymask: int = dataclasses.field(metadata=dict(static=True))

    @classmethod
    def from_host(cls, t: NgramTable) -> "DeviceNgramTable":
        return cls(buckets=jnp.asarray(t.buckets),
                   ind=jnp.asarray(t.ind),
                   size_one=t.size_one, size=t.size, keymask=t.keymask)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DeviceTables:
    quadgram: DeviceNgramTable
    quadgram2: DeviceNgramTable
    deltaocta: DeviceNgramTable
    distinctocta: DeviceNgramTable
    cjkdeltabi: DeviceNgramTable
    distinctbi: DeviceNgramTable
    cjkcompat: DeviceNgramTable
    lg_prob3: jnp.ndarray          # [240, 3] uint8: 3-entry qprob decode
    expected_score: jnp.ndarray    # [614, 4] int32
    plang_to_lang: jnp.ndarray     # [2, 256] int32 (latn, othr)
    lang_rtype_default: jnp.ndarray  # [102, 2] int32 (rtype, default lang)
    close_set: jnp.ndarray         # [614] int32 close-set id
    closest_alt: jnp.ndarray       # [614] int32 closest alternate (or 26)
    is_figs: jnp.ndarray           # [614] bool
    quad2_enabled: bool = dataclasses.field(metadata=dict(static=True))

    @classmethod
    def from_host(cls, t: ScoringTables, reg: Registry) -> "DeviceTables":
        close = np.zeros(reg.num_languages, np.int32)
        for lang in range(reg.num_languages):
            close[lang] = reg.close_set(lang)
        alt = np.full(reg.num_languages, 26, np.int32)  # 26 = UNKNOWN
        alt[:len(reg.closest_alt_lang)] = reg.closest_alt_lang
        figs = np.zeros(reg.num_languages, bool)
        for code in ("fr", "it", "de", "es"):
            figs[reg.code_to_lang[code]] = True
        rd = np.stack([reg.ulscript_rtype.astype(np.int32),
                       reg.ulscript_default_lang.astype(np.int32)], axis=1)
        return cls(
            quadgram=DeviceNgramTable.from_host(t.quadgram),
            quadgram2=DeviceNgramTable.from_host(t.quadgram2),
            deltaocta=DeviceNgramTable.from_host(t.deltaocta),
            distinctocta=DeviceNgramTable.from_host(t.distinctocta),
            cjkdeltabi=DeviceNgramTable.from_host(t.cjkdeltabi),
            distinctbi=DeviceNgramTable.from_host(t.distinctbi),
            cjkcompat=DeviceNgramTable.from_host(t.cjkcompat),
            lg_prob3=jnp.asarray(t.lg_prob[:, 5:8]),
            expected_score=jnp.asarray(
                t.avg_delta_octa_score.astype(np.int32)),
            plang_to_lang=jnp.asarray(np.stack([
                reg.plang_to_lang_latn.astype(np.int32),
                reg.plang_to_lang_othr.astype(np.int32)])),
            lang_rtype_default=jnp.asarray(rd),
            close_set=jnp.asarray(close),
            closest_alt=jnp.asarray(alt),
            is_figs=jnp.asarray(figs),
            quad2_enabled=not t.quadgram2.empty and t.quadgram2.size != 0,
        )
