"""FSM registry + conformance checking (docs/STATIC_ANALYSIS.md).

Every state machine on the concurrency surface — the circuit breaker,
the brownout ladder, pool lane health, the supervisor's worker
lifecycle and blue/green swap drill, and the in-process artifact swap —
declares its transition table here, and an AST pass proves the code
against it in BOTH directions:

  - fsm-undeclared-transition: a state assignment whose (source,
    target) pair is not in the declared table. The pass is
    flow-sensitive: it narrows the possible source set through
    ``if self._state == CONST`` guards (including early returns,
    ``and`` conjunctions, and booleans assigned from a state
    comparison), so a write guarded down to one source only needs that
    one transition declared.
  - fsm-dead-transition: a declared transition no state assignment can
    ever take — the table and the code drifted apart.

Machines come in three shapes: ``attr`` (an instance attribute holding
named integer constants, e.g. CircuitBreaker._state), ``counter`` (an
instance attribute stepped by +=1/-=1 through a declared integer range,
e.g. BrownoutLadder.level), and ``local`` (a function-local phase
variable, e.g. the supervisor drill's ``drill``). Counter steps that
would leave the declared range are assumed loop-guarded (the ladder's
``while self.level < top`` bound is a runtime value).

The conformance pass is deliberately scoped: only the declared file and
class/function are scanned, so an unrelated ``self.level`` elsewhere
never trips the ladder's table.
"""
from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from .base import Violation, apply_suppressions, load_source, repo_root


@dataclasses.dataclass(frozen=True)
class Machine:
    """One declared state machine: where it lives, its states, and the
    complete set of legal (source, target) transitions. Self-loops are
    transitions too and must be declared (e.g. a success resetting an
    already-closed breaker)."""
    name: str
    file: str          # repo-relative path holding the machine
    scope: tuple       # ("class", name) methods scanned, or ("func", name)
    kind: str          # "attr" | "counter" | "local"
    var: str           # attribute name or local variable name
    states: dict       # constant name -> int value
    initial: str
    transitions: frozenset  # of (src_name, dst_name)

    def __post_init__(self):
        unknown = {self.initial} | {s for t in self.transitions
                                    for s in t}
        unknown -= set(self.states)
        if unknown:
            raise ValueError(
                f"machine {self.name}: transition/initial states not "
                f"declared: {sorted(unknown)}")


MACHINES = (
    Machine(
        name="circuit-breaker",
        file="language_detector_tpu/service/admission.py",
        scope=("class", "CircuitBreaker"),
        kind="attr",
        var="_state",
        states={"BREAKER_CLOSED": 0, "BREAKER_HALF_OPEN": 1,
                "BREAKER_OPEN": 2},
        initial="BREAKER_CLOSED",
        transitions=frozenset({
            # success resets an already-closed breaker's failure count
            ("BREAKER_CLOSED", "BREAKER_CLOSED"),
            # consecutive failures trip
            ("BREAKER_CLOSED", "BREAKER_OPEN"),
            # half-open probe succeeded / failed
            ("BREAKER_HALF_OPEN", "BREAKER_CLOSED"),
            ("BREAKER_HALF_OPEN", "BREAKER_OPEN"),
            # cooldown elapsed: admit one probe
            ("BREAKER_OPEN", "BREAKER_HALF_OPEN"),
            # straggler failures while open refresh the cooldown clock
            ("BREAKER_OPEN", "BREAKER_OPEN"),
        }),
    ),
    Machine(
        name="brownout-ladder",
        file="language_detector_tpu/service/admission.py",
        scope=("class", "BrownoutLadder"),
        kind="counter",
        var="level",
        states={"0": 0, "1": 1, "2": 2, "3": 3},
        initial="0",
        transitions=frozenset({
            # the ladder only ever steps one level at a time
            ("0", "1"), ("1", "2"), ("2", "3"),
            ("3", "2"), ("2", "1"), ("1", "0"),
        }),
    ),
    Machine(
        name="pool-lane",
        file="language_detector_tpu/parallel/pool.py",
        scope=("class", "Lane"),
        kind="attr",
        var="_state",
        states={"LANE_ACTIVE": 0, "LANE_EVICTED": 1, "LANE_PROBING": 2,
                "LANE_CORRUPT": 3},
        initial="LANE_ACTIVE",
        transitions=frozenset({
            ("LANE_ACTIVE", "LANE_EVICTED"),   # consecutive failures
            ("LANE_EVICTED", "LANE_PROBING"),  # cooldown probe admitted
            ("LANE_PROBING", "LANE_ACTIVE"),   # probe succeeded
            ("LANE_PROBING", "LANE_EVICTED"),  # probe failed
            ("LANE_ACTIVE", "LANE_CORRUPT"),   # scrub/canary mismatch
            ("LANE_CORRUPT", "LANE_EVICTED"),  # healed: fresh tables,
            #                                    probe immediately due
        }),
    ),
    Machine(
        name="supervisor-worker",
        file="language_detector_tpu/service/supervisor.py",
        scope=("func", "main"),
        kind="local",
        var="worker",
        states={"WORKER_IDLE": 0, "WORKER_RUNNING": 1,
                "WORKER_STOPPED": 2, "WORKER_RECYCLED": 3,
                "WORKER_EXITED": 4, "WORKER_CRASHED": 5},
        initial="WORKER_IDLE",
        transitions=frozenset({
            ("WORKER_IDLE", "WORKER_RUNNING"),      # first spawn
            ("WORKER_RECYCLED", "WORKER_RUNNING"),  # immediate respawn
            ("WORKER_CRASHED", "WORKER_RUNNING"),   # post-backoff spawn
            ("WORKER_RUNNING", "WORKER_STOPPED"),
            ("WORKER_RUNNING", "WORKER_RECYCLED"),
            ("WORKER_RUNNING", "WORKER_EXITED"),
            ("WORKER_RUNNING", "WORKER_CRASHED"),
        }),
    ),
    Machine(
        name="supervisor-swap-drill",
        file="language_detector_tpu/service/supervisor.py",
        scope=("func", "_swap_drill"),
        kind="local",
        var="drill",
        states={"DRILL_IDLE": 0, "DRILL_SPAWNED": 1,
                "DRILL_CUTOVER": 2, "DRILL_PROMOTED": 3,
                "DRILL_ABORTED": 4},
        initial="DRILL_IDLE",
        transitions=frozenset({
            ("DRILL_IDLE", "DRILL_SPAWNED"),
            # pointer unreadable / injected standby_spawn fault
            ("DRILL_IDLE", "DRILL_ABORTED"),
            # standby died or never landed the ready handshake
            ("DRILL_SPAWNED", "DRILL_ABORTED"),
            ("DRILL_SPAWNED", "DRILL_CUTOVER"),
            ("DRILL_CUTOVER", "DRILL_PROMOTED"),
        }),
    ),
    Machine(
        name="fleet-member",
        file="language_detector_tpu/service/fleet.py",
        scope=("class", "FleetMember"),
        kind="attr",
        var="state",
        states={"FLEET_SPAWNING": 0, "FLEET_READY": 1,
                "FLEET_DEGRADED": 2, "FLEET_DEAD": 3,
                "FLEET_RESTARTING": 4},
        initial="FLEET_SPAWNING",
        transitions=frozenset({
            ("FLEET_SPAWNING", "FLEET_READY"),     # ready file landed
            ("FLEET_DEGRADED", "FLEET_READY"),     # scrapes recovered
            ("FLEET_READY", "FLEET_DEGRADED"),     # scrapes failing
            ("FLEET_SPAWNING", "FLEET_DEAD"),      # died before ready
            ("FLEET_READY", "FLEET_DEAD"),
            ("FLEET_DEGRADED", "FLEET_DEAD"),
            ("FLEET_DEAD", "FLEET_RESTARTING"),    # respawn decided
            ("FLEET_RESTARTING", "FLEET_SPAWNING"),  # Popen issued
        }),
    ),
    Machine(
        name="fleet-circuit",
        file="language_detector_tpu/service/fleet.py",
        scope=("class", "FleetControl"),
        kind="attr",
        var="circuit",
        states={"CIRCUIT_CLOSED": 0, "CIRCUIT_OPEN": 1,
                "CIRCUIT_PROBE": 2},
        initial="CIRCUIT_CLOSED",
        transitions=frozenset({
            # correlated crash (window full, or zero accepting) trips
            ("CIRCUIT_CLOSED", "CIRCUIT_OPEN"),
            # cooldown elapsed: admit one probe member
            ("CIRCUIT_OPEN", "CIRCUIT_PROBE"),
            ("CIRCUIT_PROBE", "CIRCUIT_CLOSED"),  # probe became READY
            ("CIRCUIT_PROBE", "CIRCUIT_OPEN"),    # probe member died
        }),
    ),
    Machine(
        name="artifact-swap",
        file="language_detector_tpu/service/swap.py",
        scope=("func", "swap_artifact"),
        kind="local",
        var="swap",
        states={"SWAP_IDLE": 0, "SWAP_LOADING": 1, "SWAP_REBOUND": 2,
                "SWAP_REFUSED": 3, "SWAP_ABORTED": 4},
        initial="SWAP_IDLE",
        transitions=frozenset({
            ("SWAP_IDLE", "SWAP_REFUSED"),    # breaker open
            ("SWAP_IDLE", "SWAP_LOADING"),
            ("SWAP_LOADING", "SWAP_ABORTED"),  # load/cutover failed
            ("SWAP_LOADING", "SWAP_REBOUND"),
        }),
    ),
    Machine(
        name="config-plane",
        file="language_detector_tpu/configplane.py",
        scope=("class", "ConfigPlane"),
        kind="attr",
        var="state",
        states={"CONFIG_IDLE": 0, "CONFIG_STAGED": 1,
                "CONFIG_PROBATION": 2, "CONFIG_COMMITTED": 3,
                "CONFIG_ROLLED_BACK": 4},
        initial="CONFIG_IDLE",
        transitions=frozenset({
            # a push stages from any settled state
            ("CONFIG_IDLE", "CONFIG_STAGED"),
            ("CONFIG_COMMITTED", "CONFIG_STAGED"),
            ("CONFIG_ROLLED_BACK", "CONFIG_STAGED"),
            # registry validation refused the batch: nothing applied
            ("CONFIG_STAGED", "CONFIG_IDLE"),
            # the batch went live under SLO probation
            ("CONFIG_STAGED", "CONFIG_PROBATION"),
            # probation window elapsed without a burn breach
            ("CONFIG_PROBATION", "CONFIG_COMMITTED"),
            # fast-window burn crossed 1.0: prior overrides restored
            ("CONFIG_PROBATION", "CONFIG_ROLLED_BACK"),
        }),
    ),
    Machine(
        name="shm-slot",
        file="language_detector_tpu/service/shmring.py",
        scope=("class", "RingSlot"),
        kind="attr",
        var="state",
        states={"SLOT_FREE": 0, "SLOT_WRITING": 1, "SLOT_READY": 2,
                "SLOT_LEASED": 3, "SLOT_DONE": 4},
        initial="SLOT_FREE",
        transitions=frozenset({
            ("SLOT_FREE", "SLOT_WRITING"),    # client claims the slot
            ("SLOT_WRITING", "SLOT_READY"),   # frame committed
            ("SLOT_READY", "SLOT_LEASED"),    # worker leases it
            ("SLOT_LEASED", "SLOT_DONE"),     # response written
            # fail-back: fenced READY / orphaned LEASED answers an
            # explicit error frame instead of hanging the client
            ("SLOT_READY", "SLOT_DONE"),
            ("SLOT_LEASED", "SLOT_DONE"),
            ("SLOT_DONE", "SLOT_FREE"),       # client consumed
            ("SLOT_WRITING", "SLOT_FREE"),    # dead writer reclaimed
        }),
    ),
)


# ---------------------------------------------------------------------
# flow-sensitive conformance pass

@dataclasses.dataclass
class _Out:
    """Result of analyzing a statement block: the possible-state set on
    the fall-through edge (None = no path falls through) and the sets
    carried by break/continue edges out of the block."""
    fall: frozenset | None
    breaks: list
    continues: list


def _union(*sets):
    acc: set = set()
    for s in sets:
        if s:
            acc |= s
    return frozenset(acc)


# sentinel member of a possible-state set marking "not yet assigned" —
# distinct from None (= unreachable). The first write on an
# uninitialized path is the initial-state check, not a transition.
_UM = "<uninit>"
_UNINIT = frozenset({_UM})


class _Scan:
    """One machine scanned against one function body."""

    def __init__(self, m: Machine, sf, observed: set, out: list):
        self.m = m
        self.sf = sf
        self.observed = observed
        self.out = out
        self.all = frozenset(m.states)
        # bool local -> (true_set, false_set) recorded when the local
        # was assigned from a state comparison
        self.bool_narrow: dict = {}

    # -- state expression / constant matching

    def _is_state_ref(self, node) -> bool:
        if self.m.kind in ("attr", "counter"):
            return (isinstance(node, ast.Attribute)
                    and node.attr == self.m.var
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self")
        return isinstance(node, ast.Name) and node.id == self.m.var

    def _const_state(self, node) -> str | None:
        if self.m.kind == "counter":
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, int) \
                    and not isinstance(node.value, bool) \
                    and str(node.value) in self.m.states:
                return str(node.value)
            return None
        if isinstance(node, ast.Name) and node.id in self.m.states:
            return node.id
        return None

    # -- condition narrowing

    def _narrow(self, test, P):
        """(possible-if-true, possible-if-false) given possible P."""
        if isinstance(test, ast.Constant):
            if test.value:
                return P, frozenset()
            return frozenset(), P
        if isinstance(test, ast.UnaryOp) \
                and isinstance(test.op, ast.Not):
            t, f = self._narrow(test.operand, P)
            return f, t
        if isinstance(test, ast.BoolOp):
            ts, fs = [], []
            for v in test.values:
                t, f = self._narrow(v, P)
                ts.append(t)
                fs.append(f)
            if isinstance(test.op, ast.And):
                t = P
                for x in ts:
                    t = frozenset(t & x)
                return t, P
            t = _union(*ts)
            f = P
            for x in fs:
                f = frozenset(f & x)
            return frozenset(t & P), f
        if isinstance(test, ast.Name) \
                and test.id in self.bool_narrow:
            t, f = self.bool_narrow[test.id]
            return frozenset(t & P), frozenset(f & P)
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and self._is_state_ref(test.left):
            op, rhs = test.ops[0], test.comparators[0]
            c = self._const_state(rhs)
            if c is not None:
                if isinstance(op, ast.Eq):
                    return frozenset(P & {c}), frozenset(P - {c})
                if isinstance(op, ast.NotEq):
                    return frozenset(P - {c}), frozenset(P & {c})
            if self.m.kind == "counter" \
                    and isinstance(rhs, ast.Constant) \
                    and isinstance(rhs.value, int):
                v = rhs.value
                val = self.m.states
                cmp = {ast.Gt: lambda s: val[s] > v,
                       ast.GtE: lambda s: val[s] >= v,
                       ast.Lt: lambda s: val[s] < v,
                       ast.LtE: lambda s: val[s] <= v}.get(type(op))
                if cmp is not None:
                    t = frozenset(s for s in P if cmp(s))
                    return t, frozenset(P - t)
        return P, P

    # -- violations

    def _flag(self, node, msg):
        self.out.append(Violation(
            "fsm-undeclared-transition", self.sf.rel, node.lineno,
            f"[{self.m.name}] {msg}"))

    def _write(self, node, P, dst):
        """Check one state write reaching targets `dst` from every
        possible source in P; returns the new possible set."""
        for src in sorted(P):
            if (src, dst) in self.m.transitions:
                self.observed.add((src, dst))
            else:
                self._flag(node,
                           f"undeclared transition {src} -> {dst} "
                           f"(declare it in tools/lint/fsm_registry.py "
                           f"or guard the write)")
        return frozenset({dst})

    # -- statement analysis

    def block(self, stmts, P) -> _Out:
        breaks: list = []
        continues: list = []
        for st in stmts:
            if P is None:
                break  # unreachable tail
            o = self._stmt(st, P)
            breaks.extend(o.breaks)
            continues.extend(o.continues)
            P = o.fall
        return _Out(P, breaks, continues)

    def _states_written_in(self, stmts) -> frozenset:
        """All state constants syntactically assigned anywhere in the
        block — the sound entry set for exception handlers."""
        found: set = set()
        for st in stmts:
            for node in ast.walk(st):
                if isinstance(node, ast.Assign) \
                        and any(self._is_state_ref_store(t)
                                for t in node.targets):
                    c = self._const_state(node.value)
                    if c is not None:
                        found.add(c)
                elif isinstance(node, ast.AugAssign) \
                        and self._is_state_ref_store(node.target):
                    found |= set(self.m.states)
        return frozenset(found)

    def _is_state_ref_store(self, node) -> bool:
        if self.m.kind in ("attr", "counter"):
            return (isinstance(node, ast.Attribute)
                    and node.attr == self.m.var
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self")
        return isinstance(node, ast.Name) and node.id == self.m.var

    def _stmt(self, st, P) -> _Out:
        if isinstance(st, (ast.Return, ast.Raise)):
            return _Out(None, [], [])
        if isinstance(st, ast.Break):
            return _Out(None, [P], [])
        if isinstance(st, ast.Continue):
            return _Out(None, [], [P])
        if isinstance(st, ast.Assign):
            return _Out(self._assign(st, P), [], [])
        if isinstance(st, ast.AugAssign):
            return _Out(self._augassign(st, P), [], [])
        if isinstance(st, ast.AnnAssign):
            if st.target is not None \
                    and self._is_state_ref_store(st.target) \
                    and st.value is not None:
                fake = ast.Assign(targets=[st.target], value=st.value)
                ast.copy_location(fake, st)
                return _Out(self._assign(fake, P), [], [])
            return _Out(P, [], [])
        if isinstance(st, ast.If):
            t, f = self._narrow(st.test, P)
            b = self.block(st.body, t)
            e = self.block(st.orelse, f)
            fall = None
            if b.fall is not None or e.fall is not None:
                fall = _union(b.fall, e.fall)
            return _Out(fall, b.breaks + e.breaks,
                        b.continues + e.continues)
        if isinstance(st, (ast.While, ast.For)):
            return self._loop(st, P)
        if isinstance(st, (ast.With, ast.AsyncWith)):
            return self.block(st.body, P)
        if isinstance(st, ast.Try):
            return self._try(st, P)
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return _Out(P, [], [])  # separate scope
        return _Out(P, [], [])

    def _assign(self, st, P):
        refs = [t for t in st.targets if self._is_state_ref_store(t)]
        if not refs:
            # a bool local assigned from a state comparison narrows a
            # later `if <local>:` (Lane.record_success's `readmitted`)
            if len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                name = st.targets[0].id
                if isinstance(st.value, ast.Compare):
                    t, f = self._narrow(st.value, P)
                    if (t, f) != (P, P):
                        self.bool_narrow[name] = (t, f)
                        return P
                self.bool_narrow.pop(name, None)
            return P
        if len(st.targets) != 1 or len(refs) != 1:
            self._flag(st, f"state {self.m.var} must be assigned "
                           f"alone, not in a multi-target assignment")
            return self.all
        c = self._const_state(st.value)
        if c is None:
            self._flag(st, f"state {self.m.var} assigned from a "
                           f"non-constant expression; only declared "
                           f"state constants may be assigned")
            return self.all
        real = frozenset(P - {_UM})
        if not real:
            # the machine's very first write: the initial-state check
            if c != self.m.initial:
                self._flag(st, f"initial state must be "
                               f"{self.m.initial}, not {c}")
            return frozenset({c})
        return self._write(st, real, c)

    def _augassign(self, st, P):
        if not self._is_state_ref_store(st.target):
            return P
        if self.m.kind != "counter":
            self._flag(st, f"state {self.m.var} stepped arithmetically "
                           f"but machine {self.m.name} is not a "
                           f"counter")
            return self.all
        step = None
        if isinstance(st.value, ast.Constant) \
                and isinstance(st.value.value, int):
            step = st.value.value
            if isinstance(st.op, ast.Sub):
                step = -step
            elif not isinstance(st.op, ast.Add):
                step = None
        if step not in (1, -1):
            self._flag(st, f"counter state {self.m.var} must step by "
                           f"exactly +/-1")
            return self.all
        P = frozenset(P - {_UM})
        if not P:
            self._flag(st, f"counter state {self.m.var} stepped "
                           f"before initialization")
            return self.all
        vals = self.m.states
        byval = {v: k for k, v in vals.items()}
        nxt: set = set()
        for s in sorted(P):
            d = byval.get(vals[s] + step)
            if d is None:
                # stepping out of the declared range is assumed
                # loop-guarded (the bound is a runtime value)
                continue
            nxt.add(d)
            if (s, d) in self.m.transitions:
                self.observed.add((s, d))
            else:
                self._flag(st, f"undeclared transition {s} -> {d}")
        return frozenset(nxt)

    def _loop(self, st, P) -> _Out:
        entry = P
        body_out = _Out(None, [], [])
        for _ in range(len(self.m.states) + 2):
            if isinstance(st, ast.While):
                t, _f = self._narrow(st.test, entry)
            else:
                t = entry
            body_out = self.block(st.body, t)
            back = _union(body_out.fall, *body_out.continues)
            new_entry = _union(entry, back)
            if new_entry == entry:
                break
            entry = new_entry
        if isinstance(st, ast.While):
            _t, f = self._narrow(st.test, entry)
            always = isinstance(st.test, ast.Constant) \
                and bool(st.test.value)
            normal = None if always else f
        else:
            normal = entry
        if st.orelse:
            e = self.block(st.orelse, normal or frozenset())
            normal = e.fall
        fall = _union(normal, *body_out.breaks) \
            if (normal is not None or body_out.breaks) else None
        return _Out(fall, [], [])

    def _try(self, st, P) -> _Out:
        body = self.block(st.body, P)
        breaks = list(body.breaks)
        continues = list(body.continues)
        h_entry = _union(P, self._states_written_in(st.body))
        falls = [body.fall]
        for h in st.handlers:
            ho = self.block(h.body, h_entry)
            falls.append(ho.fall)
            breaks.extend(ho.breaks)
            continues.extend(ho.continues)
        if st.orelse and body.fall is not None:
            eo = self.block(st.orelse, body.fall)
            falls[0] = eo.fall
            breaks.extend(eo.breaks)
            continues.extend(eo.continues)
        live = [f for f in falls if f is not None]
        fall = _union(*live) if live else None
        if st.finalbody:
            fin_in = _union(fall or frozenset(), h_entry)
            fo = self.block(st.finalbody, fin_in)
            breaks.extend(fo.breaks)
            continues.extend(fo.continues)
            if fo.fall is None:
                fall = None
        return _Out(fall, breaks, continues)

    # -- entry points

    def run_function(self, fn, is_init=False, local=False):
        self.bool_narrow = {}
        entry = _UNINIT if (local or is_init) else self.all
        self.block(fn.body, entry)


def _find_scope(tree, scope):
    """Locate the declared class or (possibly nested) function."""
    want_cls = scope[0] == "class"
    for node in ast.walk(tree):
        if want_cls and isinstance(node, ast.ClassDef) \
                and node.name == scope[1]:
            return node
        if not want_cls \
                and isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                and node.name == scope[1]:
            return node
    return None


def check_machine(m: Machine, root: Path):
    """Run the conformance pass for one machine. Returns the raw
    (unsuppressed) violation list plus the source file scanned, or
    (violations, None) when the file/scope is missing."""
    path = root / m.file
    if not path.exists():
        return [Violation("fsm-undeclared-transition", m.file, 1,
                          f"[{m.name}] declared file does not exist")], \
            None
    sf = load_source(path, root)
    scope = _find_scope(sf.tree, m.scope)
    out: list = []
    observed: set = set()
    if scope is None:
        out.append(Violation(
            "fsm-undeclared-transition", sf.rel, 1,
            f"[{m.name}] declared scope {m.scope[1]} not found"))
        return out, sf
    scan = _Scan(m, sf, observed, out)
    if m.scope[0] == "class":
        for node in scope.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                scan.run_function(node,
                                  is_init=node.name == "__init__")
    else:
        scan.run_function(scope, local=True)
    dead = m.transitions - observed
    for src, dst in sorted(dead):
        out.append(Violation(
            "fsm-dead-transition", sf.rel, scope.lineno,
            f"[{m.name}] declared transition {src} -> {dst} is never "
            f"taken by any code path — remove it from the registry or "
            f"restore the code path"))
    return out, sf


def check(root=None, files=None, machines=None):
    """Conformance-check every declared machine. `machines` overrides
    the registry (fixtures); `files` (iterable of repo-relative paths)
    restricts the scan to machines living in those files."""
    root = root or repo_root()
    machines = MACHINES if machines is None else machines
    if files is not None:
        keep = {str(f) for f in files}
        machines = [m for m in machines if m.file in keep]
    violations: list = []
    n_suppressed = 0
    by_file: dict = {}  # rel -> (sf, raw) so a file hosting two
    for m in machines:  # machines reports each suppression gap once
        raw, sf = check_machine(m, root)
        if sf is None:
            violations.extend(raw)
            continue
        entry = by_file.setdefault(sf.rel, (sf, []))
        entry[1].extend(raw)
    for sf, raw in by_file.values():
        kept, ns = apply_suppressions(sf, raw)
        violations.extend(kept)
        n_suppressed += ns
    return violations, n_suppressed
