"""Tests for the runtime config plane (configplane.py) and the knobs
registry's mutable-override machinery it sits on.
"""
from __future__ import annotations

import json

import pytest

from language_detector_tpu import configplane, knobs


@pytest.fixture(autouse=True)
def _clean_plane():
    configplane.reset_for_tests()
    yield
    configplane.reset_for_tests()


def _plane(burn=None):
    clock = _FakeClock()
    p = configplane.ConfigPlane(
        clock=clock, burn_source=(lambda: burn[0]) if burn is not None
        else (lambda: None))
    return p, clock


class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- knobs override machinery -------------------------------------------------


def test_mutable_knobs_are_declared():
    names = [k.name for k in knobs.mutable_knobs()]
    assert "LDT_MAX_INFLIGHT" in names
    assert "LDT_BROWNOUT_ALPHA" in names
    assert "LDT_CAPTURE_DIR" not in names  # paths stay immutable


def test_apply_overrides_is_atomic():
    v0 = knobs.overrides_version()
    with pytest.raises(ValueError):
        knobs.apply_overrides({"LDT_MAX_INFLIGHT": "64",
                               "LDT_BROWNOUT_ALPHA": "99"})  # out of range
    # nothing from the refused batch landed
    assert knobs.current()["overrides"] == {}
    assert knobs.overrides_version() == v0
    knobs.apply_overrides({"LDT_MAX_INFLIGHT": "64"})
    assert knobs.get_int("LDT_MAX_INFLIGHT") == 64
    assert knobs.overrides_version() == v0 + 1


def test_override_rejects_immutable_and_undeclared():
    with pytest.raises(ValueError, match="not a mutable"):
        knobs.apply_overrides({"LDT_CAPTURE_DIR": None})
    with pytest.raises(ValueError, match="undeclared"):
        knobs.apply_overrides({"LDT_NO_SUCH_KNOB": "1"})
    with pytest.raises(ValueError, match="not mutable"):
        knobs.apply_overrides({"LDT_SLO": "p99_ms=1"})


def test_none_removes_override():
    knobs.apply_overrides({"LDT_MAX_INFLIGHT": "64"})
    knobs.apply_overrides({"LDT_MAX_INFLIGHT": None})
    assert knobs.current()["overrides"] == {}


def test_bound_knob_accepts_nonpositive_as_off():
    knobs.apply_overrides({"LDT_MAX_INFLIGHT": "0"})
    assert knobs.get_int("LDT_MAX_INFLIGHT") is None  # bound: off


def test_doc_table_has_mutable_column():
    table = knobs.doc_table()
    assert "| Mutable |" in table.splitlines()[0]
    row = next(line for line in table.splitlines()
               if line.startswith("| `LDT_MAX_INFLIGHT` "))
    assert "yes [1, 65536]" in row


# -- plane FSM ----------------------------------------------------------------


def test_push_commits_after_probation_window():
    p, clock = _plane()
    snap = p.push({"LDT_MAX_INFLIGHT": "64"}, probation_sec=5.0)
    assert snap["state"] == "probation"
    assert knobs.get_int("LDT_MAX_INFLIGHT") == 64  # live immediately
    p.tick()
    assert p.state == configplane.CONFIG_PROBATION  # window not over
    clock.advance(5.1)
    p.tick()
    assert p.state == configplane.CONFIG_COMMITTED
    assert p.generation == 1


def test_zero_probation_commits_immediately():
    p, _clock = _plane()
    snap = p.push({"LDT_MAX_INFLIGHT": "64"}, probation_sec=0)
    assert snap["state"] == "committed"
    assert snap["generation"] == 1


def test_burn_during_probation_rolls_back_and_restores_prior():
    burn = [0.0]
    p, clock = _plane(burn)
    p.push({"LDT_MAX_INFLIGHT": "32"}, probation_sec=0)   # gen 1
    p.push({"LDT_MAX_INFLIGHT": "9999"}, probation_sec=5.0)
    assert knobs.get_int("LDT_MAX_INFLIGHT") == 9999
    burn[0] = 2.0
    p.tick()
    assert p.state == configplane.CONFIG_ROLLED_BACK
    # the prior committed override came back
    assert knobs.get_int("LDT_MAX_INFLIGHT") == 32
    assert p.generation == 1          # committed generation unchanged
    assert p.last_rollback["generation"] == 2
    assert "burn" in p.last_rollback["reason"]
    assert p.last_rollback["peak_burn"] == 2.0


def test_refused_batch_returns_to_idle_and_applies_nothing():
    p, _clock = _plane()
    snap = p.push({"LDT_MAX_INFLIGHT": "zebra"}, probation_sec=5.0)
    assert "error" in snap
    assert p.state == configplane.CONFIG_IDLE
    assert knobs.current()["overrides"] == {}


def test_push_refused_while_probation_in_flight():
    p, _clock = _plane()
    p.push({"LDT_MAX_INFLIGHT": "64"}, probation_sec=5.0)
    snap = p.push({"LDT_MAX_INFLIGHT": "32"}, probation_sec=5.0)
    assert "in flight" in snap["error"]
    assert knobs.get_int("LDT_MAX_INFLIGHT") == 64  # first batch holds


def test_rollback_then_next_push_restages():
    burn = [2.0]
    p, _clock = _plane(burn)
    p.push({"LDT_MAX_INFLIGHT": "64"}, probation_sec=5.0)
    p.tick()
    assert p.state == configplane.CONFIG_ROLLED_BACK
    burn[0] = 0.0
    snap = p.push({"LDT_MAX_INFLIGHT": "48"}, probation_sec=0)
    assert snap["state"] == "committed"
    assert knobs.get_int("LDT_MAX_INFLIGHT") == 48


def test_generation_stamp_is_honored():
    p, _clock = _plane()
    snap = p.push({"LDT_MAX_INFLIGHT": "64"}, probation_sec=0,
                  generation=41)
    assert snap["generation"] == 41


def test_sick_burn_source_does_not_wedge_probation():
    def explode():
        raise RuntimeError("scrape failed")

    clock = _FakeClock()
    p = configplane.ConfigPlane(clock=clock, burn_source=explode)
    p.push({"LDT_MAX_INFLIGHT": "64"}, probation_sec=5.0)
    clock.advance(5.1)
    p.tick()
    assert p.state == configplane.CONFIG_COMMITTED


# -- http-facing helpers ------------------------------------------------------


def test_handle_post_applies_and_reports():
    status, resp = configplane.handle_post(json.dumps(
        {"set": {"LDT_MAX_INFLIGHT": 64}, "probation_sec": 0}
    ).encode())
    assert status == 200
    assert resp["state"] == "committed"
    assert resp["values"]["LDT_MAX_INFLIGHT"] == 64


def test_handle_post_bad_shape_is_400():
    for body in (b"[]", b"{}", b'{"set": {}}', b"not json"):
        status, resp = configplane.handle_post(body)
        assert status == 400, body
        assert "error" in resp


def test_handle_post_conflict_is_409():
    configplane.handle_post(json.dumps(
        {"set": {"LDT_MAX_INFLIGHT": 64},
         "probation_sec": 60}).encode())
    status, resp = configplane.handle_post(json.dumps(
        {"set": {"LDT_MAX_INFLIGHT": 32}}).encode())
    assert status == 409
    assert "in flight" in resp["error"]


def test_handle_post_invalid_value_is_400():
    status, resp = configplane.handle_post(json.dumps(
        {"set": {"LDT_MAX_INFLIGHT": "zebra"}}).encode())
    assert status == 400
    assert "error" in resp


def test_handle_get_drives_probation():
    configplane.handle_post(json.dumps(
        {"set": {"LDT_MAX_INFLIGHT": 64},
         "probation_sec": 0.0}).encode())
    doc = configplane.handle_get()
    assert doc["state"] == "committed"
    assert doc["generation"] == 1
    assert doc["override_version"] == knobs.overrides_version()


def test_stats_none_until_plane_exists():
    assert configplane.stats() is None
    configplane.get_plane()
    assert configplane.stats() is not None


def test_maybe_tick_cheap_noop_without_plane():
    configplane.maybe_tick()  # must not create the plane
    assert configplane.PLANE is None


# -- debug_vars / metrics wiring ---------------------------------------------


def test_debug_vars_carries_config_section():
    from language_detector_tpu import telemetry

    d = telemetry.debug_vars()
    assert "config" in d
    assert d["config"]["generation"] == 0
    assert "LDT_MAX_INFLIGHT" in d["config"]["values"]
    configplane.handle_post(json.dumps(
        {"set": {"LDT_MAX_INFLIGHT": 64},
         "probation_sec": 0}).encode())
    d = telemetry.debug_vars()
    assert d["config"]["generation"] == 1
    assert d["config"]["values"]["LDT_MAX_INFLIGHT"] == 64


# -- admission controller pickup ---------------------------------------------
# regression: AdmissionController.from_env() used to pass the config
# positionally, which marked it injected and pinned _config_version to
# None — production fronts silently never saw a /configz override


def test_from_env_controller_picks_up_overrides():
    from language_detector_tpu.service.admission import (
        AdmissionController)

    ctl = AdmissionController.from_env()
    assert ctl._config_version is not None
    assert ctl.config.default_deadline_ms is None
    assert ctl.config.max_queue_docs is None
    configplane.handle_post(json.dumps(
        {"set": {"LDT_DEFAULT_DEADLINE_MS": "1",
                 "LDT_MAX_QUEUE_DOCS": "7"},
         "probation_sec": 0}).encode())
    ctl.try_admit(["hello world"])
    assert ctl.config.default_deadline_ms == 1.0
    assert ctl.config.max_queue_docs == 7
    dl = ctl.deadline_from_header(None)
    assert dl is not None and dl.remaining_ms() <= 1.0


def test_injected_config_controller_never_refreshes():
    from language_detector_tpu.service.admission import (
        AdmissionConfig, AdmissionController)

    ctl = AdmissionController(AdmissionConfig.from_env())
    assert ctl._config_version is None
    configplane.handle_post(json.dumps(
        {"set": {"LDT_MAX_QUEUE_DOCS": "7"},
         "probation_sec": 0}).encode())
    ctl.try_admit(["hello world"])
    assert ctl.config.max_queue_docs is None  # pinned, by contract
