"""Clean twin of jit_bad.py: donated buffers rebound before reuse,
captures limited to single-assignment factory state."""
import jax

TABLE_SCALE = 4.0  # module constant: always legal to read under jit


def accum_impl(acc, x):
    return acc + x


step = jax.jit(accum_impl, donate_argnums=(0,))


def run_donated(acc, xs):
    acc = step(acc, xs)  # rebind: the dead name never read again
    return acc * TABLE_SCALE


def make_entry(mesh):
    def entry(x):
        # `mesh` is assigned once per factory call: a per-instance
        # constant, not a per-call-varying capture
        return x + mesh.size

    return jax.jit(entry)
