"""Process-wide telemetry: latency histograms, request-scoped span
traces, compile-event tracking, and the slow-request sampler.

The reference service exports four flat Prometheus counters
(main.go:137-147); this module is the observability layer the TPU
redesign needs to say WHERE a slow request spent its time. Four pieces:

  Histogram        thread-safe fixed log-scaled latency buckets,
                   rendered in Prometheus exposition format
                   (`*_bucket`/`_sum`/`_count`) next to the counters
  Trace            request-scoped span recorder: monotonic-clock pairs,
                   one list append per span, no per-event allocation
                   beyond the span tuple — cheap enough for every
                   request on the hot path
  CompileTracker   first-execution detection per padded wire shape per
                   dispatch lane, exported as ldt_xla_compiles_total
                   (bucket-ladder churn becomes visible instead of
                   showing up as mystery multi-second requests)
  SlowTraceRing    bounded ring of full span trees for requests over
                   LDT_SLOW_TRACE_MS (off by default), served by
                   GET /debug/slow and `debug.py --slow-traces`

One module-level REGISTRY is shared by the sync and asyncio fronts, the
batcher flush workers, and the engine scheduler — a request's span tree
is assembled across all of them (handler spans + grafted flush spans),
and /metrics on either front renders the same registry.

Env knobs: LDT_SLOW_TRACE_MS (threshold, 0/unset = sampler off),
LDT_SLOW_TRACE_RING (ring capacity, default 64) — declared, like every
knob, in language_detector_tpu/knobs.py.
"""
from __future__ import annotations

import os
import time
from bisect import bisect_left
from collections import deque

from . import knobs
from .locks import make_lock

_mono = time.monotonic

_PROCESS_START = time.time()

# Central declaration of every ldt_* Prometheus series the package
# emits: name -> (type, help). This is the single source the /metrics
# renderers pull HELP/TYPE text from, and the contract `tools/lint`'s
# metric-registry analyzer enforces: a series used in code but not
# declared here, declared here but absent from docs/OBSERVABILITY.md
# (or vice versa), or declared but never emitted, all fail the lint.
METRICS: dict = {
    "ldt_stage_latency_ms": (
        "histogram",
        "Per-stage wall time (ms) through the request pipeline."),
    "ldt_request_latency_ms": (
        "histogram",
        "End-to-end HTTP request wall time (ms)."),
    "ldt_xla_compiles_total": (
        "counter",
        "Jitted-scorer compilations: first execution of a new "
        "padded wire shape, per dispatch lane."),
    "ldt_xla_compile_ms": (
        "histogram",
        "Dispatch wall time (ms) of first-execution (compiling) "
        "launches, per lane."),
    "ldt_shed_total": (
        "counter",
        "Requests shed by admission control, by reason "
        "(service/admission.py)."),
    "ldt_deadline_expired_total": (
        "counter",
        "Requests dropped at dequeue because their X-LDT-Deadline-Ms "
        "budget had already passed."),
    "ldt_batch_flushes_total": (
        "counter", "Engine batch flushes (all paths)."),
    "ldt_device_dispatches_total": (
        "counter",
        "Device program launches (recycle-watcher meter)."),
    "ldt_fallback_documents_total": (
        "counter",
        "Documents resolved off the device path "
        "(packer fallback + gate recursion)."),
    "ldt_tier_dispatches_total": (
        "counter", "Dispatches per shape-tier lane."),
    "ldt_retry_lane_dispatches_total": (
        "counter", "Overlapped retry-lane dispatches."),
    "ldt_dedup_documents_total": (
        "counter", "Documents answered by batch-internal dedup."),
    "ldt_result_cache_hit_rate": (
        "gauge", "Result-cache hit rate since start."),
    "ldt_result_cache_hits_total": (
        "counter", "Result-cache hits."),
    "ldt_result_cache_bytes": (
        "gauge", "Result-cache resident bytes."),
    "ldt_admission_queue_docs": (
        "gauge", "Documents admitted and not yet completed."),
    "ldt_admission_queue_bytes": (
        "gauge",
        "Byte-weighted admission cost currently held "
        "(4 bytes per estimated packer slot)."),
    "ldt_admission_inflight": (
        "gauge", "HTTP requests admitted and in flight."),
    "ldt_brownout_level": (
        "gauge",
        "Graceful-degradation level (0=healthy 1=skip-retry-lane "
        "2=cache+scalar-only 3=shed-non-priority)."),
    "ldt_breaker_state": (
        "gauge",
        "Device-path circuit breaker (0=closed 1=half-open 2=open)."),
    "ldt_fault_injected_total": (
        "counter",
        "Injected faults that actually fired, by fault point "
        "(language_detector_tpu/faults.py, LDT_FAULTS spec)."),
    "ldt_ready": (
        "gauge",
        "Readiness: 1 when the artifact is loaded, the breaker is not "
        "open, and brownout is below shed — the /readyz contract."),
    "ldt_worker_generation": (
        "gauge",
        "Worker generation under the supervisor (LDT_WORKER_GENERATION"
        "; 0 = unsupervised)."),
    "ldt_swap_total": (
        "counter",
        "Artifact hot swaps by result: ok (new tables serving — "
        "counted by a standby generation once ready, or by "
        "service/swap.py after an in-process rebind), error (aborted; "
        "the old tables keep serving), or integrity_refused (the "
        "standby artifact failed its digest footer; the old tables "
        "keep serving)."),
    "ldt_integrity_scrub_total": (
        "counter",
        "Integrity scrub passes per pool lane by result: ok, mismatch "
        "(digest or canary deviation — the lane quarantined), or "
        "error (the scrub itself failed; the lane keeps serving and "
        "the next pass retries)."),
    "ldt_integrity_detected_total": (
        "counter",
        "Corruption detections by kind (scrub = device table digest "
        "mismatch, canary = golden-query deviation, frame_crc = "
        "wire/shm payload CRC mismatch) and lane."),
    "ldt_integrity_healed_total": (
        "counter",
        "Quarantined lanes healed: fresh tables re-uploaded from the "
        "host mmap, fingerprint re-verified, lane re-admitted through "
        "the half-open probe flow."),
    "ldt_integrity_crc_total": (
        "counter",
        "Frame payload CRC32 checks by ingest lane and result "
        "(LDT_WIRE_CRC; a mismatch refuses the frame with a typed 400 "
        "before any parse)."),
    "ldt_warmup_ms": (
        "gauge",
        "Startup bucket-ladder warmup duration (LDT_WARMUP); 0 until "
        "warmup completes / when warmup is off."),
    "ldt_tenant_shed_total": (
        "counter",
        "Requests shed by admission control, by tenant and reason "
        "(X-LDT-Tenant header; absent = \"default\")."),
    "ldt_tenant_queue_bytes": (
        "gauge",
        "Byte-weighted admission cost currently held, per tenant."),
    "ldt_pool_lane_evicted_total": (
        "counter",
        "Device-pool lanes evicted from rotation after consecutive "
        "failures (parallel/pool.py), per lane."),
    "ldt_pool_lane_readmitted_total": (
        "counter",
        "Evicted lanes re-admitted to rotation after a successful "
        "half-open probe, per lane."),
    "ldt_pool_failover_total": (
        "counter",
        "Batches re-dispatched on a surviving lane after a lost-batch "
        "error on their original lane."),
    "ldt_pool_hedges_total": (
        "counter",
        "Straggler hedges by outcome: result=won (the hedge answered "
        "first) or result=lost (the original dispatch finished first)."),
    "ldt_pool_probe_admits_total": (
        "counter",
        "Requests admitted through a full-shed brownout as the pool's "
        "half-open probe vehicle (probes are traffic-driven; a blanket "
        "shed would leave a fully evicted pool down forever)."),
    "ldt_pool_lanes_active": (
        "gauge",
        "Device-pool lanes currently in rotation (active + probing)."),
    "ldt_pool_lanes_total": (
        "gauge", "Device-pool lane count (0 = pool disabled)."),
    "ldt_pipeline_overlap_ratio": (
        "gauge",
        "Fraction of host pack wall time that ran while a device "
        "dispatch was in flight (models/ngram.py pipeline; 0 = fully "
        "serial)."),
    "ldt_pipeline_depth": (
        "gauge",
        "Configured dispatch-pipeline depth (LDT_PIPELINE_DEPTH; 1 = "
        "serial reference path)."),
    "ldt_pipeline_donation_hits_total": (
        "counter",
        "Launches through the donating jitted scorer "
        "(donate_argnums): the device reused the dispatch buffers "
        "instead of allocating fresh ones."),
    "ldt_pipeline_staging_ring_occupancy": (
        "gauge",
        "Host staging-ring arrays currently checked out by in-flight "
        "dispatches (native pack staging; steady state stays below "
        "the ring capacity, so packing allocates nothing)."),
    "ldt_pipeline_longdoc_chunks_total": (
        "counter",
        "Span-aligned sub-documents created by the long-doc lane "
        "(LDT_LONGDOC_CHUNK_SLOTS splitting in preprocess/pack.py)."),
    "ldt_http_parse_ms": (
        "histogram",
        "Request-body parse wall time (ms) through the shared wire "
        "path (service/wire.py), fast scanner and json.loads "
        "fallback alike, on every lane."),
    "ldt_http_serialize_ms": (
        "histogram",
        "Response assembly wall time (ms): per-code fragment fill + "
        "writev-style buffer-list build (wire.post_detect)."),
    "ldt_http_parse_fast_total": (
        "counter",
        "Zero-copy scanner outcomes by result=hit|miss; a miss fell "
        "back to json.loads (non-conforming shape, escapes needing "
        "exact semantics, or invalid bodies)."),
    "ldt_http_requests_total": (
        "counter",
        "Detection requests by ingest lane (lane=tcp|uds), counted "
        "on both fronts."),
    "ldt_fleet_spawn_total": (
        "counter",
        "Fleet member spawns by reason=initial|restart|probe|swap "
        "(service/fleet.py)."),
    "ldt_fleet_worker_lost_total": (
        "counter",
        "Fleet members lost by reason=crash (nonzero exit) or "
        "reason=lost (killed via the worker_lost fault seam / health "
        "kill)."),
    "ldt_fleet_scale_total": (
        "counter",
        "Autoscale steps by direction=up|down (hysteresis-held queue "
        "depth / brownout signal)."),
    "ldt_fleet_desired": (
        "gauge",
        "Fleet desired member count (between LDT_FLEET_MIN/MAX)."),
    "ldt_fleet_ready": (
        "gauge", "Fleet members currently READY."),
    "ldt_fleet_members": (
        "gauge",
        "Fleet member slots (including spawning/dead/parked)."),
    "ldt_fleet_circuit_state": (
        "gauge",
        "Fleet crash circuit: 0 closed, 1 open (correlated crash — "
        "restarts parked), 2 half-open probe in flight."),
    "ldt_fleet_config_heal_total": (
        "counter",
        "Members re-pushed onto the fleet-committed config by the "
        "supervisor's heal pass (a respawn or missed fan-out left "
        "them on an older config generation)."),
    "ldt_shm_rings": (
        "gauge",
        "Shared-memory ring files currently attached by the scan "
        "thread (service/shmring.py)."),
    "ldt_shm_slots_free": (
        "gauge",
        "FREE slots across all attached shm rings (ring capacity "
        "headroom; equals total slots when the lane is idle)."),
    "ldt_shm_frames_total": (
        "counter",
        "Frames answered on the shm ring lane by result=ok|error|"
        "fenced (fenced = stale-generation frame failed back with an "
        "explicit error frame)."),
    "ldt_shm_reclaimed_total": (
        "counter",
        "Ring slots reclaimed by reason=writer-lost (client dead or "
        "stalled mid-WRITING), client-dead (unconsumed DONE), "
        "generation (fenced frame failed back), corrupt (header with "
        "no legal transition path), attach-fault (injected attach "
        "failure, ring retried)."),
    "ldt_quarantine_docs_total": (
        "counter",
        "Docs quarantined after bisection proved they "
        "deterministically kill a scorer batch; quarantined docs "
        "answer \"un\" and never reach the scorer again."),
    "ldt_quarantine_bisect_total": (
        "counter",
        "Bisection passes run while isolating poison docs from a "
        "killed batch (each pass re-scores the two halves)."),
    "ldt_device_ms": (
        "histogram",
        "Per-flush device-vs-host wall time split (ms) from the engine "
        "epilogue: phase=device is the device wait (fetch start to "
        "rows on host), phase=host is the native epilogue."),
    "ldt_error_traces_total": (
        "counter",
        "Span trees force-recorded into the slow ring because the "
        "request answered 5xx (reason:error capture — recorded "
        "regardless of LDT_SLOW_TRACE_MS)."),
    "ldt_flightrec_events_total": (
        "counter",
        "Structured events written to the crash-safe flight recorder "
        "(language_detector_tpu/flightrec.py, LDT_FLIGHTREC_DIR)."),
    "ldt_flightrec_dropped_total": (
        "counter",
        "Flight-recorder events dropped because their payload "
        "exceeded the ring's slot capacity."),
    "ldt_postmortem_total": (
        "counter",
        "Dead-member flight recorders harvested into postmortem JSON "
        "by the fleet/worker supervisor, by result=ok|empty|error."),
    "ldt_profile_captures_total": (
        "counter",
        "On-demand device-profiler capture windows, by "
        "result=ok|error|busy|unavailable (POST /profilez, SIGUSR2)."),
    "ldt_aot_loads_total": (
        "counter",
        "Bucket-ladder executables deserialized from the AOT bundle "
        "(LDT_AOT_DIR, aot.py) instead of compiled — the boot-hot "
        "path; one per ladder tier per process."),
    "ldt_aot_exports_total": (
        "counter",
        "Compiled scorers serialized into the AOT bundle (write-back "
        "after a compiling launch); the next generation loads these."),
    "ldt_aot_refused_total": (
        "counter",
        "AOT bundle entries refused by reason=missing|corrupt|"
        "digest_mismatch|jax_mismatch|backend_mismatch|kernel_mismatch"
        "|shape_mismatch|undeserializable|io_error|empty — each "
        "refusal falls back to a fresh compile (or raises under "
        "LDT_AOT_REQUIRE) and is overwritten by write-back."),
    "ldt_shared_cache_hits_total": (
        "counter",
        "Fleet-shared result-cache hits (LDT_RESULT_CACHE_SHM_MB, "
        "service/sharedcache.py): a doc answered from another "
        "worker's published result."),
    "ldt_shared_cache_misses_total": (
        "counter",
        "Fleet-shared result-cache lookups that found no live entry "
        "(absent, epoch-stale, torn, or CRC-refused slots all count "
        "here — the read path never distinguishes, it just misses)."),
    "ldt_shared_cache_evictions_total": (
        "counter",
        "Shared-cache slots overwritten by a new key whose probe "
        "window was full (deterministic displacement eviction)."),
    "ldt_shared_cache_epoch_flush_total": (
        "counter",
        "Shared-cache entries invalidated by an artifact-swap epoch "
        "sweep (stale-epoch slots freed so a new artifact can never "
        "serve the old artifact's results)."),
    # -- traffic capture plane (capture.py) ---------------------------
    "ldt_capture_records_total": (
        "counter",
        "Requests recorded by the traffic-capture plane (committed "
        "into the capture ring; excludes sampled-out requests)."),
    "ldt_capture_sampled_out_total": (
        "counter",
        "Requests skipped by LDT_CAPTURE_SAMPLE probabilistic "
        "sampling (capture armed but the coin came up tails)."),
    "ldt_capture_ring_occupancy": (
        "gauge",
        "Committed records currently in this process's active capture "
        "ring (seals into a segment at LDT_CAPTURE_RING_RECORDS)."),
    "ldt_capture_segments_total": (
        "counter",
        "Capture rings sealed into immutable segment files (size-"
        "bounded rotation; oldest segments pruned past "
        "LDT_CAPTURE_MAX_SEGMENTS)."),
    # -- SLO engine (slo.py) ------------------------------------------
    "ldt_slo_events_total": (
        "counter",
        "Completed requests scored by the SLO engine, labelled by "
        "result: good, bad (error or over-target latency), or shed "
        "(load-shedding rejections burn error budget separately)."),
    "ldt_slo_breaches_total": (
        "counter",
        "SLO burn-rate alerts fired (fleet scope and per-tenant "
        "scopes both count; see slo_breach flight-recorder events "
        "for the attribution)."),
    "ldt_slo_alert": (
        "gauge",
        "1 while a fleet-scope SLO burn-rate alert is firing, else 0 "
        "(per-tenant alert states are on /sloz)."),
    "ldt_slo_burn_rate": (
        "gauge",
        "Fleet-scope error-budget burn rate per window (window=fast|"
        "slow): 1.0 burns exactly the declared budget; sustained "
        ">1.0 in both windows fires the alert."),
    "ldt_slo_budget_remaining": (
        "gauge",
        "Fraction of the fleet-scope error budget left in the slow "
        "window (1.0 = untouched, 0 = fully burned)."),
    # -- runtime config plane (configplane.py) ------------------------
    "ldt_config_generation": (
        "gauge",
        "Last COMMITTED runtime-config generation in this process "
        "(0 = no POST /configz apply ever committed)."),
    "ldt_config_state": (
        "gauge",
        "Config-plane FSM state (0=idle 1=staged 2=probation "
        "3=committed 4=rolled_back)."),
    "ldt_config_applies_total": (
        "counter",
        "POST /configz apply outcomes by result: applied (live under "
        "SLO probation), committed (survived the window), rolled_back "
        "(fast-window burn crossed 1.0 — the prior overrides were "
        "restored), or refused (registry type/bound/range validation "
        "failed; nothing applied)."),
    # -- SLO autotuner (autotune.py, bench.py --autotune) -------------
    "ldt_autotune_evals_total": (
        "counter",
        "Autotuner candidate-config evaluations: one scored probe "
        "(replayed traffic slice) per candidate in the coordinate-"
        "descent search over the mutable-knob space."),
    "ldt_autotune_rounds_total": (
        "counter",
        "Autotuner coordinate-descent passes over the declared "
        "mutable-knob space (a pass with no improvement ends the "
        "search)."),
    # -- disk-full hardening (capture.py, flightrec.py, aot.py) -------
    "ldt_capture_disabled_total": (
        "counter",
        "Traffic-capture plane disabled at runtime by reason=enospc "
        "(ring create or segment seal hit a disk-full/unwritable "
        "OSError); serving continues, capture becomes a no-op."),
    "ldt_flightrec_disabled_total": (
        "counter",
        "Flight recorder disabled at runtime by reason=enospc (ring "
        "create/mmap hit a disk-full OSError); serving continues, "
        "every emit is one None check."),
    "ldt_aot_disabled_total": (
        "counter",
        "AOT export write-back disabled for the process by "
        "reason=enospc (a bundle write hit a disk-full OSError); "
        "loads keep working and serving is untouched."),
    # -- accuracy plane (evalsuite.py, detect_spans lane) -------------
    "ldt_span_docs_total": (
        "counter",
        "Documents answered through the per-span lane (detect_spans; "
        "LDT_SPANS=1 surfaces). Each doc also appears in the regular "
        "dispatch counters — this series measures span-lane share."),
    "ldt_eval_docs_total": (
        "counter",
        "Labeled corpus documents scored by the eval scorecard "
        "(evalsuite.run_eval; bench.py --eval). Counts per run, so "
        "rate() over it shows scorecard cadence, not serving load."),
}


def metric_help(name: str) -> str:
    return METRICS[name][1] if name in METRICS else name


def metric_family(name: str, samples: list) -> tuple:
    """(name, type, help, samples) exposition family for a DECLARED
    ldt_* series — the renderers build gauge/counter families through
    this so HELP/TYPE text has exactly one source."""
    mtype, help_text = METRICS[name]
    return (name, mtype, help_text, samples)

# Log-scaled (base-2) latency bucket upper bounds in milliseconds:
# 0.05ms .. ~105s. One fixed ladder for every latency series keeps the
# exposition predictable and cross-stage comparisons trivial.
BUCKET_EDGES_MS = tuple(0.05 * 2 ** k for k in range(22))


class Histogram:
    """Thread-safe fixed-bucket latency histogram.

    Cumulative-bucket semantics match Prometheus: bucket i counts
    observations <= BUCKET_EDGES_MS[i] when rendered (counts are stored
    per-bucket and cumulated at render/percentile time, so observe()
    stays one bisect + two adds under the lock)."""

    __slots__ = ("edges", "counts", "sum", "count", "max", "_lock")

    def __init__(self, edges=BUCKET_EDGES_MS):
        self.edges = edges
        self.counts = [0] * (len(edges) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        self.max = 0.0
        self._lock = make_lock("telemetry.histogram")

    def observe(self, value_ms: float):
        i = bisect_left(self.edges, value_ms)
        with self._lock:
            self.counts[i] += 1
            self.sum += value_ms
            self.count += 1
            if value_ms > self.max:
                self.max = value_ms

    def snapshot(self):
        """(per-bucket counts, sum, count, max) under one lock."""
        with self._lock:
            return list(self.counts), self.sum, self.count, self.max

    def percentile(self, q: float):
        """Approximate q-th percentile (q in [0, 100]) by linear
        interpolation inside the holding bucket; the +Inf bucket answers
        the observed max. None when empty."""
        counts, _, total, vmax = self.snapshot()
        if total == 0:
            return None
        target = total * q / 100.0
        cum = 0
        lo = 0.0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target and c > 0:
                if i >= len(self.edges):
                    return vmax
                hi = min(self.edges[i], vmax) if vmax > 0 else \
                    self.edges[i]
                if hi < lo:
                    hi = self.edges[i]
                frac = (target - (cum - c)) / c
                return lo + (hi - lo) * frac
            if i < len(self.edges):
                lo = self.edges[i]
        return vmax


class Trace:
    """One request's span recorder.

    Spans are (name, depth, start, end) tuples of monotonic seconds —
    recorded with a single list append (GIL-atomic, so flush workers on
    other threads may add() into a request's trace concurrently).
    Parent/child structure is carried by `depth` plus time order; the
    tree is reconstructed at render time, never maintained on the hot
    path."""

    __slots__ = ("t0", "t_wall", "spans", "deadline", "no_retry",
                 "tenant", "request_id", "finished")

    def __init__(self):
        self.t0 = _mono()
        self.t_wall = time.time()
        self.spans: list = []
        # admission-control freight riding the existing trace plumbing
        # (service/admission.py): the request's Deadline, whether
        # the engine should resolve gate failures scalar instead of
        # running the pipelined retry lane (brownout / near-deadline),
        # and the tenant identity for fair-queueing at dequeue
        self.deadline = None
        self.no_retry = False
        self.tenant = None
        # end-to-end correlation id (X-LDT-Request-Id / UDS v2 ext /
        # shm slot header): stamped by the front, echoed on the
        # response, carried into slow traces and flight-recorder
        # request events so /tracez can join one document's journey
        # across processes
        self.request_id = None
        # completion latch: finish_request() is the single
        # authoritative completion path and flips this exactly once,
        # so telemetry, capture, and SLO can never double-count a
        # request whose handler unwinds through two finish sites
        # (e.g. a 504-after-shed)
        self.finished = False

    def add(self, name: str, t0: float, t1: float, depth: int = 0):
        self.spans.append((name, depth, t0, t1))

    def graft(self, other: "Trace", depth: int = 1):
        """Adopt another trace's spans (a batch flush shared by several
        requests) as children at `depth` — called once per request per
        flush, off the per-event path."""
        self.spans.extend((n, d + depth, s, e)
                          for n, d, s, e in other.spans)

    def adopt_constraints(self, traces):
        """Flush-scoped traces inherit the TIGHTEST deadline and any
        no-retry flag of the request traces batched into them — the
        engine scheduler reads constraints off the one trace it is
        handed (both batchers call this when building a flush)."""
        for tr in traces:
            if tr is None:
                continue
            dl = tr.deadline
            if dl is not None and (self.deadline is None or
                                   dl.t_end < self.deadline.t_end):
                self.deadline = dl
            if tr.no_retry:
                self.no_retry = True

    def total_ms(self) -> float:
        return (_mono() - self.t0) * 1e3

    def span_ms(self, name: str) -> float:
        """Total milliseconds across spans with this name (a request can
        record several dispatch spans)."""
        return sum((e - s) for n, _, s, e in self.spans if n == name) \
            * 1e3

    def to_dict(self, total_ms: float | None = None,
                meta: dict | None = None) -> dict:
        base = self.t0
        spans = sorted(self.spans, key=lambda sp: (sp[2], sp[1]))
        out = {
            "ts": self.t_wall,
            "total_ms": round(self.total_ms()
                              if total_ms is None else total_ms, 3),
            "meta": meta or {},
            "spans": [{"name": n, "depth": d,
                       "start_ms": round((s - base) * 1e3, 3),
                       "dur_ms": round((e - s) * 1e3, 3)}
                      for n, d, s, e in spans],
        }
        if self.request_id is not None:
            out["request_id"] = self.request_id
        return out


class CompileTracker:
    """First-execution detection for jitted entry points: one padded
    wire shape per dispatch lane counts exactly once. The engine keys on
    (lane, mesh size, wire array shapes) — the same signature XLA's jit
    cache keys on (dtypes are fixed), so a fresh key means the dispatch
    about to run pays a trace + compile."""

    def __init__(self):
        self._seen: set = set()
        self._lock = make_lock("telemetry.compiles")

    def first_seen(self, lane: str, key) -> bool:
        k = (lane, key)
        with self._lock:
            if k in self._seen:
                return False
            self._seen.add(k)
            return True

    def __len__(self):
        with self._lock:
            return len(self._seen)

    def clear(self):
        with self._lock:
            self._seen.clear()


class SlowTraceRing:
    """Bounded ring of span trees for requests over the threshold.

    Off by default: LDT_SLOW_TRACE_MS unset/0 means maybe_record is a
    single float compare. The deque's maxlen IS the eviction policy —
    the newest `capacity` slow traces win."""

    def __init__(self, capacity: int | None = None,
                 threshold_ms: float | None = None):
        if capacity is None:
            capacity = knobs.get_int("LDT_SLOW_TRACE_RING") or 64
        if threshold_ms is None:
            threshold_ms = knobs.get_float("LDT_SLOW_TRACE_MS") or 0.0
        self.capacity = max(capacity, 1)
        self.threshold_ms = threshold_ms
        self.recorded = 0  # total ever recorded (evictions included)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = make_lock("telemetry.slow_ring")

    def maybe_record(self, trace: Trace, total_ms: float,
                     meta: dict | None = None) -> bool:
        if self.threshold_ms <= 0 or total_ms < self.threshold_ms:
            return False
        self.record(trace, total_ms, meta=meta)
        return True

    def record(self, trace: Trace, total_ms: float,
               meta: dict | None = None) -> None:
        """Unconditional record — the error-capture path (5xx answers
        keep their span tree regardless of the sampling threshold)."""
        d = trace.to_dict(total_ms=total_ms, meta=meta)
        with self._lock:
            self._ring.append(d)
            self.recorded += 1

    def snapshot(self) -> list:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self.recorded = 0


# -- Prometheus exposition rendering ----------------------------------------


def escape_label_value(v) -> str:
    """Label-value escaping per the exposition format: backslash, double
    quote, and newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_labels(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


def render_exposition(families) -> str:
    """families: iterable of (name, type, help, samples); each sample is
    (series_name, labels dict | None, value). Emits `# HELP` + `# TYPE`
    for every family and escapes every label value — the whole /metrics
    body passes a strict exposition parser
    (tests/test_telemetry.py::test_metrics_exposition_lint)."""
    lines: list = []
    for name, mtype, help_text, samples in families:
        lines.append(f"# HELP {name} {_escape_help(help_text)}")
        lines.append(f"# TYPE {name} {mtype}")
        for series, labels, value in samples:
            lines.append(f"{series}{_fmt_labels(labels)} "
                         f"{_fmt_value(value)}")
    return "\n".join(lines) + "\n"


def histogram_family(name: str, help_text: str, labeled_hists) -> tuple:
    """One histogram family from {labels-tuple: Histogram}: cumulative
    `_bucket` series (le as the LAST label), `_sum`, `_count`."""
    samples: list = []
    for label_items, hist in sorted(labeled_hists.items()):
        base = dict(label_items)
        counts, total_sum, total, _ = hist.snapshot()
        cum = 0
        for i, edge in enumerate(hist.edges):
            cum += counts[i]
            samples.append((f"{name}_bucket",
                            {**base, "le": repr(float(edge))}, cum))
        cum += counts[len(hist.edges)]
        samples.append((f"{name}_bucket", {**base, "le": "+Inf"}, cum))
        samples.append((f"{name}_sum", base or None,
                        round(total_sum, 6)))
        samples.append((f"{name}_count", base or None, total))
    return (name, "histogram", help_text, samples)


# -- registry ---------------------------------------------------------------


class TelemetryRegistry:
    """Histograms + counters keyed (name, sorted label items), plus the
    compile tracker and the slow-trace ring. Shared process-wide (module
    REGISTRY below); reset() clears in place so every holder of the
    reference sees the fresh state (tests)."""

    def __init__(self):
        self._lock = make_lock("telemetry.registry")
        self._hists: dict = {}     # (name, label items) -> Histogram
        self._counters: dict = {}  # (name, label items) -> number
        self.compiles = CompileTracker()
        self.slow = SlowTraceRing()

    @staticmethod
    def _key(name: str, labels: dict):
        return name, tuple(sorted(labels.items()))

    def histogram(self, name: str, **labels) -> Histogram:
        k = self._key(name, labels)
        # ldt-lint: disable=lock-discipline -- benign racy fast path: dict.get on a grow-only map; a miss falls through to the locked setdefault below
        h = self._hists.get(k)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(k, Histogram())
        return h

    def histogram_peek(self, name: str, **labels) -> "Histogram | None":
        """Read-only lookup: None instead of creating — load estimators
        (admission.expected_flush_ms) poll stages that may never run on
        this front, and each poll must not mint an empty series into
        the exposition."""
        return self._hists.get(self._key(name, labels))  # ldt-lint: disable=lock-discipline -- benign racy read of a grow-only map; a stale None only delays one estimator poll

    def percentile_across(self, name: str, q: float):
        """Max q-th percentile across every label set of a histogram
        family (None when the family is empty) — the breaker's
        compile-aware watchdog reads the worst lane."""
        with self._lock:
            hists = [h for (n, _), h in self._hists.items() if n == name]
        vals = [p for p in (h.percentile(q) for h in hists)
                if p is not None]
        return max(vals) if vals else None

    def counter_inc(self, name: str, amount=1, **labels):
        k = self._key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0) + amount

    def counter_value(self, name: str, **labels):
        with self._lock:
            return self._counters.get(self._key(name, labels), 0)

    def families(self) -> list:
        """Exposition families for everything in the registry."""
        with self._lock:
            hists = dict(self._hists)
            counters = dict(self._counters)
        fams: list = []
        by_name: dict = {}
        for (name, litems), h in hists.items():
            by_name.setdefault(name, {})[litems] = h
        for name in sorted(by_name):
            fams.append(histogram_family(
                name, metric_help(name), by_name[name]))
        cnt_by_name: dict = {}
        for (name, litems), v in counters.items():
            cnt_by_name.setdefault(name, []).append((litems, v))
        for name in sorted(cnt_by_name):
            samples = [(name, dict(litems) or None, v)
                       for litems, v in sorted(cnt_by_name[name])]
            fams.append((name, "counter", metric_help(name), samples))
        return fams

    def stage_percentiles(self) -> dict:
        """{stage: {count, p50, p95, p99, mean}} over the
        ldt_stage_latency_ms histograms — bench.py's per-stage report
        and /debug/vars both read this."""
        with self._lock:
            hists = {litems: h for (name, litems), h
                     in self._hists.items()
                     if name == "ldt_stage_latency_ms"}
        out: dict = {}
        for litems, h in hists.items():
            stage = dict(litems).get("stage", "?")
            _, total_sum, total, _ = h.snapshot()
            if not total:
                continue
            out[stage] = {
                "count": total,
                "mean": round(total_sum / total, 3),
                "p50": round(h.percentile(50), 3),
                "p95": round(h.percentile(95), 3),
                "p99": round(h.percentile(99), 3),
            }
        return out

    def compile_counts(self) -> dict:
        """{lane: count} view of ldt_xla_compiles_total."""
        with self._lock:
            items = [(dict(litems).get("lane", "?"), v)
                     for (name, litems), v in self._counters.items()
                     if name == "ldt_xla_compiles_total"]
        return dict(items)

    def reset(self):
        """Clear in place (module REGISTRY is shared by reference)."""
        with self._lock:
            self._hists.clear()
            self._counters.clear()
        self.compiles.clear()
        self.slow.clear()
        # re-read env knobs so tests that monkeypatch them take effect
        self.slow.__init__()


REGISTRY = TelemetryRegistry()


def observe_stage(stage: str, t0: float, t1: float | None = None,
                  trace: Trace | None = None, depth: int = 0) -> float:
    """Record one pipeline stage: observe its latency histogram and,
    when a trace rides along, append the span. Returns t1 so callers can
    chain stages without re-reading the clock."""
    if t1 is None:
        t1 = _mono()
    REGISTRY.histogram("ldt_stage_latency_ms", stage=stage) \
        .observe((t1 - t0) * 1e3)
    if trace is not None:
        trace.add(stage, t0, t1, depth)
    return t1


def finish_request(trace: Trace, meta: dict | None = None) -> float:
    """End-of-request hook for both fronts and every ingest lane:
    total latency into the request histogram, span tree into the slow
    ring when over threshold — or unconditionally, tagged
    reason:error, when the request answered 5xx (a failing request's
    trace is exactly the one an operator needs, and sampling only
    slow-but-successful requests would discard it). Also stamps the
    request id into the meta and emits the flight-recorder
    request_end event, then feeds the capture plane and SLO engine —
    this is the single authoritative completion path, and the trace's
    `finished` latch makes it idempotent: a handler that unwinds
    through two finish sites (504-after-shed) counts exactly once in
    telemetry, capture, and SLO alike. Returns total ms."""
    total = trace.total_ms()
    if getattr(trace, "finished", False):
        return total
    trace.finished = True
    REGISTRY.histogram("ldt_request_latency_ms").observe(total)
    if meta is not None and trace.request_id is not None:
        meta.setdefault("request_id", trace.request_id)
    status = (meta or {}).get("status")
    from . import flightrec
    if isinstance(status, int) and status >= 500:
        err_meta = dict(meta or {})
        err_meta["reason"] = "error"
        REGISTRY.slow.record(trace, total, meta=err_meta)
        REGISTRY.counter_inc("ldt_error_traces_total")
        flightrec.emit_event("slow_trace", request_id=trace.request_id,
                             total_ms=round(total, 3), reason="error")
    elif REGISTRY.slow.maybe_record(trace, total, meta=meta):
        flightrec.emit_event("slow_trace", request_id=trace.request_id,
                             total_ms=round(total, 3),
                             reason="threshold")
    flightrec.emit_event("request_end",
                         request_id=trace.request_id,
                         status=status,
                         total_ms=round(total, 3),
                         **({"front": meta["front"]}
                            if meta and "front" in meta else {}))
    # capture plane + SLO engine ride the same completion edge (both
    # are a single None check when their knob is unset); lazy imports
    # keep module-load order acyclic
    from . import capture as _capture
    from . import slo as _slo
    _capture.observe(trace, meta, total)
    _slo.observe(trace, meta, total)
    # a config probation, if one is in flight, advances on the same
    # edge (one module-attribute check when no plane exists)
    from . import configplane as _configplane
    _configplane.maybe_tick()
    return total


# -- /debug/vars ------------------------------------------------------------


def _rss_bytes() -> int:
    """Current RSS from /proc (Linux); ru_maxrss (peak) as fallback."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        try:
            import resource
            return resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:  # noqa: BLE001 - best-effort gauge
            return 0


def debug_vars(metrics=None) -> dict:
    """statusz-style process snapshot: engine stats, cache stats,
    request counters, process uptime/RSS, stage percentiles, compile
    counts, slow-ring occupancy. One serializer shared by both fronts'
    GET /debug/vars."""
    d: dict = {
        "pid": os.getpid(),
        "uptime_sec": round(time.time() - _PROCESS_START, 3),
        "rss_bytes": _rss_bytes(),
    }
    if metrics is not None:
        with metrics._lock:
            d["counters"] = dict(metrics.counters)
            d["objects"] = dict(metrics.objects)
            d["languages"] = dict(metrics.languages)
        d["engine"] = dict(metrics.engine_stats() or {})
        d["cache"] = metrics.cache_stats()
        adm_fn = getattr(metrics, "admission_stats", None)
        if adm_fn is not None:
            adm = adm_fn()
            if adm:
                d["admission"] = adm
        ready_fn = getattr(metrics, "readiness", None)
        if ready_fn is not None:
            r = ready_fn()
            if r is not None:
                d["ready"] = r
        pool_fn = getattr(metrics, "pool_stats", None)
        if pool_fn is not None:
            p = pool_fn()
            if p:
                d["pool"] = p
        pipeline_fn = getattr(metrics, "pipeline_stats", None)
        if pipeline_fn is not None:
            pl = pipeline_fn()
            if pl:
                d["pipeline"] = pl
        shm_fn = getattr(metrics, "shm_stats", None)
        if shm_fn is not None:
            sh = shm_fn()
            if sh:
                d["shm"] = sh
        quar_fn = getattr(metrics, "quarantine_stats", None)
        if quar_fn is not None:
            qs = quar_fn()
            if qs:
                d["quarantine"] = qs
        shc_fn = getattr(metrics, "shared_cache_stats", None)
        if shc_fn is not None:
            sc = shc_fn()
            if sc:
                d["shared_cache"] = sc
        slo_fn = getattr(metrics, "slo_stats", None)
        if slo_fn is not None:
            sl = slo_fn()
            if sl:
                d["slo"] = sl
        cap_fn = getattr(metrics, "capture_stats", None)
        if cap_fn is not None:
            cp = cap_fn()
            if cp:
                d["capture"] = cp
    rh = REGISTRY.histogram("ldt_request_latency_ms")
    _, rsum, rcount, rmax = rh.snapshot()
    d["requests"] = {"count": rcount,
                     "mean_ms": round(rsum / rcount, 3) if rcount else 0,
                     "max_ms": round(rmax, 3),
                     "p95_ms": round(rh.percentile(95) or 0, 3)}
    d["stage_latency_ms"] = REGISTRY.stage_percentiles()
    d["xla_compiles"] = REGISTRY.compile_counts()
    d["slow_traces"] = {"threshold_ms": REGISTRY.slow.threshold_ms,
                        "capacity": REGISTRY.slow.capacity,
                        "recorded": REGISTRY.slow.recorded,
                        "held": len(REGISTRY.slow.snapshot())}
    from . import flightrec
    fr = flightrec.stats()
    if fr is not None:
        d["flightrec"] = fr
    # effective runtime config: generation + per-process mutable-knob
    # values, so a /configz rollback is observable after the fact
    # (rendered even before any apply — the fleet's health scrape
    # reads the generation off every member unconditionally)
    from . import configplane
    cfg = configplane.stats()
    if cfg is None:
        cfg = {"state": "idle", "generation": 0,
               "values": {k.name: knobs.value(k.name)
                          for k in knobs.mutable_knobs()}}
    d["config"] = cfg
    return d
