from . import distributed
from .mesh import batch_mesh, sharded_score_chunks_fn

__all__ = ["batch_mesh", "sharded_score_chunks_fn", "distributed"]
