"""In-process artifact hot swap and the standby readiness handshake.

Two ways to roll a new scoring artifact without dropping traffic:

1. **In-process swap** — ``swap_artifact(svc, path)``: load a FRESH
   mmap of the artifact (bypassing the process-wide tables cache),
   build a new engine against it, and rebind the service's scorer
   reference between flushes. One GIL-atomic rebind: in-flight flushes
   finish on the engine they captured at call entry, new flushes pick
   up the new one. Both metrics fronts expose it as ``POST /swap``.

2. **Blue/green generation swap** — the supervisor's SIGHUP drill
   (service/supervisor.py) spawns a standby worker generation, holds
   it until ``startup_ready_task`` below reports ready (warmup done,
   bucket ladder pre-compiled), then cuts over and drains the old
   generation. This module owns only the worker side of that
   handshake: the LDT_READY_FILE drop.

Every swap outcome counts into ``ldt_swap_total{result=}``; an aborted
swap (corrupt artifact, open breaker, injected ``swap_cutover`` fault)
leaves the old tables serving — the swap path never degrades the
running service.
"""
from __future__ import annotations

import json
import os
import time
from pathlib import Path

from .. import artifact, faults, knobs, telemetry
from .admission import BREAKER_OPEN


class SwapError(RuntimeError):
    """A refused or aborted artifact swap. The old artifact is still
    serving whenever this is raised — callers surface it (HTTP 409)
    but never tear anything down."""


# In-process swap phases, declared in tools/lint/fsm_registry.py
# (machine "artifact-swap"): the `swap` local in swap_artifact()
# tracks the attempt, and the conformance analyzer proves the phase
# changes match the declared table (e.g. REBOUND is only reachable
# through LOADING — never from a refused precondition).
SWAP_IDLE = 0     # under the swap lock, preconditions being checked
SWAP_LOADING = 1  # fresh mmap + engine build in progress
SWAP_REBOUND = 2  # service references rebound to the new artifact
SWAP_REFUSED = 3  # precondition refused the swap (breaker open)
SWAP_ABORTED = 4  # load/cutover failed; old tables keep serving


def _swap_engine(svc, tables):
    """Build a new device engine over `tables` and rebind. Stats carry
    over so the ldt_engine_* counters stay monotonic across swaps."""
    from ..models.ngram import NgramBatchEngine
    new_eng = NgramBatchEngine(tables=tables)
    old = svc._engine
    if old is not None:
        with old._stats_lock:
            snap = dict(old.stats)
        with new_eng._stats_lock:
            for k, v in snap.items():
                new_eng.stats[k] = new_eng.stats.get(k, 0) + v
    svc._engine = new_eng


def swap_artifact(svc, path) -> dict:
    """Swap the service onto the artifact at `path`. Serialized by the
    service's swap lock; raises SwapError (old tables keep serving) if
    the breaker is open, the artifact fails verification, or the
    injected ``swap_cutover`` fault fires. Returns an info dict for
    the POST /swap response."""
    from ..tables import ScoringTables
    path = str(path)
    swap = SWAP_IDLE
    with svc._swap_lock:
        # a swap while the device is circuit-broken would compile the
        # new engine's ladder straight into the failing device — refuse
        # and let the operator retry once the breaker closes
        if svc._engine is not None and \
                svc.admission.breaker.stats()["state"] == BREAKER_OPEN:
            swap = SWAP_REFUSED
            telemetry.REGISTRY.counter_inc("ldt_swap_total",
                                           result="error")
            raise SwapError("swap refused: device circuit breaker is "
                            "open; retry once it closes")
        # verify the standby artifact's digest footer BEFORE any
        # rebind work: a bit-flipped standby must never replace
        # serving tables (the old artifact keeps serving)
        try:
            digest = artifact.verify_artifact(path)
        except artifact.ArtifactIntegrityError as e:
            swap = SWAP_REFUSED
            telemetry.REGISTRY.counter_inc(
                "ldt_swap_total", result="integrity_refused")
            raise SwapError(
                f"swap refused: standby artifact failed integrity "
                f"verification ({e}); old tables keep serving") from e
        except (OSError, artifact.ArtifactError) as e:
            swap = SWAP_REFUSED
            telemetry.REGISTRY.counter_inc("ldt_swap_total",
                                           result="error")
            raise SwapError(f"swap refused: cannot read standby "
                            f"artifact ({e})") from e
        t0 = time.monotonic()
        swap = SWAP_LOADING
        try:
            # FRESH mmap, never the process-wide cache: the whole point
            # is picking up new bytes at an already-seen path
            tables = ScoringTables.load_mmap(Path(path))
            if faults.ACTIVE is not None:
                faults.hit("swap_cutover")
            if svc._engine is not None:
                _swap_engine(svc, tables)
            else:
                svc._tables = tables
        except SwapError:
            swap = SWAP_ABORTED
            raise
        except Exception as e:
            swap = SWAP_ABORTED
            telemetry.REGISTRY.counter_inc("ldt_swap_total",
                                           result="error")
            raise SwapError(f"swap aborted ({path}): {e}") from e
        swap = SWAP_REBOUND
        svc._artifact_path = path
        svc._swap_count += 1
        count = svc._swap_count
        # the rebind invalidates every cached result: namespace the
        # result caches (sync Batcher's + any front-registered one)
        # to the new artifact's generation so a post-swap request can
        # never be served a pre-swap answer
        epoch = digest or f"swap-{count}"
        caches = [getattr(getattr(svc, "batcher", None), "_cache",
                          None)]
        caches.extend(getattr(svc, "_result_caches", ()))
        for c in caches:
            if c is not None:
                c.set_epoch(epoch)
        telemetry.REGISTRY.counter_inc("ldt_swap_total", result="ok")
        ms = (time.monotonic() - t0) * 1e3
    print(json.dumps({"msg": "artifact swap complete",
                      "path": path, "swap_count": count,
                      "ms": round(ms, 1)}), flush=True)
    return {"swapped": True, "path": path, "swap_count": count,
            "engine": svc._engine is not None, "ms": round(ms, 1)}


def startup_ready_task(svc, ports) -> None:
    """Post-bind startup duties, run off the serving threads by both
    fronts: run the warmup batch when LDT_WARMUP is set (readiness
    gates on it), then drop the LDT_READY_FILE handshake the
    supervisor's swap drill polls for. Never raises — a warmup failure
    leaves readiness not-ok, which IS the signal."""
    # AOT bundle preload (aot.py): deserialize every matching exported
    # executable BEFORE the warmup batch runs, so warmup's dispatches
    # (and the first real traffic) land on loaded programs instead of
    # paying lazy per-shape loads between batches. Best-effort unless
    # LDT_AOT_REQUIRE, in which case a refused entry fails warmup and
    # readiness stays closed — the supervisor keeps the old generation.
    store = getattr(getattr(svc, "_engine", None), "_aot", None)
    if store is not None:
        try:
            n = store.preload()
            if n:
                print(json.dumps({"msg": "aot bundle preloaded",
                                  "entries": n, "dir": store.dir}),
                      flush=True)
        except Exception as e:
            print(json.dumps({"msg": "aot preload failed",
                              "error": repr(e)}), flush=True)
            if knobs.get_bool("LDT_AOT_REQUIRE"):
                return
    if knobs.get_bool("LDT_WARMUP"):
        try:
            svc.warm()
        except Exception as e:
            print(json.dumps({"msg": "warmup failed",
                              "error": repr(e)}), flush=True)
            return
    ready_file = knobs.get_str("LDT_READY_FILE")
    if not ready_file:
        return
    # wait until the full readiness gate (warmup, breaker, brownout)
    # opens before telling the supervisor to cut over
    deadline = time.monotonic() + \
        (knobs.get_float("LDT_SWAP_TIMEOUT_SEC") or 30.0)
    while time.monotonic() < deadline:
        try:
            rd = svc.readiness()
        except Exception:
            rd = {"ok": False}
        if rd.get("ok"):
            break
        time.sleep(0.05)
    else:
        print(json.dumps({"msg": "ready file withheld: readiness "
                          "never opened", "path": ready_file}),
              flush=True)
        return
    if knobs.get_bool("LDT_SWAPPED"):
        # this generation exists because a blue/green cutover promoted
        # it — count the swap on the side that survived
        telemetry.REGISTRY.counter_inc("ldt_swap_total", result="ok")
    info = {"generation": knobs.get_int("LDT_WORKER_GENERATION") or 1,
            "pid": os.getpid(), "port": ports[0],
            "metrics_port": ports[1],
            "warmup_ms": round(getattr(svc, "_warmup_ms", 0.0), 3)}
    tmp = f"{ready_file}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, ready_file)
    except OSError as e:
        print(json.dumps({"msg": "ready file write failed",
                          "path": ready_file, "error": repr(e)}),
              flush=True)
