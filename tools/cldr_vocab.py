#!/usr/bin/env python3
"""Per-language vocabulary extraction from babel's CLDR locale data.

The reference snapshot is missing its quadgram tables (SURVEY.md §2.5), and
the only labeled word data inside the snapshot is the ~80K octagram-table
comment words — too sparse for 140+ languages. This tool mines the CLDR
locale data shipped with the `babel` package (the only substantial
multilingual text in this environment) for additional labeled vocabulary:
calendar terms, relative-date phrases, unit/currency/list patterns
(function-word rich), and language/territory/script display names (broad
orthography coverage).

Inheritance is deliberately NOT merged (babel.localedata.load(...,
merge_inherited=False)): merged data falls back to the root locale, which
would attribute English/root strings to every minor language.

Output: [(phrase, lang_id, qprob)] where phrase is a lowercased
space-separated token string (scanned whole, so word-boundary quadgrams are
trained too) and qprob is a CLD2-style 1..12 log-scale weight class.
"""
from __future__ import annotations

import re
import sys
import unicodedata
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# babel language code -> CLD2 registry code where they differ
ALIASES = {
    "he": "iw", "jv": "jw", "fil": "tl", "nb": "no", "ckb": "ku",
    "mni": "mni-Mtei",
}

# Languages whose CLD2 scoring path is not quadgram-based (CJK uni/bigrams
# or single-script nilgram); their CLDR vocab would waste table buckets.
SKIP_LANGS = {"zh", "zh-Hant", "yue", "ja", "ko"}

_PLACEHOLDER = re.compile(r"\{\d+\}|%\w|''")
_NONWORD = re.compile(r"[0-9_/\\(){}\[\]<>#@&+=*%°§©®™.,;:!?"
                      r"‘’“”\"'|~^$-]+")

# (data key, qprob): calendar + pattern sources carry running-text function
# words (high weight); display-name catalogs are broad but proper-noun-ish.
SOURCES = [
    ("months", 8), ("days", 8), ("quarters", 6), ("eras", 6),
    ("day_periods", 7), ("date_fields", 8), ("list_patterns", 8),
    ("unit_patterns", 7), ("unit_display_names", 7),
    ("compound_unit_patterns", 7), ("currency_unit_patterns", 6),
    ("measurement_systems", 5),
    ("languages", 4), ("territories", 4), ("scripts", 4),
    ("variants", 4), ("currency_names", 4), ("currency_names_plural", 4),
]


def _strings_of(node):
    """All str leaves of a nested CLDR data node."""
    if isinstance(node, str):
        yield node
    elif isinstance(node, dict):
        for v in node.values():
            yield from _strings_of(v)
    elif isinstance(node, (list, tuple)):
        for v in node:
            yield from _strings_of(v)
    # babel wraps some leaves in DayPeriodRule / pattern objects; their
    # `pattern` attr is a format string
    elif hasattr(node, "pattern") and isinstance(node.pattern, str):
        yield node.pattern


def _clean_phrase(s: str) -> str:
    """Pattern string -> lowercase letter phrase ('' if nothing left)."""
    s = _PLACEHOLDER.sub(" ", s)
    s = _NONWORD.sub(" ", s)
    s = " ".join(s.split())
    if not s:
        return ""
    s = s.lower()
    # Drop phrases that are pure ASCII codes/symbols with no letters
    if not any(unicodedata.category(c).startswith("L") for c in s):
        return ""
    return s


def _base_lang(locale_id: str) -> str:
    return locale_id.split("_")[0]


def collect_cldr_words(reg) -> list:
    """[(phrase, lang_id, qprob)] deduplicated per (lang, phrase) keeping
    the highest qprob seen."""
    import babel.localedata as localedata

    best: dict = {}
    for locale_id in localedata.locale_identifiers():
        code = ALIASES.get(_base_lang(locale_id), _base_lang(locale_id))
        if code in SKIP_LANGS:
            continue
        lang = reg.code_to_lang.get(code)
        if lang is None:
            continue
        try:
            data = localedata.load(locale_id, merge_inherited=False)
        except Exception:
            continue
        for key, q in SOURCES:
            node = data.get(key)
            if not node:
                continue
            for s in _strings_of(node):
                phrase = _clean_phrase(s)
                if not phrase or len(phrase) > 80:
                    continue
                k = (lang, phrase)
                if best.get(k, 0) < q:
                    best[k] = q
    return [(phrase, lang, q) for (lang, phrase), q in best.items()]


def collect_mo_phrases(reg) -> list:
    """[(phrase, lang_id, qprob)] from gettext catalogs (.mo) shipped
    inside installed packages (humanize etc.): translated UI sentences
    rich in the function words the octa delta tables deliberately omit
    (the reference's quad tables covered them)."""
    import gettext
    import site
    out = []
    seen = set()
    roots = [Path(p) for p in site.getsitepackages()]
    for root in roots:
        for mo in root.glob("*/locale/*/LC_MESSAGES/*.mo"):
            code = mo.parent.parent.name.split("_")[0]
            code = ALIASES.get(code, code)
            if code in SKIP_LANGS:
                continue
            lang = reg.code_to_lang.get(code)
            if lang is None:
                continue
            try:
                cat = gettext.GNUTranslations(mo.open("rb"))._catalog
            except Exception:
                continue
            for msg in cat.values():
                for s in (msg if isinstance(msg, (list, tuple)) else [msg]):
                    phrase = _clean_phrase(s)
                    if not phrase or len(phrase) > 120:
                        continue
                    k = (lang, phrase)
                    if k not in seen:
                        seen.add(k)
                        out.append((phrase, lang, 8))
    return out


# English function words (sklearn's ENGLISH_STOP_WORDS is itself the
# classic Glasgow IR list): the delta-octa word source systematically
# lacks them because the reference's real quadgram tables already scored
# them (so they never made the "delta" cut).
def collect_english_stopwords(reg) -> list:
    try:
        from sklearn.feature_extraction.text import ENGLISH_STOP_WORDS
    except ImportError:
        return []
    lang = reg.code_to_lang.get("en")
    if lang is None:
        return []
    return [(w, lang, 9) for w in sorted(ENGLISH_STOP_WORDS)]


def main():
    from language_detector_tpu.registry import registry
    words = collect_cldr_words(registry)
    mo = collect_mo_phrases(registry)
    sw = collect_english_stopwords(registry)
    print(f"mo phrases: {len(mo)}; en stopwords: {len(sw)}")
    words = words + mo + sw
    import collections
    per_lang = collections.Counter(lang for _, lang, _ in words)
    print(f"phrases: {len(words)} across {len(per_lang)} languages")
    for lang, n in per_lang.most_common(10):
        print(f"  {registry.code(lang):10s} {n}")
    print("fewest:")
    for lang, n in per_lang.most_common()[-10:]:
        print(f"  {registry.code(lang):10s} {n}")


if __name__ == "__main__":
    main()
