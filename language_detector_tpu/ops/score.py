"""Batched device scoring: resolved hits -> per-chunk summaries.

The numeric core of detection (ScoreOneChunk totes + top-2 + reliability,
scoreonescriptspan.cc:208-302, cldutil.cc:553-605) as one jitted program
of fixed-shape tensor ops over the resolved wire the native packer builds
(packer.cc ldt_pack_resolve): langprob decode, chunk totes over 256
per-script languages as one-hot matmuls on the MXU, masked double-argmax
top-2, and the reliability formulas.

Design rules for this device (TPU behind a high-latency tunnel): NO
scatter, NO sort, NO scan — segment reductions are one-hot matmuls over
the small chunk axis, top-k(2) is two masked argmaxes, and everything
sequential (probes, repeat cache, chunk assignment, boost rotation) lives
in the C++ packer where the few-MB tables are cache-resident. History:
ops/score.py@01ee7ba^ held the prior all-on-device program (probes +
lax.scan); profiling (docs/PERF.md) showed the wire transfer and the
fixed ~95ms dispatch latency dominating, so the split moved host-ward.

The per-document epilogue (DocTote replay, close pairs, unreliable-language
removal, summary language — all O(1) per doc) runs on the host in
models/ngram.py + native/epilogue.cc, reusing the oracle-validated scalar
semantics, so the batched path agrees with the scalar engine exactly
(tests/test_batch_agreement.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .device_tables import DeviceTables

def _decode3(lp):
    """langprob -> pslangs [.., 3] and group row index for qprob decode."""
    lp = lp.astype(jnp.uint32)
    ps = jnp.stack([(lp >> 8) & 0xFF, (lp >> 16) & 0xFF, (lp >> 24) & 0xFF],
                   axis=-1).astype(jnp.int32)
    return ps, (lp & 0xFF).astype(jnp.int32)


def _reliability_delta(s1, s2, grams):
    """cldutil.cc:553-570, integer math."""
    maxp = jnp.where(grams < 8, 12 * grams, 100)
    thresh = jnp.clip((grams * 5) >> 3, 3, 16)
    delta = s1 - s2
    pct = jnp.where(delta >= thresh, maxp,
                    jnp.where(delta <= 0, 0,
                              jnp.minimum(maxp, (100 * delta) // thresh)))
    return pct


def _reliability_expected(actual, expected):
    """cldutil.cc:587-605. f32 ratio math mirroring the scalar engine."""
    hi = jnp.maximum(actual, expected).astype(jnp.float32)
    lo = jnp.minimum(actual, expected).astype(jnp.float32)
    ratio = hi / jnp.maximum(lo, 1.0)
    pct = (100.0 * (4.0 - ratio) / 2.5).astype(jnp.int32)
    pct = jnp.where(ratio <= 1.5, 100, jnp.where(ratio > 4.0, 0, pct))
    pct = jnp.where(expected == 0, 100, pct)
    return jnp.where(actual == 0, jnp.where(expected == 0, 100, 0), pct)


def _lscript4(script):
    return jnp.where(script == 1, 0,
                     jnp.where(script == 3, 1, jnp.where(script == 6, 2, 3)))



# ---------------------------------------------------------------------------
# Resolved-wire scorer: the production path.
#
# The native packer (packer.cc ldt_pack_resolve) performs the table probes,
# quad repeat cache, chunk assignment, and distinct-boost rotation on the
# HOST (the tables are a few MB and cache-resident there), so the wire
# carries only resolved hits — 3-4 bytes per slot (u16 index into the
# concatenated indirect array + u8/u16 doc-local chunk id) instead of 8, and
# misses never cross the host->device link. The device keeps the dense
# numeric core that actually benefits from the MXU: langprob decode,
# per-chunk totes as one-hot matmuls, masked top-2, and the reliability
# formulas (cldutil.cc:553-605).
# ---------------------------------------------------------------------------

# cmeta bit layout (keep in sync with packer.cc pack_resolve_one_doc):
#   cbytes(16) | grams(12) << 16 | side << 28 | real << 29
CM2_GRAMS_SHIFT = 16
CM2_SIDE_SHIFT = 28
CM2_REAL_SHIFT = 29
# output word: lang1(10) | s1(14) << 10 | rel(7) << 24 | real << 31
OUTW_S1_SHIFT = 10
OUTW_REL_SHIFT = 24
OUTW_REAL_SHIFT = 31


def score_resolved_impl(dt: DeviceTables, p: dict):
    """Score one resolved wire into packed chunk outputs [B, C] u32.

    p (built by models/ngram.py from ldt_pack_resolve):
      idx       [S, N]  u16  cat_ind2 index per resolved hit
      chk       [S, N]  u8/u16  doc-local chunk id
      doc_start [B]     i32  doc's first slot (shard-local)
      n_slots   [B]     i32
      cmeta     [B, C]  u32  chunk meta (see CM2_* layout)
      cscript   [B, C]  u8   chunk ULScript
      l_iota    [L]     u8   dense slot-axis length carrier

    Every reduction is doc-local: safe under jit and shard_map over the
    doc axis with zero collectives."""
    idxf = p["idx"].reshape(-1)
    chkf = p["chk"].reshape(-1)
    N = idxf.shape[0]
    doc_start = p["doc_start"].astype(jnp.int32)
    n_slots = p["n_slots"].astype(jnp.int32)
    B = doc_start.shape[0]
    L = p["l_iota"].shape[0]
    cmeta = p["cmeta"].astype(jnp.uint32)
    C = cmeta.shape[1]

    # dense [B, L] reconstruction (one gather pair)
    li = jnp.arange(L, dtype=jnp.int32)
    valid = li[None, :] < n_slots[:, None]
    gidx = jnp.clip(doc_start[:, None] + li[None, :], 0, N - 1)
    lp = jnp.where(valid, dt.cat_ind2[idxf[gidx].astype(jnp.int32)], 0)
    chunk_id = jnp.where(valid, chkf[gidx].astype(jnp.int32), 0)

    # decode + per-slot language contribution [B, L, 256]
    ps, row = _decode3(lp)
    q = dt.lg_prob3[row].astype(jnp.int32)                     # [B, L, 3]
    iota256 = jnp.arange(256, dtype=jnp.int32)
    lang_val = jnp.zeros((B, L, 256), jnp.bfloat16)
    for j in range(3):
        contrib = jnp.where(valid & (ps[..., j] > 0), q[..., j], 0)
        lang_val = lang_val + jnp.where(
            ps[..., j:j + 1] == iota256, contrib[..., None], 0
        ).astype(jnp.bfloat16)

    # chunk totes on the MXU
    chunk_oh = ((chunk_id[:, None, :] == jnp.arange(C)[None, :, None]) &
                valid[:, None, :])                             # [B, C, L]
    scores = jnp.einsum("bcl,blk->bck", chunk_oh.astype(jnp.bfloat16),
                        lang_val,
                        preferred_element_type=jnp.float32).astype(jnp.int32)

    # chunk meta decode
    cbytes = (cmeta & jnp.uint32(0xFFFF)).astype(jnp.int32)
    grams = ((cmeta >> CM2_GRAMS_SHIFT) & jnp.uint32(0xFFF)) \
        .astype(jnp.int32)
    side = ((cmeta >> CM2_SIDE_SHIFT) & jnp.uint32(1)).astype(jnp.int32)
    real = ((cmeta >> CM2_REAL_SHIFT) & jnp.uint32(1)).astype(jnp.int32)
    script = p["cscript"].astype(jnp.int32)

    # group-in-use top-2 (tote.cc:30-100 semantics; qprob >= 1 invariant
    # validated at DeviceTables.from_host)
    groups = jnp.any((scores > 0).reshape(B, C, 64, 4), axis=3)
    slot_in_use = jnp.repeat(groups, 4, axis=2)
    sortkey = jnp.where(slot_in_use, scores * 256 + (255 - iota256), -1)
    k1 = jnp.argmax(sortkey, axis=-1)
    top1 = jnp.take_along_axis(sortkey, k1[..., None], axis=-1)[..., 0]
    sortkey2 = jnp.where(iota256 == k1[..., None], -1, sortkey)
    k2 = jnp.argmax(sortkey2, axis=-1)
    top2 = jnp.take_along_axis(sortkey2, k2[..., None], axis=-1)[..., 0]
    s1 = jnp.where(top1 >= 0, top1 >> 8, 0)
    s2 = jnp.where(top2 >= 0, top2 >> 8, 0)
    k1 = jnp.where(top1 >= 0, k1, 0)
    k2 = jnp.where(top2 >= 0, k2, 0)

    # per-script language mapping (rtype<=1 spans never reach the device:
    # the packer routes them through direct_adds)
    lang1 = dt.plang_to_lang[side, k1]
    lang2 = dt.plang_to_lang[side, k2]

    actual_kb = jnp.where(cbytes > 0, (s1 << 10) // jnp.maximum(cbytes, 1),
                          0)
    expected_kb = dt.expected_score[lang1, _lscript4(script)]
    rd = _reliability_delta(s1, s2, grams)
    same_set = (dt.close_set[lang1] != 0) & \
        (dt.close_set[lang1] == dt.close_set[lang2])
    rd = jnp.where(same_set, 100, rd)
    rs = _reliability_expected(actual_kb, expected_kb)
    crel = jnp.minimum(rd, rs)

    # single packed word per chunk: 32 bytes/doc device->host readback.
    # s1 clips at 16383 — chunk totes are bounded far below (<= ~110
    # entries x qprob 12 + 4x12 boosts); the batch-agreement suite pins
    # exactness against the scalar engine.
    return (lang1.astype(jnp.uint32) |
            (jnp.clip(s1, 0, 0x3FFF).astype(jnp.uint32) << OUTW_S1_SHIFT) |
            (jnp.clip(crel, 0, 127).astype(jnp.uint32) << OUTW_REL_SHIFT) |
            (real.astype(jnp.uint32) << OUTW_REAL_SHIFT))


score_resolved = jax.jit(score_resolved_impl)


def unpack_resolved_out(out: np.ndarray, cmeta: np.ndarray) -> np.ndarray:
    """Device output [B, C] u32 + host chunk meta -> the [B, C, 5] int32
    chunk-summary layout the document epilogue consumes (OUT_* lanes)."""
    lang1 = (out & 0x3FF).astype(np.int32)
    s1 = ((out >> OUTW_S1_SHIFT) & 0x3FFF).astype(np.int32)
    rel = ((out >> OUTW_REL_SHIFT) & 0x7F).astype(np.int32)
    real = ((out >> OUTW_REAL_SHIFT) & 1).astype(np.int32)
    cbytes = (cmeta & 0xFFFF).astype(np.int32)
    return np.stack([lang1, cbytes, s1, rel, real], axis=-1)
