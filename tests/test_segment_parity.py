"""Script-span segmentation parity vs the reference scanner."""
import pytest

from language_detector_tpu.preprocess.segment import segment_text

from conftest import oracle_spans

TEXTS = [
    "This is plain English text, with punctuation!",
    "Confiserie et chocolaterie — des digues du fleuve.",
    "Šach je dosková hra pre dvoch hráčov, cieľom je dať mat.",
    "Это советы помогут вам избежать проблем при покупке квартиры.",
    "国民の大多数が内閣を支持した。 Some English mixed in. ещё по-русски.",
    "Mixed: English text então Português depois English again.",
    "العربية لغة جميلة wa English words huna.",
    "ελληνικά και λατινικά letters mixed δύο scripts.",
    "    leading spaces and\t\ttabs\nnewlines   ",
    "numbers 12345 and - punctuation!!! only?",
    "ḀḁḂ unusual Latin-extended ṪṫṬ characters ẑẒ",
    "한국어 텍스트와 English 텍스트가 섞여 있습니다",
    "ภาษาไทยเป็นภาษาที่สวยงาม",
    "हिन्दी भाषा में यह वाक्य लिखा गया है",
]


@pytest.mark.parametrize("text", TEXTS)
def test_span_parity(oracle, text):
    ref = oracle_spans(oracle, text.encode("utf-8"))
    mine = segment_text(text)
    ref_clean = [(t, s) for (t, s) in ref]
    assert len(mine) == len(ref_clean), (
        [(r[0], r[1]) for r in ref_clean],
        [(sp.text, sp.ulscript) for sp in mine])
    for sp, (rt, rs) in zip(mine, ref_clean):
        assert sp.ulscript == rs, (sp.text, rt, rs)
        assert sp.text == rt, (sp.text, rt)
