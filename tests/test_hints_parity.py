"""Hints engine parity vs the oracle's ExtDetectLanguageSummary.

Covers the four CLDHints channels (content-language, TLD, encoding,
explicit language) and HTML lang= attribute scanning, on texts where the
hint matters (close pairs, short ambiguous snippets) and where it must
not override clear evidence (compact_lang_det_hint_code.cc:1394-1508,
ApplyHints impl.cc:1587-1684).
"""
import ctypes

import pytest

from language_detector_tpu.engine_scalar import detect_scalar
from language_detector_tpu.hints import (CLDHints, apply_hints,
                                         get_lang_tags_from_html)
from language_detector_tpu.registry import UNKNOWN_LANGUAGE, registry
from language_detector_tpu.tables import load_tables


def oracle_detect_hints(lib, text: bytes, flags: int = 0,
                        is_plain_text: bool = True,
                        content_language: bytes = b"", tld: bytes = b"",
                        encoding: int = 75,  # UNKNOWN_ENCODING
                        language: int = UNKNOWN_LANGUAGE):
    lib.o_detect_hints.restype = ctypes.c_int
    l3 = (ctypes.c_int * 3)()
    p3 = (ctypes.c_int * 3)()
    s3 = (ctypes.c_double * 3)()
    tb = ctypes.c_int()
    rel = ctypes.c_int()
    lang = lib.o_detect_hints(text, len(text), 1 if is_plain_text else 0,
                              flags, content_language, tld, encoding,
                              language, l3, p3, s3, ctypes.byref(tb),
                              ctypes.byref(rel))
    return (lang, [int(l3[i]) for i in range(3)],
            [int(p3[i]) for i in range(3)], bool(rel.value), tb.value)


TEXT_ID_MS = "ini rumah besar kami yang baru dan sangat cantik sekali"
TEXT_HR = "ovo je velika kuća i lijepo je vrijeme danas u gradu"
TEXT_EN = ("this is a simple english sentence with common words that "
           "should be detected without any trouble at all")

CASES = [
    # (text, plain, kwargs)
    (TEXT_ID_MS, True, dict(tld=b"my")),
    (TEXT_ID_MS, True, dict(tld=b"id")),
    (TEXT_ID_MS, True, dict(content_language=b"ms")),
    (TEXT_ID_MS, True, dict(language=registry.code_to_lang["ms"])),
    (TEXT_HR, True, dict(content_language=b"sr")),
    (TEXT_HR, True, dict(tld=b"rs")),
    (TEXT_EN, True, dict(tld=b"fr")),    # clear evidence beats weak hint
    (TEXT_EN, True, dict(content_language=b"fr")),
    ("short text", True, dict(content_language=b"de")),
    ("short text", True, dict(language=registry.code_to_lang["nl"])),
    ('<html lang="sr"><p>' + TEXT_HR + "</p></html>", False, dict()),
    # hr (Latin-only) must not whack Serbian in the Cyrillic list
    # (AddOneWhack script condition, impl.cc:1541-1561)
    ("Београд је главни град Србије и највећи град у земљи данас", True,
     dict(content_language=b"hr")),
    # >4 whacks per script exercise the rotating overwrite
    (TEXT_HR, True, dict(content_language=b"sr,no")),
    ('<meta http-equiv="content-language" content="ms"><p>' +
     TEXT_ID_MS + "</p>", False, dict()),
]


@pytest.mark.parametrize("case", CASES, ids=range(len(CASES)))
def test_hinted_detection_parity(oracle, base_tables, case):
    text, plain, kw = case
    want = oracle_detect_hints(oracle, text.encode(), is_plain_text=plain,
                               content_language=kw.get("content_language",
                                                       b""),
                               tld=kw.get("tld", b""),
                               language=kw.get("language",
                                               UNKNOWN_LANGUAGE))
    hints = CLDHints(
        content_language_hint=kw.get("content_language", b"").decode()
        or None,
        tld_hint=kw.get("tld", b"").decode() or None,
        language_hint=kw.get("language", UNKNOWN_LANGUAGE))
    r = detect_scalar(text, base_tables, registry, 0,
                      is_plain_text=plain, hints=hints)
    assert r.summary_lang == want[0], (registry.code(r.summary_lang),
                                       registry.code(want[0]))
    assert r.language3 == want[1]
    assert r.percent3 == want[2]
    assert r.is_reliable == want[3]


def test_encoding_hint_parity(oracle, base_tables):
    """Encoding-family hints (SetCLDEncodingHint)."""
    tables = load_tables()
    names = [str(n) for n in tables.encoding_names]
    for enc_name, text in [("CHINESE_GB", "短文"), ("JAPANESE_EUC_JP", "短文"),
                           ("KOREAN_EUC_KR", "短文")]:
        enc = names.index(enc_name)
        want = oracle_detect_hints(oracle, text.encode(), encoding=enc)
        r = detect_scalar(text, base_tables, registry, 0,
                          hints=CLDHints(encoding_hint=enc_name))
        assert r.summary_lang == want[0], (enc_name,
                                           registry.code(r.summary_lang),
                                           registry.code(want[0]))


def test_lang_tag_scanner():
    """GetLangTagsFromHtml normalization behaviors."""
    assert get_lang_tags_from_html('<html lang="fr">') == "fr"
    assert get_lang_tags_from_html("<html lang='pt-BR'>") == "pt-br"
    assert get_lang_tags_from_html('<div xml:lang="DE_de">x</div>') \
        == "de-de"
    # unquoted attribute values match (the reference's FindAfter needs a
    # trailing space, which a closing quote prevents — quoted values are
    # faithfully NOT matched, quirk of hint_code.cc:1328-1352)
    assert get_lang_tags_from_html(
        '<meta http-equiv=content-language content="es, en" x=y>') \
        == "es,en"
    assert get_lang_tags_from_html(
        '<meta http-equiv="content-language" content="es, en">') == ""
    # skipped elements contribute nothing
    assert get_lang_tags_from_html('<a lang="it" href=x>') == ""
    assert get_lang_tags_from_html('<script lang="js">') == ""
    # duplicates collapse
    assert get_lang_tags_from_html(
        '<p lang="fr"></p><p lang="fr"></p>') == "fr"


def test_apply_hints_whacks():
    """A single hinted close-set member whacks its rivals."""
    tables = load_tables()
    hb = apply_hints("", True,
                     CLDHints(language_hint=registry.code_to_lang["id"]),
                     tables, registry)
    assert hb.boost_latn  # INDONESIAN boost
    assert hb.whack_latn  # MALAY suppressed
    # tld=id carries a paired negative MALAY prior, so both close-set
    # members are present and no whack fires (ApplyHints counts priors
    # regardless of weight sign, impl.cc:1660-1666)
    hb2 = apply_hints("", True, CLDHints(tld_hint="id"), tables, registry)
    assert hb2.boost_latn and not hb2.whack_latn
    hb3 = apply_hints("", True,
                      CLDHints(content_language_hint="id,ms"), tables,
                      registry)
    assert not hb3.whack_latn  # both of the set hinted: no whack


# -- device prior term vs the numpy scalar-oracle extension ------------------
#
# The LDT_HINTS reduction term (ops/score.py _chunk_out_word prior add,
# post-whack / pre-top-2) is defined against evalsuite.oracle_score_chunks
# — a pure-numpy op-for-op mirror of the device program. The contract is
# BIT-identity of the packed chunk words under EVERY kernel mode, with
# and without priors on the wire, and byte-identity of prior-free wires
# (hint-off batches must trace the identical program they always did).


import numpy as np  # noqa: E402

PRIOR_TEXTS = [
    "the quick brown fox jumps over the lazy dog near the river bank",
    TEXT_ID_MS,
    TEXT_HR,
    TEXT_EN,
    "это русское предложение о языках и обнаружении текста",
    "これは日本語の文章ですよろしくお願いします",
    "dit is een nederlandse zin over taaldetectie en andere dingen",
]


@pytest.fixture(scope="module")
def eng():
    from language_detector_tpu.models.ngram import NgramBatchEngine
    return NgramBatchEngine()


def _hinted_pack(tables, with_priors):
    """PRIOR_TEXTS packed with per-doc content-language boosts; when
    with_priors, the same boosts also become cprior/prior_tbl wire
    planes (the LDT_HINTS=1 reduction input)."""
    from language_detector_tpu.hints import prior_vector
    codes = ["id", "ms", "sr", "fr", "uk", "ja", "af"]
    hbs = [apply_hints(t, True, CLDHints(content_language_hint=c),
                       tables, registry)
           for t, c in zip(PRIOR_TEXTS, codes)]
    pvs = [prior_vector(hb, tables) for hb in hbs] \
        if with_priors else None
    from language_detector_tpu import native
    return native.pack_chunks_native(PRIOR_TEXTS, tables, registry,
                                     hint_boosts=hbs, hint_priors=pvs)


def _device_modes(eng):
    """(name, score_fn) for every LDT_KERNEL program, pallas via the
    interpreter (the Mosaic lowering runs the identical kernel body)."""
    from language_detector_tpu.ops import kernels
    from language_detector_tpu.ops.score import score_chunks
    modes = [("xla", score_chunks),
             ("fused", kernels.score_chunks_fused),
             ("lax", kernels.score_chunks_lax)]
    ps, _, _ = kernels._pallas_score_fns(interpret=True)
    modes.append(("pallas-interpret", ps))
    return modes


@pytest.mark.parametrize("with_priors", [False, True],
                         ids=["no-prior", "prior"])
def test_device_matches_numpy_oracle_all_modes(eng, with_priors):
    """Every kernel mode emits the oracle's packed words bit-for-bit,
    priors on the wire or not."""
    from language_detector_tpu.evalsuite import oracle_score_chunks
    cb = _hinted_pack(eng.tables, with_priors)
    assert ("cprior" in cb.wire) == with_priors
    want = oracle_score_chunks(eng.tables, registry, cb.wire)
    for name, score in _device_modes(eng):
        got = np.asarray(score(eng.dt, cb.wire))
        assert np.array_equal(got, want), \
            (name, np.flatnonzero(got != want)[:8])


def test_prior_free_wire_identical():
    """hint_priors=None and an all-None prior list build the same wire:
    no cprior/prior_tbl keys, every shared plane byte-identical — the
    hint-off acceptance gate at the wire level."""
    from language_detector_tpu import native
    tables = load_tables()
    cb0 = native.pack_chunks_native(PRIOR_TEXTS, tables, registry)
    cb1 = native.pack_chunks_native(PRIOR_TEXTS, tables, registry,
                                    hint_priors=[None] *
                                    len(PRIOR_TEXTS))
    assert "cprior" not in cb0.wire and "cprior" not in cb1.wire
    assert "prior_tbl" not in cb1.wire
    assert set(cb0.wire) == set(cb1.wire)
    for k in cb0.wire:
        np.testing.assert_array_equal(np.asarray(cb0.wire[k]),
                                      np.asarray(cb1.wire[k]),
                                      err_msg=k)


def test_hint_prior_flips_documented_demo():
    """The documented ambiguous-document flip (docs/ACCURACY.md): the
    content-language prior changes the verdict, and the prior-free pack
    answers exactly as before."""
    from language_detector_tpu.evalsuite import hint_flip_demo
    demo = hint_flip_demo()
    assert demo["flipped"], demo
    assert demo["after"] == "id"
    assert demo["before"] != "id"


def test_prior_never_promotes_unscored_language(eng):
    """A prior only amplifies positive chunk evidence: a document with
    zero tote score for the hinted language answers identically with
    and without the prior (the where(scores > 0) guard)."""
    from language_detector_tpu import native
    from language_detector_tpu.evalsuite import oracle_score_chunks
    from language_detector_tpu.hints import prior_vector
    tables = eng.tables
    text = "これは日本語の文章ですよろしくお願いします"  # no Latin evidence
    hb = apply_hints(text, True,
                     CLDHints(content_language_hint="fr"), tables,
                     registry)
    pv = prior_vector(hb, tables)
    assert pv is not None
    cb0 = native.pack_chunks_native([text], tables, registry,
                                    hint_boosts=[hb])
    cb1 = native.pack_chunks_native([text], tables, registry,
                                    hint_boosts=[hb], hint_priors=[pv])
    w0 = oracle_score_chunks(tables, registry, cb0.wire)
    w1 = oracle_score_chunks(tables, registry, cb1.wire)
    np.testing.assert_array_equal(w0, w1)
