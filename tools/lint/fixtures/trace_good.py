"""Fixture: the trace-time-static patterns the live tree relies on —
all of them must pass clean."""
import jax
import jax.numpy as jnp

from somewhere import pack_chunks_native  # AST-only, never imported


def scorer(dt, wire, full_out=False):
    if wire.shape[-1] == 1:     # shape read: static at trace time
        pass
    for j in range(3):          # python loop over a constant range
        wire = wire + j
    if not full_out:            # literal-bool default: config flag
        pass
    g = None
    if g is None:               # identity test: trace-static
        g = wire
    return jnp.where(wire > 0, wire, 0)


score = jax.jit(scorer)


def launch(dt, cb):
    return score(dt, cb.wire)           # cb is a parameter: caller packs


def launch_local(dt, texts):
    cb = pack_chunks_native(texts)
    return score(dt, cb.wire)           # cb from the native packer
