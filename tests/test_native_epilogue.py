"""C++ epilogue (native/epilogue.cc) vs a Python document-epilogue replay.

The native path must agree with the engine_scalar.py document pipeline
(_python_doc_epilogue below, pinned to the scalar engine by
test_batch_agreement) on every document: real texts through the full
pipeline, plus randomized chunk summaries that exercise DocTote eviction,
close-pair merges, unreliable removal, and the summary-language edge
cases far beyond what natural text reaches.
"""
import numpy as np
import pytest

from language_detector_tpu import native
from language_detector_tpu.engine_scalar import detect_scalar
from language_detector_tpu.models.ngram import NgramBatchEngine
from language_detector_tpu.registry import registry
from language_detector_tpu.tables import ScoringTables

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")

TEXTS = [
    "The quick brown fox jumps over the lazy dog near the river bank",
    "Le gouvernement a annoncé de nouvelles mesures pour aider les familles",
    "Der Hund läuft schnell durch den großen Wald und findet einen Knochen",
    "こんにちは世界。今日はとても良い天気ですね。散歩に行きましょう。",
    "Привет мир, это предложение написано на русском языке для теста",
    "मैं आज बाजार गया और कुछ फल खरीदे क्योंकि वे ताजा थे",
    "Short",
    "",
    "Mixed language text avec du français and English zusammen gemischt",
    "ไปโรงเรียนทุกวันเพื่อเรียนหนังสือและพบเพื่อน",
]


@pytest.fixture(scope="module")
def eng():
    return NgramBatchEngine(ScoringTables.load(), registry)


def _python_doc_epilogue(eng, cb, rows, b):
    """DocTote replay in chunk-row order + the document post-processing
    pipeline, byte-identical to detect_scalar (impl.cc:1956-2106) — the
    behavioral spec the C++ epilogue must match. Returns None when the
    good-answer gate fails (the engine then runs the batched recursion)."""
    from language_detector_tpu.engine_scalar import (
        FLAG_BEST_EFFORT, FLAG_FINISH, GOOD_LANG1_PERCENT,
        GOOD_LANG1AND2_PERCENT, SHORT_TEXT_THRESH, DocTote, ScalarResult,
        calc_summary_lang, extract_lang_etc, refine_close_pairs,
        remove_unreliable)

    doc_tote = DocTote()
    direct = {int(cid): (int(lang), int(nb))
              for cid, lang, nb in cb.direct_adds[b] if cid >= 0}
    g0 = int(cb.doc_chunk_start[b])
    for c in range(int(cb.n_chunks[b])):
        if c in direct:
            lang, nb = direct[c]
            doc_tote.add(lang, nb, nb, 100)
        elif rows[g0 + c, 4]:
            doc_tote.add(int(rows[g0 + c, 0]), int(rows[g0 + c, 1]),
                         int(rows[g0 + c, 2]), int(rows[g0 + c, 3]))
    total_text_bytes = int(cb.text_bytes[b])
    flags = eng.flags

    refine_close_pairs(eng.reg, doc_tote)
    doc_tote.sort()
    lang3, percent3, rel3, ns3, total, is_reliable = extract_lang_etc(
        doc_tote, total_text_bytes)

    good = (flags & FLAG_FINISH) or total <= SHORT_TEXT_THRESH or \
        (is_reliable and percent3[0] >= GOOD_LANG1_PERCENT) or \
        (is_reliable and
         percent3[0] + percent3[1] >= GOOD_LANG1AND2_PERCENT)
    if not good:
        return None

    if not (flags & FLAG_BEST_EFFORT):
        remove_unreliable(eng.reg, doc_tote)
    doc_tote.sort()
    lang3, percent3, rel3, ns3, total, is_reliable = extract_lang_etc(
        doc_tote, total_text_bytes)
    summary, reliable = calc_summary_lang(eng.reg, lang3, percent3,
                                          total, is_reliable, flags)
    return ScalarResult(summary_lang=summary, language3=lang3,
                        percent3=percent3, normalized_score3=ns3,
                        text_bytes=total, is_reliable=reliable)


def test_native_epilogue_real_texts(eng):
    """ldt_epilogue_flat == the Python replay on real texts through the
    full pack+score pipeline (including gate-failure and fallback docs)."""
    texts = TEXTS * 3
    cb = native.pack_chunks_native(texts, eng.tables, eng.reg,
                                   flags=eng.flags)
    rows = eng.score_chunk_batch(cb)
    ep = native.epilogue_flat_native(rows, cb, eng.flags, eng.reg)
    for b, text in enumerate(texts):
        if cb.fallback[b]:
            assert ep[b, 12] == 1, b
            continue
        want = _python_doc_epilogue(eng, cb, rows, b)
        if want is None:
            assert ep[b, 12] == 1, (b, text[:40])
            continue
        assert ep[b, 12] == 0, (b, text[:40])
        got = (int(ep[b, 0]), [int(x) for x in ep[b, 1:4]],
               [int(x) for x in ep[b, 4:7]],
               [float(x) for x in ep[b, 7:10]], int(ep[b, 10]),
               bool(ep[b, 11]))
        assert got == dataclass_tuple(want), (b, text[:40])


def dataclass_tuple(r):
    return (r.summary_lang, r.language3, r.percent3, r.normalized_score3,
            r.text_bytes, r.is_reliable)


def test_native_epilogue_randomized(eng):
    """Synthetic chunk summaries: random languages/bytes/scores/reliability
    hammer the DocTote eviction + merge paths."""
    import dataclasses
    rng = np.random.default_rng(7)
    B, C, D = 256, 8, 4
    langs = rng.integers(0, 200, (B, C)).astype(np.int32)
    nbytes = rng.integers(0, 2000, (B, C)).astype(np.int32)
    scores = rng.integers(0, 4000, (B, C)).astype(np.int32)
    rel = rng.integers(0, 101, (B, C)).astype(np.int32)
    real = (rng.random((B, C)) < 0.8).astype(np.int32)
    rows = np.stack([langs, nbytes, scores, rel, real],
                    axis=-1).reshape(B * C, 5)
    direct = np.full((B, D, 3), -1, np.int32)
    # a third of docs get one direct add on a random chunk id
    for b in range(0, B, 3):
        direct[b, 0] = (int(rng.integers(0, C)),
                        int(rng.integers(0, 200)),
                        int(rng.integers(1, 500)))
    text_bytes = rng.integers(0, 20000, B).astype(np.int32)
    skip = np.zeros(B, bool)

    cb = native.ChunkBatch(
        wire={}, doc_chunk_start=(np.arange(B, dtype=np.int64) * C),
        direct_adds=direct, text_bytes=text_bytes, fallback=skip,
        squeezed=np.zeros(B, bool),
        n_slots=np.zeros(B, np.int32),
        n_chunks=np.full(B, C, np.int32), n_docs=B)
    ep = native.epilogue_flat_native(rows, cb, 0, registry)

    from language_detector_tpu.engine_scalar import (
        FLAG_FINISH, GOOD_LANG1_PERCENT, GOOD_LANG1AND2_PERCENT,
        SHORT_TEXT_THRESH, DocTote, calc_summary_lang, extract_lang_etc,
        refine_close_pairs, remove_unreliable)
    for b in range(B):
        doc = DocTote()
        dmap = {int(c): (int(l), int(n)) for c, l, n in direct[b] if c >= 0}
        for c in range(C):
            if c in dmap:
                lang, nb = dmap[c]
                doc.add(lang, nb, nb, 100)
            elif rows[b * C + c, 4]:
                doc.add(int(rows[b * C + c, 0]), int(rows[b * C + c, 1]),
                        int(rows[b * C + c, 2]), int(rows[b * C + c, 3]))
        refine_close_pairs(registry, doc)
        doc.sort()
        lang3, percent3, rel3, ns3, total, is_rel = extract_lang_etc(
            doc, int(text_bytes[b]))
        good = total <= SHORT_TEXT_THRESH or \
            (is_rel and percent3[0] >= GOOD_LANG1_PERCENT) or \
            (is_rel and percent3[0] + percent3[1] >= GOOD_LANG1AND2_PERCENT)
        if not good:
            assert ep[b, 12] == 1, b
            continue
        assert ep[b, 12] == 0, b
        remove_unreliable(registry, doc)
        doc.sort()
        lang3, percent3, rel3, ns3, total, is_rel = extract_lang_etc(
            doc, int(text_bytes[b]))
        summary, reliable = calc_summary_lang(registry, lang3, percent3,
                                              total, is_rel, 0)
        assert ep[b, 0] == summary, b
        assert list(ep[b, 1:4]) == lang3, b
        assert list(ep[b, 4:7]) == percent3, b
        assert [float(x) for x in ep[b, 7:10]] == ns3, b
        assert ep[b, 10] == total, b
        assert bool(ep[b, 11]) == (is_rel and reliable), b
