"""Pipelined dispatch (PR 9) identity and drain tests.

LDT_PIPELINE_DEPTH=1 is the serial reference: pack, score, fetch, one
batch at a time, no buffer donation. Depth 2+ overlaps host packing
with device scoring and donates the wire buffers of the staging ring
into the jitted scorer. The contract is BYTE-IDENTITY: every depth, on
every corpus — including under injected lane faults and a mid-stream
artifact swap — produces exactly the serial engine's results, and the
serial engine is itself pinned to the scalar oracle by
test_batch_agreement.py.

The long-doc lane splits docs whose slot demand exceeds the top bucket
into span-aligned sub-packs and merges the per-chunk score vectors
back into one doc summary (result_vector.merge_longdoc_chunks); its
exactness is pinned directly against engine_scalar here.
"""
import os
import random
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent))

BATCH = 32

# shuffled-vocabulary composition: multi-script so long docs split
# into several spans, non-repetitive so the spam squeezer stays out
# of the way (a squeezed doc resolves scalar and never exercises the
# chunk-merge path this file exists to pin)
_VOCAB = {
    "en": ("the quick brown fox jumps over a lazy dog while bright "
           "stars shine above quiet rivers and old houses near the "
           "harbor where fishermen mend their nets every "
           "morning").split(),
    "fr": ("le renard brun rapide saute par dessus le chien paresseux "
           "pendant que les etoiles brillantes scintillent au dessus "
           "des rivieres tranquilles et des vieilles maisons du "
           "port").split(),
    "ru": ("быстрая коричневая лиса прыгает через ленивую собаку пока "
           "яркие звезды сияют над тихими реками и старыми домами "
           "возле гавани где рыбаки чинят свои сети каждое "
           "утро").split(),
    "el": ("η γρηγορη καφε αλεπου πηδαει πανω απο το τεμπελικο σκυλι "
           "ενω τα λαμπερα αστερια λαμπουν πανω απο ησυχα ποταμια και "
           "παλια σπιτια κοντα στο λιμανι").split(),
}


def _sentence(rng, lang):
    words = [rng.choice(_VOCAB[lang])
             for _ in range(rng.randint(8, 14))]
    return " ".join(words) + ". "


def _long_doc(rng, size):
    """Multi-span doc: runs of one script long enough to form spans,
    switching scripts every few sentences."""
    parts: list = []
    total = 0
    while total < size:
        lang = rng.choice(list(_VOCAB))
        for _ in range(rng.randint(2, 5)):
            s = _sentence(rng, lang)
            parts.append(s)
            total += len(s)
    return "".join(parts)


def _mixed_corpus(rng, n_short=160, n_long=8):
    texts = []
    langs = list(_VOCAB)
    for i in range(n_short):
        lang = langs[i % len(langs)]
        words = [rng.choice(_VOCAB[lang])
                 for _ in range(rng.randint(6, 40))]
        texts.append(" ".join(words) + f" tag{i}")
    for _ in range(n_long):
        texts.append(_long_doc(rng, rng.randint(5000, 18000)))
    texts += ["", "a", "   ", "12345 67890 $$$"]
    rng.shuffle(texts)
    return texts


def _engine(depth, **kw):
    """Engine constructed under LDT_PIPELINE_DEPTH=depth (knobs read
    the environment at construction, so env — not monkeypatch — must
    bracket the constructor)."""
    from language_detector_tpu.models.ngram import NgramBatchEngine
    saved = os.environ.get("LDT_PIPELINE_DEPTH")
    os.environ["LDT_PIPELINE_DEPTH"] = str(depth)
    try:
        return NgramBatchEngine(**kw)
    finally:
        if saved is None:
            os.environ.pop("LDT_PIPELINE_DEPTH", None)
        else:
            os.environ["LDT_PIPELINE_DEPTH"] = saved


def _result_tuple(r):
    return (r.summary_lang, tuple(r.language3), tuple(r.percent3),
            tuple(r.normalized_score3), r.text_bytes, r.is_reliable)


def _tuples(results):
    return [_result_tuple(r) for r in results]


# -- depth identity ----------------------------------------------------------


def test_depth1_vs_depth3_byte_identical():
    """The whole point of the pipeline: depth is a latency knob, not a
    semantics knob. Depth 1 (serial) and depth 3 (two batches in
    flight, donated wire buffers) agree byte-for-byte over a mixed
    corpus of short docs, multi-span long docs, and the empty/tiny
    edge paths."""
    rng = random.Random(42)
    corpus = _mixed_corpus(rng)
    # split_slots == chunk_slots forces the long-doc lane for every
    # multi-span doc over the sub-pack size, so the identity claim
    # covers the merge path, not just the plain pipeline
    ld = dict(longdoc_split_slots=1024)
    e1, e3 = _engine(1, **ld), _engine(3, **ld)
    ref = _tuples(e1.detect_many(corpus, batch_size=BATCH))
    got = _tuples(e3.detect_many(corpus, batch_size=BATCH))
    assert got == ref
    s1, s3 = e1.pipeline_stats(), e3.pipeline_stats()
    # the serial reference must not pipeline; depth 3 must have
    # actually exercised the machinery it claims to
    assert s1["depth"] == 1 and s1["donation_hits"] == 0
    assert s3["depth"] == 3
    # every dispatch retired, every staging lease returned
    for s in (s1, s3):
        assert s["inflight"] == 0
        assert s["staging_ring_occupancy"] == 0
    assert e3.stats["longdoc_split_docs"] > 0
    assert e3.stats["retry_offtier_docs"] == 0


def test_depth2_default_matches_serial_small_batches():
    """Default depth over many small slices — the steady-state ring
    reuse shape (same bucket tier over and over)."""
    rng = random.Random(7)
    corpus = [" ".join(rng.choice(_VOCAB["en"]) for _ in range(12))
              + f" doc{i}" for i in range(96)]
    ref = _tuples(_engine(1).detect_many(corpus, batch_size=16))
    e2 = _engine(2)
    got = _tuples(e2.detect_many(corpus, batch_size=16))
    assert got == ref
    s = e2.pipeline_stats()
    assert s["staging_ring_hits"] > 0
    assert s["staging_ring_occupancy"] == 0


# -- identity under faults ---------------------------------------------------


_POOL_ENV = {"LDT_POOL_LANES": "2",
             "LDT_POOL_HEDGE_FACTOR": "0",
             "LDT_POOL_EVICT_FAILURES": "5",
             "LDT_POOL_PROBE_COOLDOWN_SEC": "0.2",
             "LDT_POOL_MAX_REDISPATCH": "8"}


def test_depth_identity_under_lane_faults():
    """Depth 3 over a 2-lane pool with lane_lost errors firing on half
    the fetches and device_flush latency jitter: failover re-dispatches
    donated batches, and the results stay byte-identical to the clean
    serial run. The lane in-flight gauges must drain to zero — a
    re-dispatched donated batch that double-counted would leak here."""
    from language_detector_tpu import faults
    saved = {k: os.environ.get(k) for k in _POOL_ENV}
    os.environ.update(_POOL_ENV)
    try:
        e1 = _engine(1, longdoc_split_slots=1024)
        if e1.pool is None:
            pytest.skip("pooled device engine unavailable")
        rng = random.Random(5)
        corpus = _mixed_corpus(rng, n_short=96, n_long=4)
        ref = _tuples(e1.detect_many(corpus, batch_size=BATCH))
        e3 = _engine(3, longdoc_split_slots=1024)
        faults.configure("lane_lost:error:p=0.5:seed=9,"
                         "device_flush:delay_ms=2:p=0.5:seed=3")
        try:
            got = _tuples(e3.detect_many(corpus, batch_size=BATCH))
        finally:
            faults.configure(None)
        assert got == ref
        for ln in e3.pool.lanes:
            assert ln.snapshot()["inflight"] == 0
        s = e3.pipeline_stats()
        assert s["inflight"] == 0
        assert s["staging_ring_occupancy"] == 0
        e1.pool.close()
        e3.pool.close()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_device_flush_error_retires_dispatch():
    """A flush that dies before its fetch must retire the dispatch:
    in-flight gauge back to zero, every staging lease released, and the
    engine healthy for the next call (which must still be exact)."""
    from language_detector_tpu import faults
    e3 = _engine(3)
    corpus = [f"plain english words number {i} for the flush test run"
              for i in range(48)]
    ref = _tuples(_engine(1).detect_many(corpus, batch_size=16))
    faults.configure("device_flush:error:once")
    try:
        with pytest.raises(faults.FaultInjected):
            e3.detect_many(corpus, batch_size=16)
    finally:
        faults.configure(None)
    s = e3.pipeline_stats()
    assert s["inflight"] == 0
    assert s["staging_ring_occupancy"] == 0
    assert _tuples(e3.detect_many(corpus, batch_size=16)) == ref


# -- mid-stream artifact swap ------------------------------------------------


def test_midstream_swap_identity():
    """The swap contract (service/swap.py): in-flight flushes finish on
    the engine they captured, new flushes land on the new engine. At
    the engine level that means a stream split across two engines of
    the same artifact — donated buffers, staging rings and all — must
    equal one serial engine's run over the whole stream."""
    rng = random.Random(11)
    corpus = _mixed_corpus(rng, n_short=96, n_long=4)
    ref = _tuples(_engine(1, longdoc_split_slots=1024)
                  .detect_many(corpus, batch_size=BATCH))
    e_a = _engine(3, longdoc_split_slots=1024)
    half = len(corpus) // 2
    got = _tuples(e_a.detect_many(corpus[:half], batch_size=BATCH))
    # the swapped-in engine (same artifact, fresh pipeline state)
    e_b = _engine(3, tables=e_a.tables, longdoc_split_slots=1024)
    got += _tuples(e_b.detect_many(corpus[half:], batch_size=BATCH))
    assert got == ref
    for e in (e_a, e_b):
        s = e.pipeline_stats()
        assert s["inflight"] == 0
        assert s["staging_ring_occupancy"] == 0


# -- long-doc lane exactness -------------------------------------------------


def test_longdoc_chunk_merge_exact_vs_scalar():
    """≥100 multi-span long docs: the span-parallel chunk lane (split
    in preprocess/pack.py, merged in result_vector.py) is byte-exact
    against the scalar reference engine on every doc."""
    from language_detector_tpu.engine_scalar import detect_scalar
    rng = random.Random(23)
    docs = [_long_doc(rng, rng.randint(5000, 14000))
            for _ in range(104)]
    eng = _engine(2, longdoc_split_slots=1024)
    got = eng.detect_many(docs, batch_size=BATCH)
    bad = []
    for i, t in enumerate(docs):
        want = detect_scalar(t, eng.tables, eng.reg)
        if _result_tuple(got[i]) != _result_tuple(want):
            bad.append((i, _result_tuple(got[i]), _result_tuple(want)))
    assert not bad, f"{len(bad)} long-doc disagreements, first: {bad[0]}"
    # the lane must actually have split — a corpus that fit the top
    # bucket would pin nothing
    assert eng.stats["longdoc_split_docs"] >= 100
    assert eng.stats["longdoc_subdocs"] > eng.stats["longdoc_split_docs"]
    assert eng.pipeline_stats()["longdoc_chunks"] > 0


def test_longdoc_lane_off_still_exact():
    """longdoc_chunk_slots=0 disables the lane (docs take the ordinary
    tail-bucket path); results must not depend on the lane being on."""
    rng = random.Random(29)
    docs = [_long_doc(rng, rng.randint(5000, 9000)) for _ in range(12)]
    ref = _tuples(_engine(1, longdoc_chunk_slots=0)
                  .detect_many(docs, batch_size=BATCH))
    eng = _engine(2, longdoc_split_slots=1024)
    assert _tuples(eng.detect_many(docs, batch_size=BATCH)) == ref
    assert eng.stats["longdoc_split_docs"] > 0


def test_longdoc_default_threshold_takes_fat_tail():
    """At the default LDT_LONGDOC_SPLIT_SLOTS, mid-size docs ride
    their tier unsplit (the split scan + merge is pure overhead for
    them) while the fat tail still splits — and stays exact."""
    from language_detector_tpu.engine_scalar import detect_scalar
    rng = random.Random(31)
    mids = [_long_doc(rng, 6000) for _ in range(4)]
    fats = [_long_doc(rng, 30000) for _ in range(4)]
    eng = _engine(2)
    got = eng.detect_many(mids + fats, batch_size=BATCH)
    assert eng.stats["longdoc_split_docs"] == len(fats)
    for t, r in zip(mids + fats, got):
        want = detect_scalar(t, eng.tables, eng.reg)
        assert _result_tuple(r) == _result_tuple(want)
