"""Chaos suite for the fault-injection framework (faults.py) and the
recovery invariants in docs/ROBUSTNESS.md.

Layers covered:
  - the LDT_FAULTS spec parser and deterministic schedules (p/seed,
    once, after, delay accumulation, loud rejection of typos);
  - artifact corruption regressions: every corruption mode raises a
    typed ArtifactError (a ValueError) with an actionable message;
  - the engine seams (scorer_launch / compile / device_flush) against
    a real NgramBatchEngine;
  - HTTP-level chaos on BOTH fronts: every submitted document resolves
    (a result or a typed 500/504, never a hang), the breaker opens
    under an injected device-error storm and recovers through a
    half-open probe, flush timeouts answer 504, queue faults fail that
    request only, accept faults drop the connection pre-read;
  - the /healthz + /readyz contract (service and metrics ports, the
    `ldt_ready` gauge, `"ready"` in /debug/vars).
"""
from __future__ import annotations

import asyncio
import http.client
import json
import os
import struct
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from language_detector_tpu import artifact, faults, native, telemetry
from language_detector_tpu.parallel import pool as pool_mod
from language_detector_tpu.service.admission import (AdmissionConfig,
                                                     AdmissionController)
from language_detector_tpu.service.batcher import Batcher
from language_detector_tpu.service.server import (DetectorService,
                                                  health_response,
                                                  make_server)

EN = ("this is a simple english sentence with common words that "
      "should be detected without any trouble at all")
FR = ("Le gouvernement a annoncé de nouvelles mesures pour aider "
      "les familles concernées")
# > TINY_BATCH_C_PATH (64) docs so device-front requests actually cross
# the launch/flush seams instead of the all-C shortcut
STORM_DOCS = [EN, FR] * 40


@pytest.fixture(autouse=True)
def _disarm():
    """Every test leaves injection disarmed, whatever it armed."""
    yield
    faults.configure(None)


def _post(url, payload, headers=None, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, json.loads(body) if body else None


def _get(url, timeout=30):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# -- spec parser & schedules -------------------------------------------------


def test_spec_rejected_loud():
    for bad in ("device_flush",                   # no action
                "not_a_point:error",              # undeclared point
                "device_flush:explode",           # unknown action
                "device_flush:error:bogus=1"):    # unknown option
        with pytest.raises(ValueError):
            faults.configure(bad)
    # the unknown-point message names the declared points
    with pytest.raises(ValueError, match="device_flush"):
        faults.configure("not_a_point:error")


def test_blank_spec_disarms():
    faults.configure("device_flush:error")
    assert faults.ACTIVE is not None
    faults.configure(None)
    assert faults.ACTIVE is None
    faults.configure("")
    assert faults.ACTIVE is None
    assert faults.evaluate("device_flush") == (0.0, False)


def test_undeclared_point_is_a_programming_error():
    with pytest.raises(KeyError):
        faults.evaluate("nope_not_declared")
    with pytest.raises(KeyError):
        faults.hit("nope_not_declared")


def test_probability_schedule_is_deterministic():
    spec = "device_flush:error:p=0.5:seed=7"
    faults.configure(spec)
    first = [faults.evaluate("device_flush")[1] for _ in range(12)]
    faults.configure(spec)  # re-arm: same seed, same schedule
    again = [faults.evaluate("device_flush")[1] for _ in range(12)]
    assert first == again
    assert True in first and False in first  # actually probabilistic


def test_once_and_after_semantics():
    faults.configure("compile:delay_ms=100:once")
    assert faults.evaluate("compile") == (0.1, False)
    assert faults.evaluate("compile") == (0.0, False)  # disarmed

    faults.configure("queue_put:error:after=2")
    assert faults.evaluate("queue_put") == (0.0, False)
    assert faults.evaluate("queue_put") == (0.0, False)
    assert faults.evaluate("queue_put") == (0.0, True)  # from arrival 3
    assert faults.evaluate("queue_put") == (0.0, True)


def test_multiple_rules_accumulate():
    faults.configure("device_flush:delay_ms=10,"
                     "device_flush:delay_ms=5,device_flush:error")
    delay, err = faults.evaluate("device_flush")
    assert err is True
    assert delay == pytest.approx(0.015)


def test_fired_faults_counted():
    before = telemetry.REGISTRY.counter_value(
        "ldt_fault_injected_total", point="queue_get")
    faults.configure("queue_get:error")
    with pytest.raises(faults.FaultInjected):
        faults.hit("queue_get")
    assert telemetry.REGISTRY.counter_value(
        "ldt_fault_injected_total", point="queue_get") == before + 1


def test_hit_async_same_contract():
    faults.configure("queue_put:delay_ms=1,queue_put:error")

    async def drive():
        with pytest.raises(faults.FaultInjected):
            await faults.hit_async("queue_put")

    asyncio.run(drive())


# -- artifact corruption regressions -----------------------------------------


@pytest.fixture()
def packed(tmp_path):
    path = tmp_path / "model.ldta"
    artifact.write_artifact(
        {"a": np.arange(16, dtype=np.int32),
         "b": np.ones((2, 3), dtype=np.float32)}, path)
    return path


def _corrupt(path, offset, blob):
    raw = bytearray(path.read_bytes())
    raw[offset:offset + len(blob)] = blob
    path.write_bytes(bytes(raw))


def test_artifact_round_trip(packed):
    out = artifact.load_artifact(packed)
    assert list(out["a"]) == list(range(16))
    assert out["b"].shape == (2, 3)


@pytest.mark.parametrize("mode", ["truncated_header", "bad_magic",
                                  "bad_version", "size_mismatch",
                                  "bad_header_bytes"])
def test_artifact_corruption_modes_fail_loud(packed, mode):
    if mode == "truncated_header":
        packed.write_bytes(packed.read_bytes()[:8])
        expect = "shorter than the header"
    elif mode == "bad_magic":
        _corrupt(packed, 0, b"\xde\xad\xbe\xef")
        expect = "bad magic"
    elif mode == "bad_version":
        _corrupt(packed, 4, struct.pack("<I", 99))
        expect = "format version 99"
    elif mode == "size_mismatch":
        packed.write_bytes(packed.read_bytes()[:-7])
        expect = "truncated or corrupt"
    else:  # bad_header_bytes: n_arrays inconsistent with header_bytes
        _corrupt(packed, 8, struct.pack("<I", 1000))
        expect = "corrupt header"
    with pytest.raises(artifact.ArtifactError) as ei:
        artifact.load_artifact(packed)
    assert expect in str(ei.value)
    # actionable: the message names the file and the fix
    assert str(packed) in str(ei.value)
    assert "artifact_tool.py" in str(ei.value)
    # pre-existing `except ValueError` load guards still catch it
    assert isinstance(ei.value, ValueError)


def test_artifact_load_fault_point(packed):
    faults.configure("artifact_load:error")
    with pytest.raises(faults.FaultInjected):
        artifact.load_artifact(packed)
    faults.configure(None)
    assert "a" in artifact.load_artifact(packed)


# -- batcher seams (no HTTP) -------------------------------------------------


def test_queue_put_fault_raises_in_submit_nothing_enqueued():
    b = Batcher(lambda texts: ["en"] * len(texts), max_delay_ms=1.0)
    try:
        faults.configure("queue_put:error")
        with pytest.raises(faults.FaultInjected):
            b.submit([EN])
        faults.configure(None)
        assert b.submit([EN]).result(timeout=10) == ["en"]
    finally:
        b.close()


def test_queue_get_fault_fails_batch_collector_survives():
    b = Batcher(lambda texts: ["en"] * len(texts), max_delay_ms=1.0)
    try:
        faults.configure("queue_get:error:once")
        fut = b.submit([EN])
        with pytest.raises(faults.FaultInjected):
            fut.result(timeout=10)
        # the collector survived the injected dequeue error
        assert b.submit([EN]).result(timeout=10) == ["en"]
    finally:
        b.close()


# -- engine seams ------------------------------------------------------------


needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native packer unavailable")


@pytest.fixture(scope="module")
def engine():
    if not native.available():
        pytest.skip("native packer unavailable")
    from language_detector_tpu.models.ngram import NgramBatchEngine
    return NgramBatchEngine()


@needs_native
def test_engine_seam_faults_raise_and_heal(engine):
    want = ["en", "fr"] * 40
    assert engine.detect_codes(STORM_DOCS) == want  # warm (compiles)

    for point in ("scorer_launch", "device_flush"):
        faults.configure(f"{point}:error")
        with pytest.raises(faults.FaultInjected):
            engine.detect_codes(STORM_DOCS)
        faults.configure(None)
        # the failure left no wedged state behind
        assert engine.detect_codes(STORM_DOCS) == want


@needs_native
def test_compile_delay_does_not_corrupt_results(engine):
    # delay-only rule on the compile seam: results stay exact
    faults.configure("compile:delay_ms=1")
    assert engine.detect_codes(STORM_DOCS) == ["en", "fr"] * 40


# -- sync front under chaos --------------------------------------------------


@pytest.fixture(scope="module")
def front():
    """Scalar-engine sync front for queue/accept/timeout chaos (the
    batcher seams are engine-independent)."""
    ctrl = AdmissionController(AdmissionConfig())
    svc = DetectorService(use_device=False, max_delay_ms=1.0,
                          admission=ctrl)
    httpd, metricsd, svc = make_server(0, 0, service=svc)
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in (httpd, metricsd)]
    for t in threads:
        t.start()
    yield {"url": f"http://127.0.0.1:{httpd.server_address[1]}",
           "metrics_url":
               f"http://127.0.0.1:{metricsd.server_address[1]}",
           "svc": svc, "ctrl": ctrl}
    httpd.shutdown()
    metricsd.shutdown()
    svc.batcher.close()


def test_sync_queue_put_fault_is_typed_500(front):
    faults.configure("queue_put:error")
    status, body = _post(front["url"], {"request": [{"text": EN}]})
    assert status == 500
    assert body == {"error": "internal error"}
    faults.configure(None)
    status, body = _post(front["url"], {"request": [{"text": EN}]})
    assert status == 200
    assert body["response"][0]["iso6391code"] == "en"


def test_sync_queue_get_fault_resolves_not_hangs(front):
    faults.configure("queue_get:error:once")
    status, body = _post(front["url"], {"request": [{"text": EN}]},
                         timeout=15)
    assert status == 500 and body == {"error": "internal error"}
    faults.configure(None)
    status, _ = _post(front["url"], {"request": [{"text": EN}]})
    assert status == 200


def test_sync_flush_timeout_is_504(front, monkeypatch):
    monkeypatch.setenv("LDT_FLUSH_TIMEOUT_SEC", "0.1")
    faults.configure("queue_get:delay_ms=700:once")
    status, body = _post(front["url"], {"request": [{"text": EN}]},
                         timeout=15)
    assert status == 504
    assert body == {"error": "detection timed out"}
    monkeypatch.delenv("LDT_FLUSH_TIMEOUT_SEC")
    faults.configure(None)
    time.sleep(0.8)  # let the delayed collector pass drain
    status, _ = _post(front["url"], {"request": [{"text": EN}]})
    assert status == 200


def test_sync_expired_work_504_under_queue_delay(front):
    # the injected dequeue delay pushes the request past its deadline:
    # dropped at dequeue (504), no detect work burned
    faults.configure("queue_get:delay_ms=300:once")
    status, body = _post(front["url"], {"request": [{"text": EN}]},
                         headers={"X-LDT-Deadline-Ms": "50"},
                         timeout=15)
    assert status == 504
    assert body == {"error": "deadline expired before dispatch"}


def test_sync_accept_fault_drops_connection(front):
    faults.configure("accept:error")
    with pytest.raises((urllib.error.URLError, ConnectionError,
                        http.client.HTTPException)):
        _post(front["url"], {"request": [{"text": EN}]}, timeout=10)
    faults.configure(None)
    status, _ = _post(front["url"], {"request": [{"text": EN}]})
    assert status == 200


def test_health_and_ready_endpoints(front):
    for base in (front["url"], front["metrics_url"]):
        status, body = _get(base + "/healthz")
        assert (status, json.loads(body)) == (200, {"status": "ok"})
        status, body = _get(base + "/readyz")
        assert status == 200
        doc = json.loads(body)
        assert doc["ok"] is True and doc["artifact_loaded"] is True
        assert doc["breaker"] == "closed" and doc["brownout_level"] == 0


def test_readyz_flips_on_brownout_and_artifact(front):
    ctrl = front["ctrl"]
    svc = front["svc"]
    ctrl.ladder.alpha = 0.0
    ctrl.ladder.ema = 1.0
    ctrl.ladder.level = 3
    try:
        status, body = _get(front["url"] + "/readyz")
        assert status == 503
        assert json.loads(body)["brownout_level"] == 3
    finally:
        ctrl.ladder.alpha = ctrl.config.brownout_alpha
        ctrl.ladder.ema = 0.0
        ctrl.ladder.level = 0
    svc._artifact_loaded = False
    try:
        status, body = _get(front["url"] + "/readyz")
        assert status == 503
        assert json.loads(body)["artifact_loaded"] is False
    finally:
        svc._artifact_loaded = True
    # healthz stays 200 through all of it: liveness is unconditional
    status, _ = _get(front["url"] + "/healthz")
    assert status == 200


def test_ready_in_metrics_and_debug_vars(front):
    _, body = _get(front["metrics_url"] + "/metrics")
    text = body.decode()
    assert "ldt_ready 1" in text
    assert "ldt_worker_generation" in text
    _, body = _get(front["metrics_url"] + "/debug/vars")
    doc = json.loads(body)
    assert doc["ready"]["ok"] is True
    assert set(doc["ready"]) == {"ok", "artifact_loaded", "breaker",
                                 "brownout_level", "warmed",
                                 "warmup_ms"}


def test_health_response_contract_unit(front):
    svc = front["svc"]
    assert health_response(svc, "/healthz") == (200, b'{"status":"ok"}')
    status, body = health_response(svc, "/readyz")
    assert status == 200 and json.loads(body)["ok"] is True


# -- breaker storm + half-open recovery, sync front --------------------------


@pytest.fixture(scope="module")
def device_front():
    """Engine-backed sync front with a tight injected breaker so the
    storm tests trip and recover in test time."""
    if not native.available():
        pytest.skip("native packer unavailable")
    ctrl = AdmissionController(AdmissionConfig(breaker_failures=2,
                                               breaker_cooldown_sec=0.2))
    svc = DetectorService(use_device=True, max_delay_ms=1.0,
                          admission=ctrl)
    if svc._engine is None:
        pytest.skip("device engine unavailable")
    httpd, metricsd, svc = make_server(0, 0, service=svc)
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in (httpd, metricsd)]
    for t in threads:
        t.start()
    yield {"url": f"http://127.0.0.1:{httpd.server_address[1]}",
           "metrics_url":
               f"http://127.0.0.1:{metricsd.server_address[1]}",
           "svc": svc, "ctrl": ctrl}
    httpd.shutdown()
    metricsd.shutdown()
    svc.batcher.close()


def test_sync_breaker_storm_opens_then_halfopen_recovers(device_front):
    url = device_front["url"]
    br = device_front["ctrl"].breaker
    payload = {"request": [{"text": t} for t in STORM_DOCS]}

    # warm: the jit compile happens on a healthy flush, not the probe
    status, body = _post(url, payload, timeout=120)
    assert status == 200
    assert [r["iso6391code"] for r in body["response"][:2]] == \
        ["en", "fr"]
    trips0 = br.stats()["trips"]

    # storm: every device fetch dies; each request resolves as a typed
    # 500 until the breaker opens, then scalar serves exact 200s
    faults.configure("device_flush:error:p=1")
    statuses = []
    while br.stats()["state"] != 2 and len(statuses) < 10:
        status, body = _post(url, payload, timeout=60)
        statuses.append(status)
        assert status in (200, 500)  # resolved, never hung
    assert br.stats()["state"] == 2  # open
    assert br.stats()["trips"] == trips0 + 1
    assert 500 in statuses

    # open: served via scalar, exact answers, readyz says route-around
    status, body = _post(url, payload, timeout=120)
    assert status == 200
    assert [r["iso6391code"] for r in body["response"][:2]] == \
        ["en", "fr"]
    status, body = _get(url + "/readyz")
    assert status == 503 and json.loads(body)["breaker"] == "open"
    status, _ = _get(url + "/healthz")
    assert status == 200

    # heal the device, wait out the cooldown: the next request is the
    # half-open probe; success closes the breaker
    faults.configure(None)
    probes0 = br.stats()["probes"]
    time.sleep(0.25)
    status, body = _post(url, payload, timeout=120)
    assert status == 200
    assert br.stats()["state"] == 0  # closed again
    assert br.stats()["probes"] == probes0 + 1
    status, body = _get(url + "/readyz")
    assert status == 200 and json.loads(body)["breaker"] == "closed"

    # the storm was counted
    assert telemetry.REGISTRY.counter_value(
        "ldt_fault_injected_total", point="device_flush") >= 2


def test_sync_probabilistic_storm_every_doc_resolves(device_front):
    """The headline chaos invariant: under a 50% device-error storm,
    every request resolves with a result or a typed error — no hangs,
    no torn connections — and the stack recovers afterwards."""
    url = device_front["url"]
    br = device_front["ctrl"].breaker
    payload = {"request": [{"text": t} for t in STORM_DOCS]}
    faults.configure("device_flush:error:p=0.5:seed=3")
    statuses = []
    for _ in range(8):
        status, body = _post(url, payload, timeout=120)
        statuses.append(status)
        assert status in (200, 500)
        if status == 200:
            assert len(body["response"]) == len(STORM_DOCS)
    faults.configure(None)
    time.sleep(0.25)  # cooldown, in case the storm tripped it
    status, body = _post(url, payload, timeout=120)
    assert status == 200
    deadline = time.time() + 5
    while br.stats()["state"] != 0 and time.time() < deadline:
        _post(url, payload, timeout=120)
        time.sleep(0.05)
    assert br.stats()["state"] == 0


# -- asyncio front under chaos -----------------------------------------------


@pytest.fixture(scope="module")
def aio_front():
    """Engine-backed asyncio front (same breaker wiring via
    svc._detect) driven from a side thread, as in test_admission."""
    if not native.available():
        pytest.skip("native packer unavailable")
    import queue as _q

    from language_detector_tpu.service.aioserver import serve

    ctrl = AdmissionController(AdmissionConfig(breaker_failures=2,
                                               breaker_cooldown_sec=0.2))
    svc = DetectorService(use_device=True, max_delay_ms=1.0,
                          start_batcher=False, admission=ctrl)
    if svc._engine is None:
        pytest.skip("device engine unavailable")
    ports_q: _q.Queue = _q.Queue()
    loop_holder = {}

    def run_loop():
        async def main():
            loop_holder["loop"] = asyncio.get_running_loop()
            ready = asyncio.get_running_loop().create_future()
            task = asyncio.get_running_loop().create_task(
                serve(0, 0, svc=svc, ready=ready))
            ports_q.put(await ready)
            try:
                await task
            except asyncio.CancelledError:
                pass
        try:
            asyncio.run(main())
        except RuntimeError:
            pass  # loop.stop() teardown ends the run mid-await

    t = threading.Thread(target=run_loop, daemon=True)
    t.start()
    port, mport = ports_q.get(timeout=30)
    yield {"url": f"http://127.0.0.1:{port}",
           "metrics_url": f"http://127.0.0.1:{mport}",
           "svc": svc, "ctrl": ctrl}
    loop = loop_holder.get("loop")
    if loop is not None:
        loop.call_soon_threadsafe(loop.stop)


def test_aio_breaker_storm_opens_then_halfopen_recovers(aio_front):
    url = aio_front["url"]
    br = aio_front["ctrl"].breaker
    payload = {"request": [{"text": t} for t in STORM_DOCS]}

    status, body = _post(url, payload, timeout=120)  # warm compile
    assert status == 200
    trips0 = br.stats()["trips"]

    faults.configure("device_flush:error:p=1")
    statuses = []
    while br.stats()["state"] != 2 and len(statuses) < 10:
        status, _ = _post(url, payload, timeout=60)
        statuses.append(status)
        assert status in (200, 500)
    assert br.stats()["state"] == 2
    assert br.stats()["trips"] == trips0 + 1

    # open: exact scalar answers; readyz 503 on service AND metrics port
    status, body = _post(url, payload, timeout=120)
    assert status == 200
    assert [r["iso6391code"] for r in body["response"][:2]] == \
        ["en", "fr"]
    for base in (url, aio_front["metrics_url"]):
        status, body = _get(base + "/readyz")
        assert status == 503 and json.loads(body)["breaker"] == "open"
        status, _ = _get(base + "/healthz")
        assert status == 200

    faults.configure(None)
    probes0 = br.stats()["probes"]
    time.sleep(0.25)
    status, _ = _post(url, payload, timeout=120)
    assert status == 200
    assert br.stats()["state"] == 0
    assert br.stats()["probes"] == probes0 + 1
    status, body = _get(url + "/readyz")
    assert status == 200 and json.loads(body)["ok"] is True


def test_aio_queue_and_timeout_chaos(aio_front, monkeypatch):
    url = aio_front["url"]
    one = {"request": [{"text": EN}]}

    # queue_put: typed 500 raised before anything is enqueued
    faults.configure("queue_put:error")
    status, body = _post(url, one, timeout=15)
    assert status == 500 and body == {"error": "internal error"}

    # queue_get: that batch's futures fail, the collector survives
    faults.configure("queue_get:error:once")
    status, body = _post(url, one, timeout=15)
    assert status == 500 and body == {"error": "internal error"}
    faults.configure(None)
    status, _ = _post(url, one)
    assert status == 200

    # flush timeout: 504 with the timeout body, then recovery
    monkeypatch.setenv("LDT_FLUSH_TIMEOUT_SEC", "0.1")
    faults.configure("queue_get:delay_ms=700:once")
    status, body = _post(url, one, timeout=15)
    assert status == 504 and body == {"error": "detection timed out"}
    monkeypatch.delenv("LDT_FLUSH_TIMEOUT_SEC")
    faults.configure(None)
    time.sleep(0.8)
    status, _ = _post(url, one)
    assert status == 200


def test_aio_accept_fault_drops_connection(aio_front):
    faults.configure("accept:error")
    with pytest.raises((urllib.error.URLError, ConnectionError,
                        http.client.HTTPException)):
        _post(aio_front["url"], {"request": [{"text": EN}]}, timeout=10)
    faults.configure(None)
    status, _ = _post(aio_front["url"], {"request": [{"text": EN}]})
    assert status == 200


# -- device-pool scheduler chaos (parallel/pool.py) --------------------------
#
# The pool fixtures run 2 SIMULATED lanes sharing the one CPU scorer:
# same rotation / eviction / failover scheduler the mesh lanes get,
# exercised through the real fronts. Pool requests need more unique
# docs than the all-C shortcut (TINY_BATCH_C_PATH=64) AND distinct
# texts per request, so every request genuinely crosses the lane seams
# instead of resolving via dedup or the result cache.


def _pool_docs(tag: str) -> list:
    return [f"the quick brown fox jumps over the lazy dog in burst "
            f"{tag} document number {i}" for i in range(80)]


_POOL_ENV = {"LDT_POOL_LANES": "2",
             "LDT_POOL_HEDGE_FACTOR": "0",      # failover only: no
             "LDT_POOL_EVICT_FAILURES": "5",    # hedge/evict noise in
             "LDT_POOL_PROBE_COOLDOWN_SEC": "0.2",  # the storm stats
             "LDT_POOL_MAX_REDISPATCH": "8"}


def _set_pool_env():
    saved = {k: os.environ.get(k) for k in _POOL_ENV}
    os.environ.update(_POOL_ENV)
    return saved


def _restore_pool_env(saved):
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


@pytest.fixture(scope="module")
def pool_front():
    """Threaded front over a 2-lane pooled engine. Env (not
    monkeypatch): the knobs must be set before engine construction and
    outlive every test of the module."""
    if not native.available():
        pytest.skip("native packer unavailable")
    saved = _set_pool_env()
    try:
        ctrl = AdmissionController(AdmissionConfig())
        svc = DetectorService(use_device=True, max_delay_ms=1.0,
                              admission=ctrl)
        if svc._engine is None or svc._engine.pool is None:
            pytest.skip("pooled device engine unavailable")
        httpd, metricsd, svc = make_server(0, 0, service=svc)
        threads = [threading.Thread(target=s.serve_forever, daemon=True)
                   for s in (httpd, metricsd)]
        for t in threads:
            t.start()
        yield {"url": f"http://127.0.0.1:{httpd.server_address[1]}",
               "svc": svc, "ctrl": ctrl}
        httpd.shutdown()
        metricsd.shutdown()
        svc.batcher.close()
        svc._engine.pool.close()
    finally:
        _restore_pool_env(saved)


@pytest.fixture(scope="module")
def pool_aio_front():
    """Asyncio front over its own 2-lane pooled engine (same side-
    thread loop scaffolding as aio_front)."""
    if not native.available():
        pytest.skip("native packer unavailable")
    import queue as _q

    from language_detector_tpu.service.aioserver import serve

    saved = _set_pool_env()
    try:
        ctrl = AdmissionController(AdmissionConfig())
        svc = DetectorService(use_device=True, max_delay_ms=1.0,
                              start_batcher=False, admission=ctrl)
        if svc._engine is None or svc._engine.pool is None:
            pytest.skip("pooled device engine unavailable")
        ports_q: _q.Queue = _q.Queue()
        loop_holder = {}

        def run_loop():
            async def main():
                loop_holder["loop"] = asyncio.get_running_loop()
                ready = asyncio.get_running_loop().create_future()
                task = asyncio.get_running_loop().create_task(
                    serve(0, 0, svc=svc, ready=ready))
                ports_q.put(await ready)
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            try:
                asyncio.run(main())
            except RuntimeError:
                pass  # loop.stop() teardown ends the run mid-await

        t = threading.Thread(target=run_loop, daemon=True)
        t.start()
        port, _mport = ports_q.get(timeout=30)
        yield {"url": f"http://127.0.0.1:{port}", "svc": svc}
        loop = loop_holder.get("loop")
        if loop is not None:
            loop.call_soon_threadsafe(loop.stop)
        svc._engine.pool.close()
    finally:
        _restore_pool_env(saved)


def _pool_burst(url, tag_prefix, n_requests=5):
    """Fire n concurrent pooled requests (distinct corpora) and return
    their (status, body) results. A request that never resolves fails
    the join timeout — the zero-lost-futures invariant."""
    results: list = []
    lock = threading.Lock()

    def worker(w):
        docs = _pool_docs(f"{tag_prefix}-{w}")
        got = _post(url, {"request": [{"text": t} for t in docs]},
                    timeout=120)
        with lock:
            results.append(got)

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(n_requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not any(t.is_alive() for t in threads), "request hung"
    return results


def test_pool_lane_lost_recovers_sync_front(pool_front):
    """A deterministic lost batch mid-request fails over to the other
    lane: the request still answers 200 with every doc resolved."""
    url = pool_front["url"]
    docs = _pool_docs("warm-sync")
    payload = {"request": [{"text": t} for t in docs]}
    status, body = _post(url, payload, timeout=120)  # warm compile
    assert status == 200 and len(body["response"]) == len(docs)

    fo0 = telemetry.REGISTRY.counter_value("ldt_pool_failover_total")
    inj0 = telemetry.REGISTRY.counter_value(
        "ldt_fault_injected_total", point="lane_lost")
    faults.configure("lane_lost:error:once")
    status, body = _post(url, payload, timeout=120)
    assert status == 200
    assert len(body["response"]) == len(docs)
    assert body["response"][0]["iso6391code"] == "en"
    assert telemetry.REGISTRY.counter_value(
        "ldt_fault_injected_total", point="lane_lost") == inj0 + 1
    assert telemetry.REGISTRY.counter_value(
        "ldt_pool_failover_total") >= fo0 + 1


def test_pool_lane_lost_midburst_sync_front(pool_front):
    """Probabilistic lane_lost + lane_stall storm under a concurrent
    burst: every request resolves 200 with a full result set (failover
    absorbs the losses; nothing hangs, nothing is dropped)."""
    url = pool_front["url"]
    faults.configure("lane_lost:error:p=0.25:seed=9,"
                     "lane_stall:delay_ms=20:p=0.2:seed=4")
    results = _pool_burst(url, "storm-sync")
    faults.configure(None)
    assert len(results) == 5
    for status, body in results:
        assert status == 200
        assert len(body["response"]) == 80
        assert body["response"][0]["iso6391code"] == "en"
    # recovery: a clean request after the storm
    status, body = _post(
        url, {"request": [{"text": t}
                          for t in _pool_docs("post-sync")]},
        timeout=120)
    assert status == 200 and len(body["response"]) == 80


def test_pool_lane_lost_midburst_aio_front(pool_aio_front):
    """The same mid-burst invariant through the asyncio front: its
    flush workers ride the identical pool seam."""
    url = pool_aio_front["url"]
    docs = _pool_docs("warm-aio")
    status, body = _post(url, {"request": [{"text": t} for t in docs]},
                         timeout=120)  # warm compile
    assert status == 200 and len(body["response"]) == len(docs)

    faults.configure("lane_lost:error:p=0.25:seed=11,"
                     "lane_stall:delay_ms=20:p=0.2:seed=6")
    results = _pool_burst(url, "storm-aio")
    faults.configure(None)
    assert len(results) == 5
    for status, body in results:
        assert status == 200
        assert len(body["response"]) == 80
        assert body["response"][0]["iso6391code"] == "en"
    status, body = _post(
        url, {"request": [{"text": t}
                          for t in _pool_docs("post-aio")]},
        timeout=120)
    assert status == 200 and len(body["response"]) == 80


# -- pool scheduler invariants (stub lanes, no HTTP) --------------------------


class _Raw:
    """Stub device future: __array__ delegates to a callable, exactly
    the shape of a jax async result the pool fetches."""

    def __init__(self, fn):
        self._fn = fn

    def __array__(self, dtype=None):
        out = np.asarray(self._fn())
        return out if dtype is None else out.astype(dtype)


def test_pool_straggler_hedge_wins_exactly_once():
    """A fetch past the hedge threshold re-dispatches on the other
    lane; the hedge's result wins, is counted once, and a second fetch
    of the future cannot re-dispatch or re-resolve."""
    lanes = [pool_mod.Lane(0, None), pool_mod.Lane(1, None)]
    pool = pool_mod.DevicePool(lanes, hedge_factor=1.0, hedge_min_ms=1.0,
                               evict_failures=5,
                               probe_cooldown_sec=60.0,
                               max_redispatch=2)
    try:
        for ln in lanes:  # trusted p95 (5ms) so the hedge arms
            for _ in range(pool_mod.HEDGE_MIN_SAMPLES + 1):
                ln.record_success(5.0, 0.0)
        release = threading.Event()

        def slow():
            release.wait(10)
            return np.array([1.0])

        calls: list = []

        def launch_fn(lane):
            calls.append(lane.name)
            return _Raw(slow) if len(calls) == 1 \
                else _Raw(lambda: np.array([2.0]))

        won0 = telemetry.REGISTRY.counter_value(
            "ldt_pool_hedges_total", result="won")
        pf = pool.launch(launch_fn)
        out = np.asarray(pf)
        assert out.tolist() == [2.0]  # the hedge's result won
        assert len(calls) == 2 and calls[0] != calls[1]
        assert telemetry.REGISTRY.counter_value(
            "ldt_pool_hedges_total", result="won") == won0 + 1
        # memoized resolution: no re-dispatch, no double-resolve
        assert np.asarray(pf).tolist() == [2.0]
        assert len(calls) == 2
    finally:
        release.set()
        pool.close()


def test_pool_evicted_lane_readmits_via_probe():
    """lane_lost chaos evicts both lanes (counted once each); after the
    cooldown each lane carries a half-open probe whose success re-admits
    it to rotation — capacity returns to full."""
    clk = [0.0]
    lanes = [pool_mod.Lane(0, None), pool_mod.Lane(1, None)]
    pool = pool_mod.DevicePool(lanes, hedge_factor=0,
                               evict_failures=2,
                               probe_cooldown_sec=5.0,
                               max_redispatch=2,
                               clock=lambda: clk[0])
    try:
        ok = _Raw(lambda: np.arange(3))
        ev0 = {ln.name: telemetry.REGISTRY.counter_value(
            "ldt_pool_lane_evicted_total", lane=ln.name)
            for ln in lanes}
        re0 = {ln.name: telemetry.REGISTRY.counter_value(
            "ldt_pool_lane_readmitted_total", lane=ln.name)
            for ln in lanes}
        faults.configure("lane_lost:error")
        for _ in range(2):  # 2 failures per lane -> both evicted
            with pytest.raises(pool_mod.PoolExhausted):
                np.asarray(pool.launch(lambda lane: ok))
        assert [ln.state() for ln in lanes] == \
            [pool_mod.LANE_EVICTED] * 2
        assert pool.capacity() == (0, 2)
        assert pool.capacity_load() == pytest.approx(1.2)
        for ln in lanes:
            assert telemetry.REGISTRY.counter_value(
                "ldt_pool_lane_evicted_total",
                lane=ln.name) == ev0[ln.name] + 1

        # heal the device, pass the cooldown: each lane's next launch
        # is its half-open probe; the successful fetch re-admits it
        faults.configure(None)
        clk[0] = 6.0
        for _ in range(4):
            assert np.asarray(
                pool.launch(lambda lane: ok)).tolist() == [0, 1, 2]
            if pool.capacity() == (2, 2):
                break
        assert [ln.state() for ln in lanes] == \
            [pool_mod.LANE_ACTIVE] * 2
        assert pool.capacity() == (2, 2)
        assert pool.capacity_load() == 0.0
        for ln in lanes:
            assert telemetry.REGISTRY.counter_value(
                "ldt_pool_lane_readmitted_total",
                lane=ln.name) == re0[ln.name] + 1
    finally:
        pool.close()


def test_pool_brownout_rises_when_half_lanes_evicted():
    """Pool-capacity loss feeds the admission brownout ladder: half the
    lanes evicted lifts the load signal to 0.6 (level 1); a fully
    evicted pool reads 1.2 and sheds like a breaker-open worker."""
    clk = [0.0]
    lanes = [pool_mod.Lane(0, None), pool_mod.Lane(1, None)]
    pool = pool_mod.DevicePool(lanes, hedge_factor=0, evict_failures=1,
                               probe_cooldown_sec=600.0,
                               max_redispatch=1,
                               clock=lambda: clk[0])
    try:
        ctrl = AdmissionController(AdmissionConfig(brownout_alpha=1.0))
        ctrl.attach_pool(lambda: pool)

        admit = ctrl.try_admit([EN])
        assert not admit.shed
        ctrl.release(admit)
        assert ctrl.stats()["brownout_level"] == 0

        lanes[0].record_failure(0.0, 1)  # evict half the pool
        admit = ctrl.try_admit([EN])
        assert not admit.shed
        ctrl.release(admit)
        assert ctrl.stats()["brownout_level"] == 1

        lanes[1].record_failure(0.0, 1)  # pool fully evicted
        admit = ctrl.try_admit([EN])
        if not admit.shed:  # the shed decision uses the NEW level
            ctrl.release(admit)
        admit = ctrl.try_admit([EN])
        assert admit.shed and admit.status == 503
        assert ctrl.stats()["brownout_level"] == 3
        # priority traffic still lands through a full brownout
        admit = ctrl.try_admit([EN], priority=True)
        assert not admit.shed
        ctrl.release(admit)

        # probe trickle: once an evicted lane's cooldown elapses, the
        # full shed must admit a plain request as the probe vehicle —
        # probes are traffic-driven, so a blanket 503 would leave the
        # pool down forever
        clk[0] = 601.0
        before = telemetry.REGISTRY.counter_value(
            "ldt_pool_probe_admits_total")
        admit = ctrl.try_admit([EN])
        assert not admit.shed
        ctrl.release(admit)
        assert telemetry.REGISTRY.counter_value(
            "ldt_pool_probe_admits_total") == before + 1
        # once a probe is in flight (lane PROBING) the trickle closes —
        # no second vehicle — and the probing lane counts as carrying
        # work again, so the ladder steps down from full shed
        assert lanes[0].try_begin_probe(clk[0], 600.0)
        assert not pool.wants_probe()
        admit = ctrl.try_admit([EN])
        assert not admit.shed
        ctrl.release(admit)
        assert ctrl.stats()["brownout_level"] < 3
    finally:
        pool.close()
