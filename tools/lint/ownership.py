"""Declared lock-ownership map for the serving stack.

This file IS the concurrency design document the lock-discipline
analyzer enforces: for every class that shares state across threads it
names the owning lock, the attributes that lock owns, the methods that
are only ever called with the lock already held, and — just as
important — the deliberately lock-free state, each entry with the reason
it is safe. An attribute touched outside its lock (and not documented
lock-free) fails `python -m tools.lint`; a documented entry that no
longer matches the code (renamed attribute, dropped lock) fails too, so
the map cannot rot.

Lock NAMES (the make_lock role strings) also feed the runtime
lock-order watchdog (language_detector_tpu/locks.py, LDT_LOCK_DEBUG=1):
the static map says who owns what, the watchdog proves at test time that
the cross-lock acquisition graph stays acyclic.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ClassLocks:
    # attribute holding the owning lock (None: class has no lock and
    # only documents lock-free state)
    lock: str | None = None
    # instance attributes that must only be touched under `with <lock>`
    attrs: frozenset = frozenset()
    # methods whose callers already hold the lock (private helpers of
    # locked sections); their bodies are treated as locked
    held_methods: frozenset = frozenset()
    # attribute -> reason it is intentionally lock-free; existence is
    # verified so stale documentation fails the lint
    lockfree: dict = dataclasses.field(default_factory=dict)
    # attribute -> owned class name: cross-object reads like
    # `self.ladder.level` are checked against the owned class's map
    aliases: dict = dataclasses.field(default_factory=dict)


def _cl(lock=None, attrs=(), held=(), lockfree=None, aliases=None):
    return ClassLocks(lock=lock, attrs=frozenset(attrs),
                      held_methods=frozenset(held),
                      lockfree=dict(lockfree or {}),
                      aliases=dict(aliases or {}))


# {repo-relative path: {class name: ClassLocks}}
LOCK_OWNERSHIP: dict = {
    "language_detector_tpu/telemetry.py": {
        "Histogram": _cl(
            lock="_lock",
            attrs=("counts", "sum", "count", "max")),
        "CompileTracker": _cl(lock="_lock", attrs=("_seen",)),
        "SlowTraceRing": _cl(
            lock="_lock", attrs=("_ring",),
            lockfree={
                "recorded": "monotonic int written only under _lock; "
                            "debug endpoints read it as a single "
                            "GIL-atomic load and tolerate staleness",
            }),
        "TelemetryRegistry": _cl(
            lock="_lock", attrs=("_hists", "_counters")),
    },
    "language_detector_tpu/flightrec.py": {
        "FlightRecorder": _cl(
            lock="_lock",
            attrs=("_seq", "_dropped"),
            lockfree={
                "mm": "mmap assigned once at init (before the recorder "
                      "is published via the module RECORDER binding); "
                      "emit() mutates it only under _lock, close() runs "
                      "after the owner stops emitting",
            }),
    },
    "language_detector_tpu/capture.py": {
        "CaptureWriter": _cl(
            lock="_lock",
            attrs=("_seq", "_segments", "_records_total",
                   "_sampled_out"),
            held=("_seal_locked", "_prune_locked"),
            lockfree={
                "mm": "mmap assigned once at init (before the writer "
                      "is published via the module WRITER binding); "
                      "append() mutates it only under _lock, close() "
                      "runs after the owner stops appending",
                "_rng": "sampling RNG touched only by append(), which "
                        "every caller reaches through the single "
                        "module-level observe() hot path; a racing "
                        "draw could only reorder samples, never "
                        "corrupt the ring (commit word publishes "
                        "records, not the RNG)",
            }),
    },
    "language_detector_tpu/slo.py": {
        "SloEngine": _cl(
            lock="_lock",
            attrs=("_fleet", "_tenants", "_alert", "_alert_since",
                   "_breaches", "_observed"),
            held=("_burns_locked", "_evaluate_locked",
                  "_window_view")),
    },
    "language_detector_tpu/service/admission.py": {
        "BrownoutLadder": _cl(lock="_lock", attrs=("ema", "level")),
        "CircuitBreaker": _cl(
            lock="_lock",
            attrs=("_state", "_consec", "_opened_at", "_probe_at",
                   "trips", "probes", "failures_total",
                   "stalls_total")),
        "AdmissionController": _cl(
            lock="_lock",
            attrs=("queue_docs", "queue_bytes", "inflight", "_shed",
                   "tenants"),
            held=("_occupancy", "_shed_out"),
            lockfree={
                "pool": "provider callable assigned at init / by "
                        "attach_pool during service build (before "
                        "traffic); the DevicePool it returns locks "
                        "its own lane state",
            },
            aliases={"ladder": "BrownoutLadder",
                     "breaker": "CircuitBreaker"}),
        # FairScheduler is deliberately lock-free by OWNERSHIP, not by
        # documentation per attribute: it is confined to the single
        # batcher collector (thread or task) that created it — push and
        # pop_batch never run concurrently
    },
    "language_detector_tpu/service/server.py": {
        "Metrics": _cl(
            lock="_lock",
            attrs=("counters", "objects", "languages"),
            lockfree={
                "engine_stats": "callable reference, assigned once at "
                                "service init before handler threads "
                                "exist; the callee locks its own state",
                "cache_stats": "callable reference, same single-"
                               "assignment-at-init contract",
                "admission_stats": "callable reference, same single-"
                                   "assignment-at-init contract",
                "readiness": "callable reference, same single-"
                             "assignment-at-init contract",
                "pool_stats": "callable reference, same single-"
                              "assignment-at-init contract",
                "pipeline_stats": "callable reference, same single-"
                                  "assignment-at-init contract",
                "shm_stats": "callable reference, rebound once by "
                             "ShmRingServer.start during service "
                             "build (before traffic); the callee "
                             "returns an immutable snapshot",
                "quarantine_stats": "callable reference, same "
                                    "ShmRingServer.start single-"
                                    "assignment contract; the callee "
                                    "locks its own state",
                "shared_cache_stats": "callable reference, same single-"
                                      "assignment-at-init contract; "
                                      "the callee locks its own state",
                "slo_stats": "callable reference (module-level "
                             "slo.stats), assigned once at init; the "
                             "engine locks its own windows",
                "capture_stats": "callable reference (module-level "
                                 "capture.stats), assigned once at "
                                 "init; the writer locks its own ring",
            }),
        "DetectorService": _cl(
            lock="_log_lock",
            attrs=("_num_processed", "_window_start",
                   "_inflight_http"),
            lockfree={
                "_frag_cache": "wire.FragmentCache (shared with the "
                               "aio front): the value for a key is a "
                               "pure function of the key, so a racing "
                               "double-compute stores the same bytes; "
                               "its internal dict get/set are "
                               "GIL-atomic",
                "_artifact_loaded": "bool written only during __init__ "
                                    "(before handler threads exist), "
                                    "read-only afterwards by "
                                    "readiness()",
                "_engine": "rebound atomically by swap_artifact under "
                           "_swap_lock; every reader (detect closure, "
                           "scalar fallback) takes ONE GIL-atomic "
                           "reference per call, so in-flight flushes "
                           "finish on the engine they captured",
                "_tables": "same swap contract as _engine: one rebind "
                           "under _swap_lock, one-reference-per-call "
                           "readers",
                "_artifact_path": "str rebound under _swap_lock; "
                                  "readers tolerate either value",
                "_swap_count": "int written only under _swap_lock; "
                               "read as a single GIL-atomic load by "
                               "stats surfaces",
                "_warmed": "bool flips False->True exactly once by the "
                           "warmup thread; readiness readers tolerate "
                           "a stale False (fail-closed)",
                "_warmup_ms": "float written once by the warmup "
                              "thread before _warmed flips; readers "
                              "see it only after the flip",
            }),
    },
    "language_detector_tpu/service/wire.py": {
        "UnixFrameServer": _cl(
            lock="_lock",
            attrs=("_conns", "_inflight", "_closing"),
            lockfree={
                "_sock": "listening socket assigned by start() before "
                         "the accept thread exists; close() racing "
                         "accept() IS the shutdown signal (accept "
                         "raises OSError and the thread exits)",
                "_detect": "callable assigned once at init, read-only",
            }),
    },
    "language_detector_tpu/parallel/pool.py": {
        "DevicePool": _cl(
            lock="_lock",
            attrs=("_rr",),
            lockfree={
                "lanes": "list assigned once at init and never "
                         "rebound; each Lane locks its own health "
                         "state",
                "lane_mesh_size": "int assigned once at init, "
                                  "read-only afterwards",
                "hedge_factor": "config scalar, init-assigned "
                                "read-only",
                "hedge_min_ms": "config scalar, init-assigned "
                                "read-only",
                "evict_failures": "config scalar, init-assigned "
                                  "read-only",
                "probe_cooldown_sec": "config scalar, init-assigned "
                                      "read-only",
                "max_redispatch": "config scalar, init-assigned "
                                  "read-only",
                "_exec": "ThreadPoolExecutor locks itself; submit is "
                         "thread-safe",
                "_now": "clock callable, init-assigned read-only",
            }),
        "Lane": _cl(
            lock="_lock",
            attrs=("_state", "_ewma_ms", "_samples", "_sample_pos",
                   "_consecutive", "_dispatches", "_failures",
                   "_inflight", "_last_completion", "_evicted_at"),
            lockfree={
                "idx": "int assigned once at init, read-only",
                "name": "str assigned once at init, read-only",
                "score_fn": "jitted callable, init-assigned read-only "
                            "(jax jit dispatch is thread-safe)",
                "mesh": "Mesh reference, init-assigned read-only",
            }),
    },
    "language_detector_tpu/aot.py": {
        "AotStore": _cl(
            lock="_lock",
            attrs=("_entries", "_exported", "loads", "exports",
                   "refusals"),
            lockfree={
                "dir": "str assigned once at init, read-only",
                "digest": "str assigned once at init, read-only",
                "backend": "str assigned once at init, read-only",
                "kernel_mode": "str assigned once at init, read-only",
                "require": "bool assigned once at init, read-only",
            }),
    },
    "language_detector_tpu/service/batcher.py": {
        "ResultCache": _cl(
            lock="_lock",
            attrs=("_d", "bytes", "hits", "misses", "_pending"),
            lockfree={
                "_shared": "SharedResultCache reference assigned once "
                           "at init; the shared table is lock-free by "
                           "protocol (seqlock slots) and its stats "
                           "take their own lock",
            }),
    },
    "language_detector_tpu/service/sharedcache.py": {
        "SharedResultCache": _cl(
            lock="_lock",
            attrs=("hits", "misses", "evictions", "epoch_flushes"),
            lockfree={
                "_mm": "mmap assigned once at init; slot access is "
                       "coordinated by the seqlock protocol, not a "
                       "process lock (cross-process sharing is the "
                       "point)",
                "path": "str assigned once at init, read-only",
                "slot_count": "int assigned once at init, read-only",
                "_epoch_word": "u64 rebound only by set_epoch (the "
                               "swap path, serialized by the service "
                               "swap lock); readers take ONE "
                               "GIL-atomic load and either epoch's "
                               "view is self-consistent",
            }),
    },
    "language_detector_tpu/service/fleet.py": {
        "FleetStatus": _cl(lock="_lock", attrs=("_snap",)),
        # FleetMember and FleetControl are deliberately lock-free by
        # OWNERSHIP: every field is confined to the fleet main loop;
        # the status thread only reads the immutable snapshot dicts
        # FleetStatus republishes under its lock
    },
    "language_detector_tpu/service/shmring.py": {
        "Quarantine": _cl(
            lock="_lock",
            attrs=("_docs", "total", "bisects")),
        "ShmRingServer": _cl(
            lock="_stat_lock",
            attrs=("_frames",),
            lockfree={
                "_snap": "immutable snapshot dict rebuilt and rebound "
                         "by the scan thread each sweep; stats() "
                         "readers take ONE GIL-atomic reference and "
                         "never mutate it",
            }),
        # RingSlot / RingFile / _WorkerRing / RingClient are
        # deliberately lock-free by OWNERSHIP: SPSC contract — the
        # mirrors and ring map are confined to the scan thread (which
        # also serves every leased frame inline, publish-order header
        # write with the state word last), the client objects to their
        # caller. Cross-process coordination happens through the
        # mmap'd slot headers.
    },
    "language_detector_tpu/service/aioserver.py": {
        # the asyncio front deliberately holds no locks: every mutation
        # below happens on the one event loop (or before it starts)
        "AioService": _cl(lockfree={
            "_writers": "event-loop confined: mutated only from handler "
                        "coroutines and the recycle watcher, all on the "
                        "same loop",
            "_busy": "event-loop confined, same as _writers",
            "recycling": "bool flag set by the recycle watcher and read "
                         "by serve(), both on the event loop",
            "draining": "bool flag set by the SIGTERM handler (runs on "
                        "the loop via add_signal_handler) and read by "
                        "serve(), both on the event loop",
        }),
        "AioBatcher": _cl(lockfree={
            "_cache": "ResultCache locks itself; flush workers and the "
                      "collector share it through its own lock",
        }),
    },
}
