"""Native (C++) host runtime: the batch packer.

Loads libldtpack.so (built on demand from packer.cc) and exposes
`pack_batch_native`, an array-for-array drop-in for the Python
preprocess.pack.pack_batch (tests/test_native_pack.py asserts equality).
Falls back gracefully: `available()` is False when no compiler/library
exists and callers keep using the Python packer.
"""
from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np

from ..registry import Registry, ULSCRIPT_LATIN
from ..tables import ScoringTables
from ..preprocess.pack import PackedBatch

_DIR = Path(__file__).parent
_SO = _DIR / "libldtpack.so"

_lib = None
_init_keepalive: list = []


def _build() -> bool:
    try:
        subprocess.run([str(_DIR / "build.sh")], check=True,
                       capture_output=True, timeout=120)
        return _SO.exists()
    except Exception:
        return False


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not _SO.exists() and not _build():
        _lib = False
        return _lib
    lib = ctypes.CDLL(str(_SO))
    lib.ldt_init.restype = None
    lib.ldt_pack_batch.restype = None
    _lib = lib
    return _lib


def available() -> bool:
    return bool(_load())


_initialized_for: tuple = ()


def _ptr(a: np.ndarray, dtype):
    assert a.dtype == dtype and a.flags.c_contiguous
    return a.ctypes.data_as(ctypes.c_void_p)


def _ensure_init(tables: ScoringTables, reg: Registry):
    """Upload table pointers once per (tables, registry) pair."""
    global _initialized_for
    key = (id(tables), id(reg))
    if _initialized_for == key:
        return
    lib = _load()
    seed_lp = np.zeros(reg.num_scripts, np.uint32)
    for s in range(reg.num_scripts):
        lang = reg.default_language(s)
        seed_lp[s] = np.uint32(
            reg.per_script_number(ULSCRIPT_LATIN, lang) << 8)
    rtype = np.ascontiguousarray(reg.ulscript_rtype.astype(np.int32))
    deflang = np.ascontiguousarray(
        reg.ulscript_default_lang.astype(np.int32))
    script_of = np.ascontiguousarray(tables.script_of_cp, dtype=np.uint8)
    lower = np.arange(0x110000, dtype=np.uint32)
    lower[tables.lower_pairs[:, 0]] = tables.lower_pairs[:, 1]
    cjk_prop = np.ascontiguousarray(tables.cjk_uni_prop, dtype=np.uint8)
    _init_keepalive.clear()
    _init_keepalive.extend([seed_lp, rtype, deflang, script_of, lower,
                            cjk_prop])
    lib.ldt_init(
        _ptr(script_of, np.uint8), _ptr(lower, np.uint32),
        _ptr(cjk_prop, np.uint8), _ptr(rtype, np.int32),
        _ptr(deflang, np.int32), _ptr(seed_lp, np.uint32),
        ctypes.c_int32(reg.num_scripts),
        ctypes.c_int32(1 if tables.distinctbi.empty else 0))
    _initialized_for = key


def pack_batch_native(texts: list[str], tables: ScoringTables,
                      reg: Registry, max_slots: int = 2048,
                      max_chunks: int = 64, max_direct: int = 4,
                      flags: int = 0, n_threads: int = 0) -> PackedBatch:
    """Native twin of preprocess.pack.pack_batch (same output contract)."""
    lib = _load()
    if not lib:
        raise RuntimeError("native packer unavailable")
    _ensure_init(tables, reg)

    B, L, C, D = len(texts), max_slots, max_chunks, max_direct
    enc = [t.encode("utf-8", errors="surrogatepass") for t in texts]
    bounds = np.zeros(B + 1, np.int64)
    np.cumsum([len(e) for e in enc], out=bounds[1:])
    blob = np.frombuffer(b"".join(enc), dtype=np.uint8) if bounds[-1] \
        else np.zeros(1, np.uint8)
    blob = np.ascontiguousarray(blob)

    out = PackedBatch(
        kind=np.zeros((B, L), np.int8),
        offset=np.zeros((B, L), np.int32),
        fp=np.zeros((B, L), np.uint32),
        fp_hi=np.zeros((B, L), np.uint8),
        chunk_base=np.zeros((B, L), np.int32),
        span_start=np.zeros((B, L), np.int32),
        span_end_off=np.zeros((B, L), np.int32),
        side=np.zeros((B, L), np.int8),
        cjk=np.zeros((B, L), np.int8),
        script=np.zeros((B, L), np.int16),
        chunk_script=np.zeros((B, C), np.int16),
        chunk_cjk=np.zeros((B, C), np.int8),
        chunk_side=np.zeros((B, C), np.int8),
        chunk_span_end=np.zeros((B, C), np.int32),
        direct_adds=np.full((B, D, 3), -1, np.int32),
        text_bytes=np.zeros(B, np.int32),
        fallback=np.zeros(B, bool),
        n_slots=np.zeros(B, np.int32),
        n_chunks=np.zeros(B, np.int32),
        n_docs=B,
    )
    if n_threads <= 0:
        import os
        n_threads = min(8, os.cpu_count() or 1)
    lib.ldt_pack_batch(
        _ptr(blob, np.uint8), _ptr(bounds, np.int64),
        ctypes.c_int32(B), ctypes.c_int32(L), ctypes.c_int32(C),
        ctypes.c_int32(D), ctypes.c_int32(flags),
        ctypes.c_int32(n_threads),
        _ptr(out.kind, np.int8), _ptr(out.offset, np.int32),
        _ptr(out.fp, np.uint32), _ptr(out.fp_hi, np.uint8),
        _ptr(out.chunk_base, np.int32), _ptr(out.span_start, np.int32),
        _ptr(out.span_end_off, np.int32), _ptr(out.side, np.int8),
        _ptr(out.cjk, np.int8), _ptr(out.script, np.int16),
        _ptr(out.chunk_script, np.int16), _ptr(out.chunk_cjk, np.int8),
        _ptr(out.chunk_side, np.int8), _ptr(out.chunk_span_end, np.int32),
        out.direct_adds.ctypes.data_as(ctypes.c_void_p),
        _ptr(out.text_bytes, np.int32),
        out.fallback.ctypes.data_as(ctypes.c_void_p),
        _ptr(out.n_slots, np.int32), _ptr(out.n_chunks, np.int32))
    return out
