"""Fixture: knob-registry violations — direct env reads, an accessor
naming an undeclared knob, and import-time caching of mutable knobs."""
import os
from os import environ

# mutable knob read at import: frozen before any /configz push lands
_CACHED_INFLIGHT = knobs.get_int("LDT_MAX_INFLIGHT")


def f():
    a = os.environ.get("LDT_X")             # direct env access
    b = os.getenv("LDT_Y")                  # direct env access
    c = knobs.get_int("LDT_NOT_DECLARED")   # undeclared knob
    return a, b, c, environ


def g(limit=knobs.get_int("LDT_MAX_QUEUE_DOCS")):  # default = def time
    return limit
