"""Shared wire fast path for both HTTP fronts + the unix-socket lane.

The engine sustains ~112k docs/sec but the fronts burned their budget
on per-request ``json.loads``/``json.dumps`` of whole batch bodies.
This module takes the host off the request path three ways:

  1. ``fast_parse_texts``: a single-pass scanner that recognizes the
     strict common request shape ``{"request": [{"text": <string>},
     ...]}`` and slices each text straight out of the request bytes
     (one str decode per doc, zero intermediate dicts/lists-of-dicts).
     ANY deviation — extra keys, non-string values, escapes that need
     exact JSON semantics, truncation, trailing bytes — bails to the
     ``json.loads`` path, so the contract (parse result, 400s, metric
     increments) is byte-identical by construction.
  2. ``post_detect``/``assemble_response``: batch responses assembled
     as a writev-style buffer list over per-code fragments cached in a
     ``FragmentCache`` (previously private to the sync front), so
     neither front builds an O(body) concatenation.
  3. A length-prefixed unix-domain-socket frame protocol
     (``LDT_UNIX_SOCKET``) for co-located callers that skips HTTP
     parsing entirely; the threaded front's ``UnixFrameServer`` lives
     here and reuses one grow-only receive buffer per connection.

Both fronts (service/server.py, service/aioserver.py) import the
contract pieces from here; server.py re-exports the moved names for
backward compatibility.
"""
from __future__ import annotations

import json
import re
import socket
import struct
import threading
import time
import zlib
from concurrent.futures import TimeoutError as FuturesTimeout

import numpy as np

from .. import faults, knobs, telemetry
from ..locks import make_lock
from .admission import DeadlineExceeded

BODY_LIMIT_BYTES = 1_000_000            # main.go:59

# single source of the contract's error payloads (both fronts + UDS)
CT_ERROR_BODY = json.dumps(
    {"error": "Content-Type must be set to application/json"}).encode()
PARSE_ERROR_BODY = json.dumps(
    {"error": "Unable to parse request - invalid JSON detected"}).encode()
OVERSIZE_BODY = json.dumps(
    {"error": "Request body exceeds 1MB limit"}).encode()
TIMEOUT_BODY = json.dumps(
    {"error": "Frame read timed out "
              "(LDT_FRAME_READ_TIMEOUT_SEC)"}).encode()
CRC_ERROR_BODY = json.dumps(
    {"error": "Frame body failed CRC32 integrity check"}).encode()
_MISSING_TEXT_FRAG = b'{"error": "Missing text key"}'

RESP_OPEN = b'{"response": ['
RESP_SEP = b", "
RESP_CLOSE = b']}'

_WS = b" \t\n\r"                        # JSON whitespace, exactly
# a raw byte < 0x20 inside a string literal is invalid JSON; the fast
# path must 400 it (via fallback), not decode it
_CTRL_RE = re.compile(rb"[\x00-\x1f]")


def strip_extras(text: str) -> str:
    """Remove @mentions and links, which skew detection
    (StripExtras, handlers.go:198-210; note the trailing space the
    word-join loop leaves behind). Texts without '@' or 'http' pass
    through untouched: the split/join also collapses whitespace, but
    the engine maps every non-letter run to one space during
    segmentation, so detection output is identical — and the scan-only
    fast path saves ~6us/doc of the single core."""
    if "@" not in text and "http" not in text:
        return text
    kept = [w for w in text.split()
            if not (w.startswith("@") or w.startswith("http"))]
    return "".join(w + " " for w in kept)


# -- request side -----------------------------------------------------------


def parse_post_body(m, content_type: str | None, body: bytes):
    """Content-Type + JSON validation (GetRequests, handlers.go:33-69).
    Returns (doc, None) on success or (None, (status, payload_bytes))
    for the error response — single source of the contract's error
    strings and metric increments for both servers."""
    if content_type != "application/json":
        m.inc("augmentation_invalid_requests_total")
        m.inc("augmentation_errors_logged_total")
        m.inc_object("unsuccessful")
        return None, (400, CT_ERROR_BODY)
    try:
        return json.loads(body), None
    except json.JSONDecodeError:
        m.inc("augmentation_invalid_requests_total")
        m.inc("augmentation_errors_logged_total")
        m.inc_object("unsuccessful")
        return None, (400, PARSE_ERROR_BODY)


def pre_detect(svc, doc):
    """Parsed request body -> (texts, slots, responses, status), or None
    when the body is not the {"request": [...]} shape (caller answers
    400). Per-item "Missing text key" errors keep the batch going with
    overall HTTP 400 (handlers.go:133-150)."""
    m = svc.metrics
    if not isinstance(doc, dict) or "request" not in doc:
        m.inc("augmentation_invalid_requests_total")
        return None
    requests = doc["request"]
    if not isinstance(requests, list):
        requests = []
    status = 200
    responses: list = []
    texts: list = []
    slots: list = []
    # fast path: every item is a {"text": ...} dict (the overwhelmingly
    # common shape) — one comprehension instead of a per-item branch loop
    try:
        texts = [strip_extras(str(item["text"])) for item in requests]
    except (TypeError, KeyError):
        pass
    else:
        return texts, range(len(texts)), [None] * len(texts), status
    texts = []
    for i, item in enumerate(requests):
        if not isinstance(item, dict) or "text" not in item:
            m.inc_object("unsuccessful")
            responses.append(_MISSING_TEXT_FRAG)
            status = 400
            continue
        texts.append(strip_extras(str(item["text"])))
        slots.append(i)
        responses.append(None)
    return texts, slots, responses, status


def _skip_ws(b, i: int, n: int) -> int:
    while i < n and b[i] in _WS:
        i += 1
    return i


def fast_parse_texts(body, n: int | None = None):
    """Zero-copy scan of the strict common shape
    ``{"request": [{"text": <string>}, ...]}`` -> list of raw text
    strings, or None to fall back to ``json.loads``.

    ``body`` is bytes, a (reused) bytearray, or an mmap (shm ring
    slots); ``n`` bounds the scan so a UDS or shm frame can parse in
    place inside a larger buffer. Strings
    without a backslash decode straight off a memoryview slice (after
    rejecting raw control bytes, which json would 400); strings WITH a
    backslash hand just the quoted token to ``json.loads`` for exact
    escape / surrogate-pair semantics — this keeps ensure_ascii bodies
    (every non-ASCII char \\uXXXX-escaped) on the fast path. Anything
    else — duplicate/extra keys, non-string values, truncation,
    trailing bytes, undecodable UTF-8 — returns None, and the fallback
    reproduces today's behavior exactly."""
    if n is None:
        n = len(body)
    mv = memoryview(body)
    i = _skip_ws(body, 0, n)
    if i >= n or body[i] != 0x7B:                       # {
        return None
    i = _skip_ws(body, i + 1, n)
    # slice-compare instead of startswith: mmap objects (the shm ring
    # lane parses frames in place off the shared mapping) have no
    # startswith, and the bound check keeps the scan inside n
    if i + 9 > n or mv[i:i + 9] != b'"request"':
        return None
    i = _skip_ws(body, i + 9, n)
    if i >= n or body[i] != 0x3A:                       # :
        return None
    i = _skip_ws(body, i + 1, n)
    if i >= n or body[i] != 0x5B:                       # [
        return None
    i = _skip_ws(body, i + 1, n)
    texts: list = []
    if i < n and body[i] == 0x5D:                       # ] (empty list)
        i += 1
    else:
        while True:
            if i >= n or body[i] != 0x7B:               # {
                return None
            i = _skip_ws(body, i + 1, n)
            if i + 6 > n or mv[i:i + 6] != b'"text"':
                return None
            i = _skip_ws(body, i + 6, n)
            if i >= n or body[i] != 0x3A:               # :
                return None
            i = _skip_ws(body, i + 1, n)
            if i >= n or body[i] != 0x22:               # opening "
                return None
            start = i + 1
            # find the closing quote: a quote preceded by an even run
            # of backslashes
            j = body.find(b'"', start, n)
            while j != -1:
                k = j - 1
                while k >= start and body[k] == 0x5C:
                    k -= 1
                if (j - k) % 2 == 1:
                    break
                j = body.find(b'"', j + 1, n)
            if j == -1:
                return None
            if body.find(b"\\", start, j) != -1:
                try:
                    s = json.loads(bytes(mv[i:j + 1]))
                except (ValueError, UnicodeDecodeError):
                    return None
            else:
                if _CTRL_RE.search(body, start, j):
                    return None
                try:
                    s = str(mv[start:j], "utf-8")
                except UnicodeDecodeError:
                    return None
            texts.append(s)
            i = _skip_ws(body, j + 1, n)
            if i >= n or body[i] != 0x7D:               # }
                return None
            i = _skip_ws(body, i + 1, n)
            if i < n and body[i] == 0x2C:               # ,
                i = _skip_ws(body, i + 1, n)
                continue
            if i < n and body[i] == 0x5D:               # ]
                i += 1
                break
            return None
    i = _skip_ws(body, i, n)
    if i >= n or body[i] != 0x7D:                       # }
        return None
    i = _skip_ws(body, i + 1, n)
    if i != n:                                          # trailing bytes
        return None
    return texts


def parse_request(svc, content_type: str | None, body, nbytes=None):
    """Single request-parsing entry point for every lane (sync front,
    asyncio front, UDS). Returns (pre, err), exactly one non-None:

        pre = (texts, slots, responses, status)   — pre_detect shape
        err = (status, payload_bytes)             — ready to send

    The fast scanner handles the strict common shape; any deviation
    falls back to the json.loads path, so responses, status codes and
    metric increments match the pre-wire fronts byte for byte."""
    m = svc.metrics
    reg = telemetry.REGISTRY
    t0 = time.monotonic()
    try:
        if content_type != "application/json":
            m.inc("augmentation_invalid_requests_total")
            m.inc("augmentation_errors_logged_total")
            m.inc_object("unsuccessful")
            return None, (400, CT_ERROR_BODY)
        if knobs.get_bool("LDT_WIRE_FASTPATH"):
            texts = fast_parse_texts(body, nbytes)
            if texts is not None:
                reg.counter_inc("ldt_http_parse_fast_total",
                                result="hit")
                texts = [strip_extras(t) for t in texts]
                return (texts, range(len(texts)),
                        [None] * len(texts), 200), None
            reg.counter_inc("ldt_http_parse_fast_total", result="miss")
        raw = body if nbytes is None else bytes(memoryview(body)[:nbytes])
        doc, err = parse_post_body(m, content_type, raw)
        if err is not None:
            return None, err
        pre = pre_detect(svc, doc)
        if pre is None:
            m.inc("augmentation_errors_logged_total")
            return None, (400, PARSE_ERROR_BODY)
        return pre, None
    finally:
        reg.histogram("ldt_http_parse_ms").observe(
            (time.monotonic() - t0) * 1e3)


# -- response side ----------------------------------------------------------


class FragmentCache:
    """Per-code pre-serialized ``{"iso6391code": ..., "name": ...}``
    fragments, shared by both fronts (previously a private dict on the
    sync front). The value for a key is a pure function of the key, so
    a racing double-compute stores the same bytes; dict get/set are
    GIL-atomic — no lock (see tools/lint/ownership.py)."""

    __slots__ = ("_frags", "_known")

    def __init__(self, known: dict):
        self._frags: dict = {}
        self._known = known

    def entry(self, code: str):
        """code -> (fragment_bytes, display_name, unknown?)."""
        ent = self._frags.get(code)
        if ent is None:
            name = self._known.get(code)
            unknown = name is None
            if unknown:
                name = "Unknown"
            ent = (json.dumps({"iso6391code": code,
                               "name": name}).encode(), name, unknown)
            self._frags[code] = ent
        return ent


def assemble_response(fragments) -> list:
    """Per-item fragments -> writev-style buffer list for the batch
    envelope. ``b"".join(result)`` is byte-identical to the old single
    concatenated payload, but the list lets both fronts emit via
    writelines/sendmsg without building an O(body) copy."""
    out = [RESP_OPEN]
    append = out.append
    first = True
    for frag in fragments:
        if first:
            first = False
        else:
            append(RESP_SEP)
        append(frag)
    append(RESP_CLOSE)
    return out


def post_detect(svc, codes: list, slots, responses: list, status: int,
                spans: list | None = None):
    """Detected codes -> (status, writev-style buffer list) + metrics.
    Unknown code answers name "Unknown" with HTTP 203
    (handlers.go:151-166). The buffers concatenate to bytes identical
    to the json.dumps they replace (fragments are built BY json.dumps,
    once per distinct code). spans (LDT_SPANS requests only): per-item
    span record lists, spliced into each cached fragment as a "spans"
    key — the span lane is low-volume, so the per-item dumps is off
    the main path and span-less responses stay byte-identical."""
    m = svc.metrics
    t0 = time.monotonic()
    lang_counts: dict = {}
    entry = svc._frag_cache.entry
    for pos, (i, code) in enumerate(zip(slots, codes)):
        frag, name, unknown = entry(code)
        if unknown and status == 200:
            status = 203
        if spans is not None:
            frag = (frag[:-1] + b', "spans": ' +
                    json.dumps([list(s) for s in spans[pos] or []],
                               separators=(",", ":")).encode() + b"}")
        responses[i] = frag
        lang_counts[name] = lang_counts.get(name, 0) + 1
    if codes:
        m.add_languages(lang_counts)
        m.inc_object("successful", len(codes))
        svc.log_processed(len(codes))
    buffers = assemble_response(responses)
    telemetry.REGISTRY.histogram("ldt_http_serialize_ms").observe(
        (time.monotonic() - t0) * 1e3)
    return status, buffers


# -- unix-domain-socket lane ------------------------------------------------
#
# Frame contract (both fronts):
#     v1 request  = !I  body_len        | body (same JSON as POST /)
#     v2 request  = !I  (V2|body_len)   | !BHI flags tenant_len deadline_ms
#                   | tenant (latin-1)  | body
#     response    = !IH body_len status | body
# The v2 bit lives in the length word's MSB — the body contract caps
# body_len at 1 MB, so no v1 client can ever emit it, which makes v1
# frames byte-compatible on a v2 server. The ext header carries what
# the HTTP front reads from X-LDT-Tenant / X-LDT-Priority /
# X-LDT-Deadline-Ms: flags bit0 = priority, tenant_len sizes the
# tenant id that follows (0 = default tenant), deadline_ms is the
# request budget (0 = absent, server default applies). The response
# body is byte-identical to the TCP front's HTTP payload for the same
# batch — pinned by tests and the ci wire smoke.

FRAME_HEADER = struct.Struct("!I")
FRAME_RESP_HEADER = struct.Struct("!IH")
FRAME_V2_FLAG = 0x80000000
FRAME_EXT_HEADER = struct.Struct("!BHI")   # flags, tenant_len, deadline_ms
FRAME_PRIORITY = 0x01                      # flags bit0
FRAME_REQID = 0x02                         # flags bit1: 1-byte id length
#                                            + id bytes follow the tenant
FRAME_CRC = 0x04                           # flags bit2: u32 crc32(body)
#                                            follows the reqid bytes
FRAME_SPANS = 0x08                         # flags bit3: request per-span
#                                            verdicts (LDT_SPANS=1 server
#                                            side; ignored when off, so
#                                            responses stay byte-identical)
FRAME_CRC_WORD = struct.Struct("!I")

# pinned v1/v2 wire widths: a drive-by field edit must fail at import,
# not desync every deployed client mid-stream
# (tools/lint/layout_registry.py declares the same widths)
assert FRAME_HEADER.size == 4
assert FRAME_RESP_HEADER.size == 6
assert FRAME_EXT_HEADER.size == 7
assert FRAME_CRC_WORD.size == 4

REQUEST_ID_HEADER = "X-LDT-Request-Id"
_REQID_RE = re.compile(r"[A-Za-z0-9._\-]{1,64}\Z")


def gen_request_id() -> str:
    """Server-generated correlation id for a request that arrived
    without one: 8 hex chars, the same shape a shm slot's u32 carrier
    renders to, so every lane's ids look alike in /tracez."""
    import os
    return os.urandom(4).hex()


def clean_request_id(raw) -> str | None:
    """Validate a caller-supplied correlation id (header or frame
    field): 1-64 chars of [A-Za-z0-9._-], else rejected to None so a
    hostile id can't smuggle header/JSON syntax back out through the
    echo."""
    if not raw:
        return None
    if isinstance(raw, bytes):
        try:
            raw = raw.decode("ascii")
        except UnicodeDecodeError:
            return None
    return raw if _REQID_RE.match(raw) else None


def pack_frame(body: bytes, tenant: str | None = None,
               deadline_ms: int | None = None,
               priority: bool = False,
               request_id: str | None = None,
               crc: bool | None = None,
               spans: bool = False) -> bytes:
    """Client-side frame builder. With no admission fields set this
    emits a plain v1 frame, so existing callers (and the parity tests'
    baseline) are untouched; any field promotes the frame to v2. A
    request_id rides as flags bit1 + 1-byte length + id bytes after
    the tenant, and the server echoes it on the response frame. crc
    (default: the LDT_WIRE_CRC knob) appends a u32 crc32(body) guard
    word after the reqid bytes; the server refuses a frame whose body
    arrives not matching it with a 400 instead of parsing garbage."""
    if crc is None:
        crc = bool(knobs.get_bool("LDT_WIRE_CRC"))
    if tenant is None and deadline_ms is None and not priority \
            and request_id is None and not crc and not spans:
        return FRAME_HEADER.pack(len(body)) + body
    tb = (tenant or "").encode("latin-1")
    flags = FRAME_PRIORITY if priority else 0
    if spans:
        flags |= FRAME_SPANS
    rb = b""
    if request_id is not None:
        rb = request_id.encode("ascii")
        if len(rb) > 255:
            raise ValueError("request_id exceeds 255 bytes")
        flags |= FRAME_REQID
        rb = bytes([len(rb)]) + rb
    cb = b""
    if crc:
        flags |= FRAME_CRC
        cb = FRAME_CRC_WORD.pack(zlib.crc32(body))
    ext = FRAME_EXT_HEADER.pack(flags, len(tb),
                                min(deadline_ms or 0, 0xFFFFFFFF))
    return FRAME_HEADER.pack(FRAME_V2_FLAG | len(body)) \
        + ext + tb + rb + cb + body

_IOV_BATCH = 512  # sendmsg segments per call, safely under IOV_MAX


def send_frame(sock, status: int, buffers: list,
               request_id: str | None = None) -> None:
    """Emit one response frame writev-style: header + fragment buffers
    go to sendmsg as-is (no join); a short write re-enters with the
    remaining tail. A request_id (echoed only when the CLIENT supplied
    one, so v1 responses stay byte-identical) sets the length word's
    MSB and prefixes the body with 1-byte id length + id bytes."""
    total = 0
    for b in buffers:
        total += len(b)
    if request_id is not None:
        rb = request_id.encode("ascii")
        iov = [FRAME_RESP_HEADER.pack(FRAME_V2_FLAG | total, status),
               bytes([len(rb)]) + rb]
    else:
        iov = [FRAME_RESP_HEADER.pack(total, status)]
    iov += buffers
    i = 0
    while i < len(iov):
        chunk = iov[i:i + _IOV_BATCH]
        try:
            sent = sock.sendmsg(chunk)
        except AttributeError:      # platform without sendmsg
            sock.sendall(b"".join(iov[i:]))
            return
        for b in chunk:
            blen = len(b)
            if sent >= blen:
                sent -= blen
                i += 1
            else:
                iov[i] = memoryview(b)[sent:]
                break


def recv_response_frame(sock):
    """Client-side response reader -> (status, request_id, body).
    Understands both the plain response header and the id-echo form
    (MSB of the length word set, 1-byte id length + id before the
    body)."""
    hdr = bytearray(FRAME_RESP_HEADER.size)
    if not _recv_exact_into(sock, memoryview(hdr), len(hdr)):
        raise ConnectionError("EOF reading response frame header")
    length, status = FRAME_RESP_HEADER.unpack(hdr)
    request_id = None
    if length & FRAME_V2_FLAG:
        length &= ~FRAME_V2_FLAG
        one = bytearray(1)
        if not _recv_exact_into(sock, memoryview(one), 1):
            raise ConnectionError("EOF reading response id length")
        rb = bytearray(one[0])
        if rb and not _recv_exact_into(sock, memoryview(rb), len(rb)):
            raise ConnectionError("EOF reading response id")
        request_id = rb.decode("ascii")
    body = bytearray(length)
    if length and not _recv_exact_into(sock, memoryview(body), length):
        raise ConnectionError("EOF reading response body")
    return status, request_id, bytes(body)


def _recv_exact_into(sock, view, n: int) -> bool:
    """Fill exactly n bytes of view from sock; False on EOF mid-read."""
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            return False
        got += r
    return True


def handle_frame(svc, body, detect=None, nbytes=None, lane="uds",
                 tenant=None, deadline_ms=None, priority=False,
                 request_id=None, want_spans=False):
    """One UDS request body through the shared wire path ->
    (status, buffer list). Mirrors the HTTP fronts' POST flow
    (admission, degrade ladder, typed errors) minus header parsing;
    tenant/deadline_ms/priority/request_id come from a v2 frame's ext
    header and feed the same per-tenant quota, deadline, brownout, and
    correlation decisions as the HTTP headers they mirror. The
    concatenated buffers are identical to the TCP payload for the same
    batch. want_spans (FRAME_SPANS ext flag) asks for per-span
    verdicts; honored only when LDT_SPANS=1 AND the request is not on
    a degrade path (spans drop to plain codes under brownout), so a
    span-less server answers byte-identical v1/v2 responses."""
    from .. import flightrec
    m = svc.metrics
    m.inc("augmentation_requests_total")
    telemetry.REGISTRY.counter_inc("ldt_http_requests_total", lane=lane)
    trace = telemetry.Trace()
    # correlate even id-less callers: the recorder/trace id is server-
    # generated then, just never echoed on the wire (v1 byte-compat)
    trace.request_id = request_id or gen_request_id()
    flightrec.emit_event("request_start", request_id=trace.request_id,
                         lane=lane)
    t = trace.t0
    if detect is None:
        detect = svc.detect_codes
    # completion-meta base shared by every finish_request exit: the
    # capture plane records request shape (bytes -> size bucket,
    # priority flag) alongside the outcome
    base = {"front": lane,
            "bytes": int(nbytes) if nbytes is not None else len(body),
            "priority": bool(priority)}
    pre, err = parse_request(svc, "application/json", body, nbytes=nbytes)
    if err is not None:
        telemetry.finish_request(trace, meta=dict(base, status=err[0]))
        return err[0], [err[1]]
    t = telemetry.observe_stage("parse", t, trace=trace)
    texts, slots, responses, status = pre
    adm = svc.admission
    admit = None
    if texts:
        admit = adm.try_admit(texts, priority=priority, tenant=tenant)
        # tenant before the shed branch: sheds must carry the
        # throttled tenant's identity into SLO/capture
        trace.tenant = admit.tenant
        if admit.shed:
            m.inc("augmentation_errors_logged_total")
            telemetry.finish_request(
                trace, meta=dict(base, docs=len(texts),
                                 status=admit.status,
                                 shed=admit.reason))
            return admit.status, [json.dumps(
                {"error": admit.message}).encode()]
        trace.deadline = adm.deadline_from_header(deadline_ms)
        if admit.level >= 1 and not admit.probe:
            trace.no_retry = True
    spans_list = None
    try:
        if admit is not None and admit.degrade:
            codes = svc.detect_codes_degraded(texts, trace=trace)
        elif want_spans and knobs.get_bool("LDT_SPANS"):
            pairs = svc.detect_spans_codes(texts, trace=trace) \
                if texts else []
            codes = [c for c, _ in pairs]
            spans_list = [s for _, s in pairs]
        else:
            codes = detect(texts, trace=trace) if texts else []
    except DeadlineExceeded:
        m.inc("augmentation_errors_logged_total")
        telemetry.finish_request(
            trace, meta=dict(base, docs=len(texts), status=504))
        return 504, [b'{"error":"deadline expired before dispatch"}']
    except (TimeoutError, FuturesTimeout):
        m.inc("augmentation_errors_logged_total")
        telemetry.finish_request(
            trace, meta=dict(base, docs=len(texts), status=504,
                             timeout="flush"))
        return 504, [b'{"error":"detection timed out"}']
    except Exception as e:  # noqa: BLE001 — typed 500, never a cut frame
        print(json.dumps({"msg": "detect failed",
                          "error": repr(e)}), flush=True)
        m.inc("augmentation_errors_logged_total")
        telemetry.finish_request(
            trace, meta=dict(base, docs=len(texts), status=500))
        return 500, [b'{"error":"internal error"}']
    finally:
        if admit is not None:
            adm.release(admit)
    t = telemetry.observe_stage("detect", t, trace=trace)
    status, buffers = post_detect(svc, codes, slots, responses, status,
                                  spans=spans_list)
    telemetry.observe_stage("encode", t, trace=trace)
    telemetry.finish_request(
        trace, meta=dict(base, docs=len(texts), status=status))
    return status, buffers


class UnixFrameServer:
    """Length-prefixed unix-domain-socket ingest lane for the threaded
    front (LDT_UNIX_SOCKET). One daemon accept thread, one daemon
    thread per connection; each connection reuses a grow-only receive
    buffer, so steady-state ingest allocates nothing per frame. A
    frame declaring more than the 1 MB body contract answers a 413
    frame and closes (length-prefix streams cannot resync). close()
    stops accepting, waits for in-flight frames up to drain_sec (the
    SIGTERM drain contract), then closes lingering connections."""

    def __init__(self, svc, path: str, detect=None):
        self.svc = svc
        self.path = path
        self._detect = detect
        self._lock = make_lock("wire.uds")
        self._conns: set = set()
        self._inflight = 0
        self._closing = False
        self._sock: socket.socket | None = None

    def start(self) -> None:
        import os
        try:
            os.unlink(self.path)
        except OSError:
            pass
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.bind(self.path)
        s.listen(128)
        self._sock = s
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="ldt-uds-accept").start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return          # listener closed: shutdown signal
            with self._lock:
                if self._closing:
                    conn.close()
                    continue
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="ldt-uds-conn").start()

    def _serve_conn(self, conn) -> None:
        svc = self.svc
        hdr = bytearray(FRAME_HEADER.size)
        hview = memoryview(hdr)
        ext = bytearray(FRAME_EXT_HEADER.size)
        eview = memoryview(ext)
        buf = bytearray(65536)
        try:
            while True:
                # the FIRST byte of a frame may wait forever (idle
                # keep-alive between frames is legal); once it arrives
                # the rest of the header and body must land within the
                # slow-loris budget or the connection answers a 408
                # frame and closes — a stalled writer cannot hold its
                # thread and grow-only buffer open indefinitely
                first = conn.recv(1)
                if not first:
                    return      # clean EOF
                hdr[0:1] = first
                tmo = knobs.get_float("LDT_FRAME_READ_TIMEOUT_SEC")
                if tmo:
                    conn.settimeout(tmo)
                try:
                    if not _recv_exact_into(conn, hview[1:],
                                            len(hdr) - 1):
                        return  # truncated header
                    (length,) = FRAME_HEADER.unpack(hdr)
                    tenant = None
                    deadline_ms = None
                    priority = False
                    request_id = None
                    crc = None
                    want_spans = False
                    if length & FRAME_V2_FLAG:
                        length &= ~FRAME_V2_FLAG
                        if not _recv_exact_into(conn, eview, len(ext)):
                            return  # truncated ext header
                        flags, tlen, dl = FRAME_EXT_HEADER.unpack(ext)
                        priority = bool(flags & FRAME_PRIORITY)
                        want_spans = bool(flags & FRAME_SPANS)
                        if dl:
                            deadline_ms = dl
                        if tlen:
                            tbuf = bytearray(tlen)
                            if not _recv_exact_into(
                                    conn, memoryview(tbuf), tlen):
                                return
                            tenant = tbuf.decode("latin-1")
                        if flags & FRAME_REQID:
                            one = bytearray(1)
                            if not _recv_exact_into(
                                    conn, memoryview(one), 1):
                                return
                            rbuf = bytearray(one[0])
                            if rbuf and not _recv_exact_into(
                                    conn, memoryview(rbuf), len(rbuf)):
                                return
                            request_id = clean_request_id(bytes(rbuf))
                        if flags & FRAME_CRC:
                            cw = bytearray(FRAME_CRC_WORD.size)
                            if not _recv_exact_into(
                                    conn, memoryview(cw), len(cw)):
                                return
                            (crc,) = FRAME_CRC_WORD.unpack(cw)
                    if length > BODY_LIMIT_BYTES:
                        m = svc.metrics
                        m.inc("augmentation_requests_total")
                        m.inc("augmentation_invalid_requests_total")
                        m.inc_object("unsuccessful")
                        telemetry.REGISTRY.counter_inc(
                            "ldt_http_requests_total", lane="uds")
                        send_frame(conn, 413, [OVERSIZE_BODY],
                                   request_id=request_id)
                        return
                    if length > len(buf):
                        buf = bytearray(length)
                    if not _recv_exact_into(
                            conn, memoryview(buf)[:length], length):
                        return  # truncated frame: no resync possible
                except socket.timeout:
                    # best-effort explicit refusal, then close (the
                    # stream cannot resync mid-frame either way)
                    send_frame(conn, 408, [TIMEOUT_BODY])
                    return
                if tmo:
                    conn.settimeout(None)
                if crc is not None:
                    if faults.ACTIVE is not None:
                        seed = faults.corruption("frame_payload")
                        if seed is not None and length:
                            bad = faults.corrupt_buffer(
                                np.frombuffer(
                                    bytes(buf[:length]),
                                    dtype=np.uint8), seed)
                            buf[:length] = bad.tobytes()
                    ok = zlib.crc32(
                        memoryview(buf)[:length]) == crc
                    telemetry.REGISTRY.counter_inc(
                        "ldt_integrity_crc_total", lane="uds",
                        result="ok" if ok else "mismatch")
                    if not ok:
                        # the full body was consumed, so the stream
                        # is still framed: refuse THIS frame and keep
                        # the connection — never parse garbage
                        telemetry.REGISTRY.counter_inc(
                            "ldt_integrity_detected_total",
                            kind="frame_crc", lane="uds")
                        m = svc.metrics
                        m.inc("augmentation_requests_total")
                        m.inc("augmentation_invalid_requests_total")
                        m.inc_object("unsuccessful")
                        telemetry.REGISTRY.counter_inc(
                            "ldt_http_requests_total", lane="uds")
                        send_frame(conn, 400, [CRC_ERROR_BODY],
                                   request_id=request_id)
                        continue
                with self._lock:
                    self._inflight += 1
                try:
                    status, buffers = handle_frame(
                        svc, buf, detect=self._detect, nbytes=length,
                        tenant=tenant, deadline_ms=deadline_ms,
                        priority=priority, request_id=request_id,
                        want_spans=want_spans)
                    send_frame(conn, status, buffers,
                               request_id=request_id)
                finally:
                    with self._lock:
                        self._inflight -= 1
        except OSError:
            return              # peer reset / closed under us
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def close(self, drain_sec: float | None = None) -> None:
        import os
        with self._lock:
            self._closing = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        try:
            os.unlink(self.path)
        except OSError:
            pass
        deadline = time.monotonic() + (drain_sec or 0.0)
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.02)
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
