#!/bin/bash
# Build the native packer shared library.
set -e
cd "$(dirname "$0")"
g++ -O2 -shared -fPIC -std=c++17 -o libldtpack.so packer.cc epilogue.cc -lpthread
echo "built $(pwd)/libldtpack.so"
