"""Native (C++) host runtime: the batch packer.

Loads libldtpack.so (built on demand from packer.cc) and exposes
`pack_batch_native`, an array-for-array drop-in for the Python
preprocess.pack.pack_batch (tests/test_native_pack.py asserts equality).
Falls back gracefully: `available()` is False when no compiler/library
exists and callers keep using the Python packer.
"""
from __future__ import annotations

import ctypes
import dataclasses
import subprocess
from pathlib import Path

import numpy as np

from ..registry import Registry, ULSCRIPT_LATIN
from ..tables import ScoringTables
from ..preprocess.pack import PackedBatch

_DIR = Path(__file__).parent
_SO = _DIR / "libldtpack.so"

_lib = None
_init_keepalive: list = []
_lock = __import__("threading").Lock()


def _build() -> bool:
    try:
        subprocess.run([str(_DIR / "build.sh")], check=True,
                       capture_output=True, timeout=120)
        return _SO.exists()
    except Exception:
        return False


_SYMBOLS = ("ldt_init", "ldt_pack_batch", "ldt_epilogue_batch",
            "ldt_init_tables", "ldt_pack_resolve", "ldt_flatten_resolved")
_ABI_VERSION = 4  # must match packer.cc ldt_abi_version()


def _try_load_all():
    """CDLL + symbol & ABI-version check; None for a missing or stale .so
    (older source set OR older ABI — signature/wire-layout changes bump
    _ABI_VERSION so a cached binary can never silently corrupt results)."""
    try:
        lib = ctypes.CDLL(str(_SO))
        lib.ldt_abi_version.restype = ctypes.c_int32
        if lib.ldt_abi_version() != _ABI_VERSION:
            return None
        for sym in _SYMBOLS:
            getattr(lib, sym).restype = None
        return lib
    except (OSError, AttributeError):
        return None


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = _try_load_all() if _SO.exists() else None
        if lib is None:
            # missing or stale: rebuild once, then retry
            try:
                _SO.unlink(missing_ok=True)
            except OSError:
                pass
            lib = _try_load_all() if _build() else None
        _lib = lib if lib is not None else False
        return _lib


def available() -> bool:
    return bool(_load())


_initialized_for: tuple = ()


def _ptr(a: np.ndarray, dtype):
    assert a.dtype == dtype and a.flags.c_contiguous
    return a.ctypes.data_as(ctypes.c_void_p)


def _ensure_init(tables: ScoringTables, reg: Registry):
    """Upload table pointers once per (tables, registry) pair. Holds
    strong references to the actual objects (not ids — CPython recycles
    addresses) and serializes re-init across threads."""
    global _initialized_for
    key = (tables, reg)
    if _initialized_for and _initialized_for[0] is tables and \
            _initialized_for[1] is reg:
        return
    lib = _load()
    with _lock:
        if _initialized_for and _initialized_for[0] is tables and \
                _initialized_for[1] is reg:
            return
        seed_lp = np.zeros(reg.num_scripts, np.uint32)
        for s in range(reg.num_scripts):
            lang = reg.default_language(s)
            seed_lp[s] = np.uint32(
                reg.per_script_number(ULSCRIPT_LATIN, lang) << 8)
        rtype = np.ascontiguousarray(reg.ulscript_rtype.astype(np.int32))
        deflang = np.ascontiguousarray(
            reg.ulscript_default_lang.astype(np.int32))
        script_of = np.ascontiguousarray(tables.script_of_cp, dtype=np.uint8)
        lower = np.arange(0x110000, dtype=np.uint32)
        lower[tables.lower_pairs[:, 0]] = tables.lower_pairs[:, 1]
        cjk_prop = np.ascontiguousarray(tables.cjk_uni_prop, dtype=np.uint8)
        _init_keepalive.clear()
        _init_keepalive.extend([seed_lp, rtype, deflang, script_of, lower,
                                cjk_prop])
        lib.ldt_init(
            _ptr(script_of, np.uint8), _ptr(lower, np.uint32),
            _ptr(cjk_prop, np.uint8), _ptr(rtype, np.int32),
            _ptr(deflang, np.int32), _ptr(seed_lp, np.uint32),
            ctypes.c_int32(reg.num_scripts),
            ctypes.c_int32(1 if tables.distinctbi.empty else 0))
        # host resolution tables (packer.cc resolve path); HostTables is
        # cached per (tables, reg) so the pointers stay alive with it
        from ..ops.device_tables import host_tables
        ht = host_tables(tables, reg)
        _init_keepalive.append(ht)
        lib.ldt_init_tables(
            _ptr(ht.cat_buckets, np.uint32), _ptr(ht.cat_ind2, np.uint32),
            ctypes.c_int64(len(ht.cat_ind)),
            _ptr(ht.bucket_off, np.int64), _ptr(ht.size, np.uint32),
            _ptr(ht.keymask, np.uint32), _ptr(ht.ind_off, np.int32),
            _ptr(ht.size_one, np.int32), _ptr(ht.probes, np.uint8),
            ctypes.c_int64(ht.q2.bucket_off),
            ctypes.c_uint32(ht.q2.size), ctypes.c_uint32(ht.q2.keymask),
            ctypes.c_int32(ht.q2.ind_off), ctypes.c_int32(ht.q2.size_one),
            ctypes.c_int32(1 if ht.q2_enabled else 0),
            ctypes.c_int32(ht.seed_ind_base))
        _initialized_for = key


def pack_batch_native(texts: list[str], tables: ScoringTables,
                      reg: Registry, max_slots: int = 2048,
                      max_chunks: int = 64, max_direct: int = 4,
                      flags: int = 0, n_threads: int = 0) -> PackedBatch:
    """Native twin of preprocess.pack.pack_batch (same output contract)."""
    lib = _load()
    if not lib:
        raise RuntimeError("native packer unavailable")
    _ensure_init(tables, reg)

    B, L, C, D = len(texts), max_slots, max_chunks, max_direct
    enc = [t.encode("utf-8", errors="surrogatepass") for t in texts]
    bounds = np.zeros(B + 1, np.int64)
    np.cumsum([len(e) for e in enc], out=bounds[1:])
    blob = np.frombuffer(b"".join(enc), dtype=np.uint8) if bounds[-1] \
        else np.zeros(1, np.uint8)
    blob = np.ascontiguousarray(blob)

    out = PackedBatch(
        kind=np.zeros((B, L), np.int8),
        offset=np.zeros((B, L), np.int32),
        fp=np.zeros((B, L), np.uint32),
        fp_hi=np.zeros((B, L), np.uint8),
        chunk_base=np.zeros((B, L), np.int32),
        span_start=np.zeros((B, L), np.int32),
        span_end_off=np.zeros((B, L), np.int32),
        side=np.zeros((B, L), np.int8),
        cjk=np.zeros((B, L), np.int8),
        script=np.zeros((B, L), np.int16),
        chunk_script=np.zeros((B, C), np.int16),
        chunk_cjk=np.zeros((B, C), np.int8),
        chunk_side=np.zeros((B, C), np.int8),
        chunk_span_end=np.zeros((B, C), np.int32),
        direct_adds=np.full((B, D, 3), -1, np.int32),
        text_bytes=np.zeros(B, np.int32),
        fallback=np.zeros(B, bool),
        n_slots=np.zeros(B, np.int32),
        n_chunks=np.zeros(B, np.int32),
        n_docs=B,
    )
    if n_threads <= 0:
        import os
        n_threads = min(8, os.cpu_count() or 1)
    lib.ldt_pack_batch(
        _ptr(blob, np.uint8), _ptr(bounds, np.int64),
        ctypes.c_int32(B), ctypes.c_int32(L), ctypes.c_int32(C),
        ctypes.c_int32(D), ctypes.c_int32(flags),
        ctypes.c_int32(n_threads),
        _ptr(out.kind, np.int8), _ptr(out.offset, np.int32),
        _ptr(out.fp, np.uint32), _ptr(out.fp_hi, np.uint8),
        _ptr(out.chunk_base, np.int32), _ptr(out.span_start, np.int32),
        _ptr(out.span_end_off, np.int32), _ptr(out.side, np.int8),
        _ptr(out.cjk, np.int8), _ptr(out.script, np.int16),
        _ptr(out.chunk_script, np.int16), _ptr(out.chunk_cjk, np.int8),
        _ptr(out.chunk_side, np.int8), _ptr(out.chunk_span_end, np.int32),
        out.direct_adds.ctypes.data_as(ctypes.c_void_p),
        _ptr(out.text_bytes, np.int32),
        out.fallback.ctypes.data_as(ctypes.c_void_p),
        _ptr(out.n_slots, np.int32), _ptr(out.n_chunks, np.int32))
    return out


# -- resolved-wire packing (packer.cc ldt_pack_resolve) ---------------------


@dataclasses.dataclass
class ResolvedBatch:
    """Host output of the resolve packer: dense per-doc resolved slots +
    chunk metadata + everything the document epilogue needs."""
    idx: np.ndarray          # [B, L] u16 cat_ind2 indices
    chk: np.ndarray          # [B, L] u16 doc-local chunk ids
    cmeta: np.ndarray        # [B, C] u32 cbytes|grams|side|real
    cscript: np.ndarray      # [B, C] u8
    direct_adds: np.ndarray  # [B, D, 3] i32
    text_bytes: np.ndarray   # [B] i32
    fallback: np.ndarray     # [B] bool
    squeezed: np.ndarray     # [B] bool: doc took the squeeze re-scan
    n_slots: np.ndarray      # [B] i32
    n_chunks: np.ndarray     # [B] i32
    n_docs: int = 0


class BufferPool:
    """Rotating output-buffer pool for pack_resolve_native.

    The dense per-doc scratch is tens of MB per batch, and
    freshly-allocated pages cost ~60ms of first-touch faults during the
    C++ writes at B=8192; rotating warm buffer sets removes that.

    Safety contract: the packer clears the cmeta/cscript/direct_adds row
    tails it does not write; idx/chk rows are valid only up to
    n_slots[b] (the wire flattener and every other consumer respect
    that bound). A pool must be owned by ONE engine/pipeline: rotation
    assumes at most RING batches of a shape are alive at once (the
    detect_many pipeline holds <= 4). Shapes evict LRU beyond MAX_KEYS
    so variable batch sizes cannot pin unbounded memory."""

    RING = 4
    MAX_KEYS = 4

    def __init__(self):
        self._rings: dict = {}
        self._lock = __import__("threading").Lock()

    def get(self, B: int, L: int, C: int, D: int) -> "ResolvedBatch":
        key = (B, L, C, D)
        with self._lock:
            ring = self._rings.pop(key, None)
            if ring is None:
                ring = []
                if len(self._rings) >= self.MAX_KEYS:
                    # evict the least-recently-used shape entirely
                    self._rings.pop(next(iter(self._rings)))
            self._rings[key] = ring  # re-insert: dict order = LRU order
            if len(ring) < self.RING:
                rb = ResolvedBatch(
                    idx=np.zeros((B, L), np.uint16),
                    chk=np.zeros((B, L), np.uint16),
                    cmeta=np.zeros((B, C), np.uint32),
                    cscript=np.zeros((B, C), np.uint8),
                    direct_adds=np.full((B, D, 3), -1, np.int32),
                    text_bytes=np.zeros(B, np.int32),
                    fallback=np.zeros(B, bool),
                    squeezed=np.zeros(B, bool),
                    n_slots=np.zeros(B, np.int32),
                    n_chunks=np.zeros(B, np.int32),
                    n_docs=B,
                )
                ring.append(rb)
                return rb
            rb = ring.pop(0)
            ring.append(rb)
            rb.n_docs = B
            return rb


def pack_resolve_native(texts: list[str], tables: ScoringTables,
                        reg: Registry, max_slots: int = 2048,
                        max_chunks: int = 64, max_direct: int | None = None,
                        flags: int = 0, n_threads: int = 0,
                        pool: BufferPool | None = None) -> ResolvedBatch:
    """texts -> resolved wire inputs (table probes, repeat filter, chunk
    assignment, and distinct boosts all done in C++; see packer.cc).

    max_direct defaults to max_chunks: every RTypeNone/One span consumes
    one chunk and one direct-add row, so a tighter cap would just send
    long multi-script documents to the scalar fallback.

    pool: optional caller-owned BufferPool reusing warm output buffers
    (the returned ResolvedBatch is then only valid until the pool cycles
    back around — see BufferPool's contract). Without a pool, fresh
    arrays are allocated per call."""
    lib = _load()
    if not lib:
        raise RuntimeError("native packer unavailable")
    _ensure_init(tables, reg)

    if max_direct is None:
        max_direct = max_chunks
    B, L, C, D = len(texts), max_slots, max_chunks, max_direct
    enc = [t.encode("utf-8", errors="surrogatepass") for t in texts]
    bounds = np.zeros(B + 1, np.int64)
    np.cumsum([len(e) for e in enc], out=bounds[1:])
    blob = np.frombuffer(b"".join(enc), dtype=np.uint8) if bounds[-1] \
        else np.zeros(1, np.uint8)
    blob = np.ascontiguousarray(blob)

    if pool is not None:
        out = pool.get(B, L, C, D)
    else:
        out = ResolvedBatch(
            idx=np.zeros((B, L), np.uint16),
            chk=np.zeros((B, L), np.uint16),
            cmeta=np.zeros((B, C), np.uint32),
            cscript=np.zeros((B, C), np.uint8),
            direct_adds=np.full((B, D, 3), -1, np.int32),
            text_bytes=np.zeros(B, np.int32),
            fallback=np.zeros(B, bool),
            squeezed=np.zeros(B, bool),
            n_slots=np.zeros(B, np.int32),
            n_chunks=np.zeros(B, np.int32),
            n_docs=B,
        )
    if n_threads <= 0:
        import os
        # oversubscribe modestly: the per-doc work mixes pointer-chasing
        # probes with byte scans, and cgroup-limited cpu counts underreport
        n_threads = min(16, 2 * (os.cpu_count() or 1) + 6)
    lib.ldt_pack_resolve(
        _ptr(blob, np.uint8), _ptr(bounds, np.int64),
        ctypes.c_int32(B), ctypes.c_int32(L), ctypes.c_int32(C),
        ctypes.c_int32(D), ctypes.c_int32(flags),
        ctypes.c_int32(n_threads),
        _ptr(out.idx, np.uint16), _ptr(out.chk, np.uint16),
        _ptr(out.cmeta, np.uint32), _ptr(out.cscript, np.uint8),
        out.direct_adds.ctypes.data_as(ctypes.c_void_p),
        _ptr(out.text_bytes, np.int32),
        out.fallback.ctypes.data_as(ctypes.c_void_p),
        out.squeezed.ctypes.data_as(ctypes.c_void_p),
        _ptr(out.n_slots, np.int32), _ptr(out.n_chunks, np.int32))
    return out


def flatten_resolved_native(rb: ResolvedBatch, n_shards: int,
                            N: int) -> dict:
    """Dense ResolvedBatch slots -> flat ragged [n_shards, N] wire leaves
    (idx, chk, doc_start)."""
    lib = _load()
    if not lib:
        raise RuntimeError("native library unavailable")
    B, L = rb.idx.shape
    idx_flat = np.zeros((n_shards, N), np.uint16)
    chk_flat = np.zeros((n_shards, N), np.uint16)
    doc_start = np.zeros(B, np.int32)
    n_slots = np.ascontiguousarray(rb.n_slots, dtype=np.int32)
    lib.ldt_flatten_resolved(
        _ptr(rb.idx, np.uint16), _ptr(rb.chk, np.uint16),
        _ptr(n_slots, np.int32), ctypes.c_int32(B), ctypes.c_int32(L),
        ctypes.c_int32(n_shards), ctypes.c_int32(N),
        _ptr(idx_flat, np.uint16), _ptr(chk_flat, np.uint16),
        _ptr(doc_start, np.int32))
    return dict(idx=idx_flat, chk=chk_flat, doc_start=doc_start,
                n_slots=n_slots)


# -- batched document epilogue (epilogue.cc) --------------------------------

_epi_reg_cache: tuple = ()  # single slot: (registry object, arrays)


def _epilogue_reg_arrays(reg: Registry):
    """close_set / closest_alt / is_figs as flat arrays, cached for the
    last-used registry object (held by strong reference — never key by
    id(), CPython recycles addresses)."""
    global _epi_reg_cache
    if _epi_reg_cache and _epi_reg_cache[0] is reg:
        return _epi_reg_cache[1]
    n = reg.num_languages
    close = np.zeros(n, np.int32)
    for lang in range(n):
        close[lang] = reg.close_set(lang)
    alt = np.full(n, 26, np.int32)
    alt[:len(reg.closest_alt_lang)] = reg.closest_alt_lang.astype(np.int32)
    figs = np.zeros(n, np.uint8)
    for code in ("fr", "it", "de", "es"):
        figs[reg.code_to_lang[code]] = 1
    arrays = (close, alt, figs)
    _epi_reg_cache = (reg, arrays)
    return arrays


def epilogue_batch_native(rows: np.ndarray, direct_adds: np.ndarray,
                          text_bytes: np.ndarray, skip: np.ndarray,
                          flags: int, reg: Registry) -> np.ndarray:
    """Batched DocTote replay + document post-processing (epilogue.cc),
    the C++ twin of models/ngram.py _doc_epilogue.

    rows: [B, C, 5] int32 chunk summaries from the device scorer.
    direct_adds: [B, D, 3] int32 (chunk_id, lang, bytes; -1 = pad).
    skip: [B] bool - packer-fallback docs the caller resolves via the
    scalar engine regardless.
    Returns [B, 14] int64: summary, lang3[3], percent3[3], ns3[3],
    text_bytes, is_reliable, need_scalar, pad."""
    lib = _load()
    if not lib:
        raise RuntimeError("native epilogue unavailable")
    B, C, _ = rows.shape
    D = direct_adds.shape[1]
    close, alt, figs = _epilogue_reg_arrays(reg)
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    direct = np.ascontiguousarray(direct_adds, dtype=np.int32)
    tb = np.ascontiguousarray(text_bytes, dtype=np.int32)
    sk = np.ascontiguousarray(skip, dtype=np.uint8)
    out = np.zeros((B, 14), np.int64)
    lib.ldt_epilogue_batch(
        _ptr(rows, np.int32), _ptr(direct, np.int32), _ptr(tb, np.int32),
        _ptr(sk, np.uint8), ctypes.c_int32(B), ctypes.c_int32(C),
        ctypes.c_int32(D), ctypes.c_int32(flags),
        _ptr(close, np.int32), _ptr(alt, np.int32), _ptr(figs, np.uint8),
        ctypes.c_int32(len(close)), _ptr(out, np.int64))
    return out
