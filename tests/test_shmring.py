"""Crash-safe shared-memory ring lane (service/shmring.py).

Chaos suite for the lease/fencing protocol: a client SIGKILLed
mid-WRITING is reclaimed (and its orphaned ring file garbage-collected),
a worker crash mid-LEASED fences the old generation and answers
explicit error frames instead of hanging the client, and quarantine
bisection isolates exactly the poison doc out of a 32-doc frame. The
happy path pins byte-identity with the UDS frame contract
(wire.handle_frame) — the shm lane is a transport, not a different
protocol.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

from language_detector_tpu import faults, telemetry
from language_detector_tpu.service import shmring, wire
from language_detector_tpu.service.server import DetectorService

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _echo(texts, trace=None):
    return ["en"] * len(texts)


def _svc():
    return DetectorService(use_device=False, start_batcher=False)


def _body(n=1, poison_at=None):
    docs = [{"text": f"plain document number {i}"} for i in range(n)]
    if poison_at is not None:
        docs[poison_at]["text"] = \
            f"bad doc {shmring.POISON_MARKER} kills the batch"
    return json.dumps({"request": docs}).encode()


def _reclaims(reason):
    return telemetry.REGISTRY.counter_value(
        "ldt_shm_reclaimed_total", reason=reason)


def test_roundtrip_parity_and_pipelining(tmp_path):
    """Responses on the shm lane are byte-identical to handle_frame's
    UDS output, and several frames pipeline across slots."""
    svc = _svc()
    srv = shmring.ShmRingServer(svc, str(tmp_path), detect=_echo)
    srv.start()
    cli = shmring.RingClient(str(tmp_path))
    try:
        cli.wait_attached(10.0)
        body = _body(3)
        st, resp = cli.request(body, timeout=10.0)
        st2, bufs = wire.handle_frame(svc, body, detect=_echo)
        assert (st, resp) == (st2, b"".join(bufs))
        assert json.loads(resp)["response"][0]["iso6391code"] == "en"
        idxs = [cli.submit(body) for _ in range(4)]
        assert all(i is not None for i in idxs)
        for i in idxs:
            s, r = cli.wait(i, timeout=10.0)
            assert (s, r) == (st, resp)
        stats = srv.stats()
        assert stats["rings"] == 1
        assert stats["frames"] >= 5
    finally:
        cli.close(unlink=True)
        srv.close()


def test_error_frames_match_uds_contract(tmp_path):
    """Malformed bodies answer the SAME error frames as the UDS lane."""
    svc = _svc()
    srv = shmring.ShmRingServer(svc, str(tmp_path), detect=_echo)
    srv.start()
    cli = shmring.RingClient(str(tmp_path))
    try:
        cli.wait_attached(10.0)
        for body in (b"not json{{", b'{"request": [{"other": 1}]}'):
            st, resp = cli.request(body, timeout=10.0)
            st2, bufs = wire.handle_frame(svc, body, detect=_echo)
            assert (st, resp) == (st2, b"".join(bufs)), body
    finally:
        cli.close(unlink=True)
        srv.close()


_CHILD_MID_WRITING = """
import os, sys, time
sys.path.insert(0, sys.argv[2])
from language_detector_tpu.service import shmring
c = shmring.RingClient(sys.argv[1])
c.slots[0].mark_writing()
c.rf.write_slot(0, shmring.SLOT_WRITING, c.rf.generation, os.getpid(),
                time.time(), 0, 0)
print("WRITING", flush=True)
time.sleep(60)
"""


def test_client_sigkill_mid_writing_is_reclaimed(tmp_path, monkeypatch):
    """SIGKILL a client that claimed a slot mid-WRITING: the lease
    sweep reclaims the slot (dead owner pid), and once every slot of
    the dead client's ring is FREE the ring file itself is GC'd."""
    monkeypatch.setenv("LDT_SHM_LEASE_TIMEOUT_SEC", "0.2")
    before = _reclaims("writer-lost")
    svc = _svc()
    srv = shmring.ShmRingServer(svc, str(tmp_path), detect=_echo)
    srv.start()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    child = subprocess.Popen(
        [sys.executable, "-c", _CHILD_MID_WRITING, str(tmp_path), ROOT],
        stdout=subprocess.PIPE, text=True, env=env)
    try:
        assert "WRITING" in child.stdout.readline()
        ring = shmring.client_ring_path(str(tmp_path), child.pid)
        assert os.path.exists(ring)
        # live writer with a fresh lease: the sweep must NOT reclaim
        time.sleep(0.15)
        rf = shmring.RingFile(ring)
        assert rf.read_slot(0)[0] == shmring.SLOT_WRITING
        rf.close()
        child.kill()
        child.wait(10)
        deadline = time.monotonic() + 10.0
        while os.path.exists(ring) and time.monotonic() < deadline:
            time.sleep(0.02)
        # slot reclaimed -> all-FREE ring of a dead client -> unlinked
        assert not os.path.exists(ring)
        assert _reclaims("writer-lost") >= before + 1
    finally:
        if child.poll() is None:
            child.kill()
        srv.close()


def test_worker_crash_fences_old_generation_no_hang(tmp_path):
    """The fleet-member-crash drill, in-process: a previous worker
    attached the ring (generation 1), leased one frame, and died with
    another frame committed READY. The restarted worker's attach bumps
    the generation, and BOTH stale frames come back as explicit 503
    error frames — the client's wait() resolves, it never hangs."""
    cli = shmring.RingClient(str(tmp_path))
    dead_pid = 2 ** 22 + 1025     # beyond pid_max: definitely dead
    cli.rf.set_generation(1, dead_pid)   # the "previous worker"
    body = _body(2)
    i0 = cli.submit(body)
    i1 = cli.submit(body)
    assert i0 is not None and i1 is not None
    # the old worker leased i1 mid-score and crashed
    cli.rf.write_slot(i1, shmring.SLOT_LEASED, 1, dead_pid,
                      time.time(), len(body), 0)
    svc = _svc()
    srv = shmring.ShmRingServer(svc, str(tmp_path), detect=_echo)
    srv.start()
    try:
        for i in (i0, i1):
            st, resp = cli.wait(i, timeout=10.0)
            assert st == 503
            assert b"fenced" in resp
        # the ring stays serviceable on the new generation
        st, resp = cli.request(body, timeout=10.0)
        assert st in (200, 203)
    finally:
        cli.close(unlink=True)
        srv.close()


def test_quarantine_bisection_isolates_poison_doc(tmp_path):
    """A 32-doc frame with ONE poison doc (deterministically kills its
    scorer batch under the poison_doc fault): bisection isolates and
    quarantines exactly that doc, the other 31 docs still answer, and a
    re-submission pre-filters the quarantined doc without re-bisecting."""
    docs_before = telemetry.REGISTRY.counter_value(
        "ldt_quarantine_docs_total")
    svc = _svc()
    srv = shmring.ShmRingServer(svc, str(tmp_path), detect=_echo)
    srv.start()
    cli = shmring.RingClient(str(tmp_path))
    faults.configure("poison_doc:error")
    try:
        cli.wait_attached(10.0)
        body = _body(32, poison_at=13)
        st, resp = cli.request(body, timeout=30.0)
        codes = [r["iso6391code"]
                 for r in json.loads(resp)["response"]]
        assert len(codes) == 32
        assert codes[13] == "un"
        assert codes.count("un") == 1          # exactly the poison doc
        assert set(codes) == {"en", "un"}
        assert srv.quarantine.total == 1
        assert srv.quarantine.stats()["bisect_batches"] >= 5
        assert telemetry.REGISTRY.counter_value(
            "ldt_quarantine_docs_total") == docs_before + 1
        # resubmit: the quarantined doc is pre-filtered (answers "un"
        # without touching the scorer) — no new bisection burned
        bisects = srv.quarantine.stats()["bisect_batches"]
        st, resp = cli.request(body, timeout=30.0)
        codes = [r["iso6391code"]
                 for r in json.loads(resp)["response"]]
        assert codes[13] == "un" and codes.count("un") == 1
        assert srv.quarantine.stats()["bisect_batches"] == bisects
        assert srv.quarantine.total == 1
    finally:
        faults.configure(None)
        cli.close(unlink=True)
        srv.close()


def test_lease_fault_retries_frame(tmp_path):
    """An injected shm_lease fault leaves the frame READY; the next
    sweep (fault disarmed) serves it — no frame is lost."""
    svc = _svc()
    srv = shmring.ShmRingServer(svc, str(tmp_path), detect=_echo)
    srv.start()
    cli = shmring.RingClient(str(tmp_path))
    faults.configure("shm_lease:error:p=0.5:seed=7")
    try:
        cli.wait_attached(10.0)
        for _ in range(8):
            st, resp = cli.request(_body(2), timeout=10.0)
            assert st in (200, 203)
    finally:
        faults.configure(None)
        cli.close(unlink=True)
        srv.close()


def test_oversize_frame_refused_at_submit(tmp_path):
    cli = shmring.RingClient(str(tmp_path), slot_bytes=4096)
    try:
        with pytest.raises(ValueError, match="slot capacity"):
            cli.submit(b"x" * (cli.rf.slot_bytes + 1))
    finally:
        cli.close(unlink=True)
