"""Artifact hot-swap contract (service/swap.py): in-process rebind on
both scorer paths, abort safety (corrupt artifact, injected fault,
open breaker), the POST /swap operator endpoint on both fronts, the
warmup readiness gate, and the compile-cache knob.

The blue/green generation swap (supervisor SIGHUP drill) is covered in
tests/test_supervisor.py; ci.sh runs the full live drill as a smoke.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import urllib.error
import urllib.request

import pytest

from language_detector_tpu import faults, native, telemetry
from language_detector_tpu.service.admission import (AdmissionConfig,
                                                     AdmissionController)
from language_detector_tpu.service.server import (DetectorService,
                                                  make_server)
from language_detector_tpu.service.swap import (SwapError, swap_artifact,
                                                startup_ready_task)

ARTIFACT = os.path.join(os.path.dirname(__file__), "..",
                        "language_detector_tpu", "data", "model.ldta")

EN = ("this is a simple english sentence with common words that "
      "should be detected without any trouble at all")


def _detect(svc, texts):
    return svc.batcher.submit(texts).result(30)


@pytest.fixture()
def scalar_svc():
    svc = DetectorService(use_device=False, max_delay_ms=1.0)
    yield svc
    svc.batcher.close()


@pytest.fixture()
def artifact_copy(tmp_path):
    return str(shutil.copy(ARTIFACT, tmp_path / "new.ldta"))


# -- in-process swap ---------------------------------------------------------


def test_scalar_swap_rebinds_tables(scalar_svc, artifact_copy):
    svc = scalar_svc
    assert _detect(svc, [EN]) == ["en"]
    old_tables = svc._tables
    ok0 = telemetry.REGISTRY.counter_value("ldt_swap_total",
                                           result="ok")
    info = swap_artifact(svc, artifact_copy)
    assert info["swapped"] and info["swap_count"] == 1
    assert not info["engine"]
    assert svc._tables is not old_tables  # FRESH mmap, not the cache
    assert svc._artifact_path == artifact_copy
    assert _detect(svc, [EN]) == ["en"]  # still serving, new tables
    assert telemetry.REGISTRY.counter_value(
        "ldt_swap_total", result="ok") == ok0 + 1


def test_swap_corrupt_artifact_aborts(scalar_svc, tmp_path):
    svc = scalar_svc
    bad = tmp_path / "bad.ldta"
    bad.write_bytes(b"not an artifact")
    old_tables = svc._tables
    err0 = telemetry.REGISTRY.counter_value("ldt_swap_total",
                                            result="error")
    with pytest.raises(SwapError):
        swap_artifact(svc, bad)
    # the old artifact keeps serving, untouched
    assert svc._tables is old_tables and svc._swap_count == 0
    assert _detect(svc, [EN]) == ["en"]
    assert telemetry.REGISTRY.counter_value(
        "ldt_swap_total", result="error") == err0 + 1


def test_swap_refuses_standby_failing_digest_footer(scalar_svc,
                                                    artifact_copy):
    """Integrity refusal: a standby whose payload fails its digest
    footer must be refused BEFORE any serving state is touched — a
    distinct result label from the generic abort."""
    from language_detector_tpu import artifact
    svc = scalar_svc
    raw = bytearray(open(artifact_copy, "rb").read())
    raw[len(raw) // 2] ^= 0x01  # one bit of payload rot
    open(artifact_copy, "wb").write(bytes(raw))
    with pytest.raises(artifact.ArtifactIntegrityError):
        artifact.verify_artifact(artifact_copy)
    old_tables = svc._tables
    ref0 = telemetry.REGISTRY.counter_value(
        "ldt_swap_total", result="integrity_refused")
    with pytest.raises(SwapError, match="integrity"):
        swap_artifact(svc, artifact_copy)
    assert svc._tables is old_tables and svc._swap_count == 0
    assert _detect(svc, [EN]) == ["en"]  # old tables keep serving
    assert telemetry.REGISTRY.counter_value(
        "ldt_swap_total", result="integrity_refused") == ref0 + 1


def test_swap_flushes_result_cache_epoch(scalar_svc, artifact_copy):
    """The staleness regression this PR fixes: a cached result must
    never survive a swap (same key, new tables -> recompute)."""
    from language_detector_tpu import artifact
    from language_detector_tpu.service.batcher import (_MISS,
                                                       ResultCache)
    svc = scalar_svc
    cache = ResultCache(1 << 20)
    old_cache, svc.batcher._cache = svc.batcher._cache, cache
    # front-registered caches (the aio front's) flush through the same
    # hook
    front = ResultCache(1 << 20)
    svc._result_caches = [front]
    try:
        key = (None, "a cached doc")
        cache.put(key, {"pin": 1}, "a cached doc")
        front.put(key, {"pin": 2}, "a cached doc")
        assert cache.get(key) == {"pin": 1}
        assert swap_artifact(svc, artifact_copy)["swapped"]
        assert cache.get(key) is _MISS   # flushed at the rebind
        assert front.get(key) is _MISS
        assert cache._epoch == artifact.artifact_digest(artifact_copy)
        assert front._epoch == cache._epoch
    finally:
        svc.batcher._cache = old_cache
        del svc._result_caches


def test_swap_cutover_fault_aborts(scalar_svc, artifact_copy):
    svc = scalar_svc
    old_tables = svc._tables
    faults.configure("swap_cutover:error")
    try:
        with pytest.raises(SwapError):
            swap_artifact(svc, artifact_copy)
    finally:
        faults.configure(None)
    assert svc._tables is old_tables
    assert _detect(svc, [EN]) == ["en"]
    # a re-run with the fault disarmed succeeds
    assert swap_artifact(svc, artifact_copy)["swapped"]


# -- device-engine swap + breaker guard --------------------------------------


@pytest.fixture(scope="module")
def device_svc():
    if not native.available():
        pytest.skip("native packer unavailable")
    ctrl = AdmissionController(AdmissionConfig(breaker_failures=2,
                                               breaker_cooldown_sec=0.1))
    svc = DetectorService(use_device=True, max_delay_ms=1.0,
                          admission=ctrl)
    if svc._engine is None:
        pytest.skip("device engine unavailable")
    yield svc
    svc.batcher.close()


def test_engine_swap_preserves_stats(device_svc, artifact_copy):
    svc = device_svc
    assert _detect(svc, [EN]) == ["en"]
    old_eng = svc._engine
    before = old_eng.stats_snapshot()
    assert before["batches"] >= 1
    info = swap_artifact(svc, artifact_copy)
    assert info["engine"]
    assert svc._engine is not old_eng
    # counters carried over: monotonic across the swap
    after = svc._engine.stats_snapshot()
    assert after["batches"] >= before["batches"]
    assert _detect(svc, [EN]) == ["en"]


def test_swap_refused_while_breaker_open(device_svc, artifact_copy):
    svc = device_svc
    br = svc.admission.breaker
    br.record_failure()
    br.record_failure()  # trips open (breaker_failures=2)
    assert br.state == 2
    count0 = svc._swap_count
    with pytest.raises(SwapError, match="breaker"):
        swap_artifact(svc, artifact_copy)
    assert svc._swap_count == count0
    # recover: cooldown, half-open probe, success closes it — swap ok
    import time
    time.sleep(0.15)
    assert br.allow_device()
    br.record_success(1.0)
    assert br.state == 0
    assert swap_artifact(svc, artifact_copy)["swapped"]


# -- POST /swap, sync front --------------------------------------------------


def _post_raw(url, data):
    req = urllib.request.Request(
        url, data=data, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, json.loads(body) if body else None


@pytest.fixture(scope="module")
def sync_front():
    svc = DetectorService(use_device=False, max_delay_ms=1.0)
    httpd, metricsd, svc = make_server(0, 0, service=svc)
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in (httpd, metricsd)]
    for t in threads:
        t.start()
    yield {"url": f"http://127.0.0.1:{httpd.server_address[1]}",
           "metrics_url":
               f"http://127.0.0.1:{metricsd.server_address[1]}",
           "svc": svc}
    httpd.shutdown()
    metricsd.shutdown()
    svc.batcher.close()


def test_sync_post_swap(sync_front, artifact_copy, tmp_path):
    murl = sync_front["metrics_url"]
    status, body = _post_raw(
        murl + "/swap", json.dumps({"path": artifact_copy}).encode())
    assert status == 200 and body["swapped"]
    # serving straight through the swap
    status, body = _post_raw(
        sync_front["url"],
        json.dumps({"request": [{"text": EN}]}).encode())
    assert status == 200
    assert body["response"][0]["iso6391code"] == "en"
    # contract errors: bad JSON 400, no path 400, corrupt artifact 409
    status, body = _post_raw(murl + "/swap", b"{nope")
    assert status == 400
    status, body = _post_raw(murl + "/swap", b"{}")
    assert status == 400 and "path" in body["error"]
    bad = tmp_path / "bad.ldta"
    bad.write_bytes(b"garbage")
    status, body = _post_raw(
        murl + "/swap", json.dumps({"path": str(bad)}).encode())
    assert status == 409
    # swap counters exported on the scrape
    with urllib.request.urlopen(murl + "/metrics") as resp:
        text = resp.read().decode()
    assert 'ldt_swap_total{result="ok"}' in text
    assert 'ldt_swap_total{result="error"}' in text


def test_sync_post_swap_unknown_path_404(sync_front):
    status, _ = _post_raw(sync_front["metrics_url"] + "/nope", b"{}")
    assert status == 404


# -- POST /swap, aio front ---------------------------------------------------


def test_aio_post_swap(artifact_copy):
    import asyncio
    import queue as _q

    from language_detector_tpu.service.aioserver import serve

    ports_q: _q.Queue = _q.Queue()
    loop_holder = {}

    def run_loop():
        async def main():
            loop_holder["loop"] = asyncio.get_running_loop()
            ready = asyncio.get_running_loop().create_future()
            svc = DetectorService(use_device=False, max_delay_ms=1.0,
                                  start_batcher=False)
            task = asyncio.get_running_loop().create_task(
                serve(0, 0, svc=svc, ready=ready))
            ports_q.put(await ready)
            try:
                await task
            except asyncio.CancelledError:
                pass
        try:
            asyncio.run(main())
        except RuntimeError:
            pass

    t = threading.Thread(target=run_loop, daemon=True)
    t.start()
    port, mport = ports_q.get(timeout=30)
    try:
        status, body = _post_raw(
            f"http://127.0.0.1:{mport}/swap",
            json.dumps({"path": artifact_copy}).encode())
        assert status == 200 and body["swapped"]
        status, body = _post_raw(
            f"http://127.0.0.1:{port}",
            json.dumps({"request": [{"text": EN}]}).encode())
        assert status == 200
        assert body["response"][0]["iso6391code"] == "en"
        status, body = _post_raw(
            f"http://127.0.0.1:{mport}/swap", b"{}")
        assert status == 400
    finally:
        loop = loop_holder.get("loop")
        if loop is not None:
            loop.call_soon_threadsafe(loop.stop)


# -- warmup readiness gate + ready-file handshake ----------------------------


def test_warmup_gates_readiness(monkeypatch):
    monkeypatch.setenv("LDT_WARMUP", "1")
    svc = DetectorService(use_device=False, max_delay_ms=1.0)
    try:
        rd = svc.readiness()
        assert not rd["ok"] and not rd["warmed"]
        ms = svc.warm()
        assert ms > 0
        rd = svc.readiness()
        assert rd["ok"] and rd["warmed"] and rd["warmup_ms"] > 0
    finally:
        svc.batcher.close()


def test_warmup_off_by_default():
    svc = DetectorService(use_device=False, max_delay_ms=1.0)
    try:
        rd = svc.readiness()
        assert rd["ok"] and rd["warmed"] and rd["warmup_ms"] == 0
    finally:
        svc.batcher.close()


def test_startup_ready_task_writes_ready_file(monkeypatch, tmp_path):
    ready = tmp_path / "ready.json"
    monkeypatch.setenv("LDT_READY_FILE", str(ready))
    monkeypatch.setenv("LDT_WARMUP", "1")
    monkeypatch.setenv("LDT_WORKER_GENERATION", "7")
    svc = DetectorService(use_device=False, max_delay_ms=1.0)
    try:
        startup_ready_task(svc, (1234, 5678))
        doc = json.loads(ready.read_text())
        assert doc["generation"] == 7 and doc["port"] == 1234
        assert doc["metrics_port"] == 5678
        assert doc["warmup_ms"] > 0
        assert svc.readiness()["ok"]
    finally:
        svc.batcher.close()


# -- compile-cache knob ------------------------------------------------------


def test_compile_cache_dir_knob(monkeypatch, tmp_path):
    if not native.available():
        pytest.skip("native packer unavailable")
    import jax
    old = jax.config.jax_compilation_cache_dir
    monkeypatch.setenv("LDT_COMPILE_CACHE_DIR", str(tmp_path))
    try:
        from language_detector_tpu.models.ngram import NgramBatchEngine
        NgramBatchEngine()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)
    finally:
        jax.config.update("jax_compilation_cache_dir", old)
