// Extract CLD2 scoring tables (model weights) from the reference snapshot
// into flat binary blobs + a text manifest, for conversion into the
// language_detector_tpu table artifact.
//
// This tool compiles AGAINST the read-only reference at /root/reference
// (generated data tables + the UTF-8 state-table interpreter). It extracts
// DATA ONLY — the runtime framework re-implements all algorithms TPU-first.
//
// Reference data contracts:
//   cld2tablesummary.h:37-49  (CLD2TableSummary: buckets/indirect/keymask)
//   generated_language.cc     (language registry arrays)
//   generated_ulscript.cc     (script registry arrays)
//   cld_generated_cjk_uni_prop_80.cc (CJK unigram UTF8PropObj DFA)
//
// Usage: extract_main <output_dir>

#include <stdio.h>
#include <string.h>
#include <stdlib.h>
#include <string>
#include <vector>

#include "integral_types.h"
#include "cld2tablesummary.h"
#include "utf8statetable.h"
#include "generated_language.h"
#include "generated_ulscript.h"
#include "cldutil_shared.h"   // kLgProbV2Tbl quantized-prob decode table

namespace CLD2 {
// Table objects defined in the generated .cc files we compile alongside.
extern const CLD2TableSummary kDeltaOcta_obj;       // deltaocta0527
extern const CLD2TableSummary kDistinctOcta_obj;    // distinctocta0527
extern const CLD2TableSummary kCjkDeltaBi_obj;      // cjk_delta_bi_32
extern const CLD2TableSummary kDistinctBiTable_obj; // distinct_bi_0 (dummy)
extern const CLD2TableSummary kCjkCompat_obj;       // cjk_compatible
extern const UTF8PropObj cld_generated_CjkUni_obj;  // cjk_uni_prop_80
extern const short kAvgDeltaOctaScore[];            // score_quad_octa_1024_256
extern const uint32 kCompatTableIndSize;            // cjkcompat ind length

// Registry arrays from generated_language.cc / generated_ulscript.cc
extern const int kLanguageToNameSize;
extern const char* const kLanguageToName[];
extern const int kLanguageToCodeSize;
extern const char* const kLanguageToCode[];
extern const int kLanguageToCNameSize;
extern const char* const kLanguageToCName[];
extern const int kLanguageToScriptsSize;
extern const FourScripts kLanguageToScripts[];
extern const int kLanguageToPLangSize;
extern const uint8 kLanguageToPLang[];
extern const uint16 kPLangToLanguageLatn[];
extern const uint16 kPLangToLanguageOthr[];
extern const uint8 kPLangToCloseSetLatn[];
extern const uint8 kPLangToCloseSetOthr[];
extern const int kULScriptToNameSize;
extern const char* const kULScriptToName[];
extern const int kULScriptToCodeSize;
extern const char* const kULScriptToCode[];
extern const int kULScriptToRtypeSize;
extern const ULScriptRType kULScriptToRtype[];
extern const int kULScriptToDefaultLangSize;
extern const Language kULScriptToDefaultLang[];
}  // namespace CLD2

// From prop_dump.cc (separate TU: macro-heavy DFA headers clash otherwise)
int ScriptNumOfCodepoint(int cp);
int LowercaseCodepoint(int cp, unsigned char* out_utf8, int* out_len);
int InterchangeValidCodepoint(int cp);

using namespace CLD2;

static FILE* g_manifest = nullptr;
static std::string g_outdir;

static void WriteBlob(const char* name, const void* data, size_t bytes,
                      const char* dtype, size_t n_elems) {
  std::string path = g_outdir + "/" + name + ".bin";
  FILE* f = fopen(path.c_str(), "wb");
  if (!f) { perror(path.c_str()); exit(1); }
  if (bytes > 0 && fwrite(data, 1, bytes, f) != bytes) {
    perror("fwrite"); exit(1);
  }
  fclose(f);
  fprintf(g_manifest, "%s %s %zu\n", name, dtype, n_elems);
}

static void WriteStrings(const char* name, const char* const* arr, int n) {
  std::string path = g_outdir + "/" + name + ".txt";
  FILE* f = fopen(path.c_str(), "wb");
  if (!f) { perror(path.c_str()); exit(1); }
  for (int i = 0; i < n; ++i) fprintf(f, "%s\n", arr[i]);
  fclose(f);
  fprintf(g_manifest, "%s str %d\n", name, n);
}

static void WriteOneString(const char* name, const char* s) {
  std::string path = g_outdir + "/" + name + ".txt";
  FILE* f = fopen(path.c_str(), "wb");
  if (!f) { perror(path.c_str()); exit(1); }
  fprintf(f, "%s\n", s);
  fclose(f);
  fprintf(g_manifest, "%s str 1\n", name);
}

static void DumpSummary(const char* name, const CLD2TableSummary& t,
                        uint32 ind_len_override = 0) {
  char buf[128];
  snprintf(buf, sizeof(buf), "%s_buckets", name);
  WriteBlob(buf, t.kCLDTable, sizeof(uint32) * 4 * t.kCLDTableSize,
            "uint32", 4 * t.kCLDTableSize);
  // Indirect array length: entries < SizeOne are single langprobs; entries at
  // SizeOne.. are pairs located at SizeOne + 2*(i - SizeOne). Scan buckets for
  // the max indirect subscript to size the array (reference sizes are static).
  uint32 max_ind = 0;
  uint32 not_keymask = ~t.kCLDTableKeyMask;
  for (uint32 b = 0; b < t.kCLDTableSize; ++b) {
    for (int k = 0; k < 4; ++k) {
      uint32 ind = t.kCLDTable[b].keyvalue[k] & not_keymask;
      if (ind > max_ind) max_ind = ind;
    }
  }
  uint32 ind_len;
  if (ind_len_override > 0) {
    ind_len = ind_len_override;
  } else if (max_ind < t.kCLDTableSizeOne) {
    ind_len = t.kCLDTableSizeOne;  // all singles
  } else {
    ind_len = max_ind + (max_ind - t.kCLDTableSizeOne) + 2;
  }
  snprintf(buf, sizeof(buf), "%s_ind", name);
  WriteBlob(buf, t.kCLDTableInd, sizeof(uint32) * ind_len, "uint32", ind_len);
  snprintf(buf, sizeof(buf), "%s_meta", name);
  uint32 meta[4] = {t.kCLDTableSizeOne, t.kCLDTableSize, t.kCLDTableKeyMask,
                    t.kCLDTableBuildDate};
  WriteBlob(buf, meta, sizeof(meta), "uint32", 4);
  snprintf(buf, sizeof(buf), "%s_langscripts", name);
  WriteOneString(buf, t.kRecognizedLangScripts);
}

// Run the CJK unigram property DFA over every codepoint -> flat uint16 array.
static void DumpCjkUniProp() {
  const int kMaxCp = 0x110000;
  std::vector<uint8> prop(kMaxCp, 0);
  for (int cp = 0; cp < kMaxCp; ++cp) {
    if (cp >= 0xD800 && cp < 0xE000) continue;  // surrogates
    unsigned char buf[8];
    int len;
    if (cp < 0x80) { buf[0] = cp; len = 1; }
    else if (cp < 0x800) {
      buf[0] = 0xC0 | (cp >> 6); buf[1] = 0x80 | (cp & 0x3F); len = 2;
    } else if (cp < 0x10000) {
      buf[0] = 0xE0 | (cp >> 12); buf[1] = 0x80 | ((cp >> 6) & 0x3F);
      buf[2] = 0x80 | (cp & 0x3F); len = 3;
    } else {
      buf[0] = 0xF0 | (cp >> 18); buf[1] = 0x80 | ((cp >> 12) & 0x3F);
      buf[2] = 0x80 | ((cp >> 6) & 0x3F); buf[3] = 0x80 | (cp & 0x3F); len = 4;
    }
    const uint8* src = buf;
    int srclen = len;
    int v = UTF8GenericPropertyBigOneByte(&cld_generated_CjkUni_obj,
                                          &src, &srclen);
    prop[cp] = static_cast<uint8>(v);
  }
  WriteBlob("cjk_uni_prop", prop.data(), prop.size(), "uint8", prop.size());
}

// Script number per codepoint (letters/marks -> ULScript, else 0) and
// CLD2 lowercase mapping, via prop_dump.cc helpers.
static void DumpScriptAndLower() {
  const int kMaxCp = 0x110000;
  std::vector<uint8> script(kMaxCp, 0);
  std::string lower_pairs;  // stream of uint32 cp, uint32 lowered_cp
  for (int cp = 0; cp < kMaxCp; ++cp) {
    if (cp >= 0xD800 && cp < 0xE000) continue;
    int s = ScriptNumOfCodepoint(cp);
    script[cp] = static_cast<uint8>(s < 0 ? 0 : (s & 0xFF));
    unsigned char out[16];
    int outlen = 0;
    int lowered = LowercaseCodepoint(cp, out, &outlen);
    if (lowered >= 0 && lowered != cp) {
      uint32 rec[2] = {static_cast<uint32>(cp), static_cast<uint32>(lowered)};
      lower_pairs.append(reinterpret_cast<const char*>(rec), 8);
    }
  }
  WriteBlob("script_of_cp", script.data(), script.size(), "uint8",
            script.size());
  WriteBlob("lower_pairs", lower_pairs.data(), lower_pairs.size(), "uint32",
            lower_pairs.size() / 4);
}

// Interchange-validity bitmap per codepoint (utf8acceptinterchange.h via
// the reference scanner; surrogates invalid by construction).
static void DumpInterchange() {
  const int kMaxCp = 0x110000;
  std::vector<uint8> ok(kMaxCp, 0);
  for (int cp = 0; cp < kMaxCp; ++cp) {
    if (cp >= 0xD800 && cp < 0xE000) continue;
    ok[cp] = static_cast<uint8>(InterchangeValidCodepoint(cp));
  }
  WriteBlob("interchange_ok", ok.data(), ok.size(), "uint8", ok.size());
}

int main(int argc, char** argv) {
  if (argc != 2) { fprintf(stderr, "usage: %s outdir\n", argv[0]); return 1; }
  g_outdir = argv[1];
  std::string mpath = g_outdir + "/manifest.txt";
  g_manifest = fopen(mpath.c_str(), "wb");
  if (!g_manifest) { perror(mpath.c_str()); return 1; }

  DumpSummary("deltaocta", kDeltaOcta_obj);
  DumpSummary("distinctocta", kDistinctOcta_obj);
  DumpSummary("cjkdeltabi", kCjkDeltaBi_obj);
  DumpSummary("distinctbi", kDistinctBiTable_obj);
  // CjkCompat's indirect array is indexed by the unigram property class
  // (not by bucket probe), so size it from the table's own extern.
  DumpSummary("cjkcompat", kCjkCompat_obj, kCompatTableIndSize);

  WriteBlob("avg_delta_octa_score", kAvgDeltaOctaScore, sizeof(short) * 614 * 4,
            "int16", 614 * 4);
  WriteBlob("lg_prob_v2_tbl", kLgProbV2Tbl, kLgProbV2TblSize * 8, "uint8",
            kLgProbV2TblSize * 8);

  WriteStrings("lang_name", kLanguageToName, kLanguageToNameSize);
  WriteStrings("lang_code", kLanguageToCode, kLanguageToCodeSize);
  WriteStrings("lang_cname", kLanguageToCName, kLanguageToCNameSize);
  {
    // FourScripts = 4 ULScript entries per language
    std::vector<int32_t> ls(kLanguageToScriptsSize * 4);
    for (int i = 0; i < kLanguageToScriptsSize; ++i)
      for (int j = 0; j < 4; ++j)
        ls[i * 4 + j] = static_cast<int32_t>(kLanguageToScripts[i][j]);
    WriteBlob("lang_scripts", ls.data(), ls.size() * 4, "int32", ls.size());
  }
  WriteBlob("lang_to_plang", kLanguageToPLang, kLanguageToPLangSize, "uint8",
            kLanguageToPLangSize);
  WriteBlob("plang_to_lang_latn", kPLangToLanguageLatn, 256 * 2, "uint16", 256);
  WriteBlob("plang_to_lang_othr", kPLangToLanguageOthr, 256 * 2, "uint16", 256);
  WriteBlob("plang_close_set_latn", kPLangToCloseSetLatn, 256, "uint8", 256);
  WriteBlob("plang_close_set_othr", kPLangToCloseSetOthr, 256, "uint8", 256);

  WriteStrings("ulscript_name", kULScriptToName, kULScriptToNameSize);
  WriteStrings("ulscript_code", kULScriptToCode, kULScriptToCodeSize);
  {
    std::vector<int32_t> rt(kULScriptToRtypeSize);
    for (int i = 0; i < kULScriptToRtypeSize; ++i)
      rt[i] = static_cast<int32_t>(kULScriptToRtype[i]);
    WriteBlob("ulscript_rtype", rt.data(), rt.size() * 4, "int32", rt.size());
  }
  {
    std::vector<int32_t> dl(kULScriptToDefaultLangSize);
    for (int i = 0; i < kULScriptToDefaultLangSize; ++i)
      dl[i] = static_cast<int32_t>(kULScriptToDefaultLang[i]);
    WriteBlob("ulscript_default_lang", dl.data(), dl.size() * 4, "int32",
              dl.size());
  }

  DumpCjkUniProp();
  DumpScriptAndLower();
  DumpInterchange();

  fclose(g_manifest);
  fprintf(stderr, "extracted tables to %s\n", g_outdir.c_str());
  return 0;
}
