"""Kernel-mode parity fuzz: every LDT_KERNEL path is bit-identical.

ops/kernels.py ships four device programs for the same math — the
reference XLA scorer (ops/score.py), the quantized fused XLA program,
the lax.scan memory-floor oracle, and the Pallas kernel (exercised here
under the interpreter; the Mosaic lowering runs the identical kernel
body on TPU). The contract is BIT-identity of the packed output words,
not approximate agreement, so the tests compare raw u32 outputs over
adversarial synthetic grids the native packer would rarely emit: empty
chunks, fully fat K=256 rows, hint-window slots at and above HINT_BASE,
whack tables present and absent, every ULScript branch of _lscript4,
decode rows at and past the 240-row clamp, and chunk totes pushed over
the s1 = 0x3FFF clip (via a doctored qprob table — real tables cannot
reach the clip, which is exactly why the boundary needs a fuzz).

Engine-level closure: an engine constructed under each LDT_KERNEL value
answers identically to the scalar oracle on real text.
"""
from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from language_detector_tpu.ops import kernels
from language_detector_tpu.ops.score import (HINT_BASE, score_chunks,
                                             score_chunks_full)

H_WINDOW = 64          # hint_lp window size for synthetic wires
N_WHACK = 5            # whack table rows


@pytest.fixture(scope="module")
def eng():
    from language_detector_tpu.models.ngram import NgramBatchEngine
    return NgramBatchEngine()


def _langprob(rng, n, hi_rows=True):
    """Random langprob u32s: row byte spans the FULL 0..255 range when
    hi_rows (rows >= 240 exercise the clamp-row replication in
    lg_prob3_pad vs XLA's clipped gather), pslangs over 0..255 with a
    bias toward 0 (the 'no language' plane terminator)."""
    row = rng.integers(0, 256 if hi_rows else 240, n, dtype=np.uint32)
    ps = rng.integers(0, 256, (n, 3), dtype=np.uint32)
    ps[rng.random((n, 3)) < 0.3] = 0
    return (row | (ps[:, 0] << 8) | (ps[:, 1] << 16)
            | (ps[:, 2] << 24)).astype(np.uint32)


def _wire(rng, G, K, hint_frac=0.0, whack=True, empty_frac=0.0,
          scripts=(1, 3, 6, 9), cat_n=4096):
    """Synthetic chunk-major flat wire (the pack_chunks_native layout)."""
    cnsl = rng.integers(0, min(K, 255) + 1, G).astype(np.int64)
    if empty_frac:
        cnsl[rng.random(G) < empty_frac] = 0
    N = max(1, int(cnsl.sum()))
    idx = rng.integers(0, cat_n, N).astype(np.uint16)
    if hint_frac:
        hints = rng.random(N) < hint_frac
        idx[hints] = (HINT_BASE
                      + rng.integers(0, H_WINDOW, int(hints.sum()))
                      ).astype(np.uint16)
    cbytes = rng.integers(0, 1500, G).astype(np.uint32)
    grams = rng.integers(0, 600, G).astype(np.uint32)
    side = rng.integers(0, 2, G).astype(np.uint32)
    real = rng.integers(0, 2, G).astype(np.uint32)
    cmeta = (cbytes | (grams << 16) | (side << 28)
             | (real << 29)).astype(np.uint32)
    if whack:
        cwhack = rng.integers(0, N_WHACK, G).astype(np.uint16)
    else:
        cwhack = np.zeros(1, np.uint16)     # the dropped-gather dummy
    return {
        "idx": idx,
        "cnsl": cnsl.astype(np.uint8).reshape(1, G),
        "cmeta": cmeta,
        "cscript": rng.choice(np.array(scripts, np.uint8), G),
        "cwhack": cwhack,
        "hint_lp": _langprob(rng, H_WINDOW),
        "whack_tbl": (rng.random((N_WHACK, 2, 256)) < 0.1
                      ).astype(np.uint8),
        "k_iota": np.arange(K, dtype=np.uint8),
    }


def _assert_all_modes_equal(dt, wire, interpret_pallas=True):
    """word1 AND the full [G, 2] output byte-identical across modes."""
    ref = np.asarray(score_chunks(dt, wire))
    reff = np.asarray(score_chunks_full(dt, wire))
    assert np.array_equal(reff[:, 0], ref)      # full embeds word1
    modes = {
        "fused": (kernels.score_chunks_fused,
                  kernels.score_chunks_fused_full),
        "lax": (kernels.score_chunks_lax,
                kernels.score_chunks_lax_full),
    }
    if interpret_pallas:
        ps, _, pf = kernels._pallas_score_fns(interpret=True)
        modes["pallas-interpret"] = (ps, pf)
    for name, (score, full) in modes.items():
        got = np.asarray(score(dt, wire))
        assert np.array_equal(got, ref), \
            f"{name} word1 diverges at rows {np.flatnonzero(got != ref)[:8]}"
        gotf = np.asarray(full(dt, wire))
        assert np.array_equal(gotf, reff), \
            f"{name} full output diverges"
    return ref


def test_parity_randomized_grids(eng):
    """Mixed everything: hints, whacks, empties, all scripts, clamp
    rows — several seeds, one bucket shape (keeps jit cache warm)."""
    for seed in range(4):
        rng = np.random.default_rng(20260805 + seed)
        wire = _wire(rng, G=24, K=24, hint_frac=0.15, whack=True,
                     empty_frac=0.1)
        _assert_all_modes_equal(eng.dt, wire)


def test_parity_empty_chunks(eng):
    """All-empty grid: cnsl = 0 everywhere, idx a single pad slot."""
    rng = np.random.default_rng(7)
    wire = _wire(rng, G=16, K=8)
    wire["cnsl"][:] = 0
    wire["idx"] = wire["idx"][:1]
    _assert_all_modes_equal(eng.dt, wire)


def test_parity_fat_rows_k256(eng):
    """The fattest legal rows: K = 256, every chunk at the 255-slot
    cnsl ceiling (the widest tile the Pallas kernel ever sees)."""
    rng = np.random.default_rng(11)
    wire = _wire(rng, G=8, K=256, hint_frac=0.1, whack=True)
    wire["cnsl"][:] = 255
    wire["idx"] = rng.integers(0, 4096, 8 * 255).astype(np.uint16)
    _assert_all_modes_equal(eng.dt, wire)


def test_parity_hint_window(eng):
    """Every slot addresses the hint window (idx >= HINT_BASE),
    including the window's last element."""
    rng = np.random.default_rng(13)
    wire = _wire(rng, G=12, K=16, hint_frac=1.0, whack=False)
    wire["idx"][0] = HINT_BASE + H_WINDOW - 1
    _assert_all_modes_equal(eng.dt, wire)


def test_parity_whack_absent_dummy(eng):
    """Hint-free batches ship a 1-wide cwhack dummy: the whack gather
    must drop out identically in every mode."""
    rng = np.random.default_rng(17)
    wire = _wire(rng, G=12, K=16, whack=False)
    assert wire["cwhack"].shape == (1,)
    _assert_all_modes_equal(eng.dt, wire)


def test_parity_each_script(eng):
    """One grid per ULScript branch of _lscript4 (Latn=1, Hani=3,
    Arab=6, other=9): the expected-score column select."""
    for script in (1, 3, 6, 9):
        rng = np.random.default_rng(100 + script)
        wire = _wire(rng, G=12, K=16, scripts=(script,), whack=True)
        _assert_all_modes_equal(eng.dt, wire)


def _doctored_dt(dt):
    """A qprob table whose rows 100/101 carry qprobs 255/63 — enough to
    push a chunk tote past the s1 clip (real tables max out at 12 and
    can never reach it). Bypasses _validate_qprobs deliberately; the
    i16 bound still holds (the test wires keep hits x 255 < 32767)."""
    lg3 = np.asarray(dt.lg_prob3).copy()
    lg3[100] = 255
    lg3[101] = 63
    pad = np.empty((256, 3), np.uint8)
    pad[:len(lg3)] = lg3
    pad[len(lg3):] = lg3[-1]
    import jax.numpy as jnp
    return dataclasses.replace(dt, lg_prob3=jnp.asarray(lg3),
                               lg_prob3_pad=jnp.asarray(pad))


def test_parity_s1_clip_boundary(eng):
    """Chunk totes straddling s1's 14-bit clip: 25500 (clipped), 16383
    (exactly 0x3FFF, unclipped), 16320 (under). All modes agree AND the
    clip really engaged — guarding against a mode that clips early or
    accumulates in a type that wraps before the clip."""
    dt = _doctored_dt(eng.dt)
    lang = 37
    mk = lambda row, n: np.full(n, row | (lang << 8), np.uint32)  # noqa: E731
    rows = [np.concatenate([mk(100, 100), np.zeros(28, np.uint32)]),
            np.concatenate([mk(100, 64), mk(101, 1),
                            np.zeros(63, np.uint32)]),
            np.concatenate([mk(100, 64), np.zeros(64, np.uint32)])]
    hint_lp = np.concatenate(rows)          # 3 x 128 crafted slots
    G, K = 3, 128
    wire = {
        "idx": (HINT_BASE + np.arange(3 * K)).astype(np.uint16),
        "cnsl": np.full((1, G), K, np.uint8).reshape(1, G),
        "cmeta": np.full(G, 500 | (100 << 16) | (1 << 29), np.uint32),
        "cscript": np.full(G, 1, np.uint8),
        "cwhack": np.zeros(1, np.uint16),
        "hint_lp": hint_lp,
        "whack_tbl": np.zeros((1, 2, 256), np.uint8),
        "k_iota": np.arange(K, dtype=np.uint8),
    }
    ref = _assert_all_modes_equal(dt, wire)
    s1 = (ref >> 10) & 0x3FFF
    assert list(s1) == [0x3FFF, 0x3FFF, 16320]


# -- engine-level closure ----------------------------------------------------


def _answers(engine, texts):
    return [(r.summary_lang, tuple(r.language3), tuple(r.percent3),
             tuple(r.normalized_score3), r.is_reliable)
            for r in engine.detect_batch(texts)]


def test_engine_modes_match_scalar(eng, monkeypatch):
    """An engine built under each LDT_KERNEL value answers identically
    to the scalar oracle; the resolved mode is surfaced in
    pipeline_stats (the /debug/vars seam ci.sh asserts on)."""
    from language_detector_tpu.engine_scalar import detect_scalar
    from language_detector_tpu.models.ngram import NgramBatchEngine
    texts = [
        "hello world this is an english sentence about detection",
        "bonjour le monde ceci est une phrase en francais",
        "", "a",
        "это русское предложение о языках и обнаружении",
        "これは日本語の文章ですよろしくお願いします",
    ]
    want = [(r.summary_lang, tuple(r.language3), tuple(r.percent3),
             tuple(r.normalized_score3), r.is_reliable)
            for r in (detect_scalar(t, eng.tables, eng.reg)
                      for t in texts)]
    expect_mode = {"xla": "xla", "fused": "fused", "lax": "lax",
                   "auto": ("pallas", "fused")}
    for knob, resolved in expect_mode.items():
        monkeypatch.setenv("LDT_KERNEL", knob)
        e = NgramBatchEngine()
        stats = e.pipeline_stats()
        assert stats["kernel"] in (
            resolved if isinstance(resolved, tuple) else (resolved,))
        assert stats["kernel_requested"] == knob
        assert stats["kernel_reason"]
        assert _answers(e, texts) == want, f"LDT_KERNEL={knob}"


def test_engine_pallas_interpret_matches_scalar(eng, monkeypatch):
    """LDT_KERNEL=pallas off-TPU degrades to fused by default, and runs
    the actual kernel body under LDT_KERNEL_INTERPRET=1 — both must
    still answer like the scalar oracle."""
    import jax

    from language_detector_tpu.models.ngram import NgramBatchEngine
    monkeypatch.setenv("LDT_KERNEL", "pallas")
    e = NgramBatchEngine()
    if jax.default_backend() == "tpu":
        assert e.pipeline_stats()["kernel"] == "pallas"
        base = _answers(e, ["hola mundo", "hello there"])
        assert base == _answers(eng, ["hola mundo", "hello there"])
        return
    assert e.pipeline_stats()["kernel"] == "fused"
    assert "Mosaic" in e.pipeline_stats()["kernel_reason"] or \
        "no Pallas" in e.pipeline_stats()["kernel_reason"]
    texts = ["hola mundo como estas hoy", "hello there my old friend"]
    assert _answers(e, texts) == _answers(eng, texts)
    if kernels._HAVE_PALLAS:
        monkeypatch.setenv("LDT_KERNEL_INTERPRET", "1")
        ei = NgramBatchEngine()
        assert ei.pipeline_stats()["kernel"] == "pallas-interpret"
        assert _answers(ei, texts) == _answers(eng, texts)


def test_unknown_kernel_value_degrades_to_auto(monkeypatch, caplog):
    monkeypatch.setenv("LDT_KERNEL", "warp-drive")
    sel = kernels.select_kernel()
    assert sel.requested == "auto"
    assert sel.mode in ("pallas", "fused")
