"""merge_longdoc_chunks keep_spans regression: nothing discarded.

Before the span work, the long-doc merge threw away the per-sub-doc
verdict rows after concatenating them into the virtual document. The
LDT_SPANS lane needs those rows back (each sub-doc slice replays the
epilogue for its span verdict), so keep_spans=True returns span_rows:
one (row_start, n_chunks, text_bytes) record per sub-document, indexing
into merged_rows. This file pins the no-waste invariant — the retained
slices are exactly the source rows, and their counts and byte totals
sum to the merged document's totals — and that keep_spans=False is
byte-for-byte the merge it always was.
"""
from __future__ import annotations

import numpy as np
import pytest

from language_detector_tpu import native
from language_detector_tpu.engine_scalar import split_for_spans
from language_detector_tpu.registry import registry
from language_detector_tpu.result_vector import merge_longdoc_chunks
from language_detector_tpu.tables import load_tables


@pytest.fixture(scope="module")
def tables():
    return load_tables()


def _split_pack(texts, tables, budget=8):
    """texts -> (sub-doc ChunkBatch, groups, synthetic rows). A tiny
    budget forces real multi-sub-doc groups; rows are a distinct-value
    ramp so slice placement is pinned exactly, not just by shape."""
    subs_all, groups = [], []
    for t in texts:
        subs, _ = split_for_spans(t, tables, budget)
        groups.append((len(subs_all), len(subs)))
        subs_all.extend(subs)
    cb = native.pack_chunks_native(subs_all, tables, registry)
    G = int(cb.n_chunks.sum())
    rows = np.arange(max(G, 1) * 5, dtype=np.int32).reshape(-1, 5)
    return cb, groups, rows


TEXTS = [
    ("hello world this is a plainly english document " * 6 +
     "это русское предложение о языках и текстах " * 6),
    ("bonjour le monde ceci est une phrase en francais " * 5 +
     "これは日本語の文章ですよろしくお願いします" * 5 +
     "and back to english words for the tail of the document " * 4),
    "short single-span doc",
]


def test_span_rows_sum_to_merged_totals(tables):
    """The headline invariant: per-group span records partition the
    merged document's chunk rows and byte total exactly."""
    cb, groups, rows = _split_pack(TEXTS, tables)
    assert any(n > 1 for _, n in groups)  # the budget actually split
    mrows, mcb, span_rows = merge_longdoc_chunks(rows, cb, groups,
                                                 keep_spans=True)
    assert len(span_rows) == len(groups)
    for j, (s, n) in enumerate(groups):
        recs = span_rows[j]
        assert len(recs) == n  # one record per sub-document
        assert sum(nc for _, nc, _ in recs) == int(mcb.n_chunks[j])
        assert sum(tb for _, _, tb in recs) == int(mcb.text_bytes[j])
        assert int(mcb.text_bytes[j]) == \
            int(cb.text_bytes[s:s + n].sum())
        # records are contiguous from the document's first merged row
        pos = int(mcb.doc_chunk_start[j])
        for rs, nc, _ in recs:
            assert rs == pos
            pos += nc


def test_retained_slices_equal_source_rows(tables):
    """Each retained slice of merged_rows is bit-identical to the
    sub-document's original row range — the rows the merge used to
    discard."""
    cb, groups, rows = _split_pack(TEXTS, tables)
    mrows, mcb, span_rows = merge_longdoc_chunks(rows, cb, groups,
                                                 keep_spans=True)
    for j, (s, n) in enumerate(groups):
        for k, (rs, nc, tb) in enumerate(span_rows[j]):
            i = s + k
            g0 = int(cb.doc_chunk_start[i])
            assert nc == int(cb.n_chunks[i])
            assert tb == int(cb.text_bytes[i])
            np.testing.assert_array_equal(mrows[rs:rs + nc],
                                          rows[g0:g0 + nc])


def test_keep_spans_false_unchanged(tables):
    """keep_spans=False returns the 2-tuple shape with the identical
    merge — the flag may not perturb the long-doc lane."""
    cb, groups, rows = _split_pack(TEXTS, tables)
    out0 = merge_longdoc_chunks(rows, cb, groups)
    assert len(out0) == 2
    mrows0, mcb0 = out0
    mrows1, mcb1, _ = merge_longdoc_chunks(rows, cb, groups,
                                           keep_spans=True)
    np.testing.assert_array_equal(mrows0, mrows1)
    np.testing.assert_array_equal(mcb0.n_chunks, mcb1.n_chunks)
    np.testing.assert_array_equal(mcb0.text_bytes, mcb1.text_bytes)
    np.testing.assert_array_equal(mcb0.doc_chunk_start,
                                  mcb1.doc_chunk_start)
    np.testing.assert_array_equal(mcb0.direct_adds, mcb1.direct_adds)
    np.testing.assert_array_equal(mcb0.fallback, mcb1.fallback)
    np.testing.assert_array_equal(mcb0.squeezed, mcb1.squeezed)
