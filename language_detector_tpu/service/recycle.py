"""Worker self-recycle: bounded memory for long-lived serving processes.

The tunneled TPU backend's platform plugin leaks ~1.2MB of host RSS per
device dispatch (characterized in docs/PERF.md — engine-side allocation
measures flat; real, non-tunneled TPU hosts are unaffected). The
reference's production mitigation is the container restart
(/root/reference/Dockerfile); this module makes that story operational
in-process: both HTTP fronts periodically evaluate `should_recycle` and,
past a dispatch-count or RSS bound, exit cleanly with RECYCLE_EXIT_CODE
so the supervisor (service/supervisor.py, or a container restart policy)
replaces the worker without dropping the listening story.

Configuration (env, unset = feature off; declared in knobs.py):
  LDT_MAX_DISPATCHES  recycle after this many engine batch dispatches
  LDT_MAX_RSS_MB      recycle when process RSS exceeds this many MB
"""
from __future__ import annotations

from .. import knobs

# Distinct from error exits so supervisors/restart policies can tell a
# planned recycle from a crash (and bare `docker restart: on-failure`
# still catches both).
RECYCLE_EXIT_CODE = 77


def check_interval_sec() -> float:
    """Watcher period (LDT_RECYCLE_CHECK_SEC env override, for tests)."""
    v = knobs.get_float("LDT_RECYCLE_CHECK_SEC")
    return max(v if v is not None else 5.0, 0.05)


def rss_mb() -> float:
    """Resident set size of this process in MB (0.0 if unreadable)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except (OSError, ValueError, IndexError):
        pass
    return 0.0


def limits_from_env() -> tuple[int | None, float | None]:
    """(max_dispatches, max_rss_mb) from the environment; None = off.
    Bound-knob semantics (knobs.py): unset, non-positive, or mistyped
    (loud warning) all answer None."""
    return (knobs.get_int("LDT_MAX_DISPATCHES"),
            knobs.get_float("LDT_MAX_RSS_MB"))


def should_recycle(dispatches: int,
                   max_dispatches: int | None,
                   max_rss_mb: float | None,
                   current_rss_mb: float | None = None) -> str | None:
    """Reason string when a bound is exceeded, else None."""
    if max_dispatches is not None and dispatches >= max_dispatches:
        return (f"dispatch bound reached ({dispatches} >= "
                f"{max_dispatches})")
    if max_rss_mb is not None:
        rss = rss_mb() if current_rss_mb is None else current_rss_mb
        if rss >= max_rss_mb:
            return f"RSS bound reached ({rss:.0f}MB >= {max_rss_mb:.0f}MB)"
    return None
