"""Worker self-recycle + supervisor (service/recycle.py, supervisor.py).

The tunneled TPU backend leaks host RSS per dispatch (docs/PERF.md);
the mitigation is a planned worker exit past a dispatch/RSS bound, with
the supervisor (or a container restart policy) starting a fresh one.
These tests pin the bound logic, the supervisor's restart/propagate
behavior, and the threaded front's end-to-end recycle exit.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

from language_detector_tpu.service.recycle import (  # noqa: E402
    RECYCLE_EXIT_CODE, limits_from_env, rss_mb, should_recycle)


def test_should_recycle_bounds():
    assert should_recycle(10, None, None) is None
    assert should_recycle(10, 11, None) is None
    assert "dispatch bound" in should_recycle(11, 11, None)
    assert should_recycle(0, None, 100.0, current_rss_mb=50.0) is None
    assert "RSS bound" in should_recycle(0, None, 100.0,
                                         current_rss_mb=150.0)


def test_rss_and_env_limits(monkeypatch):
    assert rss_mb() > 1.0  # this test process certainly exceeds 1MB
    monkeypatch.delenv("LDT_MAX_DISPATCHES", raising=False)
    monkeypatch.delenv("LDT_MAX_RSS_MB", raising=False)
    assert limits_from_env() == (None, None)
    monkeypatch.setenv("LDT_MAX_DISPATCHES", "500")
    monkeypatch.setenv("LDT_MAX_RSS_MB", "2048")
    assert limits_from_env() == (500, 2048.0)
    monkeypatch.setenv("LDT_MAX_DISPATCHES", "junk")
    monkeypatch.setenv("LDT_MAX_RSS_MB", "-1")
    assert limits_from_env() == (None, None)


def test_supervisor_restarts_on_recycle_and_propagates(tmp_path):
    """The supervisor restarts the worker while it exits with
    RECYCLE_EXIT_CODE and propagates any other exit code."""
    state = tmp_path / "count"
    stub = tmp_path / "stub_worker.py"
    stub.write_text(
        "import pathlib, sys\n"
        f"p = pathlib.Path({str(state)!r})\n"
        "n = int(p.read_text()) if p.exists() else 0\n"
        "p.write_text(str(n + 1))\n"
        f"sys.exit({RECYCLE_EXIT_CODE} if n < 2 else 3)\n")
    r = subprocess.run(
        [sys.executable, "-m", "language_detector_tpu.service.supervisor",
         "stub_worker"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
        env={**os.environ,
             "PYTHONPATH": f"{tmp_path}:{REPO}:"
                           f"{os.environ.get('PYTHONPATH', '')}"})
    assert r.returncode == 3  # third run's exit propagated
    assert int(state.read_text()) == 3  # ran exactly 3 generations
    assert r.stdout.count("worker recycled") == 2


def test_threaded_server_recycles_end_to_end():
    """Drive the real threaded front (module entry) with
    LDT_MAX_DISPATCHES=1: one detection flush must trip the watcher
    into a clean RECYCLE_EXIT_CODE exit (the supervisor's restart
    signal), after serve_forever returns so in-flight work finishes."""
    env = {**os.environ, "LISTEN_PORT": "0", "PROMETHEUS_PORT": "0",
           "LDT_MAX_DISPATCHES": "1", "LDT_RECYCLE_CHECK_SEC": "0.2",
           # APPEND to PYTHONPATH: replacing it would drop the jax
           # platform plugin's path on hosts that ship one there, and
           # the child would silently fall back to the scalar engine
           # (no dispatches -> no recycle)
           "PYTHONPATH": f"{REPO}:{os.environ.get('PYTHONPATH', '')}"}
    p = subprocess.Popen(
        [sys.executable, "-m", "language_detector_tpu.service.server"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        port = None
        deadline = time.time() + 120
        while time.time() < deadline:
            line = p.stdout.readline()
            if "listening on" in line:
                msg = json.loads(line)["msg"]
                port = int(msg.split(":")[1].split(",")[0])
                break
        assert port, "server never reported its port"
        # > TINY_BATCH_C_PATH docs: a tiny flush rides the all-C path
        # and correctly burns NO recycle budget (the watcher meters
        # device_dispatches — the leak is per DEVICE dispatch)
        docs = [{"text": f"bonjour le monde numero {i}"}
                for i in range(100)]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/",
            data=json.dumps({"request": docs}).encode(),
            headers={"Content-Type": "application/json"})
        body = urllib.request.urlopen(req, timeout=90).read()
        assert body.count(b"iso6391code") == 100
        try:
            rc = p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate(timeout=10)
            raise AssertionError(
                f"worker did not recycle; stdout={out[-400:]!r} "
                f"stderr={err[-400:]!r}")
        assert rc == RECYCLE_EXIT_CODE, (rc, p.stderr.read()[-500:])
    finally:
        if p.poll() is None:
            p.kill()


def _spawn_front(module: str, env_extra: dict = None):
    env = {**os.environ, "LISTEN_PORT": "0", "PROMETHEUS_PORT": "0",
           "PYTHONPATH": f"{REPO}:{os.environ.get('PYTHONPATH', '')}"}
    env.update(env_extra or {})
    p = subprocess.Popen(
        [sys.executable, "-m", f"language_detector_tpu.service.{module}"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    port = None
    deadline = time.time() + 120
    while time.time() < deadline:
        line = p.stdout.readline()
        if "listening on" in line:
            msg = json.loads(line)["msg"]
            port = int(msg.split(":")[1].split(",")[0])
            break
    assert port, f"{module} never reported its port"
    return p, port


def _post_docs(port: int, n: int, results: list, tag: str):
    docs = [{"text": f"bonjour le monde numero {i}"} for i in range(n)]
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"request": docs}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        body = urllib.request.urlopen(req, timeout=90).read()
        results.append((tag, body.count(b"iso6391code")))
    except Exception as e:  # noqa: BLE001 - recorded for the assert
        results.append((tag, repr(e)))


def _assert_inflight_survives_recycle(module: str):
    """Regression for the recycle handoff gap: a full-size flush still
    in flight when the dispatch watcher trips must complete (drained,
    not guillotined) before the worker exits RECYCLE_EXIT_CODE."""
    import threading
    p, port = _spawn_front(module, {"LDT_MAX_DISPATCHES": "1",
                                    "LDT_RECYCLE_CHECK_SEC": "0.05"})
    try:
        results: list = []
        # first request trips the watcher; the second lands while the
        # first flush is mid-device so it rides a LATER flush that is
        # in flight when shutdown starts
        t1 = threading.Thread(target=_post_docs,
                              args=(port, 100, results, "a"))
        t2 = threading.Thread(target=_post_docs,
                              args=(port, 100, results, "b"))
        t1.start()
        time.sleep(0.05)
        t2.start()
        t1.join(timeout=120)
        t2.join(timeout=120)
        assert sorted(results) == [("a", 100), ("b", 100)], results
        try:
            rc = p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate(timeout=10)
            raise AssertionError(
                f"worker did not recycle; stdout={out[-400:]!r} "
                f"stderr={err[-400:]!r}")
        assert rc == RECYCLE_EXIT_CODE, (rc, p.stderr.read()[-500:])
    finally:
        if p.poll() is None:
            p.kill()


def test_threaded_server_inflight_flush_survives_recycle():
    _assert_inflight_survives_recycle("server")


def test_aioserver_inflight_flush_survives_recycle():
    _assert_inflight_survives_recycle("aioserver")


def test_threaded_server_sigterm_drains_and_exits_zero():
    """The swap cutover's drain contract on the sync front: SIGTERM
    stops the accept loop, in-flight requests finish, exit code 0 (the
    supervisor propagates it instead of restarting)."""
    import signal
    import threading
    p, port = _spawn_front("server")
    try:
        results: list = []
        t = threading.Thread(target=_post_docs,
                             args=(port, 100, results, "a"))
        t.start()
        time.sleep(0.1)  # request in flight
        p.send_signal(signal.SIGTERM)
        t.join(timeout=120)
        assert results == [("a", 100)], results
        rc = p.wait(timeout=30)
        assert rc == 0, (rc, p.stderr.read()[-500:])
        out = p.stdout.read()
        assert "draining worker" in out
    finally:
        if p.poll() is None:
            p.kill()


def test_supervisor_forwards_sigterm(tmp_path):
    """PID-1 duty (the Dockerfile CMD): SIGTERM to the supervisor is
    forwarded to the worker, whose graceful exit code propagates —
    `docker stop` must not SIGKILL a worker mid-request."""
    import signal
    stub = tmp_path / "stub_worker.py"
    stub.write_text(
        "import signal, sys, time\n"
        "signal.signal(signal.SIGTERM, lambda *a: sys.exit(42))\n"
        "print('stub ready', flush=True)\n"
        "time.sleep(60)\n")
    p = subprocess.Popen(
        [sys.executable, "-m",
         "language_detector_tpu.service.supervisor", "stub_worker"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
        env={**os.environ,
             "PYTHONPATH": f"{tmp_path}:{REPO}:"
                           f"{os.environ.get('PYTHONPATH', '')}"})
    try:
        for line in p.stdout:  # wait until the worker installed handlers
            if "stub ready" in line:
                break
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=15) == 42
    finally:
        if p.poll() is None:
            p.kill()


def test_aioserver_recycles_end_to_end():
    """The asyncio (production) front's recycle path: a device-sized
    request trips LDT_MAX_DISPATCHES=1 and the worker exits with
    RECYCLE_EXIT_CODE even while an idle keep-alive connection is held
    open (Server.wait_closed on 3.12.1+ waits for every accepted
    connection; the watcher aborts survivors first)."""
    import socket
    env = {**os.environ, "LISTEN_PORT": "0", "PROMETHEUS_PORT": "0",
           "LDT_MAX_DISPATCHES": "1", "LDT_RECYCLE_CHECK_SEC": "0.2",
           "PYTHONPATH": f"{REPO}:{os.environ.get('PYTHONPATH', '')}"}
    p = subprocess.Popen(
        [sys.executable, "-m",
         "language_detector_tpu.service.aioserver"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    idle = None
    try:
        port = mport = None
        deadline = time.time() + 120
        while time.time() < deadline:
            line = p.stdout.readline()
            if "listening on" in line:
                msg = json.loads(line)["msg"]
                port = int(msg.split(":")[1].split(",")[0])
                mport = int(msg.rsplit(":", 1)[1])
                break
        assert port, "aioserver never reported its ports"
        # idle keep-alive socket on the metrics port (scraper scenario)
        idle = socket.create_connection(("127.0.0.1", mport), timeout=5)
        idle.sendall(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
        idle.recv(64)
        docs = [{"text": f"bonjour le monde numero {i}"}
                for i in range(100)]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/",
            data=json.dumps({"request": docs}).encode(),
            headers={"Content-Type": "application/json"})
        body = urllib.request.urlopen(req, timeout=90).read()
        assert body.count(b"iso6391code") == 100
        try:
            rc = p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate(timeout=10)
            raise AssertionError(
                f"aio worker did not recycle; stdout={out[-400:]!r} "
                f"stderr={err[-400:]!r}")
        assert rc == RECYCLE_EXIT_CODE, (rc, p.stderr.read()[-500:])
    finally:
        if idle is not None:
            idle.close()
        if p.poll() is None:
            p.kill()
