"""Anti-spam text squeezing: drop repetitive or space-heavy chunks.

Re-implements the reference's cheap predictor pipeline
(compact_lang_det_impl.cc:541-971): a 12-bit rolling-hash character
predictor drives three operations on span buffers:

  - cheap_squeeze_trigger_test: should the whole document be re-scanned
    with squeezing on? (>=25% spaces or >=67% predicted in first 256B)
  - cheap_squeeze: drop 48-byte chunks that are >=25% spaces or >=40%
    predicted, splicing at spaces.
  - cheap_rep_words: drop words with more than half their bytes predicted.

These guard the scoring tables from boilerplate/spam; they run on the host
(inherently sequential prediction state) ahead of device scoring.
"""
from __future__ import annotations

import numpy as np

PREDICTION_TABLE_SIZE = 4096      # 12-bit hash (kPredictionTableSize)
CHUNK_SIZE = 48                   # kChunksizeDefault
SPACES_THRESH_PERCENT = 25        # kSpacesThreshPercent
PREDICT_THRESH_PERCENT = 40       # kPredictThreshPercent
SPACES_TRIGGER_PERCENT = 25       # kSpacesTriggerPercent
PREDICT_TRIGGER_PERCENT = 67      # kPredictTriggerPercent
TEST_LEN = 256                    # kCheapSqueezeTestLen
TEST_THRESH = 4096                # kCheapSqueezeTestThresh


def count_predicted_bytes(buf: bytes, start: int, length: int,
                          hash_state: list, tbl: np.ndarray) -> int:
    """Bytes whose UTF-8 character was correctly predicted by the rolling
    12-bit-hash table (CountPredictedBytes, compact_lang_det_impl.cc:541)."""
    p_count = 0
    h = hash_state[0]
    i = start
    limit = start + length
    while i < limit:
        c = buf[i]
        incr = 1
        if c < 0xC0:
            pass
        elif (c & 0xE0) == 0xC0:
            c = (c << 8) | buf[i + 1]
            incr = 2
        elif (c & 0xF0) == 0xE0:
            c = (c << 16) | (buf[i + 1] << 8) | buf[i + 2]
            incr = 3
        else:
            c = (c << 24) | (buf[i + 1] << 16) | (buf[i + 2] << 8) | buf[i + 3]
            incr = 4
        i += incr
        if tbl[h] == c:
            p_count += incr
        tbl[h] = c
        h = ((h << 4) ^ c) & 0xFFF
    hash_state[0] = h
    return p_count


def count_spaces4(buf: bytes, start: int, length: int) -> int:
    """Space count over 4-byte groups, ignoring the odd tail
    (CountSpaces4, compact_lang_det_impl.cc:586)."""
    n = length & ~3
    a = np.frombuffer(buf[start:start + n], dtype=np.uint8)
    return int((a == 0x20).sum())


def cheap_squeeze_trigger_test(buf: bytes, src_len: int,
                               testsize: int = TEST_LEN) -> bool:
    """CheapSqueezeTriggerTest (compact_lang_det_impl.cc:952)."""
    if src_len < testsize:
        return False
    space_thresh = (testsize * SPACES_TRIGGER_PERCENT) // 100
    predict_thresh = (testsize * PREDICT_TRIGGER_PERCENT) // 100
    if count_spaces4(buf, 0, testsize) >= space_thresh:
        return True
    tbl = np.zeros(PREDICTION_TABLE_SIZE, dtype=np.int64)
    return count_predicted_bytes(buf, 0, testsize, [0], tbl) >= predict_thresh


MAX_SPACE_SCAN = 32  # kMaxSpaceScan


def _backscan_to_space(b: bytearray, dst: int) -> int:
    """BackscanToSpace (compact_lang_det_impl.cc:491-503)."""
    limit = min(dst, MAX_SPACE_SCAN)
    for n in range(limit):
        if b[dst - n - 1] == 0x20:
            return n
    for n in range(limit):
        if (b[dst - n] & 0xC0) != 0x80:
            return n
    return 0


def _forwardscan_to_space(b: bytearray, src: int, limit: int) -> int:
    """ForwardscanToSpace (compact_lang_det_impl.cc:509-521)."""
    limit = min(limit, MAX_SPACE_SCAN)
    for n in range(limit):
        if b[src + n] == 0x20:
            return n + 1
    for n in range(limit):
        if (b[src + n] & 0xC0) != 0x80:
            return n
    return 0


def cheap_squeeze(buf: bytes, src_len: int,
                  chunksize: int = CHUNK_SIZE) -> bytes:
    """Drop space-heavy / well-predicted chunks in place
    (CheapSqueezeInplace, compact_lang_det_impl.cc:785-865).

    buf must extend at least 4 bytes past src_len (span tail pad).
    Returns the squeezed text bytes (length == new text_bytes). Pointer
    arithmetic mirrors the reference's in-place dst<=src compaction so the
    no-space backscan fallback reads the same bytes."""
    b = bytearray(buf[:src_len + 4])
    hash_state = [0]
    tbl = np.zeros(PREDICTION_TABLE_SIZE, dtype=np.int64)
    space_thresh = (chunksize * SPACES_THRESH_PERCENT) // 100
    predict_thresh = (chunksize * PREDICT_THRESH_PERCENT) // 100
    skipping = False
    src = 0
    dst = 0
    while src < src_len:
        length = min(chunksize, src_len - src)
        while (b[src + length] & 0xC0) == 0x80:  # UTF-8 boundary
            length += 1
        space_n = count_spaces4(b, src, length)
        predb_n = count_predicted_bytes(b, src, length, hash_state, tbl)
        if space_n >= space_thresh or predb_n >= predict_thresh:
            if not skipping:
                # keep->skip transition: back up to a space
                dst -= _backscan_to_space(b, dst)
                if dst == 0:
                    b[0] = 0x20  # force a leading space
                    dst = 1
                skipping = True
        else:
            take_from = src
            take_len = length
            if skipping:
                # skip->keep transition: forward to a space
                n = _forwardscan_to_space(b, src, length)
                take_from += n
                take_len -= n
                skipping = False
            if take_len > 0:
                b[dst:dst + take_len] = b[take_from:take_from + take_len]
                dst += take_len
        src += length
    return bytes(b[:dst])


def cheap_squeeze_overwrite(buf: bytes, src_len: int,
                            chunksize: int = CHUNK_SIZE) -> bytes:
    """Length-preserving squeeze: overwrite dropped chunks with '.' instead
    of compacting, so span-buffer offsets still map back to the original
    text for the result-chunk vector (CheapSqueezeInplaceOverwrite,
    compact_lang_det_impl.cc:869-940)."""
    b = bytearray(buf[:src_len + 4])
    hash_state = [0]
    tbl = np.zeros(PREDICTION_TABLE_SIZE, dtype=np.int64)
    space_thresh = (chunksize * SPACES_THRESH_PERCENT) // 100
    predict_thresh = (chunksize * PREDICT_THRESH_PERCENT) // 100
    skipping = False
    src = 1  # always keep the leading space
    while src < src_len:
        length = min(chunksize, src_len - src)
        while (b[src + length] & 0xC0) == 0x80:  # UTF-8 boundary
            length += 1
        space_n = count_spaces4(b, src, length)
        predb_n = count_predicted_bytes(b, src, length, hash_state, tbl)
        if space_n >= space_thresh or predb_n >= predict_thresh:
            if not skipping:
                # keep->skip transition: dot back to a space
                n = _backscan_to_space(b, src)
                b[src - n:src] = b"." * n
                skipping = True
            b[src:src + length] = b"." * length
            b[src + length - 1] = 0x20
        elif skipping:
            # skip->keep transition: dot forward to a space
            n = _forwardscan_to_space(b, src, length)
            if n > 1:
                b[src:src + n - 1] = b"." * (n - 1)
            skipping = False
        src += length
    return bytes(b[:src_len])


def cheap_rep_words(buf: bytes, src_len: int, hash_state: list,
                    tbl: np.ndarray) -> bytes:
    """Drop words with more than half their bytes predicted
    (CheapRepWordsInplace, compact_lang_det_impl.cc:610-692). The hash and
    prediction table persist across spans of one document."""
    dst = bytearray()
    h = hash_state[0]
    word_dst = 0           # index in dst of current word start
    good_predict = 0
    word_len = 0
    src = 0
    while src < src_len:
        c = buf[src]
        dst.append(c)
        if c == 0x20:
            if good_predict * 2 > word_len:
                del dst[word_dst:]
            word_dst = len(dst)
            good_predict = 0
            word_len = 0
        incr = 1
        if c < 0xC0:
            pass
        elif (c & 0xE0) == 0xC0:
            dst.append(buf[src + 1])
            c = (c << 8) | buf[src + 1]
            incr = 2
        elif (c & 0xF0) == 0xE0:
            dst.extend(buf[src + 1:src + 3])
            c = (c << 16) | (buf[src + 1] << 8) | buf[src + 2]
            incr = 3
        else:
            dst.extend(buf[src + 1:src + 4])
            c = ((c << 24) | (buf[src + 1] << 16) | (buf[src + 2] << 8) |
                 buf[src + 3])
            incr = 4
        src += incr
        word_len += incr
        if tbl[h] == c:
            good_predict += incr
        tbl[h] = c
        h = ((h << 4) ^ c) & 0xFFF
    hash_state[0] = h
    return bytes(dst)


def cheap_rep_words_overwrite(buf: bytes, src_len: int, hash_state: list,
                              tbl: np.ndarray) -> bytes:
    """Length-preserving variant: overwrite well-predicted words with '.'
    so result-vector offset maps survive (CheapRepWordsInplaceOverwrite,
    compact_lang_det_impl.cc:696-770)."""
    b = bytearray(buf[:src_len])
    h = hash_state[0]
    word_start = 0
    good_predict = 0
    word_len = 0
    src = 0
    while src < src_len:
        c = b[src]
        if c == 0x20:
            if good_predict * 2 > word_len:
                b[word_start:src] = b"." * (src - word_start)
            word_start = src + 1
            good_predict = 0
            word_len = 0
        incr = 1
        if c < 0xC0:
            pass
        elif (c & 0xE0) == 0xC0:
            c = (c << 8) | b[src + 1]
            incr = 2
        elif (c & 0xF0) == 0xE0:
            c = (c << 16) | (b[src + 1] << 8) | b[src + 2]
            incr = 3
        else:
            c = ((c << 24) | (b[src + 1] << 16) | (b[src + 2] << 8) |
                 b[src + 3])
            incr = 4
        src += incr
        word_len += incr
        if tbl[h] == c:
            good_predict += incr
        tbl[h] = c
        h = ((h << 4) ^ c) & 0xFFF
    hash_state[0] = h
    return bytes(b)
