"""Scalar reference engine: the complete detection pipeline on the host.

This is the behavioral specification for the batched TPU path (ops/ and
models/ngram.py): a faithful, readable re-implementation of the reference
scoring pipeline over the same table artifact, validated hit-for-hit against
the compiled oracle (tools/oracle). The TPU engine must agree with this
engine, and this engine must agree with the oracle.

Pipeline (reference call stack, compact_lang_det_impl.cc:1707-2106):
  segment -> per-span hits -> linearize -> chunk -> chunk totes ->
  doc tote -> close-pair refinement -> extract top-3 -> decision gate ->
  [recurse with stricter flags] -> remove unreliable -> summary language.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .preprocess.grams import (DUAL_TABLE_FLAG, HitList, get_bi_hits,
                               get_octa_hits, get_quad_hits, get_uni_hits)
from .preprocess.segment import ScriptSpan, segment_text
from .preprocess.squeeze import (PREDICTION_TABLE_SIZE, TEST_THRESH,
                                 cheap_rep_words, cheap_rep_words_overwrite,
                                 cheap_squeeze, cheap_squeeze_overwrite,
                                 cheap_squeeze_trigger_test)
from .registry import (ENGLISH, RTYPE_CJK, RTYPE_MANY, RTYPE_NONE, RTYPE_ONE,
                       TG_UNKNOWN_LANGUAGE, ULSCRIPT_LATIN, UNKNOWN_LANGUAGE,
                       Registry, registry as default_registry)
from .tables import NgramTable, ScoringTables, load_tables

# Hit types (scoreonescriptspan.h:172-175)
UNIHIT, QUADHIT, DELTAHIT, DISTINCTHIT = 0, 1, 2, 3

# Chunk sizes (scoreonescriptspan.h:91-92)
CHUNKSIZE_QUADS = 20
CHUNKSIZE_UNIS = 50

# Flags (public compact_lang_det.h:343-350 + internal impl.h:31-38)
FLAG_SCORE_AS_QUADS = 0x0100
FLAG_BEST_EFFORT = 0x4000
FLAG_FINISH = 1
FLAG_SQUEEZE = 2
FLAG_REPEATS = 4
FLAG_TOP40 = 8
FLAG_SHORT = 16
FLAG_USE_WORDS = 64

# Decision thresholds (compact_lang_det_impl.cc:188-240, :981, :1405-1406)
GOOD_LANG1_PERCENT = 70
GOOD_LANG1AND2_PERCENT = 93
SHORT_TEXT_THRESH = 256
MIN_RELIABLE_KEEP_PERCENT = 41
NON_EN_BOILERPLATE_MIN_PERCENT = 17
NON_FIGS_BOILERPLATE_MIN_PERCENT = 20
GOOD_FIRST_MIN_PERCENT = 26
GOOD_FIRST_RELIABLE_MIN_PERCENT = 51
IGNORE_MAX_PERCENT = 20
KEEP_MIN_PERCENT = 2
GOOD_SECOND_T1T2_MIN_BYTES = 15

# Reliability model (cldutil.cc:41-44, :553-605)
MIN_GRAM_COUNT = 3
MAX_GRAM_COUNT = 16

MAX_BOOSTS = 4  # rotating distinct-word boost slots (scoreonescriptspan.h:89)


# ---------------------------------------------------------------------------
# langprob decode and totes
# ---------------------------------------------------------------------------

def decode_langprob(lp: int, lg_prob: np.ndarray) -> list[tuple[int, int]]:
    """uint32 langprob -> up to 3 (pslang, qprob) pairs (cldutil.cc:128)."""
    entry = lg_prob[lp & 0xFF]
    out = []
    for j, shift in enumerate((8, 16, 24)):
        pslang = (lp >> shift) & 0xFF
        if pslang > 0:
            out.append((pslang, int(entry[5 + j])))
    return out


class Tote:
    """Per-chunk accumulator over 256 per-script language slots (tote.h:36).

    Tracks the in-use mask of 4-slot groups: top-key scans only consider
    touched groups, which matters for zero-score runner-up slots
    (tote.cc:52-99)."""

    def __init__(self):
        self.score = np.zeros(256, dtype=np.int64)
        self.group_used = np.zeros(64, dtype=bool)
        self.score_count = 0

    def reinit(self):
        self.score[:] = 0
        self.group_used[:] = False
        self.score_count = 0

    def add(self, pslang: int, qprob: int):
        self.group_used[pslang >> 2] = True
        self.score[pslang] += qprob

    def top_three_keys(self) -> list[int]:
        """Top-3 in-use slots, lower index wins ties (tote.cc:65-99)."""
        idx = np.flatnonzero(np.repeat(self.group_used, 4))
        if len(idx) == 0:
            return [-1, -1, -1]
        s = self.score[idx]
        order = np.lexsort((idx, -s))
        picks = [int(idx[order[i]]) for i in range(min(3, len(idx)))]
        while len(picks) < 3:
            picks.append(-1)
        return picks


class DocTote:
    """24-slot 3-way set-associative document accumulator (tote.cc:127)."""

    UNUSED = 0xFFFF
    MAX = 24

    def __init__(self):
        self.key = np.full(self.MAX, self.UNUSED, dtype=np.int64)
        self.value = np.zeros(self.MAX, dtype=np.int64)   # byte count
        self.score = np.zeros(self.MAX, dtype=np.int64)
        self.rel = np.zeros(self.MAX, dtype=np.int64)     # reliability*bytes

    def add(self, lang: int, nbytes: int, score: int, reliability: int):
        subs = [lang & 15, (lang & 15) ^ 8, (lang & 7) + 16]
        for s in subs:
            if self.key[s] == lang:
                self.value[s] += nbytes
                self.score[s] += score
                self.rel[s] += reliability * nbytes
                return
        for s in subs:
            if self.key[s] == self.UNUSED:
                alloc = s
                break
        else:
            alloc = min(subs, key=lambda s: self.value[s])
        self.key[alloc] = lang
        self.value[alloc] = nbytes
        self.score[alloc] = score
        self.rel[alloc] = reliability * nbytes

    def find(self, lang: int) -> int:
        hits = np.flatnonzero(self.key == lang)
        return int(hits[0]) if len(hits) else -1

    def sort(self):
        """Stable sort by decreasing byte count (tote.cc:221-250).

        The reference bubble sort swaps only when value[sub] < value[sub2],
        which preserves first-seen order on ties."""
        self.value[self.key == self.UNUSED] = -1
        order = np.argsort(-self.value, kind="stable")
        for arr in (self.key, self.value, self.score, self.rel):
            arr[:] = arr[order]


# ---------------------------------------------------------------------------
# Reliability (cldutil.cc:553-605)
# ---------------------------------------------------------------------------

def reliability_delta(value1: int, value2: int, gramcount: int) -> int:
    max_percent = 100 if gramcount >= 8 else 12 * gramcount
    thresh = min(max(MIN_GRAM_COUNT, (gramcount * 5) >> 3), MAX_GRAM_COUNT)
    delta = value1 - value2
    if delta >= thresh:
        return max_percent
    if delta <= 0:
        return 0
    return min(max_percent, (100 * delta) // thresh)


def reliability_expected(actual_per_kb: int, expected_per_kb: int) -> int:
    if expected_per_kb == 0:
        return 100
    if actual_per_kb == 0:
        return 0
    hi, lo = max(actual_per_kb, expected_per_kb), min(actual_per_kb,
                                                      expected_per_kb)
    ratio = hi / lo
    if ratio <= 1.5:
        return 100
    if ratio > 4.0:
        return 0
    return int(100.0 * (4.0 - ratio) / (4.0 - 1.5))


# ---------------------------------------------------------------------------
# Span scoring
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ChunkSummary:
    """Per-chunk result (scoreonescriptspan.h:240-252)."""
    offset: int
    lang1: int
    lang2: int
    score1: int
    score2: int
    bytes: int
    grams: int
    ulscript: int
    reliability_delta: int
    reliability_score: int


class LangBoosts:
    """Rotating 4-slot langprob boost list (scoreonescriptspan.h:70-89)."""

    def __init__(self):
        self.langprob = [0] * MAX_BOOSTS
        self.n = 0

    def add(self, langprob: int):
        self.langprob[self.n] = langprob
        self.n = (self.n + 1) % MAX_BOOSTS


@dataclasses.dataclass
class ResultChunk:
    """Per-range result (compact_lang_det.h:147-154): byte range of the
    ORIGINAL input and its detected language."""
    offset: int
    bytes: int
    lang1: int


UNRELIABLE_PERCENT_THRESHOLD = 75  # scoreonescriptspan.cc:33


@dataclasses.dataclass
class ScoringContext:
    tables: ScoringTables
    registry: Registry
    flags: int = 0
    distinct_boost_latn: LangBoosts = dataclasses.field(default_factory=LangBoosts)
    distinct_boost_othr: LangBoosts = dataclasses.field(default_factory=LangBoosts)
    ulscript: int = 0
    hint_boosts: object = None  # hints.HintBoosts from apply_hints, or None
    # per-chunk records for the result vector, or None when not wanted:
    # (span, round_id, lo_off, nbytes, lang1, lang2, rel_delta, rel_score)
    chunk_records: list | None = None
    round_id: int = 0
    trace: object = None  # debug.DetectionTrace sink, or None

    def distinct_boost(self) -> LangBoosts:
        if self.ulscript == ULSCRIPT_LATIN:
            return self.distinct_boost_latn
        return self.distinct_boost_othr

    def prior_boosts(self) -> list:
        if self.hint_boosts is None:
            return ()
        return self.hint_boosts.boost_latn if \
            self.ulscript == ULSCRIPT_LATIN else self.hint_boosts.boost_othr

    def prior_whacks(self) -> list:
        if self.hint_boosts is None:
            return ()
        return self.hint_boosts.whack_latn if \
            self.ulscript == ULSCRIPT_LATIN else self.hint_boosts.whack_othr


def resolve_indirect(ind: int, base_obj: NgramTable,
                     base_obj2: NgramTable) -> list[int]:
    """Indirect subscript -> 1 or 2 packed langprobs
    (LinearizeAll, scoreonescriptspan.cc:926-964)."""
    obj = base_obj
    if ind & DUAL_TABLE_FLAG:
        obj = base_obj2
        ind &= ~DUAL_TABLE_FLAG
    if ind < obj.size_one:
        lp = int(obj.ind[ind])
        return [lp] if lp > 0 else []
    i = ind + (ind - obj.size_one)
    out = []
    for lp in (int(obj.ind[i]), int(obj.ind[i + 1])):
        if lp > 0:
            out.append(lp)
    return out


def default_langprob(ctx: ScoringContext) -> int:
    """Seed hit: script's default language at qprob 1 (MakeLangProb via
    DefaultLangProb, scoreonescriptspan.cc:846-851, cldutil.cc:610)."""
    lang = ctx.registry.default_language(ctx.ulscript)
    pslang = ctx.registry.per_script_number(ULSCRIPT_LATIN, lang)
    backmap = [0, 0, 1, 3, 6, 10, 15, 21, 28, 36, 45, 55, 66]
    return (pslang << 8) | backmap[1]


def linearize(ctx: ScoringContext, score_cjk: bool,
              base: HitList, delta: HitList, distinct: HitList,
              lowest_offset: int, end_offset: int):
    """Merge-sort hits by offset, resolving indirects to langprobs
    (scoreonescriptspan.cc:856-975). Returns (offsets, types, langprobs)."""
    t = ctx.tables
    if score_cjk:
        base_obj = base_obj2 = t.cjkcompat
        delta_obj, distinct_obj = t.cjkdeltabi, t.distinctbi
        base_hit = UNIHIT
    else:
        base_obj, base_obj2 = t.quadgram, t.quadgram2
        delta_obj, distinct_obj = t.deltaocta, t.distinctocta
        base_hit = QUADHIT

    offs = [lowest_offset]
    types = [base_hit]
    lps = [default_langprob(ctx)]

    bi = di = xi = 0
    bn, dn, xn = len(base.offsets), len(delta.offsets), len(distinct.offsets)
    INF = 1 << 30

    def off(arr, i, n):
        return int(arr.offsets[i]) if i < n else INF

    while bi < bn or di < dn or xi < xn:
        bo, do, xo = off(base, bi, bn), off(delta, di, dn), off(distinct, xi, xn)
        if di < dn and do <= bo and do <= xo:
            lp = int(delta_obj.ind[int(delta.indirects[di])])
            if lp > 0:
                offs.append(do); types.append(DELTAHIT); lps.append(lp)
            di += 1
        elif xi < xn and xo <= bo and xo <= do:
            lp = int(distinct_obj.ind[int(distinct.indirects[xi])])
            if lp > 0:
                offs.append(xo); types.append(DISTINCTHIT); lps.append(lp)
            xi += 1
        else:
            for lp in resolve_indirect(int(base.indirects[bi]), base_obj,
                                       base_obj2):
                offs.append(bo); types.append(base_hit); lps.append(lp)
            bi += 1

    return (np.array(offs, dtype=np.int64), np.array(types, dtype=np.int64),
            np.array(lps, dtype=np.int64), end_offset)


def chunk_boundaries(n_base: int, chunksize: int) -> list[int]:
    """Base-hit counts per chunk with runt merging
    (ChunkAll, scoreonescriptspan.cc:994-1003)."""
    out = []
    left = n_base
    while left > 0:
        if left < chunksize + (chunksize >> 1):
            take = left
        elif left < 2 * chunksize:
            take = (left + 1) >> 1
        else:
            take = chunksize
        out.append(take)
        left -= take
    return out or [0]


def score_span_hits(ctx: ScoringContext, span: ScriptSpan, score_cjk: bool,
                    doc_tote: DocTote):
    """Score a span in hitbuffer rounds of <=1000 base hits, each with its
    own seed hit, chunking, and repeat caches (ScoreCJKScriptSpan /
    ScoreQuadScriptSpan fill loops, scoreonescriptspan.cc:1163-1277)."""
    letter_limit = span.text_bytes
    letter_offset = 1
    while letter_offset < letter_limit:
        if score_cjk:
            base, next_offset = get_uni_hits(span, ctx.tables, letter_offset)
            delta, distinct = get_bi_hits(span, ctx.tables, letter_offset,
                                          next_offset)
        else:
            base, next_offset = get_quad_hits(span, ctx.tables, letter_offset)
            delta, distinct = get_octa_hits(span, ctx.tables, letter_offset,
                                            next_offset)
        _score_round(ctx, span, score_cjk, base, delta, distinct, doc_tote,
                     letter_offset, next_offset)
        ctx.round_id += 1
        if next_offset <= letter_offset:
            break  # no forward progress possible
        letter_offset = next_offset


def _score_round(ctx: ScoringContext, span: ScriptSpan, score_cjk: bool,
                 base: HitList, delta: HitList, distinct: HitList,
                 doc_tote: DocTote, lowest_offset: int, end_offset: int):
    """Linearize + chunk + tote one hitbuffer fill, adding one ChunkSummary
    per chunk to the doc tote (ProcessHitBuffer + ScoreAllHits +
    SummaryBufferToDocTote)."""
    reg = ctx.registry
    t = ctx.tables
    offs, types, lps, end_off = linearize(
        ctx, score_cjk, base, delta, distinct, lowest_offset, end_offset)

    base_hit = UNIHIT if score_cjk else QUADHIT
    chunksize = CHUNKSIZE_UNIS if score_cjk else CHUNKSIZE_QUADS
    is_base = types == base_hit
    n_base = len(base.offsets)

    takes = chunk_boundaries(n_base, chunksize)
    # chunk_start[i] = first linear index of chunk i: advance until
    # `take` base hits consumed (the initial seed entry counts as base)
    chunk_starts = [0]
    li = 0
    nlin = len(offs)
    for take in takes:
        cnt = 0
        while cnt < take and li < nlin:
            if is_base[li]:
                cnt += 1
            li += 1
        chunk_starts.append(li)
    chunk_starts[-1] = nlin

    tote = Tote()
    lg = t.lg_prob
    summaries: list[ChunkSummary] = []
    for ci in range(len(takes)):
        lo_i, hi_i = chunk_starts[ci], chunk_starts[ci + 1]
        tote.reinit()
        for i in range(lo_i, hi_i):
            lp = int(lps[i])
            for pslang, qprob in decode_langprob(lp, lg):
                tote.add(pslang, qprob)
            if types[i] <= QUADHIT:
                tote.score_count += 1
            if types[i] == DISTINCTHIT:
                ctx.distinct_boost().add(lp)
        # ScoreBoosts (scoreonescriptspan.cc:125-152): hint prior boosts,
        # then distinct-word rotating boosts, then close-set whacks
        for lp in ctx.prior_boosts():
            if lp > 0:
                for pslang, qprob in decode_langprob(lp, lg):
                    tote.add(pslang, qprob)
        for lp in ctx.distinct_boost().langprob:
            if lp > 0:
                for pslang, qprob in decode_langprob(lp, lg):
                    tote.add(pslang, qprob)
        for lp in ctx.prior_whacks():
            if lp > 0:
                tote.score[(lp >> 8) & 0xFF] = 0  # ZeroPSLang

        lo_off = int(offs[lo_i])
        hi_off = int(offs[hi_i]) if hi_i < nlin else end_off
        cs = _make_chunk_summary(ctx, tote, lo_off, hi_off - lo_off)
        summaries.append(cs)

    if ctx.chunk_records is not None:
        # vector path only, exactly like the reference (sharpening runs
        # before the DocTote adds, so chunk byte counts shift too;
        # scoreonescriptspan.cc:1099-1111)
        _sharpen_boundaries(ctx, offs, lps, chunk_starts, summaries)
    for cs in summaries:
        doc_tote.add(cs.lang1, cs.bytes, cs.score1,
                     min(cs.reliability_delta, cs.reliability_score))
        if ctx.chunk_records is not None:
            ctx.chunk_records.append(
                (span, ctx.round_id, cs.offset, cs.bytes, cs.lang1,
                 cs.lang2, cs.reliability_delta, cs.reliability_score,
                 False))
        if ctx.trace is not None:
            ctx.trace.add("chunk", offset=cs.offset, bytes=cs.bytes,
                          lang1=cs.lang1, score1=cs.score1,
                          lang2=cs.lang2, score2=cs.score2,
                          grams=cs.grams, rel_delta=cs.reliability_delta,
                          rel_score=cs.reliability_score)


def get_lang_score(lp: int, pslang: int, lg_prob: np.ndarray) -> int:
    """qprob of one pslang within a packed langprob (GetLangScore,
    cldutil.cc:141-152)."""
    entry = lg_prob[lp & 0xFF]
    for j, shift in enumerate((8, 16, 24)):
        if (lp >> shift) & 0xFF == pslang:
            return int(entry[5 + j])
    return 0


def _better_boundary(lps, lg, pslang0: int, pslang1: int,
                     linear0: int, linear1: int, linear2: int) -> int:
    """Sharpest lang0/lang1 split within [linear0, linear2): max of the
    8-wide (+ + + + - - - -) running difference of per-hit score deltas
    (BetterBoundary, scoreonescriptspan.cc:671-734)."""
    if linear2 - linear0 <= 8:
        return linear1
    running = 0
    diff = [0] * 8
    for i in range(linear0, linear0 + 8):
        j = i & 7
        lp = int(lps[i])
        diff[j] = get_lang_score(lp, pslang0, lg) - \
            get_lang_score(lp, pslang1, lg)
        if i < linear0 + 4:
            running += diff[j]
        else:
            running -= diff[j]
    best_value = 0
    best = linear1
    for i in range(linear0, linear2 - 8):
        j = i & 7
        if best_value < running:
            has_plus = any(d > 0 for d in diff)
            has_minus = any(d < 0 for d in diff)
            if has_plus and has_minus:
                best_value = running
                best = i + 4
        lp = int(lps[i + 8])
        newdiff = get_lang_score(lp, pslang0, lg) - \
            get_lang_score(lp, pslang1, lg)
        middiff = diff[(i + 4) & 7]
        olddiff = diff[j]
        diff[j] = newdiff
        running += -olddiff + 2 * middiff - newdiff
    return best


def _sharpen_boundaries(ctx: ScoringContext, offs, lps,
                        chunk_starts: list, summaries: list) -> None:
    """Move chunk boundaries between different-language neighbors to the
    sharpest per-hit score split, shifting the byte counts accordingly
    (SharpenBoundaries, scoreonescriptspan.cc:780-845). Runs only on the
    result-vector path, exactly like the reference."""
    if len(summaries) < 2:
        return
    reg = ctx.registry
    lg = ctx.tables.lg_prob
    prior_linear = chunk_starts[0]
    prior_lang = summaries[0].lang1
    for i in range(1, len(summaries)):
        cs = summaries[i]
        this_lang = cs.lang1
        if this_lang == prior_lang:
            prior_linear = chunk_starts[i]
            continue
        this_linear = chunk_starts[i]
        next_linear = chunk_starts[i + 1]
        if _same_close_set(reg, prior_lang, this_lang):
            prior_linear = this_linear
            prior_lang = this_lang
            continue
        pslang0 = reg.per_script_number(ctx.ulscript, prior_lang)
        pslang1 = reg.per_script_number(ctx.ulscript, this_lang)
        better = _better_boundary(lps, lg, pslang0, pslang1,
                                  prior_linear, this_linear, next_linear)
        old_offset = int(offs[this_linear])
        new_offset = int(offs[better])
        chunk_starts[i] = better
        cs.offset = new_offset
        cs.bytes -= new_offset - old_offset
        summaries[i - 1].bytes += new_offset - old_offset
        prior_linear = better
        prior_lang = this_lang


def _make_chunk_summary(ctx: ScoringContext, tote: Tote, offset: int,
                        nbytes: int) -> ChunkSummary:
    """SetChunkSummary (scoreonescriptspan.cc:60-96)."""
    reg = ctx.registry
    t = ctx.tables
    k3 = tote.top_three_keys()
    lang1 = reg.from_per_script_number(ctx.ulscript, max(k3[0], 0))
    lang2 = reg.from_per_script_number(ctx.ulscript, max(k3[1], 0))
    score1 = int(tote.score[k3[0]]) if k3[0] >= 0 else 0
    score2 = int(tote.score[k3[1]]) if k3[1] >= 0 else 0
    actual_per_kb = (score1 << 10) // nbytes if nbytes > 0 else 0
    expected_per_kb = int(
        t.avg_delta_octa_score[lang1, _lscript4(ctx.ulscript)])
    rd = reliability_delta(score1, score2, tote.score_count)
    if _same_close_set(reg, lang1, lang2):
        rd = 100
    rs = reliability_expected(actual_per_kb, expected_per_kb)
    return ChunkSummary(offset=offset, lang1=lang1, lang2=lang2,
                        score1=score1, score2=score2, bytes=nbytes,
                        grams=tote.score_count, ulscript=ctx.ulscript,
                        reliability_delta=rd, reliability_score=rs)


def _lscript4(ulscript: int) -> int:
    """Script -> {Latn, Cyrl, Arab, Other} index (lang_script.h LScript4)."""
    if ulscript == ULSCRIPT_LATIN:
        return 0
    if ulscript == 3:   # Cyrillic
        return 1
    if ulscript == 6:   # Arabic
        return 2
    return 3


def _same_close_set(reg: Registry, lang1: int, lang2: int) -> bool:
    s1 = reg.close_set(lang1)
    return s1 != 0 and s1 == reg.close_set(lang2)


# ---------------------------------------------------------------------------
# Document-level pipeline
# ---------------------------------------------------------------------------

def score_one_span(ctx: ScoringContext, span: ScriptSpan, doc_tote: DocTote):
    """ScoreOneScriptSpan (scoreonescriptspan.cc:1302)."""
    reg = ctx.registry
    ctx.ulscript = span.ulscript
    rtype = reg.rtype(span.ulscript)
    if (ctx.flags & FLAG_SCORE_AS_QUADS) and rtype != RTYPE_CJK:
        rtype = RTYPE_MANY
    if rtype in (RTYPE_NONE, RTYPE_ONE):
        lang = reg.default_language(span.ulscript)
        doc_tote.add(lang, span.text_bytes, span.text_bytes, 100)
        if ctx.chunk_records is not None:
            # JustOneItemToVector (scoreonescriptspan.cc:513-548): offsets
            # map straight through ItemToVector — no word-boundary trim,
            # no reliability/close-set relabeling
            ctx.chunk_records.append(
                (span, ctx.round_id, 1, span.text_bytes - 1, lang,
                 UNKNOWN_LANGUAGE, 100, 100, True))
            ctx.round_id += 1
        if ctx.trace is not None:
            # vector-record view: [1, text_bytes) like JustOneItemToVector
            ctx.trace.add("chunk", offset=1, bytes=span.text_bytes - 1,
                          lang1=lang, score1=span.text_bytes,
                          lang2=UNKNOWN_LANGUAGE, score2=0, grams=0,
                          rel_delta=100, rel_score=100)
    else:
        score_span_hits(ctx, span, rtype == RTYPE_CJK, doc_tote)


def refine_close_pairs(reg: Registry, doc_tote: DocTote):
    """Winner-take-all within close sets (RefineScoredClosePairs,
    compact_lang_det_impl.cc:1154-1203)."""
    for sub in range(DocTote.MAX):
        lang = int(doc_tote.key[sub])
        if lang == DocTote.UNUSED:
            continue
        cs = reg.close_set(lang)
        if cs == 0:
            continue
        for sub2 in range(sub + 1, DocTote.MAX):
            lang2 = int(doc_tote.key[sub2])
            if lang2 == DocTote.UNUSED or reg.close_set(lang2) != cs:
                continue
            frm, to = (sub, sub2) if doc_tote.value[sub] < doc_tote.value[sub2] \
                else (sub2, sub)
            doc_tote.value[to] += doc_tote.value[frm]
            doc_tote.score[to] += doc_tote.score[frm]
            doc_tote.rel[to] += doc_tote.rel[frm]
            doc_tote.key[frm] = DocTote.UNUSED
            doc_tote.value[frm] = 0
            doc_tote.score[frm] = 0
            doc_tote.rel[frm] = 0
            break


def remove_unreliable(reg: Registry, doc_tote: DocTote):
    """Merge/delete languages below 41% reliability
    (RemoveUnreliableLanguages, compact_lang_det_impl.cc:997-1101)."""
    for sub in range(DocTote.MAX):
        lang = int(doc_tote.key[sub])
        if lang == DocTote.UNUSED:
            continue
        nbytes = int(doc_tote.value[sub])
        if nbytes == 0:
            continue
        pct = int(doc_tote.rel[sub]) // nbytes
        if pct >= MIN_RELIABLE_KEEP_PERCENT:
            continue
        alt = reg.closest_alt(lang)
        if alt == UNKNOWN_LANGUAGE:
            continue
        altsub = doc_tote.find(alt)
        if altsub < 0:
            continue
        bytes2 = int(doc_tote.value[altsub])
        if bytes2 == 0:
            continue
        pct2 = int(doc_tote.rel[altsub]) // bytes2
        tosub, fromsub = (altsub, sub)
        if pct2 < pct or (pct2 == pct and lang < alt):
            tosub, fromsub = (sub, altsub)
        newpct = max(pct, pct2, MIN_RELIABLE_KEEP_PERCENT)
        newbytes = nbytes + bytes2
        doc_tote.key[fromsub] = DocTote.UNUSED
        doc_tote.score[fromsub] = 0
        doc_tote.rel[fromsub] = 0
        doc_tote.score[tosub] = newbytes   # reference stores bytes via SetScore
        doc_tote.rel[tosub] = newpct * newbytes

    for sub in range(DocTote.MAX):
        lang = int(doc_tote.key[sub])
        if lang == DocTote.UNUSED:
            continue
        nbytes = int(doc_tote.value[sub])
        if nbytes == 0:
            continue
        pct = int(doc_tote.rel[sub]) // nbytes
        if pct < MIN_RELIABLE_KEEP_PERCENT:
            doc_tote.key[sub] = DocTote.UNUSED
            doc_tote.score[sub] = 0
            doc_tote.rel[sub] = 0


def extract_lang_etc(doc_tote: DocTote, total_text_bytes: int):
    """Top-3 languages, percents, scores (ExtractLangEtc,
    compact_lang_det_impl.cc:1276-1384)."""
    lang3 = [UNKNOWN_LANGUAGE] * 3
    percent3 = [0] * 3
    rel3 = [0] * 3
    ns3 = [0.0] * 3
    bc = [0] * 3
    for i in range(3):
        lang = int(doc_tote.key[i])
        if lang != DocTote.UNUSED and lang != UNKNOWN_LANGUAGE:
            lang3[i] = lang
            bc[i] = int(doc_tote.value[i])
            rel3[i] = int(doc_tote.rel[i]) // max(bc[i], 1)
            # GetNormalizedScore does C integer division (impl.cc:1269-1273)
            ns3[i] = float((int(doc_tote.score[i]) << 10) // bc[i]) \
                if bc[i] else 0.0

    total12 = bc[0] + bc[1]
    total123 = total12 + bc[2]
    total = max(total_text_bytes, total123)
    div = max(1, total)
    percent3[0] = bc[0] * 100 // div
    percent3[1] = total12 * 100 // div
    percent3[2] = total123 * 100 // div
    percent3[2] -= percent3[1]
    percent3[1] -= percent3[0]
    if percent3[1] < percent3[2]:
        percent3[1] += 1
        percent3[2] -= 1
    if percent3[0] < percent3[1]:
        percent3[0] += 1
        percent3[1] -= 1

    is_reliable = False
    if lang3[0] != UNKNOWN_LANGUAGE:
        is_reliable = rel3[0] >= MIN_RELIABLE_KEEP_PERCENT
    ignore_percent = 100 - sum(percent3)
    if ignore_percent > IGNORE_MAX_PERCENT:
        is_reliable = False
    return lang3, percent3, rel3, ns3, total, is_reliable


def _is_figs(lang: int, reg: Registry) -> bool:
    return reg.code(lang) in ("fr", "it", "de", "es")


def _is_efigs(lang: int, reg: Registry) -> bool:
    return lang == ENGLISH or _is_figs(lang, reg)


def calc_summary_lang(reg: Registry, lang3, percent3, total_text_bytes: int,
                      is_reliable: bool, flags: int):
    """CalcSummaryLang (compact_lang_det_impl.cc:1414-1522)."""
    slot = [0, 1, 2]
    slot_count = 3
    ignore_percent = 0
    return_percent = percent3[0]
    summary = lang3[0]
    reliable = True
    if percent3[0] < KEEP_MIN_PERCENT:
        reliable = False

    for i in range(3):
        if lang3[i] == TG_UNKNOWN_LANGUAGE:
            ignore_percent += percent3[i]
            for j in range(i + 1, 3):
                slot[j - 1] = slot[j]
            slot_count -= 1
            return_percent = (percent3[0] * 100) // (101 - ignore_percent)
            summary = lang3[slot[0]]
            if percent3[slot[0]] < KEEP_MIN_PERCENT:
                reliable = False

    second_bytes = (total_text_bytes * percent3[slot[1]]) // 100
    if (lang3[slot[0]] == ENGLISH and lang3[slot[1]] != ENGLISH and
            lang3[slot[1]] != UNKNOWN_LANGUAGE and
            percent3[slot[1]] >= NON_EN_BOILERPLATE_MIN_PERCENT and
            second_bytes >= GOOD_SECOND_T1T2_MIN_BYTES):
        ignore_percent += percent3[slot[0]]
        return_percent = (percent3[slot[1]] * 100) // (101 - ignore_percent)
        summary = lang3[slot[1]]
        if percent3[slot[1]] < KEEP_MIN_PERCENT:
            reliable = False
    elif (_is_figs(lang3[slot[0]], reg) and
          not _is_efigs(lang3[slot[1]], reg) and
          lang3[slot[1]] != UNKNOWN_LANGUAGE and
          percent3[slot[1]] >= NON_FIGS_BOILERPLATE_MIN_PERCENT and
          second_bytes >= GOOD_SECOND_T1T2_MIN_BYTES):
        ignore_percent += percent3[slot[0]]
        return_percent = (percent3[slot[1]] * 100) // (101 - ignore_percent)
        summary = lang3[slot[1]]
        if percent3[slot[1]] < KEEP_MIN_PERCENT:
            reliable = False
    elif lang3[slot[1]] == ENGLISH and lang3[slot[0]] != ENGLISH:
        ignore_percent += percent3[slot[1]]
        return_percent = (percent3[slot[0]] * 100) // (101 - ignore_percent)
    elif (_is_figs(lang3[slot[1]], reg) and
          not _is_efigs(lang3[slot[0]], reg)):
        ignore_percent += percent3[slot[1]]
        return_percent = (percent3[slot[0]] * 100) // (101 - ignore_percent)

    if return_percent < GOOD_FIRST_MIN_PERCENT and \
            not (flags & FLAG_BEST_EFFORT):
        summary = UNKNOWN_LANGUAGE
        reliable = False
    if return_percent < GOOD_FIRST_RELIABLE_MIN_PERCENT:
        reliable = False
    ignore_percent = 100 - sum(percent3)
    if ignore_percent > IGNORE_MAX_PERCENT:
        reliable = False
    if slot_count == 0:
        summary = UNKNOWN_LANGUAGE
        reliable = False
    return summary, (is_reliable and reliable)


@dataclasses.dataclass
class ScalarResult:
    summary_lang: int
    language3: list
    percent3: list
    normalized_score3: list
    text_bytes: int
    is_reliable: bool
    chunks: list | None = None  # ResultChunk vector when requested
    # per-span verdicts [(byte_offset, byte_len, code, pct, reliable)]
    # — filled only by the LDT_SPANS surfaces (engine detect_spans /
    # detector span synthesis); None everywhere else
    spans: list | None = None


# -- per-span output (LDT_SPANS) --------------------------------------------
#
# The span contract (docs/ACCURACY.md): spans TILE the document's bytes.
# Sub-document k's scored extent starts at its first letter char, so
# span 0 pulls its start back to byte 0, span k ends where span k+1
# starts, and the last span ends at the document's last byte —
# non-letter gaps between scored extents attach to the preceding span.
# The default split budget matches the pack ladder's mid tier (~4KB of
# text per span group).

SPAN_SPLIT_SLOTS = 1024


def span_coverage_records(text: str, bounds: list,
                          verdicts: list) -> list:
    """(char extents, per-sub verdicts) -> covering span records
    [(byte_offset, byte_len, code, pct, reliable)]. bounds[k] = (a, b)
    char extent of sub-doc k (split_longdoc want_bounds); verdicts[k] =
    (code, pct, reliable). Shared between the batched engine's span
    lane and the scalar oracle so both emit byte-identical records."""
    n = len(bounds)
    starts = [0] + [bounds[k][0] for k in range(1, n)]
    ends = starts[1:] + [len(text)]
    spans = []
    off = 0
    for k in range(n):
        seg = text[starts[k]:ends[k]]
        blen = len(seg.encode("utf-8", "surrogatepass"))
        code, pct, rel = verdicts[k]
        spans.append((off, blen, code, pct, rel))
        off += blen
    return spans


def split_for_spans(text: str, tables, split_slots: int):
    """(subs, bounds) for the span surfaces: the long-doc lane's
    span-aligned split (the only exact split points), or one whole-doc
    span when the document is under budget / refuses to split."""
    from .preprocess.pack import split_longdoc
    got = split_longdoc(text, tables, max(split_slots, 1),
                        want_bounds=True)
    if not got:
        return [text], [(0, len(text))]
    return got


def detect_scalar_spans(text: str, tables, reg, flags: int = 0,
                        split_slots: int = SPAN_SPLIT_SLOTS
                        ) -> "ScalarResult":
    """Scalar oracle for the LDT_SPANS surface: the same span-aligned
    split as the batched engine's span lane, each sub-document through
    detect_scalar, records via the shared coverage builder. The batched
    lane resolves every exception sub-doc through detect_scalar and
    agrees with it everywhere else (the engine's core invariant), so
    its spans are bit-identical to this function's by construction
    (tests/test_spans.py pins it)."""
    subs, bounds = split_for_spans(text, tables, split_slots)
    res = detect_scalar(text, tables, reg, flags)
    verdicts = []
    for sub in subs:
        r = detect_scalar(sub, tables, reg, flags)
        verdicts.append((reg.code(r.summary_lang), int(r.percent3[0]),
                         bool(r.is_reliable)))
    res.spans = span_coverage_records(text, bounds, verdicts)
    return res


def _respan(text_bytes: bytes, ulscript: int,
            src_idx: np.ndarray | None = None) -> ScriptSpan:
    """Rebuild a ScriptSpan around squeezed/stripped span text. src_idx is
    carried through only for the length-preserving Overwrite rewrites,
    where byte offsets still map to the original input."""
    buf = np.zeros(len(text_bytes) + 32, dtype=np.uint8)
    buf[:len(text_bytes)] = np.frombuffer(text_bytes, dtype=np.uint8)
    buf[len(text_bytes):len(text_bytes) + 3] = 0x20
    cps = np.frombuffer(
        text_bytes.decode("utf-8", errors="replace").encode("utf-32-le"),
        dtype=np.uint32)
    return ScriptSpan(buf=buf, text_bytes=len(text_bytes), ulscript=ulscript,
                      cps=np.concatenate([cps, [0x20]]).astype(np.uint32),
                      src_idx=src_idx)


def build_result_chunks(orig_text: str, records: list, reg: Registry,
                        html_offsets=None) -> list:
    """Chunk records -> merged ResultChunk vector over ORIGINAL byte
    offsets (SummaryBufferToVector scoreonescriptspan.cc:389-509 +
    ItemToVector :341-378 + FinishResultVector impl.cc:1688-1704).

    Offset mapping composes the span src_idx arrays (span-buffer byte ->
    segmenter-input char), the optional HTML clean-text offset map
    (clean char -> original char), and the original text's char->byte
    cumsum — the index-array equivalent of the reference's composed
    OffsetMaps (offsetmap.cc:428-496). The merge itself runs in
    merge_mapped_records, shared with the batched engine's chunk-vector
    path (which arrives with offsets already mapped)."""
    raw = orig_text.encode("utf-8", "surrogatepass")
    cps = np.frombuffer(orig_text.encode("utf-32-le", "surrogatepass"),
                        np.uint32)
    from .preprocess.segment import utf8_len_of_cps
    byte_of_char = np.zeros(len(cps) + 1, np.int64)
    if len(cps):
        np.cumsum(utf8_len_of_cps(cps), out=byte_of_char[1:])

    def map_back(span, off):
        src = int(span.src_idx[min(off, len(span.src_idx) - 1)])
        if html_offsets is not None:
            src = int(html_offsets[min(src, len(html_offsets) - 1)]) \
                if len(html_offsets) else 0
        return int(byte_of_char[min(src, len(byte_of_char) - 1)])

    # map ends lazily: merge_mapped_records consults `end` only for the
    # final record (consecutive chunks are contiguous)
    mapped = [(rid,
               map_back(span, lo),
               map_back(span, lo + nbytes) if i == len(records) - 1
               else 0,
               lang1, lang2, rd, rs, is_one)
              for i, (span, rid, lo, nbytes, lang1, lang2, rd, rs,
                      is_one) in enumerate(records)]
    return merge_mapped_records(raw, mapped, reg)


def merge_mapped_records(raw: bytes, records: list, reg: Registry) -> list:
    """Mapped chunk records -> merged ResultChunk vector. records:
    (rid, start, end, lang1, lang2, rd, rs, is_one) with start/end in
    ORIGINAL byte offsets; `end` is consulted only for the final record
    (the reference's continuous offset maps make consecutive chunks
    contiguous, so every other end IS the next record's start). The
    word-boundary trim, reliability/close-set relabeling, same-language
    merge, and FinishResultVector semantics live here, shared verbatim
    between the scalar engine and the batched engine's vector path."""
    raw_starts = [start for _, start, *_ in records]
    vec: list = []
    for i, (rid, start, end_mapped, lang1, lang2, rd, rs, is_one) in \
            enumerate(records):
        mapped_offset = raw_starts[i]
        # Trim back to a word boundary (scoreonescriptspan.cc:419-460);
        # JustOneItem records skip the trim (scoreonescriptspan.cc:513-548)
        if mapped_offset > 0 and not is_one:
            prior_size = vec[-1].bytes if vec else 0
            n_limit = min(prior_size - 3, mapped_offset, 12)
            n = 0
            while n < n_limit and raw[mapped_offset - n - 1] >= 0x41:
                n += 1
            if n >= n_limit:
                n = 0
            if n < n_limit and \
                    raw[mapped_offset - n - 1:mapped_offset - n] in \
                    (b"'", b'"', b"#", b"@"):
                n += 1
            if n > 0 and vec:
                vec[-1].bytes -= n
                mapped_offset -= n
        end = raw_starts[i + 1] if i + 1 < len(records) \
            else end_mapped
        mapped_len = end - mapped_offset

        new_lang = lang1
        if not is_one:
            # reliability / close-set relabeling (SummaryBufferToVector,
            # scoreonescriptspan.cc:462-505); JustOneItem records bypass it
            rd_bad = rd < UNRELIABLE_PERCENT_THRESHOLD
            rs_bad = rs < UNRELIABLE_PERCENT_THRESHOLD
            prior_lang = vec[-1].lang1 if vec else UNKNOWN_LANGUAGE
            if prior_lang == lang1:
                rd_bad = False
            if _same_close_set(reg, lang1, prior_lang):
                new_lang = prior_lang
                rd_bad = False
            if _same_close_set(reg, lang1, lang2) and prior_lang == lang2:
                new_lang = prior_lang
                rd_bad = False
            # next chunk's lang1, within the same hitbuffer round only
            next_lang = records[i + 1][3] if i + 1 < len(records) and \
                records[i + 1][0] == rid else UNKNOWN_LANGUAGE
            if rd_bad and prior_lang == lang2 and next_lang == lang2:
                new_lang = prior_lang
                rd_bad = False
            if rd_bad or rs_bad:
                new_lang = UNKNOWN_LANGUAGE

        # ItemToVector: extend the prior entry on same language
        if vec and vec[-1].lang1 == new_lang:
            vec[-1].bytes = (mapped_offset + mapped_len) - vec[-1].offset
        else:
            vec.append(ResultChunk(offset=mapped_offset, bytes=mapped_len,
                                   lang1=new_lang))

    # FinishResultVector: cover [0, len) exactly
    if vec:
        if vec[0].offset > 0:
            vec[0].bytes += vec[0].offset
            vec[0].offset = 0
        last = vec[-1]
        if last.offset + last.bytes < len(raw):
            last.bytes = len(raw) - last.offset
    return vec


def detect_scalar(text: str, tables: ScoringTables | None = None,
                  reg: Registry | None = None,
                  flags: int = 0, is_plain_text: bool = True,
                  hints=None, want_chunks: bool = False,
                  _hint_boosts=None, _vec_src=None,
                  _trace=None) -> ScalarResult:
    """Full-document detection (DetectLanguageSummaryV2,
    compact_lang_det_impl.cc:1707-2106), including the squeeze/repeat
    anti-spam recursion. is_plain_text=False strips HTML tags / expands
    entities first (preprocess/html.py). hints is an optional
    hints.CLDHints; HTML lang= attributes are always scanned for
    non-plain text (ApplyHints, impl.cc:1587)."""
    tables = tables or load_tables()
    reg = reg or default_registry
    if _hint_boosts is None and (hints is not None or not is_plain_text):
        from .hints import apply_hints
        _hint_boosts = apply_hints(text, is_plain_text, hints, tables, reg)
    if _vec_src is None:
        orig_text = text
        html_offsets = None
        if not is_plain_text:
            from .preprocess.html import clean_html
            text, html_offsets = clean_html(text, tables)
        # Recursive passes receive the already-cleaned text plus this
        # mapping context so result chunks always cover the ORIGINAL input
        _vec_src = (orig_text, html_offsets)
    else:
        orig_text, html_offsets = _vec_src
    # When chunks are wanted, squeeze/repeat-strip switch to the
    # length-preserving Overwrite rewrites so span offsets keep mapping to
    # the original input (impl.cc:1856-1862, :1908-1916) — detection then
    # scores the dotted text, exactly as the reference's vector path does.
    collect = want_chunks
    if _trace is not None:
        _trace.add("pass", flags=flags)
    ctx = ScoringContext(tables=tables, registry=reg, flags=flags,
                         hint_boosts=_hint_boosts,
                         chunk_records=[] if collect else None,
                         trace=_trace)
    doc_tote = DocTote()
    total_text_bytes = 0
    if flags & FLAG_REPEATS:
        rep_hash = [0]
        predict_tbl = np.zeros(PREDICTION_TABLE_SIZE, dtype=np.int64)
    for span in segment_text(text, tables):
        if flags & FLAG_SQUEEZE:
            # Remove repetitive or mostly-space chunks (impl.cc:1852-1864)
            if collect:
                dotted = cheap_squeeze_overwrite(span.buf.tobytes(),
                                                 span.text_bytes)
                span = _respan(dotted, span.ulscript, src_idx=span.src_idx)
            else:
                squeezed = cheap_squeeze(span.buf.tobytes(), span.text_bytes)
                span = _respan(squeezed, span.ulscript)
        elif (TEST_THRESH >> 1) < span.text_bytes and \
                not (flags & FLAG_FINISH):
            # Should the whole doc be re-scanned with squeezing on?
            # (impl.cc:1866-1901)
            if cheap_squeeze_trigger_test(span.buf.tobytes(),
                                          span.text_bytes):
                return detect_scalar(text, tables, reg,
                                     flags | FLAG_SQUEEZE,
                                     want_chunks=want_chunks,
                                     _hint_boosts=_hint_boosts,
                                     _vec_src=_vec_src, _trace=_trace)
        if flags & FLAG_REPEATS:
            # Remove repeated words (impl.cc:1905-1918)
            if collect:
                dotted = cheap_rep_words_overwrite(
                    span.buf.tobytes(), span.text_bytes, rep_hash,
                    predict_tbl)
                span = _respan(dotted, span.ulscript, src_idx=span.src_idx)
            else:
                stripped = cheap_rep_words(span.buf.tobytes(),
                                           span.text_bytes,
                                           rep_hash, predict_tbl)
                span = _respan(stripped, span.ulscript)
        if _trace is not None:
            _trace.add("span", script=span.ulscript,
                       bytes=span.text_bytes,
                       rtype=reg.rtype(span.ulscript))
        score_one_span(ctx, span, doc_tote)
        total_text_bytes += span.text_bytes

    if _trace is not None:
        _trace.add_tote("scored", doc_tote, reg)
    refine_close_pairs(reg, doc_tote)
    doc_tote.sort()
    if _trace is not None:
        _trace.add_tote("close_pairs_refined", doc_tote, reg)
    lang3, percent3, rel3, ns3, total, is_reliable = extract_lang_etc(
        doc_tote, total_text_bytes)

    good = (flags & FLAG_FINISH) or total <= SHORT_TEXT_THRESH or \
        (is_reliable and percent3[0] >= GOOD_LANG1_PERCENT) or \
        (is_reliable and percent3[0] + percent3[1] >= GOOD_LANG1AND2_PERCENT)

    if not good:
        # Refine with repeat-stripping and a forced finish
        # (compact_lang_det_impl.cc:2061-2105; Top40/Short/UseWords are
        # vestigial in this CLD2 version -- only Repeats/Finish act).
        extra = FLAG_TOP40 | FLAG_REPEATS | FLAG_FINISH
        if total < SHORT_TEXT_THRESH:
            extra |= FLAG_SHORT | FLAG_USE_WORDS
        return detect_scalar(text, tables, reg, flags | extra,
                             want_chunks=want_chunks,
                             _hint_boosts=_hint_boosts, _vec_src=_vec_src,
                             _trace=_trace)

    if not (flags & FLAG_BEST_EFFORT):
        remove_unreliable(reg, doc_tote)
        if _trace is not None:
            _trace.add_tote("unreliable_removed", doc_tote, reg)
    doc_tote.sort()
    lang3, percent3, rel3, ns3, total, is_reliable = extract_lang_etc(
        doc_tote, total_text_bytes)
    summary, reliable = calc_summary_lang(reg, lang3, percent3, total,
                                          is_reliable, flags)
    if _trace is not None:
        _trace.add("summary", lang=summary, reliable=reliable,
                   top3=list(zip(lang3, percent3)), text_bytes=total)
    chunks = build_result_chunks(orig_text, ctx.chunk_records, reg,
                                 html_offsets) if collect else None
    return ScalarResult(summary_lang=summary, language3=lang3,
                        percent3=percent3, normalized_score3=ns3,
                        text_bytes=total, is_reliable=reliable,
                        chunks=chunks)


def result_from_epilogue_row(row) -> ScalarResult:
    """ldt_epilogue_flat [14]-lane row -> ScalarResult (shared by the
    batched engine's retry path and the all-C detect() fast path —
    lives here so the C path needs no jax import)."""
    return ScalarResult(
        summary_lang=int(row[0]),
        language3=[int(row[1]), int(row[2]), int(row[3])],
        percent3=[int(row[4]), int(row[5]), int(row[6])],
        normalized_score3=[float(row[7]), float(row[8]), float(row[9])],
        text_bytes=int(row[10]),
        is_reliable=bool(row[11]))
